"""Transformer encoder-decoder for WMT en-de — BASELINE.md config 4
("Transformer-big WMT en-de, dynamic shapes + beam search infer").

Training builds on the program IR like the reference's transformer example
(reference analog: the fluid Transformer in its models repo driven through
dist_transformer.py, python/paddle/fluid/tests/unittests/dist_transformer.py);
decoding is where the designs diverge hard:

* reference: beam search as LoD-manipulating graph ops inside a While op
  (reference: paddle/fluid/operators/beam_search_op.cc,
  beam_search_decode_op.cc — per-step host-visible LoD surgery).
* here: a single jitted `lax.while_loop` with static [batch, beam, max_len]
  state and per-layer KV caches — dense shapes, no LoD, the whole decode is
  ONE XLA computation (SURVEY §5.7: LoD subsumed by padding; §7 hard parts:
  beam search needs bucketing + static shapes up front).

Weight sharing between the IR training program and the functional decoder is
by parameter NAME: build_wmt_train names every parameter, and
`params_from_scope` pulls the trained values for the decode function.
"""

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr

__all__ = [
    "TransformerConfig",
    "build_wmt_train",
    "params_from_scope",
    "make_beam_decoder",
    "BucketedBeamTranslator",
    "synthetic_batch",
]


class TransformerConfig:
    def __init__(
        self,
        vocab_size=37000,
        d_model=1024,
        n_heads=16,
        d_ffn=4096,
        n_enc_layers=6,
        n_dec_layers=6,
        max_len=256,
        dropout=0.1,
        label_smooth=0.1,
        bos_id=0,
        eos_id=1,
        pad_id=2,
        pre_ln=True,
    ):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_heads = n_heads
        self.d_ffn = d_ffn
        self.n_enc_layers = n_enc_layers
        self.n_dec_layers = n_dec_layers
        self.max_len = max_len
        self.dropout = dropout
        self.label_smooth = label_smooth
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.pad_id = pad_id
        # pre-LN ("normalize_before") trains stably without long warmup;
        # post-LN (pre_ln=False) matches the 2017 paper layout
        self.pre_ln = pre_ln

    @staticmethod
    def big():
        return TransformerConfig()

    @staticmethod
    def base():
        return TransformerConfig(d_model=512, n_heads=8, d_ffn=2048)

    @staticmethod
    def tiny():
        return TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, d_ffn=64,
            n_enc_layers=2, n_dec_layers=2, max_len=32, dropout=0.0,
        )


def _sinusoid(max_len, d_model):
    pos = np.arange(max_len)[:, None].astype("float64")
    i = np.arange(d_model)[None, :].astype("float64")
    angle = pos / np.power(10000.0, 2 * (i // 2) / d_model)
    enc = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
    return enc.astype("float32")


# ---------------------------------------------------------------------------
# IR training program
# ---------------------------------------------------------------------------


def _init(cfg):
    return fluid.initializer.Xavier()


def _dense(x, size, cfg, act=None, name=None, nfd=2):
    return fluid.layers.fc(
        x, size=size, num_flatten_dims=nfd, act=act,
        param_attr=ParamAttr(name=name + ".w", initializer=_init(cfg)),
        bias_attr=ParamAttr(name=name + ".b"),
        name=name,
    )


def _ln(x, cfg, name):
    return fluid.layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=name + ".scale"),
        bias_attr=ParamAttr(name=name + ".bias"),
        name=name,
    )


def _mha(q_in, kv_in, bias, cfg, name):
    """Multi-head attention through IR ops; bias is additive, broadcastable
    to [B, heads, Sq, Sk]."""
    H, n, d = cfg.d_model, cfg.n_heads, cfg.d_model // cfg.n_heads
    q = _dense(q_in, H, cfg, name=name + ".q")
    k = _dense(kv_in, H, cfg, name=name + ".k")
    v = _dense(kv_in, H, cfg, name=name + ".v")

    def split(t):
        t = fluid.layers.reshape(t, [0, 0, n, d])
        return fluid.layers.transpose(t, [0, 2, 1, 3])

    q, k, v = split(q), split(k), split(v)
    scores = fluid.layers.matmul(q, k, transpose_y=True, alpha=1.0 / math.sqrt(d))
    scores = fluid.layers.elementwise_add(scores, bias)
    probs = fluid.layers.softmax(scores)
    if cfg.dropout:
        probs = fluid.layers.dropout(
            probs, cfg.dropout, dropout_implementation="upscale_in_train"
        )
    ctx = fluid.layers.matmul(probs, v)
    ctx = fluid.layers.transpose(ctx, [0, 2, 1, 3])
    ctx = fluid.layers.reshape(ctx, [0, 0, H])
    return _dense(ctx, H, cfg, name=name + ".out")


def _res_drop(x, y, cfg):
    if cfg.dropout:
        y = fluid.layers.dropout(
            y, cfg.dropout, dropout_implementation="upscale_in_train"
        )
    return fluid.layers.elementwise_add(x, y)


def _ffn(x, cfg, name):
    h = _dense(x, cfg.d_ffn, cfg, act="relu", name=name + "1")
    return _dense(h, cfg.d_model, cfg, name=name + "2")


def _embed(ids, cfg, pos_table, name_prefix=""):
    emb = fluid.layers.embedding(
        ids, size=[cfg.vocab_size, cfg.d_model],
        param_attr=ParamAttr(name="word_emb", initializer=_init(cfg)),
    )
    emb = fluid.layers.scale(emb, scale=math.sqrt(cfg.d_model))
    emb = fluid.layers.elementwise_add(emb, pos_table)
    if cfg.dropout:
        emb = fluid.layers.dropout(
            emb, cfg.dropout, dropout_implementation="upscale_in_train"
        )
    return emb


def _const(arr, name, dtype):
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("const_" + name)
    out = helper.block.create_var(
        name=helper.name, shape=list(arr.shape), dtype=dtype, stop_gradient=True
    )
    helper.append_op(
        "assign_value", {}, {"Out": [out.name]},
        {"shape": list(arr.shape), "dtype": dtype,
         "values": np.asarray(arr).reshape(-1).tolist()},
    )
    return out


def build_wmt_train(cfg=None, src_len=64, tgt_len=64, lr=2.0, warmup=4000,
                    optimizer=None):
    """Teacher-forced training program with label smoothing and Noam LR.
    Feeds: src_ids [B,S], tgt_ids [B,T] (decoder input, BOS-prefixed),
    labels [B,T] (gold, EOS-suffixed); pad_id positions are masked out.
    Returns (main, startup, feeds, fetches=[loss])."""
    cfg = cfg or TransformerConfig.base()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src_ids = fluid.data("src_ids", shape=[-1, src_len], dtype="int64")
        tgt_ids = fluid.data("tgt_ids", shape=[-1, tgt_len], dtype="int64")
        labels = fluid.data("labels", shape=[-1, tgt_len], dtype="int64")

        pos_src = _const(_sinusoid(src_len, cfg.d_model)[None], "pos_src", "float32")
        pos_tgt = _const(_sinusoid(tgt_len, cfg.d_model)[None], "pos_tgt", "float32")

        # masks -> additive biases
        src_pad = fluid.layers.cast(
            fluid.layers.tensor.not_equal(
                src_ids, fluid.layers.tensor.fill_constant([1], "int64", cfg.pad_id)
            ), "float32",
        )  # [B,S] 1=token
        src_bias = fluid.layers.reshape(
            fluid.layers.scale(src_pad, scale=1e4, bias=-1e4), [0, 1, 1, src_len]
        )
        causal = np.triu(np.full((tgt_len, tgt_len), -1e4, "float32"), k=1)
        tgt_bias = _const(causal[None, None], "causal", "float32")

        # encoder
        x = _embed(src_ids, cfg, pos_src)
        for i in range(cfg.n_enc_layers):
            nm = f"enc_{i}"
            if cfg.pre_ln:
                xn = _ln(x, cfg, nm + ".ln1")
                x = _res_drop(x, _mha(xn, xn, src_bias, cfg, nm + ".self"), cfg)
                x = _res_drop(x, _ffn(_ln(x, cfg, nm + ".ln2"), cfg, nm + ".ffn"), cfg)
            else:
                x = _ln(_res_drop(x, _mha(x, x, src_bias, cfg, nm + ".self"), cfg),
                        cfg, nm + ".ln1")
                x = _ln(_res_drop(x, _ffn(x, cfg, nm + ".ffn"), cfg), cfg, nm + ".ln2")
        if cfg.pre_ln:
            x = _ln(x, cfg, "enc_ln")
        enc_out = x

        # decoder
        y = _embed(tgt_ids, cfg, pos_tgt)
        for i in range(cfg.n_dec_layers):
            nm = f"dec_{i}"
            if cfg.pre_ln:
                yn = _ln(y, cfg, nm + ".ln1")
                y = _res_drop(y, _mha(yn, yn, tgt_bias, cfg, nm + ".self"), cfg)
                y = _res_drop(
                    y, _mha(_ln(y, cfg, nm + ".ln2"), enc_out, src_bias, cfg,
                            nm + ".cross"), cfg)
                y = _res_drop(y, _ffn(_ln(y, cfg, nm + ".ln3"), cfg, nm + ".ffn"), cfg)
            else:
                y = _ln(_res_drop(y, _mha(y, y, tgt_bias, cfg, nm + ".self"), cfg),
                        cfg, nm + ".ln1")
                y = _ln(_res_drop(y, _mha(y, enc_out, src_bias, cfg, nm + ".cross"), cfg),
                        cfg, nm + ".ln2")
                y = _ln(_res_drop(y, _ffn(y, cfg, nm + ".ffn"), cfg), cfg, nm + ".ln3")
        if cfg.pre_ln:
            y = _ln(y, cfg, "dec_ln")

        # tied output projection: logits = y @ word_emb^T
        word_emb = main.global_block().var("word_emb")
        logits = fluid.layers.matmul(y, word_emb, transpose_y=True)  # [B,T,V]

        # label-smoothed CE over non-pad positions
        labels3 = fluid.layers.reshape(labels, [0, tgt_len, 1])
        nll = fluid.layers.softmax_with_cross_entropy(logits, labels3, axis=-1)
        logp = fluid.layers.log_softmax(logits)  # [B,T,V]
        uniform = fluid.layers.scale(
            fluid.layers.reduce_sum(logp, dim=[-1], keep_dim=True),
            scale=-1.0 / cfg.vocab_size,
        )
        eps = cfg.label_smooth
        tok_loss = fluid.layers.elementwise_add(
            fluid.layers.scale(nll, scale=1.0 - eps),
            fluid.layers.scale(uniform, scale=eps),
        )  # [B,T,1]
        non_pad = fluid.layers.cast(
            fluid.layers.tensor.not_equal(
                labels, fluid.layers.tensor.fill_constant([1], "int64", cfg.pad_id)
            ), "float32",
        )
        non_pad3 = fluid.layers.reshape(non_pad, [0, tgt_len, 1])
        denom = fluid.layers.elementwise_max(
            fluid.layers.reduce_sum(non_pad3),
            fluid.layers.tensor.fill_constant([1], "float32", 1.0),
        )
        loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(tok_loss, non_pad3)
            ),
            denom,
        )

        if optimizer is None:
            sched = fluid.layers.scale(
                fluid.layers.learning_rate_scheduler.noam_decay(
                    cfg.d_model, warmup_steps=warmup
                ),
                scale=lr,
            )
            optimizer = fluid.optimizer.Adam(
                learning_rate=sched, beta1=0.9, beta2=0.997, epsilon=1e-9
            )
        optimizer.minimize(loss)
    return main, startup, [src_ids, tgt_ids, labels], [loss]


# ---------------------------------------------------------------------------
# functional decoder (beam search, one jitted while_loop)
# ---------------------------------------------------------------------------


def params_from_scope(cfg, scope=None):
    """Pull trained weights by name into a flat dict of jnp arrays."""
    scope = scope or fluid.global_scope()
    names = ["word_emb"]
    for i in range(cfg.n_enc_layers):
        nm = f"enc_{i}"
        for part in (".self.q", ".self.k", ".self.v", ".self.out",
                     ".ffn1", ".ffn2"):
            names += [nm + part + ".w", nm + part + ".b"]
        for part in (".ln1", ".ln2"):
            names += [nm + part + ".scale", nm + part + ".bias"]
    for i in range(cfg.n_dec_layers):
        nm = f"dec_{i}"
        for part in (".self.q", ".self.k", ".self.v", ".self.out",
                     ".cross.q", ".cross.k", ".cross.v", ".cross.out",
                     ".ffn1", ".ffn2"):
            names += [nm + part + ".w", nm + part + ".b"]
        for part in (".ln1", ".ln2", ".ln3"):
            names += [nm + part + ".scale", nm + part + ".bias"]
    if cfg.pre_ln:
        for nm in ("enc_ln", "dec_ln"):
            names += [nm + ".scale", nm + ".bias"]
    out = {}
    for n in names:
        v = scope.find_var(n)
        if v is None:
            raise KeyError(f"parameter {n} not found in scope (train first?)")
        out[n] = jnp.asarray(v)
    return out


def _f_ln(p, nm, x):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + 1e-5) * p[nm + ".scale"] + p[nm + ".bias"]


def _f_dense(p, nm, x, act=None):
    y = x @ p[nm + ".w"] + p[nm + ".b"]
    return jax.nn.relu(y) if act == "relu" else y


def _f_heads(cfg, t):
    B, S, _ = t.shape
    return t.reshape(B, S, cfg.n_heads, -1).transpose(0, 2, 1, 3)


def _f_mha(p, nm, cfg, q_in, kv_in, bias):
    d = cfg.d_model // cfg.n_heads
    q = _f_heads(cfg, _f_dense(p, nm + ".q", q_in))
    k = _f_heads(cfg, _f_dense(p, nm + ".k", kv_in))
    v = _f_heads(cfg, _f_dense(p, nm + ".v", kv_in))
    s = q @ k.transpose(0, 1, 3, 2) / math.sqrt(d) + bias
    ctx = jax.nn.softmax(s, axis=-1) @ v
    B = ctx.shape[0]
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, -1, cfg.d_model)
    return _f_dense(p, nm + ".out", ctx)


def _f_encode(p, cfg, src_ids):
    """src_ids [B,S] -> (enc_out [B,S,H], src_bias [B,1,1,S])."""
    B, S = src_ids.shape
    x = p["word_emb"][src_ids] * math.sqrt(cfg.d_model)
    x = x + jnp.asarray(_sinusoid(S, cfg.d_model))[None]
    src_bias = jnp.where(src_ids == cfg.pad_id, -1e4, 0.0).astype(jnp.float32)
    src_bias = src_bias[:, None, None, :]
    for i in range(cfg.n_enc_layers):
        nm = f"enc_{i}"
        if cfg.pre_ln:
            xn = _f_ln(p, nm + ".ln1", x)
            x = x + _f_mha(p, nm + ".self", cfg, xn, xn, src_bias)
            x = x + _f_dense(p, nm + ".ffn2",
                             _f_dense(p, nm + ".ffn1",
                                      _f_ln(p, nm + ".ln2", x), act="relu"))
        else:
            x = _f_ln(p, nm + ".ln1",
                      x + _f_mha(p, nm + ".self", cfg, x, x, src_bias))
            x = _f_ln(p, nm + ".ln2",
                      x + _f_dense(p, nm + ".ffn2",
                                   _f_dense(p, nm + ".ffn1", x, act="relu")))
    if cfg.pre_ln:
        x = _f_ln(p, "enc_ln", x)
    return x, src_bias


def make_beam_decoder(cfg, beam_size=4, max_len=None, length_penalty=0.6):
    """Returns a jitted fn: (params, src_ids [B,S]) -> (tokens [B,L],
    scores [B]). Greedy = beam_size 1. The whole search — encoder, KV-cached
    decoder steps, beam bookkeeping — is one XLA computation."""
    max_len = max_len or cfg.max_len
    K, V, H = beam_size, cfg.vocab_size, cfg.d_model
    n_h, d_h = cfg.n_heads, cfg.d_model // cfg.n_heads
    NEG = -1e9

    pos_table = jnp.asarray(_sinusoid(max_len, cfg.d_model))

    def step_logits(p, tok, t, self_caches, cross_kv, src_bias):
        """tok [N] current input token; returns (logits [N,V], new caches).
        self_caches: per dec layer (k,v) [N, n_h, max_len, d_h]."""
        N = tok.shape[0]
        x = p["word_emb"][tok][:, None, :] * math.sqrt(H)  # [N,1,H]
        x = x + lax.dynamic_slice_in_dim(pos_table, t, 1)[None]
        new_caches = []
        # causal bias over cache positions: only <= t visible
        valid = (jnp.arange(max_len) <= t).astype(jnp.float32)
        self_bias = (1.0 - valid) * NEG  # [max_len]
        def self_attn(nm, xin, i):
            q = _f_heads(cfg, _f_dense(p, nm + ".self.q", xin))  # [N,h,1,d]
            k1 = _f_heads(cfg, _f_dense(p, nm + ".self.k", xin))
            v1 = _f_heads(cfg, _f_dense(p, nm + ".self.v", xin))
            ck, cv = self_caches[i]
            ck = lax.dynamic_update_slice_in_dim(ck, k1, t, axis=2)
            cv = lax.dynamic_update_slice_in_dim(cv, v1, t, axis=2)
            new_caches.append((ck, cv))
            s = (q @ ck.transpose(0, 1, 3, 2)) / math.sqrt(d_h)
            s = s + self_bias[None, None, None, :]
            ctx = jax.nn.softmax(s, axis=-1) @ cv  # [N,h,1,d]
            ctx = ctx.transpose(0, 2, 1, 3).reshape(N, 1, H)
            return _f_dense(p, nm + ".self.out", ctx)

        def cross_attn(nm, xin, i):
            ek, ev = cross_kv[i]  # [N,h,S,d]
            q2 = _f_heads(cfg, _f_dense(p, nm + ".cross.q", xin))
            s2 = (q2 @ ek.transpose(0, 1, 3, 2)) / math.sqrt(d_h) + src_bias
            ctx2 = jax.nn.softmax(s2, axis=-1) @ ev
            ctx2 = ctx2.transpose(0, 2, 1, 3).reshape(N, 1, H)
            return _f_dense(p, nm + ".cross.out", ctx2)

        def ffn(nm, xin):
            return _f_dense(p, nm + ".ffn2",
                            _f_dense(p, nm + ".ffn1", xin, act="relu"))

        for i in range(cfg.n_dec_layers):
            nm = f"dec_{i}"
            if cfg.pre_ln:
                x = x + self_attn(nm, _f_ln(p, nm + ".ln1", x), i)
                x = x + cross_attn(nm, _f_ln(p, nm + ".ln2", x), i)
                x = x + ffn(nm, _f_ln(p, nm + ".ln3", x))
            else:
                x = _f_ln(p, nm + ".ln1", x + self_attn(nm, x, i))
                x = _f_ln(p, nm + ".ln2", x + cross_attn(nm, x, i))
                x = _f_ln(p, nm + ".ln3", x + ffn(nm, x))
        if cfg.pre_ln:
            x = _f_ln(p, "dec_ln", x)
        logits = (x[:, 0, :] @ p["word_emb"].T)  # [N,V]
        return logits, new_caches

    def decode(p, src_ids):
        B, S = src_ids.shape
        N = B * K
        enc_out, src_bias = _f_encode(p, cfg, src_ids)
        # expand to beams
        enc_out = jnp.repeat(enc_out, K, axis=0)           # [N,S,H]
        src_bias_n = jnp.repeat(src_bias, K, axis=0)       # [N,1,1,S]
        cross_kv = []
        for i in range(cfg.n_dec_layers):
            nm = f"dec_{i}"
            ek = _f_heads(cfg, _f_dense(p, nm + ".cross.k", enc_out))
            ev = _f_heads(cfg, _f_dense(p, nm + ".cross.v", enc_out))
            cross_kv.append((ek, ev))

        ys = jnp.full((B, K, max_len), cfg.pad_id, jnp.int32)
        scores = jnp.tile(
            jnp.array([0.0] + [NEG] * (K - 1), jnp.float32)[None], (B, 1)
        )
        finished = jnp.zeros((B, K), bool)
        tok = jnp.full((N,), cfg.bos_id, jnp.int32)
        caches = tuple(
            (jnp.zeros((N, n_h, max_len, d_h), jnp.float32),
             jnp.zeros((N, n_h, max_len, d_h), jnp.float32))
            for _ in range(cfg.n_dec_layers)
        )

        lengths = jnp.zeros((B, K), jnp.int32)

        def body(state):
            t, ys, scores, finished, lengths, tok, caches = state
            logits, caches = step_logits(
                p, tok, t, caches, cross_kv, src_bias_n
            )
            logp = jax.nn.log_softmax(logits).reshape(B, K, V)
            # finished beams: only EOS continuation, at zero added cost
            eos_only = jnp.full((V,), NEG).at[cfg.eos_id].set(0.0)
            logp = jnp.where(finished[:, :, None], eos_only[None, None, :], logp)
            cand = scores[:, :, None] + logp              # [B,K,V]
            top_scores, top_idx = lax.top_k(cand.reshape(B, K * V), K)
            beam_idx = top_idx // V                        # [B,K]
            new_tok = (top_idx % V).astype(jnp.int32)
            # reorder beam state
            ys = jnp.take_along_axis(ys, beam_idx[:, :, None], axis=1)
            was_finished = jnp.take_along_axis(finished, beam_idx, axis=1)
            lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
            # already-finished beams write pad (not EOS spam), and their
            # length stays frozen so the GNMT penalty compares true lengths
            write_tok = jnp.where(was_finished, cfg.pad_id, new_tok)
            ys = ys.at[:, :, t].set(write_tok)
            lengths = jnp.where(was_finished, lengths, t + 1)
            finished = was_finished | (new_tok == cfg.eos_id)
            flat_idx = (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)
            caches = tuple(
                (ck[flat_idx], cv[flat_idx]) for ck, cv in caches
            )
            return (t + 1, ys, top_scores, finished, lengths,
                    new_tok.reshape(-1), caches)

        def cond2(state):
            t, _, _, finished, _, _, _ = state
            return (t < max_len) & ~finished.all()

        state = (jnp.array(0), ys, scores, finished, lengths, tok, caches)
        _, ys, scores, finished, lengths, _, _ = lax.while_loop(
            cond2, body, state
        )
        # length penalty (GNMT): score / ((5+len)/6)^alpha
        lp = ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** length_penalty
        norm = scores / jnp.where(lp == 0, 1.0, lp)
        best = norm.argmax(axis=1)
        return (
            jnp.take_along_axis(ys, best[:, None, None], axis=1)[:, 0, :],
            jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0],
        )

    from paddle_tpu.core.lowering import jit_compile

    return jit_compile(decode)


class BucketedBeamTranslator:
    """AOT bucketed-length beam-search serving — BASELINE workload 4's
    inference half ("dynamic-shape sequences, beam-search infer"). XLA
    compiles one executable per static shape, so dynamic source lengths
    are served by LENGTH BUCKETS: an incoming batch pads (cfg.pad_id) to
    the smallest bucket >= its length and runs that bucket's pre-compiled
    decode. Pad keys are masked in encoder self-attention AND decoder
    cross-attention (src_bias), so the bucket-padded result equals the
    exact-length run bit-for-bit — asserted by tests/test_transformer.py.

    The reference streams beam search through per-step LoD ops on the host
    (reference: paddle/fluid/operators/beam_search_op.cc); here each
    bucket's whole search is ONE jitted while_loop (make_beam_decoder),
    and `warmup` AOT-compiles every bucket before serving. Throughput is
    tracked as generated (non-pad) tokens per wall-second."""

    def __init__(self, cfg, params, beam_size=4,
                 src_buckets=(16, 32, 64, 128, 256), batch_size=None,
                 max_len=None, length_penalty=0.6):
        self.cfg = cfg
        self.params = params
        self.batch_size = batch_size
        self.buckets = tuple(sorted(src_buckets))
        self._decode = make_beam_decoder(
            cfg, beam_size=beam_size, max_len=max_len,
            length_penalty=length_penalty,
        )
        self.stats = {
            "tokens": 0, "seconds": 0.0, "sentences": 0,
            "bucket_hits": {b: 0 for b in self.buckets},
        }

    def _bucket_for(self, length):
        for b in self.buckets:
            if length <= b:
                return b
        raise ValueError(
            f"source length {length} exceeds the largest bucket "
            f"{self.buckets[-1]} — add a bucket or truncate"
        )

    def warmup(self, batch_size=None):
        """AOT-compile every bucket's executable up front (serving must
        not pay a compile on the first real request). Warming BINDS the
        serving batch size: translate() then row-pads every request to it,
        so real traffic only ever hits the pre-compiled shapes."""
        bs = batch_size or self.batch_size or 1
        self.batch_size = bs
        for b in self.buckets:
            dummy = jnp.full((bs, b), self.cfg.pad_id, jnp.int32)
            toks, _ = self._decode(self.params, dummy)
            toks.block_until_ready()
        return self

    def translate(self, src_ids):
        """src_ids [B, L] int -> (tokens [B, max_len], scores [B]).
        Routes to the length bucket, padding batch rows if a fixed
        batch_size was configured."""
        import time

        src = np.asarray(src_ids)
        B, L = src.shape
        bucket = self._bucket_for(L)
        padded = np.full((B, bucket), self.cfg.pad_id, src.dtype)
        padded[:, :L] = src
        rows = B
        if self.batch_size is not None:
            if B > self.batch_size:
                raise ValueError(
                    f"batch {B} > configured batch_size {self.batch_size}"
                )
            if B < self.batch_size:
                pad_rows = np.full(
                    (self.batch_size - B, bucket), self.cfg.pad_id,
                    src.dtype,
                )
                padded = np.concatenate([padded, pad_rows], axis=0)
        t0 = time.perf_counter()
        toks, scores = self._decode(self.params, jnp.asarray(padded))
        toks = np.asarray(toks)[:rows]
        scores = np.asarray(scores)[:rows]
        dt = time.perf_counter() - t0
        generated = int((toks != self.cfg.pad_id).sum())
        self.stats["tokens"] += generated
        self.stats["seconds"] += dt
        self.stats["sentences"] += rows
        self.stats["bucket_hits"][bucket] += 1
        return toks, scores

    def tokens_per_sec(self):
        s = self.stats["seconds"]
        return self.stats["tokens"] / s if s else 0.0


def synthetic_batch(rng, batch, src_len, tgt_len, cfg):
    """Copy-task data: target = source (the model must learn identity),
    giving a real learnable signal for convergence tests."""
    body = rng.randint(3, cfg.vocab_size, (batch, src_len - 1)).astype("int64")
    src = np.concatenate(
        [body, np.full((batch, 1), cfg.pad_id, "int64")], axis=1
    )
    tgt_in = np.full((batch, tgt_len), cfg.pad_id, "int64")
    labels = np.full((batch, tgt_len), cfg.pad_id, "int64")
    L = min(tgt_len - 1, src_len - 1)
    tgt_in[:, 0] = cfg.bos_id
    tgt_in[:, 1:L + 1] = body[:, :L]
    labels[:, :L] = body[:, :L]
    labels[:, L] = cfg.eos_id
    return {"src_ids": src, "tgt_ids": tgt_in, "labels": labels}
