"""Datasets: high-throughput file-based training input.

Reference: python/paddle/fluid/dataset.py — DatasetFactory :22,
InMemoryDataset :292 (load_into_memory + local/global shuffle),
QueueDataset :672 (streaming); backed by the C++ data-feed layer
(reference: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed,
data_set.cc DatasetImpl). Here the native backend is csrc/datafeed —
threaded MultiSlot parsing, shuffle, and padded batch assembly in C++ —
bound via ctypes with a pure-Python fallback. Variable-length slots come
back as padded [B, maxlen] arrays plus a `<name>.lens` int64 vector
(TPU-friendly padding + lengths instead of LoD, SURVEY §5.7).
"""

import ctypes
import os

import numpy as np

from paddle_tpu.observability import lockdep
from paddle_tpu.utils.enforce import enforce
from paddle_tpu.utils.native import NativeBuildError, load_native

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


def _fleet_rank_world(fleet):
    if fleet is not None:
        try:
            return fleet.worker_index(), fleet.worker_num()
        except Exception:
            pass
    return (
        int(os.environ.get("PADDLE_TRAINER_ID", "0")),
        int(os.environ.get("PADDLE_TRAINERS_NUM", "1")),
    )


class DatasetFactory:
    """reference: dataset.py:22."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class}")


class _SlotSpec:
    def __init__(self, name, dtype, length):
        self.name = name
        self.dtype = dtype  # "float32" | "int64"
        self.length = length  # >0 fixed, -1 variable


class _PyFeed:
    """Pure-Python fallback backend mirroring the native C ABI semantics."""

    def __init__(self, slots):
        self.slots = slots
        self.records = []
        self.order = None
        self._cursor = 0
        self._batch = []

    def load_buffer(self, text):
        for line in text.splitlines():
            if not line.strip():
                continue
            toks = line.split()
            pos = 0
            rec = []
            for s in self.slots:
                cnt = int(toks[pos]); pos += 1
                vals = toks[pos:pos + cnt]; pos += cnt
                conv = float if s.dtype == "float32" else int
                rec.append([conv(v) for v in vals])
            self.records.append(rec)

    def load_files(self, paths, nthreads):
        for p in paths:
            with open(p) as f:
                self.load_buffer(f.read())

    def size(self):
        return len(self.records)

    def shuffle(self, seed):
        # compose onto the existing permutation (matches the native backend:
        # repeated per-epoch shuffles keep mixing rather than resetting)
        rng = np.random.RandomState(seed)
        if self.order is None or len(self.order) != len(self.records):
            self.order = np.arange(len(self.records))
        rng.shuffle(self.order)

    def begin_pass(self, batch_size, drop_last):
        if self.order is None or len(self.order) != len(self.records):
            self.order = np.arange(len(self.records))
        self._cursor = 0
        self._bs = batch_size
        self._drop = drop_last

    def next_batch(self):
        rem = len(self.records) - self._cursor
        take = min(self._bs, rem)
        if take == 0 or (self._drop and take < self._bs):
            return 0
        self._batch = self.order[self._cursor:self._cursor + take]
        self._cursor += take
        return take

    def batch_arrays(self, slot_idx):
        s = self.slots[slot_idx]
        rows = [self.records[r][slot_idx] for r in self._batch]
        lens = np.array([len(r) for r in rows], dtype=np.int64)
        maxlen = s.length if s.length > 0 else max((len(r) for r in rows), default=0)
        dt = np.float32 if s.dtype == "float32" else np.int64
        out = np.zeros((len(rows), max(maxlen, 1)), dtype=dt)
        for i, r in enumerate(rows):
            out[i, : len(r)] = r[:maxlen] if maxlen else r
        return out, lens


class _NativeFeed:
    """ctypes binding over csrc/datafeed (threaded C++ parse/shuffle/batch)."""

    def __init__(self, slots):
        self.slots = slots
        self.lib = load_native("datafeed")
        lib = self.lib
        lib.paddle_ds_create.restype = ctypes.c_void_p
        lib.paddle_ds_create.argtypes = [ctypes.c_char_p]
        lib.paddle_ds_error.restype = ctypes.c_char_p
        lib.paddle_ds_error.argtypes = [ctypes.c_void_p]
        lib.paddle_ds_load_files.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.c_int,
        ]
        lib.paddle_ds_load_buffer.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_long,
        ]
        lib.paddle_ds_size.restype = ctypes.c_long
        lib.paddle_ds_size.argtypes = [ctypes.c_void_p]
        lib.paddle_ds_shuffle.argtypes = [ctypes.c_void_p, ctypes.c_uint]
        lib.paddle_ds_begin_pass.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
        ]
        lib.paddle_ds_next_batch.restype = ctypes.c_int
        lib.paddle_ds_next_batch.argtypes = [ctypes.c_void_p]
        lib.paddle_ds_batch_maxlen.restype = ctypes.c_int
        lib.paddle_ds_batch_maxlen.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.paddle_ds_batch_copy.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.paddle_ds_destroy.argtypes = [ctypes.c_void_p]
        spec = ",".join(
            f"{s.name}:{'f' if s.dtype == 'float32' else 'i'}:{s.length}"
            for s in slots
        )
        self.h = lib.paddle_ds_create(spec.encode())
        enforce(self.h, f"bad slot spec {spec}")
        self._cur_bs = 0

    def _check(self, rc):
        if rc != 0:
            raise RuntimeError(self.lib.paddle_ds_error(self.h).decode())

    def load_buffer(self, text):
        data = text.encode()
        self._check(self.lib.paddle_ds_load_buffer(self.h, data, len(data)))

    def load_files(self, paths, nthreads):
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        self._check(
            self.lib.paddle_ds_load_files(self.h, arr, len(paths), nthreads)
        )

    def size(self):
        return self.lib.paddle_ds_size(self.h)

    def shuffle(self, seed):
        self.lib.paddle_ds_shuffle(self.h, seed & 0xFFFFFFFF)

    def begin_pass(self, batch_size, drop_last):
        self.lib.paddle_ds_begin_pass(self.h, batch_size, int(drop_last))

    def next_batch(self):
        self._cur_bs = self.lib.paddle_ds_next_batch(self.h)
        return self._cur_bs

    def batch_arrays(self, slot_idx):
        s = self.slots[slot_idx]
        maxlen = (
            s.length
            if s.length > 0
            else max(self.lib.paddle_ds_batch_maxlen(self.h, slot_idx), 1)
        )
        dt = np.float32 if s.dtype == "float32" else np.int64
        out = np.zeros((self._cur_bs, maxlen), dtype=dt)
        lens = np.zeros(self._cur_bs, dtype=np.int64)
        self.lib.paddle_ds_batch_copy(
            self.h, slot_idx,
            out.ctypes.data_as(ctypes.c_void_p),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            maxlen,
        )
        return out, lens

    def __del__(self):
        try:
            self.lib.paddle_ds_destroy(self.h)
        except Exception:
            pass


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._num_workers = 0
        self._filelist = []
        self._slots = []
        self._feed = None
        self._use_native = True
        self._drop_last = False
        self._emit_lengths = False
        self._loaded = False
        self._pad_to = {}
        self._truncated_rows = {}
        self._warned_truncate = set()
        self._truncate_lock = lockdep.named_lock("dataio.dataset.truncate")
        # the feed backend is a stateful cursor; passes may be driven
        # from pipeline threads (num_workers / DevicePrefetcher), so
        # access is lock-serialized and generation-stamped: starting a
        # new pass invalidates any still-running producer of the old one
        self._feed_lock = lockdep.named_lock("dataio.dataset.feed")
        self._pass_gen = 0

    def truncated_row_counts(self):
        """Per-slot count of rows whose tokens were dropped by pad_to
        truncation (visible data loss, never silent)."""
        return dict(self._truncated_rows)

    # -- configuration (reference: dataset.py DatasetBase) -----------------
    def set_batch_size(self, batch_size):
        self._batch_size = batch_size

    def set_thread(self, thread_num):
        self._thread_num = thread_num

    def set_num_workers(self, num_workers):
        """Pad/assemble batches on the dataio ordered worker pool
        (reference: data_feed.cc's parse threads). Batch ORDER is
        unchanged — round-robin reassembly makes output order independent
        of worker timing — only the numpy padding/bucketing work runs
        concurrently with the training step."""
        self._num_workers = int(num_workers)

    def set_filelist(self, filelist):
        self._filelist = list(filelist)
        self._loaded = False

    def set_use_var(self, var_list):
        """Declare the feed vars, in slot order. Variable-length slots are
        vars whose non-batch shape is unknown (any -1 beyond dim 0)."""
        self._slots = []
        for v in var_list:
            dtype = "int64" if "int" in str(v.dtype) else "float32"
            trailing = list(v.shape[1:]) if v.shape else []
            if trailing and all(isinstance(d, int) and d > 0 for d in trailing):
                length = int(np.prod(trailing))
            else:
                length = -1
            self._slots.append(_SlotSpec(v.name, dtype, length))

    def set_emit_lengths(self, emit=True):
        """Also yield `<name>.lens` int64 arrays for variable-length slots."""
        self._emit_lengths = emit

    def set_pad_to(self, pad_lengths):
        """Fixed pad length per variable-length slot: {slot_name: L}. Without
        this, var-len slots pad to the next power of two above the batch max
        (shape bucketing) — otherwise every distinct batch max-length would
        recompile the XLA step (the cache keys on feed shapes)."""
        self._pad_to.update(pad_lengths)

    def _make_feed(self):
        if self._feed is not None:
            return self._feed
        enforce(self._slots, "call set_use_var before loading data")
        if self._use_native:
            try:
                self._feed = _NativeFeed(self._slots)
            except NativeBuildError:
                self._feed = _PyFeed(self._slots)
        else:
            self._feed = _PyFeed(self._slots)
        return self._feed

    def _load(self):
        feed = self._make_feed()
        if self._filelist and not self._loaded:
            feed.load_files(self._filelist, self._thread_num)
            self._loaded = True

    # -- iteration ---------------------------------------------------------
    def _assemble_batch(self, raw):
        """Pad/bucket one raw batch ([(arr, lens)] per slot) into the feed
        dict. Pure numpy — safe on the worker pool; the truncation
        bookkeeping is the only shared state and sits under a lock."""
        out = {}
        for s, (arr, lens) in zip(self._slots, raw):
            if s.length < 0:
                want = self._pad_to.get(s.name)
                if want is None:
                    # bucket to next pow2 so step shapes stabilize
                    want = 1 << max(int(np.ceil(np.log2(arr.shape[1]))), 0)
                if arr.shape[1] < want:
                    arr = np.pad(arr, [(0, 0), (0, want - arr.shape[1])])
                elif arr.shape[1] > want:
                    # truncation drops real tokens — make the data loss
                    # visible (once per slot) instead of silent
                    with self._truncate_lock:
                        self._truncated_rows[s.name] = self._truncated_rows.get(
                            s.name, 0
                        ) + int(np.sum(lens > want))
                        first = s.name not in self._warned_truncate
                        self._warned_truncate.add(s.name)
                    if first:
                        import warnings

                        warnings.warn(
                            f"slot '{s.name}': batch length {arr.shape[1]} "
                            f"exceeds pad_to={want}; truncating (tokens are "
                            "dropped — raise pad_to to keep them). "
                            "Truncated-row counts accumulate in "
                            "dataset.truncated_row_counts()."
                        )
                    arr = arr[:, :want]
            out[s.name] = arr
            if self._emit_lengths and s.length < 0:
                out[s.name + ".lens"] = np.minimum(lens, arr.shape[1])
        return out

    def _iter_batches(self, drop_last=None):
        self._load()
        feed = self._feed
        drop = self._drop_last if drop_last is None else drop_last
        with self._feed_lock:
            self._pass_gen += 1
            gen = self._pass_gen
            feed.begin_pass(self._batch_size, drop)

        def raw_batches():
            # the backend cursor is stateful, so raw extraction stays
            # serial (one atomic next_batch+copy per lock hold); the
            # numpy pad/assemble work is what parallelizes. A producer
            # thread left over from an ABANDONED pass sees the bumped
            # generation and stops instead of corrupting the new cursor.
            while True:
                with self._feed_lock:
                    if gen != self._pass_gen:
                        return  # superseded by a newer pass
                    if feed.next_batch() <= 0:
                        return
                    raw = [feed.batch_arrays(i)
                           for i in range(len(self._slots))]
                yield raw

        from paddle_tpu.dataio.engine import parallel_map_ordered

        # num_workers=0 runs the pool's synchronous path — identical
        # ordering/error contract and the same spans/metrics
        yield from parallel_map_ordered(
            raw_batches(), self._assemble_batch, self._num_workers,
            name="dataset",
        )

    def get_memory_data_size(self):
        return self._feed.size() if self._feed else 0


class InMemoryDataset(DatasetBase):
    """reference: dataset.py:292."""

    def load_into_memory(self):
        self._load()

    def local_shuffle(self, seed=0):
        enforce(self._feed is not None, "load_into_memory first")
        self._feed.shuffle(seed)

    def global_shuffle(self, fleet=None, thread_num=12, seed=0,
                       exchange_dir=None, timeout=300):
        """Cross-worker record exchange + local shuffle.

        The reference moves records between workers over PS RPC
        (reference: paddle/fluid/framework/data_set.cc GlobalShuffle); the
        TPU build's exchange plane is the shared filesystem the fleet
        already requires for checkpoints (the reference's own Gloo
        rendezvous ran over HDFS paths): each worker hash-partitions its
        raw records into per-destination files under `exchange_dir`,
        barriers on done-markers, re-reads the partitions addressed to it,
        then local-shuffles. Single worker (or no exchange_dir in a
        single-process job) degrades to local_shuffle.
        """
        rank, world = _fleet_rank_world(fleet)
        if world <= 1:
            self.local_shuffle(seed)
            return
        enforce(
            exchange_dir is not None,
            "global_shuffle across workers needs exchange_dir= on a "
            "shared filesystem",
        )
        import glob as _glob
        import hashlib
        import time as _time

        # per-call epoch namespace: reusing one exchange_dir across epochs
        # must not see the previous epoch's done-markers (instant barrier
        # pass over half-written files) or clobber part files that ARE the
        # current filelist
        self._shuffle_epoch = getattr(self, "_shuffle_epoch", -1) + 1
        exchange_dir = os.path.join(
            exchange_dir, f"epoch_{self._shuffle_epoch}"
        )
        os.makedirs(exchange_dir, exist_ok=True)
        outs = [
            open(os.path.join(exchange_dir, f"part_src{rank}_dst{d}.txt"),
                 "w")
            for d in range(world)
        ]
        n_records = 0
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    h = hashlib.md5(
                        (str(seed) + line).encode()
                    ).digest()
                    outs[int.from_bytes(h[:4], "little") % world].write(line)
                    n_records += 1
        for o in outs:
            o.close()
        with open(os.path.join(exchange_dir, f"done_{rank}"), "w") as f:
            f.write(str(n_records))
        deadline = _time.monotonic() + timeout
        while True:
            done = _glob.glob(os.path.join(exchange_dir, "done_*"))
            if len(done) >= world:
                break
            enforce(
                _time.monotonic() < deadline,
                f"global_shuffle barrier timed out: {len(done)}/{world} "
                "workers finished partitioning",
            )
            _time.sleep(0.1)
        mine = sorted(
            _glob.glob(os.path.join(exchange_dir, f"part_src*_dst{rank}.txt"))
        )
        self.set_filelist(mine)
        self._feed = None
        self._loaded = False
        self._load()
        self.local_shuffle(seed + rank)

    def release_memory(self):
        self._feed = None
        self._loaded = False


class QueueDataset(DatasetBase):
    """Streaming flavor (reference: dataset.py:672). Batches stream out of
    the native store pass-by-pass without shuffling."""

    def local_shuffle(self, seed=0):
        raise RuntimeError("QueueDataset does not support shuffle")

    def global_shuffle(self, fleet=None, thread_num=12, seed=0):
        raise RuntimeError("QueueDataset does not support shuffle")
