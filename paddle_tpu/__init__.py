"""paddle_tpu: a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid v1.7, built on JAX/XLA/Pallas/pjit.

Architecture (vs the reference at /root/reference):
  * Program IR (core/ir.py) mirrors ProgramDesc's structure, but whole blocks
    compile to single XLA computations (core/executor.py) instead of per-op
    kernel dispatch.
  * One jax lowering rule per op (ops/) replaces per-(place,dtype,layout)
    kernels; grads are synthesized from lowerings via jax.vjp (core/backward.py).
  * Distribution is mesh-sharding (compiler.py, parallel/) instead of NCCL
    op-handles; collectives ride ICI via GSPMD/shard_map.
"""

from paddle_tpu.core import (
    CPUPlace,
    TPUPlace,
    Program,
    Scope,
    default_main_program,
    default_startup_program,
    global_scope,
    name_scope,
    program_guard,
    scope_guard,
    is_compiled_with_tpu,
)
from paddle_tpu.core.executor import Executor
from paddle_tpu.core.backward import append_backward, gradients
import paddle_tpu.ops  # noqa: F401  (registers the op library)
from paddle_tpu import layers
from paddle_tpu import initializer
from paddle_tpu import optimizer
from paddle_tpu import regularizer
from paddle_tpu import clip
from paddle_tpu.compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from paddle_tpu import dygraph
from paddle_tpu.dygraph.base import in_dygraph_mode
from paddle_tpu import io
from paddle_tpu import amp
from paddle_tpu.param_attr import ParamAttr, WeightNormParamAttr
from paddle_tpu import reader
from paddle_tpu.reader import DataLoader, PyReader
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu import dataset
from paddle_tpu.dataset import DatasetFactory
from paddle_tpu import trainer_desc
from paddle_tpu import device_worker
from paddle_tpu import contrib
from paddle_tpu import metrics
from paddle_tpu import observability
from paddle_tpu import profiler
from paddle_tpu import debugger
from paddle_tpu import fleet
from paddle_tpu import inference
from paddle_tpu import serving
from paddle_tpu import passes
from paddle_tpu import analysis
from paddle_tpu import resilience
from paddle_tpu import dataio
from paddle_tpu import embedding


class FetchHandler:
    """Periodic fetch callback for dataset training (reference:
    python/paddle/fluid/executor.py:406). Subclass and override handler();
    handler receives {fetch_name: value} built from the train_from_dataset
    fetch_list (var_dict is accepted for reference API parity — fetches are
    selected by fetch_list here, not by this mapping). ``background=True``
    moves delivery onto an observability.FetchHandlerMonitor thread so the
    cadence holds even when single steps outlast period_secs."""

    def __init__(self, var_dict=None, period_secs=60, background=False):
        self.var_dict = var_dict or {}
        self.period_secs = period_secs
        self.background = background

    def handler(self, fetch_vars):
        import numpy as _np

        for name, value in fetch_vars.items():
            print(f"{name}: {_np.asarray(value).reshape(-1)[:8]}")
from paddle_tpu.layers.tensor import data_v2 as data
from paddle_tpu.utils.flags import set_flags, get_flags
from paddle_tpu.utils.enforce import EnforceError

# Alias namespace matching the reference's `fluid` surface
CUDAPlace = TPUPlace  # source compatibility: device index semantics match

__version__ = "0.1.0"
