"""Order-deterministic multi-worker input pipeline.

Reference: paddle/fluid/framework/data_feed.cc runs N parse threads into
per-thread channels, so the batch stream a trainer sees depends on which
thread won each race — two runs of the same job train on different
sample orders. The TPU build keeps the worker pool but makes ordering a
structural property: samples are dispatched round-robin to per-worker
bounded queues, each worker's output queue preserves its own dispatch
order, and the reassembler pops the output queues in the same
round-robin — so the emitted order equals the dispatch order no matter
how long any individual transform takes. Determinism costs head-of-line
blocking on the slowest in-flight sample, which the bounded queues turn
into backpressure rather than unbounded memory.

Workers are THREADS: the transforms this framework cares about (numpy
decode/augment, `DataFeeder.feed` batch assembly, padding) release the
GIL inside BLAS/ufunc loops, so a thread pool scales on CPU-bound
preprocessing without the pickling and fork-safety taxes of process
pools (tools/bench_input.py measures the scaling; the acceptance bar is
2x at four workers).

`DataEngine` composes the pool with a deterministic `ShardedSource`
(source.py) and checkpointable position (state.py): epoch order is a
pure function of (seed, epoch), the cursor only advances when a batch is
EMITTED, and augmentation RNGs are derived per-sample from
(seed, epoch, global index) — so a resumed, re-sharded, or re-timed run
reproduces the exact stream.
"""

import inspect
import itertools
import logging
import queue
import random
import threading
import time

from paddle_tpu.dataio.source import ShardedSource, mix_seed
from paddle_tpu.dataio.state import IteratorState, elastic_resume
from paddle_tpu.observability import registry, trace_scope
from paddle_tpu.observability.logger import RateLimitedLogger
from paddle_tpu.resilience import faults
from paddle_tpu.utils.enforce import enforce

__all__ = ["DataEngine", "parallel_map_ordered"]

log = logging.getLogger("paddle_tpu.dataio")

# queue message kinds (seq, kind, value)
_OK = "ok"
_ERR = "err"
_END = "end"


class _PreErr:
    """A payload whose production already failed (e.g. a source read):
    workers forward it as an error marker without calling the transform,
    so the failure occupies its sequence slot and ordering holds."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def _abortable_put(q, item, stop):
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _pool(iterable, fn, num_workers, queue_depth, name):
    """Yield (seq, kind, value) in strict input order from a round-robin
    worker pool. kind is "ok" (value = fn(payload)) or "err" (value = the
    exception fn or the iterable's producer raised for that slot).
    Exceptions raised by the ITERABLE itself (not tied to one slot)
    propagate after every completed slot."""
    reg = registry()
    labels = {"pipeline": name}
    in_depth = reg.gauge(
        "dataio_queue_depth", "items buffered in pipeline queues",
        labels={**labels, "queue": "in"},
    )
    out_depth = reg.gauge(
        "dataio_queue_depth", "items buffered in pipeline queues",
        labels={**labels, "queue": "out"},
    )
    producer_wait = reg.histogram(
        "dataio_producer_wait_seconds",
        "time workers spent blocked on a full output queue",
        labels=labels,
    )
    consumer_wait = reg.histogram(
        "dataio_consumer_wait_seconds",
        "time the consumer spent blocked waiting for the next result",
        labels=labels,
    )

    if num_workers <= 0:
        # synchronous path: same contract, no threads. fn runs OUTSIDE
        # the yield so consumer close (GeneratorExit) is never mistaken
        # for a record failure.
        for seq, payload in enumerate(iterable):
            if isinstance(payload, _PreErr):
                yield seq, _ERR, payload.exc
                continue
            try:
                with trace_scope("dataio::transform", cat="dataio", seq=seq):
                    res = fn(payload)
            except Exception as e:
                yield seq, _ERR, e
                continue
            yield seq, _OK, res
        return

    w_n = int(num_workers)
    in_qs = [queue.Queue(maxsize=queue_depth) for _ in range(w_n)]
    out_qs = [queue.Queue(maxsize=queue_depth) for _ in range(w_n)]
    stop = threading.Event()
    feed_err = []

    def dispatch():
        try:
            for seq, payload in enumerate(iterable):
                if not _abortable_put(in_qs[seq % w_n],
                                      (seq, payload), stop):
                    return
        except BaseException as e:  # producer failure: surfaces at the end
            feed_err.append(e)
        finally:
            for q_ in in_qs:
                _abortable_put(q_, _END, stop)

    def work(w):
        while True:
            try:
                msg = in_qs[w].get(timeout=0.1)
            except queue.Empty:
                if stop.is_set():
                    return
                continue
            if msg is _END:
                _abortable_put(out_qs[w], _END, stop)
                return
            seq, payload = msg
            if isinstance(payload, _PreErr):
                out = (seq, _ERR, payload.exc)
            else:
                # BaseException is caught so a dying transform can never
                # strand the consumer (the marker must flow), but skip
                # logic downstream only ever skips Exception subclasses
                # — SystemExit/KeyboardInterrupt always re-raise, same
                # as the synchronous path
                try:
                    with trace_scope("dataio::transform", cat="dataio",
                                     seq=seq, worker=w):
                        out = (seq, _OK, fn(payload))
                except BaseException as e:
                    out = (seq, _ERR, e)
            t0 = time.perf_counter()
            if not _abortable_put(out_qs[w], out, stop):
                return
            producer_wait.observe(time.perf_counter() - t0)

    threads = [threading.Thread(target=dispatch, daemon=True,
                                name=f"{name}-dispatch")]
    threads += [
        threading.Thread(target=work, args=(w,), daemon=True,
                         name=f"{name}-worker{w}")
        for w in range(w_n)
    ]
    for t in threads:
        t.start()
    try:
        for seq in itertools.count():
            q_ = out_qs[seq % w_n]
            t0 = time.perf_counter()
            msg = q_.get()
            consumer_wait.observe(time.perf_counter() - t0)
            in_depth.set(sum(x.qsize() for x in in_qs))
            out_depth.set(sum(x.qsize() for x in out_qs))
            if msg is _END:
                break
            got_seq, kind, value = msg
            # structural invariant of round-robin reassembly; a violation
            # means a queue was shared or a worker died mid-slot
            enforce(got_seq == seq,
                    f"dataio pool order broke: got seq {got_seq}, "
                    f"expected {seq}")
            yield got_seq, kind, value
        if feed_err:
            raise feed_err[0]
    finally:
        stop.set()


def parallel_map_ordered(iterable, fn, num_workers, queue_depth=8,
                         name="dataio"):
    """Map `fn` over `iterable` with a deterministic worker pool; yields
    results in input order; the first error (from fn or the producer)
    raises at its input position. The building block DataLoader and
    Dataset ride; DataEngine uses the marker-level pool directly so it
    can convert errors into bounded skips."""
    for _seq, kind, value in _pool(iterable, fn, num_workers, queue_depth,
                                   name):
        if kind == _ERR:
            raise value
        yield value


class DataEngine:
    """Deterministic multi-worker pipeline over a ShardedSource.

        source = ListSource(samples, seed=7)
        engine = DataEngine(source, transform=decode, batch_size=32,
                            num_workers=4)
        for epoch in range(epochs):
            for batch in engine:          # iter == one epoch; resumable
                train_step(batch)
                ckpt.maybe_save(step)     # data position rides along

    Contract: the emitted stream is a pure function of
    (seed, epoch sequence, world size, batch_size, transform) —
    independent of num_workers, worker timing, and host load. `iter()`
    yields the CURRENT epoch from the current cursor, then advances to
    the next epoch; `state_dict()`/`load_state_dict()` round-trip the
    position exactly (cursor counts only samples covered by emitted
    batches, so a checkpoint taken between steps never loses or repeats
    in-flight samples).

    `transform(item)` or `transform(item, rng)`: the two-arg form gets a
    ``random.Random`` seeded from (seed, epoch, global index) — same
    augmentation stream regardless of sharding or worker count.

    ``skip_errors=True`` turns per-record failures (source reads — fault
    site ``dataio.read`` — and transform raises) into bounded, counted,
    rate-limit-logged skips instead of a dead epoch.
    """

    def __init__(self, source, transform=None, batch_size=None,
                 drop_last=False, num_workers=0, queue_depth=8,
                 collate=None, skip_errors=False, max_skips=1024,
                 name="dataio", elastic=False):
        enforce(isinstance(source, ShardedSource),
                f"source must be a ShardedSource, got {type(source)!r}")
        self._source = source
        self._transform = transform
        self._wants_rng = self._transform_wants_rng(transform)
        self._batch_size = batch_size
        self._drop_last = bool(drop_last)
        self._num_workers = int(num_workers)
        self._queue_depth = int(queue_depth)
        self._collate = collate
        self._skip_errors = bool(skip_errors)
        self._max_skips = int(max_skips)
        self._name = name
        # elastic=True lets load_state_dict accept a checkpoint written
        # under a DIFFERENT shard geometry by translating its cursor to
        # the epoch-global stream position (state.elastic_resume);
        # False keeps the strict same-geometry contract.
        self._elastic = bool(elastic)
        # position (the checkpointable part). No live RNG object: every
        # random draw (epoch order, per-sample augmentation) is derived
        # from (seed, epoch, idx), so position + seed IS the RNG state.
        # `_base` is the epoch-global offset this geometry's shards were
        # cut from — 0 except mid-epoch after an elastic resume, and it
        # resets to 0 when the epoch (suffix) is fully consumed.
        self._epoch = 0
        self._cursor = 0
        self._base = 0
        self._emitted_batches = 0
        self._skip_counter = registry().counter(
            "dataio_skipped_records_total",
            "records skipped by skip_errors pipelines",
            labels={"pipeline": name},
        )
        self._batch_counter = registry().counter(
            "dataio_batches_total", "batches emitted by the data engine",
            labels={"pipeline": name},
        )

    @staticmethod
    def _transform_wants_rng(transform):
        if transform is None:
            return False
        try:
            params = [
                p for p in inspect.signature(transform).parameters.values()
                if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            ]
            return len(params) >= 2
        except (TypeError, ValueError):
            return False

    # -- position ----------------------------------------------------------
    @property
    def epoch(self):
        return self._epoch

    @property
    def cursor(self):
        return self._cursor

    @property
    def base(self):
        return self._base

    @property
    def global_cursor(self):
        """Epoch-global stream position already consumed (state.py):
        geometry-free, so it survives elastic resizes."""
        return self._base + self._cursor * self._source.world

    @property
    def emitted_batches(self):
        return self._emitted_batches

    def state_dict(self):
        return IteratorState(
            epoch=self._epoch,
            cursor=self._cursor,
            base=self._base,
            emitted_batches=self._emitted_batches,
            seed=self._source.seed,
            world=self._source.world,
            rank=self._source.rank,
        ).to_dict()

    def load_state_dict(self, d):
        st = IteratorState.from_dict(d)
        if self._elastic and (st.world != self._source.world
                              or st.rank != self._source.rank):
            # a checkpoint from a different gang geometry: translate its
            # cursor to the epoch-global position and re-base this
            # rank's shards on the remaining stream suffix
            log.info(
                "dataio elastic resume: translating state from "
                "world=%d rank=%d to world=%d rank=%d "
                "(global cursor %d)", st.world, st.rank,
                self._source.world, self._source.rank, st.global_cursor(),
            )
            st = IteratorState.from_dict(elastic_resume(
                d, self._source.world, self._source.rank))
        enforce(
            st.world == self._source.world,
            f"checkpointed data state is for world size {st.world}, this "
            f"run has {self._source.world}: the shard cursor is not "
            "portable across world sizes",
        )
        enforce(
            st.rank == self._source.rank,
            f"checkpointed data state belongs to rank {st.rank}, this "
            f"process is rank {self._source.rank}",
        )
        if st.seed != self._source.seed:
            log.warning(
                "dataio resume: checkpoint seed %d != source seed %d; "
                "using the checkpointed seed so the stream continues "
                "exactly", st.seed, self._source.seed,
            )
            self._source.seed = st.seed
        self._epoch = st.epoch
        self._cursor = st.cursor
        self._base = st.base
        self._emitted_batches = st.emitted_batches

    # -- iteration ---------------------------------------------------------
    def _payloads(self, shard, epoch, start):
        """(global_idx, item) payloads for shard positions [start:);
        source-read failures become _PreErr markers so they hold their
        sequence slot (and become skips under skip_errors)."""
        for pos in range(start, len(shard)):
            idx = shard[pos]
            try:
                faults.fire("dataio.read", step=pos)
                item = self._source.item(idx)
            except Exception as e:
                yield _PreErr(e)
                continue
            yield (idx, item)

    def _apply(self, payload):
        idx, item = payload
        if self._transform is None:
            return item
        if self._wants_rng:
            rng = random.Random(mix_seed(self._source.seed, self._epoch, idx))
            return self._transform(item, rng)
        return self._transform(item)

    def __iter__(self):
        epoch = self._epoch
        start = self._cursor
        shard = self._source.epoch_shard(epoch, base=self._base)
        limited = RateLimitedLogger(log, max_records=8)
        skips = 0
        buf = []
        bs = self._batch_size
        with trace_scope("dataio::epoch", cat="dataio", epoch=epoch,
                         start=start, base=self._base,
                         shard_len=len(shard),
                         workers=self._num_workers):
            results = _pool(
                self._payloads(shard, epoch, start), self._apply,
                self._num_workers, self._queue_depth, self._name,
            )
            for seq, kind, value in results:
                pos = start + seq  # position within the epoch shard
                if kind == _ERR:
                    # only Exception subclasses are skippable:
                    # SystemExit/KeyboardInterrupt-class failures abort
                    # the epoch identically for every num_workers
                    if not self._skip_errors or \
                            not isinstance(value, Exception):
                        raise value
                    skips += 1
                    self._skip_counter.inc()
                    if skips > self._max_skips:
                        log.error(
                            "dataio pipeline '%s' exceeded max_skips=%d; "
                            "re-raising", self._name, self._max_skips,
                        )
                        limited.summarize(what="skipped records")
                        raise value
                    limited.warning(
                        "skipping bad record at epoch %d pos %d "
                        "(skip %d/%d): %s: %s", epoch, pos, skips,
                        self._max_skips, type(value).__name__, value,
                    )
                    continue
                if bs is None:
                    self._cursor = pos + 1
                    self._emitted_batches += 1
                    self._batch_counter.inc()
                    yield value
                    continue
                buf.append(value)
                if len(buf) == bs:
                    batch = (self._collate(buf) if self._collate is not None
                             else buf)
                    buf = []
                    self._cursor = pos + 1
                    self._emitted_batches += 1
                    self._batch_counter.inc()
                    yield batch
            if buf and not self._drop_last:
                batch = (self._collate(buf) if self._collate is not None
                         else buf)
                self._cursor = len(shard)
                self._emitted_batches += 1
                self._batch_counter.inc()
                yield batch
            limited.summarize(what="skipped records")
        # epoch fully consumed: advance (a mid-epoch elastic base only
        # lives until its suffix is drained — the next epoch re-shards
        # the full order)
        self._epoch = epoch + 1
        self._cursor = 0
        self._base = 0
