"""Deterministic sharded sample sources.

Reference: paddle/fluid/framework/data_set.cc assigns filelist slices to
trainers and data_feed.cc channels shuffle inside each trainer — but both
draw on process-global RNG, so two runs of the same job see different
streams. Here the epoch order is a PURE FUNCTION of (seed, epoch): a
local ``random.Random((seed, epoch))`` permutes the global index space,
then each rank takes a strided slice. Resuming, re-running, or adding
workers can therefore reconstruct the exact stream from three integers
(seed, epoch, cursor) — the contract `state.py` checkpoints.

Shard geometry comes from ``parallel.env.ParallelEnv`` (the
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM the fleet launcher exports) or
an explicit fleet role, and ragged tails wrap around so every rank's
epoch shard has identical length — collective steps never deadlock on a
rank that ran out of data one batch early.
"""

import random

from paddle_tpu.utils.enforce import enforce

__all__ = ["ShardedSource", "ListSource", "FileSource", "mix_seed"]

def mix_seed(*parts):
    """Fold (seed, epoch[, idx]) into one deterministic integer seed —
    arithmetic, not hash(): stable across processes, interpreters, and
    PYTHONHASHSEED. Fixed 64-bit lanes keep the mix injective for any
    realistic part (no multiplier wraparound where a huge sample index
    could alias the next epoch's stream); python ints are arbitrary
    precision, and random.Random seeds from big ints natively."""
    acc = 0
    for p in parts:
        acc = (acc << 64) | (int(p) & 0xFFFFFFFFFFFFFFFF)
    return acc


def _discover_rank_world(fleet=None):
    """rank/world from an explicit fleet role, else the launcher env."""
    if fleet is not None:
        try:
            return int(fleet.worker_index()), int(fleet.worker_num())
        except Exception:
            pass
    from paddle_tpu.parallel.env import ParallelEnv

    env = ParallelEnv()
    return env.rank, env.world_size


class ShardedSource:
    """Base class: deterministic per-epoch order + per-rank shard.

    Subclasses implement ``__len__`` (global sample count, identical on
    every rank) and ``item(idx)`` (fetch/parse global sample ``idx``).
    """

    def __init__(self, seed=0, shuffle=True, rank=None, world=None,
                 fleet=None):
        if rank is None or world is None:
            d_rank, d_world = _discover_rank_world(fleet)
            rank = d_rank if rank is None else rank
            world = d_world if world is None else world
        enforce(world >= 1, f"world must be >= 1, got {world}")
        enforce(0 <= rank < world, f"rank {rank} outside world {world}")
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.rank = int(rank)
        self.world = int(world)

    # -- subclass surface --------------------------------------------------
    def __len__(self):
        raise NotImplementedError

    def item(self, idx):
        raise NotImplementedError

    # -- deterministic order ----------------------------------------------
    def epoch_order(self, epoch):
        """Global index permutation for `epoch` — same on every rank.
        A LOCAL Random seeded from (seed, epoch): no dependence on the
        module-global RNG or on call history."""
        order = list(range(len(self)))
        if self.shuffle:
            random.Random(mix_seed(self.seed, epoch)).shuffle(order)
        return order

    def epoch_shard(self, epoch, base=0):
        """This rank's slice of the epoch order. The order is first
        padded by cyclic tiling to a multiple of `world`, so every rank
        gets exactly ceil(n / world) samples — equal step counts keep
        data-parallel collectives in lockstep even when the dataset is
        smaller than the world size.

        ``base`` (elastic resume, state.py) cuts the shards from the
        stream SUFFIX ``order[base:]`` instead of the whole epoch: a
        gang resized mid-epoch re-shards exactly the positions the old
        geometry had not consumed, under the same padding rule (the
        suffix wraps onto itself so every rank stays equal-length).
        ``base=0`` is byte-identical to the pre-elastic behavior."""
        order = self.epoch_order(epoch)
        base = int(base)
        if base < 0:
            raise ValueError(f"base must be >= 0, got {base}")
        if base:
            # positions past the real epoch length are wrap-padding the
            # old geometry already consumed — nothing left to re-shard
            order = order[base:] if base < len(order) else []
        if self.world > 1 and order:
            per_rank = -(-len(order) // self.world)
            total = per_rank * self.world
            reps = -(-total // len(order))
            order = (order * reps)[:total]
            return order[self.rank::self.world]
        return order

    def state_dict(self):
        return {
            "seed": self.seed,
            "shuffle": self.shuffle,
            "rank": self.rank,
            "world": self.world,
            "size": len(self),
        }


class ListSource(ShardedSource):
    """In-memory samples (list/sequence)."""

    def __init__(self, items, **kwargs):
        super().__init__(**kwargs)
        self._items = list(items)

    def __len__(self):
        return len(self._items)

    def item(self, idx):
        return self._items[idx]


class FileSource(ShardedSource):
    """Line-record files (the MultiSlot text layout dataset.py consumes).

    The global sample space is the concatenation of all files' non-blank
    lines in filelist order; `parse` (optional) maps the raw line to a
    sample. Lines are indexed lazily on first access so constructing the
    source on every rank stays cheap.
    """

    def __init__(self, filelist, parse=None, **kwargs):
        super().__init__(**kwargs)
        self._filelist = list(filelist)
        self._parse = parse
        self._lines = None

    def _load(self):
        if self._lines is None:
            lines = []
            for path in self._filelist:
                with open(path) as f:
                    lines.extend(l for l in f.read().splitlines()
                                 if l.strip())
            self._lines = lines
        return self._lines

    def __len__(self):
        return len(self._load())

    def item(self, idx):
        line = self._load()[idx]
        return self._parse(line) if self._parse is not None else line
