"""Sparse CTR batch assembly: the ``sparse_batch`` sample transform.

A click-log record carries VARIABLE-length feature-id lists per slot
(the reference feeds these as LoDTensors; data_feed.cc's
MultiSlotDataFeed parses exactly this layout). XLA wants fixed shapes,
so the transform ports the reference's slot layout to a dense
(ids, weights, dense) triple per slot:

* ids pad to ``ids_per_slot`` by REPEATING the slot's first id — the
  padding id is one the batch already contains, so the engine's dedup
  gather (embedding/gather.py) admits no extra unique row for padding;
* weights carry 1.0 for real ids and 0.0 for padding — the model
  multiplies the looked-up rows by the weight, so padding contributes
  exactly 0.0 to the pooled slot embedding (bit-exact against the
  variable-length math, the serving padding discipline);
* an EMPTY slot emits ids of 0 with all-zero weights (one dead unique
  row, zero contribution).

Built for the ordered worker pool: hand the transform to
``DataLoader.from_generator(num_workers=N).set_sample_generator(...,
sample_transform=...)`` (or any ``parallel_map_ordered`` stage) and the
padding/truncation runs on the pool with the engine's deterministic
ordering guarantees.
"""

import numpy as np

__all__ = ["make_sparse_batch_transform", "pad_slot"]


def pad_slot(ids, ids_per_slot, id_dtype="int64"):
    """(ids [S], weights [S]) from a variable-length id list: truncate
    past S, pad by repeating ids[0] at weight 0; empty -> zeros."""
    s = int(ids_per_slot)
    ids = list(ids)[:s]
    n = len(ids)
    if n == 0:
        return (np.zeros(s, dtype=id_dtype),
                np.zeros(s, dtype=np.float32))
    out = np.full(s, ids[0], dtype=id_dtype)
    out[:n] = np.asarray(ids, dtype=id_dtype)
    w = np.zeros(s, dtype=np.float32)
    w[:n] = 1.0
    return out, w


def make_sparse_batch_transform(slots, ids_per_slot, dense=(),
                                label="click", id_dtype="int64"):
    """Per-sample transform for CTR records shaped
    ``{"slots": {name: [ids...]}, <dense fields...>, label: x}``.

    Returns a tuple in feed order — for each slot name: ids [S],
    weights [S]; then each dense field as float32; then the label as
    float32 [1] — matching a feed_list declared in the same order
    (examples/wide_deep.py). Samples missing a slot get the empty-slot
    encoding."""
    slots = list(slots)
    dense = list(dense)

    def transform(sample):
        rec_slots = sample.get("slots", {})
        out = []
        for name in slots:
            ids, w = pad_slot(rec_slots.get(name, ()), ids_per_slot,
                              id_dtype)
            out.append(ids)
            out.append(w)
        for name in dense:
            out.append(np.asarray(sample[name], dtype=np.float32))
        out.append(
            np.asarray([sample[label]], dtype=np.float32)
        )
        return tuple(out)

    return transform
