"""TPU-native data engine: deterministic multi-worker input pipelines.

The fifth subsystem (SURVEY §5.7 — the reference's Dataset/data_feed.cc
+ buffered_reader.cc input plane, rebuilt with determinism and
resumability as first-class properties):

* ``source``   — deterministic sharded sources: per-rank epoch shards as
  a pure function of (seed, epoch) via a local ``random.Random``.
* ``engine``   — ``DataEngine``: a worker pool with round-robin
  reassembly, so the emitted order is independent of worker timing;
  plus ``parallel_map_ordered``, the same pool as a reusable map.
* ``prefetch`` — ``DevicePrefetcher``: bounded double-buffer of
  ``jax.device_put`` batches, sharding-aware for data-parallel meshes.
* ``state``    — checkpointable iterator position (epoch, shard cursor,
  RNG state, emitted-batch count) riding ``incubate/checkpoint.py``
  manifests, so ``resume()`` restores data position exactly; plus the
  elastic translation (``elastic_resume``) that projects a per-rank
  cursor to the epoch-global stream position so a resized gang
  (``DataEngine(elastic=True)``) resumes the exact global stream with
  zero samples lost or double-consumed.

DataLoader (``from_generator(num_workers=...)``) and
``Dataset.set_num_workers`` ride this layer; everything reports
``dataio::`` spans, queue-depth gauges, and producer/consumer wait
histograms through the observability registry, and source reads are a
``dataio.read`` fault site for the resilience harness.
"""

from paddle_tpu.dataio.engine import DataEngine, parallel_map_ordered
from paddle_tpu.dataio.prefetch import DevicePrefetcher
from paddle_tpu.dataio.source import FileSource, ListSource, ShardedSource
from paddle_tpu.dataio.sparse import make_sparse_batch_transform, pad_slot
from paddle_tpu.dataio.state import (
    STATE_KEY,
    IteratorState,
    decode_state,
    elastic_resume,
    encode_state,
)

__all__ = [
    "DataEngine",
    "make_sparse_batch_transform",
    "pad_slot",
    "parallel_map_ordered",
    "DevicePrefetcher",
    "ShardedSource",
    "ListSource",
    "FileSource",
    "IteratorState",
    "STATE_KEY",
    "encode_state",
    "decode_state",
    "elastic_resume",
]
