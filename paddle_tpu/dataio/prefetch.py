"""Device prefetch: bounded double-buffer of device-resident batches.

Reference: paddle/fluid/operators/reader/buffered_reader.cc keeps two
in-flight GPU copies on a dedicated CUDA stream so the H2D of batch N+1
overlaps the compute of batch N. The TPU-native equivalent needs no
stream management: ``jax.device_put`` dispatch is asynchronous, so a
producer thread that stages the NEXT batch while the training loop runs
the current one gets the same overlap; the bounded queue is the
double-buffer (depth 2 by default — deeper only buys memory pressure).

Sharding-aware placement: given a data-parallel mesh and the batch axis
name, each array is placed with ``NamedSharding(mesh, P(batch_axis))``
so every host stages ONLY its shard of the global batch (arrays whose
leading dim does not divide across the axis are replicated instead).
Without a mesh, arrays land on the default (or an explicit) device.
"""

import queue
import threading
import time

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec

from paddle_tpu.dataio.engine import _abortable_put
from paddle_tpu.observability import registry, trace_scope

__all__ = ["DevicePrefetcher"]

_END = object()


class DevicePrefetcher:
    """Iterate `batches` (dicts of arrays, or bare arrays) with a
    background thread that device-puts `depth` batches ahead. Producer
    exceptions re-raise in the consumer at the position they occurred;
    abandoning iteration unblocks and stops the producer.

    Checkpointing a prefetched engine: the producer thread consumes the
    wrapped iterable up to `depth` batches AHEAD of the training loop,
    so snapshotting the ENGINE's position directly would record batches
    still sitting in the queue (resume would skip them). The prefetcher
    therefore proxies checkpoint state itself — it pairs every staged
    batch with the source's state at that point and exposes the pair
    belonging to the last YIELDED batch. Attach the PREFETCHER, not the
    engine:

        pre = DevicePrefetcher(engine, depth=2)
        ckpt = AutoCheckpoint(exe, prog, dirname, data_state=pre)
    """

    def __init__(self, batches, depth=2, mesh=None, batch_axis=None,
                 device=None, name="prefetch"):
        self._batches = batches
        self._last_state = None  # source state as of the last YIELDED batch
        self._depth = max(1, int(depth))
        self._mesh = mesh
        self._batch_axis = batch_axis
        self._device = device
        self._name = name
        reg = registry()
        self._depth_gauge = reg.gauge(
            "dataio_queue_depth", "items buffered in pipeline queues",
            labels={"pipeline": name, "queue": "prefetch"},
        )
        self._consumer_wait = reg.histogram(
            "dataio_consumer_wait_seconds",
            "time the consumer spent blocked waiting for the next result",
            labels={"pipeline": name},
        )
        if mesh is not None and batch_axis is not None:
            self._axis_size = mesh.shape[batch_axis]
        else:
            self._axis_size = None

    # -- placement ---------------------------------------------------------
    def _put_one(self, value):
        arr = value if isinstance(value, jax.Array) else np.asarray(value)
        if self._axis_size is not None:
            if arr.ndim >= 1 and arr.shape[0] % self._axis_size == 0:
                spec = PartitionSpec(self._batch_axis)
            else:  # not batch-shaped along the axis: replicate
                spec = PartitionSpec()
            return jax.device_put(arr, NamedSharding(self._mesh, spec))
        if self._device is not None:
            return jax.device_put(arr, self._device)
        return jax.device_put(arr)

    def _stage(self, batch):
        with trace_scope("dataio::device_put", cat="dataio"):
            if isinstance(batch, dict):
                return {k: self._put_one(v) for k, v in batch.items()}
            if isinstance(batch, (list, tuple)):
                return type(batch)(self._put_one(v) for v in batch)
            return self._put_one(batch)

    # -- checkpointable-state proxy ----------------------------------------
    def state_dict(self):
        """Source position as of the last batch the CONSUMER received —
        not the producer's read-ahead position. Before any batch is
        yielded, falls through to the source's current state."""
        if self._last_state is not None:
            return self._last_state
        getter = getattr(self._batches, "state_dict", None)
        return getter() if getter is not None else None

    def load_state_dict(self, d):
        """Forwards to the wrapped source — including the elastic
        geometry translation when the source is a
        ``DataEngine(elastic=True)`` (the prefetcher proxies position,
        it never owns geometry)."""
        self._batches.load_state_dict(d)
        self._last_state = None

    def global_cursor(self):
        """Epoch-global stream position as of the last batch the
        CONSUMER received (None when the wrapped source keeps no
        state) — the geometry-free coordinate an elastic resize hands
        to the next gang generation. Read from the consumer-exact proxy
        state, NOT the producer's read-ahead position."""
        st = self.state_dict()
        if st is None:
            return None
        from paddle_tpu.dataio.state import IteratorState

        return IteratorState.from_dict(st).global_cursor()

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        q = queue.Queue(maxsize=self._depth)
        err = []
        stop = threading.Event()
        get_state = getattr(self._batches, "state_dict", None)

        def produce():
            try:
                for batch in self._batches:
                    # snapshot the source position the moment the batch
                    # left it: this pair is what state_dict() exposes
                    # once the batch reaches the consumer
                    st = get_state() if get_state is not None else None
                    if not _abortable_put(q, (self._stage(batch), st),
                                          stop):
                        return
            except BaseException as e:
                err.append(e)
            finally:
                _abortable_put(q, _END, stop)

        t = threading.Thread(target=produce, daemon=True,
                             name=f"{self._name}-prefetch")
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                self._consumer_wait.observe(time.perf_counter() - t0)
                self._depth_gauge.set(q.qsize())
                if item is _END:
                    if err:
                        raise err[0]
                    return
                batch, st = item
                if st is not None:
                    self._last_state = st
                yield batch
        finally:
            stop.set()
            while not q.empty():  # unblock producer, drop device buffers
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            # bounded shutdown: the producer sees `stop` within one
            # abortable-put poll; a daemon thread that outlives this is
            # a bug we want joined-or-surfaced, not leaked silently
            t.join(timeout=5.0)
            if t.is_alive():
                from paddle_tpu.observability.logger import get_logger

                get_logger("dataio.prefetch").warning(
                    "prefetch producer %s still alive 5s after abandon "
                    "(blocked in device_put?); leaking daemon thread",
                    t.name)
