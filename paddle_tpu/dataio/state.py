"""Checkpointable iterator state: the data-position half of a checkpoint.

PR 3 made parameter state crash-consistent, but a restored job still
replayed or skipped data because the input iterator's position was not
part of training state (the reference has the same hole: Dataset/
data_feed.cc keep cursors in C++ channel objects that io.py never
serializes). This module defines the schema — epoch, shard cursor, RNG
state, emitted-batch count — plus the codec that rides the existing
`incubate/checkpoint.py` manifests: the state is serialized to a JSON
blob stored as a uint8 array under ``STATE_KEY`` inside ``state.npz``,
so it inherits the per-array CRC32, the whole-file CRC, the atomic
rename, and the corrupt-walkback behavior for free.

Elastic translation (r14): a per-rank cursor is only meaningful under
the shard geometry that produced it, but the GLOBAL stream position is
geometry-free. Ranks consume the epoch's padded order round-robin
(position p belongs to rank ``p % world`` at shard index ``p // world``,
source.py), so a gang whose ranks all sit at shard cursor ``c`` has
consumed exactly the first ``base + c * world`` positions of the epoch
stream. ``IteratorState.global_cursor()`` performs that projection and
``elastic_resume()`` re-bases a checkpointed state onto a NEW
(world, rank): the translated state starts a fresh shard slice of the
REMAINING stream (``base`` = the global cursor, ``cursor`` = 0), which
``ShardedSource.epoch_shard(epoch, base=...)`` turns back into per-rank
sample indices. The round trip loses nothing and repeats nothing: the
old geometry consumed positions ``[0, g)``, the new one consumes
``[g, ...)`` — the contract tools/chaos_elastic.py proves end to end.
"""

import json

import numpy as np

__all__ = [
    "STATE_KEY",
    "STATE_VERSION",
    "IteratorState",
    "encode_state",
    "decode_state",
    "elastic_resume",
]

# array name inside state.npz; dunder-prefixed so it can never collide
# with a program variable name (verifier rejects those)
STATE_KEY = "__dataio_state__"
# version 2 adds `base` (the epoch-global offset this geometry's shards
# started from — 0 except after an elastic resize); version-1 states
# decode with base=0, so pre-elastic checkpoints keep resuming exactly
STATE_VERSION = 2


class IteratorState:
    """Plain data-position record.

    epoch            current epoch number (0-based)
    cursor           samples of THIS RANK's epoch shard already consumed
                     by emitted batches (skipped records count: the
                     cursor is a position in shard order, not a count of
                     good samples)
    base             epoch-global position this geometry's shards were
                     cut from (0 except after an elastic resize: the
                     resumed geometry re-shards the stream suffix
                     starting at `base`)
    emitted_batches  lifetime batch count across epochs (monotonic)
    seed             base seed the per-epoch orders derive from
    world / rank     shard geometry the cursor is valid under
    rng              reserved: the engine derives every draw from
                     (seed, epoch, idx), so no live generator state
                     exists to save; custom sources that DO keep one can
                     round-trip it here (JSON-serializable form)
    """

    def __init__(self, epoch=0, cursor=0, emitted_batches=0, seed=0,
                 world=1, rank=0, rng=None, base=0):
        self.epoch = int(epoch)
        self.cursor = int(cursor)
        self.base = int(base)
        self.emitted_batches = int(emitted_batches)
        self.seed = int(seed)
        self.world = int(world)
        self.rank = int(rank)
        self.rng = rng

    def global_cursor(self):
        """Project the per-rank shard cursor to the epoch-global stream
        position: a gang whose ranks all sit at shard cursor `cursor`
        has consumed exactly the positions ``[0, base + cursor * world)``
        of the epoch stream (ranks consume the padded order round-robin,
        source.py). This is the geometry-free coordinate an elastic
        resize hands to the next gang generation."""
        return self.base + self.cursor * self.world

    def to_dict(self):
        return {
            "version": STATE_VERSION,
            "epoch": self.epoch,
            "cursor": self.cursor,
            "base": self.base,
            "emitted_batches": self.emitted_batches,
            "seed": self.seed,
            "world": self.world,
            "rank": self.rank,
            "rng": self.rng,
        }

    @classmethod
    def from_dict(cls, d):
        version = d.get("version", STATE_VERSION)
        if version > STATE_VERSION:
            raise ValueError(
                f"dataio state version {version} is newer than this "
                f"build understands ({STATE_VERSION})"
            )
        return cls(
            epoch=d.get("epoch", 0),
            cursor=d.get("cursor", 0),
            base=d.get("base", 0),
            emitted_batches=d.get("emitted_batches", 0),
            seed=d.get("seed", 0),
            world=d.get("world", 1),
            rank=d.get("rank", 0),
            rng=d.get("rng"),
        )


def elastic_resume(d, world, rank):
    """Translate a checkpointed state dict onto a NEW shard geometry.

    The old geometry's per-rank cursor projects to the epoch-global
    position ``g = base + cursor * old_world`` (every rank of a
    step-synchronized gang checkpoints the same ``cursor`` at the same
    step, so any rank's blob yields the same ``g``); the translated
    state re-bases rank ``rank`` of the NEW ``world`` at that position:
    the new gang's shards are cut from the stream suffix ``[g, ...)``
    and together consume it exactly once — zero samples lost or
    double-consumed across the resize. ``emitted_batches`` carries over
    as the gang-lifetime count; ``epoch``/``seed`` are untouched, so the
    suffix order is the same permutation the old gang was walking.
    """
    st = IteratorState.from_dict(d)
    world = int(world)
    rank = int(rank)
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} outside world {world}")
    return IteratorState(
        epoch=st.epoch,
        cursor=0,
        base=st.global_cursor(),
        emitted_batches=st.emitted_batches,
        seed=st.seed,
        world=world,
        rank=rank,
        rng=st.rng,
    ).to_dict()


def encode_state(d):
    """dict -> uint8 ndarray of JSON bytes (an npz-storable array, so the
    checkpoint manifest CRCs it like any parameter)."""
    raw = json.dumps(d, sort_keys=True).encode("utf-8")
    return np.frombuffer(raw, dtype=np.uint8).copy()


def decode_state(arr):
    """uint8 ndarray (or bytes) of JSON -> dict."""
    raw = bytes(np.asarray(arr, dtype=np.uint8).tobytes())
    return json.loads(raw.decode("utf-8"))
