"""Checkpointable iterator state: the data-position half of a checkpoint.

PR 3 made parameter state crash-consistent, but a restored job still
replayed or skipped data because the input iterator's position was not
part of training state (the reference has the same hole: Dataset/
data_feed.cc keep cursors in C++ channel objects that io.py never
serializes). This module defines the schema — epoch, shard cursor, RNG
state, emitted-batch count — plus the codec that rides the existing
`incubate/checkpoint.py` manifests: the state is serialized to a JSON
blob stored as a uint8 array under ``STATE_KEY`` inside ``state.npz``,
so it inherits the per-array CRC32, the whole-file CRC, the atomic
rename, and the corrupt-walkback behavior for free.
"""

import json

import numpy as np

__all__ = [
    "STATE_KEY",
    "STATE_VERSION",
    "IteratorState",
    "encode_state",
    "decode_state",
]

# array name inside state.npz; dunder-prefixed so it can never collide
# with a program variable name (verifier rejects those)
STATE_KEY = "__dataio_state__"
STATE_VERSION = 1


class IteratorState:
    """Plain data-position record.

    epoch            current epoch number (0-based)
    cursor           samples of THIS RANK's epoch shard already consumed
                     by emitted batches (skipped records count: the
                     cursor is a position in shard order, not a count of
                     good samples)
    emitted_batches  lifetime batch count across epochs (monotonic)
    seed             base seed the per-epoch orders derive from
    world / rank     shard geometry the cursor is valid under
    rng              reserved: the engine derives every draw from
                     (seed, epoch, idx), so no live generator state
                     exists to save; custom sources that DO keep one can
                     round-trip it here (JSON-serializable form)
    """

    def __init__(self, epoch=0, cursor=0, emitted_batches=0, seed=0,
                 world=1, rank=0, rng=None):
        self.epoch = int(epoch)
        self.cursor = int(cursor)
        self.emitted_batches = int(emitted_batches)
        self.seed = int(seed)
        self.world = int(world)
        self.rank = int(rank)
        self.rng = rng

    def to_dict(self):
        return {
            "version": STATE_VERSION,
            "epoch": self.epoch,
            "cursor": self.cursor,
            "emitted_batches": self.emitted_batches,
            "seed": self.seed,
            "world": self.world,
            "rank": self.rank,
            "rng": self.rng,
        }

    @classmethod
    def from_dict(cls, d):
        version = d.get("version", STATE_VERSION)
        if version > STATE_VERSION:
            raise ValueError(
                f"dataio state version {version} is newer than this "
                f"build understands ({STATE_VERSION})"
            )
        return cls(
            epoch=d.get("epoch", 0),
            cursor=d.get("cursor", 0),
            emitted_batches=d.get("emitted_batches", 0),
            seed=d.get("seed", 0),
            world=d.get("world", 1),
            rank=d.get("rank", 0),
            rng=d.get("rng"),
        )


def encode_state(d):
    """dict -> uint8 ndarray of JSON bytes (an npz-storable array, so the
    checkpoint manifest CRCs it like any parameter)."""
    raw = json.dumps(d, sort_keys=True).encode("utf-8")
    return np.frombuffer(raw, dtype=np.uint8).copy()


def decode_state(arr):
    """uint8 ndarray (or bytes) of JSON -> dict."""
    raw = bytes(np.asarray(arr, dtype=np.uint8).tobytes())
    return json.loads(raw.decode("utf-8"))
