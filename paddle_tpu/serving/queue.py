"""Bounded admission queue with priority lanes and deadline expiry.

The reference served requests thread-per-predictor with no shared queue;
under overload that design queues inside the kernel's accept backlog and
times out opaquely. Here admission is explicit (Clipper's front-end
pattern): a bounded queue that REJECTS with a retry-after estimate when
full, priority lanes so interactive traffic overtakes batch traffic, and
deadline expiry so the TPU never runs a request whose caller already
gave up.

The retry-after hint is MEASURED, not fixed: the queue keeps an EWMA of
its own drain rate (rows leaving via dispatch or expiry per second) and,
when full, estimates how long until enough rows have drained to admit
THIS request. Callers may still pass an explicit hint (the engine's
batch-rate model) — the queue reports whichever is larger, so backoff
never undershoots either signal. Deadline expiries are counted apart
from admission rejections (`stats()`): "we were too full" and "the
caller's SLO died waiting" are different capacity problems. Requests
pulled back out for re-dispatch on another replica (`reroute()`, the
fleet router's failover and drain-before-retire paths) are a third
outcome — counted separately again, because a rerouted request is
still served, just elsewhere.

Locking: the queue owns an RLock (`queue.lock`); single calls take it
internally, and the engine's batcher takes it around compound
scan-and-remove operations (and builds its dispatch Condition on it).
The lock is lockdep-named ``serving.queue`` — under
``PADDLE_TPU_LOCKDEP=1`` every acquisition order against other named
classes (``decode.tenant`` et al.) is witnessed; see README
"Concurrency discipline".
"""

import time
from collections import deque

from paddle_tpu.observability import lockdep
from paddle_tpu.serving.request import Priority, RejectedError

__all__ = ["RequestQueue"]

# before any drain has been observed there is no rate to extrapolate —
# this seed hint is the cold-start fallback, not a fixed answer
_COLD_START_HINT_S = 0.05
_EWMA_ALPHA = 0.3


class RequestQueue:
    def __init__(self, max_depth=256):
        self.max_depth = int(max_depth)
        self.lock = lockdep.named_lock("serving.queue", rlock=True)
        self._lanes = {p: deque() for p in Priority.LANES}
        self._depth = 0
        self._closed = False
        # drain-rate EWMA (rows/s) + separated outcome counters
        self._drain_rate = 0.0
        self._last_drain_t = None
        self._deferred_rows = 0
        self._rejected_full = 0
        self._expired_in_queue = 0
        self._rerouted = 0

    # -- admission ---------------------------------------------------------
    def put(self, request, retry_after_s=None):
        """Admit or reject-with-backpressure. The rejection's
        `retry_after_s` is estimated from the queue's measured drain
        rate (time until `request.rows` rows of headroom exist);
        `retry_after_s`, when given, is a caller-side floor — the hint
        reported is the max of both estimates."""
        with self.lock:
            if self._closed:
                raise RejectedError(
                    "serving engine is draining; not accepting requests",
                    retry_after_s=0.0,
                )
            if self._depth + request.rows > self.max_depth:
                self._rejected_full += 1
                hint = self.retry_after_estimate(request.rows)
                if retry_after_s is not None:
                    hint = max(hint, float(retry_after_s))
                raise RejectedError(
                    f"queue full ({self._depth}/{self.max_depth} rows); "
                    f"retry after {hint:.3f}s",
                    retry_after_s=hint,
                )
            self._lanes[request.priority].append(request)
            self._depth += request.rows
        return request

    def retry_after_estimate(self, rows=1):
        """Seconds until `rows` rows of headroom should exist at the
        current drain rate (bounded to [5ms, 5s]; cold-start fallback
        before the first drain). O(1) — runs on every rejected submit."""
        with self.lock:
            overflow = max(self._depth + rows - self.max_depth, 1)
            if self._drain_rate <= 0.0:
                return _COLD_START_HINT_S
            return min(max(overflow / self._drain_rate, 0.005), 5.0)

    def _note_drained(self, rows, now):
        """EWMA update on every row leaving the queue (dispatch OR
        expiry — both free admission capacity). Caller holds `lock`.

        Only back-to-back drains of a continuously busy queue are
        service-rate samples: when the queue goes empty the timer resets,
        otherwise the first drain after an idle gap measures the ARRIVAL
        rate and a burst hitting a long-idle queue would be told to back
        off as if the engine were that slow."""
        if rows <= 0:
            return
        if self._last_drain_t is not None:
            dt = max(now - self._last_drain_t, 1e-6)
            sample = rows / dt
            self._drain_rate = (
                sample if self._drain_rate == 0.0
                else _EWMA_ALPHA * sample
                + (1.0 - _EWMA_ALPHA) * self._drain_rate
            )
        self._last_drain_t = now if self._depth > 0 else None

    def close(self):
        """Stop admitting (drain mode); queued requests still serve."""
        with self.lock:
            self._closed = True

    def reopen(self):
        with self.lock:
            self._closed = False

    # -- scheduling surface (callers hold `lock` across compound use) ------
    def expire(self, now=None):
        """Remove and return every deadline-expired request (they are
        rejected BEFORE dispatch — no device time on dead answers).
        Counted separately from admission rejections in `stats()`."""
        now = now if now is not None else time.perf_counter()
        dead = []
        with self.lock:
            for lane in self._lanes.values():
                kept = deque()
                for r in lane:
                    (dead if r.expired(now) else kept).append(r)
                lane.clear()
                lane.extend(kept)
            rows = 0
            for r in dead:
                self._depth -= r.rows
                rows += r.rows
            self._expired_in_queue += len(dead)
            self._note_drained(rows, time.perf_counter())
        return dead

    def lane(self, priority):
        """The queued requests of one priority lane, in FIFO order — the
        decode engine's weighted-fair picker scans this under `lock` to
        choose WHICH tenant's head request dispatches next (plain FIFO
        callers never need it)."""
        return tuple(self._lanes[priority])

    def head(self):
        """Oldest request in the highest non-empty lane (dispatch order),
        or None."""
        with self.lock:
            for p in Priority.LANES:
                if self._lanes[p]:
                    return self._lanes[p][0]
        return None

    def iter_requests(self):
        """Snapshot in dispatch order (priority lanes, FIFO within)."""
        with self.lock:
            out = []
            for p in Priority.LANES:
                out.extend(self._lanes[p])
            return out

    def remove(self, requests, batch=False):
        """Remove specific admitted requests (they were taken for a
        batch). ``batch=True`` defers the drain-rate sample: a caller
        picking ONE request at a time within a single admission round
        accumulates the rows and samples them as one drain via
        `note_drained()` — sampling each pick would measure the pick
        loop's microsecond gaps (~1e6 rows/s) instead of service."""
        ids = {r.id for r in requests}
        with self.lock:
            for lane in self._lanes.values():
                kept = [r for r in lane if r.id not in ids]
                if len(kept) != len(lane):
                    lane.clear()
                    lane.extend(kept)
            rows = 0
            for r in requests:
                self._depth -= r.rows
                rows += r.rows
            if batch:
                self._deferred_rows += rows
            else:
                self._note_drained(rows, time.perf_counter())

    def reroute(self, requests):
        """Remove admitted requests for RE-DISPATCH on another replica
        (fleet failover / drain-before-retire): the rows leave this
        queue like any dispatch, but the outcome is counted apart from
        both rejections and expiries — a rerouted request is still going
        to be SERVED, just elsewhere. The request objects keep their
        absolute deadline, so the re-dispatching caller inherits the
        remaining budget rather than a fresh one."""
        self.remove(requests)
        with self.lock:
            self._rerouted += len(requests)

    def note_drained(self):
        """Sample the rows of `remove(batch=True)` calls accumulated
        since the last sample as ONE drain event (call once per
        admission round)."""
        with self.lock:
            rows, self._deferred_rows = self._deferred_rows, 0
            self._note_drained(rows, time.perf_counter())

    def pressure(self, now=None, horizon_s=1.0):
        """Normalized pressure signals for the brownout controller
        (serving/brownout.py), sampled once per scheduler iteration:

        * ``queue_seconds`` — queued rows over the measured drain rate,
          normalized against ``horizon_s`` (1.0 == a full horizon of
          work is backed up). Zero before the first drain sample: an
          idle queue must not brown out on its cold-start hint.
        * ``deadline`` — ``1 - headroom / budget`` for the most urgent
          queued request (0 fresh, 1 at expiry); 0 when nothing queued
          carries a deadline.
        * ``depth_frac`` — queued rows over ``max_depth``.
        """
        now = now if now is not None else time.perf_counter()
        with self.lock:
            depth = self._depth
            rate = self._drain_rate
            worst = 0.0
            for lane in self._lanes.values():
                for r in lane:
                    if r.deadline is None:
                        continue
                    budget = r.deadline - r.submit_time
                    if budget <= 0.0:
                        worst = 1.0
                        continue
                    frac = 1.0 - (r.deadline - now) / budget
                    worst = max(worst, min(max(frac, 0.0), 1.0))
        qs = 0.0
        if depth > 0 and rate > 0.0:
            qs = min((depth / rate) / float(horizon_s), 1.0)
        return {
            "queue_seconds": qs,
            "deadline": worst,
            "depth_frac": depth / float(max(self.max_depth, 1)),
        }

    # -- introspection -----------------------------------------------------
    def depth(self):
        """Queued rows (admission unit: a 4-row request costs 4)."""
        with self.lock:
            return self._depth

    def lane_depths(self):
        """{priority: queued rows} — the per-lane gauge source."""
        with self.lock:
            return {p: sum(r.rows for r in lane)
                    for p, lane in self._lanes.items()}

    def stats(self):
        """Queue-side counters: depth, per-lane depths, the measured
        drain rate, and the rejected-at-admission vs expired-in-queue
        split."""
        with self.lock:
            return {
                "depth": self._depth,
                "lane_depths": self.lane_depths(),  # RLock: re-entrant
                "drain_rate_rows_per_s": self._drain_rate,
                "rejected_at_admission": self._rejected_full,
                "expired_in_queue": self._expired_in_queue,
                "rerouted": self._rerouted,
            }

    def empty(self):
        with self.lock:
            return self._depth == 0

    def closed(self):
        with self.lock:
            return self._closed
