"""Bounded admission queue with priority lanes and deadline expiry.

The reference served requests thread-per-predictor with no shared queue;
under overload that design queues inside the kernel's accept backlog and
times out opaquely. Here admission is explicit (Clipper's front-end
pattern): a bounded queue that REJECTS with a retry-after estimate when
full, priority lanes so interactive traffic overtakes batch traffic, and
deadline expiry so the TPU never runs a request whose caller already
gave up.

Locking: the queue owns an RLock (`queue.lock`); single calls take it
internally, and the engine's batcher takes it around compound
scan-and-remove operations (and builds its dispatch Condition on it).
"""

import threading
import time
from collections import deque

from paddle_tpu.serving.request import Priority, RejectedError

__all__ = ["RequestQueue"]


class RequestQueue:
    def __init__(self, max_depth=256):
        self.max_depth = int(max_depth)
        self.lock = threading.RLock()
        self._lanes = {p: deque() for p in Priority.LANES}
        self._depth = 0
        self._closed = False

    # -- admission ---------------------------------------------------------
    def put(self, request, retry_after_s=0.05):
        """Admit or reject-with-backpressure. `retry_after_s` is the
        engine's current drain-time estimate, forwarded verbatim in the
        rejection so callers back off proportionally to real load."""
        with self.lock:
            if self._closed:
                raise RejectedError(
                    "serving engine is draining; not accepting requests",
                    retry_after_s=0.0,
                )
            if self._depth + request.rows > self.max_depth:
                raise RejectedError(
                    f"queue full ({self._depth}/{self.max_depth} rows); "
                    f"retry after {retry_after_s:.3f}s",
                    retry_after_s=retry_after_s,
                )
            self._lanes[request.priority].append(request)
            self._depth += request.rows
        return request

    def close(self):
        """Stop admitting (drain mode); queued requests still serve."""
        with self.lock:
            self._closed = True

    def reopen(self):
        with self.lock:
            self._closed = False

    # -- scheduling surface (callers hold `lock` across compound use) ------
    def expire(self, now=None):
        """Remove and return every deadline-expired request (they are
        rejected BEFORE dispatch — no device time on dead answers)."""
        now = now if now is not None else time.perf_counter()
        dead = []
        with self.lock:
            for lane in self._lanes.values():
                kept = deque()
                for r in lane:
                    (dead if r.expired(now) else kept).append(r)
                lane.clear()
                lane.extend(kept)
            for r in dead:
                self._depth -= r.rows
        return dead

    def head(self):
        """Oldest request in the highest non-empty lane (dispatch order),
        or None."""
        with self.lock:
            for p in Priority.LANES:
                if self._lanes[p]:
                    return self._lanes[p][0]
        return None

    def iter_requests(self):
        """Snapshot in dispatch order (priority lanes, FIFO within)."""
        with self.lock:
            out = []
            for p in Priority.LANES:
                out.extend(self._lanes[p])
            return out

    def remove(self, requests):
        """Remove specific admitted requests (they were taken for a
        batch)."""
        ids = {r.id for r in requests}
        with self.lock:
            for lane in self._lanes.values():
                kept = [r for r in lane if r.id not in ids]
                if len(kept) != len(lane):
                    lane.clear()
                    lane.extend(kept)
            for r in requests:
                self._depth -= r.rows

    # -- introspection -----------------------------------------------------
    def depth(self):
        """Queued rows (admission unit: a 4-row request costs 4)."""
        with self.lock:
            return self._depth

    def empty(self):
        with self.lock:
            return self._depth == 0

    def closed(self):
        with self.lock:
            return self._closed
