"""Request/Response futures and structured serving errors.

Every way a request can fail short of an answer is a typed error with a
machine-readable `code`, so front-ends (Python, C, Go via the C ABI) can
branch on failure class without parsing prose: `rejected` means back off
and retry after `retry_after_s` (admission backpressure), `deadline`
means the SLO expired while queued, `request_failed` means THIS request
was bad — its batchmates were served normally.
"""

import threading
import time

__all__ = [
    "Priority",
    "ServingError",
    "RejectedError",
    "DeadlineExceededError",
    "RequestError",
    "ReplicaLostError",
    "Request",
    "Response",
]


class Priority:
    """Admission lanes, drained strictly in order (HIGH before NORMAL
    before LOW). An SLO-critical interactive request overtakes queued
    batch traffic at dispatch time; within a lane, FIFO."""

    HIGH = 0
    NORMAL = 1
    LOW = 2
    LANES = (HIGH, NORMAL, LOW)


class ServingError(RuntimeError):
    """Base of all structured serving failures. `code` is stable API."""

    code = "serving_error"

    def to_dict(self):
        return {"code": self.code, "message": str(self)}


class RejectedError(ServingError):
    """Admission refused (queue full, engine draining, or inadmissible
    shape). Backpressure is explicit: `retry_after_s` estimates when the
    queue will have drained enough to admit — callers should retry after
    that, not hammer."""

    code = "rejected"

    def __init__(self, message, retry_after_s=0.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)

    def to_dict(self):
        d = super().to_dict()
        d["retry_after_s"] = self.retry_after_s
        return d


class DeadlineExceededError(ServingError):
    """The request's deadline expired while it waited in the queue; it
    was never dispatched (no TPU time was spent on a dead answer)."""

    code = "deadline"


class RequestError(ServingError):
    """This request failed during batch assembly or execution. Isolation
    guarantee: a RequestError never propagates to batchmates."""

    code = "request_failed"


class ReplicaLostError(RequestError):
    """The REPLICA failed while this request was in flight (a donated
    decode step or arena inject died, or the process hosting it went
    away) — the request itself was fine. Distinguished from
    `RequestError` because the fleet router's failover treats the two
    oppositely: a replica-lost request is transparently re-dispatched to
    a healthy replica (decode is bit-deterministic, so the retried
    answer is byte-identical), while a request-attributed failure is
    delivered — retrying a poison request elsewhere just spreads it."""

    code = "replica_lost"


class Response:
    """Write-once future for one request's outputs.

    The engine thread completes it exactly once with either a
    {fetch_name: np.ndarray} dict or a ServingError; callers block in
    `result()` or poll with `done()` (the C ABI's poll entry maps onto
    exactly this surface)."""

    __slots__ = ("_event", "_outputs", "_error", "finish_time")

    def __init__(self):
        self._event = threading.Event()
        self._outputs = None
        self._error = None
        self.finish_time = None

    def _complete(self, outputs=None, error=None):
        if self._event.is_set():  # write-once; late completions are bugs
            raise RuntimeError("response completed twice")
        self._outputs = outputs
        self._error = error
        self.finish_time = time.perf_counter()
        self._event.set()

    def done(self):
        return self._event.is_set()

    def error(self):
        """The ServingError, or None (call after done())."""
        return self._error

    def result(self, timeout=None):
        """Block until served; returns {fetch_name: np.ndarray} or raises
        the structured ServingError."""
        if not self._event.wait(timeout):
            raise TimeoutError("response not ready")
        if self._error is not None:
            raise self._error
        return self._outputs


class Request:
    """One admitted inference request.

    `inputs` maps feed name -> np.ndarray whose axis 0 is this request's
    row count (all inputs agree on it). `group_key` identifies the set of
    requests that may share a padded batch: same feed names, dtypes, and
    trailing dims outside the padded axis. `deadline` is an absolute
    perf_counter() time or None."""

    __slots__ = ("id", "inputs", "rows", "priority", "deadline",
                 "submit_time", "dispatch_time", "group_key", "var_len",
                 "response")

    def __init__(self, rid, inputs, rows, priority, deadline, group_key,
                 var_len):
        self.id = rid
        self.inputs = inputs
        self.rows = rows
        self.priority = priority
        self.deadline = deadline
        self.submit_time = time.perf_counter()
        self.dispatch_time = None
        self.group_key = group_key
        self.var_len = var_len  # padded-axis length (0 when nothing pads)
        self.response = Response()

    def expired(self, now=None):
        if self.deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) > self.deadline
