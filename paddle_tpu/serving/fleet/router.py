"""FleetRouter: chaos-proven failover over N decode replicas.

The tier above one engine (ROADMAP item 3, PAPER.md's L6 fleet layer
rebuilt TPU-natively): a front-end router that accepts generation
requests once and then OWNS delivering an answer, whatever happens to
the replica serving them.

Guarantees (the chaos gate in tools/chaos_serve.py asserts all of them):

* **At-most-once-VISIBLE re-dispatch.** A request lost to a dead or
  quarantined replica is transparently retried on a healthy one under
  the caller's ORIGINAL deadline (the absolute deadline travels with the
  request — a retry never gets a fresh budget). The request may
  EXECUTE more than once, but because decode is bit-deterministic the
  caller-visible answer is byte-identical to the single-replica offline
  reference, and the write-once Response future makes exactly one
  delivery possible. Accounting identity: every accepted request ends
  completed, deadline-missed, failed (request-attributed), or
  drained-unserved — never silently lost.
* **Prefix-affinity routing.** Requests route by rendezvous hash of the
  prompt's leading tokens, so PR 10's prefix cache keeps paying off
  fleet-wide (same prefix -> same replica -> ZERO prefill on repeats),
  with spill to the least-loaded healthy replica when the affinity
  target is saturated or down. Rendezvous hashing keeps the mapping
  stable when replicas join or leave — only keys owned by a dead
  replica move.
* **Fleet-wide load shedding.** When every healthy replica rejects, the
  router sheds with the SOONEST measured drain-rate retry-after among
  them (serving/queue.py's EWMA) — backpressure reflects when the fleet
  will actually have capacity.
* **Health + failover.** A pump thread heartbeats every replica
  (``fleet.health`` fault site) and drives the PR-2 breaker contract:
  consecutive failures quarantine, cooldown probes re-admit. Transport
  loss or the ``replica.kill`` site mark a replica DEAD: its in-flight
  requests re-dispatch immediately and it leaves routing until revived
  (autoscale replacement or supervisor ``restart(rank)``).
* **Elasticity.** Occupancy/queue-depth-driven scale-up/scale-down via
  a replica factory. A scale-up replica is serving-ready with ZERO
  traces (compile-cache memory/disk tiers) — ``last_scaleup_traces``
  records the counter the chaos gate asserts on.
* **Rolling deploys.** ``deploy()`` walks the fleet one replica at a
  time: quarantine from routing, steal the queued backlog for
  re-dispatch (deadlines intact), wait for in-flight slots to land,
  register the new (model, version), drain-retire the old. Unversioned
  traffic stays PINNED to the old version until every replica hosts the
  new one, then the pin flips — no request ever races the roll.

Locking: ONE router lock, lockdep class ``fleet.router``, at the TOP of
the declared hierarchy ``fleet.router -> serving.queue -> decode.tenant``
(reading a local replica's queue depth during routing nests the queue
lock under it; the decode engine supplies the lower edge). Transport
I/O (RPC, heartbeats, dispatch) always happens OUTSIDE the router lock.
"""

import hashlib
import logging
import threading
import time

from paddle_tpu.observability import lockdep
from paddle_tpu.resilience import faults
from paddle_tpu.serving.decode.pool import block_hashes, prompt_key
from paddle_tpu.serving.fleet.metrics import FleetMetrics
from paddle_tpu.serving.fleet.replica import ReplicaError
from paddle_tpu.serving.request import (
    DeadlineExceededError,
    Priority,
    RejectedError,
    ReplicaLostError,
    Response,
)

__all__ = ["FleetRouter", "RoutedRequest"]

log = logging.getLogger("paddle_tpu.serving.fleet.router")

# The router holds its lock while reading replica queue depths (routing)
# and while the pump commits failover state; the decode engine's
# scheduler supplies serving.queue -> decode.tenant below it. Declared
# so an inversion anywhere names the RULE.
lockdep.declare_order("fleet.router", "serving.queue", "decode.tenant")

_SHED_COLD_HINT_S = 0.05


class RoutedRequest:
    """One request the fleet has accepted. ``response`` is the ROUTER's
    write-once future — inner per-replica futures/tickets come and go
    across re-dispatches; this one is the only thing the caller sees."""

    __slots__ = ("id", "prompt", "max_new", "tenant", "priority",
                 "deadline_at", "model", "version", "response",
                 "submit_time", "attempts", "replica", "ticket", "state")

    def __init__(self, rid, prompt, max_new, tenant, priority, deadline_at,
                 model, version):
        self.id = rid
        self.prompt = [int(t) for t in prompt]
        self.max_new = int(max_new)
        self.tenant = str(tenant)
        self.priority = priority
        self.deadline_at = deadline_at
        self.model = model
        self.version = version
        self.response = Response()
        self.submit_time = time.perf_counter()
        self.attempts = []       # replica ids, dispatch order
        self.replica = None      # current replica id (state == inflight)
        self.ticket = None       # current replica-side ticket
        self.state = "new"       # new -> inflight <-> parked -> done


class FleetRouter:
    _SEQ = 0

    def __init__(self, replica_factory=None, affinity_prefix=4,
                 saturation_rows=None, health_interval_s=0.05,
                 pump_interval_s=0.002, breaker_threshold=3,
                 breaker_cooldown_s=1.0, min_replicas=1, max_replicas=8,
                 autoscale=False, scale_up_rows_per_replica=16,
                 scale_down_idle_ticks=40, supervisor=None,
                 revive_factory=None, label=None):
        FleetRouter._SEQ += 1
        self.label = label or f"fleet-{FleetRouter._SEQ}"
        self._factory = replica_factory
        self._affinity_prefix = int(affinity_prefix)
        self._saturation_rows = saturation_rows
        self._health_interval_s = float(health_interval_s)
        self._pump_interval_s = float(pump_interval_s)
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._min_replicas = int(min_replicas)
        self._max_replicas = int(max_replicas)
        self._autoscale = bool(autoscale)
        self._scale_up_rows = int(scale_up_rows_per_replica)
        self._scale_down_idle_ticks = int(scale_down_idle_ticks)
        self._lock = lockdep.named_lock("fleet.router", rlock=True)
        self._replicas = {}      # rid -> handle
        self._health = {}        # rid -> ReplicaHealth
        self._draining = set()   # rids quarantined from routing (deploy)
        self._inflight = {}      # routed id -> RoutedRequest (incl parked)
        self._pin = {}           # model name -> pinned default version
        self._default_name = None
        self._next_id = 0
        self._next_index = 0
        self._metrics = FleetMetrics(self.label)
        self._pump = None
        self._stop = False
        self._last_health = 0.0
        self._idle_ticks = 0
        self.last_scaleup_traces = None
        # router-initiated supervisor integration: a DEAD replica whose
        # rank a GangSupervisor owns is restarted INTO ITS OWN slot
        # (supervisor.restart(rank) + revive_factory(rid, index) ->
        # revive_replica) instead of being replaced by a scale-up
        self._supervisor = supervisor
        self._revive_factory = revive_factory
        self._revive_failed = set()   # rids: one attempt per death episode

    # -- replica set -------------------------------------------------------
    def add_replica(self, handle):
        """Adopt a serving-ready replica handle (any transport)."""
        from paddle_tpu.serving.fleet.health import ReplicaHealth

        with self._lock:
            if handle.rid in self._replicas:
                raise ValueError(f"replica {handle.rid} already routed")
            self._replicas[handle.rid] = handle
            self._health[handle.rid] = ReplicaHealth(
                self._breaker_threshold, self._breaker_cooldown_s)
            self._next_index = max(self._next_index, handle.index + 1)
            for name, version in handle.models():
                if self._default_name is None:
                    self._default_name = name
                self._pin.setdefault(name, version)
        return handle

    def scale_up(self):
        """Grow the fleet by one factory-built replica. The factory
        returns a serving-ready handle; with a warm compile cache the
        new replica pays ZERO traces (``last_scaleup_traces`` keeps the
        counter the chaos gate asserts)."""
        if self._factory is None:
            raise RuntimeError("router has no replica factory")
        with self._lock:
            index = self._next_index
            self._next_index += 1
        handle = self._factory(index)
        self.add_replica(handle)
        self.last_scaleup_traces = handle.trace_count()
        self._metrics.incr("scale_ups")
        return handle

    def scale_down(self, rid=None, timeout=60.0):
        """Drain-before-retire one replica (default: the idlest): stop
        routing to it, steal its queued backlog for re-dispatch, wait
        for in-flight slots to land, then close it."""
        with self._lock:
            if rid is None:
                cands = self._routable()
                if len(cands) <= 1:
                    raise RuntimeError("nothing retirable: the fleet "
                                       "needs at least one replica")
                rid = min(cands, key=lambda r: (
                    self._replicas[r].load(), -self._replicas[r].index))
            handle = self._replicas[rid]
            self._draining.add(rid)
        try:
            self._steal_and_park(rid, handle)
            self._wait_inflight_drained(rid, timeout)
        except Exception:
            # drain failed: RE-ADMIT the replica instead of dropping it
            # with work still in flight (those requests would strand)
            with self._lock:
                self._draining.discard(rid)
            raise
        with self._lock:
            self._draining.discard(rid)
            self._replicas.pop(rid, None)
            self._health.pop(rid, None)
        handle.close()
        self._metrics.incr("scale_downs")
        return rid

    def revive_replica(self, handle):
        """Swap a fresh handle into a DEAD replica's slot (supervisor
        ``restart(rank)`` / manual relaunch): fresh breaker, back in the
        routing set."""
        with self._lock:
            old = self._replicas.get(handle.rid)
            health = self._health.get(handle.rid)
            if old is None or health is None:
                raise ValueError(f"no replica slot {handle.rid} to revive")
            self._replicas[handle.rid] = handle
            health.revive()
        self._metrics.incr("replicas_revived")
        return handle

    def replicas(self):
        with self._lock:
            return {rid: self._health[rid].state()
                    for rid in sorted(self._replicas)}

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._pump is not None:
            return self
        self._stop = False
        self._pump = threading.Thread(
            target=self._pump_loop, name=f"{self.label}-pump", daemon=True)
        self._pump.start()
        return self

    def shutdown(self, timeout=60.0):
        """Graceful: stop admitting, give in-flight work `timeout` to
        land (the pump keeps delivering), then complete anything still
        parked with a structured rejection (visible, never lost)."""
        with self._lock:
            self._stop = True
        if self._pump is not None:
            self._pump.join(timeout)
            self._pump = None
        self._drain_deadline(time.perf_counter() + timeout)
        with self._lock:
            leftovers = [rr for rr in self._inflight.values()
                         if not rr.response.done()]
            self._inflight.clear()
        for rr in leftovers:
            self._metrics.incr("drained_unserved")
            rr.response._complete(error=RejectedError(
                "fleet router shut down before this request was served",
                retry_after_s=0.0))
        with self._lock:
            handles = list(self._replicas.values())
        for h in handles:
            h.close(timeout)

    def _drain_deadline(self, deadline):
        while time.perf_counter() < deadline:
            self._tick()
            with self._lock:
                live = [rr for rr in self._inflight.values()
                        if not rr.response.done()]
                # nothing can make progress: every survivor is parked
                # and no replica is routable — stop burning the timeout
                stuck = (all(rr.state == "parked" for rr in live)
                         and not self._routable())
            if not live or stuck:
                return
            time.sleep(self._pump_interval_s)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- admission ---------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=16, tenant="default",
               priority=Priority.NORMAL, deadline_ms=None, model=None,
               version=None):
        """Accept one generation request into the fleet; returns the
        router-owned Response future. Raises RejectedError (with the
        fleet's soonest measured retry-after) when every healthy replica
        refuses — the request was never accepted. After acceptance the
        router owns delivery: replica death re-dispatches transparently
        under the original deadline."""
        self._metrics.incr("submitted")
        def bad(msg):
            self._metrics.incr("rejected_invalid")
            raise RejectedError(msg)

        try:
            prompt = [int(t) for t in prompt_ids]
        except (TypeError, ValueError):
            prompt = None
        if prompt is None:
            bad("prompt_ids must be a sequence of token ids")
        if not prompt:
            bad("empty prompt")
        if int(max_new_tokens) < 1:
            bad(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        deadline_at = (time.perf_counter() + deadline_ms / 1e3
                       if deadline_ms is not None else None)
        with self._lock:
            if self._stop:
                raise RejectedError("fleet router is draining",
                                    retry_after_s=0.0)
            if priority != Priority.HIGH:
                sevs = [self._health[r].severity for r in self._routable()]
                if sevs and min(sevs) >= 4:
                    # fleet-wide brownout L4: every routable replica is
                    # already shedding non-HIGH — turn it away at the
                    # router door instead of burning a dispatch sweep
                    self._metrics.incr("brownout_shed")
                    self._metrics.incr("rejected_shed")
                    raise RejectedError(
                        "fleet brownout: every routable replica is "
                        "shedding non-HIGH traffic",
                        retry_after_s=_SHED_COLD_HINT_S)
            self._next_id += 1
            rid = self._next_id
            if model is None:
                model = self._default_name
            if version is None and model is not None:
                version = self._pin.get(model)
        rr = RoutedRequest(rid, prompt, max_new_tokens, tenant,
                           priority, deadline_at, model, version)
        kind, err = self._try_dispatch(rr)
        if kind != "ok":
            self._metrics.incr("rejected_shed")
            raise err
        self._metrics.incr("accepted")
        return rr.response

    # -- routing -----------------------------------------------------------
    def _routable(self, exclude=()):
        """Caller holds the lock. Dead/quarantined/draining replicas are
        out; breaker half-open replicas are IN (probe traffic is the
        re-admission mechanism)."""
        return [rid for rid in sorted(self._replicas)
                if rid not in exclude and rid not in self._draining
                and self._health[rid].routable()]

    @staticmethod
    def _rendezvous_score(key, rid):
        return int.from_bytes(
            hashlib.sha256(f"{key}|{rid}".encode()).digest()[:8], "big")

    def _route(self, rr, exclude):
        """Caller holds the lock: affinity target by rendezvous hash of
        the prompt's leading KV BLOCK, spilled to least-loaded when the
        target is saturated. The affinity key is the chained block hash
        (`pool.block_hashes` with ``affinity_prefix`` as the block
        size) — the SAME digest family the paged engine's radix tree
        keys physical blocks by, so two prompts the router co-locates
        are exactly two prompts whose first block the replica can serve
        from shared storage (zero prefill AND zero extra rows). Prompts
        shorter than one block fall back to the whole-prompt hash. Load
        reads a local replica's queue depth — the witnessed
        ``fleet.router -> serving.queue`` edge."""
        cands = self._routable(exclude)
        if not cands:
            return None
        chain = block_hashes(rr.prompt, self._affinity_prefix)
        key = chain[0] if chain else prompt_key(rr.prompt)
        target = max(cands,
                     key=lambda rid: self._rendezvous_score(key, rid))
        sat = self._saturation_rows
        if sat is not None and self._replicas[target].load() >= sat:
            spill = min(cands, key=lambda rid: (
                self._replicas[rid].load(), rid))
            if self._replicas[spill].load() < self._replicas[target].load():
                target = spill
        # brownout bias: an affinity target at severity >= 3 gives way
        # to the least-browned-out (then least-loaded) candidate —
        # affinity saves prefill, but a capped replica costs more than
        # the prefill it saves
        if self._health[target].severity >= 3:
            calm = min(cands, key=lambda rid: (
                self._health[rid].severity, self._replicas[rid].load(), rid))
            if self._health[calm].severity < self._health[target].severity:
                target = calm
        return target

    def _try_dispatch(self, rr):
        """Route + dispatch with failover across replicas. Returns
        ("ok", None) once a replica admits; ("shed", RejectedError)
        when every routable replica refused but the refusals were
        RETRYABLE (backpressure, transport churn — worth re-trying
        later); ("dead_end", RejectedError) when every routable replica
        PERMANENTLY rejected (e.g. the requested (model, version) is
        retired fleet-wide — re-trying can never succeed). Dispatch I/O
        runs OUTSIDE the router lock."""
        tried = set()
        hints = []
        retryable = False
        while True:
            with self._lock:
                target = self._route(rr, tried)
                handle = self._replicas.get(target) if target else None
                probing = (target is not None
                           and self._health[target].probing())
            if target is None:
                hint = min(hints) if hints else _SHED_COLD_HINT_S
                err = RejectedError(
                    f"fleet saturated or unavailable "
                    f"({len(tried)} replicas refused); retry after "
                    f"{hint:.3f}s", retry_after_s=hint)
                kind = ("dead_end" if tried and not retryable
                        else "shed")
                return kind, err
            if probing:
                self._metrics.incr("breaker_probes")
            try:
                faults.fire("fleet.dispatch", rank=handle.index)
                ticket = handle.submit(
                    rr.prompt, rr.max_new, rr.tenant, rr.priority,
                    rr.deadline_at, model=rr.model, version=rr.version)
            except RejectedError as e:
                # a measured retry-after means backpressure (queue
                # full, quota): retryable. retry_after 0.0 means the
                # replica can NEVER serve this (unknown model/version,
                # invalid request) — if every replica says so, parking
                # is a busy-wait on the impossible.
                hints.append(e.retry_after_s)
                if e.retry_after_s > 0:
                    retryable = True
                tried.add(target)
                continue
            except Exception as e:
                # transport death / injected dispatch fault: the
                # replica, not the request, failed this attempt — the
                # replica set can change, so this stays retryable
                retryable = True
                tried.add(target)
                self._note_replica_failure(target, e, during="dispatch")
                continue
            with self._lock:
                was_parked = rr.state == "parked"
                rr.attempts.append(target)
                self._inflight[rr.id] = rr
                # the replica may have died between our submit landing
                # and this commit — _mark_dead's victim sweep has
                # already run, so an 'inflight' record on a dead
                # replica would never be swept again: park instead
                # (decode is deterministic, the re-dispatch is free)
                if self._health[target].dead:
                    rr.state = "parked"
                    rr.replica = rr.ticket = None
                else:
                    rr.replica, rr.ticket = target, ticket
                    rr.state = "inflight"
            self._note_replica_success(target)
            if was_parked:
                self._metrics.incr("rerouted")
            return "ok", None

    # -- health plumbing ---------------------------------------------------
    def _health_event(self, event):
        if event:
            self._metrics.incr(event)

    def _note_replica_success(self, rid):
        with self._lock:
            health = self._health.get(rid)
            event = health.note_success() if health else None
        self._health_event(event)

    def _note_replica_failure(self, rid, exc, during):
        self._metrics.incr("dispatch_faults" if during == "dispatch"
                           else "health_probe_failures")
        fatal = isinstance(exc, ReplicaError) and exc.fatal
        if fatal:
            self._mark_dead(rid, exc)
            return
        with self._lock:
            health = self._health.get(rid)
            event = health.note_failure() if health else None
        self._health_event(event)

    def _mark_dead(self, rid, reason):
        """A replica is GONE: latch dead, pull every in-flight routed
        request off it into the parked set — the pump re-dispatches them
        under their original deadlines. The victim sweep is idempotent
        and runs even when the replica was ALREADY dead: a dispatch
        that raced the first death can still commit an inflight record
        afterwards, and this is its only way back out."""
        with self._lock:
            health = self._health.get(rid)
            if health is None:
                return
            first = not health.dead
            if first:
                health.mark_dead(reason)
                # fresh death episode: the revive path gets one attempt
                self._revive_failed.discard(rid)
            for rr in self._inflight.values():
                if rr.replica == rid and rr.state == "inflight":
                    rr.state = "parked"
                    rr.replica = rr.ticket = None
        if first:
            self._metrics.incr("replica_deaths")

    def _maybe_revive(self):
        """Router-initiated supervisor integration: every DEAD replica
        whose rank a GangSupervisor owns is terminated+respawned INTO
        ITS ORIGINAL endpoint slot (``supervisor.restart(rank)`` — a
        structured ``rank_restart`` event and
        ``resilience_events_total{kind=rank_restart}``), then a fresh
        handle from ``revive_factory(rid, index)`` re-enters routing via
        ``revive_replica``. One attempt per death episode; a failed
        attempt leaves the slot dead for autoscale replacement. All
        process/transport I/O runs OUTSIDE the router lock."""
        if self._supervisor is None or self._revive_factory is None:
            return
        with self._lock:
            dead = [(rid, self._replicas[rid].index)
                    for rid in sorted(self._replicas)
                    if self._health[rid].dead
                    and rid not in self._revive_failed]
            for rid, _ in dead:
                self._revive_failed.add(rid)   # claimed; cleared on success
        for rid, index in dead:
            try:
                self._supervisor.restart(index)
                self._metrics.incr("supervisor_restarts")
                handle = self._revive_factory(rid, index)
                self.revive_replica(handle)
            except Exception:
                log.exception(
                    "supervisor restart of replica %s (rank %d) failed; "
                    "slot stays dead for autoscale replacement", rid, index)
                continue
            with self._lock:
                self._revive_failed.discard(rid)

    # -- the pump ----------------------------------------------------------
    def _pump_loop(self):
        while True:
            with self._lock:
                if self._stop:
                    return
            try:
                self._tick()
            except Exception:
                # the pump is the fleet's heartbeat: one bad tick
                # (factory failure, drain timeout) must not silently
                # kill delivery for every in-flight request
                log.exception("fleet pump tick failed; continuing")
            time.sleep(self._pump_interval_s)

    def _tick(self, now=None):
        """One pump iteration (also called directly by tests and the
        lockdep evidence driver for a single-threaded deterministic
        pass): poll in-flight tickets, run the health pass when due,
        re-dispatch parked requests, autoscale."""
        now = now if now is not None else time.perf_counter()
        self._poll_inflight()
        if now - self._last_health >= self._health_interval_s:
            self._last_health = now
            self._health_pass()
        self._flush_parked(now)
        # restart-in-place runs BEFORE autoscale: a supervised rank
        # returns to its own endpoint slot instead of being replaced
        self._maybe_revive()
        self._maybe_scale()

    def _poll_inflight(self):
        with self._lock:
            by_replica = {}
            for rr in self._inflight.values():
                if rr.state == "inflight":
                    by_replica.setdefault(rr.replica, []).append(rr)
            handles = {rid: self._replicas.get(rid) for rid in by_replica}
        for rid, rrs in by_replica.items():
            handle = handles.get(rid)
            if handle is None:
                continue
            try:
                results = handle.poll_many([rr.ticket for rr in rrs])
            except Exception as e:
                self._note_replica_failure(rid, e, during="poll")
                continue
            for rr, res in zip(rrs, results):
                if res is None:
                    continue
                kind, payload = res
                if kind == "ok":
                    self._complete(rr, outputs=payload)
                else:
                    self._on_inner_error(rr, payload)

    def _on_inner_error(self, rr, err):
        """Classify a replica-side failure: replica-lost and mid-drain
        rejections re-dispatch (the REQUEST was fine); deadline and
        request-attributed failures deliver — retrying a poison request
        elsewhere just spreads it."""
        if isinstance(err, (ReplicaLostError, RejectedError)):
            self._park(rr)
        else:
            self._complete(rr, error=err)

    def _park(self, rr):
        with self._lock:
            if rr.response.done():
                return
            rr.state = "parked"
            rr.replica = rr.ticket = None

    def _complete(self, rr, outputs=None, error=None):
        with self._lock:
            if rr.response.done():
                return
            rr.state = "done"
            self._inflight.pop(rr.id, None)
        rr.response._complete(outputs=outputs, error=error)
        if error is None:
            self._metrics.incr("completed")
        elif isinstance(error, DeadlineExceededError):
            self._metrics.incr("deadline_missed")
        else:
            self._metrics.incr("failed")
        self._metrics.observe_latency(
            time.perf_counter() - rr.submit_time)

    @staticmethod
    def _severity_of(stats):
        """Max brownout severity across a replica's hosted entries (0
        when the stats shape predates the ladder — subprocess workers on
        an older wheel report full service, not an error)."""
        try:
            models = stats.get("engine", {}).get("models", {})
            return max((int(ms.get("brownout_severity", 0) or 0)
                        for ms in models.values()), default=0)
        except Exception:
            return 0

    def _health_pass(self):
        with self._lock:
            items = [(rid, self._replicas[rid], self._health[rid])
                     for rid in sorted(self._replicas)]
        for rid, handle, health in items:
            if health.dead:
                continue
            try:
                faults.fire("fleet.health", rank=handle.index)
                handle.heartbeat()
            except Exception as e:
                self._note_replica_failure(rid, e, during="health")
                continue
            self._note_replica_success(rid)
            # sample brownout severity alongside the heartbeat (I/O
            # outside the lock, like every other RPC here): the router
            # biases dispatch away from browned-out replicas and sheds
            # fleet-wide when every routable one reports L4
            try:
                sev = self._severity_of(handle.stats())
            except Exception:
                sev = 0
            with self._lock:
                h = self._health.get(rid)
                if h is not None:
                    h.severity = sev
        with self._lock:
            self._metrics.set_healthy(len(self._routable()))

    def _flush_parked(self, now):
        with self._lock:
            parked = [rr for rr in self._inflight.values()
                      if rr.state == "parked"]
        for rr in parked:
            if rr.deadline_at is not None and now > rr.deadline_at:
                self._complete(rr, error=DeadlineExceededError(
                    "original deadline expired during re-dispatch "
                    f"(request {rr.id}, {len(rr.attempts)} attempts)"))
                continue
            kind, err = self._try_dispatch(rr)
            if kind == "dead_end":
                # every routable replica PERMANENTLY rejected (e.g. the
                # version was retired fleet-wide mid-failover): deliver
                # the structured rejection instead of re-trying forever
                self._complete(rr, error=err)
            # "shed" stays parked: backpressure clears, replicas revive

    # -- elasticity --------------------------------------------------------
    def _maybe_scale(self):
        if self._factory is None or not self._autoscale:
            return
        with self._lock:
            if self._stop:
                return
            routable = self._routable()
            total = len([h for rid, h in self._replicas.items()
                         if not self._health[rid].dead])
            queued = sum(self._replicas[rid].load() for rid in routable)
            inflight = sum(1 for rr in self._inflight.values()
                           if rr.state == "inflight")
        try:
            if (len(routable) < self._min_replicas
                    and total < self._max_replicas):
                self.scale_up()
                return
            if (routable and total < self._max_replicas
                    and queued > self._scale_up_rows * len(routable)):
                self.scale_up()
                return
        except Exception:
            # a factory failure is an event, not a pump death
            log.exception("autoscale scale-up failed; continuing")
            return
        if (len(routable) > self._min_replicas and queued == 0
                and inflight == 0):
            self._idle_ticks += 1
            if self._idle_ticks >= self._scale_down_idle_ticks:
                self._idle_ticks = 0
                try:
                    self.scale_down()
                except (RuntimeError, TimeoutError):
                    # nothing retirable / drain raced new traffic — the
                    # replica was re-admitted; try again when idle
                    pass
        else:
            self._idle_ticks = 0

    # -- rolling deploys ---------------------------------------------------
    def deploy(self, builder, version, name=None, timeout=120.0,
               worker_spec=None):
        """Roll (name, version) across the fleet with zero downtime, in
        two passes. Pass 1 makes every live replica HOST the new version
        while the old one keeps serving (unversioned traffic stays
        PINNED to the old version, so nothing races the roll — a
        mixed-version fleet is only reachable by explicit version):

        * local replicas register the builder in-place (the multi-tenant
          registry hosts both versions);
        * subprocess replicas deploy by WORKER REPLACEMENT — a builder
          closure cannot cross the process boundary, so the router
          spawns a replacement worker hosting old+new from
          ``worker_spec`` (the new version's decoder geometry kwargs),
          steals the old worker's queued backlog for re-dispatch
          (deadlines intact), waits for its in-flight slots to land,
          swaps the replacement into the same routing slot, and closes
          the old process.

        Once every replica hosts the new version the pin flips
        atomically; pass 2 then DRAIN-RETIRES the old version replica by
        replica (over the RPC wire for subprocess replicas) — queued and
        in-flight old-version generations finish before each entry
        leaves its registry. Explicit old-version requests after the
        flip fail over between replicas until the version is gone, then
        shed with a structured rejection."""
        with self._lock:
            name = name or self._default_name
            if name is None:
                raise RuntimeError("no model to deploy over")
            old_version = self._pin.get(name)
            rids = [rid for rid in sorted(self._replicas)
                    if not self._health[rid].dead]
            # precondition BEFORE any replica is mutated: a mixed fleet
            # missing worker_spec must fail with zero replicas touched
            # (a half-registered pass 1 cannot be retried — re-register
            # raises on the replicas that already host the version)
            if worker_spec is None and any(
                    hasattr(self._replicas[rid], "spawn_replacement")
                    for rid in rids):
                raise RuntimeError(
                    "fleet contains replicas that deploy by worker "
                    "replacement: deploy(..., worker_spec={decoder "
                    "geometry kwargs}) is required")
        version = str(version)
        for rid in rids:            # pass 1: host new, old keeps serving
            with self._lock:
                handle = self._replicas.get(rid)
                if handle is None or self._health[rid].dead:
                    continue
            if hasattr(handle, "spawn_replacement"):
                self._replace_replica(
                    rid, handle,
                    {**worker_spec, "name": name, "version": version},
                    timeout)
            else:
                handle.deploy(builder, name, version)
        with self._lock:
            self._pin[name] = version
        if old_version is not None and old_version != version:
            for rid in rids:        # pass 2: drain-before-retire the old
                with self._lock:
                    handle = self._replicas.get(rid)
                    if handle is None or self._health[rid].dead:
                        continue
                handle.retire(name, old_version, timeout=timeout)
        self._metrics.incr("deploys")
        return version

    def _replace_replica(self, rid, old, spec, timeout):
        """Swap a freshly spawned replacement worker into `rid`'s slot:
        spawn FIRST (the fleet never dips below strength), then
        quarantine the old worker from routing, steal its queued backlog
        (re-dispatched under original deadlines), wait for in-flight
        slots to land, commit the swap, close the old process. A spawn
        or drain failure re-admits the old worker untouched. All
        process/transport I/O runs OUTSIDE the router lock."""
        replacement = old.spawn_replacement(spec)
        with self._lock:
            self._draining.add(rid)
        try:
            self._steal_and_park(rid, old)
            self._wait_inflight_drained(rid, timeout)
        except Exception:
            with self._lock:
                self._draining.discard(rid)
            replacement.close()
            raise
        with self._lock:
            self._draining.discard(rid)
            self._replicas[rid] = replacement
            self._health[rid].revive()   # fresh process, fresh breaker
        old.close()
        self._metrics.incr("replaced_deploys")
        return replacement

    def _steal_and_park(self, rid, handle):
        try:
            stolen = set(handle.steal_queued())
        except ReplicaError as e:
            self._note_replica_failure(rid, e, during="steal")
            return
        if not stolen:
            return
        with self._lock:
            for rr in self._inflight.values():
                if (rr.state == "inflight" and rr.replica == rid
                        and rr.ticket in stolen):
                    rr.state = "parked"
                    rr.replica = rr.ticket = None
        self._metrics.incr("stolen_queued", len(stolen))

    def _wait_inflight_drained(self, rid, timeout):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                live = any(rr.state == "inflight" and rr.replica == rid
                           for rr in self._inflight.values())
            if not live:
                return
            # the pump keeps polling/delivering — unless the pump is
            # not running (hand-stepped tests) or this wait IS on the
            # pump thread (autoscale scale_down): then tick inline or
            # nothing would ever deliver the completions we wait on
            if (self._pump is None
                    or threading.current_thread() is self._pump):
                self._poll_inflight()
            time.sleep(self._pump_interval_s)
        raise TimeoutError(
            f"replica {rid} did not drain in-flight work in {timeout}s")

    # -- observability -----------------------------------------------------
    def stats(self):
        with self._lock:
            per_replica = {
                rid: {
                    "state": self._health[rid].state(),
                    "transport": self._replicas[rid].transport,
                    "load": self._replicas[rid].load(),
                    "deaths": self._health[rid].deaths,
                    "draining": rid in self._draining,
                    "severity": self._health[rid].severity,
                }
                for rid in sorted(self._replicas)
            }
            fleet_severity = max(
                (self._health[rid].severity for rid in self._routable()),
                default=0)
            inflight = sum(1 for rr in self._inflight.values()
                           if rr.state == "inflight")
            parked = sum(1 for rr in self._inflight.values()
                         if rr.state == "parked")
            pinned = dict(self._pin)
        return self._metrics.snapshot(extra={
            "replicas": per_replica,
            "inflight": inflight,
            "parked": parked,
            "pinned_versions": pinned,
            "fleet_severity": fleet_severity,
            "last_scaleup_traces": self.last_scaleup_traces,
        })

    @property
    def metrics(self):
        return self._metrics
