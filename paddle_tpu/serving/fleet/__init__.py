"""Fleet serving: a chaos-proven request router over decode replicas.

Everything below this package serves from one process; this is the tier
ROADMAP item 3 and PAPER.md's L6 layer name — a front-end router over N
``GenerationEngine`` replicas (in-process handles or subprocess workers
over a length-prefixed RPC) that turns replica death from an outage
into a re-dispatch:

* `router`  — ``FleetRouter``: prefix-affinity routing (rendezvous hash
  of the prompt prefix, spill to least-loaded), at-most-once-VISIBLE
  re-dispatch under the caller's original deadline, fleet-wide load
  shedding on the measured drain-rate retry-after, occupancy-driven
  scale-up/down, and rolling ``(model, version)`` deploys with
  drain-before-retire.
* `replica` — ``LocalReplica`` / ``SubprocessReplica``: one transport-
  blind handle surface (submit / poll_many / heartbeat / steal_queued /
  deploy / close); the ``replica.kill`` fault site makes death
  deterministically injectable on both transports.
* `health`  — ``ReplicaHealth``: the PR-2 circuit-breaker contract
  (quarantine after K consecutive failures, cooldown probe re-admission)
  under an explicit DEAD latch for hard failures.
* `worker`  — the subprocess replica entrypoint
  (``python -m paddle_tpu.serving.fleet.worker``).
* `metrics` — ``FleetMetrics``: the acceptance/outcome accounting whose
  identity (accepted == completed + deadline + failed + drained) IS the
  zero-loss gate in ``tools/chaos_serve.py``.

Locking adopts ``lockdep.named_lock`` from day one; the declared
hierarchy is ``fleet.router -> serving.queue -> decode.tenant``
(witnessed in CONCURRENCY_EVIDENCE_r11.json).
"""

from paddle_tpu.serving.fleet.health import ReplicaHealth
from paddle_tpu.serving.fleet.metrics import FleetMetrics
from paddle_tpu.serving.fleet.replica import (
    LocalReplica,
    ReplicaError,
    SubprocessReplica,
)
from paddle_tpu.serving.fleet.router import FleetRouter, RoutedRequest

__all__ = [
    "FleetMetrics",
    "FleetRouter",
    "LocalReplica",
    "ReplicaError",
    "ReplicaHealth",
    "RoutedRequest",
    "SubprocessReplica",
]
