"""Fleet-router metrics: acceptance/outcome accounting + the failover
lifecycle counters the chaos gate asserts over.

Same discipline as ServingMetrics: always-on registry-backed counters
(one ``router=<label>`` label set per FleetRouter, reset on router
creation so a rebuilt router starts from zero) plus an end-to-end
latency histogram whose p99 is the chaos scenario's headline number.

The zero-loss invariant is an ACCOUNTING identity over these counters:
every ``accepted`` request ends in exactly one of ``completed``,
``failed``, ``deadline_missed``, or ``drained_unserved`` — the chaos
tool recomputes ``accepted == completed`` (no deadlines, no drain in
the scenario) and any gap is an accepted-then-lost request.
"""

from paddle_tpu.observability import metrics as obs_metrics

__all__ = ["FleetMetrics"]


class FleetMetrics:
    COUNTERS = (
        # admission / outcome (the zero-loss identity's terms)
        "submitted", "accepted", "completed", "failed", "deadline_missed",
        "rejected_shed", "rejected_invalid", "drained_unserved",
        # failover lifecycle
        "rerouted", "dispatch_faults", "health_probe_failures",
        "replica_deaths", "replicas_revived", "supervisor_restarts",
        # per-replica circuit breaker (PR-2 contract at fleet scope)
        "breaker_opened", "breaker_probes", "breaker_closed",
        "breaker_reopened",
        # elasticity + rolling deploys ("replaced_deploys" = subprocess
        # worker-replacement swaps inside a deploy() pass)
        "scale_ups", "scale_downs", "deploys", "replaced_deploys",
        "stolen_queued",
        # brownout (r18): fleet-level sheds when EVERY routable replica
        # reports severity 4 (non-HIGH turned away at the router door)
        "brownout_shed",
    )

    def __init__(self, router_label, registry=None):
        self._registry = registry or obs_metrics.registry()
        self.router_label = str(router_label)
        labels = {"router": self.router_label}
        self._counts = {
            name: self._registry.counter(
                f"fleet_{name}_total", f"fleet router {name} count",
                labels=labels,
            )
            for name in self.COUNTERS
        }
        self._latency = self._registry.histogram(
            "fleet_latency_seconds",
            "submit-to-answer latency through the router", labels=labels,
        )
        self._healthy = self._registry.gauge(
            "fleet_healthy_replicas", "routable replica count",
            labels=labels,
        )
        for series in list(self._counts.values()) + [self._latency]:
            series.reset()
        self._healthy.set(0)

    def incr(self, name, n=1):
        self._counts[name].inc(n)

    def count(self, name):
        return self._counts[name].value

    def observe_latency(self, seconds):
        self._latency.observe(seconds)

    def set_healthy(self, n):
        self._healthy.set(n)

    def snapshot(self, extra=None):
        out = {name: c.value for name, c in self._counts.items()}
        out.update(self._latency.snapshot("latency"))
        if extra:
            out.update(extra)
        return out
