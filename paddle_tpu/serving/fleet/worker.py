"""Fleet replica worker: one decode replica in its own process.

Run as ``python -m paddle_tpu.serving.fleet.worker --index N ...``: the
worker builds the canonical cached-attention decoder from its CLI
geometry, registers it with a GenerationEngine (compile cache dir from
``PADDLE_TPU_CACHE_DIR`` — a warm disk tier means the worker is
serving-ready with ZERO traces), prints one ``FLEET_WORKER_READY``
JSON line naming its port and compile sources, and serves the router's
length-prefixed JSON RPC on a single connection.

Rolling deploys by REPLACEMENT (ROADMAP 3(b)): a builder closure cannot
cross a process boundary, so a subprocess replica never deploys
in-place. Instead the worker accepts any number of ``--model-spec
'{...}'`` JSON geometries (each a (name, version) registry entry served
concurrently by the multi-tenant engine), and the router rolls a new
version by spawning a REPLACEMENT worker hosting old+new specs into the
dead man's slot, stealing the old worker's backlog, and drain-retiring
it — then pass 2 retires the old version from the replacement via the
``retire`` RPC (a registry unregistration, which DOES cross the wire).
The legacy single-model flags stay byte-compatible.

Chaos contract: the worker fires the ``replica.kill`` fault site (rank
= ``--index``) at the top of EVERY RPC it serves, so a schedule entry
``{"site": "replica.kill", "action": "kill", "rank": N, "at_call": K}``
hard-exits this process (``os._exit`` — no flushes, no goodbyes) in the
middle of live traffic. The router observes the dropped connection,
marks the replica dead, and re-dispatches its in-flight requests — the
subprocess kill-a-replica test asserts the retried answers are
byte-identical.
"""

import argparse
import json
import os
import socket
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _send(conn, obj):
    from paddle_tpu.distributed.ps import frame_send

    frame_send(conn, json.dumps(obj).encode())


def _result_payload(resp):
    err = resp.error()
    if err is not None:
        return {"error": err.to_dict()}
    return {"tokens": [int(t) for t in resp.result()["tokens"]]}


def model_specs(args):
    """The (possibly several) decoder geometries this worker hosts:
    every ``--model-spec`` JSON, each defaulted from the legacy single-
    model flags; no specs = exactly the legacy single model."""
    base = dict(vocab_size=args.vocab_size, hidden=args.hidden,
                num_layers=args.num_layers, slots=args.slots,
                max_len=args.max_len, eos_id=args.eos_id,
                name=args.name, version=args.version)
    if not args.model_spec:
        return [base]
    return [{**base, **json.loads(s)} for s in args.model_spec]


def serve(args):
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving.decode import (
        GenerationEngine,
        build_decoder_model,
    )
    from paddle_tpu.serving.request import Priority

    engine = GenerationEngine(
        queue_depth=args.queue_depth, breaker_threshold=0,
        label=f"fleet-worker-{args.index}",
    )
    entries = []
    for spec in model_specs(args):
        entries.append(engine.register_model(
            lambda spec=spec: build_decoder_model(**spec)))
    engine.start()

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", args.port))
    srv.listen(1)
    print("FLEET_WORKER_READY " + json.dumps({
        "port": srv.getsockname()[1],
        "pid": os.getpid(),
        "models": ["@".join(k) for k in engine.models()],
        "trace": sum(e.compile_sources.get("trace", 0) for e in entries),
        "compile_sources": entries[0].compile_sources,
    }), flush=True)

    conn, _addr = srv.accept()
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    from paddle_tpu.distributed.ps import frame_recv

    tickets = {}          # ticket -> inner Response
    next_ticket = 0
    while True:
        msg = json.loads(frame_recv(conn).decode())
        # THE chaos kill site: action "kill" never returns
        faults.fire("replica.kill", rank=args.index)
        cmd = msg.get("cmd")
        if cmd == "submit":
            budget = msg.get("deadline_budget_ms")
            deadline_at = (time.perf_counter() + budget / 1e3
                           if budget is not None else None)
            try:
                resp = engine.submit(
                    msg["prompt"], model=msg.get("model"),
                    version=msg.get("version"),
                    tenant=msg.get("tenant", "default"),
                    priority=msg.get("priority", Priority.NORMAL),
                    max_new_tokens=msg.get("max_new", 16),
                    deadline_at=deadline_at,
                )
            except Exception as e:
                payload = (e.to_dict() if hasattr(e, "to_dict")
                           else {"code": "request_failed",
                                 "message": str(e)})
                _send(conn, {"ok": False, "error": payload})
                continue
            next_ticket += 1
            tickets[next_ticket] = resp
            _send(conn, {"ok": True, "ticket": next_ticket})
        elif cmd == "poll":
            done = {}
            for t in msg.get("tickets", []):
                resp = tickets.get(int(t))
                if resp is not None and resp.done():
                    done[str(t)] = _result_payload(resp)
                    del tickets[int(t)]
            _send(conn, {"done": done})
        elif cmd == "ping":
            load = 0
            for key in engine.models():
                e = engine.entry(*key)
                load += e._queue.depth() + e._pool.active_count
            _send(conn, {
                "ok": True, "load": load,
                "models": ["@".join(k) for k in engine.models()],
                "trace": sum(engine.entry(*k).compile_sources.get(
                    "trace", 0) for k in engine.models()),
            })
        elif cmd == "steal":
            stolen = []
            for key in list(engine.models()):
                for r in engine.reroute_queued(*key):
                    for t, resp in list(tickets.items()):
                        if resp is r.response:
                            stolen.append(t)
                            del tickets[t]
                            break
            _send(conn, {"tickets": stolen})
        elif cmd == "retire":
            # rolling-deploy pass 2 over the wire: drain-before-retire
            # one hosted (name, version) from the multi-tenant registry
            try:
                engine.unregister_model(
                    msg["name"], msg["version"],
                    timeout=float(msg.get("timeout", 120.0)))
            except Exception as e:
                _send(conn, {"ok": False,
                             "error": {"code": "request_failed",
                                       "message": str(e)}})
                continue
            _send(conn, {"ok": True,
                         "models": ["@".join(k) for k in engine.models()]})
        elif cmd == "stop":
            engine.shutdown()
            _send(conn, {"ok": True})
            break
        else:
            _send(conn, {"ok": False,
                         "error": {"code": "request_failed",
                                   "message": f"unknown cmd {cmd!r}"}})
    conn.close()
    srv.close()
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--index", type=int, required=True,
                    help="replica index (the replica.kill rank selector)")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--vocab-size", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=8)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-len", type=int, default=16)
    ap.add_argument("--eos-id", type=int, default=None)
    ap.add_argument("--name", type=str, default="fleet")
    ap.add_argument("--version", type=str, default="1")
    ap.add_argument("--model-spec", action="append", default=None,
                    help="JSON decoder geometry to host (repeatable; "
                         "each a (name, version) registry entry, "
                         "defaulted from the single-model flags)")
    ap.add_argument("--queue-depth", type=int, default=64)
    args = ap.parse_args(argv)
    try:
        return serve(args)
    except ConnectionError:
        # router went away: drain and exit clean (not a crash)
        return 0


if __name__ == "__main__":
    sys.exit(main())
