"""Per-replica health: the PR-2 circuit-breaker contract under an
explicit DEAD state.

Each replica the router fronts carries one ``ReplicaHealth``: a
``_ReplicaBreaker`` (serving/engine.py — closed -> K consecutive
failures -> open -> cooldown -> half-open probe) driven by BOTH dispatch
outcomes and heartbeat probes, plus a ``dead`` latch for hard failures
(transport EOF, the ``replica.kill`` fault site, a worker process
exiting). The distinction matters for routing: a quarantined (breaker-
open) replica still gets periodic probes and re-admits itself after a
healthy one; a dead replica never self-heals — it leaves the routing
set until something external (supervisor ``restart(rank)``, autoscale
replacement) revives it with a FRESH breaker.

Mutation happens under the router's ``fleet.router`` lock (the router
owns the table); the breaker keeps its own ``serving.breaker`` leaf
lock so probe gating stays safe from the health pass too.
"""

import time

from paddle_tpu.serving.engine import _ReplicaBreaker

__all__ = ["ReplicaHealth"]


class ReplicaHealth:
    __slots__ = ("threshold", "cooldown_s", "breaker", "dead",
                 "death_reason", "deaths", "last_seen", "severity")

    def __init__(self, threshold=3, cooldown_s=1.0):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.breaker = (_ReplicaBreaker(threshold, cooldown_s)
                        if threshold and threshold > 0 else None)
        self.dead = False
        self.death_reason = None
        self.deaths = 0
        self.last_seen = None
        # brownout severity (serving/brownout.py) last sampled from the
        # replica's engine stats by the router's health pass: 0 = full
        # service .. 4 = shedding; the router biases dispatch away from
        # browned-out replicas and sheds fleet-wide at 4
        self.severity = 0

    # -- routing gate ------------------------------------------------------
    def routable(self):
        """May the router dispatch here? Breaker 'probe' counts as
        routable — the probe TRAFFIC is what closes a half-open breaker
        (the PR-2 re-admission contract)."""
        if self.dead:
            return False
        if self.breaker is None:
            return True
        verdict, _ = self.breaker.gate()
        return verdict in ("dispatch", "probe")

    def probing(self):
        """True when the next dispatch is a half-open re-admission
        probe (counted by the router as `breaker_probes`)."""
        if self.dead or self.breaker is None:
            return False
        return self.breaker.gate()[0] == "probe"

    def state(self):
        if self.dead:
            return "dead"
        return self.breaker.state if self.breaker is not None else "closed"

    # -- outcome plumbing (returns the breaker lifecycle event or None) ----
    def note_success(self):
        self.last_seen = time.perf_counter()
        if self.dead or self.breaker is None:
            return None
        # consult the cooldown gate first: an open breaker whose
        # cooldown elapsed moves to half_open, so THIS healthy
        # heartbeat/dispatch is the re-admission probe that closes it
        # (without traffic, nothing else would ever call gate())
        self.breaker.gate()
        return self.breaker.record_success()

    def note_failure(self):
        if self.dead or self.breaker is None:
            return None
        # same gate-first rule: a failure after cooldown is a FAILED
        # probe — the breaker re-opens with a fresh cooldown window
        # instead of staying open on a stale opened_at
        self.breaker.gate()
        return self.breaker.record_failure()

    # -- hard lifecycle ----------------------------------------------------
    def mark_dead(self, reason=None):
        already = self.dead
        self.dead = True
        self.death_reason = str(reason) if reason is not None else None
        if not already:
            self.deaths += 1
        return not already

    def revive(self):
        """A restarted/replaced process behind the same slot: fresh
        breaker (the old failure streak belongs to the dead
        incarnation), death latch cleared."""
        self.dead = False
        self.death_reason = None
        self.breaker = (_ReplicaBreaker(self.threshold, self.cooldown_s)
                        if self.threshold and self.threshold > 0 else None)
