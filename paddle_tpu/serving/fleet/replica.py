"""Replica handles: one uniform surface over in-process and subprocess
decode replicas.

The router speaks to every replica through the same five verbs —
``submit`` (returns an opaque ticket), ``poll_many`` (tickets ->
finished results), ``heartbeat`` (liveness + load + trace counters),
``steal_queued`` (pull the admission backlog for re-dispatch), and
``deploy``/``close`` — so failover, affinity, and rolling-deploy logic
is transport-blind.

* ``LocalReplica`` wraps an in-process ``GenerationEngine``. Its tickets
  ARE the engine's Response futures. ``kill()`` simulates process death:
  the handle latches dead and refuses every verb with a fatal
  ``ReplicaError`` — exactly what the router observes when a real
  process vanishes (the abandoned engine self-drains in the background;
  nothing it produces is ever reported again). The ``replica.kill``
  fault site fires on every heartbeat, so a schedule entry
  ``{"site": "replica.kill", "action": "raise", "rank": <index>}``
  deterministically kills replica <index> at its next health probe.
* ``SubprocessReplica`` spawns ``paddle_tpu/serving/fleet/worker.py``
  (its own process, scope, and compile-cache disk tier) and speaks the
  same length-prefixed JSON protocol the PS client uses for framing
  (distributed/ps.py), with ``resilience.retry`` guarding the connect
  path. A dropped connection is a FATAL ReplicaError — the process is
  gone; failover, not reconnection, is the recovery story.

Bit-exactness note: every replica built from the same model builder
materializes byte-identical weights (deterministic init) and content-
identical programs (the compile cache proves it: a second replica warms
with zero traces), which is what makes cross-replica re-dispatch
invisible — the retried answer is the same bytes the dead replica would
have produced.
"""

import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time

from paddle_tpu.distributed.ps import frame_recv, frame_send
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.retry import RetryPolicy
from paddle_tpu.serving.request import (
    DeadlineExceededError,
    RejectedError,
    ReplicaLostError,
    RequestError,
    ServingError,
)

__all__ = ["ReplicaError", "LocalReplica", "SubprocessReplica",
           "error_from_dict"]


class ReplicaError(RuntimeError):
    """The REPLICA (not the request) failed. ``fatal=True`` means the
    process/handle is gone for good (router marks it dead and re-routes
    its in-flight work); non-fatal means this attempt failed but the
    replica may recover (drives the breaker toward quarantine)."""

    def __init__(self, message, fatal=False):
        super().__init__(message)
        self.fatal = bool(fatal)


_ERROR_CLASSES = {
    "rejected": RejectedError,
    "deadline": DeadlineExceededError,
    "replica_lost": ReplicaLostError,
    "request_failed": RequestError,
}


def error_from_dict(d):
    """Rebuild a typed ServingError from its wire ``to_dict()`` form —
    the subprocess transport's errors classify identically to local
    ones (the router branches on class, never on prose)."""
    cls = _ERROR_CLASSES.get(d.get("code"), ServingError)
    if cls is RejectedError:
        return cls(d.get("message", ""),
                   retry_after_s=d.get("retry_after_s", 0.0))
    return cls(d.get("message", ""))


class LocalReplica:
    """In-process replica: a GenerationEngine behind the handle verbs."""

    transport = "local"

    def __init__(self, rid, index, engine):
        self.rid = str(rid)
        self.index = int(index)
        self.engine = engine
        self._dead = False

    @classmethod
    def create(cls, rid, index, builder, queue_depth=64,
               breaker_threshold=0, place=None):
        """Build a serving-ready replica: engine + model + scheduler.
        The entry-level breaker defaults OFF — at fleet scope the
        ROUTER's breaker owns quarantine/probe (a replica relaunching
        itself underneath the router would double-count failures)."""
        from paddle_tpu.serving.decode import GenerationEngine

        engine = GenerationEngine(
            place=place, queue_depth=queue_depth,
            breaker_threshold=breaker_threshold, label=f"fleet-{rid}",
        )
        engine.register_model(builder)
        engine.start()
        return cls(rid, index, engine)

    # -- verbs -------------------------------------------------------------
    def _check_alive(self):
        if self._dead:
            raise ReplicaError(f"replica {self.rid} is dead", fatal=True)

    def submit(self, prompt, max_new, tenant, priority, deadline_at,
               model=None, version=None):
        self._check_alive()
        return self.engine.submit(
            prompt, model=model, version=version, tenant=tenant,
            priority=priority, max_new_tokens=max_new,
            deadline_at=deadline_at,
        )

    def poll_many(self, tickets):
        """Ticket (= inner Response) -> None while pending, else
        ("ok", outputs) / ("error", ServingError)."""
        self._check_alive()
        out = []
        for resp in tickets:
            if not resp.done():
                out.append(None)
            elif resp.error() is not None:
                out.append(("error", resp.error()))
            else:
                out.append(("ok", resp.result()))
        return out

    def load(self):
        """Queued rows + active slots across hosted entries — the
        router's saturation/least-loaded signal. Reading the queue depth
        takes ``serving.queue`` under the caller's ``fleet.router`` lock:
        the witnessed top edge of the fleet hierarchy."""
        if self._dead:
            return float("inf")
        total = 0
        for key in self.engine.models():
            entry = self.engine.entry(*key)
            total += entry._queue.depth() + entry._pool.active_count
        return total

    def heartbeat(self):
        """Liveness probe. Fires the ``replica.kill`` fault site (rank =
        this replica's index): an injected fault here IS the simulated
        process death — the handle latches dead and the probe reports it
        fatally, like a worker that stopped answering."""
        self._check_alive()
        try:
            faults.fire("replica.kill", rank=self.index)
        except faults.InjectedFault as e:
            self.kill()
            raise ReplicaError(
                f"replica {self.rid} killed by fault injection: {e}",
                fatal=True) from e
        return {
            "ok": True,
            "load": self.load(),
            "models": ["@".join(k) for k in self.engine.models()],
            "trace": self.trace_count(),
        }

    def steal_queued(self):
        """Remove every queued (not yet prefilled) request; returns
        their tickets so the router can re-dispatch the matching routed
        requests elsewhere. In-flight slots are untouched."""
        self._check_alive()
        stolen = []
        for key in list(self.engine.models()):
            for r in self.engine.reroute_queued(*key):
                stolen.append(r.response)
        return stolen

    def deploy(self, builder, name, new_version):
        """Register the new (name, version) alongside the old one — the
        multi-tenant registry serves both until the router retires the
        old version (rolling-deploy pass 1). With a warm compile cache
        the new entry lowers without tracing."""
        self._check_alive()
        self.engine.register_model(builder)

    def retire(self, name, version, timeout=120.0):
        """Drain-before-retire one hosted version (rolling-deploy pass
        2): queued + in-flight generations of that version finish, then
        the entry leaves the registry."""
        self._check_alive()
        self.engine.unregister_model(name, version, timeout=timeout)

    def trace_count(self):
        """Total XLA traces paid by this replica's entries — 0 on a
        warm-pool scale-up (memory/disk compile-cache tiers)."""
        total = 0
        for key in self.engine.models():
            total += self.engine.entry(*key).compile_sources.get("trace", 0)
        return total

    def models(self):
        return list(self.engine.models())

    def stats(self):
        return {"dead": self._dead, "engine": self.engine.stats()}

    # -- lifecycle ---------------------------------------------------------
    def kill(self):
        """Simulated hard death. The engine object is abandoned exactly
        like a crashed process: its daemon threads drain what they hold,
        but this handle never reports anything from it again."""
        if self._dead:
            return
        self._dead = True
        for key in list(self.engine.models()):
            entry = self.engine.entry(*key)
            entry._queue.close()
            with entry._cond:
                entry._stop = True
                entry._cond.notify_all()

    def close(self, timeout=60.0):
        if not self._dead:
            self.engine.shutdown(timeout)
            self._dead = True


class SubprocessReplica:
    """A decode replica in its own PROCESS, spoken to over a length-
    prefixed JSON socket (the PS wire framing). The worker is
    ``python -m paddle_tpu.serving.fleet.worker``; its env carries the
    compile-cache dir (zero-trace warm start via the jax.export disk
    tier) and any ``PADDLE_TPU_FAULTS`` schedule — the worker fires the
    ``replica.kill`` site on every RPC it serves, so a schedule with
    ``action: "kill"`` hard-exits the process mid-service."""

    transport = "subprocess"

    _CONNECT_RETRY = RetryPolicy(max_attempts=40, base_delay_s=0.1,
                                 max_delay_s=1.0, deadline_s=240.0)

    def __init__(self, rid, index, proc, sock, meta, specs=None,
                 extra_env=None):
        self.rid = str(rid)
        self.index = int(index)
        self.proc = proc
        self._sock = sock
        self._sock_lock = threading.Lock()
        self._dead = False
        self._meta = dict(meta)
        self._last_load = 0
        # remembered spawn inputs: what a REPLACEMENT worker must host
        # (rolling deploys add the new version's spec on top)
        self._specs = [dict(s) for s in (specs or [])]
        self._extra_env = dict(extra_env or {})

    @classmethod
    def spawn(cls, rid, index, model_args, extra_env=None,
              startup_timeout=240.0):
        """Spawn + handshake: the worker prints one READY line naming
        its port and where its three executables came from, then serves
        RPCs. Connect rides the shared RetryPolicy. ``model_args`` is
        one spec dict (legacy) or a list of spec dicts — each a
        (name, version) decoder geometry the worker hosts."""
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (repo, env.get("PYTHONPATH")) if p)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.update(extra_env or {})
        specs = (list(model_args) if isinstance(model_args, (list, tuple))
                 else [model_args])
        cmd = [sys.executable, "-m", "paddle_tpu.serving.fleet.worker",
               "--index", str(index)]
        for spec in specs:
            cmd += ["--model-spec", json.dumps(spec)]
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                text=True)
        deadline = time.monotonic() + startup_timeout
        meta = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                raise ReplicaError(
                    f"replica {rid} worker exited during startup "
                    f"(code {proc.poll()})", fatal=True)
            if line.startswith("FLEET_WORKER_READY "):
                meta = json.loads(line[len("FLEET_WORKER_READY "):])
                break
        if meta is None:
            proc.kill()
            raise ReplicaError(f"replica {rid} never became ready",
                               fatal=True)

        def connect():
            s = socket.create_connection(("127.0.0.1", meta["port"]),
                                         timeout=60)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s

        sock = cls._CONNECT_RETRY.call(connect)
        return cls(rid, index, proc, sock, meta, specs=specs,
                   extra_env=extra_env)

    # -- wire --------------------------------------------------------------
    def _rpc(self, obj, timeout=None):
        """One request/response over the framed socket. ``timeout``
        temporarily widens the socket timeout for RPCs whose server-side
        work legitimately blocks (retire drains a whole version) — the
        default 60s connect timeout would otherwise trip mid-drain and
        mark a healthy worker dead."""
        if self._dead:
            raise ReplicaError(f"replica {self.rid} is dead", fatal=True)
        body = json.dumps(obj).encode()
        try:
            with self._sock_lock:
                old_to = self._sock.gettimeout()
                if timeout is not None:
                    self._sock.settimeout(timeout)
                try:
                    frame_send(self._sock, body)
                    resp = frame_recv(self._sock)
                finally:
                    if timeout is not None:
                        self._sock.settimeout(old_to)
        except (ConnectionError, OSError, struct.error) as e:
            self._dead = True
            raise ReplicaError(
                f"replica {self.rid} transport lost: {e}", fatal=True
            ) from e
        return json.loads(resp.decode())

    # -- verbs -------------------------------------------------------------
    def submit(self, prompt, max_new, tenant, priority, deadline_at,
               model=None, version=None):
        budget_ms = (max(deadline_at - time.perf_counter(), 0.0) * 1e3
                     if deadline_at is not None else None)
        resp = self._rpc({
            "cmd": "submit", "prompt": list(prompt), "max_new": int(max_new),
            "tenant": tenant, "priority": int(priority),
            "deadline_budget_ms": budget_ms, "model": model,
            "version": version,
        })
        if not resp.get("ok"):
            raise error_from_dict(resp["error"])
        return int(resp["ticket"])

    def poll_many(self, tickets):
        resp = self._rpc({"cmd": "poll", "tickets": list(tickets)})
        done = resp.get("done", {})
        out = []
        for t in tickets:
            r = done.get(str(t))
            if r is None:
                out.append(None)
            elif "error" in r:
                out.append(("error", error_from_dict(r["error"])))
            else:
                out.append(("ok", {"tokens": r["tokens"]}))
        return out

    def load(self):
        """Last heartbeat's load (a live RPC per routing decision would
        put the transport inside the router lock — cached instead)."""
        return float("inf") if self._dead else self._last_load

    def heartbeat(self):
        resp = self._rpc({"cmd": "ping"})
        self._last_load = resp.get("load", 0)
        return resp

    def steal_queued(self):
        resp = self._rpc({"cmd": "steal"})
        return [int(t) for t in resp.get("tickets", [])]

    def deploy(self, builder, name, new_version):
        raise ReplicaError(
            "subprocess replicas deploy by replacement (spawn a worker "
            "hosting the new version, drain + retire this one) — the "
            "router's deploy(worker_spec=...) drives spawn_replacement()"
            ", not in-place registration")

    def spawn_replacement(self, new_spec, startup_timeout=240.0):
        """Rolling-deploy pass 1 for the subprocess transport: spawn a
        fresh worker into THIS replica's slot (same rid/index, same env)
        hosting every spec this worker hosts PLUS ``new_spec`` — the old
        version keeps serving on the replacement until the router's pin
        flips and pass 2 retires it over the wire."""
        return SubprocessReplica.spawn(
            self.rid, self.index, self._specs + [dict(new_spec)],
            extra_env=self._extra_env, startup_timeout=startup_timeout)

    def retire(self, name, version, timeout=120.0):
        """Drain-before-retire one hosted version over the RPC wire
        (registry unregistration crosses processes fine; only builder
        closures cannot)."""
        resp = self._rpc({"cmd": "retire", "name": name,
                          "version": str(version), "timeout": timeout},
                         timeout=timeout + 30.0)
        if not resp.get("ok"):
            raise ReplicaError(
                f"replica {self.rid} retire({name}@{version}) failed: "
                f"{resp.get('error', {}).get('message')}")
        self._meta["models"] = resp.get("models",
                                        self._meta.get("models", []))
        self._specs = [s for s in self._specs
                       if not (s.get("name") == name
                               and str(s.get("version")) == str(version))]

    def trace_count(self):
        return int(self._meta.get("trace", -1))

    def models(self):
        return [tuple(m.split("@", 1)) for m in self._meta.get("models", [])]

    def stats(self):
        return {"dead": self._dead, "meta": dict(self._meta),
                "load": self._last_load}

    # -- lifecycle ---------------------------------------------------------
    def kill(self):
        """Hard-kill the worker process (chaos lever; the schedule-driven
        path is the worker-side ``replica.kill`` fault site)."""
        self._dead = True
        if self.proc.poll() is None:
            self.proc.kill()

    def close(self, timeout=60.0):
        if not self._dead:
            try:
                self._rpc({"cmd": "stop"})
            except ReplicaError:
                pass
            self._dead = True
        try:
            self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
        try:
            self._sock.close()
        except OSError:
            pass
