"""Online serving subsystem: admission queue, bucketed dynamic batcher,
and SLO-aware scheduling over the AOT predictor.

The inference predictor (inference/predictor.py) is a single-request
engine: one call, one AOT-compiled executable, one answer. This package
turns it into a service. The design follows the prediction-serving
literature — Clipper's (NSDI'17) dynamic batching behind an admission
front-end and Orca's (OSDI'22) batch-window scheduling — re-based on the
TPU constraint that every served shape must be a pre-compiled bucket:
the batcher only ever forms (batch, seq-len) shapes drawn from a fixed
bucket lattice, so a warmed engine never retraces.

Layers (each its own module, composable and separately testable):

* `request`  — Request/Response futures + the structured serving errors
  (`RejectedError` carries retry-after for backpressure,
  `DeadlineExceededError` for SLO misses, `RequestError` for per-request
  failures that must not fail batchmates).
* `queue`    — `RequestQueue`: bounded-depth admission queue with
  priority lanes and deadline expiry; rejects loudly instead of queueing
  unboundedly.
* `batcher`  — `BucketLattice` (the fixed shape grid + total bucket
  mapping) and `DynamicBatcher` (coalesce queued requests into padded
  lattice batches under a max-wait timer).
* `engine`   — `ServingEngine`: worker loop over one or more Predictor
  replicas; scatter/gather of per-request rows, failure isolation,
  graceful drain, and the `stats()` snapshot.
* `metrics`  — always-on serving counters + latency reservoirs, mirrored
  into profiler.py's event/counter machinery when profiling is enabled.
* `decode`   — the continuous-batching generation subsystem (serving
  v2): iteration-level scheduler over a slotted KV arena, multi-tenant
  model registry, AOT warm start (`GenerationEngine`, `DecodeModel`,
  `build_decoder_model`).
* `fleet`    — the multi-replica tier (serving v3): `FleetRouter` over
  N engine replicas with prefix-affinity routing, health-tracked
  at-most-once-visible re-dispatch, load shedding, autoscaling, and
  rolling deploys (`LocalReplica`, `SubprocessReplica`).
"""

from paddle_tpu.serving.batcher import BucketLattice, DynamicBatcher
from paddle_tpu.serving.decode import (
    DecodeModel,
    GenerationEngine,
    build_decoder_model,
)
from paddle_tpu.serving.engine import ServingEngine
from paddle_tpu.serving.fleet import (
    FleetRouter,
    LocalReplica,
    SubprocessReplica,
)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.queue import RequestQueue
from paddle_tpu.serving.request import (
    DeadlineExceededError,
    Priority,
    RejectedError,
    ReplicaLostError,
    Request,
    RequestError,
    Response,
    ServingError,
)

__all__ = [
    "BucketLattice",
    "DeadlineExceededError",
    "DecodeModel",
    "DynamicBatcher",
    "FleetRouter",
    "GenerationEngine",
    "LocalReplica",
    "SubprocessReplica",
    "build_decoder_model",
    "Priority",
    "RejectedError",
    "ReplicaLostError",
    "Request",
    "RequestError",
    "RequestQueue",
    "Response",
    "ServingEngine",
    "ServingError",
    "ServingMetrics",
]
