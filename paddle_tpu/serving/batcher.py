"""Bucket lattice + dynamic batcher: padded batches that never retrace.

On TPU every distinct input-shape signature costs an XLA compile, so a
batcher that forms arbitrary (rows, len) shapes would turn traffic
diversity into retrace storms. `BucketLattice` fixes the admissible
grid up front — a batch-size ladder times an optional padded-axis
(sequence-length) ladder — and `DynamicBatcher` only ever emits batches
whose shapes sit exactly on that grid: requests are stacked along axis 0
and right-padded along the padded axis, dummy rows fill the batch bucket
and are sliced back out of the outputs. Warm the lattice once
(`Predictor.warmup`) and the compile-cache hit rate stays 100%.

Scheduling is Clipper-style: wait for the bucket to fill, but never past
`max_wait_s` from the head request's admission, and never past a
gathered request's deadline — latency SLOs bound batching gain, not the
other way round.
"""

import time

import numpy as np

from paddle_tpu.observability.tracer import trace_scope
from paddle_tpu.serving.request import RejectedError

__all__ = ["BucketLattice", "DynamicBatcher", "BatchPlan"]


class BucketLattice:
    """The fixed (batch, padded-axis-length) shape grid.

    `batch_sizes` is the row ladder; `seq_lens` (optional) the padded-axis
    ladder — when None the batcher never pads trailing dims, so only
    requests with identical trailing shapes share a batch. `pad_axis` is
    the axis that gets length-padded on every input that has it (inputs
    of rank <= pad_axis are stacked only). Bucket mapping is
    deterministic and total over admissible shapes: smallest ladder entry
    >= the observed value.
    """

    def __init__(self, batch_sizes=(1, 2, 4, 8), seq_lens=None, pad_axis=1,
                 pad_value=0):
        batch_sizes = sorted(int(b) for b in batch_sizes)
        if not batch_sizes or batch_sizes[0] < 1:
            raise ValueError(f"bad batch ladder {batch_sizes}")
        self.batch_sizes = tuple(batch_sizes)
        self.seq_lens = tuple(sorted(int(s) for s in seq_lens)) if seq_lens \
            else None
        if self.seq_lens and self.seq_lens[0] < 1:
            raise ValueError(f"bad seq ladder {self.seq_lens}")
        self.pad_axis = int(pad_axis)
        self.pad_value = pad_value

    @staticmethod
    def pow2(max_batch, max_seq=None, min_seq=8, pad_axis=1):
        """Power-of-two ladders up to the given maxima — the C ABI's
        scalar (max_batch, max_seq) spelling of a lattice."""
        batches = [1]
        while batches[-1] * 2 <= int(max_batch):
            batches.append(batches[-1] * 2)
        seqs = None
        if max_seq:
            seqs = [int(min_seq)]
            while seqs[-1] * 2 <= int(max_seq):
                seqs.append(seqs[-1] * 2)
        return BucketLattice(batches, seqs, pad_axis=pad_axis)

    @property
    def max_rows(self):
        return self.batch_sizes[-1]

    @property
    def max_len(self):
        return self.seq_lens[-1] if self.seq_lens else None

    def bucket_rows(self, rows):
        """Smallest batch bucket >= rows (total over 1..max_rows)."""
        for b in self.batch_sizes:
            if b >= rows:
                return b
        raise RejectedError(
            f"request rows {rows} exceed the largest batch bucket "
            f"{self.max_rows}; split the request or widen the lattice"
        )

    def bucket_len(self, length):
        """Smallest length bucket >= length (total over 1..max_len)."""
        if self.seq_lens is None:
            return 0
        for s in self.seq_lens:
            if s >= length:
                return s
        raise RejectedError(
            f"padded-axis length {length} exceeds the largest bucket "
            f"{self.max_len}; truncate the request or widen the lattice"
        )

    def classify(self, inputs, var_feeds=None):
        """Admission-time shape analysis: returns (rows, var_len,
        group_key) or raises RejectedError for inadmissible shapes.
        group_key captures everything batchmates must agree on — feed
        names, dtypes, and trailing dims with the padded axis masked.

        `var_feeds` (optional) names the inputs whose pad_axis dim is
        genuinely variable (declared -1 in the program); inputs outside
        it keep their trailing dims fixed — a declared-fixed dim must
        never be padded to a length bucket (the resulting shape was
        never warmed AND the program would reject it). Without the set,
        every input of sufficient rank is treated as variable."""
        rows = None
        var_len = 0
        key = []
        for name in sorted(inputs):
            arr = inputs[name]
            if arr.ndim < 1:
                raise RejectedError(f"input '{name}' is rank-0; requests "
                                    "need a leading batch axis")
            if rows is None:
                rows = int(arr.shape[0])
            elif int(arr.shape[0]) != rows:
                raise RejectedError(
                    f"input '{name}' has {arr.shape[0]} rows; other inputs "
                    f"have {rows} — all inputs share the batch axis"
                )
            tail = list(arr.shape[1:])
            if (self.seq_lens is not None and arr.ndim > self.pad_axis
                    and (var_feeds is None or name in var_feeds)):
                var_len = max(var_len, int(arr.shape[self.pad_axis]))
                tail[self.pad_axis - 1] = None  # masked: padded away
            key.append((name, str(arr.dtype), tuple(tail)))
        if rows is None:
            raise RejectedError("request has no inputs")
        if rows < 1:
            raise RejectedError("request has zero rows")
        self.bucket_rows(rows)  # raises when inadmissible
        if var_len:
            self.bucket_len(var_len)
        return rows, var_len, tuple(key)


class BatchPlan:
    """One dispatchable padded batch: which requests, at which lattice
    point, and where each request's rows sit."""

    __slots__ = ("requests", "bucket_rows", "bucket_len", "offsets")

    def __init__(self, requests, bucket_rows, bucket_len):
        self.requests = requests
        self.bucket_rows = bucket_rows
        self.bucket_len = bucket_len
        self.offsets = []
        off = 0
        for r in requests:
            self.offsets.append(off)
            off += r.rows

    @property
    def real_rows(self):
        return sum(r.rows for r in self.requests)

    @property
    def occupancy(self):
        return self.real_rows / float(self.bucket_rows)


class DynamicBatcher:
    """Coalesce queued requests into lattice batches under a max-wait
    timer. Callers hold `queue.lock` across plan() (it scans and then
    removes — the engine's dispatch Condition is built on that lock).

    `feed_specs` / `fetch_specs` ({name: declared shape list or None})
    come from the served program and make padding/scatter decisions
    exact: only a feed whose pad_axis dim is declared -1 is
    length-padded, only a fetch whose leading dim is declared -1 is
    row-sliced back out. Without specs both fall back to shape-based
    heuristics (rank for feeds, first-dim match for fetches)."""

    def __init__(self, lattice, max_wait_s=0.005, feed_specs=None,
                 fetch_specs=None):
        self.lattice = lattice
        self.max_wait_s = float(max_wait_s)
        self.feed_specs = feed_specs
        self.fetch_specs = fetch_specs
        if feed_specs is None:
            self.var_feeds = None
        else:
            self.var_feeds = {
                n for n, shape in feed_specs.items()
                if shape is None or (len(shape) > lattice.pad_axis
                                     and int(shape[lattice.pad_axis]) == -1)
            }

    def _pads_feed(self, name, proto):
        if proto.ndim <= self.lattice.pad_axis:
            return False
        return self.var_feeds is None or name in self.var_feeds

    def _batched_fetch(self, name, out, plan):
        """Is this output batch-aligned (axis 0 = bucket rows)?"""
        if out.ndim < 1 or out.shape[0] != plan.bucket_rows:
            return False
        if self.fetch_specs is None or name not in self.fetch_specs:
            return True  # heuristic: first dim matches the bucket
        shape = self.fetch_specs[name]
        return shape is None or (len(shape) >= 1 and int(shape[0]) == -1)

    def _var_fetch(self, name):
        """May this output's pad_axis be length-sliced per request?"""
        if self.fetch_specs is None or name not in self.fetch_specs:
            return True
        shape = self.fetch_specs[name]
        return shape is None or (len(shape) > self.lattice.pad_axis
                                 and int(shape[self.lattice.pad_axis]) == -1)

    # -- planning ----------------------------------------------------------
    def plan(self, queue, now=None, force=False):
        """Form the next batch, or None when waiting longer is the better
        schedule. Deterministic given queue contents + clock: take the
        head (oldest, highest lane), gather group-compatible requests
        whose padded length fits the head's length bucket, dispatch when
        the batch bucket is full, the head aged past max_wait, or a
        gathered deadline is imminent."""
        now = now if now is not None else time.perf_counter()
        head = queue.head()
        if head is None:
            return None
        target_len = (self.lattice.bucket_len(head.var_len)
                      if head.var_len else 0)
        gathered, rows = [], 0
        for r in queue.iter_requests():
            if r.group_key != head.group_key:
                continue
            if target_len and r.var_len > target_len:
                continue  # longer sequences wait for their own bucket
            if rows + r.rows > self.lattice.max_rows:
                continue  # would overflow the largest bucket; next batch
            gathered.append(r)
            rows += r.rows
        full = rows >= self.lattice.max_rows
        aged = (now - head.submit_time) >= self.max_wait_s
        urgent = any(
            r.deadline is not None and (r.deadline - now) <= self.max_wait_s
            for r in gathered
        )
        if not (force or full or aged or urgent):
            return None
        queue.remove(gathered)
        for r in gathered:
            r.dispatch_time = now
        return BatchPlan(gathered, self.lattice.bucket_rows(rows), target_len)

    def wait_hint(self, queue, now=None):
        """Seconds the worker may sleep before the head batch must
        dispatch (max-wait expiry or earliest queued deadline)."""
        now = now if now is not None else time.perf_counter()
        head = queue.head()
        if head is None:
            return self.max_wait_s
        hint = max(0.0, self.max_wait_s - (now - head.submit_time))
        for r in queue.iter_requests():
            if r.deadline is not None:
                hint = min(hint, max(0.0, r.deadline - now))
        return hint

    # -- padding / scatter -------------------------------------------------
    def assemble(self, plan):
        """Build the padded feed dict for one plan. Per-request assembly
        failures raise RequestError-compatible exceptions upward; the
        engine isolates them (a bad request must not fail batchmates)."""
        with trace_scope("serving::batch_form", cat="serving",
                         rows=plan.real_rows, bucket=plan.bucket_rows):
            return self._assemble(plan)

    def _assemble(self, plan):
        first = plan.requests[0].inputs
        feeds = {}
        for name, proto in first.items():
            shape = list(proto.shape)
            shape[0] = plan.bucket_rows
            if plan.bucket_len and self._pads_feed(name, proto):
                shape[self.lattice.pad_axis] = plan.bucket_len
            out = np.full(shape, self.lattice.pad_value, dtype=proto.dtype)
            for r, off in zip(plan.requests, plan.offsets):
                a = r.inputs[name]
                idx = (slice(off, off + r.rows),) + tuple(
                    slice(0, d) for d in a.shape[1:]
                )
                out[idx] = a
            feeds[name] = out
        return feeds

    def scatter(self, plan, outputs, request=None):
        """Split padded batch outputs back into per-request dicts.

        Batch-aligned outputs (axis 0 == bucket rows) are row-sliced, and
        a padded axis matching the length bucket is cut back to each
        request's real length; outputs without a batch axis (e.g. a
        scalar score) are replicated to every request as-is."""
        reqs = ([request] if request is not None else plan.requests)
        offs = ([0] if request is not None else plan.offsets)
        results = []
        for r, off in zip(reqs, offs):
            per = {}
            for name, out in outputs.items():
                o = out
                if self._batched_fetch(name, o, plan):
                    o = o[off:off + r.rows]
                    if (plan.bucket_len and r.var_len
                            and o.ndim > self.lattice.pad_axis
                            and o.shape[self.lattice.pad_axis]
                            == plan.bucket_len
                            and self._var_fetch(name)):
                        idx = ((slice(None),) * self.lattice.pad_axis
                               + (slice(0, r.var_len),))
                        o = o[idx]
                per[name] = np.asarray(o)
            results.append(per)
        return results
