"""Generation modes for the paged decode engine: the decode-POLICY layer
between the scheduler and the fixed-shape programs.

Everything the engine compiles stays exactly as PR 13 left it — one
``[S, 1]`` decode step, donated arenas, the content-addressed compile
cache — and every mode here is host-side policy over the fetched logits
and the block tables:

* ``sampling`` — temperature/top-k/top-p on a committed threefry
  stream keyed per-(request seed, absolute token index): replay is
  bit-exact for any admission order, batchmates, or slot assignment,
  and speculative acceptance graduates from greedy-match to the
  committed-coupling rejection rule (same realized stream as
  target-only sampled decode).
* ``beam`` — beam search as COW forks over the paged block arena:
  beams are slots in the shared decode batch, a fork is refcount++ plus
  one private tail block, pruning releases through the normal retire
  path (row conservation asserted).
* ``grammar`` — JSON-schema / regex compiled host-side to per-step
  fixed-shape ``[S, V]`` logits masks fed as DATA through the
  ``DEC_MASK`` feed: structured output with zero retraces.

Each mode (and each composition) is bit-identical to its offline
whole-sequence reference — the GEN_EVIDENCE_r17 property, drift-gated
by tools/decode_report.py.
"""

from paddle_tpu.serving.decode.generate.beam import (
    BeamParams,
    offline_beam_decode,
)
from paddle_tpu.serving.decode.generate.grammar import (
    CompiledGrammar,
    GrammarConstraint,
    compile_regex,
    json_schema_regex,
)
from paddle_tpu.serving.decode.generate.sampling import (
    SamplingParams,
    gumbel_vector,
    sample_token,
)

__all__ = [
    "BeamParams",
    "CompiledGrammar",
    "GrammarConstraint",
    "SamplingParams",
    "compile_regex",
    "gumbel_vector",
    "json_schema_regex",
    "offline_beam_decode",
    "sample_token",
]
