"""Replayable sampled decode: temperature/top-k/top-p on a committed
threefry stream.

The sampling contract that makes continuous-batching sampling REPLAYABLE
is the same one that makes paged decode bit-exact: make every source of
randomness a pure function of request-local state. The stream here is
jax's counter-based threefry — ``PRNGKey(seed)`` folded with the ABSOLUTE
index of the token being chosen — so the noise for request R's token t is
a function of ``(R.seed, t)`` and NOTHING else: not the batchmates, not
the slot index, not the admission order, not whether the token was
emitted by a plain decode step or inside a speculative verify cycle.
Replaying a request with the same seed reproduces the byte-identical
token stream in any of those configurations (the GEN_EVIDENCE_r17
property), because

* threefry is counter-based and bit-exact across backends/platforms (a
  jax guarantee the compile-cache work already leans on), and
* everything downstream of the raw bits is float64 numpy on the host —
  one IEEE-deterministic code path shared by the engine, the
  speculative verify loop, and the offline reference.

Selection is **Gumbel-max**: ``argmax(z + g)`` over the filtered scaled
logits ``z`` (an exact draw from ``softmax(z)``). Argmax-with-noise
keeps greedy decode (``temperature == 0``) and sampled decode on ONE
code shape, and is what the speculative coupling below rides on.

Speculative acceptance — the committed-coupling rejection rule
--------------------------------------------------------------
Greedy speculative decoding accepts a draft proposal iff it equals the
target's argmax. The sampled graduation keeps the same shape: at each
position the target draws ITS OWN committed-stream sample ``t`` (from
the Gumbel vector keyed by the absolute position), always emits ``t``,
and accepts the draft's proposal iff ``proposal == t`` (acceptance lets
the cycle keep consuming verify positions; a mismatch makes ``t`` the
correction token and ends the cycle). This is the rejection-sampling
rule under the maximal coupling induced by the shared committed stream:
the acceptance probability of a draft token is exactly the target's
probability mass on it, and the residual (correction) draw IS the
target's own Gumbel-max sample. The payoff over the distributional
rule: the realized stream is bit-for-bit the target-only sampled
stream — replay, drift gates, and the offline reference stay
byte-comparable, and ``temperature -> 0`` degrades exactly to the
greedy-match rule instead of to a different code path.
"""

import numpy as np

__all__ = ["SamplingParams", "gumbel_vector", "filtered_scores",
           "sample_token"]


class SamplingParams:
    """Per-request sampling policy. ``temperature == 0`` is greedy (the
    stream is never consulted); ``top_k``/``top_p`` filter BEFORE the
    Gumbel draw in the usual nucleus order (k-truncate, then p-truncate
    over the survivors). ``seed`` is the replay contract: same seed +
    same prompt => byte-identical stream under ANY admission order."""

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature=1.0, top_k=0, top_p=1.0, seed=0):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not (0.0 < self.top_p <= 1.0):
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")

    @property
    def greedy(self):
        return self.temperature == 0.0

    def describe(self):
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}


def gumbel_vector(seed, step, vocab_size):
    """The committed noise for token index ``step`` of a request seeded
    ``seed``: a ``[V]`` float64 Gumbel(0,1) vector, a pure function of
    ``(seed, step)``. Threefry bits -> open-interval uniforms
    ``(b + 0.5) / 2^32`` (never exactly 0 or 1, so the double log below
    is always finite) -> ``-log(-log(u))``, all float64 numpy."""
    import jax

    key = jax.random.fold_in(jax.random.PRNGKey(int(seed)), int(step))
    bits = np.asarray(jax.random.bits(key, (int(vocab_size),), "uint32"))
    u = (bits.astype(np.float64) + 0.5) / np.float64(2.0 ** 32)
    return -np.log(-np.log(u))


def filtered_scores(logits, params):
    """Scaled-and-filtered scores ``z`` (float64 ``[V]``): kept tokens
    carry ``logits / temperature``, filtered tokens ``-inf``. The keep
    order is fully deterministic — ties in the logits break by token id
    (ascending), via one stable lexsort shared with nothing
    platform-dependent."""
    x = np.asarray(logits, dtype=np.float64).reshape(-1)
    v = x.size
    # tokens sorted by (logit desc, id asc): the canonical nucleus order
    order = np.lexsort((np.arange(v), -x))
    keep = np.ones(v, dtype=bool)
    if params.top_k and params.top_k < v:
        keep[order[params.top_k:]] = False
    if params.top_p < 1.0:
        xs = x[order]
        m = xs[0]
        probs = np.exp(xs - m)
        probs /= probs.sum()
        cum = np.cumsum(probs)
        # the token that CROSSES top_p is included (standard nucleus);
        # everything past it is cut
        cut = int(np.searchsorted(cum, params.top_p, side="left")) + 1
        drop = order[cut:]
        keep[drop] = False
    z = np.where(keep, x / np.float64(params.temperature or 1.0),
                 -np.inf)
    return z


def sample_token(logits, params, step):
    """Choose token index ``step`` of the request: greedy argmax when
    ``temperature == 0`` (ties by lowest id, numpy argmax), else
    Gumbel-max over the filtered scaled scores with the committed noise
    for ``(params.seed, step)``. Pure host function — the engine's
    decode step, the speculative verify loop, and the offline reference
    all call exactly this."""
    x = np.asarray(logits, dtype=np.float64).reshape(-1)
    if params is None or params.greedy:
        return int(np.argmax(x))
    z = filtered_scores(x, params)
    g = gumbel_vector(params.seed, step, x.size)
    return int(np.argmax(z + g))
