"""Beam search as COW forks: the selection semantics, committed once.

SURVEY §7 flags beam search as the hard dynamic-shape case a fixed-shape
serving design has to absorb: hypotheses fork, prune, and finish every
step, while the compiled world permits exactly one ``[S, 1]`` decode
executable. The engine's answer (engine.py) is that **a beam is just a
slot**: live hypotheses of one request occupy ordinary batch slots of
the shared decode step, a fork is a block-table copy (refcount++ on the
shared full blocks + one private tail block) over the PR 13 paged
arena, and pruning releases blocks through the same retire path as any
finished request — so the block-pool row-conservation invariant is
checkable across every fork/prune and the compiled shapes never change.

This module owns the HOST half: candidate scoring and the selection
rule, shared verbatim by the engine's incremental loop and by
``offline_beam_decode`` (the whole-sequence reference every beam result
is bit-compared against). Determinism contract:

* scores are float64 log-softmax sums computed from the fetched float32
  logits — one IEEE code path, no platform-dependent reductions;
* candidates rank by ``(-score, parent index, token id)`` — every tie
  breaks by position in the PARENT ORDER then token id, so equal-score
  hypotheses resolve identically everywhere;
* masked tokens (additive ``-1e9`` grammar mask) are excluded from
  candidacy outright rather than relying on their score sinking — a
  constrained beam can THIN below its width, never violate the grammar;
* selection fills ``width - |finished|`` live continuations per step,
  diverting EOS candidates to the finished set as they rank (the
  standard in-order split), and a continuation that exhausts
  ``max_new`` or the arena length finishes immediately with its score.

Beam search is deterministic — it composes with grammar masks but is
rejected with sampling or speculation at submit (documented in the
README mode matrix).
"""

import numpy as np

__all__ = ["BeamParams", "log_softmax64", "rank_candidates", "select",
           "finished_ranking", "offline_beam_decode"]

# candidacy floor: anything at or below half the additive mask value is
# a banned token, not a real logit (real logits live at |x| << 5e8)
_BANNED = -5e8


class BeamParams:
    """Per-request beam policy: ``width`` live hypotheses (slots). The
    score is the plain sum of token log-probabilities — no length
    penalty, so the reference stays a pure argmax-free fold."""

    __slots__ = ("width",)

    def __init__(self, width):
        self.width = int(width)
        if self.width < 1:
            raise ValueError(f"beam width must be >= 1, got {self.width}")

    def describe(self):
        return {"width": self.width}


def log_softmax64(logits):
    """Float64 log-softmax of a ``[V]`` logits row, max-shifted."""
    x = np.asarray(logits, dtype=np.float64).reshape(-1)
    m = x.max()
    return x - (m + np.log(np.exp(x - m).sum()))


def rank_candidates(scores, logits_rows):
    """All (parent, token) continuations ranked by
    ``(-total_score, parent, token)``; banned (masked) tokens never
    become candidates. ``scores`` are the parents' cumulative float64
    log-probs; ``logits_rows`` their fetched (already masked, when a
    grammar is active) float32 logits."""
    parents, tokens, totals = [], [], []
    for p, (s, row) in enumerate(zip(scores, logits_rows)):
        raw = np.asarray(row, dtype=np.float64).reshape(-1)
        ls = log_softmax64(raw)
        ok = np.nonzero(raw > _BANNED)[0]
        parents.append(np.full(ok.shape, p, dtype=np.int64))
        tokens.append(ok.astype(np.int64))
        totals.append(np.float64(s) + ls[ok])
    if not parents:
        return []
    parents = np.concatenate(parents)
    tokens = np.concatenate(tokens)
    totals = np.concatenate(totals)
    order = np.lexsort((tokens, parents, -totals))
    return [(int(parents[i]), int(tokens[i]), float(totals[i]))
            for i in order]


def select(scores, logits_rows, room, eos_id):
    """ONE beam step's selection: consume ranked candidates in order,
    diverting EOS continuations to ``finished`` until
    ``len(live) + len(finished) == room`` (``room`` = width minus the
    hypotheses already finished). Returns ``(live, finished)`` lists of
    ``(parent, token, score)``."""
    live, finished = [], []
    for parent, token, total in rank_candidates(scores, logits_rows):
        if len(live) + len(finished) >= room:
            break
        if eos_id is not None and token == eos_id:
            finished.append((parent, token, total))
        else:
            live.append((parent, token, total))
    return live, finished


def finished_ranking(finished):
    """Final ranking of finished hypotheses: score desc, then token
    sequence (ascending lexicographic) — fully deterministic even for
    exact score ties."""
    return sorted(finished, key=lambda f: (-f[1], tuple(f[0])))


def offline_beam_decode(logits_fn, prompt, max_new, params, eos_id,
                        max_len, grammar=None):
    """The whole-sequence beam reference: ``logits_fn(tokens)`` returns
    the float32 ``[V]`` next-token logits of a full forward over
    ``tokens`` (the engine wires the prefill program in). The loop here
    IS the committed selection semantics — the engine's slot-based
    incremental beam must reproduce its output byte-for-byte, which the
    GEN_EVIDENCE_r17 drift gate asserts.

    Returns finished hypotheses ``[(tokens, score), ...]`` best-first
    (``finished_ranking``); tokens include the EOS when one fired."""
    prompt = [int(t) for t in prompt]
    live = [([], 0.0, grammar.fork() if grammar is not None else None)]
    finished = []
    while live and len(finished) < params.width:
        rows = []
        for toks, _score, g in live:
            row = np.asarray(logits_fn(prompt + toks),
                             dtype="float32").reshape(-1)
            if g is not None:
                row = row + g.mask()          # float32, the DEC_MASK add
            rows.append(row)
        room = params.width - len(finished)
        sel_live, sel_fin = select([s for _t, s, _g in live], rows,
                                   room, eos_id)
        for parent, token, total in sel_fin:
            finished.append((live[parent][0] + [token], total))
        nxt = []
        for parent, token, total in sel_live:
            toks2 = live[parent][0] + [token]
            g2 = live[parent][2]
            if g2 is not None:
                g2 = g2.fork().advance(token)
            if (len(toks2) >= max_new
                    or len(prompt) + len(toks2) >= max_len):
                finished.append((toks2, total))
            else:
                nxt.append((toks2, total, g2))
        live = nxt
    return finished_ranking(finished)
