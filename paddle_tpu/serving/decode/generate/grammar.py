"""Grammar-constrained decode: regex / JSON-schema -> per-step logits
masks, compiled host-side, fed as data.

The structured-output contract has to survive the engine's two
non-negotiables: fixed shapes (one compiled decode executable, ZERO
retraces) and bit-exact replay. Both fall out of compiling the grammar
to a **token-level mask function on the host** and feeding the result
through one fixed-shape ``[S, 1, V]`` additive feed (model.py's
``DEC_MASK``; prefill-derived logits are masked with the same float32
add on the host — IEEE ``x + 0.0 == x`` and the repo-wide ``-1e9``
padding contract make the two application points byte-identical):

* regex (a practical subset: literals, escapes, ``.``, ``[...]``
  classes with ranges/negation, grouping, ``|``, ``* + ?``) compiles
  through Thompson NFA -> subset-construction DFA over exactly the
  characters the vocabulary can emit;
* DFA states that cannot reach an accepting state are pruned as DEAD,
  so a live state always has at least one allowed continuation — a
  constrained generation can never paint itself into a corner;
* a token is allowed in state ``s`` iff walking its string lands in a
  live state; EOS is allowed exactly in accepting states (which is why
  grammar requests require a model with an ``eos_id``);
* per-state ``[V]`` masks are computed lazily and cached on the
  COMPILED grammar (shared by every request and every beam using it);
  the per-request/per-beam cursor is ONE integer, which is what makes
  grammar state forkable for free in beam search.

JSON-schema support is a canonical-form subset (objects with declared
properties in order, no whitespace; string/integer/number/boolean/null
/enum/array leaves) lowered to a regex and compiled through the same
engine — one mask semantics, one evidence path.
"""

import numpy as np

from paddle_tpu.serving.decode.model import NEG_INF

__all__ = ["CompiledGrammar", "GrammarConstraint", "compile_regex",
           "json_schema_regex"]


# -- regex -> NFA (Thompson construction) --------------------------------

_CLASSES = {
    "d": set("0123456789"),
    "w": set("abcdefghijklmnopqrstuvwxyz"
             "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "s": set(" \t\n\r"),
}


class _Frag:
    __slots__ = ("start", "accepts")

    def __init__(self, start, accepts):
        self.start = start
        self.accepts = accepts


class _NFA:
    def __init__(self):
        self.eps = []        # state -> [state]
        self.trans = []      # state -> [(frozenset(chars) | None=any, state)]

    def new_state(self):
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1


class _RegexParser:
    """Recursive-descent regex -> NFA fragment. Grammar:
    alt := concat ('|' concat)* ; concat := repeat* ;
    repeat := atom ('*'|'+'|'?')? ; atom := literal | class | '.' | '(' alt ')'
    """

    def __init__(self, pattern, nfa):
        self.p = pattern
        self.i = 0
        self.nfa = nfa

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _take(self):
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        frag = self._alt()
        if self.i != len(self.p):
            raise ValueError(
                f"unexpected {self.p[self.i]!r} at {self.i} in regex "
                f"{self.p!r}")
        return frag

    def _alt(self):
        frags = [self._concat()]
        while self._peek() == "|":
            self._take()
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        start = self.nfa.new_state()
        accepts = []
        for f in frags:
            self.nfa.eps[start].append(f.start)
            accepts.extend(f.accepts)
        return _Frag(start, accepts)

    def _concat(self):
        frags = []
        while self._peek() is not None and self._peek() not in "|)":
            frags.append(self._repeat())
        if not frags:
            s = self.nfa.new_state()
            return _Frag(s, [s])
        out = frags[0]
        for f in frags[1:]:
            for a in out.accepts:
                self.nfa.eps[a].append(f.start)
            out = _Frag(out.start, f.accepts)
        return out

    def _repeat(self):
        frag = self._atom()
        c = self._peek()
        if c not in ("*", "+", "?"):
            return frag
        self._take()
        start = self.nfa.new_state()
        end = self.nfa.new_state()
        self.nfa.eps[start].append(frag.start)
        for a in frag.accepts:
            self.nfa.eps[a].append(end)
        if c in ("*", "?"):
            self.nfa.eps[start].append(end)      # skip
        if c in ("*", "+"):
            self.nfa.eps[end].append(frag.start)  # loop
        return _Frag(start, [end])

    def _atom(self):
        c = self._take()
        if c == "(":
            frag = self._alt()
            if self._peek() != ")":
                raise ValueError(f"unbalanced '(' in regex {self.p!r}")
            self._take()
            return frag
        if c == "[":
            return self._char_frag(self._char_class())
        if c == ".":
            return self._char_frag(None)          # any char
        if c == "\\":
            return self._char_frag(self._escape(self._take()))
        if c in "*+?)|":
            raise ValueError(f"unexpected {c!r} in regex {self.p!r}")
        return self._char_frag(frozenset(c))

    def _escape(self, c):
        if c in _CLASSES:
            return frozenset(_CLASSES[c])
        if c == "n":
            return frozenset("\n")
        if c == "t":
            return frozenset("\t")
        return frozenset(c)                       # \. \\ \[ \" ...

    def _char_class(self):
        negate = False
        if self._peek() == "^":
            self._take()
            negate = True
        chars = set()
        while True:
            c = self._peek()
            if c is None:
                raise ValueError(f"unbalanced '[' in regex {self.p!r}")
            if c == "]":
                self._take()
                break
            c = self._take()
            if c == "\\":
                chars |= set(self._escape(self._take()))
                continue
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._take()
                hi = self._take()
                chars |= {chr(x) for x in range(ord(c), ord(hi) + 1)}
            else:
                chars.add(c)
        if negate:
            return ("negate", frozenset(chars))
        return frozenset(chars)

    def _char_frag(self, charset):
        start = self.nfa.new_state()
        end = self.nfa.new_state()
        self.nfa.trans[start].append((charset, end))
        return _Frag(start, [end])


def _charset_match(charset, ch):
    if charset is None:                           # '.'
        return True
    if isinstance(charset, tuple):                # ("negate", chars)
        return ch not in charset[1]
    return ch in charset


class _DFA:
    """Deterministic automaton with dead states pruned: ``step`` returns
    the next LIVE state or None; ``accepting`` is per-state."""

    __slots__ = ("start", "table", "accepting")

    def __init__(self, start, table, accepting):
        self.start = start
        self.table = table            # state -> {char: state}
        self.accepting = accepting    # list[bool]

    def step(self, state, ch):
        return self.table[state].get(ch)

    def walk(self, state, text):
        for ch in text:
            state = self.table[state].get(ch)
            if state is None:
                return None
        return state


def compile_regex(pattern, alphabet):
    """Compile ``pattern`` to a dead-state-free DFA over ``alphabet``
    (the set of characters the vocabulary can emit — characters outside
    it can never be generated, so the DFA doesn't need them)."""
    nfa = _NFA()
    frag = _RegexParser(str(pattern), nfa).parse()
    accept_set = frozenset(frag.accepts)
    alphabet = sorted(set(alphabet))

    def eps_closure(states):
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    start = eps_closure({frag.start})
    index = {start: 0}
    order = [start]
    table = []
    queue = [start]
    while queue:
        cur = queue.pop(0)
        row = {}
        for ch in alphabet:
            nxt = set()
            for s in cur:
                for charset, t in nfa.trans[s]:
                    if _charset_match(charset, ch):
                        nxt.add(t)
            if not nxt:
                continue
            closed = eps_closure(nxt)
            if closed not in index:
                index[closed] = len(order)
                order.append(closed)
                queue.append(closed)
                table.append(None)   # placeholder; filled when popped
            row[ch] = index[closed]
        if len(table) <= index[cur]:
            table.extend([None] * (index[cur] + 1 - len(table)))
        table[index[cur]] = row
    accepting = [bool(st & accept_set) for st in order]
    # prune DEAD states (cannot reach an accepting state): reverse BFS
    n = len(order)
    rev = [[] for _ in range(n)]
    for s, row in enumerate(table):
        for t in row.values():
            rev[t].append(s)
    live = set(i for i in range(n) if accepting[i])
    stack = list(live)
    while stack:
        s = stack.pop()
        for p in rev[s]:
            if p not in live:
                live.add(p)
                stack.append(p)
    if 0 not in live:
        raise ValueError(
            f"regex {pattern!r} matches nothing over this vocabulary")
    pruned = [{ch: t for ch, t in row.items() if t in live}
              for row in table]
    return _DFA(0, pruned, accepting)


# -- JSON schema (canonical-form subset) -> regex ------------------------

_JSON_STRING = '"[a-zA-Z0-9_ ]*"'
_JSON_INT = "(-?(0|[1-9][0-9]*))"
_JSON_NUM = _JSON_INT + "(\\.[0-9][0-9]*)?"
_JSON_BOOL = "(true|false)"


def json_schema_regex(schema):
    """Lower a JSON-schema subset to a regex over the CANONICAL encoding
    (properties in declared order, all present, no whitespace). Supports
    type string/integer/number/boolean/null, enum (of strings), array
    (homogeneous items), object (nested). Canonical form is the honest
    contract: the mask constrains the decode to one unambiguous
    byte-serialization, which is what a structured-output consumer
    parses."""
    if "enum" in schema:
        opts = []
        for v in schema["enum"]:
            if not isinstance(v, str):
                raise ValueError(f"enum supports strings, got {v!r}")
            opts.append('"' + _regex_escape(v) + '"')
        return "(" + "|".join(opts) + ")"
    t = schema.get("type")
    if t == "string":
        return _JSON_STRING
    if t == "integer":
        return _JSON_INT
    if t == "number":
        return _JSON_NUM
    if t == "boolean":
        return _JSON_BOOL
    if t == "null":
        return "null"
    if t == "array":
        item = json_schema_regex(schema.get("items", {"type": "integer"}))
        return "(\\[\\]|\\[" + item + "(," + item + ")*\\])"
    if t == "object":
        props = schema.get("properties", {})
        if not props:
            return "\\{\\}"
        parts = []
        for name, sub in props.items():
            parts.append('"' + _regex_escape(name) + '":'
                         + json_schema_regex(sub))
        return "\\{" + ",".join(parts) + "\\}"
    raise ValueError(f"unsupported JSON schema: {schema!r}")


def _regex_escape(text):
    out = []
    for ch in text:
        if ch in "\\.[](){}|*+?^\"-":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


# -- token-level compiled grammar ----------------------------------------

class CompiledGrammar:
    """A DFA lifted to the TOKEN alphabet of one model: ``vocab[t]`` is
    the string token ``t`` emits (EOS's entry is ignored). Per-state
    ``[V]`` float32 additive masks (0.0 allowed / -1e9 banned) are
    cached here, shared by every request and beam on this grammar —
    the per-consumer state is just a DFA state id."""

    def __init__(self, dfa, vocab, eos_id):
        if eos_id is None:
            raise ValueError(
                "grammar-constrained decode needs an eos_id: EOS is how "
                "an accepting state terminates the generation")
        self.dfa = dfa
        self.vocab = [str(s) for s in vocab]
        self.eos_id = int(eos_id)
        self._masks = {}          # state -> float32 [V]
        self._steps = {}          # (state, token) -> state | None

    @classmethod
    def from_regex(cls, pattern, vocab, eos_id):
        alphabet = set()
        for i, s in enumerate(vocab):
            if i != eos_id:
                alphabet |= set(str(s))
        return cls(compile_regex(pattern, alphabet), vocab, eos_id)

    @classmethod
    def from_json_schema(cls, schema, vocab, eos_id):
        return cls.from_regex(json_schema_regex(schema), vocab, eos_id)

    @property
    def start_state(self):
        return self.dfa.start

    def token_step(self, state, token):
        key = (state, int(token))
        if key not in self._steps:
            if int(token) == self.eos_id:
                self._steps[key] = None
            else:
                self._steps[key] = self.dfa.walk(state,
                                                 self.vocab[int(token)])
        return self._steps[key]

    def mask(self, state):
        """Additive ``[V]`` float32 mask for ``state``: 0.0 where the
        token's string walks to a live state (or is EOS in an accepting
        state), ``NEG_INF`` elsewhere. Cached per state."""
        cached = self._masks.get(state)
        if cached is None:
            v = len(self.vocab)
            m = np.full((v,), np.float32(NEG_INF), dtype="float32")
            for t in range(v):
                if t == self.eos_id:
                    if self.dfa.accepting[state]:
                        m[t] = 0.0
                elif self.token_step(state, t) is not None:
                    m[t] = 0.0
            self._masks[state] = m
            cached = m
        return cached


class GrammarConstraint:
    """The per-request (or per-beam) cursor over a CompiledGrammar: one
    DFA state id plus the shared grammar. ``fork()`` is O(1) — beam
    forks clone grammar state for free."""

    __slots__ = ("grammar", "state")

    def __init__(self, grammar, state=None):
        self.grammar = grammar
        self.state = grammar.start_state if state is None else state

    def mask(self):
        return self.grammar.mask(self.state)

    def advance(self, token):
        """Consume an emitted token. EOS is terminal (state freezes);
        an emitted token the mask banned is a contract violation and
        raises — the engine never produces one, because selection runs
        over the masked logits."""
        if int(token) == self.grammar.eos_id:
            if not self.accepting():
                raise ValueError(
                    "EOS emitted in a non-accepting grammar state")
            return self
        nxt = self.grammar.token_step(self.state, token)
        if nxt is None:
            raise ValueError(
                f"token {int(token)} ({self.grammar.vocab[int(token)]!r}) "
                "is not allowed by the grammar here")
        self.state = nxt
        return self

    def accepting(self):
        return self.grammar.dfa.accepting[self.state]

    def fork(self):
        return GrammarConstraint(self.grammar, self.state)
