"""Host-RAM KV block tier: the PR 8 two-tier store pattern applied to
decode KV blocks.

The device arena (pool.py) is the hot tier — a fixed budget of
``num_blocks * block_size`` HBM rows. This module is the warm tier: a
byte-capacity-bounded host store of KV rows, keyed two ways:

* ``blk:<chain_hash>`` — a registered FULL block's rows, written back
  when the pool's LRU eviction recycles it (write-back discipline: a
  registered block is immutable once written, so eviction time is the
  one moment its bytes leave HBM — the pool calls ``put`` while holding
  ``decode.blocks``, hence the declared ``decode.blocks -> decode.tier``
  order). A later prompt walking the same chain re-injects these rows
  instead of recomputing prefill, so prefix-cache reach is bounded by
  host RAM, not HBM.
* ``park:<request_id>:<hyp>`` — a preempted session's private rows
  ``[0:cursor)``, spilled when the scheduler parks it under arena
  exhaustion. Resume pops the entry and re-injects.

Every entry carries a CRC32 over its row bytes (the
``incubate/checkpoint.py`` quarantine idiom): ``get`` re-checksums and a
mismatch QUARANTINES the entry (dropped + counted, never served). That
is safe because every row here is a pure function of its token history
under causal attention — a reader that finds its entry quarantined (or
LRU-evicted) recomputes the rows from tokens, byte-identically.

Capacity is a hard byte budget with LRU eviction; ``put`` refuses only
an entry larger than the WHOLE budget — that is "host tier exhausted",
the one condition that makes arena exhaustion loud again.
"""

import zlib
from collections import OrderedDict

import numpy as np

from paddle_tpu.observability import lockdep

__all__ = ["HostKVTier", "TierEntry"]

# the pool writes back evicted blocks while holding its allocator lock
lockdep.declare_order("decode.blocks", "decode.tier")


def _rows_crc(kv_rows):
    crc = 0
    for k, v in kv_rows:
        crc = zlib.crc32(np.ascontiguousarray(k).tobytes(), crc)
        crc = zlib.crc32(np.ascontiguousarray(v).tobytes(), crc)
    return crc & 0xFFFFFFFF


def _rows_bytes(kv_rows):
    return sum(np.asarray(k).nbytes + np.asarray(v).nbytes
               for k, v in kv_rows)


class TierEntry:
    """One spilled row run: per-layer ``[(k, v), ...]`` numpy arrays of
    shape ``[size_used, hidden]`` plus the token history that produced
    them (the recompute key for CRC walk-back)."""

    __slots__ = ("key", "tokens", "size_used", "kv_rows", "crc", "nbytes")

    def __init__(self, key, tokens, size_used, kv_rows):
        self.key = key
        self.tokens = tuple(int(t) for t in tokens)
        self.size_used = int(size_used)
        self.kv_rows = [(np.ascontiguousarray(k), np.ascontiguousarray(v))
                        for k, v in kv_rows]
        self.crc = _rows_crc(self.kv_rows)
        self.nbytes = _rows_bytes(self.kv_rows)


class HostKVTier:
    """LRU host store of spilled KV rows with CRC-verified reads.

    Thread-safety: one ``decode.tier`` named lock guards the map; the
    pool calls ``put`` under ``decode.blocks`` (declared order above),
    the engine calls ``get``/``pop``/``put`` lock-free on its scheduler
    thread, and ``stats`` may be read from anywhere."""

    def __init__(self, capacity_bytes=64 << 20):
        self.capacity_bytes = int(capacity_bytes)
        self._lock = lockdep.named_lock("decode.tier")
        self._entries = OrderedDict()    # key -> TierEntry, LRU order
        self._bytes = 0
        self.spills = 0          # park-keyed puts
        self.writebacks = 0      # blk-keyed puts (pool eviction write-back)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.corrupt_dropped = 0
        self.rejected = 0        # entry larger than the whole budget

    def put(self, key, kv_rows, size_used, tokens=()):
        """Store (replacing any same-key entry). Returns False — host
        tier exhausted — only when the entry alone exceeds the byte
        budget; otherwise LRU-evicts until it fits."""
        ent = TierEntry(key, tokens, size_used, kv_rows)
        with self._lock:
            if ent.nbytes > self.capacity_bytes:
                self.rejected += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            while self._bytes + ent.nbytes > self.capacity_bytes:
                _, lru = self._entries.popitem(last=False)
                self._bytes -= lru.nbytes
                self.evictions += 1
            self._entries[key] = ent
            self._bytes += ent.nbytes
            if key.startswith("park:"):
                self.spills += 1
            else:
                self.writebacks += 1
            return True

    def _get_locked(self, key, remove):
        ent = self._entries.get(key)
        if ent is None:
            self.misses += 1
            return None
        if _rows_crc(ent.kv_rows) != ent.crc:
            # quarantine: never serve corrupt rows — the reader
            # recomputes from tokens (byte-identical by construction)
            del self._entries[key]
            self._bytes -= ent.nbytes
            self.corrupt_dropped += 1
            self.misses += 1
            return None
        if remove:
            del self._entries[key]
            self._bytes -= ent.nbytes
        else:
            self._entries.move_to_end(key)
        self.hits += 1
        return ent

    def get(self, key):
        """CRC-verified lookup; corrupt entries are quarantined and read
        as a miss (None)."""
        with self._lock:
            return self._get_locked(key, remove=False)

    def pop(self, key):
        """CRC-verified take (the resume path: parked rows are consumed
        exactly once)."""
        with self._lock:
            return self._get_locked(key, remove=True)

    def discard(self, key):
        """Drop without reading (parked session cancelled/expired)."""
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is not None:
                self._bytes -= ent.nbytes

    def __contains__(self, key):
        with self._lock:
            return key in self._entries

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def corrupt_entry(self, key):
        """Chaos/test seam (mirrors ``faults.corrupt_file``): flip one
        byte of the stored rows WITHOUT updating the CRC, so the next
        read must quarantine. Returns True when the entry existed."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                return False
            k, v = ent.kv_rows[0]
            k = np.array(k, copy=True)
            k.view(np.uint8).reshape(-1)[0] ^= 0xFF
            ent.kv_rows[0] = (k, v)
            return True

    def stats(self):
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
                "spills": self.spills,
                "writebacks": self.writebacks,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "corrupt_dropped": self.corrupt_dropped,
                "rejected": self.rejected,
            }
