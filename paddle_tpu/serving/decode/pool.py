"""Paged KV-pool bookkeeping: slot allocator, block allocator, radix
prefix index, and the whole-prompt prefill cache.

The device side of the pool is ONE flat ``[R, H]`` row arena per layer
per K/V inside the decode/inject programs (model.py), where
``R = num_blocks * block_size``; this module is the host side: which
block is free, which slot owns which blocks, and — the storage-dedup
upgrade over PR 10's sha256 prefill cache — a **radix tree over chained
block hashes** so N requests sharing a prompt prefix share PHYSICAL
blocks, not just prefill compute.

Sharing rules (all bit-exactness-preserving by construction — a KV row
for position ``p`` is a pure function of ``tokens[:p+1]`` under causal
attention, so content-equal prefixes have byte-equal rows):

* **Full blocks** are immutable once written and are registered in the
  radix tree keyed by the chain hash of their token history. A later
  prompt that walks the same chain references the same physical rows
  (refcount++) and skips both the inject AND the storage.
* **Partial tail blocks** are shareable only when their host-side rows
  are retained (the prefill cache supplies them); a shared partial is
  frozen — the first writer to APPEND at its free offset diverges from
  its sharers and pays a **copy-on-write**: a fresh private block plus a
  host-row re-inject, never a mutation another slot could observe.
* **Generated-token blocks** are always private (refcount 1, never
  registered): speculative/greedy continuations differ per request, so
  indexing them would only grow the tree.

A retired request's refcount-0 REGISTERED blocks stay cached (LRU) so
the next prompt with the same prefix still shares storage; eviction
returns the LRU cached block to the free list when allocation needs it.

Locks: ``decode.blocks`` guards the allocator, ``decode.radix`` the
tree; the pool calls into the tree while holding its own lock, declared
``decode.blocks -> decode.radix`` for the lockdep witness.
"""

import hashlib
from collections import OrderedDict

import numpy as np

from paddle_tpu.observability import lockdep

__all__ = ["SlotPool", "PrefixCache", "BlockPool", "Block", "prompt_key",
           "block_hashes"]

lockdep.declare_order("decode.blocks", "decode.radix")


def prompt_key(prompt_ids):
    """Content hash of a prompt (the whole-prompt prefill dedup key)."""
    arr = np.ascontiguousarray(np.asarray(prompt_ids, dtype=np.int64))
    return hashlib.sha256(arr.tobytes()).hexdigest()


def _tok_bytes(tokens):
    return np.ascontiguousarray(
        np.asarray(list(tokens), dtype=np.int64)).tobytes()


def block_hashes(tokens, block_size):
    """Chained content hashes of the FULL blocks covering ``tokens``:
    ``h[i] = sha256(h[i-1] || tokens[i*bs:(i+1)*bs])``. The chain makes
    a block hash name its whole history, so equal hashes mean equal
    prefixes — the radix key, and the fleet router's block-affinity key
    (same first block -> same replica -> the replica that already holds
    those physical rows)."""
    bs = int(block_size)
    toks = [int(t) for t in tokens]
    out = []
    h = b"paged-kv-v1"
    for i in range(len(toks) // bs):
        h = hashlib.sha256(h + _tok_bytes(toks[i * bs:(i + 1) * bs])).digest()
        out.append(h.hex())
    return out


class SlotPool:
    """Fixed-capacity slot allocator. Slots are just indices into the
    decode batch's leading axis; state per slot lives with the
    scheduler. Not thread-safe by itself — the scheduler owns it from
    one loop thread."""

    def __init__(self, slots):
        self.slots = int(slots)
        self._free = list(range(self.slots - 1, -1, -1))  # pop() -> slot 0 first
        self._active = set()

    def acquire(self):
        """Lowest free slot index, or None when the batch is full."""
        if not self._free:
            return None
        s = self._free.pop()
        self._active.add(s)
        return s

    def release(self, slot):
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        self._active.discard(slot)
        self._free.append(slot)
        self._free.sort(reverse=True)

    def active(self):
        return sorted(self._active)

    @property
    def free_count(self):
        return len(self._free)

    @property
    def active_count(self):
        return len(self._active)

    def reset(self):
        self._free = list(range(self.slots - 1, -1, -1))
        self._active.clear()


class PrefixCache:
    """Bounded LRU of whole-prompt prefill results keyed by prompt
    content hash (prefill COMPUTE dedup; the BlockPool radix below is
    the storage dedup that rides on top of it).

    Values are host numpy tuples ``(kv_rows, logits_row)`` where
    ``kv_rows`` is the per-layer ``[1, L, H]`` K/V list and
    ``logits_row`` the ``[V]`` logits at the prompt's last position.
    Thread-safe (submissions from many clients race admission)."""

    def __init__(self, capacity=64):
        self.capacity = int(capacity)
        self._map = OrderedDict()
        self._lock = lockdep.named_lock("decode.prefix")
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            val = self._map.get(key)
            if val is None:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return val

    def put(self, key, kv_rows, logits_row):
        if self.capacity <= 0:
            return
        with self._lock:
            self._map[key] = (
                [np.asarray(r) for r in kv_rows], np.asarray(logits_row),
            )
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def __len__(self):
        with self._lock:
            return len(self._map)

    def clear(self):
        with self._lock:
            self._map.clear()


class Block:
    """One fixed-size run of ``block_size`` arena rows. ``row0`` is its
    first physical row; position ``p`` of a sequence whose block list
    holds this block at chunk ``p // bs`` lives at row
    ``row0 + p % bs``. ``host_rows`` (per-layer ``[(k, v), ...]`` numpy
    rows, present only for prefill-sourced blocks) is what makes a
    partial block COW-able: divergence re-injects these bytes into a
    fresh block."""

    __slots__ = ("id", "row0", "size_used", "tokens", "chain_hash",
                 "refcount", "host_rows", "registered", "partial_of")

    def __init__(self, bid, row0):
        self.id = bid
        self.row0 = row0
        self.reset()

    def reset(self):
        self.size_used = 0
        self.tokens = ()
        self.chain_hash = None
        self.refcount = 0
        self.host_rows = None
        self.registered = False
        self.partial_of = None   # parent chain hash for partial entries


class _RadixNode:
    __slots__ = ("children", "block_id", "chain_hash", "partials", "parent",
                 "tokens")

    def __init__(self, chain_hash, parent=None, tokens=()):
        self.children = {}       # tokens-tuple -> _RadixNode (full blocks)
        self.partials = {}       # tokens-tuple -> block id (shared tails)
        self.block_id = None
        self.chain_hash = chain_hash
        self.parent = parent
        self.tokens = tuple(tokens)


class _RadixTree:
    """Radix tree over block token-chunks; each depth-d node names one
    FULL block whose history is the d-chunk chain, carrying the chain
    hash. Partial tails hang off their parent node keyed by the tail
    tokens."""

    def __init__(self):
        self._root = _RadixNode(chain_hash="root")
        self._lock = lockdep.named_lock("decode.radix")
        self._by_block = {}      # block id -> node (or (node, tail-key))

    def lookup_chain(self, tokens, block_size):
        """Longest registered full-block chain covering ``tokens``:
        returns ``(block_ids, last_node, tail_block_id)`` where
        ``tail_block_id`` is a registered shared PARTIAL holding exactly
        the remaining tail tokens (or None)."""
        bs = int(block_size)
        toks = [int(t) for t in tokens]
        with self._lock:
            node, ids = self._root, []
            n_full = len(toks) // bs
            for i in range(n_full):
                chunk = tuple(toks[i * bs:(i + 1) * bs])
                child = node.children.get(chunk)
                if child is None:
                    break
                node = child
                ids.append(node.block_id)
            tail = tuple(toks[len(ids) * bs:])
            tail_bid = node.partials.get(tail) if tail else None
            return ids, node, tail_bid

    def insert_full(self, tokens_chunk, chain_hash, block_id, parent_node):
        with self._lock:
            chunk = tuple(int(t) for t in tokens_chunk)
            child = parent_node.children.get(chunk)
            if child is None:
                child = _RadixNode(chain_hash, parent=parent_node,
                                   tokens=chunk)
                child.block_id = block_id
                parent_node.children[chunk] = child
                self._by_block[block_id] = child
            return child

    def insert_partial(self, tail_tokens, block_id, parent_node):
        with self._lock:
            key = tuple(int(t) for t in tail_tokens)
            if key not in parent_node.partials:
                parent_node.partials[key] = block_id
                self._by_block[block_id] = (parent_node, key)
                return True
            return False

    @property
    def root(self):
        return self._root

    def node_of(self, block_id, default=None):
        with self._lock:
            entry = self._by_block.get(block_id)
            return entry if isinstance(entry, _RadixNode) else default

    def remove(self, block_id):
        with self._lock:
            entry = self._by_block.pop(block_id, None)
            if entry is None:
                return
            if isinstance(entry, tuple):
                node, key = entry
                node.partials.pop(key, None)
                return
            node = entry
            node.block_id = None
            # prune leaf chains with no registered descendants
            while (node.parent is not None and not node.children
                   and not node.partials and node.block_id is None):
                parent = node.parent
                parent.children.pop(node.tokens, None)
                node = parent

    def __len__(self):
        with self._lock:
            return len(self._by_block)


class CowCopy:
    """What a copy-on-write owes the device: re-inject ``host_rows``
    (per-layer ``[(k, v)]`` covering ``size_used`` offsets) into
    ``block`` before any append lands there."""

    __slots__ = ("block", "host_rows", "size_used")

    def __init__(self, block, host_rows, size_used):
        self.block = block
        self.host_rows = host_rows
        self.size_used = size_used


class BlockPool:
    """Block-granular allocator over the flat row arena + the radix
    prefix index. All allocation calls happen on the entry's scheduler
    thread; ``stats()`` may be read from any thread (the lock makes the
    counters coherent)."""

    def __init__(self, num_blocks, block_size):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._blocks = [Block(i, i * self.block_size)
                        for i in range(self.num_blocks)]
        self._free = list(range(self.num_blocks - 1, -1, -1))
        self._cached = OrderedDict()   # block id -> None (LRU of refcount-0)
        self._radix = _RadixTree()
        self._lock = lockdep.named_lock("decode.blocks")
        self._tier = None              # HostKVTier (attach_tier)
        self._tier_read = None         # block -> per-layer [(k, v)] rows
        self.cow_copies = 0
        self.evictions = 0
        self.radix_hits = 0            # shared-block references served
        self.forks = 0                 # beam forks served (refcount++ paths)
        self.tier_writebacks = 0       # evicted blocks spilled to host

    def attach_tier(self, tier, read_rows=None):
        """Adopt a host-RAM tier (tier.py): LRU eviction write-backs a
        registered full block's rows to ``tier`` under ``blk:<chain>``
        before recycling it. ``read_rows(block)`` reads the block's
        device rows (the pool is host bookkeeping only — the engine owns
        the arena scope); called, like the ``tier.put``, while holding
        ``decode.blocks`` (declared ``decode.blocks -> decode.tier``)."""
        self._tier = tier
        self._tier_read = read_rows

    @property
    def rows(self):
        return self.num_blocks * self.block_size

    def block(self, bid):
        return self._blocks[bid]

    # -- allocation --------------------------------------------------------
    def _alloc_locked(self):
        if not self._free:
            # evict the LRU cached (refcount-0, registered) block
            if not self._cached:
                return None
            bid, _ = self._cached.popitem(last=False)
            self._writeback_locked(self._blocks[bid])
            self._radix.remove(bid)
            self._blocks[bid].reset()
            self._free.append(bid)
            self.evictions += 1
        bid = self._free.pop()
        b = self._blocks[bid]
        b.reset()
        b.refcount = 1
        return b

    def _writeback_locked(self, b):
        """Spill an about-to-be-evicted FULL registered block's rows to
        the host tier (write-back discipline: registered blocks are
        immutable, so this is the one moment their bytes leave the
        arena). Partial tails already retain ``host_rows`` host-side and
        are cheap to recompute; only chain-hashed full blocks spill."""
        if self._tier is None or b.chain_hash is None:
            return
        rows = b.host_rows
        if rows is None and self._tier_read is not None:
            rows = self._tier_read(b)
        if rows is None:
            return
        if self._tier.put("blk:" + b.chain_hash, rows, b.size_used,
                          tokens=b.tokens):
            self.tier_writebacks += 1

    def acquire_rows(self, n_rows):
        """Fresh PRIVATE blocks covering ``n_rows`` positions with
        ``size_used`` preset (the preemption-resume path: the caller
        re-injects spilled rows, so these blocks hold real content the
        moment they are handed out). Returns None when the pool cannot
        cover the run."""
        bs = self.block_size
        n = (int(n_rows) + bs - 1) // bs
        with self._lock:
            if n > len(self._free) + len(self._cached):
                return None
            out = []
            for i in range(n):
                b = self._alloc_locked()
                b.size_used = min(bs, int(n_rows) - i * bs)
                out.append(b)
            return out

    def acquire_for_prompt(self, tokens):
        """Map a prompt onto blocks: longest shared full-block chain
        from the radix tree (+ a shared partial tail when one matches),
        fresh private blocks for the rest. Returns
        ``(blocks, shared_len)`` — ``shared_len`` positions already hold
        the right rows on device and must NOT be re-injected — or
        ``(None, 0)`` when the pool cannot cover the prompt."""
        toks = [int(t) for t in tokens]
        bs = self.block_size
        ids, node, tail_bid = self._radix.lookup_chain(toks, bs)
        with self._lock:
            shared = []
            for bid in ids:
                b = self._blocks[bid]
                shared.append(b)
            tail_block = None
            if tail_bid is not None:
                tail_block = self._blocks[tail_bid]
            shared_len = len(shared) * bs
            if tail_block is not None:
                shared_len += tail_block.size_used
            n_new = (len(toks) - shared_len + bs - 1) // bs
            sharing = shared + ([tail_block] if tail_block is not None
                                else [])
            # capacity check must not count cached blocks this very call
            # is about to re-reference as shared — they stop being
            # evictable the moment the commit refs them
            shared_ids = {b.id for b in sharing}
            evictable = sum(1 for bid in self._cached
                            if bid not in shared_ids)
            if n_new > (len(self._free) + evictable):
                return None, 0
            # commit: reference shared, allocate private
            for b in sharing:
                if b.refcount == 0:
                    self._cached.pop(b.id, None)
                b.refcount += 1
                self.radix_hits += 1
            blocks = list(sharing)
            for i in range(n_new):
                nb = self._alloc_locked()
                start = shared_len + i * bs
                nb.tokens = tuple(toks[start:start + bs])
                nb.size_used = min(bs, len(toks) - start)
                blocks.append(nb)
            return blocks, shared_len

    def register_prompt_blocks(self, blocks, tokens, host_rows=None):
        """Index this prompt's freshly written blocks in the radix tree
        so later prompts share them. Full blocks always register;
        the partial tail registers only when ``host_rows`` (a callable
        ``(start, stop) -> per-layer [(k, v)]``) can retain its bytes
        for copy-on-write."""
        toks = [int(t) for t in tokens]
        bs = self.block_size
        hashes = block_hashes(toks, bs)
        node = self._radix.root
        with self._lock:
            for i, b in enumerate(blocks):
                if (i + 1) * bs <= len(toks):
                    chunk = tuple(toks[i * bs:(i + 1) * bs])
                    if b.registered:
                        node = self._radix.node_of(b.id, node)
                        continue
                    b.chain_hash = hashes[i]
                    b.registered = True
                    node = self._radix.insert_full(chunk, hashes[i], b.id,
                                                   node)
                else:
                    tail = tuple(toks[i * bs:])
                    if not tail or b.registered or host_rows is None:
                        break
                    b.host_rows = host_rows(i * bs, len(toks))
                    if self._radix.insert_partial(tail, b.id, node):
                        b.registered = True
                        b.partial_of = node.chain_hash
                    break

    def ensure_appendable(self, blocks, cursor):
        """Make position ``cursor`` writable for ONE owner. Returns
        ``(blocks, new_block, cow)``:

        * cursor opens a new chunk -> allocate a fresh private block
          (``new_block`` set);
        * cursor lands in a SHARED partial tail (refcount > 1) ->
          copy-on-write: fresh block + a ``CowCopy`` the caller must
          re-inject before building row feeds;
        * cursor lands in an exclusively-owned registered partial ->
          unregister it (its content is about to stop matching its key)
          and append in place.

        Returns ``(None, None, None)`` when the pool is exhausted."""
        bs = self.block_size
        idx = cursor // bs
        if idx >= len(blocks):
            with self._lock:
                nb = self._alloc_locked()
            if nb is None:
                return None, None, None
            return blocks + [nb], nb, None
        b = blocks[idx]
        with self._lock:
            if b.refcount > 1:
                if b.host_rows is None:
                    raise RuntimeError(
                        f"shared block {b.id} has no host rows to COW")
                nb = self._alloc_locked()
                if nb is None:
                    return None, None, None
                nb.size_used = b.size_used
                nb.tokens = b.tokens
                cow = CowCopy(nb, b.host_rows, b.size_used)
                b.refcount -= 1
                self.cow_copies += 1
                out = list(blocks)
                out[idx] = nb
                return out, nb, cow
            if b.registered:
                self._radix.remove(b.id)
                b.registered = False
                b.partial_of = None
        return blocks, None, None

    def note_append(self, block):
        """One row landed in ``block`` (host bookkeeping only)."""
        with self._lock:
            block.size_used = min(block.size_used + 1, self.block_size)

    def fork_blocks(self, blocks, written):
        """Beam fork: a second owner for the first ``written`` positions
        of ``blocks``. Full covered blocks are SHARED (refcount++ — they
        are immutable history for both beams; appends can never land in
        them because the cursor is past their last offset), and a
        partial tail gets a fresh PRIVATE block the caller must fill by
        copying the parent's ``written % block_size`` device rows (the
        engine reads them out of the arena scope and re-injects).

        Returns ``(child_blocks, new_tail, src_tail)`` — ``new_tail`` /
        ``src_tail`` are None when ``written`` is block-aligned — or
        ``(None, None, None)`` when the pool is exhausted."""
        bs = self.block_size
        full = int(written) // bs
        tail_used = int(written) % bs
        with self._lock:
            child = list(blocks[:full])
            nb = None
            src = None
            if tail_used:
                src = blocks[full]
                nb = self._alloc_locked()
                if nb is None:
                    return None, None, None
                nb.size_used = tail_used
                nb.tokens = src.tokens
            for b in child:
                b.refcount += 1
            self.forks += 1
            if nb is not None:
                child.append(nb)
            return child, nb, src

    def release(self, blocks):
        """Drop one owner's references. Registered refcount-0 blocks
        stay cached (LRU) for future prefix hits; private ones free."""
        with self._lock:
            for b in blocks:
                b.refcount -= 1
                if b.refcount > 0:
                    continue
                if b.registered:
                    self._cached[b.id] = None
                    self._cached.move_to_end(b.id)
                else:
                    b.reset()
                    self._free.append(b.id)

    def reset(self):
        """Arena wiped (relaunch path): every block returns to the free
        list and the radix index empties — the device rows are zeros."""
        with self._lock:
            for b in self._blocks:
                if b.registered:
                    self._radix.remove(b.id)
                b.reset()
            self._free = list(range(self.num_blocks - 1, -1, -1))
            self._cached.clear()

    def check_conservation(self):
        """The row-conservation invariant, assertable after every beam
        fork/prune: each block is in EXACTLY ONE of {free list, LRU
        cache, live (refcount > 0)}, the three counts sum to the pool
        size, and no refcount is negative. Raises AssertionError naming
        the violation; returns the three counts when clean."""
        with self._lock:
            free = set(self._free)
            cached = set(self._cached)
            live = {b.id for b in self._blocks if b.refcount > 0}
            neg = [b.id for b in self._blocks if b.refcount < 0]
            assert not neg, f"negative refcount on blocks {neg}"
            assert not (free & cached), (
                f"blocks both free and cached: {sorted(free & cached)}")
            assert not (free & live), (
                f"blocks both free and live: {sorted(free & live)}")
            assert not (cached & live), (
                f"blocks both cached and live: {sorted(cached & live)}")
            total = len(free) + len(cached) + len(live)
            assert total == self.num_blocks, (
                f"row conservation broken: {len(free)} free + "
                f"{len(cached)} cached + {len(live)} live != "
                f"{self.num_blocks} total")
            return {"blocks_free": len(free), "blocks_cached": len(cached),
                    "blocks_live": len(live)}

    # -- observability -----------------------------------------------------
    def stats(self):
        with self._lock:
            live = [b for b in self._blocks if b.refcount > 0]
            physical = sum(b.size_used for b in live)
            logical = sum(b.refcount * b.size_used for b in live)
            cached_rows = sum(self._blocks[bid].size_used
                              for bid in self._cached)
            return {
                "block_size": self.block_size,
                "blocks_total": self.num_blocks,
                "blocks_free": len(self._free),
                "blocks_cached": len(self._cached),
                "blocks_live": len(live),
                "rows_total": self.rows,
                "rows_live": physical,
                "rows_cached": cached_rows,
                "rows_logical": logical,
                "occupancy": physical / float(max(self.rows, 1)),
                "dedup_ratio": logical / float(max(physical, 1)),
                "cow_copies": self.cow_copies,
                "forks": self.forks,
                "evictions": self.evictions,
                "radix_hits": self.radix_hits,
                "radix_entries": len(self._radix),
                "tier_writebacks": self.tier_writebacks,
            }
