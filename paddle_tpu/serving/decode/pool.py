"""Slotted KV-cache pool bookkeeping: slot allocator + prefix-reuse cache.

The device side of the pool is the pre-allocated ``[S, L, H]`` arenas
inside the decode/inject programs (model.py); this module is the host
side: which slot is free, where each live slot's write cursor is, and a
content-hash cache of prefill results so two requests with the same
prompt pay for ONE prefill forward.

The prefix cache stores host copies of the prefill program's outputs
(per-layer K/V rows + the first-token logits row). Reuse is exact by
construction: the inject program writes the SAME bytes into the arena
whether they came from a fresh prefill or the cache, so a prefix hit
cannot perturb generation — asserted by the dedup test in
tests/test_decode.py.
"""

import hashlib
from collections import OrderedDict

import numpy as np

from paddle_tpu.observability import lockdep

__all__ = ["SlotPool", "PrefixCache", "prompt_key"]


def prompt_key(prompt_ids):
    """Content hash of a prompt (the shared-prefix dedup key)."""
    arr = np.ascontiguousarray(np.asarray(prompt_ids, dtype=np.int64))
    return hashlib.sha256(arr.tobytes()).hexdigest()


class SlotPool:
    """Fixed-capacity slot allocator. Slots are just indices into the
    arena's leading axis; state per slot lives with the scheduler. Not
    thread-safe by itself — the scheduler owns it from one loop thread."""

    def __init__(self, slots):
        self.slots = int(slots)
        self._free = list(range(self.slots - 1, -1, -1))  # pop() -> slot 0 first
        self._active = set()

    def acquire(self):
        """Lowest free slot index, or None when the batch is full."""
        if not self._free:
            return None
        s = self._free.pop()
        self._active.add(s)
        return s

    def release(self, slot):
        if slot not in self._active:
            raise ValueError(f"slot {slot} is not active")
        self._active.discard(slot)
        self._free.append(slot)
        self._free.sort(reverse=True)

    def active(self):
        return sorted(self._active)

    @property
    def free_count(self):
        return len(self._free)

    @property
    def active_count(self):
        return len(self._active)

    def reset(self):
        self._free = list(range(self.slots - 1, -1, -1))
        self._active.clear()


class PrefixCache:
    """Bounded LRU of prefill results keyed by prompt content hash.

    Values are host numpy tuples ``(kv_rows, logits_row)`` where
    ``kv_rows`` is the per-layer ``[1, L, H]`` K/V list and
    ``logits_row`` the ``[V]`` logits at the prompt's last position.
    Thread-safe (submissions from many clients race admission)."""

    def __init__(self, capacity=64):
        self.capacity = int(capacity)
        self._map = OrderedDict()
        self._lock = lockdep.named_lock("decode.prefix")
        self.hits = 0
        self.misses = 0

    def get(self, key):
        with self._lock:
            val = self._map.get(key)
            if val is None:
                self.misses += 1
                return None
            self._map.move_to_end(key)
            self.hits += 1
            return val

    def put(self, key, kv_rows, logits_row):
        if self.capacity <= 0:
            return
        with self._lock:
            self._map[key] = (
                [np.asarray(r) for r in kv_rows], np.asarray(logits_row),
            )
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def __len__(self):
        with self._lock:
            return len(self._map)

    def clear(self):
        with self._lock:
            self._map.clear()
