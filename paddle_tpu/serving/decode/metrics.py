"""Decode-engine metrics: the serving counter set + iteration-level series.

Extends ServingMetrics (same engine-label discipline, same registry /
profiler mirroring, same per-tenant counters) with the quantities that
only exist under iteration-level scheduling: decode steps, active
slot-steps (the occupancy numerator), generated tokens, prefill runs vs
prefix-cache hits, retirements, and step/prefill latency histograms.
``occupancy()`` is the headline number: mean fraction of the S-slot batch
doing real work per iteration — what continuous batching buys over
request-at-a-time bucketing.
"""

from paddle_tpu.serving.metrics import ServingMetrics

__all__ = ["DecodeMetrics"]


class DecodeMetrics(ServingMetrics):
    COUNTERS = ServingMetrics.COUNTERS + (
        # iteration-level scheduler ("generated_tokens" counts tokens a
        # decode STEP produced; each admission's prefill-derived first
        # token is "prefill_tokens" — delivered total is their sum)
        "decode_steps", "active_slot_steps", "generated_tokens",
        "prefill_tokens", "retired", "step_failures",
        # admission / KV pool (prefix hit/miss totals live on
        # PrefixCache itself — stats() reports them from that one
        # source; only the per-tenant prefix_hits series is a counter)
        "prefills", "rejected_quota", "blocks_exhausted",
        # chunked prefill (one budgeted chunk per engine iteration)
        "chunk_runs", "chunk_tokens",
        # speculative decoding: target verify forwards vs emitted tokens
        # is the headline ratio; accepted/proposed is the acceptance rate
        "spec_target_steps", "spec_draft_steps", "spec_proposed_tokens",
        "spec_accepted_tokens", "spec_emitted_tokens",
        # draft-KV speculative slots (r17): O(1)-per-token proposals from
        # the draft entry's own paged arena; fallbacks count reversion to
        # whole-prompt replay proposals (resource exhaustion / poisoning)
        "spec_draft_kv_steps", "spec_draft_kv_prefills",
        "spec_draft_kv_fallbacks",
        # generation modes (r17): committed-stream sampling, grammar
        # mask steps, and beam lifecycle events
        "sampled_tokens", "grammar_steps", "beam_requests", "beam_forks",
        "beam_prunes", "beam_finished",
        # circuit breaker relaunch (AOT-warmed replacement replicas)
        "relaunches",
        # graceful degradation (r18): arena exhaustion now splits into
        # park-with-retry (session spilled to the host tier, resumed
        # byte-identically later) vs loud failure (host tier exhausted
        # or the request can never fit); "blocks_exhausted" stays the
        # umbrella total of both outcomes
        "blocks_parked_total", "blocks_failed_total",
        "sessions_parked", "sessions_resumed", "resume_replays",
        "tier_hits", "admissions_deferred",
        # brownout ladder (serving/brownout.py): witnessed transitions
        # and L4 sheds
        "brownout_transitions", "brownout_shed",
    )

    def __init__(self, engine_label=None, registry=None):
        super().__init__(engine_label=engine_label, registry=registry)
        labels = {"engine": self.engine_label}
        self._step = self._registry.histogram(
            "serving_decode_step_seconds",
            "one decode iteration (all slots)", labels=labels,
        )
        self._prefill = self._registry.histogram(
            "serving_prefill_seconds",
            "prompt prefill forward latency", labels=labels,
        )
        self._chunk = self._registry.histogram(
            "serving_chunk_prefill_seconds",
            "one budgeted chunk-prefill forward", labels=labels,
        )
        for h in (self._step, self._prefill, self._chunk):
            h.reset()

    def observe_step(self, active_slots, new_tokens, seconds):
        self.incr("decode_steps")
        self.incr("active_slot_steps", active_slots)
        self.incr("generated_tokens", new_tokens)
        self._step.observe(seconds)

    def observe_prefill(self, seconds):
        self.incr("prefills")
        self._prefill.observe(seconds)

    def observe_chunk(self, tokens, seconds):
        self.incr("chunk_runs")
        self.incr("chunk_tokens", tokens)
        self._chunk.observe(seconds)

    def occupancy(self, slots):
        steps = self.count("decode_steps")
        if steps <= 0:
            return 0.0
        return self.count("active_slot_steps") / float(steps * slots)

    def tokens_per_step(self):
        steps = self.count("decode_steps")
        if steps <= 0:
            return 0.0
        return self.count("generated_tokens") / float(steps)

    def snapshot(self, extra=None):
        out = super().snapshot(extra=None)
        out.update(self._step.snapshot("decode_step"))
        out.update(self._prefill.snapshot("prefill"))
        out.update(self._chunk.snapshot("chunk_prefill"))
        if extra:
            out.update(extra)
        return out
