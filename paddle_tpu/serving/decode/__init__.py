"""Continuous-batching decode subsystem (online serving v4).

The PR-2 engine (serving/engine.py) schedules at REQUEST granularity:
whole requests coalesce into fixed (batch, seq) buckets and a finished
sequence holds its rows until the slowest batchmate drains. This package
schedules at ITERATION granularity (Orca, OSDI'22) over a **paged KV
arena** (vLLM's PagedAttention block tables, SOSP'23): a decode batch of
S slots is stepped once per model iteration through ONE compiled
``[S, 1]`` executable, finished sequences retire between iterations,
admitted prompts prefill into free slots mid-flight, KV storage is
allocated block-by-block so memory scales with USED tokens, and prompts
sharing a prefix share PHYSICAL blocks through a radix tree over chained
block hashes (copy-on-write at divergence). Long prompts stream through
a budgeted chunk-prefill program interleaved with decode iterations, and
a draft-model **speculative decoding** path (Leviathan et al.) emits
multiple greedy-exact tokens per target forward.

r17 adds the **generation-modes layer** (``generate/``): committed
threefry **sampling** (temperature / top-k / top-p, replay bit-exact
under any admission order), **beam search** as copy-on-write forks over
the radix block arena (each hypothesis is a live slot; fork = refcount++
plus a private tail block), **draft-KV speculative slots** (proposals
decode O(1)/token from the draft entry's own paged arena instead of
replaying the prompt), and **grammar-constrained decode** (regex / JSON
schema compiled host-side to per-step fixed-shape logits masks fed as
data through the donated ``DEC_MASK`` input — zero retraces).

Modules:

* `model`  — `DecodeModel`: the fixed-shape paged-program contract
  (decode step / prefill / inject / optional chunk prefill) +
  `build_decoder_model`, the canonical cached-attention decoder builder.
* `pool`   — host-side slot allocator, block allocator + radix prefix
  index (storage dedup), and the content-hash prefill cache (compute
  dedup).
* `engine` — `GenerationEngine`: multi-tenant model registry, weighted-
  fair admission over the queue's priority lanes, the per-entry
  scheduler loop (decode steps, chunked prefill, speculative verify
  cycles), circuit-breaker relaunch, and AOT warm start through the
  compile cache.
* `metrics`— `DecodeMetrics`: the serving counter set + occupancy /
  tokens-per-step / block-pool / speculative-acceptance series.
* `generate` — the decode-policy layer: `SamplingParams`, `BeamParams`,
  `CompiledGrammar` / `GrammarConstraint`, the offline beam reference.
"""

from paddle_tpu.serving.decode.engine import (
    GenerationEngine,
    GenerationRequest,
)
from paddle_tpu.serving.decode.generate import (
    BeamParams,
    CompiledGrammar,
    GrammarConstraint,
    SamplingParams,
)
from paddle_tpu.serving.decode.metrics import DecodeMetrics
from paddle_tpu.serving.decode.model import DecodeModel, build_decoder_model
from paddle_tpu.serving.decode.pool import (
    BlockPool,
    PrefixCache,
    SlotPool,
    block_hashes,
    prompt_key,
)

__all__ = [
    "BeamParams",
    "BlockPool",
    "CompiledGrammar",
    "DecodeMetrics",
    "DecodeModel",
    "GenerationEngine",
    "GenerationRequest",
    "GrammarConstraint",
    "PrefixCache",
    "SamplingParams",
    "SlotPool",
    "block_hashes",
    "build_decoder_model",
    "prompt_key",
]
