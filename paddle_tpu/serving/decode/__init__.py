"""Continuous-batching decode subsystem (online serving v2).

The PR-2 engine (serving/engine.py) schedules at REQUEST granularity:
whole requests coalesce into fixed (batch, seq) buckets and a finished
sequence holds its rows until the slowest batchmate drains. This package
schedules at ITERATION granularity (Orca, OSDI'22) over a slotted KV
arena (the fixed-shape analog of vLLM's paged KV, SOSP'23): a decode
batch of S slots is stepped once per model iteration through ONE
compiled ``[S, 1]`` executable, finished sequences retire between
iterations, and admitted prompts prefill into free slots mid-flight.

Modules:

* `model`  — `DecodeModel`: the three-program (decode step / prefill /
  inject) fixed-shape contract + `build_decoder_model`, the canonical
  cached-attention decoder builder.
* `pool`   — host-side slot allocator + content-hash prefix cache over
  prefill results (shared-prefix dedup).
* `engine` — `GenerationEngine`: multi-tenant model registry, weighted-
  fair admission over the queue's priority lanes, the per-entry
  scheduler loop, circuit-breaker relaunch, and AOT warm start through
  the compile cache.
* `metrics`— `DecodeMetrics`: the serving counter set + occupancy /
  tokens-per-step / step-latency series.
"""

from paddle_tpu.serving.decode.engine import (
    GenerationEngine,
    GenerationRequest,
)
from paddle_tpu.serving.decode.metrics import DecodeMetrics
from paddle_tpu.serving.decode.model import DecodeModel, build_decoder_model
from paddle_tpu.serving.decode.pool import PrefixCache, SlotPool, prompt_key

__all__ = [
    "DecodeMetrics",
    "DecodeModel",
    "GenerationEngine",
    "GenerationRequest",
    "PrefixCache",
    "SlotPool",
    "build_decoder_model",
    "prompt_key",
]
