"""DecodeModel: the fixed-shape program contract of the paged decode engine.

A generation model is served through THREE (optionally FOUR) fixed-shape
programs that share one scope (weights by name) and one **paged KV
arena**: per layer, one flat persistable ``[R, H]`` row matrix per K and
per V, where ``R = num_blocks * block_size``. Block tables live on the
host (serving/decode/pool.py); programs see only **row-index feeds**, so
memory scales with *used* tokens while every compiled shape stays
static:

* **decode step** — the per-iteration hot path. ONE static shape: token
  ``[S, 1]`` + position ``[S, 1]`` + attention bias ``[S, 1, L]`` + a
  gather row map ``[S * L]`` (position ``p`` of slot ``s`` reads arena
  row ``rows[s * L + p]``) + a scatter row ``[S]`` naming where each
  slot's new K/V row lands (``R`` = "write nowhere", dropped — retired
  slots are bit-invisible, admitted slots join mid-flight, and the
  compiled executable never sees the batch change). Arenas are DONATED
  through core/lowering.py: the scatter is an in-place device update.
* **prefill** — whole-prompt forward at ``[1, L]`` with a causal
  additive bias, fetching per-layer K/V rows ``[1, L, H]`` and logits
  ``[1, L, V]``. Stateless (donation off): its outputs are
  host-cacheable, which is what feeds both the prefill cache and the
  copy-on-write bytes of shared partial blocks.
* **inject** — scatters up to ``L`` prefill K/V rows into arbitrary
  arena rows by a row map ``[L]`` (rows >= ``R`` dropped). Shared-prefix
  admissions inject ONLY their non-shared suffix — shared blocks
  already hold byte-identical rows.
* **chunk prefill** (built when ``chunk_tokens`` is set) — ``[1, C]``
  prompt chunk against the paged arena: scatters the chunk's own K/V
  rows, gathers the full ``[L]`` context view back, and attends under a
  host-fed bias that opens exactly the causal prefix. Long prompts
  stream through it one budgeted chunk per engine iteration instead of
  stalling the decode batch.

All shapes are static, so a warmed engine holds exactly three (four
with chunking) executables and can never retrace. Every parameter,
feed, and arena var name is derived from the ``(name, version)`` prefix
— content-identical rebuilds (circuit-breaker relaunch, a cold replica)
re-derive identical programs and hit the compile cache instead of
recompiling.

Exactness: gather/scatter move rows byte-for-byte and the additive
``-1e9`` bias zeroes masked positions exactly, so paged decode is
bit-identical to the dense slotted design for any block size — the
degenerate geometry ``block_size=max_len, num_blocks=slots`` IS the
PR 10 slotted arena.
"""

import numpy as np

__all__ = ["DecodeModel", "build_decoder_model"]

# additive-mask value: exp(-1e9) underflows to exactly 0.0 (the repo-wide
# padding contract), so masked cache positions are bit-invisible
NEG_INF = -1e9


class DecodeModel:
    """The paged programs + their naming contract and geometry.

    ``state_names`` lists per-layer ``(k_arena, v_arena)`` var names
    (each ``[R, H]``); ``prefill_kv_fetches`` the matching per-layer
    ``(k_rows, v_rows)`` fetch names of the prefill program. ``builder``
    (optional) is a zero-arg callable that re-creates a content-identical
    DecodeModel — the circuit breaker's relaunch path uses it to rebuild
    a replica that warms entirely from the compile cache."""

    # feed-name contract (fixed; the engine builds these arrays)
    DEC_TOKEN = "dec_token"
    DEC_POSITION = "dec_position"
    DEC_BIAS = "dec_bias"
    DEC_ROWS = "dec_rows"
    DEC_WRITE_ROWS = "dec_write_rows"
    DEC_MASK = "dec_mask"
    PRE_TOKENS = "pre_tokens"
    PRE_POSITIONS = "pre_positions"
    PRE_BIAS = "pre_bias"
    INJ_ROWS = "inj_rows"
    CHU_TOKENS = "chu_tokens"
    CHU_POSITIONS = "chu_positions"
    CHU_BIAS = "chu_bias"
    CHU_ROWS = "chu_rows"
    CHU_WRITE_ROWS = "chu_write_rows"

    def __init__(self, *, decode_program, prefill_program, inject_program,
                 startup_program, slots, max_len, vocab_size, hidden,
                 state_names, logits_fetch, prefill_logits_fetch,
                 prefill_kv_fetches, inject_kv_feeds, block_size,
                 num_blocks, chunk_program=None, chunk_tokens=None,
                 chunk_logits_fetch=None, eos_id=None, name="model",
                 version="1", builder=None, logits_mask=False):
        self.decode_program = decode_program
        self.prefill_program = prefill_program
        self.inject_program = inject_program
        self.chunk_program = chunk_program
        self.startup_program = startup_program
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.vocab_size = int(vocab_size)
        self.hidden = int(hidden)
        self.block_size = int(block_size)
        self.num_blocks = int(num_blocks)
        self.chunk_tokens = int(chunk_tokens) if chunk_tokens else None
        self.state_names = list(state_names)
        self.logits_fetch = logits_fetch
        self.prefill_logits_fetch = prefill_logits_fetch
        self.chunk_logits_fetch = chunk_logits_fetch
        self.prefill_kv_fetches = list(prefill_kv_fetches)
        self.inject_kv_feeds = list(inject_kv_feeds)
        self.eos_id = eos_id
        self.name = str(name)
        self.version = str(version)
        self.builder = builder
        self.logits_mask = bool(logits_mask)

    @property
    def key(self):
        return (self.name, self.version)

    @property
    def label(self):
        return f"{self.name}@{self.version}"

    @property
    def rows(self):
        """Physical arena rows: the paged pool's capacity in tokens."""
        return self.num_blocks * self.block_size

    def arena_bytes(self):
        """Exact bytes of the paged KV pool: 2 arenas x layers x
        ``[R, H]`` float32 — what `analysis/memory.py` sees as
        persistent state and what the HBM budget gate reasons about.
        The slotted design's ``S * max_len`` rows become
        ``num_blocks * block_size``, sized to USED tokens."""
        per = self.rows * self.hidden * 4
        return per * 2 * len(self.state_names)

    def slotted_equivalent_bytes(self):
        """What the PR 10 dense design would reserve for the same
        ``(slots, max_len)`` grid — the paged-vs-slotted comparison
        baseline in DECODE_EVIDENCE."""
        per = self.slots * self.max_len * self.hidden * 4
        return per * 2 * len(self.state_names)

    # -- feed signatures (ordered like each program's feed list) ---------
    def decode_feed_sig(self):
        s, l = self.slots, self.max_len
        sig = [
            (self.DEC_TOKEN, (s, 1), "int64"),
            (self.DEC_POSITION, (s, 1), "int64"),
            (self.DEC_BIAS, (s, 1, l), "float32"),
            (self.DEC_ROWS, (s * l,), "int64"),
            (self.DEC_WRITE_ROWS, (s,), "int64"),
        ]
        if self.logits_mask:
            # grammar-constrained decode: per-step [S, 1, V] additive
            # logits mask, fed as DATA (zeros when no grammar is active
            # — IEEE x + 0.0 == x keeps unconstrained slots bit-exact)
            sig.append((self.DEC_MASK, (s, 1, self.vocab_size), "float32"))
        return tuple(sig)

    def prefill_feed_sig(self):
        l = self.max_len
        return (
            (self.PRE_TOKENS, (1, l), "int64"),
            (self.PRE_POSITIONS, (1, l), "int64"),
            (self.PRE_BIAS, (1, l, l), "float32"),
        )

    def inject_feed_sig(self):
        l, h = self.max_len, self.hidden
        sig = [(self.INJ_ROWS, (l,), "int64")]
        for kn, vn in self.inject_kv_feeds:
            sig.append((kn, (1, l, h), "float32"))
            sig.append((vn, (1, l, h), "float32"))
        return tuple(sig)

    def chunk_feed_sig(self):
        c, l = self.chunk_tokens, self.max_len
        return (
            (self.CHU_TOKENS, (1, c), "int64"),
            (self.CHU_POSITIONS, (1, c), "int64"),
            (self.CHU_BIAS, (1, c, l), "float32"),
            (self.CHU_ROWS, (l,), "int64"),
            (self.CHU_WRITE_ROWS, (c,), "int64"),
        )


def _state_var(main_program, startup_program, name, shape):
    """A persistable float32 state var declared in ``main_program`` and
    zero-initialized ONCE in the shared startup (create_global_var would
    append a duplicate fill per program that declares the arena)."""
    mblock = main_program.global_block()
    var = mblock.vars.get(name)
    if var is None:
        var = mblock.create_var(name=name, shape=list(shape),
                                dtype="float32", persistable=True)
        var.stop_gradient = True
    sblock = startup_program.global_block()
    if name not in sblock.vars:
        sblock.create_var(name=name, shape=list(shape), dtype="float32",
                          persistable=True)
        sblock.append_op(
            "fill_constant", {}, {"Out": [name]},
            {"shape": list(shape), "dtype": "float32", "value": 0.0},
        )
    return var


def build_decoder_model(vocab_size, hidden=16, num_layers=2, ffn_dim=None,
                        slots=4, max_len=32, eos_id=None, name="decoder",
                        version="1", block_size=None, num_blocks=None,
                        chunk_tokens=None, fused_attention=True,
                        logits_mask=False):
    """Build the canonical cached-attention decoder as a paged
    DecodeModel.

    Residual transformer decoder: token+position embeddings, per layer
    (q/k/v projection -> paged cached attention -> output projection ->
    residual -> relu FFN -> residual), logits head. Offline/prefill and
    decode paths share every weight by explicit name, which is both the
    bit-exactness contract (one set of parameters, two access patterns)
    and the relaunch contract (rebuilding produces byte-identical
    programs, so the compile cache, not XLA, pays for the restart).

    ``block_size`` defaults to ``min(8, max_len)``; ``num_blocks``
    defaults to FULL capacity (``slots * ceil(max_len / block_size)``),
    so by default nothing can run out of blocks — size it DOWN (with
    the analysis/memory.py gate) to get the paged memory win.
    ``chunk_tokens`` >= 2 additionally builds the chunk-prefill program.

    ``logits_mask`` (default False — opt-in so pre-r17 program
    structures and their committed evidence stay byte-reproducible)
    adds a fixed-shape ``[S, 1, V]`` additive mask feed applied to the
    decode step's logits (``layers.logits_mask_add``): the
    grammar-constrained decode contract. Per-step masks enter as data —
    the compiled shape never changes, so constrained decode cannot
    retrace; an all-zeros mask is a bit-exact no-op for every
    unconstrained slot.

    ``fused_attention`` (default True) routes the decode step's
    attention through ONE ``paged_attention`` op — the row-index feeds
    enter the op directly, so the kernel registry
    (paddle_tpu/kernels/) can serve it with a fused Pallas kernel that
    never materializes the dense ``[S, L, H]`` gather view in HBM. The
    op's reference lowering is the exact gather+attention composite, so
    tokens are BIT-identical to ``fused_attention=False`` (the pre-r15
    op sequence, kept for the DECODE_EVIDENCE_r13 static recompute)
    with kernels on or off.
    """
    import paddle_tpu as fluid
    from paddle_tpu.core.ir import Program, program_guard
    from paddle_tpu.utils import unique_name

    V, H, S, L = int(vocab_size), int(hidden), int(slots), int(max_len)
    NL = int(num_layers)
    FFN = int(ffn_dim) if ffn_dim else 4 * H
    if L < 2:
        raise ValueError(f"max_len {L} leaves no room to generate")
    BS = int(block_size) if block_size else min(8, L)
    per_slot = -(-L // BS)                      # ceil: blocks per full slot
    NB = int(num_blocks) if num_blocks else S * per_slot
    R = NB * BS
    C = int(chunk_tokens) if chunk_tokens else None
    if C is not None and not (2 <= C <= L):
        # C == 1 would route the chunk's projections through the GEMV
        # path, whose summation order differs from the prefill GEMM —
        # the bit-exactness contract needs >= 2 rows per matmul
        raise ValueError(f"chunk_tokens must be in [2, {L}], got {C}")
    prefix = f"{name}_v{version}"

    def attr(suffix):
        return fluid.ParamAttr(name=f"{prefix}.{suffix}")

    def proj(h, size, suffix, act=None):
        return fluid.layers.fc(
            h, size, num_flatten_dims=2, act=act,
            param_attr=attr(suffix + ".w"), bias_attr=attr(suffix + ".b"),
        )

    def embed(toks, pos):
        te = fluid.layers.embedding(toks, size=(V, H),
                                    param_attr=attr("tok_emb"))
        pe = fluid.layers.embedding(pos, size=(L, H),
                                    param_attr=attr("pos_emb"))
        return fluid.layers.elementwise_add(te, pe)

    def ffn_block(h, i):
        ff = proj(h, FFN, f"l{i}.ffn1", act="relu")
        return fluid.layers.elementwise_add(h, proj(ff, H, f"l{i}.ffn2"))

    sm_scale = 1.0 / float(np.sqrt(H))
    state_names = [(f"{prefix}.kcache{i}", f"{prefix}.vcache{i}")
                   for i in range(NL)]
    startup = Program()

    # -- prefill: whole-prompt causal forward at [1, L] ------------------
    prefill = Program()
    kv_fetches = []
    # unique_name.guard(): auto-named temp vars restart per program, so a
    # rebuild ANYWHERE in a process (the breaker's relaunch, a second
    # engine) is textually identical and hits the compile cache instead
    # of retracing
    with unique_name.guard(), program_guard(prefill, startup):
        toks = fluid.data(DecodeModel.PRE_TOKENS, [1, L], dtype="int64")
        pos = fluid.data(DecodeModel.PRE_POSITIONS, [1, L], dtype="int64")
        bias = fluid.data(DecodeModel.PRE_BIAS, [1, L, L], dtype="float32")
        h = embed(toks, pos)
        for i in range(NL):
            q = proj(h, H, f"l{i}.q")
            k = proj(h, H, f"l{i}.k")
            v = proj(h, H, f"l{i}.v")
            scores = fluid.layers.matmul(q, k, transpose_y=True,
                                         alpha=sm_scale)
            att = fluid.layers.softmax(
                fluid.layers.elementwise_add(scores, bias), axis=-1)
            ctx = fluid.layers.matmul(att, v)
            h = fluid.layers.elementwise_add(h, proj(ctx, H, f"l{i}.out"))
            h = ffn_block(h, i)
            kv_fetches.append((k.name, v.name))
        pre_logits = proj(h, V, "head")

    # -- decode step: one token per slot at [S, 1], paged arena ----------
    decode = Program()
    with unique_name.guard(), program_guard(decode, startup):
        tok = fluid.data(DecodeModel.DEC_TOKEN, [S, 1], dtype="int64")
        pos = fluid.data(DecodeModel.DEC_POSITION, [S, 1], dtype="int64")
        bias = fluid.data(DecodeModel.DEC_BIAS, [S, 1, L], dtype="float32")
        rows = fluid.data(DecodeModel.DEC_ROWS, [S * L], dtype="int64")
        wrows = fluid.data(DecodeModel.DEC_WRITE_ROWS, [S], dtype="int64")
        lmask = (fluid.data(DecodeModel.DEC_MASK, [S, 1, V],
                            dtype="float32") if logits_mask else None)
        h = embed(tok, pos)
        for i in range(NL):
            kc = _state_var(decode, startup, state_names[i][0], [R, H])
            vc = _state_var(decode, startup, state_names[i][1], [R, H])
            q = proj(h, H, f"l{i}.q")
            k = proj(h, H, f"l{i}.k")
            v = proj(h, H, f"l{i}.v")
            nk = fluid.layers.block_scatter_write(
                kc, wrows, fluid.layers.squeeze(k, [1]))
            nv = fluid.layers.block_scatter_write(
                vc, wrows, fluid.layers.squeeze(v, [1]))
            # persist: the lowering donates the arenas, so this is an
            # in-place device update, not a copy
            fluid.layers.assign(nk, output=kc)
            fluid.layers.assign(nv, output=vc)
            if fused_attention:
                ctx = fluid.layers.paged_attention(
                    fluid.layers.squeeze(q, [1]), nk, nv, rows, bias,
                    S, L, sm_scale=sm_scale)
            else:
                gk = fluid.layers.block_gather(nk, rows, S, L)
                gv = fluid.layers.block_gather(nv, rows, S, L)
                ctx = fluid.layers.cached_attention(
                    fluid.layers.squeeze(q, [1]), gk, gv, bias,
                    sm_scale=sm_scale)
            ctx = fluid.layers.unsqueeze(ctx, [1])
            h = fluid.layers.elementwise_add(h, proj(ctx, H, f"l{i}.out"))
            h = ffn_block(h, i)
        dec_logits = proj(h, V, "head")
        if lmask is not None:
            dec_logits = fluid.layers.logits_mask_add(dec_logits, lmask)

    # -- inject: scatter prefill rows into arbitrary arena rows ----------
    inject = Program()
    inj_feeds = []
    with unique_name.guard(), program_guard(inject, startup):
        irows = fluid.data(DecodeModel.INJ_ROWS, [L], dtype="int64")
        for i in range(NL):
            kc = _state_var(inject, startup, state_names[i][0], [R, H])
            vc = _state_var(inject, startup, state_names[i][1], [R, H])
            kn, vn = f"inj_k{i}", f"inj_v{i}"
            rk = fluid.data(kn, [1, L, H], dtype="float32")
            rv = fluid.data(vn, [1, L, H], dtype="float32")
            nk = fluid.layers.block_scatter_write(
                kc, irows, fluid.layers.squeeze(rk, [0]))
            nv = fluid.layers.block_scatter_write(
                vc, irows, fluid.layers.squeeze(rv, [0]))
            fluid.layers.assign(nk, output=kc)
            fluid.layers.assign(nv, output=vc)
            inj_feeds.append((kn, vn))

    # -- chunk prefill: [1, C] prompt chunk against the paged arena ------
    chunk = None
    chu_logits_name = None
    if C is not None:
        chunk = Program()
        with unique_name.guard(), program_guard(chunk, startup):
            toks = fluid.data(DecodeModel.CHU_TOKENS, [1, C], dtype="int64")
            pos = fluid.data(DecodeModel.CHU_POSITIONS, [1, C],
                             dtype="int64")
            bias = fluid.data(DecodeModel.CHU_BIAS, [1, C, L],
                              dtype="float32")
            crows = fluid.data(DecodeModel.CHU_ROWS, [L], dtype="int64")
            cwrows = fluid.data(DecodeModel.CHU_WRITE_ROWS, [C],
                                dtype="int64")
            h = embed(toks, pos)
            for i in range(NL):
                kc = _state_var(chunk, startup, state_names[i][0], [R, H])
                vc = _state_var(chunk, startup, state_names[i][1], [R, H])
                q = proj(h, H, f"l{i}.q")
                k = proj(h, H, f"l{i}.k")
                v = proj(h, H, f"l{i}.v")
                nk = fluid.layers.block_scatter_write(
                    kc, cwrows, fluid.layers.squeeze(k, [0]))
                nv = fluid.layers.block_scatter_write(
                    vc, cwrows, fluid.layers.squeeze(v, [0]))
                fluid.layers.assign(nk, output=kc)
                fluid.layers.assign(nv, output=vc)
                # gather AFTER the scatter: the context view includes the
                # chunk's own rows; the host bias opens exactly the
                # causal prefix per chunk position
                gk = fluid.layers.block_gather(nk, crows, 1, L)
                gv = fluid.layers.block_gather(nv, crows, 1, L)
                scores = fluid.layers.matmul(q, gk, transpose_y=True,
                                             alpha=sm_scale)
                att = fluid.layers.softmax(
                    fluid.layers.elementwise_add(scores, bias), axis=-1)
                ctx = fluid.layers.matmul(att, gv)
                h = fluid.layers.elementwise_add(
                    h, proj(ctx, H, f"l{i}.out"))
                h = ffn_block(h, i)
            chu_logits = proj(h, V, "head")
            chu_logits_name = chu_logits.name

    kwargs = dict(vocab_size=V, hidden=H, num_layers=NL, ffn_dim=FFN,
                  slots=S, max_len=L, eos_id=eos_id, name=name,
                  version=version, block_size=BS, num_blocks=NB,
                  chunk_tokens=C, fused_attention=fused_attention,
                  logits_mask=logits_mask)
    return DecodeModel(
        decode_program=decode, prefill_program=prefill,
        inject_program=inject, chunk_program=chunk,
        startup_program=startup,
        slots=S, max_len=L, vocab_size=V, hidden=H,
        block_size=BS, num_blocks=NB, chunk_tokens=C,
        state_names=state_names, logits_fetch=dec_logits.name,
        prefill_logits_fetch=pre_logits.name,
        chunk_logits_fetch=chu_logits_name,
        prefill_kv_fetches=kv_fetches, inject_kv_feeds=inj_feeds,
        eos_id=eos_id, name=name, version=version,
        builder=lambda: build_decoder_model(**kwargs),
        logits_mask=logits_mask,
    )
