"""DecodeModel: the three-program contract of the continuous-batching engine.

A generation model is served through THREE fixed-shape programs that share
one scope (weights by name) and one slotted KV arena:

* **decode step** — the per-iteration hot path. ONE static shape: token
  ``[S, 1]`` + position ``[S, 1]`` + attention bias ``[S, 1, L]`` + write
  one-hot ``[S, L]``, against per-layer K/V arenas ``[S, L, H]`` held as
  persistable state. The arena update composes multiply/add (see
  ``layers.kv_cache_write``), so a slot whose write row is all-zero is
  bit-untouched — retired slots are invisible, admitted slots join
  mid-flight, and the compiled executable never sees the batch change.
* **prefill** — whole-prompt forward at ``[1, L]`` with a causal additive
  bias, fetching per-layer K/V rows ``[1, L, H]`` and logits ``[1, L, V]``.
  Stateless (donation off): its outputs are host-cacheable, which is what
  makes shared-prefix dedup by content hash possible.
* **inject** — writes prefill K/V rows into one slot of the arena by slot
  one-hot ``[S, 1, 1]`` (broadcast multiply/add, same exactness argument).

All three shapes are static, so a warmed engine holds exactly three
executables and can never retrace. Every parameter, feed, and arena var
name is derived from the ``(name, version)`` prefix — content-identical
rebuilds (circuit-breaker relaunch, a cold replica) re-derive identical
programs and hit the compile cache instead of recompiling.

``build_decoder_model`` is the canonical builder: a small pre-norm-free
residual transformer decoder (token+position embedding, per-layer
attention + FFN, logits head). Custom architectures follow the same feed/
fetch contract and plug into the same engine.
"""

import numpy as np

__all__ = ["DecodeModel", "build_decoder_model"]

# additive-mask value: exp(-1e9) underflows to exactly 0.0 (the repo-wide
# padding contract), so masked cache positions are bit-invisible
NEG_INF = -1e9


class DecodeModel:
    """The three programs + their naming contract and geometry.

    ``state_names`` lists per-layer ``(k_arena, v_arena)`` var names;
    ``prefill_kv_fetches`` the matching per-layer ``(k_rows, v_rows)``
    fetch names of the prefill program. ``builder`` (optional) is a
    zero-arg callable that re-creates a content-identical DecodeModel —
    the circuit breaker's relaunch path uses it to rebuild a replica that
    warms entirely from the compile cache."""

    # feed-name contract (fixed; the engine builds these arrays)
    DEC_TOKEN = "dec_token"
    DEC_POSITION = "dec_position"
    DEC_BIAS = "dec_bias"
    DEC_WRITE = "dec_write"
    PRE_TOKENS = "pre_tokens"
    PRE_POSITIONS = "pre_positions"
    PRE_BIAS = "pre_bias"
    INJ_SLOT = "inj_slot"

    def __init__(self, *, decode_program, prefill_program, inject_program,
                 startup_program, slots, max_len, vocab_size, hidden,
                 state_names, logits_fetch, prefill_logits_fetch,
                 prefill_kv_fetches, inject_kv_feeds, eos_id=None,
                 name="model", version="1", builder=None):
        self.decode_program = decode_program
        self.prefill_program = prefill_program
        self.inject_program = inject_program
        self.startup_program = startup_program
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.vocab_size = int(vocab_size)
        self.hidden = int(hidden)
        self.state_names = list(state_names)
        self.logits_fetch = logits_fetch
        self.prefill_logits_fetch = prefill_logits_fetch
        self.prefill_kv_fetches = list(prefill_kv_fetches)
        self.inject_kv_feeds = list(inject_kv_feeds)
        self.eos_id = eos_id
        self.name = str(name)
        self.version = str(version)
        self.builder = builder

    @property
    def key(self):
        return (self.name, self.version)

    @property
    def label(self):
        return f"{self.name}@{self.version}"

    def arena_bytes(self):
        """Exact bytes of the slotted KV pool: 2 arenas x layers x
        ``[S, L, H]`` float32 — what `analysis/memory.py` sees as
        persistent state and what the HBM budget gate reasons about."""
        per = self.slots * self.max_len * self.hidden * 4
        return per * 2 * len(self.state_names)

    # -- feed signatures (ordered like each program's feed list) ---------
    def decode_feed_sig(self):
        s, l = self.slots, self.max_len
        return (
            (self.DEC_TOKEN, (s, 1), "int64"),
            (self.DEC_POSITION, (s, 1), "int64"),
            (self.DEC_BIAS, (s, 1, l), "float32"),
            (self.DEC_WRITE, (s, l), "float32"),
        )

    def prefill_feed_sig(self):
        l = self.max_len
        return (
            (self.PRE_TOKENS, (1, l), "int64"),
            (self.PRE_POSITIONS, (1, l), "int64"),
            (self.PRE_BIAS, (1, l, l), "float32"),
        )

    def inject_feed_sig(self):
        s, l, h = self.slots, self.max_len, self.hidden
        sig = [(self.INJ_SLOT, (s, 1, 1), "float32")]
        for kn, vn in self.inject_kv_feeds:
            sig.append((kn, (1, l, h), "float32"))
            sig.append((vn, (1, l, h), "float32"))
        return tuple(sig)


def _state_var(main_program, startup_program, name, shape):
    """A persistable float32 state var declared in ``main_program`` and
    zero-initialized ONCE in the shared startup (create_global_var would
    append a duplicate fill per program that declares the arena)."""
    mblock = main_program.global_block()
    var = mblock.vars.get(name)
    if var is None:
        var = mblock.create_var(name=name, shape=list(shape),
                                dtype="float32", persistable=True)
        var.stop_gradient = True
    sblock = startup_program.global_block()
    if name not in sblock.vars:
        sblock.create_var(name=name, shape=list(shape), dtype="float32",
                          persistable=True)
        sblock.append_op(
            "fill_constant", {}, {"Out": [name]},
            {"shape": list(shape), "dtype": "float32", "value": 0.0},
        )
    return var


def build_decoder_model(vocab_size, hidden=16, num_layers=2, ffn_dim=None,
                        slots=4, max_len=32, eos_id=None, name="decoder",
                        version="1"):
    """Build the canonical cached-attention decoder as a DecodeModel.

    Residual transformer decoder: token+position embeddings, per layer
    (q/k/v projection -> cached attention -> output projection ->
    residual -> relu FFN -> residual), logits head. Offline/prefill and
    decode paths share every weight by explicit name, which is both the
    bit-exactness contract (one set of parameters, two access patterns)
    and the relaunch contract (rebuilding produces byte-identical
    programs, so the compile cache, not XLA, pays for the restart)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.ir import Program, program_guard
    from paddle_tpu.utils import unique_name

    V, H, S, L = int(vocab_size), int(hidden), int(slots), int(max_len)
    NL = int(num_layers)
    FFN = int(ffn_dim) if ffn_dim else 4 * H
    if L < 2:
        raise ValueError(f"max_len {L} leaves no room to generate")
    prefix = f"{name}_v{version}"

    def attr(suffix):
        return fluid.ParamAttr(name=f"{prefix}.{suffix}")

    def proj(h, size, suffix, act=None):
        return fluid.layers.fc(
            h, size, num_flatten_dims=2, act=act,
            param_attr=attr(suffix + ".w"), bias_attr=attr(suffix + ".b"),
        )

    def embed(toks, pos):
        te = fluid.layers.embedding(toks, size=(V, H),
                                    param_attr=attr("tok_emb"))
        pe = fluid.layers.embedding(pos, size=(L, H),
                                    param_attr=attr("pos_emb"))
        return fluid.layers.elementwise_add(te, pe)

    def ffn_block(h, i):
        ff = proj(h, FFN, f"l{i}.ffn1", act="relu")
        return fluid.layers.elementwise_add(h, proj(ff, H, f"l{i}.ffn2"))

    sm_scale = 1.0 / float(np.sqrt(H))
    state_names = [(f"{prefix}.kcache{i}", f"{prefix}.vcache{i}")
                   for i in range(NL)]
    startup = Program()

    # -- prefill: whole-prompt causal forward at [1, L] ------------------
    prefill = Program()
    kv_fetches = []
    # unique_name.guard(): auto-named temp vars restart per program, so a
    # rebuild ANYWHERE in a process (the breaker's relaunch, a second
    # engine) is textually identical and hits the compile cache instead
    # of retracing
    with unique_name.guard(), program_guard(prefill, startup):
        toks = fluid.data(DecodeModel.PRE_TOKENS, [1, L], dtype="int64")
        pos = fluid.data(DecodeModel.PRE_POSITIONS, [1, L], dtype="int64")
        bias = fluid.data(DecodeModel.PRE_BIAS, [1, L, L], dtype="float32")
        h = embed(toks, pos)
        for i in range(NL):
            q = proj(h, H, f"l{i}.q")
            k = proj(h, H, f"l{i}.k")
            v = proj(h, H, f"l{i}.v")
            scores = fluid.layers.matmul(q, k, transpose_y=True,
                                         alpha=sm_scale)
            att = fluid.layers.softmax(
                fluid.layers.elementwise_add(scores, bias), axis=-1)
            ctx = fluid.layers.matmul(att, v)
            h = fluid.layers.elementwise_add(h, proj(ctx, H, f"l{i}.out"))
            h = ffn_block(h, i)
            kv_fetches.append((k.name, v.name))
        pre_logits = proj(h, V, "head")

    # -- decode step: one token per slot at [S, 1] -----------------------
    decode = Program()
    with unique_name.guard(), program_guard(decode, startup):
        tok = fluid.data(DecodeModel.DEC_TOKEN, [S, 1], dtype="int64")
        pos = fluid.data(DecodeModel.DEC_POSITION, [S, 1], dtype="int64")
        bias = fluid.data(DecodeModel.DEC_BIAS, [S, 1, L], dtype="float32")
        write = fluid.data(DecodeModel.DEC_WRITE, [S, L], dtype="float32")
        h = embed(tok, pos)
        for i in range(NL):
            kc = _state_var(decode, startup, state_names[i][0], [S, L, H])
            vc = _state_var(decode, startup, state_names[i][1], [S, L, H])
            q = proj(h, H, f"l{i}.q")
            k = proj(h, H, f"l{i}.k")
            v = proj(h, H, f"l{i}.v")
            nk = fluid.layers.kv_cache_write(
                kc, fluid.layers.squeeze(k, [1]), write)
            nv = fluid.layers.kv_cache_write(
                vc, fluid.layers.squeeze(v, [1]), write)
            # persist: the lowering donates the arenas, so this is an
            # in-place device update, not a copy
            fluid.layers.assign(nk, output=kc)
            fluid.layers.assign(nv, output=vc)
            ctx = fluid.layers.cached_attention(
                fluid.layers.squeeze(q, [1]), nk, nv, bias,
                sm_scale=sm_scale)
            ctx = fluid.layers.unsqueeze(ctx, [1])
            h = fluid.layers.elementwise_add(h, proj(ctx, H, f"l{i}.out"))
            h = ffn_block(h, i)
        dec_logits = proj(h, V, "head")

    # -- inject: write prefill rows into one arena slot ------------------
    inject = Program()
    inj_feeds = []
    with unique_name.guard(), program_guard(inject, startup):
        slot = fluid.data(DecodeModel.INJ_SLOT, [S, 1, 1], dtype="float32")
        for i in range(NL):
            kc = _state_var(inject, startup, state_names[i][0], [S, L, H])
            vc = _state_var(inject, startup, state_names[i][1], [S, L, H])
            kn, vn = f"inj_k{i}", f"inj_v{i}"
            rk = fluid.data(kn, [1, L, H], dtype="float32")
            rv = fluid.data(vn, [1, L, H], dtype="float32")
            nk = fluid.layers.masked_write(kc, rk, slot)
            nv = fluid.layers.masked_write(vc, rv, slot)
            fluid.layers.assign(nk, output=kc)
            fluid.layers.assign(nv, output=vc)
            inj_feeds.append((kn, vn))

    kwargs = dict(vocab_size=V, hidden=H, num_layers=NL, ffn_dim=FFN,
                  slots=S, max_len=L, eos_id=eos_id, name=name,
                  version=version)
    return DecodeModel(
        decode_program=decode, prefill_program=prefill,
        inject_program=inject, startup_program=startup,
        slots=S, max_len=L, vocab_size=V, hidden=H,
        state_names=state_names, logits_fetch=dec_logits.name,
        prefill_logits_fetch=pre_logits.name,
        prefill_kv_fetches=kv_fetches, inject_kv_feeds=inj_feeds,
        eos_id=eos_id, name=name, version=version,
        builder=lambda: build_decoder_model(**kwargs),
    )
