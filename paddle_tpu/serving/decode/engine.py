"""GenerationEngine: continuous-batching decode over a paged KV arena.

The PR-2 ServingEngine batches whole requests into fixed buckets — a
finished sequence holds its rows until the whole bucket drains. This
engine schedules at ITERATION granularity (Orca, OSDI'22): a fixed batch
of S slots is stepped once per model iteration through ONE compiled
``[S, 1]`` decode executable; finished sequences retire between
iterations and admitted prompts prefill into free slots mid-flight, so
occupancy tracks offered load instead of the slowest batchmate.

Storage is a **block-granular paged arena** (vLLM's PagedAttention,
SOSP'23): KV rows live in fixed-size blocks handed out by
``pool.BlockPool``; the compiled programs see only flat row-index feeds,
so HBM scales with USED tokens, prompts sharing a prefix share PHYSICAL
blocks through the radix index (copy-on-write at divergence), and the
arena is sized against ``analysis/memory.py``'s pre-compile HBM gate
instead of reserving a dense ``slots x max_len`` grid.

Scheduling modes, all bit-identical to the offline whole-sequence
reference for any admission order (tested, not asserted by construction
alone):

* **decode** — the ``[S, 1]`` hot path, as in PR 10.
* **chunked prefill** — a prompt longer than the chunk budget streams
  through the ``[1, C]`` chunk program ONE chunk per engine iteration,
  interleaved with decode steps, so a 32k-token admission never stalls
  in-flight generations for more than one chunk's compute. Chunks fully
  covered by radix-shared blocks are skipped (shared prefixes share
  prefill work AND storage).
* **speculative** — a draft model (just another ``(model, version)``
  registry entry) greedily proposes k tokens; the target verifies all
  of them in ONE batch-prefill forward and emits the longest matching
  prefix plus its own correction token. Greedy acceptance makes the
  output BIT-IDENTICAL to target-only decode; the win is target
  steps-per-emitted-token < 1.

Correctness contract: (a) retired/foreign slots touch the arena only
through dropped or disjoint row scatters (exact no-ops), and (b) the
additive ``-1e9`` attention bias makes positions beyond a slot's cursor
contribute exactly 0.0 (the repo-wide padding contract); gather/scatter
relocate rows byte-for-byte, so the paged rebuild preserves PR 10's
bit-exactness property for every block size.

Multi-tenancy: one engine hosts N ``(model, version)`` entries, each with
its own slot batch, queue, and scheduler thread. Admission applies
per-tenant quotas (queued rows reject at the door; in-flight caps make
the picker skip, not reject) and WEIGHTED-FAIR selection layered over the
queue's strict priority lanes (stride scheduling).

Cold start: the executables per entry lower through ``core/lowering.py``
into the content-addressed compile cache. With ``PADDLE_TPU_CACHE_DIR``
set, a fresh replica (or the circuit breaker's relaunched replacement)
restores them from the ``jax.export`` disk tier with ZERO traces —
subprocess-asserted in tests/test_decode.py. Before anything compiles,
the paged arena is sized against the peak-HBM budget via
``analysis/memory.py`` — an oversized block pool fails with sizing
advice, not an XLA OOM.
"""

import threading
import time

import numpy as np

from paddle_tpu import profiler
from paddle_tpu.observability import lockdep
from paddle_tpu.resilience import faults
from paddle_tpu.serving.decode.generate import (
    BeamParams,
    CompiledGrammar,
    GrammarConstraint,
    SamplingParams,
    offline_beam_decode,
    sample_token,
)
from paddle_tpu.serving.decode.generate.beam import (
    finished_ranking as beam_finished_ranking,
)
from paddle_tpu.serving.decode.generate.beam import select as beam_select
from paddle_tpu.serving.brownout import BrownoutController
from paddle_tpu.serving.decode.metrics import DecodeMetrics
from paddle_tpu.serving.decode.model import NEG_INF, DecodeModel
from paddle_tpu.serving.decode.pool import (
    BlockPool,
    PrefixCache,
    SlotPool,
    block_hashes,
    prompt_key,
)
from paddle_tpu.serving.decode.tier import HostKVTier
from paddle_tpu.serving.engine import _ReplicaBreaker
from paddle_tpu.serving.queue import RequestQueue
from paddle_tpu.serving.request import (
    DeadlineExceededError,
    Priority,
    RejectedError,
    ReplicaLostError,
    RequestError,
    Response,
)

__all__ = ["GenerationEngine", "GenerationRequest"]

# The scheduler takes the queue lock, then the tenant table inside it
# (_admit_free_slots -> _pick); PR 10's ABBA fix (quota rejects estimate
# retry-after OUTSIDE _tenant_lock) exists precisely to preserve this.
# Declared so a future inversion names the RULE, not just the cycle.
lockdep.declare_order("serving.queue", "decode.tenant")
# Draft-KV speculation: a TARGET entry's scheduler thread takes the draft
# entry's decode.draft lock, then allocates from the draft's block pool
# inside it (catch-up / proposal appends) — the draft lock is strictly
# OUTSIDE the pool lock, never the reverse.
lockdep.declare_order("decode.draft", "decode.blocks")


class GenerationRequest:
    """One admitted generation request. `response.result()` yields
    ``{"tokens": int64 array}`` — the generated tokens, including the
    stop token when eos fired (beam requests add ``"beams"``: every
    finished hypothesis with its score, best first). ``draft_key`` (a
    registry ``(name, version)``) opts the request into speculative
    decoding with ``spec_k`` proposals per verify cycle; ``rows`` is the
    slot footprint — 1 for everything except beam search, whose live
    hypotheses each hold a batch slot."""

    __slots__ = ("id", "prompt", "max_new", "tenant", "priority", "deadline",
                 "submit_time", "dispatch_time", "response", "rows",
                 "draft_key", "spec_k", "sampling", "beam", "grammar",
                 "draft_kv")

    def __init__(self, rid, prompt, max_new, tenant, priority, deadline,
                 draft_key=None, spec_k=0, sampling=None, beam=None,
                 grammar=None, draft_kv=False):
        self.id = rid
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.tenant = str(tenant)
        self.priority = priority
        self.deadline = deadline
        self.submit_time = time.perf_counter()
        self.dispatch_time = None
        self.response = Response()
        self.sampling = sampling      # SamplingParams or None (greedy)
        self.beam = beam              # BeamParams or None
        self.grammar = grammar        # CompiledGrammar or None
        self.draft_kv = bool(draft_kv)
        self.rows = beam.width if beam is not None else 1
        self.draft_key = draft_key
        self.spec_k = int(spec_k)

    def expired(self, now=None):
        if self.deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) > self.deadline


class _ArenaInvalidError(RuntimeError):
    """A DONATED arena update (inject/chunk) failed mid-execution: the
    old buffers were consumed and the new ones never materialized, so the
    whole KV pool — not just the admitting request — is undefined."""


class _DeferAdmission(Exception):
    """Raised out of ``_acquire_blocks`` when the arena is exhausted and
    the request cannot be admitted right now, but WILL fit later (parked
    sessions hold its blocks, or victims could not be preempted safely).
    The admission loop parks the request on ``_pending`` and retries
    every iteration — never a hard failure."""


class _TenantState:
    __slots__ = ("weight", "max_in_flight", "max_queued", "in_flight",
                 "queued", "vtime")

    def __init__(self, weight=1.0, max_in_flight=None, max_queued=None):
        self.weight = float(weight)
        self.max_in_flight = max_in_flight
        self.max_queued = max_queued
        self.in_flight = 0
        self.queued = 0
        self.vtime = 0.0


class _Slot:
    """Host-side state of one live batch slot.

    ``mode`` is "decode" (stepping through the [S,1] program),
    "prefill" (a long prompt streaming through the chunk program),
    "spec" (speculative verify cycles — holds no TARGET arena blocks),
    or "beam" (one live beam hypothesis; its group coordinates via
    ``beam``). ``blocks`` is the slot's block table; ``row_map[p]`` the
    physical arena row of position ``p`` (the device half of the
    table). ``d_*`` is the draft-KV footprint of a speculative slot:
    its slot/blocks/row-map ON THE DRAFT ENTRY plus ``d_cursor``, the
    next draft arena position without a committed KV row."""

    __slots__ = ("request", "mode", "cursor", "last_token", "generated",
                 "blocks", "row_map", "plen", "done", "shared_len", "toks",
                 "sampling", "grammar", "beam", "score", "seq",
                 "d_entry", "d_slot", "d_blocks", "d_row_map", "d_cursor")

    def __init__(self, request, mode="decode"):
        self.request = request
        self.mode = mode
        self.cursor = 0
        self.last_token = None
        self.generated = []
        self.blocks = []
        self.row_map = None
        self.seq = 0            # admission order (default victim policy)
        self.plen = len(request.prompt)
        self.done = 0           # chunked prefill: prompt positions landed
        self.shared_len = 0     # positions served by radix-shared blocks
        self.toks = None        # spec mode: prompt + emitted so far
        self.sampling = None    # SamplingParams (committed-stream sampling)
        self.grammar = None     # per-hypothesis GrammarConstraint
        self.beam = None        # _BeamGroup this slot belongs to
        self.score = 0.0        # beam: cumulative float64 log-prob
        self.d_entry = None     # draft-KV: the draft _ModelEntry
        self.d_slot = None
        self.d_blocks = None
        self.d_row_map = None
        self.d_cursor = 0


class _BeamGroup:
    """One beam request's shared state across its live hypothesis slots.
    ``order`` is the live slot ids in REFERENCE hypothesis order — the
    rank order of the last selection — which is what makes the
    incremental engine's tie-breaking (by parent index) bit-identical
    to ``offline_beam_decode``'s live-list order."""

    __slots__ = ("request", "width", "finished", "order", "spare")

    def __init__(self, request):
        self.request = request
        self.width = request.beam.width
        self.finished = []      # [(token list, float64 score), ...]
        self.order = []         # live slot ids, hypothesis order
        # the group RESERVES width slots for its lifetime (that is what
        # request.rows promised admission): pruned hypotheses park their
        # slot here for later forks instead of returning it to the pool,
        # so a fork can never lose its slot to a concurrent admission
        self.spare = []


class _ParkedSession:
    """One preempted in-flight session waiting off-device. ``states``
    holds the live ``_Slot`` objects (host state — sampling stream,
    grammar cursor, committed tokens — travels with them untouched);
    ``keys`` the host-tier keys of each hypothesis's spilled KV rows
    (empty for spec mode, which holds no target arena rows). Resume is
    FIFO: re-acquire slots + blocks, re-inject (or recompute) the rows,
    and the session continues byte-identically."""

    __slots__ = ("request", "mode", "states", "keys", "group", "parked_at")

    def __init__(self, request, mode, states, keys, group=None):
        self.request = request
        self.mode = mode
        self.states = states
        self.keys = keys
        self.group = group
        self.parked_at = time.perf_counter()


class _ModelEntry:
    """One hosted (model, version): programs + executables + slot batch +
    block pool + its scheduler thread. All slot/arena/block mutation
    happens on the loop thread; admission hand-off goes through the
    queue."""

    def __init__(self, engine, model, queue_depth, breaker_threshold,
                 breaker_cooldown_s, prefix_cache_size):
        self._engine = engine
        self._model = model
        self._queue = RequestQueue(queue_depth)
        self._cond = threading.Condition(self._queue.lock)
        self._pool = SlotPool(model.slots)
        self._slots = [None] * model.slots
        self._blocks = BlockPool(model.num_blocks, model.block_size)
        self._prefix = PrefixCache(prefix_cache_size)
        # graceful degradation (r18): host-RAM KV tier, parked sessions,
        # deferred admissions, and the brownout severity ladder. The
        # pool writes registered blocks back to the tier at LRU eviction
        # (decode.blocks -> decode.tier); reads go through the engine so
        # the device rows come off the live arena.
        self._tier = HostKVTier(capacity_bytes=engine._host_tier_bytes)
        self._blocks.attach_tier(self._tier, read_rows=self._read_block_rows)
        self._parked = []       # [_ParkedSession] FIFO
        self._pending = []      # [GenerationRequest] deferred admissions
        self._brownout = BrownoutController()
        self._bt_seen = 0       # brownout transitions already counted
        self._admit_seq = 0
        self._chunk_throttle = False
        self.victim_policy = None   # callable([slot ids]) -> slot id
        self._breaker = (
            _ReplicaBreaker(breaker_threshold, breaker_cooldown_s)
            if breaker_threshold and breaker_threshold > 0 else None
        )
        self._metrics = DecodeMetrics(
            engine_label=f"{engine.label}:{model.label}")
        self.compile_sources = {"trace": 0, "disk": 0, "memory": 0}
        self._entries = {}      # kind -> (LoweredStep, executable)
        self._thread = None
        self._stop = False
        self._scope = None
        self._rng0 = None
        self._pref_rr = 0       # round-robin cursor over prefilling slots
        # half-open relaunch latch: one rebuild per breaker episode
        self._probe_relaunched = False
        # draft-KV speculation, when THIS entry serves as the draft:
        # every draft-side device call from a target's scheduler thread
        # serializes under _draft_lock; _draft_pinned closes the entry to
        # primary submissions (its own loop then never touches the arena,
        # so the donated draft decode/inject calls cannot race it);
        # _draft_ok poisons the entry after a failed donated draft call —
        # users fall back to replay proposals instead of reading an
        # undefined arena
        self._draft_lock = lockdep.named_lock("decode.draft")
        self._draft_pinned = False
        self._draft_ok = True

    # -- build / warmup ---------------------------------------------------
    def build(self):
        """Run startup (weights + zeroed arenas into the scope), then
        lower + AOT-compile the executables. With a warm compile cache
        nothing here traces (`compile_sources` says so)."""
        import paddle_tpu as fluid
        from paddle_tpu.core.lowering import zero_rng_key

        self._scope = fluid.Scope()
        exe = fluid.Executor(self._engine.place)
        with fluid.scope_guard(self._scope):
            exe.run(self._model.startup_program)
        self._rng0 = zero_rng_key(self._engine.device)
        self._lower_all()
        return self

    def _lower_all(self):
        from paddle_tpu.core import lowering

        m = self._model
        plans = [
            ("step", m.decode_program, m.decode_feed_sig(),
             [m.logits_fetch], True),
            ("prefill", m.prefill_program, m.prefill_feed_sig(),
             [m.prefill_logits_fetch] + [n for kv in m.prefill_kv_fetches
                                         for n in kv], False),
            ("inject", m.inject_program, m.inject_feed_sig(), [], True),
        ]
        if m.chunk_program is not None:
            plans.append(("chunk", m.chunk_program, m.chunk_feed_sig(),
                          [m.chunk_logits_fetch], True))
        sources = dict(self.compile_sources)
        with profiler.RecordEvent("decode::warmup"):
            for kind, prog, feed_sig, fetches, donate in plans:
                entry, source = lowering.lower_step(
                    prog, self._scope, feed_sig, fetches, donate=donate,
                    label=f"decode:{m.label}:{kind}",
                )
                sources[source] = sources.get(source, 0) + 1
                executable = entry.aot_compile(
                    lowering.abstract_signature(entry, feed_sig,
                                                self._scope))
                self._entries[kind] = (entry, executable)
        # atomic rebind, not in-place mutation: a breaker relaunch runs
        # this on the loop thread while stats() dict-copies concurrently
        self.compile_sources = sources

    def _run(self, kind, feeds):
        """Execute one lowered program against the entry scope; written
        persistables (the arenas — donated, updated in place on device)
        re-enter the scope for the next call."""
        import jax

        entry, executable = self._entries[kind]
        dev = self._engine.device
        feed_vals = tuple(
            jax.device_put(np.ascontiguousarray(feeds[n]), dev)
            for n in entry.feed_names
        )
        donated = tuple(self._scope.find_var(n) for n in entry.donated)
        readonly = tuple(self._scope.find_var(n) for n in entry.readonly)
        fetches, updates = executable(feed_vals, donated, readonly,
                                      self._rng0)
        for n, u in zip(entry.written, updates):
            self._scope.set(n, u)
        return fetches

    def _reset_arenas(self):
        """Zero the KV pool and drop all slot/block state (relaunch
        path: a failed donated call leaves the old arena buffers
        invalid)."""
        import jax
        import jax.numpy as jnp

        m = self._model
        for kn, vn in m.state_names:
            for n in (kn, vn):
                self._scope.set(n, jax.device_put(
                    jnp.zeros((m.rows, m.hidden), jnp.float32),
                    self._engine.device))
        self._pool.reset()
        self._blocks.reset()
        self._slots = [None] * m.slots

    def relaunch(self):
        """The circuit breaker's replacement replica: rebuild programs
        from the model's builder (content-identical by construction),
        re-lower — every entry should come from the compile cache, not a
        trace — and reset the arena. Weights stay; queued requests are
        served by the relaunched replica."""
        if self._model.builder is not None:
            self._model = self._model.builder()
        self._lower_all()
        self._reset_arenas()
        self._metrics.incr("relaunches")

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop = False
        self._queue.reopen()
        self._thread = threading.Thread(
            target=self._loop, name=f"decode-{self._model.label}",
            daemon=True)
        self._thread.start()

    def shutdown(self, timeout=60.0):
        self._queue.close()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def notify(self):
        with self._cond:
            self._cond.notify()

    # -- scheduler loop ---------------------------------------------------
    def _loop(self):
        while not self._iterate():
            pass

    def _iterate(self):
        """ONE scheduler iteration: expire, (breaker), admit up to the
        free slots, advance AT MOST ONE prefill chunk, run one verify
        cycle per speculative slot, then one decode step. Extracted so
        tests can hand-step the interleaving deterministically. Returns
        True when the loop should exit."""
        with self._cond:
            for r in self._queue.expire():
                self._reject_expired(r)
            # shutdown drains parked sessions and deferred admissions
            # too: capacity frees as slots retire, so they resume and
            # finish rather than abandoning their futures
            if (self._stop and self._queue.empty()
                    and self._pool.active_count == 0
                    and not self._parked and not self._pending):
                return True
        self._brownout_tick()
        if self._breaker is not None and not self._stop:
            verdict, wait_s = self._breaker.gate()
            if verdict == "wait":
                with self._cond:
                    for r in self._queue.expire():
                        self._reject_expired(r)
                    if not self._stop:
                        self._cond.wait(timeout=min(wait_s, 0.1))
                return False
            if verdict == "probe" and not self._probe_relaunched:
                # re-admission probe IS a relaunch: fresh programs,
                # zeroed arena, executables from the compile cache —
                # ONCE per half-open episode (the flag); the probe
                # STEP's outcome then closes or reopens the breaker,
                # so an idle engine doesn't rebuild every loop tick
                self._metrics.incr("breaker_probes")
                try:
                    self.relaunch()
                    self._probe_relaunched = True
                except Exception:
                    self._breaker_event(self._breaker.record_failure())
                    return False
        # parked sessions and deferred admissions get first claim on
        # freed capacity — FIFO, before any new pick from the queue
        admitted = self._service_parked() + self._admit_free_slots()
        progressed = self._advance_prefills() + self._advance_spec()
        if not any(st is not None and st.mode in ("decode", "beam")
                   for st in self._slots):
            # nothing decodable AND this round moved nothing — either
            # the queue is empty, or everything queued is blocked on a
            # tenant cap held by another entry's in-flight work; poll,
            # don't spin
            if not admitted and not progressed:
                with self._cond:
                    if not self._stop:
                        self._cond.wait(timeout=0.02)
            return False
        self._step()
        return False

    def _reject_expired(self, request):
        self._metrics.incr("deadline_missed")
        self._engine._tenant_unqueue(request.tenant)
        request.response._complete(error=DeadlineExceededError(
            "deadline expired after "
            f"{time.perf_counter() - request.submit_time:.3f}s in queue"))
        self._metrics.observe_request(request)

    def _breaker_event(self, event):
        if event:
            self._metrics.incr(event)

    # -- admission (blocks + prefill/inject into a free slot) -------------
    def _admit_free_slots(self):
        picked = []
        # brownout L3+: LOW-lane dispatch quota drops to zero — queued
        # LOW requests wait out the pressure episode instead of landing
        # on an oversubscribed arena
        lanes = (Priority.LANES if self._brownout.level < 3
                 else tuple(p for p in Priority.LANES if p != Priority.LOW))
        with self._cond:
            rows = 0
            while self._pool.free_count - rows > 0:
                # budget in ROWS, not requests: a beam admission claims
                # width slots (seed + first-selection forks) before the
                # next pick runs
                req = self._engine._pick(
                    self._queue, max_rows=self._pool.free_count - rows,
                    lanes=lanes)
                if req is None:
                    break
                picked.append(req)
                rows += req.rows
            # the round's picks are ONE drain event for the rate EWMA
            self._queue.note_drained()
        for req in picked:
            self._engine._tenant_unqueue(req.tenant)
            if self._admit_one(req) == "deferred":
                # lockdep: ok(single writer: the scheduler thread; submit-side readers only probe emptiness (GIL-atomic) and tolerate staleness)
                self._pending.append(req)
        return len(picked)

    def _admit_one(self, req):
        """Admit one request (freshly picked or retried from
        ``_pending``) into a free slot. The caller's pick-time tenant
        in-flight reservation is held throughout; it is released here on
        every terminal outcome and KEPT on "deferred" (the request is
        still committed to this entry — it just waits for arena
        capacity). Returns "admitted" | "deferred" | "done"."""
        if req.expired():
            # picked but dead: release the pick-time in-flight
            # reservation; no slot to free
            self._engine._tenant_unflight(req.tenant)
            self._metrics.incr("deadline_missed")
            req.response._complete(error=DeadlineExceededError(
                "deadline expired before prefill"))
            self._metrics.observe_request(req)
            return "done"
        slot = self._pool.acquire()
        if slot is None:
            # only reachable on a _pending retry (fresh picks are
            # budgeted against free_count): wait for a retirement
            return "deferred"
        try:
            self._prefill_into(req, slot)
        except _DeferAdmission:
            self._pool.release(slot)
            self._slots[slot] = None
            return "deferred"
        except _ArenaInvalidError as e:
            # donated inject failed: like a step failure, every
            # in-flight sequence is lost (failed loudly), the
            # outcome drives the breaker, and the arena resets
            self._slots[slot] = None
            self._engine._tenant_unflight(req.tenant)
            self._metrics.incr("failed")
            req.response._complete(error=RequestError(
                f"request {req.id} failed in inject: {e}"))
            self._metrics.observe_request(req)
            self._metrics.incr("step_failures")
            self._probe_relaunched = False
            if self._breaker is not None:
                self._breaker_event(self._breaker.record_failure())
            self._reject_all_slots(lambda r: ReplicaLostError(
                f"request {r.id} lost to arena "
                f"failure during admission: {e}"))
            self._reset_arenas()
            # the reset arena is valid (zeroed): the REMAINING picked
            # requests still admit — dropping them would abandon
            # their futures and leak their tenants' queued counters
            return "done"
        except Exception as e:  # request-attributed, not replica health
            self._pool.release(slot)
            self._slots[slot] = None
            self._engine._tenant_unflight(req.tenant)
            self._metrics.incr("failed")
            req.response._complete(error=RequestError(
                f"request {req.id} failed in prefill: {e}"))
            self._metrics.observe_request(req)
            return "done"
        return "admitted"

    def _row_of(self, st, p):
        b = st.blocks[p // self._model.block_size]
        return b.row0 + p % self._model.block_size

    def _rebuild_row_map(self, st):
        m = self._model
        bs = m.block_size
        if st.row_map is None:
            st.row_map = np.zeros(m.max_len, dtype="int64")
        for i, b in enumerate(st.blocks):
            lo = i * bs
            hi = min(lo + bs, m.max_len)
            st.row_map[lo:hi] = b.row0 + np.arange(hi - lo)

    def _acquire_blocks(self, req):
        """Acquire the prompt's block chain, parking victims instead of
        hard-failing under exhaustion. Loud failure is reserved for the
        one unfixable case — the prompt alone can never fit the pool.
        Otherwise victims are preempted (spilled to the host tier, to
        resume byte-identically) until the prompt fits; if that is not
        possible right now, ``_DeferAdmission`` sends the request to
        ``_pending`` with its tenant reservation intact."""
        blocks, shared_len = self._blocks.acquire_for_prompt(req.prompt)
        if blocks is not None:
            return blocks, shared_len
        m = self._model
        self._metrics.incr("blocks_exhausted")
        if (len(req.prompt) + m.block_size - 1) // m.block_size \
                > m.num_blocks:
            self._metrics.incr("blocks_failed_total")
            raise RuntimeError(
                f"block pool exhausted ({self._blocks.stats()['blocks_free']}"
                f" free of {m.num_blocks}) and the prompt alone can never "
                "fit; shorten the prompt or host the model with more blocks")
        # don't preempt on behalf of NEW work while earlier preempted
        # sessions are still waiting — they have first claim on capacity
        while blocks is None and not self._parked:
            if not self._park_victim(req):
                break
            blocks, shared_len = self._blocks.acquire_for_prompt(req.prompt)
        self._metrics.incr("blocks_parked_total")
        if blocks is None:
            self._metrics.incr("admissions_deferred")
            raise _DeferAdmission()
        return blocks, shared_len

    # -- preemption / host-tier spill / resume ----------------------------
    def _read_block_rows(self, b):
        """Tier write-back reader: one registered block's live arena rows
        (called by the pool inside ``decode.blocks`` at LRU eviction —
        before the evictee's rows can be overwritten by its successor)."""
        out = []
        for kn, vn in self._model.state_names:
            k = np.asarray(self._scope.find_var(kn))
            v = np.asarray(self._scope.find_var(vn))
            out.append((np.array(k[b.row0:b.row0 + b.size_used]),
                        np.array(v[b.row0:b.row0 + b.size_used])))
        return out

    def _read_rows(self, row_map, n):
        """One slot's KV rows ``[0:n)`` off the live arena, per layer."""
        idx = np.asarray(row_map[:n], dtype=np.int64)
        out = []
        for kn, vn in self._model.state_names:
            k = np.asarray(self._scope.find_var(kn))
            v = np.asarray(self._scope.find_var(vn))
            out.append((np.array(k[idx]), np.array(v[idx])))
        return out

    def _park_victim(self, req):
        """Pick and park one decode-mode victim to free blocks for
        ``req``. Policy is a seam (tests shuffle it); the default preempts
        the most recently admitted session — oldest work is closest to
        finishing and freeing everything anyway."""
        cands = [s for s in range(self._model.slots)
                 if self._slots[s] is not None
                 and self._slots[s].mode == "decode"
                 and self._slots[s].request is not req]
        if not cands:
            return False
        if self.victim_policy is not None:
            pick = self.victim_policy(cands)
        else:
            pick = max(cands, key=lambda s: self._slots[s].seq)
        return self._park_slot(pick)

    def _park_slot(self, s):
        """Preempt one live slot: spill its private KV rows ``[0:cursor)``
        to the host tier, free its blocks + slot (+ draft footprint), and
        queue the session for FIFO resume. Host state (sampling stream,
        grammar cursor, committed tokens) stays on the parked ``_Slot``
        untouched — resume is byte-identical by construction. Returns
        False when the session cannot be parked (host tier exhausted, or
        it can never be resumed because its lifetime footprint exceeds
        the whole pool)."""
        st = self._slots[s]
        if st is None or st.mode not in ("decode", "spec"):
            return False
        req = st.request
        m = self._model
        if st.mode == "spec":
            # no target arena rows: the park is pure host state. The
            # draft-KV footprint (if any) is released; resume falls back
            # to replay proposals — same committed tokens either way.
            with profiler.RecordEvent("decode::spill"):
                faults.fire("decode.spill")
                self._release_draft_locked(st)
            self._slots[s] = None
            self._pool.release(s)
            # lockdep: ok(single writer: the scheduler thread; submit-side readers only probe emptiness (GIL-atomic) and tolerate staleness)
            self._parked.append(_ParkedSession(req, "spec", [st], []))
            self._metrics.incr("sessions_parked")
            return True
        need = (st.plen + req.max_new + m.block_size - 1) // m.block_size
        if need > m.num_blocks:
            return False
        key = f"park:{req.id}:0"
        with profiler.RecordEvent("decode::spill"):
            faults.fire("decode.spill")
            rows = self._read_rows(st.row_map, st.cursor)
            toks = (list(req.prompt) + list(st.generated))[:st.cursor]
            if not self._tier.put(key, rows, st.cursor, tokens=toks):
                return False
        self._slots[s] = None
        self._pool.release(s)
        self._blocks.release(st.blocks)
        st.blocks = []
        self._release_draft_locked(st)
        # lockdep: ok(single writer: the scheduler thread; submit-side readers only probe emptiness (GIL-atomic) and tolerate staleness)
        self._parked.append(_ParkedSession(req, "decode", [st], [key]))
        self._metrics.incr("sessions_parked")
        return True

    def _park_group(self, group):
        """Preempt a whole beam group: every live hypothesis spills its
        rows (rank-keyed), the group releases ALL its slots (spares
        included), and resume rebuilds ``order`` in the same rank order —
        selection tie-breaking stays bit-identical."""
        req = group.request
        m = self._model
        live = [(sid, self._slots[sid]) for sid in group.order]
        need = sum((st.cursor + m.block_size - 1) // m.block_size
                   for _, st in live)
        if need > m.num_blocks:
            return False
        keys = []
        with profiler.RecordEvent("decode::spill"):
            faults.fire("decode.spill")
            for rank, (sid, st) in enumerate(live):
                key = f"park:{req.id}:{rank}"
                rows = self._read_rows(st.row_map, st.cursor)
                toks = (list(req.prompt) + list(st.generated))[:st.cursor]
                if not self._tier.put(key, rows, st.cursor, tokens=toks):
                    for k in keys:
                        # lockdep: ok(HostKVTier is internally locked — decode.tier, a leaf under decode.blocks)
                        self._tier.discard(k)
                    return False
                keys.append(key)
        states = []
        for sid, st in live:
            self._slots[sid] = None
            self._pool.release(sid)
            self._blocks.release(st.blocks)
            st.blocks = []
            states.append(st)
        for sid in group.spare:
            self._pool.release(sid)
        group.spare = []
        group.order = []
        # lockdep: ok(single writer: the scheduler thread; submit-side readers only probe emptiness (GIL-atomic) and tolerate staleness)
        self._parked.append(
            _ParkedSession(req, "beam", states, keys, group=group))
        self._metrics.incr("sessions_parked")
        return True

    def _service_parked(self):
        """Resume parked sessions (FIFO, stop at the first that does not
        fit yet), then retry deferred admissions. Runs at the top of
        every iteration, before new picks — preempted work has first
        claim on freed capacity."""
        progressed = 0
        while self._parked:
            ps = self._parked[0]
            if ps.request.expired():
                # lockdep: ok(single writer: the scheduler thread; submit-side readers only probe emptiness (GIL-atomic) and tolerate staleness)
                self._parked.pop(0)
                self._drop_parked(ps, DeadlineExceededError(
                    "deadline expired while parked under arena pressure"))
                continue
            if not self._resume_session(ps):
                break
            # lockdep: ok(single writer: the scheduler thread; submit-side readers only probe emptiness (GIL-atomic) and tolerate staleness)
            self._parked.pop(0)
            progressed += 1
        if not self._parked and self._pending:
            pend, self._pending = self._pending, []
            for req in pend:
                if self._admit_one(req) == "deferred":
                    # lockdep: ok(single writer: the scheduler thread; submit-side readers only probe emptiness (GIL-atomic) and tolerate staleness)
                    self._pending.append(req)
                else:
                    progressed += 1
        return progressed

    def _drop_parked(self, ps, error):
        for key in ps.keys:
            # lockdep: ok(HostKVTier is internally locked — decode.tier, a leaf under decode.blocks)
            self._tier.discard(key)
        self._engine._tenant_unflight(ps.request.tenant)
        self._metrics.incr("deadline_missed"
                           if isinstance(error, DeadlineExceededError)
                           else "failed")
        ps.request.response._complete(error=error)
        self._metrics.observe_request(ps.request)

    def _resume_session(self, ps):
        """Re-admit one parked session. Returns False when capacity is
        still insufficient (caller retries next iteration); True when the
        session left the parked list — resumed, or terminally failed via
        an arena loss during re-injection."""
        m = self._model
        if ps.mode == "spec":
            s = self._pool.acquire()
            if s is None:
                return False
            with profiler.RecordEvent("decode::resume"):
                faults.fire("decode.resume")
                self._slots[s] = ps.states[0]
            self._metrics.incr("sessions_resumed")
            return True
        if ps.mode == "decode":
            st = ps.states[0]
            s = self._pool.acquire()
            if s is None:
                return False
            blocks = self._blocks.acquire_rows(st.cursor)
            if blocks is None:
                self._pool.release(s)
                return False
            st.blocks = blocks
            st.shared_len = 0
            self._rebuild_row_map(st)
            self._slots[s] = st
            with profiler.RecordEvent("decode::resume"):
                faults.fire("decode.resume")
                ok = self._inject_rows(st, ps.keys[0])
            if not ok:
                return True     # arena lost; session rejected with the rest
            self._metrics.incr("sessions_resumed")
            return True
        # beam: all live hypotheses come back together, in rank order
        group = ps.group
        got = []
        ok = True
        for st in ps.states:
            s = self._pool.acquire()
            blocks = (self._blocks.acquire_rows(st.cursor)
                      if s is not None else None)
            if s is None or blocks is None:
                if s is not None:
                    self._pool.release(s)
                ok = False
                break
            got.append((s, st, blocks))
        if not ok:
            for s, st, blocks in got:
                self._pool.release(s)
                self._blocks.release(blocks)
            return False
        group.order = []
        for s, st, blocks in got:
            st.blocks = blocks
            st.shared_len = 0
            self._rebuild_row_map(st)
            self._slots[s] = st
            group.order.append(s)
        # re-establish the group's width reservation, best-effort: forks
        # need spares, and admission must not steal them back first
        while len(group.order) + len(group.spare) < group.width:
            sid = self._pool.acquire()
            if sid is None:
                break
            group.spare.append(sid)
        with profiler.RecordEvent("decode::resume"):
            faults.fire("decode.resume")
            for rank, (s, st, blocks) in enumerate(got):
                if not self._inject_rows(st, ps.keys[rank]):
                    for key in ps.keys:
                        # lockdep: ok(HostKVTier is internally locked — decode.tier, a leaf under decode.blocks)
                        self._tier.discard(key)
                    return True     # arena lost; group rejected with the rest
        self._metrics.incr("sessions_resumed")
        return True

    def _inject_rows(self, st, key):
        """Re-inject a resumed session's KV rows ``[0:cursor)``. The tier
        entry is consumed if present and CRC-clean; otherwise (evicted or
        quarantined) the rows are RECOMPUTED from the committed tokens —
        byte-identical, because a causal KV row is a pure function of its
        token prefix. Returns False on arena loss (the donated inject
        failed; ``_arena_lost`` already rejected every slot, this session
        included)."""
        m = self._model
        n = st.cursor
        # lockdep: ok(HostKVTier is internally locked — decode.tier, a leaf under decode.blocks)
        ent = self._tier.pop(key)
        if ent is not None and ent.size_used == n:
            kv = ent.kv_rows
        else:
            toks = (list(st.request.prompt) + list(st.generated))[:n]
            fetches = self._run("prefill", self._prefill_feeds(toks))
            kvr = [np.asarray(f) for f in fetches[1:]]
            kv = [(kvr[2 * i][0, :n], kvr[2 * i + 1][0, :n])
                  for i in range(len(m.state_names))]
            self._metrics.incr("resume_replays")
        inj_rows = np.full((m.max_len,), m.rows, dtype="int64")
        inj_rows[:n] = st.row_map[:n]
        inj = {DecodeModel.INJ_ROWS: inj_rows}
        for i, (kn, vn) in enumerate(m.inject_kv_feeds):
            karr = np.zeros((1, m.max_len, m.hidden), "float32")
            varr = np.zeros((1, m.max_len, m.hidden), "float32")
            karr[0, :n] = kv[i][0]
            varr[0, :n] = kv[i][1]
            inj[kn] = karr
            inj[vn] = varr
        try:
            self._run("inject", inj)
        except Exception as e:
            self._arena_lost(f"resume inject failure: {e}")
            return False
        return True

    def _restore_from_tier(self, st):
        """Chunked admission's host-tier fast path: contiguous full
        prompt blocks just past the radix-shared prefix whose rows were
        written back at eviction re-INJECT instead of re-running chunk
        prefill — prefix-cache reach is bounded by host RAM, not HBM.
        Returns the prompt position covered through (0 = no extension);
        only applies from a block boundary, since a shared partial tail
        already occupies the next block index."""
        m = self._model
        bs = m.block_size
        if st.shared_len % bs != 0:
            return 0
        prompt = st.request.prompt
        hashes = block_hashes(prompt, bs)
        start = st.shared_len // bs
        ents = []
        idx = start
        while idx < len(hashes) and (idx + 1) * bs <= st.plen:
            ent = self._tier.get("blk:" + hashes[idx])
            if ent is None or ent.size_used != bs:
                break
            ents.append(ent)
            idx += 1
        if not ents:
            return 0
        lo, hi = start * bs, idx * bs
        inj_rows = np.full((m.max_len,), m.rows, dtype="int64")
        inj_rows[lo:hi] = st.row_map[lo:hi]
        inj = {DecodeModel.INJ_ROWS: inj_rows}
        for i, (kn, vn) in enumerate(m.inject_kv_feeds):
            karr = np.zeros((1, m.max_len, m.hidden), "float32")
            varr = np.zeros((1, m.max_len, m.hidden), "float32")
            for j, ent in enumerate(ents):
                p = lo + j * bs
                karr[0, p:p + bs] = ent.kv_rows[i][0]
                varr[0, p:p + bs] = ent.kv_rows[i][1]
            inj[kn] = karr
            inj[vn] = varr
        try:
            with profiler.RecordEvent("decode::inject"):
                self._run("inject", inj)
        except Exception as e:
            raise _ArenaInvalidError(str(e)) from e
        self._metrics.incr("tier_hits", len(ents))
        return hi

    # -- brownout ----------------------------------------------------------
    def _brownout_tick(self):
        """One severity evaluation per scheduler iteration. Occupancy
        saturates while anything is parked or deferred — the arena is
        over-subscribed even if the instantaneous row count dipped."""
        occ = self._blocks.stats()["occupancy"]
        if self._parked or self._pending:
            occ = 1.0
        qp = self._queue.pressure()
        self._brownout.step(occupancy=occ,
                            queue_seconds=qp["queue_seconds"],
                            deadline=qp["deadline"])
        n = len(self._brownout.transitions)
        if n > self._bt_seen:
            self._metrics.incr("brownout_transitions", n - self._bt_seen)
            self._bt_seen = n

    def _shed_confirmed(self):
        """Live pressure re-check guarding the two REJECT gates (L4
        shed, L3 beam cap). Severity is sampled by the scheduler tick
        and decays hysteretically, so right after a burst clears it can
        overstate the instantaneous state — degrading quality on a
        stale reading is harmless, but turning a request away is not.
        Read-only: no controller mutation, safe from the submit
        thread."""
        if self._parked or self._pending:
            return True
        try:
            occ = self._blocks.stats()["occupancy"]
        except Exception:
            occ = 0.0
        qp = self._queue.pressure()
        live = max(occ, qp["queue_seconds"], qp["deadline"])
        return live >= self._brownout.exit[self._brownout.level - 1]

    def _prefill_into(self, req, slot):
        m = self._model
        req.dispatch_time = time.perf_counter()
        self._admit_seq += 1
        # brownout L1/L2: shed OUTPUT-INVISIBLE quality first — committed
        # tokens are identical with or without speculation/draft-KV, only
        # the step count changes
        severity = self._brownout.level
        if req.draft_key is not None and severity < 2:
            # speculative: no TARGET arena footprint — verification
            # re-derives every KV it needs inside the (stateless) batch
            # prefill. With draft_kv the proposals get their own slot +
            # blocks on the DRAFT entry (O(1) per proposed token);
            # admission failure there degrades to replay proposals.
            st = _Slot(req, mode="spec")
            st.seq = self._admit_seq
            st.toks = list(req.prompt)
            st.sampling = req.sampling
            if req.grammar is not None:
                st.grammar = GrammarConstraint(req.grammar)
            self._slots[slot] = st
            if req.draft_kv and severity < 1:
                draft = self._engine._entries.get(req.draft_key)
                if draft is not None:
                    self._admit_draft_kv(st, draft)
            self._metrics.incr("admitted")
            self._metrics.tenant_incr("admitted", req.tenant)
            return
        prompt = req.prompt
        plen = len(prompt)
        if (m.chunk_tokens and "chunk" in self._entries
                and plen > m.chunk_tokens):
            blocks, shared_len = self._acquire_blocks(req)
            st = _Slot(req, mode="prefill")
            st.seq = self._admit_seq
            st.blocks = blocks
            st.shared_len = shared_len
            # the FINAL chunk always runs (it produces the last-position
            # logits), even when the radix served every block
            st.done = min(shared_len, plen - 1)
            self._rebuild_row_map(st)
            restored = self._restore_from_tier(st)
            if restored > st.done:
                st.done = min(restored, plen - 1)
            self._slots[slot] = st
            self._metrics.incr("admitted")
            self._metrics.tenant_incr("admitted", req.tenant)
            return
        key = prompt_key(prompt)
        cached = self._prefix.get(key)
        if cached is not None:
            kv_rows, logits_row = cached
            # hit/miss totals live on PrefixCache (one source, surfaced
            # by stats()); only the per-tenant series is a counter here
            self._metrics.tenant_incr("prefix_hits", req.tenant)
        else:
            t0 = time.perf_counter()
            with profiler.RecordEvent("decode::prefill"):
                faults.fire("decode.prefill")
                fetches = self._run("prefill", self._prefill_feeds(prompt))
            logits = np.asarray(fetches[0])          # [1, L, V]
            kv_rows = [np.asarray(f) for f in fetches[1:]]
            # copy: a view would pin the whole [1, L, V] prefill logits
            # buffer in the prefix cache for the life of the entry
            logits_row = np.array(logits[0, len(prompt) - 1])
            self._prefix.put(key, kv_rows, logits_row)
            self._metrics.observe_prefill(time.perf_counter() - t0)
        blocks, shared_len = self._acquire_blocks(req)
        st = _Slot(req, mode="decode")
        st.seq = self._admit_seq
        st.blocks = blocks
        st.shared_len = shared_len
        self._rebuild_row_map(st)
        if shared_len < plen:
            # inject ONLY the non-shared suffix: shared blocks already
            # hold byte-identical rows (same tokens -> same prefix ->
            # same KV bytes)
            inj_rows = np.full((m.max_len,), m.rows, dtype="int64")
            inj_rows[shared_len:plen] = st.row_map[shared_len:plen]
            inj = {DecodeModel.INJ_ROWS: inj_rows}
            for i, (kn, vn) in enumerate(m.inject_kv_feeds):
                inj[kn] = kv_rows[2 * i]
                inj[vn] = kv_rows[2 * i + 1]
            try:
                with profiler.RecordEvent("decode::inject"):
                    faults.fire("decode.inject")
                    self._run("inject", inj)
            except Exception as e:
                raise _ArenaInvalidError(str(e)) from e

        def host_rows(start, stop):
            return [(np.array(kv_rows[2 * i][0, start:stop]),
                     np.array(kv_rows[2 * i + 1][0, start:stop]))
                    for i in range(len(m.state_names))]

        self._blocks.register_prompt_blocks(blocks, prompt,
                                            host_rows=host_rows)
        st.cursor = plen
        self._slots[slot] = st
        self._metrics.incr("admitted")
        self._metrics.tenant_incr("admitted", req.tenant)
        if req.beam is not None:
            st.mode = "beam"
            self._begin_beam(slot, logits_row)
            return
        st.sampling = req.sampling
        if req.grammar is not None:
            st.grammar = GrammarConstraint(req.grammar)
        first = self._choose_token(st, logits_row, device_masked=False)
        st.last_token = first
        st.generated = [first]
        # the prefill's first token: counted apart from generated_tokens
        # so tokens_per_step stays a decode-step quantity (<= S)
        self._metrics.incr("prefill_tokens")
        self._metrics.tenant_incr("tokens", req.tenant)
        if self._finished(st):
            self._retire(slot)

    def _prefill_feeds(self, prompt):
        m = self._model
        toks = np.zeros((1, m.max_len), "int64")
        toks[0, :len(prompt)] = prompt
        pos = np.arange(m.max_len, dtype="int64")[None]
        bias = np.triu(np.full((m.max_len, m.max_len), NEG_INF, "float32"),
                       k=1)[None]
        return {DecodeModel.PRE_TOKENS: toks,
                DecodeModel.PRE_POSITIONS: pos,
                DecodeModel.PRE_BIAS: bias}

    # -- chunked prefill ---------------------------------------------------
    def _advance_prefills(self):
        """Process ONE budgeted chunk for ONE prefilling slot
        (round-robin): the per-iteration prompt work is bounded by
        ``chunk_tokens``, which is the fairness contract — in-flight
        decode slots stall for at most one chunk's compute per admitted
        long prompt."""
        m = self._model
        pref = [s for s in range(m.slots)
                if self._slots[s] is not None
                and self._slots[s].mode == "prefill"]
        if not pref:
            return 0
        # brownout L2+: halve the chunk budget (one chunk every OTHER
        # iteration) — admitted long prompts land later, but in-flight
        # decode slots keep their step cadence under pressure
        if self._brownout.level >= 2:
            self._chunk_throttle = not self._chunk_throttle
            if self._chunk_throttle:
                return 0
        s = pref[self._pref_rr % len(pref)]
        self._pref_rr += 1
        st = self._slots[s]
        req = st.request
        if req.expired():
            self._reject_in_flight(req, DeadlineExceededError(
                f"deadline expired during chunked prefill after "
                f"{st.done}/{st.plen} tokens"), slot=s)
            return 1
        C, L, R = m.chunk_tokens, m.max_len, m.rows
        start = st.done
        stop = min(start + C, st.plen)
        real = stop - start
        toks = np.zeros((1, C), "int64")
        toks[0, :real] = req.prompt[start:stop]
        pos = np.zeros((1, C), "int64")
        pos[0, :real] = np.arange(start, stop)
        bias = np.full((1, C, L), NEG_INF, "float32")
        bias[0, :real] = np.where(
            np.arange(L)[None, :] <= (start + np.arange(real))[:, None],
            np.float32(0.0), np.float32(NEG_INF))
        wrows = np.full((C,), R, dtype="int64")
        for c in range(real):
            p = start + c
            if p >= st.shared_len:   # never rewrite radix-shared rows
                wrows[c] = st.row_map[p]
        t0 = time.perf_counter()
        try:
            with profiler.RecordEvent("decode::chunk"):
                faults.fire("decode.chunk")
                fetches = self._run("chunk", {
                    DecodeModel.CHU_TOKENS: toks,
                    DecodeModel.CHU_POSITIONS: pos,
                    DecodeModel.CHU_BIAS: bias,
                    DecodeModel.CHU_ROWS: st.row_map,
                    DecodeModel.CHU_WRITE_ROWS: wrows,
                })
        except Exception as e:
            self._arena_lost(f"chunk-prefill failure: {e}")
            return 1
        self._metrics.observe_chunk(real, time.perf_counter() - t0)
        st.done = stop
        if st.done < st.plen:
            return 1
        logits = np.asarray(fetches[0])              # [1, C, V]
        self._blocks.register_prompt_blocks(st.blocks, req.prompt)
        st.cursor = st.plen
        if req.beam is not None:
            st.mode = "beam"
            try:
                self._begin_beam(s, np.array(logits[0, real - 1]))
            except _ArenaInvalidError as e:
                self._arena_lost(f"beam fork inject failure: {e}")
            return 1
        st.mode = "decode"
        st.sampling = req.sampling
        if req.grammar is not None:
            st.grammar = GrammarConstraint(req.grammar)
        first = self._choose_token(st, logits[0, real - 1],
                                   device_masked=False)
        st.last_token = first
        st.generated = [first]
        self._metrics.incr("prefill_tokens")
        self._metrics.tenant_incr("tokens", req.tenant)
        if self._finished(st):
            self._retire(s)
        return 1

    # -- speculative decoding ----------------------------------------------
    def _advance_spec(self):
        """One draft-propose + target-verify cycle per speculative slot.
        The draft greedily proposes up to ``spec_k`` tokens (one
        stateless draft-prefill forward each); the target verifies ALL
        of them in ONE batch-prefill forward — logits at position
        ``n-1+j`` depend only on tokens ``<= n-1+j`` (causal mask,
        exact-zero padding), so each emitted token equals what
        target-only greedy decode would emit: bit-identical by
        construction, fewer target steps per token by measurement."""
        m = self._model
        progressed = 0
        for s in range(m.slots):
            st = self._slots[s]
            if st is None or st.mode != "spec":
                continue
            progressed += 1
            req = st.request
            if req.expired():
                self._reject_in_flight(req, DeadlineExceededError(
                    "deadline expired mid-speculation after "
                    f"{len(st.generated)} tokens"), slot=s)
                continue
            draft = self._engine._entries.get(req.draft_key)
            if draft is None:
                self._reject_in_flight(req, RequestError(
                    f"draft model {'@'.join(req.draft_key)} left the "
                    "registry mid-generation"), slot=s)
                continue
            n = len(st.toks)
            k = min(req.spec_k, req.max_new - len(st.generated),
                    m.max_len - n, draft.model.max_len - n)
            k = max(k, 0)
            # both forwards are STATELESS prefills (donation off): a
            # failure loses nothing but this cycle, so it is a
            # request-attributed failure — never a dead scheduler
            # thread, never an arena loss. (This also contains the
            # cross-entry read: draft._run from this thread may race a
            # draft-side breaker relaunch, whose builder contract makes
            # any observed executable content-identical — and any torn
            # state it could still surface lands here, on one request.)
            try:
                props = None
                if st.d_slot is not None and k > 0:
                    props = self._draft_propose_kv(st, draft, k)
                if props is None:
                    props = []
                    dtoks = list(st.toks)
                    for _ in range(k):
                        with profiler.RecordEvent("decode::spec_draft"):
                            fetches = draft._run(
                                "prefill", draft._prefill_feeds(dtoks))
                        nxt = int(np.argmax(
                            np.asarray(fetches[0])[0, len(dtoks) - 1]))
                        props.append(nxt)
                        dtoks.append(nxt)
                    self._metrics.incr("spec_draft_steps", k)
                else:
                    dtoks = list(st.toks) + props
                self._metrics.incr("spec_proposed_tokens", k)
                t0 = time.perf_counter()
                with profiler.RecordEvent("decode::spec_verify"):
                    faults.fire("decode.verify")
                    fetches = self._run("prefill",
                                        self._prefill_feeds(dtoks))
            except Exception as e:
                self._reject_in_flight(req, RequestError(
                    f"request {req.id} failed in speculative cycle: "
                    f"{e}"), slot=s)
                continue
            self._metrics.incr("spec_target_steps")
            self._metrics.observe_prefill(time.perf_counter() - t0)
            logits = np.asarray(fetches[0])          # [1, L, V]
            finished = False
            accepted_n = 0
            for j in range(k + 1):
                # COMMITTED COUPLING: the target always derives ITS OWN
                # token from its (masked, sampled) committed stream at
                # this position; a proposal is accepted iff it equals
                # that token. The realized stream is therefore
                # bit-identical to target-only decode in EVERY policy —
                # greedy acceptance is the temperature-0 special case.
                t = self._choose_token(st, logits[0, n - 1 + j],
                                       device_masked=False)
                st.generated.append(t)
                st.toks.append(t)
                st.last_token = t
                self._metrics.incr("spec_emitted_tokens")
                self._metrics.tenant_incr("tokens", req.tenant)
                if j < k and props[j] == t:
                    self._metrics.incr("spec_accepted_tokens")
                    accepted_n += 1
                    accepted = True
                else:
                    accepted = False
                if (len(st.generated) >= req.max_new
                        or (m.eos_id is not None and t == m.eos_id)
                        or len(st.toks) >= m.max_len):
                    finished = True
                    break
                if not accepted:
                    break   # t was the correction token: later positions
                            # saw the wrong draft prefix
            st.cursor = len(st.toks)
            if st.d_slot is not None:
                # roll the draft cursor back to the first position whose
                # written KV row may disagree with the committed tokens
                # (the rejected proposal's slot onward); the next
                # cycle's catch-up rewrites from there
                st.d_cursor = min(st.d_cursor, n + accepted_n)
            if finished:
                self._retire(s)
        return progressed

    # -- draft-KV speculative slots ---------------------------------------
    def _admit_draft_kv(self, st, draft):
        """Give a speculative slot its own KV slot + blocks on the DRAFT
        entry and prefill the prompt into them ONCE; every later
        proposal is then one [S,1] draft decode step instead of a
        whole-prompt replay. Draft blocks are deliberately never
        radix-registered: the draft arena shares no partial tails, so
        the proposal hot path can never trigger a COW there. Any
        failure falls back to replay proposals (counted), never fails
        the request."""
        if not draft._draft_ok or not draft._draft_pinned:
            return
        prompt = st.request.prompt
        d_slot = None
        blocks = None
        try:
            with draft._draft_lock:
                d_slot = draft._pool.acquire()
                if d_slot is None:
                    self._metrics.incr("spec_draft_kv_fallbacks")
                    return
                blocks, _shared = draft._blocks.acquire_for_prompt(prompt)
                if blocks is None:
                    draft._pool.release(d_slot)
                    self._metrics.incr("spec_draft_kv_fallbacks")
                    return
                with profiler.RecordEvent("decode::spec_draft_prefill"):
                    fetches = draft._run("prefill",
                                         draft._prefill_feeds(prompt))
                kv_rows = [np.asarray(f) for f in fetches[1:]]
                st.d_entry = draft
                st.d_slot = d_slot
                st.d_blocks = blocks
                st.d_row_map = None
                self._rebuild_draft_row_map(draft, st)
                dm = draft.model
                plen = len(prompt)
                inj_rows = np.full((dm.max_len,), dm.rows, dtype="int64")
                inj_rows[:plen] = st.d_row_map[:plen]
                inj = {DecodeModel.INJ_ROWS: inj_rows}
                for i, (kn, vn) in enumerate(dm.inject_kv_feeds):
                    inj[kn] = kv_rows[2 * i]
                    inj[vn] = kv_rows[2 * i + 1]
                with profiler.RecordEvent("decode::spec_draft_inject"):
                    draft._run("inject", inj)
                st.d_cursor = plen
                self._metrics.incr("spec_draft_kv_prefills")
        except Exception:
            # the inject is DONATED on the draft arena: poison the entry
            # (all draft-KV users revert to replay) rather than trusting
            # an undefined arena
            draft._draft_ok = False
            if st.d_entry is draft:
                st.d_entry = None
                st.d_slot = None
                st.d_blocks = None
                st.d_row_map = None
                st.d_cursor = 0
            if blocks is not None:
                draft._blocks.release(blocks)
            if d_slot is not None:
                draft._pool.release(d_slot)
            self._metrics.incr("spec_draft_kv_fallbacks")

    def _rebuild_draft_row_map(self, draft, st):
        dm = draft.model
        bs = dm.block_size
        if st.d_row_map is None:
            st.d_row_map = np.zeros(dm.max_len, dtype="int64")
        for i, b in enumerate(st.d_blocks):
            lo = i * bs
            hi = min(lo + bs, dm.max_len)
            st.d_row_map[lo:hi] = b.row0 + np.arange(hi - lo)

    def _release_draft(self, st):
        """Return a spec slot's draft-side footprint (caller holds the
        draft lock, or knows no other thread can touch this state)."""
        draft = st.d_entry
        if draft is None:
            return
        if st.d_blocks:
            draft._blocks.release(st.d_blocks)
        if st.d_slot is not None:
            draft._pool.release(st.d_slot)
        st.d_entry = None
        st.d_slot = None
        st.d_blocks = None
        st.d_row_map = None
        st.d_cursor = 0

    def _release_draft_locked(self, st):
        draft = st.d_entry
        if draft is None:
            return
        with draft._draft_lock:
            self._release_draft(st)

    def _draft_propose_kv(self, st, draft, k):
        """Greedy draft proposals in O(1) decode steps per token from
        the draft's own arena slot. Catch-up first feeds every committed
        token whose draft KV row is not yet written (at most the last
        cycle's correction + bonus positions) — the final catch-up
        step's logits ARE the first proposal — then each further
        proposal is one more draft decode step. Returns the k proposals
        (bit-identical to replay-prefill proposals by the decode ≡
        prefill invariant applied to the draft entry), or None to make
        the caller fall back to replay."""
        if not draft._draft_ok:
            self._release_draft_locked(st)
            self._metrics.incr("spec_draft_kv_fallbacks")
            return None
        n = len(st.toks)
        props = []
        with draft._draft_lock:
            cur = None
            for p in range(min(st.d_cursor, n - 1), n):
                cur = self._draft_step_kv(st, draft, st.toks[p], p,
                                          write=p >= st.d_cursor)
                if cur is None:
                    return None
                st.d_cursor = max(st.d_cursor, p + 1)
            props.append(int(np.argmax(cur)))
            for j in range(1, k):
                cur = self._draft_step_kv(st, draft, props[j - 1],
                                          n + j - 1, write=True)
                if cur is None:
                    return None
                st.d_cursor = max(st.d_cursor, n + j)
                props.append(int(np.argmax(cur)))
        return props

    def _draft_step_kv(self, st, draft, token, p, write):
        """ONE draft decode step: feed ``token`` at position ``p`` into
        the spec slot's draft arena slot (writing KV row p when asked;
        rewriting an already-correct row is a byte-identical no-op) and
        return the [V] logits row. Returns None after releasing the
        draft footprint when the draft pool is exhausted or the draft
        arena died — the caller reverts to replay proposals."""
        dm = draft.model
        if write:
            blocks, _nb, cow = draft._blocks.ensure_appendable(
                st.d_blocks, p)
            if blocks is None:
                self._release_draft(st)
                self._metrics.incr("spec_draft_kv_fallbacks")
                return None
            assert cow is None, "draft blocks are never radix-shared"
            st.d_blocks = blocks
            if _nb is not None:
                self._rebuild_draft_row_map(draft, st)
        S, L, R = dm.slots, dm.max_len, dm.rows
        tok = np.zeros((S, 1), "int64")
        pos = np.zeros((S, 1), "int64")
        bias = np.full((S, 1, L), NEG_INF, "float32")
        rows = np.zeros((S, L), "int64")
        wrows = np.full((S,), R, dtype="int64")
        s = st.d_slot
        tok[s, 0] = int(token)
        pos[s, 0] = p
        bias[s, 0, :p + 1] = 0.0
        rows[s] = st.d_row_map
        if write:
            b = st.d_blocks[p // dm.block_size]
            wrows[s] = b.row0 + p % dm.block_size
        feeds = {DecodeModel.DEC_TOKEN: tok, DecodeModel.DEC_POSITION: pos,
                 DecodeModel.DEC_BIAS: bias,
                 DecodeModel.DEC_ROWS: rows.reshape(-1),
                 DecodeModel.DEC_WRITE_ROWS: wrows}
        if dm.logits_mask:
            feeds[DecodeModel.DEC_MASK] = np.zeros(
                (S, 1, dm.vocab_size), "float32")
        try:
            with profiler.RecordEvent("decode::spec_draft_kv"):
                fetches = draft._run("step", feeds)
        except Exception:
            # donated call on the DRAFT arena failed: poison the draft
            # for every user; this request reverts to replay proposals
            draft._draft_ok = False
            self._release_draft(st)
            self._metrics.incr("spec_draft_kv_fallbacks")
            return None
        if write:
            draft._blocks.note_append(st.d_blocks[p // dm.block_size])
        self._metrics.incr("spec_draft_kv_steps")
        return np.asarray(fetches[0])[s, 0]

    # -- the decode iteration ---------------------------------------------
    def _arena_lost(self, why):
        """A donated call failed: the arena is undefined. Fail every
        in-flight sequence loudly, drive the breaker, reset."""
        self._metrics.incr("step_failures")
        self._probe_relaunched = False
        if self._breaker is not None:
            self._breaker_event(self._breaker.record_failure())
        self._reject_all_slots(lambda r: ReplicaLostError(
            f"request {r.id} lost to {why}"))
        self._reset_arenas()

    def _reject_all_slots(self, make_error):
        """Fail every in-flight sequence loudly — ONE completion per
        request, even when a beam request holds several slots."""
        groups = []
        for s, st in enumerate(list(self._slots)):
            if st is None:
                continue
            if st.beam is not None:
                if st.beam not in groups:
                    groups.append(st.beam)
                continue
            self._reject_in_flight(st.request, make_error(st.request),
                                   slot=s)
        for g in groups:
            self._reject_beam_group(g, make_error(g.request))

    def _apply_cow(self, st, cow):
        """Copy-on-write landed a fresh block: re-inject the shared
        partial's retained host rows into it, then remap the slot."""
        m = self._model
        u = cow.size_used
        inj_rows = np.full((m.max_len,), m.rows, dtype="int64")
        inj_rows[:u] = cow.block.row0 + np.arange(u)
        inj = {DecodeModel.INJ_ROWS: inj_rows}
        for i, (kn, vn) in enumerate(m.inject_kv_feeds):
            karr = np.zeros((1, m.max_len, m.hidden), "float32")
            varr = np.zeros((1, m.max_len, m.hidden), "float32")
            karr[0, :u] = cow.host_rows[i][0]
            varr[0, :u] = cow.host_rows[i][1]
            inj[kn] = karr
            inj[vn] = varr
        with profiler.RecordEvent("decode::cow_inject"):
            self._run("inject", inj)
        self._rebuild_row_map(st)

    # -- generation policy (host-side selection over fetched logits) ------
    def _choose_token(self, st, logits_row, device_masked):
        """The ONE token-selection point for non-beam paths: grammar
        mask (host-applied unless the decode program already added the
        DEC_MASK feed — bit-identical either way, float32 add on both
        sides), then the committed-stream sampler or plain argmax. The
        step index is the absolute emitted-token index, so the sampled
        stream replays bit-exactly for ANY admission order, batchmates,
        or slot assignment."""
        row = np.asarray(logits_row, dtype=np.float32).reshape(-1)
        if st.grammar is not None and not device_masked:
            row = row + st.grammar.mask()
        if st.sampling is not None and not st.sampling.greedy:
            faults.fire("decode.sample")
            t = sample_token(row, st.sampling, len(st.generated))
            self._metrics.incr("sampled_tokens")
        else:
            t = int(np.argmax(row))
        if st.grammar is not None:
            st.grammar.advance(t)
            self._metrics.incr("grammar_steps")
        return t

    # -- beam search (COW forks over the block arena) ----------------------
    def _begin_beam(self, s, logits_row):
        """First selection of a freshly prefilled beam request: the seed
        hypothesis (empty continuation, score 0) expands into up to
        ``width`` live beams — the seed slot hosts the top survivor in
        place, the rest fork from it."""
        st = self._slots[s]
        req = st.request
        group = _BeamGroup(req)
        st.beam = group
        st.score = 0.0
        if req.grammar is not None:
            st.grammar = GrammarConstraint(req.grammar)
        group.order = [s]
        # claim the rest of the group's row reservation up front (the
        # admission round budgeted width rows for this pick)
        for _ in range(group.width - 1):
            sid = self._pool.acquire()
            if sid is None:
                break
            group.spare.append(sid)
        self._metrics.incr("beam_requests")
        try:
            row = np.asarray(logits_row, dtype=np.float32).reshape(-1)
            if st.grammar is not None:
                row = row + st.grammar.mask()
            self._commit_beam_selection(group, [row])
        except _ArenaInvalidError:
            raise               # admission's arena handler owns cleanup
        except Exception as e:
            self._reject_beam_group(group, RequestError(
                f"request {req.id} failed in first beam selection: {e}"))

    def _commit_beam_selection(self, group, rows):
        """ONE beam step's bookkeeping: run the committed selection rule
        over the live hypotheses' (masked) logits rows, divert EOS and
        length-exhausted continuations to ``finished``, release pruned
        parents, keep each parent's top continuation in its slot, fork
        the rest (refcount++ + private tail copy), and re-assert block
        row conservation. Returns False when the group retired or
        failed (its slots are gone)."""
        m = self._model
        req = group.request
        live_ids = list(group.order)
        live = [self._slots[s] for s in live_ids]
        room = group.width - len(group.finished)
        sel_live, sel_fin = beam_select(
            [b.score for b in live], rows, room, m.eos_id)
        for p, t, sc in sel_fin:
            group.finished.append((live[p].generated + [t], sc))
        survivors = []
        for p, t, sc in sel_live:
            n2 = len(live[p].generated) + 1
            if n2 >= req.max_new or live[p].plen + n2 >= m.max_len:
                group.finished.append((live[p].generated + [t], sc))
            else:
                survivors.append((p, t, sc))
        keep = {p for p, _t, _s in survivors}
        for i, sid in enumerate(live_ids):
            if i not in keep:
                self._release_beam_slot(sid, to_spare=True)
                self._metrics.incr("beam_prunes")
        # slot assignment preserves RANK order in group.order; children
        # fork BEFORE their parent's in-place update (deferred) so every
        # fork sees the parent's pre-step tokens/grammar/score
        new_order = []
        taken = set()
        deferred = []
        for p, t, sc in survivors:
            if p not in taken:
                taken.add(p)
                new_order.append(live_ids[p])
                deferred.append((live[p], t, sc))
            else:
                try:
                    child = self._fork_beam(group, live[p], t, sc)
                except _ArenaInvalidError:
                    raise
                except Exception as e:
                    self._reject_beam_group(group, RequestError(
                        f"request {req.id} beam fork failed: {e}"))
                    return False
                new_order.append(child)
                self._metrics.incr("beam_forks")
        for st, t, sc in deferred:
            st.generated = st.generated + [t]
            st.last_token = t
            st.score = sc
            if st.grammar is not None:
                st.grammar.advance(t)
        group.order = new_order
        self._blocks.check_conservation()
        if len(group.finished) >= group.width or not new_order:
            self._retire_beam(group)
            return False
        return True

    def _fork_beam(self, group, parent, token, score):
        """COW-fork one live hypothesis: second owner on the parent's
        full blocks, a private tail block filled by a device row copy
        (arena scope read -> inject), and a fresh slot carrying the
        forked host state."""
        m = self._model
        child_blocks, nb, src = self._blocks.fork_blocks(
            parent.blocks, parent.cursor)
        if child_blocks is None:
            raise RuntimeError("block pool exhausted forking a beam")
        slot = group.spare.pop() if group.spare else self._pool.acquire()
        if slot is None:
            self._blocks.release(child_blocks)
            raise RuntimeError("slot pool exhausted forking a beam")
        if nb is not None:
            u = nb.size_used
            inj_rows = np.full((m.max_len,), m.rows, dtype="int64")
            inj_rows[:u] = nb.row0 + np.arange(u)
            inj = {DecodeModel.INJ_ROWS: inj_rows}
            for i, (kn_s, vn_s) in enumerate(m.state_names):
                kn, vn = m.inject_kv_feeds[i]
                karr = np.zeros((1, m.max_len, m.hidden), "float32")
                varr = np.zeros((1, m.max_len, m.hidden), "float32")
                karr[0, :u] = np.asarray(
                    self._scope.find_var(kn_s))[src.row0:src.row0 + u]
                varr[0, :u] = np.asarray(
                    self._scope.find_var(vn_s))[src.row0:src.row0 + u]
                inj[kn] = karr
                inj[vn] = varr
            try:
                with profiler.RecordEvent("decode::beam_fork_inject"):
                    self._run("inject", inj)
            except Exception as e:
                raise _ArenaInvalidError(str(e)) from e
        st = _Slot(group.request, mode="beam")
        st.beam = group
        st.blocks = child_blocks
        st.plen = parent.plen
        st.shared_len = parent.shared_len
        st.cursor = parent.cursor
        st.last_token = int(token)
        st.generated = parent.generated + [int(token)]
        st.score = score
        if parent.grammar is not None:
            st.grammar = parent.grammar.fork().advance(token)
        self._rebuild_row_map(st)
        self._slots[slot] = st
        return slot

    def _release_beam_slot(self, sid, to_spare=False):
        st = self._slots[sid]
        self._slots[sid] = None
        if to_spare and st is not None and st.beam is not None:
            st.beam.spare.append(sid)   # keep the group's reservation
        else:
            self._pool.release(sid)
        if st.blocks:
            self._blocks.release(st.blocks)

    def _release_group_slots(self, group):
        for sid, st in enumerate(self._slots):
            if st is not None and st.beam is group:
                self._release_beam_slot(sid)
        for sid in group.spare:
            self._pool.release(sid)
        group.spare = []

    def _retire_beam(self, group):
        self._release_group_slots(group)
        req = group.request
        self._engine._tenant_unflight(req.tenant)
        ranked = beam_finished_ranking(group.finished)
        if not ranked:
            req.response._complete(error=RequestError(
                f"request {req.id}: beam search finished no hypothesis"))
            self._metrics.incr("failed")
            self._metrics.observe_request(req)
            return
        req.response._complete(outputs={
            "tokens": np.asarray(ranked[0][0], dtype="int64"),
            "beams": [{"tokens": np.asarray(t, dtype="int64"),
                       "score": float(sc)} for t, sc in ranked],
        })
        self._metrics.incr("completed")
        self._metrics.incr("retired")
        self._metrics.incr("beam_finished", len(ranked))
        self._metrics.tenant_incr("completed", req.tenant)
        self._metrics.observe_request(req)

    def _reject_beam_group(self, group, error):
        """Fail one beam request as a UNIT: release every slot the group
        still holds, then complete its single response once. (The
        arena-failure path may already have completed it through the
        admitting request's handler — the done() guard keeps the
        write-once future honest.)"""
        self._release_group_slots(group)
        req = group.request
        if req.response.done():
            return
        self._engine._tenant_unflight(req.tenant)
        self._metrics.incr(
            "deadline_missed" if isinstance(error, DeadlineExceededError)
            else "failed")
        req.response._complete(error=error)
        self._metrics.observe_request(req)

    def _step(self):
        m = self._model
        S, L, R = m.slots, m.max_len, m.rows
        tok = np.zeros((S, 1), "int64")
        pos = np.zeros((S, 1), "int64")
        bias = np.full((S, 1, L), NEG_INF, "float32")
        rows = np.zeros((S, L), "int64")
        wrows = np.full((S,), R, dtype="int64")
        dmask = (np.zeros((S, 1, m.vocab_size), "float32")
                 if m.logits_mask else None)
        active = []
        groups = []     # beam groups with a live slot this step
        for s in range(S):
            st = self._slots[s]
            if st is None or st.mode not in ("decode", "beam"):
                continue
            # make the cursor position writable: allocate a fresh block
            # when it opens a new chunk, COW when it lands in a SHARED
            # partial tail (divergence), unregister an exclusively-owned
            # partial before mutating it
            try:
                blocks, _nb, cow = self._blocks.ensure_appendable(
                    st.blocks, st.cursor)
            except RuntimeError as e:
                # pool invariant violation: loud per-request failure,
                # never a dead scheduler thread
                if st.mode == "beam":
                    self._reject_beam_group(st.beam, RequestError(
                        f"request {st.request.id} failed: {e}"))
                else:
                    self._reject_in_flight(st.request, RequestError(
                        f"request {st.request.id} failed: {e}"), slot=s)
                continue
            if blocks is None:
                # mid-generation exhaustion: park the session (spill to
                # the host tier, resume byte-identically later) instead
                # of failing; loud only when the host tier cannot absorb
                # it or the session can never be resumed
                self._metrics.incr("blocks_exhausted")
                parked = (self._park_group(st.beam) if st.mode == "beam"
                          else self._park_slot(s))
                if parked:
                    self._metrics.incr("blocks_parked_total")
                    continue
                self._metrics.incr("blocks_failed_total")
                err = RequestError(
                    f"request {st.request.id} failed: block pool "
                    "exhausted mid-generation and the host KV tier "
                    "cannot absorb the session")
                if st.mode == "beam":
                    self._reject_beam_group(st.beam, err)
                else:
                    self._reject_in_flight(st.request, err, slot=s)
                continue
            st.blocks = blocks
            if cow is not None:
                try:
                    self._apply_cow(st, cow)
                except Exception as e:
                    # the COW re-inject is a DONATED call: its failure
                    # invalidates the whole arena, not one request
                    self._arena_lost(f"copy-on-write inject failure: {e}")
                    return
            elif _nb is not None:
                self._rebuild_row_map(st)
            if st.mode == "beam":
                if st.beam not in groups:
                    groups.append(st.beam)
            else:
                active.append(s)
            tok[s, 0] = st.last_token
            pos[s, 0] = st.cursor
            bias[s, 0, :st.cursor + 1] = 0.0
            rows[s] = st.row_map
            wrows[s] = self._row_of(st, st.cursor)
            if dmask is not None and st.grammar is not None:
                # the grammar's next-token constraint rides in as DATA —
                # same compiled program for every request, zero retraces
                dmask[s, 0] = st.grammar.mask()
        if not active and not groups:
            return
        feeds = {DecodeModel.DEC_TOKEN: tok, DecodeModel.DEC_POSITION: pos,
                 DecodeModel.DEC_BIAS: bias,
                 DecodeModel.DEC_ROWS: rows.reshape(-1),
                 DecodeModel.DEC_WRITE_ROWS: wrows}
        if dmask is not None:
            feeds[DecodeModel.DEC_MASK] = dmask
        t0 = time.perf_counter()
        try:
            with profiler.RecordEvent("decode::step"):
                faults.fire("decode.step")
                fetches = self._run("step", feeds)
        except Exception as e:
            # a failed donated call leaves the arena undefined: every
            # in-flight sequence is lost (failed loudly), the batch-level
            # outcome drives the breaker, and the arena resets
            self._arena_lost(f"decode-step failure: {e}")
            return
        if self._breaker is not None:
            self._breaker_event(self._breaker.record_success())
        logits = np.asarray(fetches[0])              # [S, 1, V]
        now = time.perf_counter()
        stepped = len(active)
        for s in active:
            st = self._slots[s]
            self._blocks.note_append(
                st.blocks[st.cursor // m.block_size])
            nxt = self._choose_token(st, logits[s, 0],
                                     device_masked=m.logits_mask)
            st.generated.append(nxt)
            st.cursor += 1
            st.last_token = nxt
            self._metrics.tenant_incr("tokens", st.request.tenant)
            # finished wins over expired: the device already paid for a
            # COMPLETE generation, deliver it (the prefill fast path
            # retires without an expiry check — same policy)
            if self._finished(st):
                self._retire(s)
            elif st.request.expired(now):
                self._reject_in_flight(st.request, DeadlineExceededError(
                    "deadline expired mid-generation after "
                    f"{len(st.generated)} tokens"), slot=s)
        for group in groups:
            if group.request.response.done():
                continue    # rejected while another slot was being fed
            if not group.order:
                continue    # parked while another slot was being fed
            # commit this step's KV append per live hypothesis, collect
            # its (device-masked) logits row in HYPOTHESIS order, then
            # run the shared selection rule once for the whole group
            rows_l = []
            for sid in group.order:
                bst = self._slots[sid]
                self._blocks.note_append(
                    bst.blocks[bst.cursor // m.block_size])
                bst.cursor += 1
                row = np.asarray(logits[sid, 0],
                                 dtype=np.float32).reshape(-1)
                if bst.grammar is not None and dmask is None:
                    row = row + bst.grammar.mask()
                rows_l.append(row)
            stepped += len(rows_l)
            try:
                alive = self._commit_beam_selection(group, rows_l)
            except _ArenaInvalidError as e:
                self._arena_lost(f"beam fork inject failure: {e}")
                return
            if alive and group.request.expired(now):
                self._reject_beam_group(group, DeadlineExceededError(
                    "deadline expired mid-generation after "
                    f"{len(group.finished)} finished hypotheses"))
        self._metrics.observe_step(stepped, stepped,
                                   time.perf_counter() - t0)

    def _finished(self, st):
        m = self._model
        return (len(st.generated) >= st.request.max_new
                or (m.eos_id is not None and st.last_token == m.eos_id)
                or st.cursor >= m.max_len)

    def _retire(self, slot):
        st = self._slots[slot]
        self._slots[slot] = None
        self._pool.release(slot)
        if st.blocks:
            self._blocks.release(st.blocks)
        self._release_draft_locked(st)
        req = st.request
        self._engine._tenant_unflight(req.tenant)
        req.response._complete(outputs={
            "tokens": np.asarray(st.generated, dtype="int64"),
        })
        self._metrics.incr("completed")
        self._metrics.incr("retired")
        self._metrics.tenant_incr("completed", req.tenant)
        self._metrics.observe_request(req)

    def _reject_in_flight(self, req, error, slot=None):
        if slot is not None:
            st = self._slots[slot]
            self._slots[slot] = None
            self._pool.release(slot)
            if st is not None and st.blocks:
                self._blocks.release(st.blocks)
            if st is not None:
                self._release_draft_locked(st)
        self._engine._tenant_unflight(req.tenant)
        self._metrics.incr(
            "deadline_missed" if isinstance(error, DeadlineExceededError)
            else "failed")
        req.response._complete(error=error)
        self._metrics.observe_request(req)

    # -- reference path ----------------------------------------------------
    def offline_decode(self, prompt, max_new, sampling=None, grammar=None):
        """Offline whole-sequence reference: re-run the full causal
        prefill forward per generated token (no KV cache, no slots) with
        identical finish rules and the SAME committed selection policy
        (host-masked grammar + committed-stream sampling). The
        bit-exactness tests compare continuous output — in EVERY mode
        (paged decode, chunked prefill, speculative, sampled,
        constrained) — against THIS."""
        m = self._model
        toks = list(prompt)
        out = []
        g = GrammarConstraint(grammar) if grammar is not None else None
        for _ in range(int(max_new)):
            t = len(toks) - 1
            fetches = self._run("prefill", self._prefill_feeds(toks))
            row = np.asarray(fetches[0])[0, t].astype(np.float32)
            if g is not None:
                row = row + g.mask()
            if sampling is not None and not sampling.greedy:
                nxt = sample_token(row, sampling, len(out))
            else:
                nxt = int(np.argmax(row))
            if g is not None:
                g.advance(nxt)
            out.append(nxt)
            toks.append(nxt)
            if m.eos_id is not None and nxt == m.eos_id:
                break
            if len(toks) >= m.max_len:
                break
        return out

    def offline_beam(self, prompt, max_new, params, grammar=None):
        """Offline beam reference: ``generate.offline_beam_decode`` with
        this entry's prefill forward as the whole-sequence logits
        oracle. The engine's slot-based incremental beam is bit-compared
        against this by tests and GEN_EVIDENCE_r17."""
        m = self._model

        def logits_fn(tokens):
            fetches = self._run("prefill", self._prefill_feeds(tokens))
            return np.asarray(fetches[0])[0, len(tokens) - 1]

        g = GrammarConstraint(grammar) if grammar is not None else None
        return offline_beam_decode(logits_fn, prompt, int(max_new), params,
                                   m.eos_id, m.max_len, grammar=g)

    # -- observability ----------------------------------------------------
    def stats(self):
        m = self._model
        pool = self._blocks.stats()
        spec_t = self._metrics.count("spec_target_steps")
        spec_e = self._metrics.count("spec_emitted_tokens")
        spec_p = self._metrics.count("spec_proposed_tokens")
        return self._metrics.snapshot(extra={
            **self._metrics.queue_snapshot(self._queue),
            "model": m.name, "version": m.version,
            "slots": m.slots, "max_len": m.max_len,
            "block_size": m.block_size, "num_blocks": m.num_blocks,
            "active_slots": self._pool.active_count,
            "occupancy": self._metrics.occupancy(m.slots),
            "tokens_per_step": self._metrics.tokens_per_step(),
            "arena_mib": m.arena_bytes() / 2**20,
            "slotted_equivalent_mib":
                m.slotted_equivalent_bytes() / 2**20,
            "block_pool": pool,
            "block_dedup_ratio": pool["dedup_ratio"],
            "spec_steps_per_token": (spec_t / spec_e) if spec_e else None,
            "spec_acceptance_rate": (
                self._metrics.count("spec_accepted_tokens") / spec_p
                if spec_p else None),
            "spec_draft_kv_steps_per_token": (
                self._metrics.count("spec_draft_kv_steps") / spec_e
                if spec_e else None),
            "draft_pinned": self._draft_pinned,
            "prefix_cache_entries": len(self._prefix),
            "prefix_hits": self._prefix.hits,
            "prefix_misses": self._prefix.misses,
            "compile_sources": dict(self.compile_sources),
            "breaker_state": (self._breaker.state if self._breaker
                              else None),
            "tenant_tokens": self._metrics.tenant_counts("tokens"),
            "tenant_completed": self._metrics.tenant_counts("completed"),
            "host_tier": self._tier.stats(),
            "brownout_severity": self._brownout.level,
            "brownout": self._brownout.snapshot(),
            "parked_sessions": len(self._parked),
            "pending_admissions": len(self._pending),
        })

    @property
    def metrics(self):
        return self._metrics

    @property
    def model(self):
        return self._model

    @property
    def prefix_cache(self):
        return self._prefix

    @property
    def block_pool(self):
        return self._blocks


class GenerationEngine:
    """Multi-tenant front door over N hosted decode models."""

    _SEQ = 0

    def __init__(self, place=None, queue_depth=256, breaker_threshold=3,
                 breaker_cooldown_s=1.0, prefix_cache_size=64,
                 hbm_budget_mb=None, host_tier_mb=64, label=None):
        import paddle_tpu as fluid

        if place is None:
            import jax

            place = (fluid.TPUPlace(0) if jax.default_backend() == "tpu"
                     else fluid.CPUPlace())
        self.place = place
        self.device = place.jax_device()
        GenerationEngine._SEQ += 1
        self.label = label or f"genengine-{GenerationEngine._SEQ}"
        self._queue_depth = int(queue_depth)
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._prefix_cache_size = prefix_cache_size
        self._hbm_budget_mb = hbm_budget_mb
        # per-entry host-RAM KV tier budget (spill/write-back target)
        self._host_tier_bytes = int(host_tier_mb) << 20
        self._entries = {}        # (name, version) -> _ModelEntry
        self._latest = {}         # name -> version (last registered)
        self._reg_order = []      # keys in registration order (latest wins)
        self._tenants = {}        # tenant -> _TenantState
        self._tenant_lock = lockdep.named_lock("decode.tenant")
        self._vclock = 0.0        # engine-wide virtual time (last dispatch)
        self._started = False
        self._next_id = 0
        self._id_lock = lockdep.named_lock("decode.ids")

    # -- model registry ---------------------------------------------------
    def register_model(self, model):
        """Host one (model, version). Sizes the paged arena against the
        HBM budget BEFORE any compile, then builds + warms the entry
        (from the compile cache when one is populated). Returns the
        entry."""
        if not isinstance(model, DecodeModel):
            model = model()        # zero-arg builder
        if model.key in self._entries:
            raise ValueError(f"model {model.label} already registered")
        self._check_hbm(model)
        entry = _ModelEntry(
            self, model, self._queue_depth, self._breaker_threshold,
            self._breaker_cooldown_s, self._prefix_cache_size,
        ).build()
        self._entries[model.key] = entry
        self._latest[model.name] = model.version
        self._reg_order.append(model.key)
        if self._started:
            entry.start()
        return entry

    def unregister_model(self, name, version, timeout=60.0):
        """Retire one hosted (model, version): graceful DRAIN-BEFORE-
        RETIRE — admission to the entry closes, queued and in-flight
        generations finish, THEN the entry leaves the registry. The
        rolling-deploy path calls this for the old version once the new
        one serves; `latest` falls back to the newest still-hosted
        version of the name (registration order)."""
        key = (str(name), str(version))
        entry = self._entries.get(key)
        if entry is None:
            raise ValueError(
                f"no model {name}@{version} to unregister; hosted: "
                f"{['@'.join(k) for k in sorted(self._entries)]}")
        entry.shutdown(timeout)
        del self._entries[key]
        self._reg_order.remove(key)
        remaining = [v for n, v in self._reg_order if n == key[0]]
        if remaining:
            self._latest[key[0]] = remaining[-1]
        else:
            self._latest.pop(key[0], None)
        return entry

    def reroute_queued(self, name=None, version=None):
        """Pull every QUEUED (not yet prefilled) request off one entry's
        admission queue for re-dispatch elsewhere — the fleet router's
        drain accelerator: instead of waiting for a retiring/deploying
        replica to chew through its backlog, the backlog moves to
        healthy replicas with its original deadlines intact. In-flight
        slots are untouched (they finish here). Returns the removed
        GenerationRequests; their responses never complete — the caller
        owns re-dispatching them."""
        entry = self._resolve(name, version)
        with entry._cond:
            reqs = [r for r in entry._queue.iter_requests()]
            entry._queue.reroute(reqs)
        for r in reqs:
            self._tenant_unqueue(r.tenant)
        return reqs

    def _check_hbm(self, model):
        """Static pre-compile gate: decode-step peak HBM (the paged
        arena is persistable state, so it dominates) must fit the
        budget."""
        if not self._hbm_budget_mb:
            return
        from paddle_tpu.analysis.memory import (
            check_hbm_budget,
            estimate_peak_hbm,
        )
        from paddle_tpu.utils.enforce import EnforceError

        report = estimate_peak_hbm(
            model.decode_program,
            feed_shapes={n: s for n, s, _d in model.decode_feed_sig()},
            fetch_names=[model.logits_fetch],
        )
        diags = check_hbm_budget(
            report, self._hbm_budget_mb * 2**20, label=model.label)
        if diags:
            raise EnforceError(
                "KV arena does not fit the HBM budget:\n  "
                + "\n  ".join(d.message for d in diags))

    def models(self):
        return sorted(self._entries)

    def entry(self, name=None, version=None):
        return self._resolve(name, version)

    def _resolve(self, name, version):
        if name is None:
            if len(self._entries) != 1:
                raise RejectedError(
                    f"engine hosts {len(self._entries)} models; submit "
                    "must name one")
            return next(iter(self._entries.values()))
        name = str(name)
        if version is None:
            version = self._latest.get(name)
        entry = self._entries.get((name, str(version)))
        if entry is None:
            raise RejectedError(
                f"no model {name}@{version}; hosted: "
                f"{['@'.join(k) for k in sorted(self._entries)]}")
        return entry

    # -- tenancy ----------------------------------------------------------
    def set_tenant(self, tenant, weight=1.0, max_in_flight=None,
                   max_queued=None):
        """Configure one tenant: scheduling weight (stride share under
        contention) and admission quotas. Unknown tenants default to
        weight 1.0, no quotas."""
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        with self._tenant_lock:
            st = self._tenants.get(str(tenant))
            if st is None:
                self._tenants[str(tenant)] = _TenantState(
                    weight, max_in_flight, max_queued)
            else:
                st.weight = float(weight)
                st.max_in_flight = max_in_flight
                st.max_queued = max_queued

    def _tenant(self, tenant):
        st = self._tenants.get(tenant)
        if st is None:
            st = _TenantState()
            self._tenants[tenant] = st
        return st

    def _tenant_unqueue(self, tenant):
        with self._tenant_lock:
            st = self._tenant(tenant)
            st.queued = max(st.queued - 1, 0)

    def _tenant_unflight(self, tenant):
        with self._tenant_lock:
            st = self._tenant(tenant)
            st.in_flight = max(st.in_flight - 1, 0)

    def _pick(self, queue, max_rows=None, lanes=None):
        """Weighted-fair pick (caller holds queue.lock): first non-empty
        priority lane wins (strict priority), then the lane's queued
        tenant with the smallest virtual time, skipping tenants at their
        in-flight cap. The winner's FIRST queued request dispatches
        (per-tenant FIFO) and the tenant pays 1/weight virtual time.
        ``max_rows`` is the admission round's remaining slot budget: a
        tenant whose head request needs more rows (a beam) is skipped
        for the round — head-of-line within the tenant is deliberate,
        per-tenant FIFO is the ordering contract. ``lanes`` restricts the
        eligible priority lanes (brownout L3 zeroes the LOW-lane
        dispatch quota this way — queued LOW waits, it is not lost)."""
        with self._tenant_lock:
            for lane in (lanes if lanes is not None else Priority.LANES):
                requests = queue.lane(lane)
                if not requests:
                    continue
                best = None
                candidates = {}
                for r in requests:
                    if r.tenant in candidates:
                        continue
                    st = self._tenant(r.tenant)
                    if (st.max_in_flight is not None
                            and st.in_flight >= st.max_in_flight):
                        continue
                    if max_rows is not None and r.rows > max_rows:
                        # not enough free slots THIS round for the
                        # tenant's head request; its turn comes back
                        candidates[r.tenant] = None
                        continue
                    candidates[r.tenant] = (st, r)
                candidates = {t: c for t, c in candidates.items()
                              if c is not None}
                if not candidates:
                    continue  # every queued tenant here is capped
                for tenant, (st, r) in candidates.items():
                    if best is None or st.vtime < best[0].vtime:
                        best = (st, r)
                st, req = best
                # catch-up: a long-idle tenant wins its first contested
                # pick (it IS behind) but then re-enters at the engine's
                # virtual clock instead of burning banked lag into a
                # starvation burst
                base = max(st.vtime, self._vclock)
                st.vtime = base + 1.0 / st.weight
                self._vclock = base
                # in-flight is RESERVED at pick time: a multi-slot
                # admission round calls _pick repeatedly before any
                # prefill runs, so charging later would let one round
                # blow through max_in_flight
                st.in_flight += 1
                queue.remove([req], batch=True)
                return req
        return None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._started:
            return self
        self._started = True
        for entry in self._entries.values():
            entry.start()
        return self

    def shutdown(self, timeout=60.0):
        """Graceful drain: stop admitting; queued + in-flight sequences
        finish generating before the loops exit."""
        for entry in self._entries.values():
            entry.shutdown(timeout)
        self._started = False

    drain = shutdown

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- admission --------------------------------------------------------
    def submit(self, prompt_ids, model=None, version=None, tenant="default",
               priority=Priority.NORMAL, max_new_tokens=16,
               deadline_ms=None, deadline_at=None, draft_model=None,
               draft_version=None, spec_k=4, sampling=None,
               beam_width=None, grammar=None, draft_kv=True):
        """Admit one generation request; returns its Response future
        (``result()`` -> ``{"tokens": int64 array}``). Raises structured
        RejectedError on invalid prompts, over-quota tenants, or a full
        queue (with a measured retry-after). ``deadline_at`` is an
        ABSOLUTE ``time.perf_counter()`` deadline (it wins over
        ``deadline_ms``): a re-dispatched request carries its ORIGINAL
        deadline through the retry instead of being granted a fresh
        budget — the fleet router's at-most-once-visible failover
        depends on this. ``draft_model`` (+ optional ``draft_version``)
        opts into speculative decoding: the draft must be a hosted
        registry entry sharing the target's vocabulary; committed-
        coupling acceptance keeps the output bit-identical to
        non-speculative decode (greedy acceptance is its temperature-0
        case). ``draft_kv`` (default on) gives the proposals their own
        KV slot on the draft entry — O(1) draft work per token — when
        the draft entry can be PINNED (no primary traffic); otherwise
        the request silently uses replay proposals.

        Generation modes (r17): ``sampling`` — a SamplingParams (or
        kwargs dict) selecting temperature/top-k/top-p on the
        per-request committed threefry stream; ``beam_width`` — beam
        search over N slot-hypotheses (deterministic; exclusive with
        sampling and speculation); ``grammar`` — a CompiledGrammar
        whose per-step masks constrain output (requires a model built
        with ``logits_mask=True`` except on the speculative path, which
        masks host-side)."""
        entry = self._resolve(model, version)
        m = entry.model
        tenant = str(tenant)
        entry.metrics.incr("submitted")
        entry.metrics.tenant_incr("submitted", tenant)
        severity = entry._brownout.level
        if (severity >= 4 and priority != Priority.HIGH
                and entry._shed_confirmed()):
            # brownout L4: the ladder's last rung — shed non-HIGH at the
            # door with a measured retry-after instead of queueing work
            # the drain rate says will miss its deadline anyway
            entry.metrics.incr("rejected")
            entry.metrics.incr("brownout_shed")
            entry.metrics.tenant_incr("rejected", tenant)
            raise RejectedError(
                f"brownout {entry._brownout.name}: shedding non-HIGH "
                "traffic under overload",
                retry_after_s=entry._queue.retry_after_estimate(1))
        self._validate(m, prompt_ids, max_new_tokens, priority, entry)
        if isinstance(sampling, dict):
            sampling = SamplingParams(**sampling)
        if sampling is not None and not isinstance(sampling, SamplingParams):
            self._bad(entry, "sampling must be a SamplingParams or dict")
        beam = None
        if beam_width is not None:
            beam = BeamParams(beam_width)
            if beam.width > m.slots:
                self._bad(entry,
                          f"beam width {beam.width} exceeds the entry's "
                          f"{m.slots} batch slots")
            if sampling is not None and not sampling.greedy:
                self._bad(entry, "beam search is deterministic; it does "
                                 "not compose with sampling")
            if draft_model is not None:
                self._bad(entry, "beam search does not compose with "
                                 "speculative decoding")
            if (severity >= 3 and beam.width > entry._brownout.beam_cap
                    and entry._shed_confirmed()):
                # brownout L3: wide beams multiply slot + block footprint;
                # cap NEW admissions (in-flight groups keep their width)
                entry.metrics.incr("rejected")
                entry.metrics.incr("brownout_shed")
                entry.metrics.tenant_incr("rejected", tenant)
                raise RejectedError(
                    f"brownout {entry._brownout.name}: beam width capped "
                    f"at {entry._brownout.beam_cap} under pressure",
                    retry_after_s=entry._queue.retry_after_estimate(1))
        if grammar is not None:
            if not isinstance(grammar, CompiledGrammar):
                self._bad(entry, "grammar must be a CompiledGrammar")
            if m.eos_id is None:
                self._bad(entry, "grammar-constrained decode needs a "
                                 "model with an eos_id")
            if grammar.eos_id != m.eos_id:
                self._bad(entry,
                          f"grammar eos_id {grammar.eos_id} != model "
                          f"eos_id {m.eos_id}")
            if len(grammar.vocab) != m.vocab_size:
                self._bad(entry,
                          f"grammar vocab size {len(grammar.vocab)} != "
                          f"model vocab {m.vocab_size}")
            if draft_model is None and not m.logits_mask:
                self._bad(entry,
                          "grammar-constrained decode needs a model "
                          "built with logits_mask=True (the DEC_MASK "
                          "feed); only the speculative path masks "
                          "host-side")
        draft_key = None
        draft_kv = bool(draft_kv)
        if draft_model is not None:
            draft_entry = self._resolve(draft_model, draft_version)
            dm = draft_entry.model
            if dm.key == m.key:
                self._bad(entry, "draft model must differ from the target")
            if dm.vocab_size != m.vocab_size:
                self._bad(entry,
                          f"draft vocab {dm.vocab_size} != target vocab "
                          f"{m.vocab_size}")
            need = len(list(prompt_ids)) + int(max_new_tokens)
            if need > dm.max_len:
                self._bad(entry,
                          f"prompt + max_new_tokens ({need}) exceeds the "
                          f"draft model's max_len {dm.max_len}")
            if int(spec_k) < 1:
                self._bad(entry, f"spec_k must be >= 1, got {spec_k}")
            draft_key = dm.key
            if draft_kv:
                # pin the draft: draft-KV decode/inject calls DONATE the
                # draft arena, so the draft entry must carry no primary
                # traffic. Pinning is best-effort at admission (a request
                # picked but not yet slotted can slip the busy check);
                # production deployments dedicate the draft entry by
                # configuration, and the per-call _draft_lock serializes
                # every spec user either way.
                with draft_entry._cond:
                    busy = (not draft_entry._queue.empty()
                            or draft_entry._pool.active_count > 0)
                    if busy and not draft_entry._draft_pinned:
                        draft_kv = False    # replay fallback, this request
                    else:
                        draft_entry._draft_pinned = True
        else:
            draft_kv = False
        with self._tenant_lock:
            st = self._tenant(tenant)
            over_quota = (st.max_queued is not None
                          and st.queued >= st.max_queued)
            quota = (st.queued, st.max_queued)
            if not over_quota:
                st.queued += 1
        if over_quota:
            # the queue lock is taken OUTSIDE _tenant_lock here: the
            # scheduler thread acquires them in queue-then-tenant order
            # (_admit_free_slots -> _pick), so estimating retry-after
            # while still holding _tenant_lock would be an ABBA deadlock
            entry.metrics.incr("rejected")
            entry.metrics.incr("rejected_quota")
            entry.metrics.tenant_incr("rejected", tenant)
            raise RejectedError(
                f"tenant '{tenant}' is at its admission quota "
                f"({quota[0]}/{quota[1]} queued)",
                retry_after_s=entry._queue.retry_after_estimate(1),
            )
        if deadline_at is not None:
            deadline = float(deadline_at)
        else:
            deadline = (time.perf_counter() + deadline_ms / 1e3
                        if deadline_ms is not None else None)
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        req = GenerationRequest(rid, prompt_ids, max_new_tokens, tenant,
                                priority, deadline, draft_key=draft_key,
                                spec_k=spec_k, sampling=sampling, beam=beam,
                                grammar=grammar, draft_kv=draft_kv)
        with entry._cond:
            pinned = entry._draft_pinned
        if pinned:
            # a pinned draft entry serves speculative proposals through
            # donated arena calls — concurrent primary traffic would
            # corrupt them. Reject before enqueue (best-effort, like the
            # pinning busy-check itself: dedicating the draft entry by
            # configuration is the production posture).
            self._tenant_unqueue(tenant)
            self._bad(entry, "entry is pinned as a draft-KV proposal "
                             "server; submit primary traffic elsewhere")
        try:
            with entry._cond:
                entry._queue.put(req)
                entry._cond.notify()
        except RejectedError:
            self._tenant_unqueue(tenant)
            entry.metrics.incr("rejected")
            entry.metrics.incr("rejected_shutdown" if entry._queue.closed()
                               else "rejected_queue_full")
            entry.metrics.tenant_incr("rejected", tenant)
            raise
        return req.response

    @staticmethod
    def _bad(entry, msg):
        entry.metrics.incr("rejected")
        entry.metrics.incr("rejected_invalid")
        raise RejectedError(msg)

    def _validate(self, m, prompt_ids, max_new, priority, entry):
        def bad(msg):
            self._bad(entry, msg)

        try:
            prompt = [int(t) for t in prompt_ids]
        except (TypeError, ValueError):
            bad("prompt_ids must be a sequence of token ids")
        if priority not in Priority.LANES:
            bad(f"unknown priority {priority!r}")
        if not prompt:
            bad("empty prompt")
        if any(t < 0 or t >= m.vocab_size for t in prompt):
            bad(f"prompt token out of range [0, {m.vocab_size})")
        if int(max_new) < 1:
            bad(f"max_new_tokens must be >= 1, got {max_new}")
        if len(prompt) + int(max_new) > m.max_len:
            bad(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the KV arena length {m.max_len}; shorten the "
                "request or host the model with a longer max_len")

    # -- observability ----------------------------------------------------
    def stats(self):
        per_model = {e.model.label: e.stats()
                     for e in self._entries.values()}
        with self._tenant_lock:
            tenants = {
                t: {"weight": st.weight, "in_flight": st.in_flight,
                    "queued": st.queued,
                    "max_in_flight": st.max_in_flight,
                    "max_queued": st.max_queued}
                for t, st in self._tenants.items()
            }
        return {
            "models": per_model,
            "tenants": tenants,
            "hosted": ["@".join(k) for k in sorted(self._entries)],
        }
