"""GenerationEngine: continuous-batching decode over slotted KV arenas.

The PR-2 ServingEngine batches whole requests into fixed buckets — a
finished sequence holds its rows until the whole bucket drains. This
engine schedules at ITERATION granularity (Orca, OSDI'22): a fixed batch
of S slots is stepped once per model iteration through ONE compiled
``[S, 1]`` decode executable; finished sequences retire between
iterations and admitted prompts prefill into free slots mid-flight, so
occupancy tracks offered load instead of the slowest batchmate.

Correctness contract (tested, not asserted by construction alone):
generation is bit-identical to offline whole-sequence decode for the
same prompt, regardless of admission order, slot assignment, or what the
other slots are doing — because (a) retired/foreign slots touch the
arena only through multiply-by-zero writes (exact no-ops in IEEE
arithmetic), and (b) the additive ``-1e9`` attention bias makes
positions beyond a slot's cursor contribute exactly 0.0 (the repo-wide
padding contract).

Multi-tenancy: one engine hosts N ``(model, version)`` entries, each with
its own slot batch, queue, and scheduler thread. Admission applies
per-tenant quotas (queued rows reject at the door; in-flight caps make
the picker skip, not reject) and WEIGHTED-FAIR selection layered over the
queue's strict priority lanes: within the head non-empty lane, the
tenant with the smallest virtual time wins the free slot and pays
``1/weight`` virtual time for it (stride scheduling), so a tenant with
weight 2 gets two slots for every one a weight-1 tenant gets — under
contention, and only then.

Cold start: the three executables per entry lower through
``core/lowering.py`` into the content-addressed compile cache. With
``PADDLE_TPU_CACHE_DIR`` set, a fresh replica (or the circuit breaker's
relaunched replacement) restores decode/prefill/inject from the
``jax.export`` disk tier with ZERO traces — subprocess-asserted in
tests/test_decode.py. Before anything compiles, the KV arena is sized
against the peak-HBM budget via ``analysis/memory.py`` — an oversized
``slots x max_len`` grid fails with sizing advice, not an XLA OOM.
"""

import threading
import time

import numpy as np

from paddle_tpu import profiler
from paddle_tpu.observability import lockdep
from paddle_tpu.resilience import faults
from paddle_tpu.serving.decode.metrics import DecodeMetrics
from paddle_tpu.serving.decode.model import NEG_INF, DecodeModel
from paddle_tpu.serving.decode.pool import PrefixCache, SlotPool, prompt_key
from paddle_tpu.serving.engine import _ReplicaBreaker
from paddle_tpu.serving.queue import RequestQueue
from paddle_tpu.serving.request import (
    DeadlineExceededError,
    Priority,
    RejectedError,
    ReplicaLostError,
    RequestError,
    Response,
)

__all__ = ["GenerationEngine", "GenerationRequest"]

# The scheduler takes the queue lock, then the tenant table inside it
# (_admit_free_slots -> _pick); PR 10's ABBA fix (quota rejects estimate
# retry-after OUTSIDE _tenant_lock) exists precisely to preserve this.
# Declared so a future inversion names the RULE, not just the cycle.
lockdep.declare_order("serving.queue", "decode.tenant")


class GenerationRequest:
    """One admitted generation request (rows is always 1: a request holds
    one slot). `response.result()` yields ``{"tokens": int64 array}`` —
    the generated tokens, including the stop token when eos fired."""

    __slots__ = ("id", "prompt", "max_new", "tenant", "priority", "deadline",
                 "submit_time", "dispatch_time", "response", "rows")

    def __init__(self, rid, prompt, max_new, tenant, priority, deadline):
        self.id = rid
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.tenant = str(tenant)
        self.priority = priority
        self.deadline = deadline
        self.submit_time = time.perf_counter()
        self.dispatch_time = None
        self.response = Response()
        self.rows = 1

    def expired(self, now=None):
        if self.deadline is None:
            return False
        return (now if now is not None else time.perf_counter()) > self.deadline


class _ArenaInvalidError(RuntimeError):
    """A DONATED arena update (inject) failed mid-execution: the old
    buffers were consumed and the new ones never materialized, so the
    whole KV pool — not just the admitting request — is undefined."""


class _TenantState:
    __slots__ = ("weight", "max_in_flight", "max_queued", "in_flight",
                 "queued", "vtime")

    def __init__(self, weight=1.0, max_in_flight=None, max_queued=None):
        self.weight = float(weight)
        self.max_in_flight = max_in_flight
        self.max_queued = max_queued
        self.in_flight = 0
        self.queued = 0
        self.vtime = 0.0


class _Slot:
    """Host-side state of one live arena slot."""

    __slots__ = ("request", "cursor", "last_token", "generated")

    def __init__(self, request, cursor, first_token):
        self.request = request
        self.cursor = cursor          # next arena position to write
        self.last_token = first_token
        self.generated = [first_token]


class _ModelEntry:
    """One hosted (model, version): programs + executables + slot batch +
    its scheduler thread. All slot/arena mutation happens on the loop
    thread; admission hand-off goes through the queue."""

    def __init__(self, engine, model, queue_depth, breaker_threshold,
                 breaker_cooldown_s, prefix_cache_size):
        self._engine = engine
        self._model = model
        self._queue = RequestQueue(queue_depth)
        self._cond = threading.Condition(self._queue.lock)
        self._pool = SlotPool(model.slots)
        self._slots = [None] * model.slots
        self._prefix = PrefixCache(prefix_cache_size)
        self._breaker = (
            _ReplicaBreaker(breaker_threshold, breaker_cooldown_s)
            if breaker_threshold and breaker_threshold > 0 else None
        )
        self._metrics = DecodeMetrics(
            engine_label=f"{engine.label}:{model.label}")
        self.compile_sources = {"trace": 0, "disk": 0, "memory": 0}
        self._entries = {}      # kind -> (LoweredStep, executable)
        self._thread = None
        self._stop = False
        self._scope = None
        self._rng0 = None
        # half-open relaunch latch: one rebuild per breaker episode
        self._probe_relaunched = False

    # -- build / warmup ---------------------------------------------------
    def build(self):
        """Run startup (weights + zeroed arenas into the scope), then
        lower + AOT-compile the three executables. With a warm compile
        cache nothing here traces (`compile_sources` says so)."""
        import paddle_tpu as fluid
        from paddle_tpu.core.lowering import zero_rng_key

        self._scope = fluid.Scope()
        exe = fluid.Executor(self._engine.place)
        with fluid.scope_guard(self._scope):
            exe.run(self._model.startup_program)
        self._rng0 = zero_rng_key(self._engine.device)
        self._lower_all()
        return self

    def _lower_all(self):
        from paddle_tpu.core import lowering

        m = self._model
        plans = (
            ("step", m.decode_program, m.decode_feed_sig(),
             [m.logits_fetch], True),
            ("prefill", m.prefill_program, m.prefill_feed_sig(),
             [m.prefill_logits_fetch] + [n for kv in m.prefill_kv_fetches
                                         for n in kv], False),
            ("inject", m.inject_program, m.inject_feed_sig(), [], True),
        )
        sources = dict(self.compile_sources)
        with profiler.RecordEvent("decode::warmup"):
            for kind, prog, feed_sig, fetches, donate in plans:
                entry, source = lowering.lower_step(
                    prog, self._scope, feed_sig, fetches, donate=donate,
                    label=f"decode:{m.label}:{kind}",
                )
                sources[source] = sources.get(source, 0) + 1
                executable = entry.aot_compile(
                    lowering.abstract_signature(entry, feed_sig,
                                                self._scope))
                self._entries[kind] = (entry, executable)
        # atomic rebind, not in-place mutation: a breaker relaunch runs
        # this on the loop thread while stats() dict-copies concurrently
        self.compile_sources = sources

    def _run(self, kind, feeds):
        """Execute one lowered program against the entry scope; written
        persistables (the arenas — donated, updated in place on device)
        re-enter the scope for the next call."""
        import jax

        entry, executable = self._entries[kind]
        dev = self._engine.device
        feed_vals = tuple(
            jax.device_put(np.ascontiguousarray(feeds[n]), dev)
            for n in entry.feed_names
        )
        donated = tuple(self._scope.find_var(n) for n in entry.donated)
        readonly = tuple(self._scope.find_var(n) for n in entry.readonly)
        fetches, updates = executable(feed_vals, donated, readonly,
                                      self._rng0)
        for n, u in zip(entry.written, updates):
            self._scope.set(n, u)
        return fetches

    def _reset_arenas(self):
        """Zero the KV pool and drop all slot state (relaunch path: a
        failed donated call leaves the old arena buffers invalid)."""
        import jax
        import jax.numpy as jnp

        m = self._model
        for kn, vn in m.state_names:
            for n in (kn, vn):
                self._scope.set(n, jax.device_put(
                    jnp.zeros((m.slots, m.max_len, m.hidden), jnp.float32),
                    self._engine.device))
        self._pool.reset()
        self._slots = [None] * m.slots

    def relaunch(self):
        """The circuit breaker's replacement replica: rebuild programs
        from the model's builder (content-identical by construction),
        re-lower — every entry should come from the compile cache, not a
        trace — and reset the arena. Weights stay; queued requests are
        served by the relaunched replica."""
        if self._model.builder is not None:
            self._model = self._model.builder()
        self._lower_all()
        self._reset_arenas()
        self._metrics.incr("relaunches")

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop = False
        self._queue.reopen()
        self._thread = threading.Thread(
            target=self._loop, name=f"decode-{self._model.label}",
            daemon=True)
        self._thread.start()

    def shutdown(self, timeout=60.0):
        self._queue.close()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def notify(self):
        with self._cond:
            self._cond.notify()

    # -- scheduler loop ---------------------------------------------------
    def _loop(self):
        while True:
            with self._cond:
                for r in self._queue.expire():
                    self._reject_expired(r)
                if (self._stop and self._queue.empty()
                        and self._pool.active_count == 0):
                    return
            if self._breaker is not None and not self._stop:
                verdict, wait_s = self._breaker.gate()
                if verdict == "wait":
                    with self._cond:
                        for r in self._queue.expire():
                            self._reject_expired(r)
                        if not self._stop:
                            self._cond.wait(timeout=min(wait_s, 0.1))
                    continue
                if verdict == "probe" and not self._probe_relaunched:
                    # re-admission probe IS a relaunch: fresh programs,
                    # zeroed arena, executables from the compile cache —
                    # ONCE per half-open episode (the flag); the probe
                    # STEP's outcome then closes or reopens the breaker,
                    # so an idle engine doesn't rebuild every loop tick
                    self._metrics.incr("breaker_probes")
                    try:
                        self.relaunch()
                        self._probe_relaunched = True
                    except Exception:
                        self._breaker_event(self._breaker.record_failure())
                        continue
            admitted = self._admit_free_slots()
            if self._pool.active_count == 0:
                # nothing decodable AND this round admitted nothing —
                # either the queue is empty, or everything queued is
                # blocked on a tenant cap held by another entry's
                # in-flight work; poll, don't spin
                with self._cond:
                    if not self._stop and not admitted:
                        self._cond.wait(timeout=0.02)
                continue
            self._step()

    def _reject_expired(self, request):
        self._metrics.incr("deadline_missed")
        self._engine._tenant_unqueue(request.tenant)
        request.response._complete(error=DeadlineExceededError(
            "deadline expired after "
            f"{time.perf_counter() - request.submit_time:.3f}s in queue"))
        self._metrics.observe_request(request)

    def _breaker_event(self, event):
        if event:
            self._metrics.incr(event)

    # -- admission (prefill + inject into a free slot) --------------------
    def _admit_free_slots(self):
        picked = []
        with self._cond:
            while self._pool.free_count - len(picked) > 0:
                req = self._engine._pick(self._queue)
                if req is None:
                    break
                picked.append(req)
            # the round's picks are ONE drain event for the rate EWMA
            self._queue.note_drained()
        for req in picked:
            self._engine._tenant_unqueue(req.tenant)
            if req.expired():
                # picked but dead: release the pick-time in-flight
                # reservation; no slot to free
                self._engine._tenant_unflight(req.tenant)
                self._metrics.incr("deadline_missed")
                req.response._complete(error=DeadlineExceededError(
                    "deadline expired before prefill"))
                self._metrics.observe_request(req)
                continue
            slot = self._pool.acquire()
            try:
                self._prefill_into(req, slot)
            except _ArenaInvalidError as e:
                # donated inject failed: like a step failure, every
                # in-flight sequence is lost (failed loudly), the
                # outcome drives the breaker, and the arena resets
                self._slots[slot] = None
                self._engine._tenant_unflight(req.tenant)
                self._metrics.incr("failed")
                req.response._complete(error=RequestError(
                    f"request {req.id} failed in inject: {e}"))
                self._metrics.observe_request(req)
                self._metrics.incr("step_failures")
                self._probe_relaunched = False
                if self._breaker is not None:
                    self._breaker_event(self._breaker.record_failure())
                for s, st in enumerate(self._slots):
                    if st is not None:
                        self._reject_in_flight(st.request, ReplicaLostError(
                            f"request {st.request.id} lost to arena "
                            f"failure during admission: {e}"), slot=s)
                self._reset_arenas()
                # the reset arena is valid (zeroed): the REMAINING picked
                # requests still admit — dropping them would abandon
                # their futures and leak their tenants' queued counters
            except Exception as e:  # request-attributed, not replica health
                self._pool.release(slot)
                self._slots[slot] = None
                self._engine._tenant_unflight(req.tenant)
                self._metrics.incr("failed")
                req.response._complete(error=RequestError(
                    f"request {req.id} failed in prefill: {e}"))
                self._metrics.observe_request(req)
        return len(picked)

    def _prefill_into(self, req, slot):
        m = self._model
        req.dispatch_time = time.perf_counter()
        prompt = req.prompt
        key = prompt_key(prompt)
        cached = self._prefix.get(key)
        if cached is not None:
            kv_rows, logits_row = cached
            # hit/miss totals live on PrefixCache (one source, surfaced
            # by stats()); only the per-tenant series is a counter here
            self._metrics.tenant_incr("prefix_hits", req.tenant)
        else:
            t0 = time.perf_counter()
            with profiler.RecordEvent("decode::prefill"):
                faults.fire("decode.prefill")
                fetches = self._run("prefill", self._prefill_feeds(prompt))
            logits = np.asarray(fetches[0])          # [1, L, V]
            kv_rows = [np.asarray(f) for f in fetches[1:]]
            # copy: a view would pin the whole [1, L, V] prefill logits
            # buffer in the prefix cache for the life of the entry
            logits_row = np.array(logits[0, len(prompt) - 1])
            self._prefix.put(key, kv_rows, logits_row)
            self._metrics.observe_prefill(time.perf_counter() - t0)
        inj = {DecodeModel.INJ_SLOT:
               np.eye(m.slots, dtype="float32")[slot][:, None, None]}
        for i, (kn, vn) in enumerate(m.inject_kv_feeds):
            inj[kn] = kv_rows[2 * i]
            inj[vn] = kv_rows[2 * i + 1]
        try:
            with profiler.RecordEvent("decode::inject"):
                faults.fire("decode.inject")
                self._run("inject", inj)
        except Exception as e:
            raise _ArenaInvalidError(str(e)) from e
        first = int(np.argmax(logits_row))
        self._slots[slot] = _Slot(req, len(prompt), first)
        self._metrics.incr("admitted")
        # the prefill's first token: counted apart from generated_tokens
        # so tokens_per_step stays a decode-step quantity (<= S)
        self._metrics.incr("prefill_tokens")
        self._metrics.tenant_incr("admitted", req.tenant)
        self._metrics.tenant_incr("tokens", req.tenant)
        if self._finished(self._slots[slot]):
            self._retire(slot)

    def _prefill_feeds(self, prompt):
        m = self._model
        toks = np.zeros((1, m.max_len), "int64")
        toks[0, :len(prompt)] = prompt
        pos = np.arange(m.max_len, dtype="int64")[None]
        bias = np.triu(np.full((m.max_len, m.max_len), NEG_INF, "float32"),
                       k=1)[None]
        return {DecodeModel.PRE_TOKENS: toks,
                DecodeModel.PRE_POSITIONS: pos,
                DecodeModel.PRE_BIAS: bias}

    # -- the decode iteration ---------------------------------------------
    def _step(self):
        m = self._model
        S, L = m.slots, m.max_len
        tok = np.zeros((S, 1), "int64")
        pos = np.zeros((S, 1), "int64")
        bias = np.full((S, 1, L), NEG_INF, "float32")
        write = np.zeros((S, L), "float32")
        active = []
        for s in range(S):
            st = self._slots[s]
            if st is None:
                continue
            active.append(s)
            tok[s, 0] = st.last_token
            pos[s, 0] = st.cursor
            bias[s, 0, :st.cursor + 1] = 0.0
            write[s, st.cursor] = 1.0
        t0 = time.perf_counter()
        try:
            with profiler.RecordEvent("decode::step"):
                faults.fire("decode.step")
                fetches = self._run("step", {
                    DecodeModel.DEC_TOKEN: tok, DecodeModel.DEC_POSITION: pos,
                    DecodeModel.DEC_BIAS: bias, DecodeModel.DEC_WRITE: write,
                })
        except Exception as e:
            # a failed donated call leaves the arena undefined: every
            # in-flight sequence is lost (failed loudly), the batch-level
            # outcome drives the breaker, and the arena resets
            self._metrics.incr("step_failures")
            self._probe_relaunched = False
            if self._breaker is not None:
                self._breaker_event(self._breaker.record_failure())
            for s in list(active):
                st = self._slots[s]
                self._reject_in_flight(st.request, ReplicaLostError(
                    f"request {st.request.id} lost to decode-step failure: "
                    f"{e}"), slot=s)
            self._reset_arenas()
            return
        if self._breaker is not None:
            self._breaker_event(self._breaker.record_success())
        logits = np.asarray(fetches[0])              # [S, 1, V]
        now = time.perf_counter()
        for s in active:
            st = self._slots[s]
            nxt = int(np.argmax(logits[s, 0]))
            st.generated.append(nxt)
            st.cursor += 1
            st.last_token = nxt
            self._metrics.tenant_incr("tokens", st.request.tenant)
            # finished wins over expired: the device already paid for a
            # COMPLETE generation, deliver it (the prefill fast path
            # retires without an expiry check — same policy)
            if self._finished(st):
                self._retire(s)
            elif st.request.expired(now):
                self._reject_in_flight(st.request, DeadlineExceededError(
                    "deadline expired mid-generation after "
                    f"{len(st.generated)} tokens"), slot=s)
        self._metrics.observe_step(len(active), len(active),
                                   time.perf_counter() - t0)

    def _finished(self, st):
        m = self._model
        return (len(st.generated) >= st.request.max_new
                or (m.eos_id is not None and st.last_token == m.eos_id)
                or st.cursor >= m.max_len)

    def _retire(self, slot):
        st = self._slots[slot]
        self._slots[slot] = None
        self._pool.release(slot)
        req = st.request
        self._engine._tenant_unflight(req.tenant)
        req.response._complete(outputs={
            "tokens": np.asarray(st.generated, dtype="int64"),
        })
        self._metrics.incr("completed")
        self._metrics.incr("retired")
        self._metrics.tenant_incr("completed", req.tenant)
        self._metrics.observe_request(req)

    def _reject_in_flight(self, req, error, slot=None):
        if slot is not None:
            self._slots[slot] = None
            self._pool.release(slot)
        self._engine._tenant_unflight(req.tenant)
        self._metrics.incr(
            "deadline_missed" if isinstance(error, DeadlineExceededError)
            else "failed")
        req.response._complete(error=error)
        self._metrics.observe_request(req)

    # -- reference path ----------------------------------------------------
    def offline_decode(self, prompt, max_new):
        """Offline whole-sequence reference: re-run the full causal
        prefill forward per generated token (no KV cache, no slots) with
        identical finish rules. The bit-exactness tests compare
        continuous output against THIS."""
        m = self._model
        toks = list(prompt)
        out = []
        for _ in range(int(max_new)):
            t = len(toks) - 1
            fetches = self._run("prefill", self._prefill_feeds(toks))
            nxt = int(np.argmax(np.asarray(fetches[0])[0, t]))
            out.append(nxt)
            toks.append(nxt)
            if m.eos_id is not None and nxt == m.eos_id:
                break
            if len(toks) >= m.max_len:
                break
        return out

    # -- observability ----------------------------------------------------
    def stats(self):
        m = self._model
        return self._metrics.snapshot(extra={
            **self._metrics.queue_snapshot(self._queue),
            "model": m.name, "version": m.version,
            "slots": m.slots, "max_len": m.max_len,
            "active_slots": self._pool.active_count,
            "occupancy": self._metrics.occupancy(m.slots),
            "tokens_per_step": self._metrics.tokens_per_step(),
            "arena_mib": m.arena_bytes() / 2**20,
            "prefix_cache_entries": len(self._prefix),
            "prefix_hits": self._prefix.hits,
            "prefix_misses": self._prefix.misses,
            "compile_sources": dict(self.compile_sources),
            "breaker_state": (self._breaker.state if self._breaker
                              else None),
            "tenant_tokens": self._metrics.tenant_counts("tokens"),
            "tenant_completed": self._metrics.tenant_counts("completed"),
        })

    @property
    def metrics(self):
        return self._metrics

    @property
    def model(self):
        return self._model

    @property
    def prefix_cache(self):
        return self._prefix


class GenerationEngine:
    """Multi-tenant front door over N hosted decode models."""

    _SEQ = 0

    def __init__(self, place=None, queue_depth=256, breaker_threshold=3,
                 breaker_cooldown_s=1.0, prefix_cache_size=64,
                 hbm_budget_mb=None, label=None):
        import paddle_tpu as fluid

        if place is None:
            import jax

            place = (fluid.TPUPlace(0) if jax.default_backend() == "tpu"
                     else fluid.CPUPlace())
        self.place = place
        self.device = place.jax_device()
        GenerationEngine._SEQ += 1
        self.label = label or f"genengine-{GenerationEngine._SEQ}"
        self._queue_depth = int(queue_depth)
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._prefix_cache_size = prefix_cache_size
        self._hbm_budget_mb = hbm_budget_mb
        self._entries = {}        # (name, version) -> _ModelEntry
        self._latest = {}         # name -> version (last registered)
        self._reg_order = []      # keys in registration order (latest wins)
        self._tenants = {}        # tenant -> _TenantState
        self._tenant_lock = lockdep.named_lock("decode.tenant")
        self._vclock = 0.0        # engine-wide virtual time (last dispatch)
        self._started = False
        self._next_id = 0
        self._id_lock = lockdep.named_lock("decode.ids")

    # -- model registry ---------------------------------------------------
    def register_model(self, model):
        """Host one (model, version). Sizes the KV arena against the HBM
        budget BEFORE any compile, then builds + warms the entry (from
        the compile cache when one is populated). Returns the entry."""
        if not isinstance(model, DecodeModel):
            model = model()        # zero-arg builder
        if model.key in self._entries:
            raise ValueError(f"model {model.label} already registered")
        self._check_hbm(model)
        entry = _ModelEntry(
            self, model, self._queue_depth, self._breaker_threshold,
            self._breaker_cooldown_s, self._prefix_cache_size,
        ).build()
        self._entries[model.key] = entry
        self._latest[model.name] = model.version
        self._reg_order.append(model.key)
        if self._started:
            entry.start()
        return entry

    def unregister_model(self, name, version, timeout=60.0):
        """Retire one hosted (model, version): graceful DRAIN-BEFORE-
        RETIRE — admission to the entry closes, queued and in-flight
        generations finish, THEN the entry leaves the registry. The
        rolling-deploy path calls this for the old version once the new
        one serves; `latest` falls back to the newest still-hosted
        version of the name (registration order)."""
        key = (str(name), str(version))
        entry = self._entries.get(key)
        if entry is None:
            raise ValueError(
                f"no model {name}@{version} to unregister; hosted: "
                f"{['@'.join(k) for k in sorted(self._entries)]}")
        entry.shutdown(timeout)
        del self._entries[key]
        self._reg_order.remove(key)
        remaining = [v for n, v in self._reg_order if n == key[0]]
        if remaining:
            self._latest[key[0]] = remaining[-1]
        else:
            self._latest.pop(key[0], None)
        return entry

    def reroute_queued(self, name=None, version=None):
        """Pull every QUEUED (not yet prefilled) request off one entry's
        admission queue for re-dispatch elsewhere — the fleet router's
        drain accelerator: instead of waiting for a retiring/deploying
        replica to chew through its backlog, the backlog moves to
        healthy replicas with its original deadlines intact. In-flight
        slots are untouched (they finish here). Returns the removed
        GenerationRequests; their responses never complete — the caller
        owns re-dispatching them."""
        entry = self._resolve(name, version)
        with entry._cond:
            reqs = [r for r in entry._queue.iter_requests()]
            entry._queue.reroute(reqs)
        for r in reqs:
            self._tenant_unqueue(r.tenant)
        return reqs

    def _check_hbm(self, model):
        """Static pre-compile gate: decode-step peak HBM (the arena is
        persistable state, so it dominates) must fit the budget."""
        if not self._hbm_budget_mb:
            return
        from paddle_tpu.analysis.memory import (
            check_hbm_budget,
            estimate_peak_hbm,
        )
        from paddle_tpu.utils.enforce import EnforceError

        report = estimate_peak_hbm(
            model.decode_program,
            feed_shapes={n: s for n, s, _d in model.decode_feed_sig()},
            fetch_names=[model.logits_fetch],
        )
        diags = check_hbm_budget(
            report, self._hbm_budget_mb * 2**20, label=model.label)
        if diags:
            raise EnforceError(
                "KV arena does not fit the HBM budget:\n  "
                + "\n  ".join(d.message for d in diags))

    def models(self):
        return sorted(self._entries)

    def entry(self, name=None, version=None):
        return self._resolve(name, version)

    def _resolve(self, name, version):
        if name is None:
            if len(self._entries) != 1:
                raise RejectedError(
                    f"engine hosts {len(self._entries)} models; submit "
                    "must name one")
            return next(iter(self._entries.values()))
        name = str(name)
        if version is None:
            version = self._latest.get(name)
        entry = self._entries.get((name, str(version)))
        if entry is None:
            raise RejectedError(
                f"no model {name}@{version}; hosted: "
                f"{['@'.join(k) for k in sorted(self._entries)]}")
        return entry

    # -- tenancy ----------------------------------------------------------
    def set_tenant(self, tenant, weight=1.0, max_in_flight=None,
                   max_queued=None):
        """Configure one tenant: scheduling weight (stride share under
        contention) and admission quotas. Unknown tenants default to
        weight 1.0, no quotas."""
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        with self._tenant_lock:
            st = self._tenants.get(str(tenant))
            if st is None:
                self._tenants[str(tenant)] = _TenantState(
                    weight, max_in_flight, max_queued)
            else:
                st.weight = float(weight)
                st.max_in_flight = max_in_flight
                st.max_queued = max_queued

    def _tenant(self, tenant):
        st = self._tenants.get(tenant)
        if st is None:
            st = _TenantState()
            self._tenants[tenant] = st
        return st

    def _tenant_unqueue(self, tenant):
        with self._tenant_lock:
            st = self._tenant(tenant)
            st.queued = max(st.queued - 1, 0)

    def _tenant_unflight(self, tenant):
        with self._tenant_lock:
            st = self._tenant(tenant)
            st.in_flight = max(st.in_flight - 1, 0)

    def _pick(self, queue):
        """Weighted-fair pick (caller holds queue.lock): first non-empty
        priority lane wins (strict priority), then the lane's queued
        tenant with the smallest virtual time, skipping tenants at their
        in-flight cap. The winner's FIRST queued request dispatches
        (per-tenant FIFO) and the tenant pays 1/weight virtual time."""
        with self._tenant_lock:
            for lane in Priority.LANES:
                requests = queue.lane(lane)
                if not requests:
                    continue
                best = None
                candidates = {}
                for r in requests:
                    if r.tenant in candidates:
                        continue
                    st = self._tenant(r.tenant)
                    if (st.max_in_flight is not None
                            and st.in_flight >= st.max_in_flight):
                        continue
                    candidates[r.tenant] = (st, r)
                if not candidates:
                    continue  # every queued tenant here is capped
                for tenant, (st, r) in candidates.items():
                    if best is None or st.vtime < best[0].vtime:
                        best = (st, r)
                st, req = best
                # catch-up: a long-idle tenant wins its first contested
                # pick (it IS behind) but then re-enters at the engine's
                # virtual clock instead of burning banked lag into a
                # starvation burst
                base = max(st.vtime, self._vclock)
                st.vtime = base + 1.0 / st.weight
                self._vclock = base
                # in-flight is RESERVED at pick time: a multi-slot
                # admission round calls _pick repeatedly before any
                # prefill runs, so charging later would let one round
                # blow through max_in_flight
                st.in_flight += 1
                queue.remove([req], batch=True)
                return req
        return None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._started:
            return self
        self._started = True
        for entry in self._entries.values():
            entry.start()
        return self

    def shutdown(self, timeout=60.0):
        """Graceful drain: stop admitting; queued + in-flight sequences
        finish generating before the loops exit."""
        for entry in self._entries.values():
            entry.shutdown(timeout)
        self._started = False

    drain = shutdown

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- admission --------------------------------------------------------
    def submit(self, prompt_ids, model=None, version=None, tenant="default",
               priority=Priority.NORMAL, max_new_tokens=16,
               deadline_ms=None, deadline_at=None):
        """Admit one generation request; returns its Response future
        (``result()`` -> ``{"tokens": int64 array}``). Raises structured
        RejectedError on invalid prompts, over-quota tenants, or a full
        queue (with a measured retry-after). ``deadline_at`` is an
        ABSOLUTE ``time.perf_counter()`` deadline (it wins over
        ``deadline_ms``): a re-dispatched request carries its ORIGINAL
        deadline through the retry instead of being granted a fresh
        budget — the fleet router's at-most-once-visible failover
        depends on this."""
        entry = self._resolve(model, version)
        m = entry.model
        tenant = str(tenant)
        entry.metrics.incr("submitted")
        entry.metrics.tenant_incr("submitted", tenant)
        self._validate(m, prompt_ids, max_new_tokens, priority, entry)
        with self._tenant_lock:
            st = self._tenant(tenant)
            over_quota = (st.max_queued is not None
                          and st.queued >= st.max_queued)
            quota = (st.queued, st.max_queued)
            if not over_quota:
                st.queued += 1
        if over_quota:
            # the queue lock is taken OUTSIDE _tenant_lock here: the
            # scheduler thread acquires them in queue-then-tenant order
            # (_admit_free_slots -> _pick), so estimating retry-after
            # while still holding _tenant_lock would be an ABBA deadlock
            entry.metrics.incr("rejected")
            entry.metrics.incr("rejected_quota")
            entry.metrics.tenant_incr("rejected", tenant)
            raise RejectedError(
                f"tenant '{tenant}' is at its admission quota "
                f"({quota[0]}/{quota[1]} queued)",
                retry_after_s=entry._queue.retry_after_estimate(1),
            )
        if deadline_at is not None:
            deadline = float(deadline_at)
        else:
            deadline = (time.perf_counter() + deadline_ms / 1e3
                        if deadline_ms is not None else None)
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        req = GenerationRequest(rid, prompt_ids, max_new_tokens, tenant,
                                priority, deadline)
        try:
            with entry._cond:
                entry._queue.put(req)
                entry._cond.notify()
        except RejectedError:
            self._tenant_unqueue(tenant)
            entry.metrics.incr("rejected")
            entry.metrics.incr("rejected_shutdown" if entry._queue.closed()
                               else "rejected_queue_full")
            entry.metrics.tenant_incr("rejected", tenant)
            raise
        return req.response

    def _validate(self, m, prompt_ids, max_new, priority, entry):
        def bad(msg):
            entry.metrics.incr("rejected")
            entry.metrics.incr("rejected_invalid")
            raise RejectedError(msg)

        try:
            prompt = [int(t) for t in prompt_ids]
        except (TypeError, ValueError):
            bad("prompt_ids must be a sequence of token ids")
        if priority not in Priority.LANES:
            bad(f"unknown priority {priority!r}")
        if not prompt:
            bad("empty prompt")
        if any(t < 0 or t >= m.vocab_size for t in prompt):
            bad(f"prompt token out of range [0, {m.vocab_size})")
        if int(max_new) < 1:
            bad(f"max_new_tokens must be >= 1, got {max_new}")
        if len(prompt) + int(max_new) > m.max_len:
            bad(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the KV arena length {m.max_len}; shorten the "
                "request or host the model with a longer max_len")

    # -- observability ----------------------------------------------------
    def stats(self):
        per_model = {e.model.label: e.stats()
                     for e in self._entries.values()}
        with self._tenant_lock:
            tenants = {
                t: {"weight": st.weight, "in_flight": st.in_flight,
                    "queued": st.queued,
                    "max_in_flight": st.max_in_flight,
                    "max_queued": st.max_queued}
                for t, st in self._tenants.items()
            }
        return {
            "models": per_model,
            "tenants": tenants,
            "hosted": ["@".join(k) for k in sorted(self._entries)],
        }
