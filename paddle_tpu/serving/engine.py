"""ServingEngine: SLO-aware worker loop over AOT predictor replicas.

One engine owns the admission queue, the batcher, and N predictor
replicas (clones — shared weights and compile cache, independent I/O;
the reference's thread-per-predictor serving pattern upgraded with a
shared scheduler). Worker threads race to form the next padded batch
under the queue lock, then run it on their replica outside the lock —
XLA releases the GIL during execution, so replicas overlap host
scatter/gather with device compute.

Guarantees:

* zero retrace after start(): warmup pre-compiles every lattice point
  and the batcher only emits lattice shapes — `stats()` reports the
  post-warmup compile-cache hit rate so regressions are measurable;
* failure isolation: a request that breaks a batch is re-run alone and
  fails alone (`RequestError`); batchmates are served from the re-run;
* explicit backpressure: admission rejects with retry-after once the
  queue is full, instead of queueing unboundedly;
* graceful drain: shutdown() stops admission, flushes partial batches,
  and joins workers — no request admitted is ever silently dropped;
* replica quarantine: a circuit breaker per replica opens after
  `breaker_threshold` CONSECUTIVE batch-run failures (a healthy batch
  resets the count) and stops dispatching to that replica; after
  `breaker_cooldown_s` the next batch is a PROBE — success re-admits
  the replica, failure re-opens the breaker for another cooldown.
  Lifecycle counters (batch_failures / breaker_opened / breaker_probes
  / breaker_closed / breaker_reopened) flow through `stats()` and the
  C ABI's PD_ServingStats JSON. Draining bypasses quarantine — on
  shutdown every queued request gets an answer attempt.
"""

import threading
import time

import numpy as np

from paddle_tpu import profiler
from paddle_tpu.observability import lockdep
from paddle_tpu.resilience import faults
from paddle_tpu.serving.batcher import BatchPlan, BucketLattice, DynamicBatcher
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.queue import RequestQueue
from paddle_tpu.serving.request import (
    DeadlineExceededError,
    Priority,
    RejectedError,
    Request,
    RequestError,
)

__all__ = ["ServingEngine"]


class _ReplicaBreaker:
    """Per-replica circuit breaker: closed -> (K consecutive batch
    failures) -> open -> (cooldown) -> half_open probe -> closed on
    success / open again on failure. Only batch-level outcomes drive it;
    per-request isolation failures are attributed to the request, not
    the replica."""

    def __init__(self, threshold, cooldown_s):
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"
        self.consecutive = 0
        self.opened_at = None
        self._lock = lockdep.named_lock("serving.breaker")

    def gate(self):
        """Dispatch decision: ('dispatch' | 'probe' | 'wait', wait_s)."""
        with self._lock:
            if self.state == "closed":
                return "dispatch", 0.0
            if self.state == "half_open":
                return "probe", 0.0
            remaining = self.cooldown_s - (time.perf_counter() - self.opened_at)
            if remaining > 0:
                return "wait", remaining
            self.state = "half_open"
            return "probe", 0.0

    def record_failure(self):
        with self._lock:
            self.consecutive += 1
            if self.state == "half_open":
                self.state = "open"
                self.opened_at = time.perf_counter()
                return "breaker_reopened"
            if self.state == "closed" and self.consecutive >= self.threshold:
                self.state = "open"
                self.opened_at = time.perf_counter()
                return "breaker_opened"
            return None

    def record_success(self):
        with self._lock:
            self.consecutive = 0
            if self.state == "half_open":
                self.state = "closed"
                self.opened_at = None
                return "breaker_closed"
            return None


class ServingEngine:
    def __init__(self, config_or_predictor, lattice=None, num_replicas=1,
                 queue_depth=256, max_wait_ms=5.0, breaker_threshold=3,
                 breaker_cooldown_s=1.0):
        from paddle_tpu.inference.predictor import Predictor

        if isinstance(config_or_predictor, Predictor):
            base = config_or_predictor
        else:
            base = Predictor(config_or_predictor)
        self._base = base
        if lattice is None:
            spec = base._config.serving_buckets()
            if spec is None:
                raise ValueError(
                    "ServingEngine needs a bucket lattice: call "
                    "Config.set_serving_buckets(...) or pass lattice="
                )
            lattice = BucketLattice(
                spec["batch_sizes"], spec["seq_lens"],
                pad_axis=spec["pad_axis"],
            )
        self._lattice = lattice
        self._replicas = [base] + [base.clone()
                                   for _ in range(int(num_replicas) - 1)]
        self._queue = RequestQueue(queue_depth)
        # declared feed specs drive strict admission (a shape/dtype the
        # lattice can't serve is rejected at the door, never compiled)
        # and make the batcher's padding/scatter decisions exact: only
        # declared-variable dims pad/slice
        block = base._program.global_block()
        self._feed_specs = {}
        for n in base.get_input_names():
            v = block._find_var_recursive(n)
            self._feed_specs[n] = (
                list(v.shape) if v is not None else None,
                str(v.dtype) if v is not None and v.dtype else None,
            )
        fetch_specs = {}
        for n in base.get_output_names():
            v = block._find_var_recursive(n)
            fetch_specs[n] = (list(v.shape)
                              if v is not None and v.shape else None)
        self._batcher = DynamicBatcher(
            lattice, max_wait_s=max_wait_ms / 1e3,
            feed_specs={n: s for n, (s, _) in self._feed_specs.items()},
            fetch_specs=fetch_specs,
        )
        self._breakers = [
            _ReplicaBreaker(breaker_threshold, breaker_cooldown_s)
            if breaker_threshold and breaker_threshold > 0 else None
            for _ in self._replicas
        ]
        self._metrics = ServingMetrics()
        self._cond = threading.Condition(self._queue.lock)
        self._workers = []
        self._stop = False
        self._started = False
        self._next_id = 0
        self._id_lock = lockdep.named_lock("serving.ids")
        self._warm_base = {"hits": 0, "misses": 0}

    # -- lifecycle ---------------------------------------------------------
    def start(self, warmup=True):
        """Warm every lattice point, then start one worker per replica."""
        if self._started:
            return self
        if warmup:
            with profiler.RecordEvent("serving::warmup"):
                self._base.warmup(buckets={
                    "batch_sizes": self._lattice.batch_sizes,
                    "seq_lens": self._lattice.seq_lens,
                    "pad_axis": self._lattice.pad_axis,
                })
        cs = self._base.cache_stats()
        self._warm_base = {"hits": cs["hits"], "misses": cs["misses"]}
        self._stop = False
        self._queue.reopen()
        self._started = True
        for i, rep in enumerate(self._replicas):
            t = threading.Thread(
                target=self._worker, args=(rep, self._breakers[i]),
                name=f"serving-worker-{i}", daemon=True,
            )
            t.start()
            self._workers.append(t)
        return self

    def shutdown(self, timeout=60.0):
        """Graceful drain: stop admitting, flush queued requests (partial
        batches dispatch immediately), join workers."""
        self._queue.close()
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        for t in self._workers:
            t.join(timeout)
        self._workers = []
        self._started = False

    drain = shutdown

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # -- admission ---------------------------------------------------------
    def submit(self, inputs, priority=Priority.NORMAL, deadline_ms=None):
        """Admit one request; returns its Response future. Raises
        RejectedError (structured, with retry_after_s) when admission
        refuses — queue full, draining, or inadmissible inputs."""
        self._metrics.incr("submitted")
        try:
            norm = self._validate(inputs)
            rows, var_len, group_key = self._lattice.classify(
                norm, var_feeds=self._batcher.var_feeds
            )
        except RejectedError:
            self._metrics.incr("rejected")
            self._metrics.incr("rejected_invalid")
            raise
        if priority not in Priority.LANES:
            self._metrics.incr("rejected")
            self._metrics.incr("rejected_invalid")
            raise RejectedError(f"unknown priority {priority!r}")
        deadline = (time.perf_counter() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        req = Request(rid, norm, rows, priority, deadline, group_key, var_len)
        try:
            with self._cond:
                self._queue.put(req, retry_after_s=self._drain_estimate())
                self._cond.notify()
        except RejectedError as e:
            self._metrics.incr("rejected")
            self._metrics.incr("rejected_shutdown" if self._queue.closed()
                               else "rejected_queue_full")
            raise e
        self._metrics.incr("admitted")
        return req.response

    def _validate(self, inputs):
        """Strict admission against the program's declared feeds: right
        names, right dtypes, right concrete trailing dims. Anything the
        warmed lattice can't serve bit-exactly is refused here — after
        this point a request can only fail at runtime, never retrace."""
        if not isinstance(inputs, dict):
            raise RejectedError("inputs must be {feed_name: array}")
        names = set(inputs)
        expect = set(self._feed_specs)
        if names != expect:
            raise RejectedError(
                f"inputs {sorted(names)} != declared feeds {sorted(expect)}"
            )
        norm = {}
        for n, v in inputs.items():
            arr = np.ascontiguousarray(v)
            shape, dtype = self._feed_specs[n]
            if dtype and str(arr.dtype) != dtype:
                raise RejectedError(
                    f"input '{n}' dtype {arr.dtype} != declared {dtype}; "
                    "cast before submitting (dtype is part of the compile "
                    "bucket key)"
                )
            if shape:
                if arr.ndim != len(shape):
                    raise RejectedError(
                        f"input '{n}' rank {arr.ndim} != declared "
                        f"{len(shape)} ({shape})"
                    )
                for i, d in enumerate(shape):
                    if i == 0 or int(d) == -1:
                        continue
                    if int(arr.shape[i]) != int(d):
                        raise RejectedError(
                            f"input '{n}' dim {i} is {arr.shape[i]}, "
                            f"declared {d}"
                        )
            norm[n] = arr
        return norm

    def _drain_estimate(self):
        """Caller-side backpressure floor: time for the current queue to
        drain at the observed batch rate (bounded; 50ms default before
        any data). The queue combines this with its own measured
        drain-rate estimate and reports the larger of the two.
        O(1) — it runs on every submit under the queue lock."""
        per_batch = self._metrics.run_avg_s() or 0.05
        batches = (self._queue.depth() / float(self._lattice.max_rows)
                   / max(len(self._replicas), 1))
        return min(max(per_batch * max(batches, 1.0), 0.005), 5.0)

    # -- worker loop -------------------------------------------------------
    def _worker(self, replica, breaker=None):
        while True:
            probing = False
            # quarantine gate (bypassed while draining: every queued
            # request deserves an answer attempt on shutdown)
            if breaker is not None and not self._stop:
                verdict, wait_s = breaker.gate()
                if verdict == "wait":
                    with self._cond:
                        # deadlines keep expiring while quarantined — a
                        # single-replica engine must still reject dead
                        # requests at their deadline, not after cooldown
                        for r in self._queue.expire():
                            self._reject_expired(r)
                        if not self._stop:
                            self._cond.wait(timeout=min(wait_s, 0.1))
                    continue
                probing = verdict == "probe"
            with self._cond:
                for r in self._queue.expire():
                    self._reject_expired(r)
                plan = self._batcher.plan(self._queue, force=self._stop)
                if plan is None:
                    if self._stop and self._queue.empty():
                        return
                    self._cond.wait(
                        timeout=max(
                            self._batcher.wait_hint(self._queue), 0.0005
                        )
                    )
                    continue
            if probing:
                self._metrics.incr("breaker_probes")
            self._execute(replica, plan, breaker)

    def _reject_expired(self, request):
        self._metrics.incr("deadline_missed")
        request.response._complete(error=DeadlineExceededError(
            "deadline expired after "
            f"{time.perf_counter() - request.submit_time:.3f}s in queue"
        ))
        self._metrics.observe_request(request)

    def _breaker_event(self, event):
        if event:
            self._metrics.incr(event)

    def _execute(self, replica, plan, breaker=None):
        t0 = time.perf_counter()
        try:
            feeds = self._batcher.assemble(plan)
            with profiler.RecordEvent("serving::batch_run"):
                faults.fire("serving.run_batch")
                outputs = replica.run_batch(feeds)
        except Exception:
            # one request poisoned the batch (bad buffer, runtime fault):
            # isolate by re-running each request alone at its own lattice
            # point (still warmed — no retrace) so only the poison fails.
            # The breaker counts the batch-level outcome — K consecutive
            # of these quarantine the replica.
            self._metrics.incr("batch_failures")
            if breaker is not None:
                self._breaker_event(breaker.record_failure())
            self._isolate(replica, plan)
            return
        if breaker is not None:
            self._breaker_event(breaker.record_success())
        self._metrics.observe_batch(plan, time.perf_counter() - t0)
        for req, res in zip(plan.requests,
                            self._batcher.scatter(plan, outputs)):
            req.response._complete(outputs=res)
            self._metrics.incr("completed", 1)
            self._metrics.observe_request(req)

    def _isolate(self, replica, plan):
        for req in plan.requests:
            single = BatchPlan(
                [req], self._lattice.bucket_rows(req.rows), plan.bucket_len
            )
            t0 = time.perf_counter()
            try:
                feeds = self._batcher.assemble(single)
                with profiler.RecordEvent("serving::isolated_run"):
                    faults.fire("serving.run_batch")
                    outputs = replica.run_batch(feeds)
            except Exception as e:
                self._metrics.incr("failed")
                req.response._complete(error=RequestError(
                    f"request {req.id} failed: {e}"
                ))
                self._metrics.observe_request(req)
                continue
            self._metrics.observe_batch(single, time.perf_counter() - t0)
            req.response._complete(
                outputs=self._batcher.scatter(single, outputs, request=req)[0]
            )
            self._metrics.incr("completed", 1)
            self._metrics.observe_request(req)

    # -- observability -----------------------------------------------------
    def stats(self):
        """One coherent snapshot: queue, batcher, latency, and the
        post-warmup compile-cache hit rate (1.0 == zero retraces)."""
        cs = self._base.cache_stats()
        hits = cs["hits"] - self._warm_base["hits"]
        misses = cs["misses"] - self._warm_base["misses"]
        breakers = [b.state for b in self._breakers if b is not None]
        return self._metrics.snapshot(extra={
            **self._metrics.queue_snapshot(self._queue),
            "num_replicas": len(self._replicas),
            "breaker_states": breakers,
            "breaker_open_replicas": sum(
                1 for s in breakers if s != "closed"
            ),
            "batch_buckets": list(self._lattice.batch_sizes),
            "seq_buckets": (list(self._lattice.seq_lens)
                            if self._lattice.seq_lens else None),
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": hits / max(hits + misses, 1),
            "compile_seconds": cs["compile_s"],
        })

    @property
    def metrics(self):
        return self._metrics

    @property
    def lattice(self):
        return self._lattice

    @property
    def predictor(self):
        return self._base
