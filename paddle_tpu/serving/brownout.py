"""Adaptive brownout controller: a severity ladder between "full
service" and "turn traffic away".

Pressure is the max of three normalized signals the serving stack
already measures — KV pool occupancy, queue depth against its drain
rate (how many seconds of work are queued), and deadline headroom (how
close the most urgent queued request is to missing its SLO). The
controller maps pressure onto severity levels L0..L4, each degrading
something OUTPUT-INVISIBLE before the next one sheds:

========  ==========================================================
severity  effect (all byte-exactness-preserving for admitted requests)
========  ==========================================================
L0        full service
L1        new speculative requests lose their draft-KV slot (the
          draft replays instead — same committed tokens, more steps)
L2        speculation disabled for new requests; chunked-prefill
          budget shrinks to one advancing prompt per iteration
L3        new beam admissions capped at width ``beam_cap``; LOW-lane
          dispatch quota tightened to zero (queued LOW waits)
L4        non-HIGH admissions shed with a measured retry-after
========  ==========================================================

Escalation is immediate (pressure >= ``enter[i]`` jumps straight to the
highest qualifying level); de-escalation is hysteretic — one level at a
time, and only after ``hold`` consecutive evaluations below that
level's ``exit`` threshold — so the ladder never flaps around a
threshold. Every transition is recorded with the trigger signal and its
value (the OVERLOAD_EVIDENCE witness).

The controller is a pure hand-steppable object: no threads, no clocks —
callers feed signals, it returns a level.
"""

__all__ = ["BrownoutController", "SEVERITY_NAMES"]

SEVERITY_NAMES = ("l0_full", "l1_no_draft_kv", "l2_no_spec",
                  "l3_caps", "l4_shed")


class BrownoutController:
    """Severity ladder with asymmetric hysteresis.

    ``enter[i]`` / ``exit[i]`` govern level ``i + 1``: pressure >=
    ``enter[i]`` escalates to (at least) ``i + 1`` immediately;
    de-escalating FROM ``i + 1`` needs ``hold`` consecutive steps with
    pressure < ``exit[i]``. ``exit[i] < enter[i]`` is the hysteresis
    band."""

    LEVELS = 4
    SIGNALS = ("occupancy", "queue_seconds", "deadline")

    def __init__(self, enter=(0.60, 0.75, 0.85, 0.95),
                 exit=(0.45, 0.60, 0.70, 0.80), hold=3, beam_cap=2):
        if len(enter) != self.LEVELS or len(exit) != self.LEVELS:
            raise ValueError(f"need {self.LEVELS} enter/exit thresholds")
        for en, ex in zip(enter, exit):
            if not ex < en:
                raise ValueError(
                    f"hysteresis requires exit < enter, got {ex} >= {en}")
        self.enter = tuple(float(x) for x in enter)
        self.exit = tuple(float(x) for x in exit)
        self.hold = int(hold)
        self.beam_cap = int(beam_cap)
        self.level = 0
        self.steps = 0
        self.transitions = []    # {"step", "from", "to", "trigger", "value"}
        self._clear_streak = 0

    def _pressure(self, occupancy, queue_seconds, deadline):
        """Normalize the three signals onto [0, 1] and take the max —
        the binding constraint names the trigger. ``queue_seconds`` is
        queued work over drain rate, saturating at ``1.0`` when a full
        second of work is backed up; ``deadline`` is ``1 - headroom /
        budget`` for the most urgent queued request."""
        sig = {
            "occupancy": min(max(float(occupancy), 0.0), 1.0),
            "queue_seconds": min(max(float(queue_seconds), 0.0), 1.0),
            "deadline": min(max(float(deadline), 0.0), 1.0),
        }
        trigger = max(sig, key=lambda k: sig[k])
        return sig[trigger], trigger, sig

    def step(self, occupancy=0.0, queue_seconds=0.0, deadline=0.0):
        """One evaluation. Returns the (possibly new) severity level."""
        self.steps += 1
        pressure, trigger, sig = self._pressure(
            occupancy, queue_seconds, deadline)
        target = 0
        for i in range(self.LEVELS):
            if pressure >= self.enter[i]:
                target = i + 1
        if target > self.level:
            self.transitions.append({
                "step": self.steps, "from": self.level, "to": target,
                "trigger": trigger, "value": round(pressure, 4),
            })
            self.level = target
            self._clear_streak = 0
        elif self.level > 0 and pressure < self.exit[self.level - 1]:
            self._clear_streak += 1
            if self._clear_streak >= self.hold:
                self.transitions.append({
                    "step": self.steps, "from": self.level,
                    "to": self.level - 1, "trigger": trigger,
                    "value": round(pressure, 4),
                })
                self.level -= 1
                self._clear_streak = 0
        else:
            self._clear_streak = 0
        return self.level

    @property
    def name(self):
        return SEVERITY_NAMES[self.level]

    def snapshot(self):
        return {
            "level": self.level,
            "name": self.name,
            "steps": self.steps,
            "transitions": [dict(t) for t in self.transitions],
            "enter": list(self.enter),
            "exit": list(self.exit),
            "hold": self.hold,
            "beam_cap": self.beam_cap,
        }
