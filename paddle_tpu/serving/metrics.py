"""Serving metrics: always-on registry-backed counters + latency histograms.

One instrumentation point, three sinks. The engine records into the
process-global observability registry (a service must answer `stats()`
and a Prometheus scrape whether or not anyone is profiling), every
recording is mirrored into profiler.py's event/counter machinery so a
`with profiler.profiler():` session shows serving counters next to the
framework's own events, and the engine's spans (queue wait, batch run)
ride the tracer. Each ServingMetrics instance is one `engine=<label>`
label set, so two engines in a process scrape as two series while each
engine's `stats()` stays exact.

Latency percentiles come from bucketed histograms (p50/p95/p99 by
linear interpolation inside the target bucket) — O(buckets) memory at
any traffic level, where the old ring-buffer reservoir held 4096
samples per series.

Per-lane queue-depth gauges (`serving_queue_lane_depth{engine,lane}`)
and per-tenant counters (`serving_tenant_<name>_total{engine,tenant}`)
ride the same engine label set: a scrape shows which priority lane is
backed up and which tenant is consuming the capacity, and the same
numbers flow through `stats()` into the C-ABI stats JSON.
"""

import itertools

from paddle_tpu import profiler
from paddle_tpu.observability import lockdep
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.serving.request import Priority

__all__ = ["ServingMetrics"]

_ENGINE_SEQ = itertools.count()

LANE_NAMES = {Priority.HIGH: "high", Priority.NORMAL: "normal",
              Priority.LOW: "low"}


class ServingMetrics:
    COUNTERS = (
        "submitted", "admitted", "rejected", "rejected_queue_full",
        "rejected_shutdown", "rejected_invalid", "deadline_missed",
        "completed", "failed", "batches", "batched_rows", "padded_rows",
        # replica circuit breaker (engine.py): quarantine/probe lifecycle
        "batch_failures", "breaker_opened", "breaker_probes",
        "breaker_closed", "breaker_reopened",
    )

    def __init__(self, engine_label=None, registry=None):
        self._registry = registry or obs_metrics.registry()
        self.engine_label = (engine_label
                            or f"engine-{next(_ENGINE_SEQ)}")
        labels = {"engine": self.engine_label}
        self._counts = {
            name: self._registry.counter(
                f"serving_{name}_total", f"serving {name} count",
                labels=labels,
            )
            for name in self.COUNTERS
        }
        self._queue_wait = self._registry.histogram(
            "serving_queue_wait_seconds",
            "submit-to-dispatch wait", labels=labels,
        )
        self._run = self._registry.histogram(
            "serving_run_seconds", "batch execution latency", labels=labels,
        )
        self._total = self._registry.histogram(
            "serving_latency_seconds", "submit-to-finish latency",
            labels=labels,
        )
        # float sum feeding avg_batch_occupancy; a Counter because it only
        # grows (sum of per-batch occupancies in (0, 1])
        self._occupancy_sum = self._registry.counter(
            "serving_batch_occupancy_sum",
            "sum of per-batch row occupancy", labels=labels,
        )
        self._lane_depth = {
            lane: self._registry.gauge(
                "serving_queue_lane_depth",
                "queued rows per priority lane",
                labels={**labels, "lane": name},
            )
            for lane, name in LANE_NAMES.items()
        }
        self._tenant_counts = {}  # (counter_name, tenant) -> Counter
        self._tenant_lock = lockdep.named_lock("serving.metrics.tenant")
        # batches/batched_rows/occupancy must move together for the
        # derived averages in snapshot() to be consistent
        self._batch_lock = lockdep.named_lock("serving.metrics.batch")
        # a ServingMetrics instance is one engine LIFETIME: re-creating an
        # engine under a reused label must start from zero (the registry
        # series are get-or-create, so without this a restart would resume
        # the previous engine's totals)
        for series in list(self._counts.values()) + [
            self._queue_wait, self._run, self._total, self._occupancy_sum,
        ] + list(self._lane_depth.values()):
            series.reset()

    def incr(self, name, n=1):
        self._counts[name].inc(n)
        profiler.incr_counter(f"serving.{name}", n)

    def tenant_incr(self, name, tenant, n=1):
        """Per-tenant counter `serving_tenant_<name>_total{engine,tenant}`
        (get-or-create per label set; tenants are few and long-lived)."""
        key = (name, tenant)
        c = self._tenant_counts.get(key)
        if c is None:
            with self._tenant_lock:
                c = self._tenant_counts.get(key)
                if c is None:
                    c = self._registry.counter(
                        f"serving_tenant_{name}_total",
                        f"per-tenant serving {name} count",
                        labels={"engine": self.engine_label,
                                "tenant": str(tenant)},
                    )
                    c.reset()
                    self._tenant_counts[key] = c
        c.inc(n)

    def tenant_counts(self, name):
        """{tenant: count} snapshot for one per-tenant counter family."""
        with self._tenant_lock:  # tenant_incr inserts concurrently
            items = list(self._tenant_counts.items())
        return {t: c.value for (n, t), c in items if n == name}

    def set_lane_depths(self, depths):
        """Update the per-lane queue-depth gauges from
        `RequestQueue.lane_depths()`."""
        for lane, rows in depths.items():
            g = self._lane_depth.get(lane)
            if g is not None:
                g.set(rows)

    def queue_snapshot(self, queue):
        """ONE consistent `queue.stats()` read shaped into the shared
        `stats()` extra keys (depth and lane depths from the same lock
        acquisition), updating the per-lane gauges on the way — the
        single definition both engines' stats() methods use."""
        qs = queue.stats()
        lane_depths = qs.pop("lane_depths")
        self.set_lane_depths(lane_depths)
        return {
            "queue_depth": qs["depth"],
            "queue_lane_depths": {
                name: lane_depths.get(lane, 0)
                for lane, name in LANE_NAMES.items()
            },
            "queue_drain_rate_rows_per_s": qs["drain_rate_rows_per_s"],
            "queue_rejected_at_admission": qs["rejected_at_admission"],
            "queue_expired_in_queue": qs["expired_in_queue"],
            "queue_rerouted": qs["rerouted"],
        }

    def observe_batch(self, plan, run_seconds):
        with self._batch_lock:
            self._counts["batches"].inc()
            self._counts["batched_rows"].inc(plan.real_rows)
            self._counts["padded_rows"].inc(plan.bucket_rows - plan.real_rows)
            self._occupancy_sum.inc(plan.occupancy)
        self._run.observe(run_seconds)
        profiler.incr_counter("serving.batches")
        profiler.incr_counter("serving.batched_rows", plan.real_rows)

    def observe_request(self, request):
        """Called at completion: queue-wait + end-to-end latency."""
        finish = request.response.finish_time
        if request.dispatch_time is not None:
            self._queue_wait.observe(
                request.dispatch_time - request.submit_time
            )
        if finish is not None:
            self._total.observe(finish - request.submit_time)

    def count(self, name):
        return self._counts[name].value

    def run_avg_s(self):
        """O(1) mean batch-run latency (no percentile math — safe on
        the admission hot path)."""
        return self._run.avg

    def snapshot(self, extra=None):
        with self._batch_lock:
            out = {name: c.value for name, c in self._counts.items()}
            occupancy_sum = self._occupancy_sum.value
        batches = max(out["batches"], 1)
        out["avg_batch_occupancy"] = occupancy_sum / batches
        out["avg_batch_rows"] = out["batched_rows"] / batches
        out.update(self._queue_wait.snapshot("queue_wait"))
        out.update(self._run.snapshot("run"))
        out.update(self._total.snapshot("latency"))
        if extra:
            out.update(extra)
        return out
