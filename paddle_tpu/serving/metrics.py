"""Serving metrics: always-on counters + latency reservoirs.

Two sinks, one instrumentation point. The engine records into this
module's always-on structures (a service must answer `stats()` whether
or not anyone is profiling), and every recording is mirrored into
profiler.py's event/counter machinery so a `with profiler.profiler():`
session shows serving spans (queue wait, batch run) and counters next to
the framework's own events — the same RecordEvent stream the reference
used for op dispatch.
"""

import threading

from paddle_tpu import profiler

__all__ = ["ServingMetrics"]

_RESERVOIR = 4096  # newest-N latency window per series


class _Latency:
    """Windowed latency series: count/total over all samples, percentile
    over the newest `_RESERVOIR` (ring buffer — recent behavior is what
    an SLO dashboard wants)."""

    __slots__ = ("count", "total", "ring", "pos")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.ring = []
        self.pos = 0

    def add(self, seconds):
        self.count += 1
        self.total += seconds
        if len(self.ring) < _RESERVOIR:
            self.ring.append(seconds)
        else:
            self.ring[self.pos] = seconds
            self.pos = (self.pos + 1) % _RESERVOIR

    def percentile(self, p):
        if not self.ring:
            return 0.0
        data = sorted(self.ring)
        k = min(len(data) - 1, max(0, int(round((p / 100.0) * (len(data) - 1)))))
        return data[k]

    def snapshot(self, prefix):
        return {
            f"{prefix}_count": self.count,
            f"{prefix}_avg_s": self.total / max(self.count, 1),
            f"{prefix}_p50_s": self.percentile(50),
            f"{prefix}_p99_s": self.percentile(99),
        }


class ServingMetrics:
    COUNTERS = (
        "submitted", "admitted", "rejected", "rejected_queue_full",
        "rejected_shutdown", "rejected_invalid", "deadline_missed",
        "completed", "failed", "batches", "batched_rows", "padded_rows",
        # replica circuit breaker (engine.py): quarantine/probe lifecycle
        "batch_failures", "breaker_opened", "breaker_probes",
        "breaker_closed", "breaker_reopened",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {name: 0 for name in self.COUNTERS}
        self._queue_wait = _Latency()
        self._run = _Latency()
        self._total = _Latency()
        self._occupancy_sum = 0.0

    def incr(self, name, n=1):
        with self._lock:
            self._counts[name] += n
        profiler.incr_counter(f"serving.{name}", n)

    def observe_batch(self, plan, run_seconds):
        with self._lock:
            self._counts["batches"] += 1
            self._counts["batched_rows"] += plan.real_rows
            self._counts["padded_rows"] += plan.bucket_rows - plan.real_rows
            self._occupancy_sum += plan.occupancy
            self._run.add(run_seconds)
        profiler.incr_counter("serving.batches")
        profiler.incr_counter("serving.batched_rows", plan.real_rows)

    def observe_request(self, request):
        """Called at completion: queue-wait + end-to-end latency."""
        finish = request.response.finish_time
        with self._lock:
            if request.dispatch_time is not None:
                self._queue_wait.add(
                    request.dispatch_time - request.submit_time
                )
            if finish is not None:
                self._total.add(finish - request.submit_time)

    def count(self, name):
        with self._lock:
            return self._counts[name]

    def run_avg_s(self):
        """O(1) mean batch-run latency (no percentile sorts — safe on
        the admission hot path)."""
        with self._lock:
            return self._run.total / max(self._run.count, 1)

    def snapshot(self, extra=None):
        with self._lock:
            out = dict(self._counts)
            batches = max(out["batches"], 1)
            out["avg_batch_occupancy"] = self._occupancy_sum / batches
            out["avg_batch_rows"] = out["batched_rows"] / batches
            out.update(self._queue_wait.snapshot("queue_wait"))
            out.update(self._run.snapshot("run"))
            out.update(self._total.snapshot("latency"))
        if extra:
            out.update(extra)
        return out
