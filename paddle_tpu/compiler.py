"""CompiledProgram: data-parallel compilation over a device mesh.

TPU-native replacement for the reference's ParallelExecutor pipeline
(reference: python/paddle/fluid/compiler.py:87 CompiledProgram,
:160 with_data_parallel; paddle/fluid/framework/parallel_executor.cc:402).
Where the reference builds a per-device SSA graph and inserts one NCCL
allreduce op-handle per gradient (reference: paddle/fluid/framework/ir/
multi_devices_graph_pass/multi_devices_graph_pass.h:110), here the step
function is jit-compiled with the batch dimension sharded over a 1-D mesh
axis: GSPMD partitions the whole computation, and the gradient all-reduces
over ICI fall out of partitioning the batch reductions — fused, scheduled,
and overlapped by XLA rather than hand-built op handles. BuildStrategy knobs
therefore collapse into sharding config.
"""

import warnings

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.executor import (
    _CACHE_HITS,
    _CACHE_MISSES,
    _interpret_block,
    plan_step,
)
from paddle_tpu.core.scope import global_scope
from paddle_tpu.observability.tracer import trace_scope
from paddle_tpu.parallel.env import make_mesh, shard_map as _shard_map
from paddle_tpu.utils.enforce import EnforceError, enforce
from paddle_tpu.utils.flags import flags


def _to_global(arr, sharding):
    """Commit a host value to a (possibly multi-process) mesh sharding.

    Single-process meshes take the fast device_put path. In a
    multi-controller job (the reference's multi-trainer NCCL world,
    SURVEY §2.8) the mesh spans processes, where numpy inputs must become
    global jax.Arrays explicitly; every process feeds the same full-size
    value, and each host materializes only its addressable shards."""
    if isinstance(arr, jax.Array) and arr.sharding == sharding:
        return arr  # steady state: the previous step's output, already global
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(arr, sharding)
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        # global -> global reshard (supported device_put path)
        return jax.device_put(arr, sharding)
    np_arr = np.asarray(arr)
    return jax.make_array_from_callback(
        np_arr.shape, sharding, lambda idx: np_arr[idx]
    )


def _to_global_verified(scope, name, sharding, store):
    """_to_global with the scope's verified-cache fast path (the mesh
    twin of Executor._committed): a value the previous step wrote back
    under this exact sharding OBJECT (cache entries hold stable ones)
    skips the per-step sharding comparison — one dict lookup + identity
    check for ~600 entries on a real model. The set holds a strong
    reference to the sharding, so the identity can never be recycled;
    user-facing scope.set invalidates.

    `store=False` for DONATED inputs: their committed buffer is consumed
    by the step, so storing it would leave a deleted array in the scope
    whenever the step fails (or forever, for a parent-scope param) — the
    post-step write-back is their only legitimate store. Their steady
    state still fast-paths: the write-back marks the output verified."""
    owner = scope._find_owner(name)
    if owner is not None:
        ver = owner._device_verified.get(name)
        if ver is not None and len(ver) == 1 and \
                next(iter(ver)) is sharding:
            return owner._vars[name]
    out = _to_global(scope.find_var(name), sharding)
    if store:
        # child-scope store (shadowing a parent var, like scope.set
        # always has): the parent keeps its original valid value
        scope._set_verified(name, out, sharding)
    return out


class BuildStrategy:
    """Accepted for API parity (reference: paddle/fluid/framework/details/
    build_strategy.h:37). Fusion/memory-opt toggles are XLA's job here:
    operator fusion happens in the XLA compiler, memory reuse comes from
    buffer donation (core/executor.py), and all-reduce fusion from GSPMD's
    collective combiner — flipping those fields changes NOTHING and says
    so once (a silent no-op would let a tuning session chase a knob that
    is not connected). The meaningful knobs map to sharding choices."""

    #: parity-only fields: owned by XLA/GSPMD/donation on this backend
    _XLA_OWNED = {
        "fuse_all_reduce_ops": "GSPMD's all-reduce combiner",
        "fuse_elewise_add_act_ops": "XLA fusion",
        "memory_optimize": "XLA buffer assignment + donation",
        "enable_inplace": "buffer donation (FLAGS_use_donation)",
    }
    _warned = set()

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        d = object.__setattr__
        d(self, "reduce_strategy", BuildStrategy.ReduceStrategy.AllReduce)
        d(self, "fuse_all_reduce_ops", True)
        d(self, "fuse_elewise_add_act_ops", True)
        d(self, "memory_optimize", True)
        d(self, "enable_inplace", True)
        d(self, "num_trainers", 1)
        d(self, "trainer_id", 0)

    def __setattr__(self, name, value):
        owner = self._XLA_OWNED.get(name)
        if owner is not None and name not in BuildStrategy._warned:
            BuildStrategy._warned.add(name)
            warnings.warn(
                f"BuildStrategy.{name} is a no-op on this backend: "
                f"{owner} owns that optimization (set once per process; "
                "this message will not repeat)",
                stacklevel=2,
            )
        object.__setattr__(self, name, value)


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._mesh = None
        self._loss_name = None
        self._share_vars_from = None
        self._cache = {}
        self._param_rules = None      # pattern -> spec table (sharding.py)
        self._param_overrides = None  # exact name -> spec
        self._input_specs = None      # feed name -> spec (default: batch on 'data')
        self._axis_tags = None        # mesh axis -> 'ici'|'dcn' (cost stage)
        self._pipeline_schedule = None   # 'gpipe'|'1f1b' (pipeline_stack)
        self._pipeline_interleave = None  # 1f1b chunks/device (default 2)
        self._spec_layout = None      # SpecLayout | False (off) | None (auto)
        self._auto_layout_cache = {}  # (prog uid, version) -> SpecLayout|None

    @property
    def program(self):
        return self._program

    def with_data_parallel(
        self,
        loss_name=None,
        build_strategy=None,
        exec_strategy=None,
        share_vars_from=None,
        places=None,
    ):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._share_vars_from = share_vars_from
        devices = None
        if places is not None:
            devices = [p.jax_device() for p in places]
        self._mesh = make_mesh(devices=devices)
        return self

    def with_parallel(
        self,
        mesh=None,
        loss_name=None,
        param_rules=None,
        param_specs=None,
        input_specs=None,
        spec_layout=None,
        axis_tags=None,
        pipeline_schedule=None,
        pipeline_interleave=None,
    ):
        # spec_layout contract: an instance/True = that registry;
        # False = placement stays exactly as passed (pre-PR-9 behavior);
        # None (the default) = AUTO — meshes with a tp/fsdp axis and no
        # other placement source get the canonical registry, gated behind
        # the static sharding analyzer proving the registry leaves zero
        # weight-sized collectives for THIS program (see _auto_spec_layout)
        """Generic SPMD compilation over an n-D mesh: DP (batch on 'data'),
        Megatron TP (params matched by `param_rules`/`param_specs` sharded on
        'model'), and context/sequence parallelism (feeds sharded on 'seq'
        via `input_specs`) in one mechanism. GSPMD propagates the shardings
        through the whole traced block and inserts the ICI collectives —
        the TPU-native answer to the reference's per-strategy graph builders
        (reference: paddle/fluid/framework/ir/multi_devices_graph_pass/
        multi_devices_graph_pass.h:39-182, one C++ builder per strategy).

        ``spec_layout`` routes parameter placement through the canonical
        sharding layer (parallel/spec_layout.py): every parameter gets a
        role-derived PartitionSpec (embeddings, column/row matmuls, norm
        scales, optimizer slots inheriting their parent), ``param_specs``
        still wins as exact per-var overrides, and the layout fingerprint
        joins the compile-cache program fingerprint. ``True`` means "the
        default registry"."""
        self._is_data_parallel = True
        self._loss_name = loss_name
        self._mesh = mesh if mesh is not None else make_mesh()
        self._param_rules = param_rules
        self._param_overrides = param_specs
        self._input_specs = input_specs
        # axis_tags: mesh axis -> 'ici' | 'dcn', consumed by the 'cost'
        # static diagnostic stage's two-level collective model; declaring
        # a 'dcn' axis arms the hierarchical-collective linter as an error
        self._axis_tags = dict(axis_tags) if axis_tags else None
        # pipeline_schedule: schedule choice for pipeline_stack ops —
        # compile-cache CONTENT (joins the cheap key and the lowering
        # fingerprint), bound to the lowering via schedule_override so the
        # op and the cache key can never disagree. Validated eagerly so a
        # typo fails here, not mid-trace.
        if pipeline_schedule is not None:
            from paddle_tpu.parallel.pipeline_runtime.schedule import (
                SCHEDULE_KINDS,
            )

            if pipeline_schedule not in SCHEDULE_KINDS:
                raise EnforceError(
                    f"with_parallel: unknown pipeline_schedule "
                    f"{pipeline_schedule!r}; kinds are {SCHEDULE_KINDS}"
                )
        self._pipeline_schedule = pipeline_schedule
        self._pipeline_interleave = (
            int(pipeline_interleave) if pipeline_interleave else None
        )
        if spec_layout is True:
            from paddle_tpu.parallel.spec_layout import SpecLayout

            spec_layout = SpecLayout()
        if spec_layout not in (None, False) and param_rules is not None:
            # one placement authority: a pattern table alongside the
            # registry would be silently ignored — refuse instead (exact
            # per-var pins belong in param_specs / layout.override())
            raise EnforceError(
                "with_parallel: pass either spec_layout (the role "
                "registry) or param_rules (a pattern table), not both; "
                "use param_specs or SpecLayout.override() for exact "
                "per-var placements"
            )
        self._spec_layout = spec_layout
        # the AUTO decision depends on everything set above (mesh geometry,
        # rules, input_specs) — a re-placement must re-run the analyzer gate
        self._auto_layout_cache.clear()
        return self

    # ------------------------------------------------------------------
    def _resolve_spec_layout(self, feed_arrays):
        """The spec_layout actually used for this compile.

        Explicit settings win: an instance is used as-is, ``False`` keeps
        the pre-registry behavior (everything not otherwise placed stays
        replicated). The ``None`` default is AUTO (ROADMAP item 1's
        remaining question): a mesh carrying a tp/fsdp axis with no other
        placement source (param_rules/param_specs) gets the canonical
        registry — but ONLY when the static sharding analyzer
        (analysis/sharding.py) proves the registry leaves zero
        weight-sized collectives for this exact program. If the analyzer
        predicts any (a parameter the registry cannot shard whose update
        would be gathered), placement falls back to the old replicated
        behavior rather than trade one gather pattern for another.
        Pure-dp meshes skip all of this and stay byte-identical."""
        if self._spec_layout is False:
            return None
        if self._spec_layout is not None:
            return self._spec_layout
        if self._param_rules is not None or self._param_overrides:
            return None
        from paddle_tpu.parallel.spec_layout import tensor_parallel_axes

        axis_sizes = dict(zip(self._mesh.axis_names,
                              self._mesh.devices.shape))
        if not tensor_parallel_axes(axis_sizes):
            return None  # pure dp/seq/ep/stage mesh: registry is a no-op
        key = (self._program._uid, self._program._version)
        if key in self._auto_layout_cache:
            return self._auto_layout_cache[key]
        from paddle_tpu.analysis.sharding import (
            analyze_sharding,
            weight_param_shapes,
            weight_sized_events,
        )
        from paddle_tpu.parallel.spec_layout import SpecLayout

        candidate = SpecLayout()
        feed_shapes = {
            n: tuple(np.shape(v)) for n, v in (feed_arrays or {}).items()
        }
        try:
            report = analyze_sharding(
                self._program, self._mesh, spec_layout=candidate,
                input_specs=self._input_specs, feed_shapes=feed_shapes,
            )
            offenders = weight_sized_events(
                report, weight_param_shapes(self._program)
            )
        except Exception as e:  # analyzer must never break a compile
            warnings.warn(
                f"spec_layout auto-default skipped: static sharding "
                f"analysis failed ({e!r}); parameters stay replicated "
                f"(pass spec_layout=True to force the registry)"
            )
            offenders = [object()]
        chosen = None if offenders else candidate
        self._auto_layout_cache[key] = chosen
        return chosen

    # ------------------------------------------------------------------
    def _run(self, exe, feed, fetch_list, scope, return_numpy):
        if not self._is_data_parallel:
            return exe.run(
                self._program, feed, fetch_list, scope, return_numpy
            )
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        fetch_names = [f if isinstance(f, str) else f.name for f in fetch_list]
        from paddle_tpu.passes import (
            apply_deferred_sharded_embedding_rewrite,
            apply_deferred_sparse_rewrite,
            resolve_tensor_array_indices,
        )

        apply_deferred_sparse_rewrite(self._program)
        apply_deferred_sharded_embedding_rewrite(self._program)
        resolve_tensor_array_indices(self._program)
        block = self._program.global_block()
        mesh = self._mesh
        n_dev = int(np.prod(mesh.devices.shape))

        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        batch_axis = "data" if "data" in mesh.axis_names else mesh.axis_names[0]
        input_specs = self._input_specs or {}
        feed_arrays = {}
        for name, value in feed.items():
            arr = np.asarray(value) if not isinstance(value, jax.Array) else value
            # validate divisibility against the axes the feed's dim 0 is
            # actually sharded over (default: the batch axis)
            spec = input_specs.get(name, P(batch_axis))
            dim0_axes = ()
            if len(spec) > 0 and spec[0] is not None:
                dim0_axes = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
            shard = int(np.prod([axis_sizes.get(a, 1) for a in dim0_axes] or [1]))
            enforce(
                arr.ndim == 0 or arr.shape[0] % shard == 0,
                f"feed '{name}' dim 0 ({arr.shape[0] if arr.ndim else 1}) must "
                f"divide its sharding {dim0_axes} (total {shard})",
            )
            feed_arrays[name] = arr

        feed_names = sorted(feed_arrays)
        feed_sig = tuple(
            (n, tuple(feed_arrays[n].shape), str(np.asarray(feed_arrays[n]).dtype))
            for n in feed_names
        )
        # DGC sparse-exchange mode (reference: details/
        # sparse_all_reduce_op_handle.h): a data-parallel program carrying
        # dgc_momentum ops runs the WHOLE block per-shard under shard_map
        # so per-shard gradients exist for the top-k (index, value)
        # all_gather; U/V become per-shard state with a leading shard axis.
        # Requires a pure-DP mesh and no nested-manual ops; otherwise the
        # dense fused form runs with a warning.
        dgc_state = set()
        for op in block.ops:
            if op.type == "dgc_momentum":
                dgc_state.update(op.inputs.get("U", ()))
                dgc_state.update(op.inputs.get("V", ()))
        n_batch = axis_sizes.get(batch_axis, 1)
        dgc_sparse = bool(dgc_state) and n_batch > 1 and \
            flags.dgc_sparse_exchange
        if dgc_sparse:
            # ops whose lowerings open their OWN shard_map cannot nest
            # inside the per-shard DGC region
            def _opens_shard_map(op):
                if op.type in ("pipeline_stack",) or op.type.startswith("c_"):
                    return True
                if op.type == "moe_ffn":
                    ax = op.attrs.get("expert_axis", "expert")
                    return axis_sizes.get(ax, 1) > 1
                if op.type == "scaled_dot_product_attention" and \
                        op.attrs.get("seq_parallel"):
                    ax = op.attrs.get("seq_axis", "seq")
                    return axis_sizes.get(ax, 1) > 1
                return False

            def _batch_stat_writeback(op):
                # ops whose persistable write-back is computed FROM the
                # batch (running stats, class centers): per-shard execution
                # would store shard-varying values through a replicated
                # out_spec — silently wrong state
                return (
                    op.type in ("batch_norm", "data_norm", "center_loss")
                    and not op.attrs.get("is_test", False)
                )

            manual_ops = {
                op.type for op in block.ops
                if _opens_shard_map(op) or _batch_stat_writeback(op)
            }
            multi_axis = any(
                s > 1 for a, s in axis_sizes.items() if a != batch_axis
            )
            if manual_ops or multi_axis:
                warnings.warn(
                    "DGCMomentumOptimizer: sparse exchange needs a pure "
                    f"data-parallel mesh without manual-region ops (found "
                    f"{sorted(manual_ops) or 'multi-axis mesh'}); falling "
                    "back to the dense fused form (no wire savings)"
                )
                dgc_sparse = False
        from paddle_tpu.kernels import registry as _kernel_registry

        # resolved kernel mode joins the cheap key (see executor.py)
        key = (self._program._uid, self._program._version, feed_sig,
               tuple(fetch_names), dgc_sparse,
               _kernel_registry.resolved_mode(),
               self._pipeline_schedule, self._pipeline_interleave)
        entry = self._cache.get(key)
        if dgc_sparse:
            # expand U/V accumulators to per-shard [n, ...] state; runs on
            # EVERY call (a fresh scope behind a warm compile cache would
            # otherwise feed declared-shape state into the per-shard step).
            # The block var's declared shape distinguishes fresh from
            # expanded.
            for n in sorted(dgc_state):
                if not scope.has_var(n):
                    continue
                val = scope.find_var(n)
                # .shape alone — no host transfer on the steady-state path
                cur = tuple(np.shape(val))
                declared = tuple(
                    d for d in (block._find_var_recursive(n).shape or ())
                )
                if cur == declared:
                    arr = np.asarray(val)
                    scope.set(
                        n,
                        np.broadcast_to(arr, (n_batch,) + declared).copy(),
                    )
                elif cur != (n_batch,) + declared:
                    raise EnforceError(
                        f"dgc accumulator {n} has shape {cur}, "
                        f"expected {declared} or {(n_batch,) + declared}"
                    )
        if entry is None:
            with trace_scope("compiled_program::plan", ops=len(block.ops)):
                donated, readonly, written, live = plan_step(
                    block, feed_names, fetch_names, scope, flags.use_donation
                )
            # shapes below come from scope vars — all of them must exist
            # BEFORE the entry is built, or a poisoned entry gets cached
            absent = [n for n in donated + readonly if not scope.has_var(n)]
            if absent:
                raise EnforceError(
                    f"variables {absent} not initialized in scope "
                    f"(run the startup program first?)"
                )

            from paddle_tpu.parallel.sharding import check_spec, derive_shardings

            repl = NamedSharding(mesh, P())
            feed_shardings = []
            feed_specs = []
            for n in feed_names:
                spec = input_specs.get(n, P(batch_axis))
                spec = check_spec(tuple(np.shape(feed_arrays[n])), spec, mesh)
                feed_specs.append(spec)
                feed_shardings.append(NamedSharding(mesh, spec))

            if dgc_sparse:
                from jax import lax

                from paddle_tpu.parallel.env import dgc_axis_context

                # batch-shaped fetches would be SILENTLY averaged across
                # different examples by the per-shard pmean — refuse them
                # up front on declared shapes
                for n in fetch_names:
                    fv = block._find_var_recursive(n)
                    shape = tuple(fv.shape or ()) if fv is not None else ()
                    static = [d for d in shape if d and d > 0]
                    dynamic = any(d in (-1, None) or (d and d < 0)
                                  for d in shape)
                    non_float = fv is None or (
                        fv.dtype is not None and "float" not in str(fv.dtype)
                    )
                    if dynamic or non_float or \
                            int(np.prod(static or [1])) > 1:
                        raise EnforceError(
                            f"fetch '{n}' (declared shape {list(shape)}, "
                            f"dtype {getattr(fv, 'dtype', None)}) is not a "
                            "scalar float: DGC sparse-exchange mode runs "
                            "the block per-shard and can only fetch scalar "
                            "float losses/metrics (cross-shard means). "
                            "Fetch those, or disable the sparse exchange "
                            "with FLAGS_dgc_sparse_exchange=0"
                        )

                def make_step(blk, plan):
                    (p_feed, p_fetch, p_donated, p_readonly, p_written,
                     p_live) = plan

                    def step(feed_vals, donated_vals, readonly_vals, rng_key):
                        def local_step(feed_vals, donated_vals,
                                       readonly_vals, rng_key):
                            # decorrelate per-shard stochastic ops (dropout)
                            rng_key = jax.random.fold_in(
                                rng_key, lax.axis_index(batch_axis)
                            )
                            env = dict(zip(p_feed, feed_vals))
                            env.update(zip(p_donated, donated_vals))
                            env.update(zip(p_readonly, readonly_vals))
                            with dgc_axis_context(batch_axis):
                                _interpret_block(blk, env, rng_key,
                                                 ops=p_live)
                            # scalar float fetches (losses/metrics of the
                            # local shard) are cross-shard means;
                            # non-scalars were rejected at entry build (the
                            # local view here cannot tell a scalar from a
                            # batch shard)
                            fetches = []
                            for n in p_fetch:
                                val = env[n]
                                if "float" in str(val.dtype):
                                    val = lax.pmean(val, batch_axis)
                                fetches.append(val)
                            return fetches, [env.get(n) for n in p_written]

                        def state_spec(names):
                            return tuple(
                                P(batch_axis) if n in dgc_state else P()
                                for n in names
                            )

                        return _shard_map(
                            local_step,
                            mesh=mesh,
                            in_specs=(
                                tuple(feed_specs),
                                state_spec(p_donated),
                                state_spec(p_readonly),
                                P(),
                            ),
                            out_specs=(
                                [P()] * len(p_fetch),
                                list(state_spec(p_written)),
                            ),
                            # vma checking is off: param updates are
                            # invariant by construction (the sparse exchange
                            # all_gathers identical (idx, value) sets on
                            # every shard)
                            check_vma=False,
                        )(feed_vals, donated_vals, readonly_vals, rng_key)

                    return step
            else:
                # default step body (core/lowering.py) is exactly the
                # non-dgc form
                make_step = None
            scope_names = donated + readonly
            layout_sig = None
            spec_layout = self._resolve_spec_layout(feed_arrays)
            if spec_layout is not None:
                # canonical sharding layer: role-derived specs for every
                # scope input, exact param_specs layered on top
                scope_shardings = spec_layout.derive_shardings(
                    self._program,
                    scope_names,
                    [np.shape(scope.find_var(n)) for n in scope_names],
                    mesh,
                    overrides=self._param_overrides,
                )
                layout_sig = spec_layout.fingerprint()
            elif self._param_rules is not None or self._param_overrides:
                scope_shardings = derive_shardings(
                    scope_names,
                    [np.shape(scope.find_var(n)) for n in scope_names],
                    mesh,
                    rules=self._param_rules,
                    overrides=self._param_overrides,
                )
            else:
                scope_shardings = {n: repl for n in scope_names}
            if dgc_sparse:
                # per-shard U/V state lives sharded on the batch axis
                for n in dgc_state:
                    if n in scope_shardings:
                        scope_shardings[n] = NamedSharding(mesh, P(batch_axis))
            in_shardings = (
                tuple(feed_shardings),
                tuple(scope_shardings[n] for n in donated),
                tuple(scope_shardings[n] for n in readonly),
                repl,
            )
            # pin written-back state to its input sharding so params stay
            # sharded in the scope across steps (no reshard churn)
            out_shardings = (
                None,
                [scope_shardings.get(n) for n in written],
            )
            from paddle_tpu.core import lowering

            entry, source = lowering.lower_step(
                self._program, scope, feed_sig, fetch_names,
                donate=flags.use_donation, make_step=make_step,
                plan=(donated, readonly, written, live),
                mesh=mesh, in_shardings=in_shardings,
                out_shardings=out_shardings,
                layout_sig=layout_sig,
                placement={
                    "spec_layout": spec_layout,
                    "param_rules": self._param_rules,
                    "param_specs": self._param_overrides,
                    "input_specs": self._input_specs,
                    "axis_tags": self._axis_tags,
                },
                extra_fingerprint=(
                    ("dgc", dgc_sparse),
                    ("pipe_sched", self._pipeline_schedule,
                     self._pipeline_interleave),
                ),
                label="compiled_program",
            )
            entry.meta["scope_shardings"] = scope_shardings
            entry.meta["feed_shardings"] = tuple(feed_shardings)
            if source == "trace":
                _CACHE_MISSES.inc()
            self._cache[key] = entry
        else:
            _CACHE_HITS.inc()
        compiled = entry.fn
        donated, readonly, written = entry.donated, entry.readonly, entry.written
        scope_shardings = entry.meta["scope_shardings"]
        missing = [n for n in donated + readonly if not scope.has_var(n)]
        if missing:
            raise EnforceError(
                f"variables {missing} not initialized in scope "
                f"(run the startup program first?)"
            )
        feed_vals = tuple(
            _to_global(feed_arrays[n], sh)
            for n, sh in zip(feed_names, entry.meta["feed_shardings"])
        )
        # commit scope inputs to their mesh shardings so first-step vs
        # steady-state layouts match — same fix as Executor._run_compiled
        donated_vals = tuple(
            _to_global_verified(scope, n, scope_shardings[n], store=False)
            for n in donated
        )
        readonly_vals = tuple(
            _to_global_verified(scope, n, scope_shardings[n], store=True)
            for n in readonly
        )
        rng_key = exe._next_rng_key(self._program)
        from paddle_tpu.parallel.env import mesh_context
        from paddle_tpu.parallel.pipeline_runtime.runtime import (
            schedule_override,
        )

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            # mesh context: nested-shard_map ops (pipeline_stack) find the
            # mesh during tracing, which happens inside this first call;
            # the schedule override rides the same window so the choice in
            # the cache key is the choice the op lowers
            span = ("compiled_program::trace_compile_execute"
                    if not entry.executed else "compiled_program::execute")
            with mesh_context(mesh), \
                    schedule_override(self._pipeline_schedule,
                                      self._pipeline_interleave), \
                    trace_scope(span):
                fetches, updates = compiled(
                    feed_vals, donated_vals, readonly_vals, rng_key
                )
        entry.executed = True
        for name, val in zip(written, updates):
            if val is not None:
                # owner-targeted (see Executor._run_compiled write-back)
                target = scope._find_owner(name) or scope
                sh = scope_shardings.get(name)
                if sh is not None:
                    # out_shardings pinned this output to `sh`: mark
                    # verified so the next step's commit is one lookup
                    target._set_verified(name, val, sh)
                else:
                    target.set(name, val)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)
