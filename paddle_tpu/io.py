"""Model IO: save/load params, persistables, inference models, program state.

reference: python/paddle/fluid/io.py — save_params :336, save_persistables
:556, save_inference_model :1022, load_inference_model :1229, program-state
save/load :1507,1565,1731. The reference implements checkpointing as graph
execution (save/load ops appended to a save program, io.py:208-335); here
persistence is host-side array serialization — on TPU the device→host gather
is a jax.device_get, and making it graph ops would only force an XLA
round-trip. The on-disk layout mirrors the reference: a `__model__` program
file plus per-variable files (separate-files mode) or one combined params
file (save_combine mode, reference: operators/save_combine_op.cc).
"""

import json
import os
import zlib

import numpy as np

from paddle_tpu.core.ir import Parameter, Program
from paddle_tpu.core.scope import global_scope
from paddle_tpu.reader.decorator import robust  # noqa: F401  (fluid.io.robust)
from paddle_tpu.utils.enforce import EnforceError, enforce

MODEL_FORMAT_VERSION = 1


def array_crc32(arr):
    """Integrity checksum of an array's payload bytes (dtype-agnostic —
    bf16 views included); the unit of verification for checkpoint
    manifests (incubate/checkpoint.py) and separate-files saves below."""
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _is_persistable(var):
    return var.persistable and not var.is_data


def _is_parameter(var):
    return isinstance(var, Parameter)


def _gather_vars(program, predicate, scope):
    out = {}
    for var in program.global_block().vars.values():
        if not predicate(var):
            continue
        val = scope.find_var(var.name)
        if val is None:
            raise EnforceError(
                f"variable {var.name} is not initialized in scope; run the "
                f"startup program before saving"
            )
        out[var.name] = np.asarray(val)
    return out


def _write_combined(path, arrays):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    names = sorted(arrays)
    payload = {}
    bf16_names = []
    for i, n in enumerate(names):
        a = np.asarray(arrays[n])
        if str(a.dtype) == "bfloat16":
            # numpy's npz format can't represent ml_dtypes.bfloat16 (it
            # degrades to void16); store the raw bits as uint16 + a tag
            a = a.view(np.uint16)
            bf16_names.append(n)
        payload[f"arr_{i}"] = a
    np.savez(
        path,
        __names__=np.array(names, dtype=object),
        __bf16__=np.array(bf16_names, dtype=object),
        **payload,
    )


def _read_combined(path):
    real = path if os.path.exists(path) else path + ".npz"
    enforce(os.path.exists(real), f"params file {path} not found")
    with np.load(real, allow_pickle=True) as data:
        names = [str(n) for n in data["__names__"]]
        bf16 = (
            {str(n) for n in data["__bf16__"]} if "__bf16__" in data else set()
        )
        out = {}
        for i, n in enumerate(names):
            a = data[f"arr_{i}"]
            if n in bf16:
                import ml_dtypes

                a = a.view(ml_dtypes.bfloat16)
            out[n] = a
        return out


# ---------------------------------------------------------------------------
# save/load params + persistables (reference: io.py:336,556,744,802)
# ---------------------------------------------------------------------------


def save_vars(executor, dirname, main_program=None, predicate=None, filename=None, vars=None):
    from paddle_tpu.core.ir import default_main_program

    program = main_program or default_main_program()
    scope = global_scope()
    if vars is not None:
        arrays = {}
        for v in vars:
            name = v if isinstance(v, str) else v.name
            val = scope.find_var(name)
            enforce(val is not None, f"variable {name} not in scope")
            arrays[name] = np.asarray(val)
    else:
        arrays = _gather_vars(program, predicate or _is_persistable, scope)
    if filename is None:
        os.makedirs(dirname, exist_ok=True)
        for name, arr in arrays.items():
            np.save(os.path.join(dirname, name.replace("/", "_")) + ".npy", arr)
        manifest = {
            "format_version": MODEL_FORMAT_VERSION,
            "vars": sorted(arrays),
            # per-var payload CRCs: load_vars verifies these, so a torn
            # or bit-rotted .npy fails loudly naming the variable
            "crc32": {n: array_crc32(a) for n, a in arrays.items()},
        }
        with open(os.path.join(dirname, "__manifest__.json"), "w") as f:
            json.dump(manifest, f)
    else:
        _write_combined(os.path.join(dirname, filename), arrays)
    return sorted(arrays)


def load_vars(executor, dirname, main_program=None, predicate=None, filename=None, vars=None):
    from paddle_tpu.core.ir import default_main_program

    import jax.numpy as jnp

    program = main_program or default_main_program()
    scope = global_scope()
    if vars is not None:
        names = [v if isinstance(v, str) else v.name for v in vars]
    else:
        names = [
            v.name
            for v in program.global_block().vars.values()
            if (predicate or _is_persistable)(v)
        ]
    if filename is None:
        crcs = {}
        man_p = os.path.join(dirname, "__manifest__.json")
        if os.path.exists(man_p):
            try:
                with open(man_p) as f:
                    crcs = json.load(f).get("crc32", {})
            except (ValueError, json.JSONDecodeError) as e:
                raise EnforceError(f"corrupt manifest {man_p}: {e}")
        for name in names:
            path = os.path.join(dirname, name.replace("/", "_")) + ".npy"
            enforce(os.path.exists(path), f"no saved file for variable {name}")
            arr = np.load(path)
            if name in crcs:
                crc = array_crc32(arr)
                enforce(
                    crc == crcs[name],
                    f"variable {name} is corrupt: CRC {crc:#x} != saved "
                    f"{crcs[name]:#x} ({path})",
                )
            scope.set(name, jnp.asarray(arr))
    else:
        arrays = _read_combined(os.path.join(dirname, filename))
        missing = [n for n in names if n not in arrays]
        enforce(not missing, f"saved file is missing variables {missing[:5]}")
        for name in names:
            scope.set(name, jnp.asarray(arrays[name]))
    return names


def save_params(executor, dirname, main_program=None, filename=None):
    """reference: python/paddle/fluid/io.py:336."""
    return save_vars(executor, dirname, main_program, _is_parameter, filename)


def load_params(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, _is_parameter, filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    """Includes optimizer accumulators — they are persistable vars
    (reference: python/paddle/fluid/io.py:556)."""
    return save_vars(executor, dirname, main_program, _is_persistable, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    return load_vars(executor, dirname, main_program, _is_persistable, filename)


# ---------------------------------------------------------------------------
# unified save/load (reference: io.py:1507 save, :1565 load)
# ---------------------------------------------------------------------------


def save(program, model_path):
    scope = global_scope()
    params = _gather_vars(program, _is_parameter, scope)
    _write_combined(model_path + ".pdparams", params)
    others = {
        n: a
        for n, a in _gather_vars(program, _is_persistable, scope).items()
        if n not in params
    }
    _write_combined(model_path + ".pdopt", others)


def load(program, model_path, executor=None):
    import jax.numpy as jnp

    scope = global_scope()
    arrays = _read_combined(model_path + ".pdparams")
    arrays.update(_read_combined(model_path + ".pdopt"))
    for var in program.global_block().vars.values():
        if _is_persistable(var) and var.name in arrays:
            scope.set(var.name, jnp.asarray(arrays[var.name]))


def load_program_state(model_path):
    """reference: io.py:1731 — returns name->ndarray for partial/transfer
    loading."""
    state = _read_combined(model_path + ".pdparams")
    opt_path = model_path + ".pdopt"
    if os.path.exists(opt_path) or os.path.exists(opt_path + ".npz"):
        state.update(_read_combined(opt_path))
    return state


def set_program_state(program, state):
    import jax.numpy as jnp

    scope = global_scope()
    used = set()
    for var in program.global_block().vars.values():
        if var.name in state:
            scope.set(var.name, jnp.asarray(state[var.name]))
            used.add(var.name)
    return sorted(used)


# ---------------------------------------------------------------------------
# inference model export (reference: io.py:1022,1229)
# ---------------------------------------------------------------------------


def save_inference_model(
    dirname,
    feeded_var_names,
    target_vars,
    executor,
    main_program=None,
    model_filename=None,
    params_filename=None,
):
    """Prune to the feed→fetch subgraph, strip train-only behavior, save
    program + params (reference: python/paddle/fluid/io.py:1022)."""
    from paddle_tpu.core.ir import default_main_program

    program = main_program or default_main_program()
    infer = program.clone(for_test=True)
    target_names = [t if isinstance(t, str) else t.name for t in target_vars]
    infer._prune(target_names)

    # verify the pruned program is well-formed and the feeds suffice for the
    # targets before anything touches disk — a saved-then-broken model fails
    # here with op attribution, not at load/serve time
    from paddle_tpu.analysis.verify import verify_program

    errors = [
        d for d in verify_program(
            infer, feed_names=feeded_var_names, fetch_names=target_names,
        )
        if d.severity == "error"
    ]
    enforce(
        not errors,
        "inference program failed verification:\n"
        + "\n".join(str(d) for d in errors),
    )

    infer._attrs["feed_var_names"] = list(feeded_var_names)
    infer._attrs["fetch_var_names"] = target_names

    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or "__model__")
    desc = infer.desc()
    desc["feed_var_names"] = list(feeded_var_names)
    desc["fetch_var_names"] = target_names
    with open(model_path, "wb") as f:
        f.write(json.dumps(desc, sort_keys=True).encode("utf-8"))

    scope = global_scope()
    arrays = {}
    for var in infer.global_block().vars.values():
        if var.persistable and not var.is_data:
            val = scope.find_var(var.name)
            if val is not None:
                arrays[var.name] = np.asarray(val)
    _write_combined(
        os.path.join(dirname, params_filename or "__params__"), arrays
    )
    return target_names


def load_inference_model(
    dirname, executor, model_filename=None, params_filename=None
):
    """Returns (program, feed_names, fetch_vars)
    (reference: python/paddle/fluid/io.py:1229)."""
    import jax.numpy as jnp

    model_path = os.path.join(dirname, model_filename or "__model__")
    enforce(os.path.exists(model_path), f"{model_path} not found")
    with open(model_path, "rb") as f:
        desc = json.loads(f.read().decode("utf-8"))
    program = Program.from_bytes(
        json.dumps({k: v for k, v in desc.items() if k not in ("feed_var_names", "fetch_var_names")}).encode()
    )
    feed_names = desc.get("feed_var_names", [])
    fetch_names = desc.get("fetch_var_names", [])
    arrays = _read_combined(os.path.join(dirname, params_filename or "__params__"))
    scope = global_scope()
    for name, arr in arrays.items():
        scope.set(name, jnp.asarray(arr))
    block = program.global_block()
    fetch_vars = [block.var(n) for n in fetch_names]
    return program, feed_names, fetch_vars


# ---------------------------------------------------------------------------
# train-model export for the C train API (reference: paddle/fluid/train/ -
# demo_trainer.cc loads serialized main/startup ProgramDescs and trains
# without Python; here the same contract feeds csrc/capi's PD_Trainer)
# ---------------------------------------------------------------------------


def save_train_model(dirname, main_program, startup_program, loss=None,
                     executor=None):
    """Serialize (main, startup) programs + meta so a C host can train
    (csrc/capi PD_NewTrainer). With `executor`, current persistables are
    saved too (warm start); otherwise the C side runs the startup program."""
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "main_program"), "wb") as f:
        f.write(main_program.to_bytes())
    with open(os.path.join(dirname, "startup_program"), "wb") as f:
        f.write(startup_program.to_bytes())
    meta = {"format_version": MODEL_FORMAT_VERSION}
    if loss is not None:
        meta["loss"] = loss if isinstance(loss, str) else loss.name
    with open(os.path.join(dirname, "meta.json"), "w") as f:
        json.dump(meta, f)
    if executor is not None:
        save_persistables(
            executor, os.path.join(dirname, "params"),
            main_program=main_program,
        )


def load_train_model(dirname):
    """Returns (main_program, startup_program, loss_name_or_None)."""
    from paddle_tpu.core.ir import Program

    with open(os.path.join(dirname, "main_program"), "rb") as f:
        main = Program.from_bytes(f.read())
    with open(os.path.join(dirname, "startup_program"), "rb") as f:
        startup = Program.from_bytes(f.read())
    loss = None
    meta_p = os.path.join(dirname, "meta.json")
    if os.path.exists(meta_p):
        with open(meta_p) as f:
            loss = json.load(f).get("loss")
    return main, startup, loss
