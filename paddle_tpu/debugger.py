"""Program visualization & text debugging.

Reference: python/paddle/fluid/debugger.py (draw_block_graphviz) and
paddle/fluid/framework/ir/graph_viz_pass.cc — dump the op/var graph as
graphviz dot for inspection.
"""

__all__ = ["draw_block_graphviz", "program_summary"]

_OP_STYLE = 'shape=box, style="rounded,filled", fillcolor="#d5e8f7"'
_VAR_STYLE = 'shape=ellipse, style=filled, fillcolor="#eeeeee"'
_PARAM_STYLE = 'shape=ellipse, style=filled, fillcolor="#d9ead3"'


def _q(s):
    return '"' + str(s).replace('"', '\\"') + '"'


def draw_block_graphviz(block, highlights=None, path=None):
    """Render a block as graphviz dot text; optionally write to `path`."""
    from paddle_tpu.core.ir import Parameter

    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    var_nodes = set()

    def var_node(name):
        if name in var_nodes:
            return
        var_nodes.add(name)
        v = block._find_var_recursive(name)
        style = _PARAM_STYLE if isinstance(v, Parameter) else _VAR_STYLE
        if name in highlights:
            style += ', color=red, penwidth=2'
        label = name
        if v is not None and v.shape is not None:
            label += f"\\n{list(v.shape)}|{v.dtype}"
        lines.append(f"  {_q('var_' + name)} [{style}, label={_q(label)}];")

    for i, op in enumerate(block.ops):
        op_id = f"op_{i}_{op.type}"
        lines.append(f"  {_q(op_id)} [{_OP_STYLE}, label={_q(op.type)}];")
        for name in op.input_names():
            var_node(name)
            lines.append(f"  {_q('var_' + name)} -> {_q(op_id)};")
        for name in op.output_names():
            var_node(name)
            lines.append(f"  {_q(op_id)} -> {_q('var_' + name)};")
    lines.append("}")
    dot = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot


def program_summary(program):
    """Compact per-block op/var counts + op histogram."""
    from collections import Counter

    out = []
    for b in program.blocks:
        hist = Counter(op.type for op in b.ops)
        out.append(
            {
                "block": b.idx,
                "num_ops": len(b.ops),
                "num_vars": len(b.vars),
                "op_histogram": dict(hist.most_common()),
            }
        )
    return out
