"""Detection ops: anchors/priors, box transforms, IoU, NMS, YOLO decode.

The reference's detection library (reference: paddle/fluid/operators/
detection/ — multiclass_nms_op.cc, yolo_box_op.h, prior_box_op.h,
box_coder_op.h, iou_similarity_op.h, bipartite_match_op.cc) is host-side
C++ with dynamic-length outputs. TPU-native redesign: everything is
fixed-shape and vectorized — NMS returns a fixed keep_top_k slate with a
validity mask and -1 labels for empty slots instead of a variable-length
LoD tensor, so the whole post-processing graph stays on-device under XLA.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe

_NEG = -1e9


def _iou(a, b):
    """Pairwise IoU. a: [N, 4], b: [M, 4] in (x1, y1, x2, y2)."""
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity", nondiff_inputs=("X", "Y"))
def _iou_similarity(ins, attrs):
    """reference: paddle/fluid/operators/detection/iou_similarity_op.h."""
    x = first(ins, "X")
    y = first(ins, "Y")
    if x.ndim == 3:  # batched [B, N, 4]
        out = jax.vmap(_iou)(x, y)
    else:
        out = _iou(x, y)
    return {"Out": [out]}


@register_op("box_clip", nondiff_inputs=("ImInfo",))
def _box_clip(ins, attrs):
    """Clip boxes to image bounds (reference: box_clip_op.h). ImInfo rows are
    (height, width, scale)."""
    boxes = first(ins, "Input")
    im = first(ins, "ImInfo")
    h = im[..., 0:1] - 1.0
    w = im[..., 1:2] - 1.0
    if boxes.ndim == 3:
        h = h[:, None]
        w = w[:, None]
    x1 = jnp.clip(boxes[..., 0::4], 0, w)
    y1 = jnp.clip(boxes[..., 1::4], 0, h)
    x2 = jnp.clip(boxes[..., 2::4], 0, w)
    y2 = jnp.clip(boxes[..., 3::4], 0, h)
    out = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(boxes.shape)
    return {"Output": [out]}


@register_op("box_coder", nondiff_inputs=("PriorBox", "PriorBoxVar"))
def _box_coder(ins, attrs):
    """Encode/decode boxes against priors
    (reference: paddle/fluid/operators/detection/box_coder_op.h)."""
    prior = first(ins, "PriorBox")  # [M, 4]
    pvar = maybe(ins, "PriorBoxVar")
    target = first(ins, "TargetBox")
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    one = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    var = (
        pvar
        if pvar is not None
        else jnp.asarray(attrs.get("variance", [1.0, 1.0, 1.0, 1.0]),
                         jnp.float32)
    )
    if code_type.startswith("encode"):
        # target [N, 4] against every prior -> [N, M, 4]
        tw = target[:, 2] - target[:, 0] + one
        th = target[:, 3] - target[:, 1] + one
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(jnp.clip(tw[:, None] / pw[None, :], 1e-8))
        dh = jnp.log(jnp.clip(th[:, None] / ph[None, :], 1e-8))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        v = var if var.ndim == 2 else var.reshape(1, -1)
        out = out / v[None, :, :] if var.ndim == 2 else out / v[None]
    else:  # decode: target [N, M, 4] deltas (or [M, 4])
        t = target if target.ndim == 3 else target[None]
        v = var if var.ndim == 2 else var.reshape(1, 1, -1)
        t = t * (v if v.ndim == 3 else var[None, :, :])
        cx = t[..., 0] * pw[None, :] + pcx[None, :]
        cy = t[..., 1] * ph[None, :] + pcy[None, :]
        w = jnp.exp(t[..., 2]) * pw[None, :]
        h = jnp.exp(t[..., 3]) * ph[None, :]
        out = jnp.stack(
            [cx - w * 0.5, cy - h * 0.5,
             cx + w * 0.5 - one, cy + h * 0.5 - one], axis=-1
        )
        if target.ndim == 2:
            out = out[0]
    return {"OutputBox": [out]}


@register_op("prior_box", nondiff_inputs=("Input", "Image"))
def _prior_box(ins, attrs):
    """SSD prior boxes per feature-map cell
    (reference: paddle/fluid/operators/detection/prior_box_op.h)."""
    feat = first(ins, "Input")  # [B, C, H, W]
    img = first(ins, "Image")  # [B, C, IH, IW]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for a in attrs.get("aspect_ratios", [1.0]):
        a = float(a)
        if not any(abs(a - e) < 1e-6 for e in ars):
            ars.append(a)
            if attrs.get("flip", True):
                ars.append(1.0 / a)
    H, W = feat.shape[2], feat.shape[3]
    IH, IW = img.shape[2], img.shape[3]
    step_w = attrs.get("step_w", 0.0) or IW / W
    step_h = attrs.get("step_h", 0.0) or IH / H
    offset = attrs.get("offset", 0.5)
    cx = (jnp.arange(W) + offset) * step_w
    cy = (jnp.arange(H) + offset) * step_h
    widths, heights = [], []
    for k, ms in enumerate(min_sizes):
        for ar in ars:
            widths.append(ms * (ar ** 0.5))
            heights.append(ms / (ar ** 0.5))
        if max_sizes:
            s = (ms * max_sizes[k]) ** 0.5
            widths.append(s)
            heights.append(s)
    wv = jnp.asarray(widths, jnp.float32)
    hv = jnp.asarray(heights, jnp.float32)
    P = wv.shape[0]
    gx = jnp.broadcast_to(cx[None, :, None], (H, W, P))
    gy = jnp.broadcast_to(cy[:, None, None], (H, W, P))
    boxes = jnp.stack(
        [
            (gx - wv / 2) / IW,
            (gy - hv / 2) / IH,
            (gx + wv / 2) / IW,
            (gy + hv / 2) / IH,
        ],
        axis=-1,
    )
    if attrs.get("clip", False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]), jnp.float32)
    variances = jnp.broadcast_to(var, (H, W, P, 4))
    return {"Boxes": [boxes], "Variances": [variances]}


@register_op("yolo_box", nondiff_inputs=("X", "ImgSize"))
def _yolo_box(ins, attrs):
    """Decode YOLOv3 head output to boxes+scores
    (reference: paddle/fluid/operators/detection/yolo_box_op.h)."""
    x = first(ins, "X")  # [B, A*(5+C), H, W]
    img_size = first(ins, "ImgSize")  # [B, 2] (h, w)
    anchors = attrs["anchors"]  # flat [w0, h0, w1, h1, ...]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    clip_bbox = attrs.get("clip_bbox", True)
    B, _, H, W = x.shape
    A = len(anchors) // 2
    aw = jnp.asarray(anchors[0::2], jnp.float32)
    ah = jnp.asarray(anchors[1::2], jnp.float32)
    x = x.reshape(B, A, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    bx = (jax.nn.sigmoid(x[:, :, 0]) + gx) / W
    by = (jax.nn.sigmoid(x[:, :, 1]) + gy) / H
    in_h = H * downsample
    in_w = W * downsample
    bw = jnp.exp(x[:, :, 2]) * aw[None, :, None, None] / in_w
    bh = jnp.exp(x[:, :, 3]) * ah[None, :, None, None] / in_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    keep = conf > conf_thresh
    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(B, A * H * W, 4)
    scores = jnp.where(keep[:, :, None], probs, 0.0)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(B, A * H * W, class_num)
    return {"Boxes": [boxes], "Scores": [scores]}


def _nms_single_class(iou_full, scores, iou_threshold, top_k):
    """Greedy NMS over one class given the PRECOMPUTED pairwise IoU of all
    boxes (shared across classes — boxes are class-independent, only the
    score order differs). Returns (scores, idx) of the top_k slate,
    suppressed entries scored -inf. Static shapes, lax.fori_loop."""
    N = scores.shape[0]
    top_k = min(top_k, N)
    order = jnp.argsort(-scores)
    s = scores[order]
    iou = iou_full[order][:, order]

    def body(i, alive):
        # if candidate i is alive, kill everything it overlaps
        kill = (iou[i] > iou_threshold) & (jnp.arange(N) > i)
        return jnp.where(alive[i], alive & ~kill, alive)

    alive = jax.lax.fori_loop(0, N, body, jnp.ones((N,), bool))
    kept_scores = jnp.where(alive, s, _NEG)
    sel = jnp.argsort(-kept_scores)[:top_k]
    return kept_scores[sel], order[sel]


@register_op("multiclass_nms", nondiff_inputs=("BBoxes", "Scores"))
def _multiclass_nms(ins, attrs):
    """Fixed-slate multiclass NMS (reference: multiclass_nms_op.cc).

    The reference emits a variable-length LoD result; here the output is
    Out [B, keep_top_k, 6] rows (label, score, x1, y1, x2, y2) with label=-1
    for empty slots, plus NumDetections [B] — the static-shape contract XLA
    needs. score_threshold/nms_top_k/keep_top_k/nms_threshold as reference.
    """
    bboxes = first(ins, "BBoxes")  # [B, N, 4]
    scores = first(ins, "Scores")  # [B, C, N]
    score_thresh = attrs.get("score_threshold", 0.0)
    nms_thresh = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 100)
    background = attrs.get("background_label", 0)
    B, C, N = scores.shape

    def per_image(boxes, sc):
        iou_full = _iou(boxes, boxes)  # once per image, shared by classes
        slates_s, slates_l, slates_b, slates_i = [], [], [], []
        for c in range(C):
            if c == background:
                continue
            s = jnp.where(sc[c] > score_thresh, sc[c], _NEG)
            ks, ki = _nms_single_class(iou_full, s, nms_thresh,
                                       min(nms_top_k, N))
            slates_s.append(ks)
            slates_l.append(jnp.full(ks.shape, c, jnp.float32))
            slates_b.append(boxes[ki])
            slates_i.append(ki)
        all_s = jnp.concatenate(slates_s)
        all_l = jnp.concatenate(slates_l)
        all_b = jnp.concatenate(slates_b)
        all_i = jnp.concatenate(slates_i)
        k = min(keep_top_k, all_s.shape[0])
        sel = jnp.argsort(-all_s)[:k]
        s = all_s[sel]
        valid = s > max(score_thresh, _NEG / 2)
        out = jnp.concatenate(
            [
                jnp.where(valid, all_l[sel], -1.0)[:, None],
                jnp.where(valid, s, 0.0)[:, None],
                jnp.where(valid[:, None], all_b[sel], 0.0),
            ],
            axis=1,
        )
        kept = jnp.where(valid, all_i[sel], -1).astype(jnp.int32)
        return out, valid.sum().astype(jnp.int64), kept

    out, num, kept = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [out], "NumDetections": [num], "Index": [kept]}


@register_op("bipartite_match", nondiff_inputs=("DistMat",))
def _bipartite_match(ins, attrs):
    """Greedy bipartite matching of columns to rows by descending distance
    (reference: bipartite_match_op.cc BipartiteMatch). DistMat [N, M]:
    rows = ground truth, cols = priors. Outputs per-col matched row ids
    (-1 unmatched) and the match distance."""
    dist = first(ins, "DistMat")

    def match(d):
        N, M = d.shape

        def body(_, carry):
            row_used, col_match, col_dist = carry
            masked = jnp.where(row_used[:, None], _NEG, d)
            masked = jnp.where(col_match[None, :] >= 0, _NEG, masked)
            flat = jnp.argmax(masked)
            r, c = flat // M, flat % M
            best = masked[r, c]
            do = best > _NEG / 2
            row_used = row_used.at[r].set(row_used[r] | do)
            col_match = col_match.at[c].set(
                jnp.where(do, r, col_match[c])
            )
            col_dist = col_dist.at[c].set(
                jnp.where(do, best, col_dist[c])
            )
            return row_used, col_match, col_dist

        init = (
            jnp.zeros((N,), bool),
            jnp.full((M,), -1, jnp.int32),
            jnp.zeros((M,), jnp.float32),
        )
        _, col_match, col_dist = jax.lax.fori_loop(0, N, body, init)
        return col_match, col_dist

    if dist.ndim == 3:
        ids, d = jax.vmap(match)(dist)
    else:
        ids, d = match(dist)
        ids, d = ids[None], d[None]
    return {"ColToRowMatchIndices": [ids], "ColToRowMatchDist": [d]}


@register_op("anchor_generator", nondiff_inputs=("Input",))
def _anchor_generator(ins, attrs):
    """RPN anchors per cell (reference: anchor_generator_op.h)."""
    feat = first(ins, "Input")  # [B, C, H, W]
    sizes = [float(s) for s in attrs.get("anchor_sizes", [64.0])]
    ratios = [float(r) for r in attrs.get("aspect_ratios", [1.0])]
    stride = attrs.get("stride", [16.0, 16.0])
    offset = attrs.get("offset", 0.5)
    H, W = feat.shape[2], feat.shape[3]
    ws = jnp.asarray(
        [s * (1.0 / r) ** 0.5 for r in ratios for s in sizes], jnp.float32
    )
    hs = jnp.asarray(
        [s * r ** 0.5 for r in ratios for s in sizes], jnp.float32
    )
    cx = (jnp.arange(W) + offset) * stride[0]
    cy = (jnp.arange(H) + offset) * stride[1]
    A = ws.shape[0]
    gx = jnp.broadcast_to(cx[None, :, None], (H, W, A))
    gy = jnp.broadcast_to(cy[:, None, None], (H, W, A))
    anchors = jnp.stack(
        [gx - ws / 2, gy - hs / 2, gx + ws / 2, gy + hs / 2], axis=-1
    )
    var = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]), jnp.float32)
    variances = jnp.broadcast_to(var, (H, W, A, 4))
    return {"Anchors": [anchors], "Variances": [variances]}
