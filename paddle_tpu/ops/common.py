"""Shared helpers for op lowering rules."""

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import to_numpy_dtype


def vma_names(x):
    """Manual-mesh-axis (shard_map 'vma') names of x's abstract value, as
    a frozenset. jax.typeof only exists on newer jax releases and pre-vma
    avals have no .vma attribute — on those, the empty set is correct
    (nothing can be inside a manual region whose machinery doesn't
    exist). One compat seam instead of per-site getattr chains."""
    typeof = getattr(jax, "typeof", None)
    aval = typeof(x) if typeof is not None else jax.core.get_aval(x)
    return getattr(aval, "vma", None) or frozenset()


def first(ins, slot):
    return ins[slot][0]


def maybe(ins, slot, default=None):
    vals = ins.get(slot)
    return vals[0] if vals else default


def np_dtype(attrs, key="dtype", default="float32"):
    return to_numpy_dtype(attrs.get(key, default))


def broadcast_y(x, y, axis):
    """Reference elementwise broadcast semantics: Y aligns into X starting at
    `axis` (reference: paddle/fluid/operators/elementwise/
    elementwise_op_function.h). axis=-1 aligns trailing dims (numpy rule)."""
    if axis is None or axis == -1 or x.ndim == y.ndim:
        return y
    trailing = x.ndim - axis - y.ndim
    if trailing < 0:
        return y
    return y.reshape((1,) * axis + y.shape + (1,) * trailing)


def rng_key(ins):
    key = ins.get("__rng_key__")
    if key is None:
        raise RuntimeError("stateful op executed without an rng key")
    return key[0]


def seeded_rng_key(ins, attrs):
    """Key honoring a fixed per-op `seed` attr while still advancing between
    executor runs (the reference's seeded generator semantics)."""
    import jax
    import jax.numpy as jnp

    seed = attrs.get("seed", 0)
    if not seed:
        return rng_key(ins)
    base = jax.random.PRNGKey(seed)
    injected = ins.get("__rng_key__")
    if injected is None:
        return base
    raw = jnp.asarray(injected[0]).astype(jnp.uint32)
    return jax.random.fold_in(base, raw[0] ^ raw[1])


def reduce_axes(attrs, ndim):
    if attrs.get("reduce_all", False):
        return tuple(range(ndim))
    dims = attrs.get("dim", [0])
    if isinstance(dims, int):
        dims = [dims]
    return tuple(d % ndim for d in dims)


def normalize_padding(attrs, spatial_dims, ksize, strides, in_shape):
    """Resolve the reference's padding attrs (explicit list / SAME / VALID)
    into lax-style ((lo, hi), ...) pairs."""
    algo = attrs.get("padding_algorithm", "EXPLICIT")
    pads = attrs.get("paddings", [0] * spatial_dims)
    if algo == "VALID":
        return ((0, 0),) * spatial_dims
    if algo == "SAME":
        out = []
        for i in range(spatial_dims):
            out_size = -(-in_shape[i] // strides[i])
            total = max(0, (out_size - 1) * strides[i] + ksize[i] - in_shape[i])
            out.append((total // 2, total - total // 2))
        return tuple(out)
    if len(pads) == spatial_dims:
        return tuple((p, p) for p in pads)
    return tuple((pads[2 * i], pads[2 * i + 1]) for i in range(spatial_dims))


def astype_like(g, ref):
    return g.astype(ref.dtype) if g.dtype != ref.dtype else g


def flat_float(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
