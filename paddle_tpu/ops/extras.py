"""Misc dense ops: data/spectral/l2 norms, CTR helpers, partial ops, row
convolutions, sampled-softmax losses.

reference: paddle/fluid/operators/{data_norm_op.cc, spectral_norm_op.cc,
norm_op.cc, selu_op.cc, l1_norm_op.cc, pad_constant_like_op.cc,
partial_concat_op.cc, partial_sum_op.cc, cvm_op.h, row_conv_op.cc,
conv_shift_op.cc, hinge_loss_op.cc, center_loss_op.cc, nce_op.h,
detection/sigmoid_focal_loss_op.cu}. Each is re-expressed as a vectorized
jnp/lax computation; stateful sampling uses the executor-threaded rng key.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_grad, register_op
from paddle_tpu.ops.common import first, maybe


@register_op("data_norm", nondiff_inputs=("BatchSize", "BatchSum", "BatchSquareSum"))
def _data_norm(ins, attrs):
    """reference: paddle/fluid/operators/data_norm_op.cc:208 —
    means = batch_sum / batch_size, scales = sqrt(batch_size /
    batch_square_sum), y = (x - mean) * scale. The reference updates the
    stat tables through the grad kernel (d_batch_size = N, d_batch_sum =
    per-channel sum x, d_batch_square_sum = sum x^2) plus the optimizer's
    summary rule; here the accumulated tables are emitted as BatchSizeOut /
    BatchSumOut / BatchSquareSumOut and aliased back onto the stat params
    by the layer (the CentersOut write-back pattern), so the stats actually
    track the data stream."""
    x = first(ins, "X")
    bsize = first(ins, "BatchSize").astype(jnp.float32)
    bsum = first(ins, "BatchSum").astype(jnp.float32)
    bsq = first(ins, "BatchSquareSum").astype(jnp.float32)
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    y = (x.astype(jnp.float32) - means[None, :]) * scales[None, :]
    xf = jax.lax.stop_gradient(x.astype(jnp.float32))
    n = jnp.float32(x.shape[0])
    # is_test (set by clone(for_test=True) / flip_test_mode): keep tables
    # frozen — eval passes must not drift the training statistics. The
    # outputs are still emitted (unchanged) so the executor always has a
    # value to bind for the declared write-back.
    train = not attrs.get("is_test", False)
    return {
        "Y": [y.astype(x.dtype)],
        "Means": [means],
        "Scales": [scales],
        "BatchSizeOut": [bsize + n if train else bsize],
        "BatchSumOut": [bsum + jnp.sum(xf, axis=0) if train else bsum],
        "BatchSquareSumOut": [
            bsq + jnp.sum(jnp.square(xf), axis=0) if train else bsq
        ],
    }


@register_grad("data_norm")
def _data_norm_grad(ins, attrs):
    """dX = dY * scales, from the SAVED Scales output — the stat tables in
    the scope have already been advanced by the forward write-back, so
    re-running the lowering (generic grad) would differentiate against
    post-update stats, disagreeing with the forward pass it backs."""
    dy = first(ins, "Y@GRAD")
    scales = first(ins, "Scales")
    return {"X@GRAD": [(dy.astype(jnp.float32) * scales[None, :]).astype(dy.dtype)]}


@register_op("spectral_norm", nondiff_inputs=("U", "V"))
def _spectral_norm(ins, attrs):
    """reference: paddle/fluid/operators/spectral_norm_op.cc — weight /
    sigma_max via `power_iters` rounds of power iteration from U, V. The
    reference updates U/V in place each forward so the iterates converge
    across steps; here they are emitted as UOut/VOut and aliased back onto
    the U/V parameters by the layer (CentersOut write-back pattern)."""
    w = first(ins, "Weight")
    u = first(ins, "U").reshape(-1)
    v = first(ins, "V").reshape(-1)
    dim = attrs.get("dim", 0)
    power_iters = attrs.get("power_iters", 1)
    eps = attrs.get("eps", 1e-12)
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)
    wm = jnp.transpose(w, perm).reshape(w.shape[dim], -1)  # [h, wd]

    def normalize(x):
        return x / (jnp.linalg.norm(x) + eps)

    def body(_, carry):
        u_, v_ = carry
        v_ = normalize(wm.T @ u_)
        u_ = normalize(wm @ v_)
        return u_, v_

    # power_iters=0 runs no iterations (reference loops exactly power_iters
    # times and leaves U/V at their current values)
    u, v = jax.lax.fori_loop(0, power_iters, body, (u, v))
    u = jax.lax.stop_gradient(u)
    v = jax.lax.stop_gradient(v)
    sigma = u @ (wm @ v)
    return {"Out": [w / sigma], "UOut": [u], "VOut": [v]}


@register_grad("spectral_norm")
def _spectral_norm_grad(ins, attrs):
    """Closed-form vjp of w -> w/sigma(u,v) at the SAVED iterates: the
    write-back stores exactly the u/v the forward's sigma used, but the
    generic grad would re-run the lowering and power-iterate a step further,
    differentiating a different sigma than the forward produced."""
    dout = first(ins, "Out@GRAD")
    w = first(ins, "Weight")
    u = first(ins, "UOut").reshape(-1)
    v = first(ins, "VOut").reshape(-1)
    dim = attrs.get("dim", 0)
    perm = (dim,) + tuple(i for i in range(w.ndim) if i != dim)

    def f(wt):
        wm = jnp.transpose(wt, perm).reshape(wt.shape[dim], -1)
        sigma = u @ (wm @ v)
        return wt / sigma

    _, vjp = jax.vjp(f, w)
    return {"Weight@GRAD": [vjp(dout)[0]]}


@register_op("norm")
def _norm(ins, attrs):
    """reference: paddle/fluid/operators/norm_op.cc — l2-normalize along
    `axis`, emitting the norm as a saved output."""
    x = first(ins, "X")
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    sq = jnp.sum(jnp.square(x.astype(jnp.float32)), axis=axis, keepdims=True)
    norm = jnp.sqrt(sq + eps)
    return {"Out": [(x / norm).astype(x.dtype)], "Norm": [norm]}


@register_op("l1_norm")
def _l1_norm(ins, attrs):
    """reference: paddle/fluid/operators/l1_norm_op.cc."""
    x = first(ins, "X")
    return {"Out": [jnp.sum(jnp.abs(x))]}


@register_op("pad_constant_like")
def _pad_constant_like(ins, attrs):
    """reference: paddle/fluid/operators/pad_constant_like_op.cc — pad Y up
    to X's (larger) shape with pad_value; X only supplies the target shape."""
    x = first(ins, "X")
    y = first(ins, "Y")
    val = attrs.get("pad_value", 0.0)
    pads = [(0, x.shape[i] - y.shape[i]) for i in range(y.ndim)]
    return {"Out": [jnp.pad(y, pads, constant_values=val)]}


def _partial_slices(ins, attrs):
    start = attrs.get("start_index", 0)
    length = attrs.get("length", -1)
    xs = ins["X"]
    cols = xs[0].shape[1]
    s = start % cols if start < 0 else start
    ln = cols - s if length < 0 else length
    return [x[:, s:s + ln] for x in xs]


@register_op("partial_concat")
def _partial_concat(ins, attrs):
    """reference: paddle/fluid/operators/partial_concat_op.cc."""
    return {"Out": [jnp.concatenate(_partial_slices(ins, attrs), axis=1)]}


@register_op("partial_sum")
def _partial_sum(ins, attrs):
    """reference: paddle/fluid/operators/partial_sum_op.cc."""
    parts = _partial_slices(ins, attrs)
    out = parts[0]
    for p in parts[1:]:
        out = out + p
    return {"Out": [out]}


@register_op("cvm", nondiff_inputs=("CVM",))
def _cvm(ins, attrs):
    """reference: paddle/fluid/operators/cvm_op.h CvmComputeKernel —
    use_cvm keeps the width and log-transforms the (show, click) columns;
    otherwise the two CVM columns are dropped. Show/click get no gradient
    (the reference grad kernel re-injects the raw CVM input)."""
    x = first(ins, "X")
    use_cvm = attrs.get("use_cvm", True)
    if not use_cvm:
        return {"Y": [x[:, 2:]]}
    head = jax.lax.stop_gradient(x[:, :2])
    c0 = jnp.log1p(head[:, 0:1])
    c1 = jnp.log1p(head[:, 1:2]) - c0
    return {"Y": [jnp.concatenate([c0, c1, x[:, 2:]], axis=1)]}


@register_op("hinge_loss")
def _hinge_loss(ins, attrs):
    """reference: paddle/fluid/operators/hinge_loss_op.cc —
    max(0, 1 - (2*label - 1) * logits), labels in {0, 1}."""
    logits = first(ins, "Logits")
    labels = first(ins, "Labels").astype(logits.dtype)
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)]}


@register_op("sigmoid_focal_loss", nondiff_inputs=("Label", "FgNum"))
def _sigmoid_focal_loss(ins, attrs):
    """reference: paddle/fluid/operators/detection/sigmoid_focal_loss_op.cu —
    per-(sample, class) focal loss; Label is 1-based (0 = background) and
    FgNum normalizes."""
    x = first(ins, "X")  # [N, C] logits
    label = first(ins, "Label").reshape(-1)  # [N], 0 = background
    fg = jnp.maximum(first(ins, "FgNum").astype(jnp.float32).reshape(()), 1.0)
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    C = x.shape[1]
    xf = x.astype(jnp.float32)
    # target[n, c] = 1 iff label[n] == c + 1
    tgt = (label[:, None] == (jnp.arange(C)[None, :] + 1)).astype(jnp.float32)
    p = jax.nn.sigmoid(xf)
    ce_pos = -jax.nn.log_sigmoid(xf)          # -log(p)
    ce_neg = -jax.nn.log_sigmoid(-xf)         # -log(1-p)
    loss = tgt * alpha * jnp.power(1.0 - p, gamma) * ce_pos + \
        (1.0 - tgt) * (1.0 - alpha) * jnp.power(p, gamma) * ce_neg
    return {"Out": [(loss / fg).astype(x.dtype)]}


@register_op("center_loss", nondiff_inputs=("Label", "Centers", "CenterUpdateRate"))
def _center_loss(ins, attrs):
    """reference: paddle/fluid/operators/center_loss_op.h — per-sample
    0.5*||x - c_label||^2 plus the class-count-normalized center update,
    emitted as the CentersOut data output (functional state threading)."""
    x = first(ins, "X").astype(jnp.float32)
    label = first(ins, "Label").reshape(-1).astype(jnp.int32)
    centers = first(ins, "Centers").astype(jnp.float32)
    lr = first(ins, "CenterUpdateRate").astype(jnp.float32).reshape(())
    diff = x - centers[label]  # [N, D]
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if attrs.get("need_update", True):
        counts = jnp.zeros((centers.shape[0],), jnp.float32).at[label].add(1.0)
        sums = jnp.zeros_like(centers).at[label].add(
            jax.lax.stop_gradient(diff)
        )
        centers_out = centers + lr * sums / (1.0 + counts)[:, None]
    else:
        centers_out = centers
    return {
        "Loss": [loss],
        "SampleCenterDiff": [diff],
        "CentersOut": [centers_out],
    }


@register_op("row_conv")
def _row_conv(ins, attrs):
    """reference: paddle/fluid/operators/row_conv_op.cc — lookahead
    convolution over time: y[t] = sum_j w[j] * x[t + j]. Batched form
    X [B, T, D], Filter [k, D] (the reference's LoD form maps each sequence
    to a batch row)."""
    x = first(ins, "X")
    w = first(ins, "Filter")
    k = w.shape[0]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for j in range(k):  # k is small & static (lookahead window)
        out = out + xp[:, j:j + x.shape[1], :] * w[j][None, None, :]
    return {"Out": [out]}


@register_op("conv_shift")
def _conv_shift(ins, attrs):
    """reference: paddle/fluid/operators/conv_shift_op.cc — circular
    correlation: out[b, i] = sum_j x[b, (i + j - m//2) mod n] * y[b, j]."""
    x = first(ins, "X")  # [B, N]
    y = first(ins, "Y")  # [B, M]
    n, m = x.shape[1], y.shape[1]
    i = jnp.arange(n)[:, None]
    j = jnp.arange(m)[None, :]
    idx = (i + j - m // 2) % n  # [N, M]
    gathered = x[:, idx]  # [B, N, M]
    return {"Out": [jnp.einsum("bnm,bm->bn", gathered, y)]}


@register_op("nce", stateful=True,
             nondiff_inputs=("Label", "SampleWeight", "CustomDistProbs",
                             "CustomDistAlias", "CustomDistAliasProbs"))
def _nce(ins, attrs):
    """reference: paddle/fluid/operators/nce_op.h — noise-contrastive
    estimation with a uniform negative sampler. Per-step negatives come from
    the executor-threaded rng key; the sampled ids are re-drawn each step
    exactly like the reference's per-iteration sampler."""
    from paddle_tpu.ops.common import seeded_rng_key

    x = first(ins, "Input")           # [B, D]
    label = first(ins, "Label")       # [B, num_true]
    w = first(ins, "Weight")          # [num_classes, D]
    b = maybe(ins, "Bias")            # [num_classes]
    num_total = attrs["num_total_classes"]
    num_neg = attrs.get("num_neg_samples", 10)
    sampler = attrs.get("sampler", 0)  # 0 uniform, 1 log_uniform (ref enum)
    B = x.shape[0]
    if label.ndim == 1:
        label = label[:, None]
    num_true = label.shape[1]
    key = seeded_rng_key(ins, attrs)
    if sampler == 1:
        # log-uniform (Zipfian): P(k) = (log(k+2)-log(k+1)) / log(K+1),
        # sampled by inverse CDF on a uniform draw
        u = jax.random.uniform(key, (B, num_neg))
        neg = jnp.clip(
            jnp.floor(jnp.exp(u * jnp.log(float(num_total + 1))) - 1.0)
            .astype(jnp.int32), 0, num_total - 1,
        )

        def log_q_of(ids):
            idf = ids.astype(jnp.float32)
            q = (jnp.log(idf + 2.0) - jnp.log(idf + 1.0)) / jnp.log(
                float(num_total + 1)
            )
            return jnp.log(num_neg * q)
    else:
        neg = jax.random.randint(key, (B, num_neg), 0, num_total)

        def log_q_of(ids):
            return jnp.full(ids.shape,
                            jnp.log(num_neg / float(num_total)), jnp.float32)

    def logits(ids):
        wv = w[ids]  # [B, K, D]
        out = jnp.einsum("bd,bkd->bk", x, wv)
        if b is not None:
            out = out + b[ids]
        return out

    # reference cost form (nce_op.h:266): o = sigmoid(logit),
    # b = num_neg * q(target); true terms -log(o/(o+b)) summed UNSCALED,
    # sampled terms -log(b/(o+b)). Stable rewrite:
    #   -log(o/(o+b)) = log(o+b) - log_sigmoid(l)
    #   -log(b/(o+b)) = log(o+b) - log(b)
    pos_ids = label.astype(jnp.int32)
    pos_raw = logits(pos_ids)
    neg_raw = logits(neg)
    log_b_pos = log_q_of(pos_ids)  # log(num_neg * q)
    log_b_neg = log_q_of(neg)
    o_pos = jax.nn.sigmoid(pos_raw)
    o_neg = jax.nn.sigmoid(neg_raw)
    pos_cost = (jnp.log(o_pos + jnp.exp(log_b_pos))
                - jax.nn.log_sigmoid(pos_raw)).sum(axis=1)
    neg_cost = (jnp.log(o_neg + jnp.exp(log_b_neg)) - log_b_neg).sum(axis=1)
    cost = (pos_cost + neg_cost)[:, None]
    sw = maybe(ins, "SampleWeight")
    if sw is not None:
        cost = cost * sw.reshape(-1, 1)
    # SampleLogits holds post-sigmoid probabilities, as the reference's
    # forward leaves sample_out_data (nce_op.h:242)
    return {
        "Cost": [cost],
        "SampleLogits": [jnp.concatenate([o_pos, o_neg], axis=1)],
        "SampleLabels": [jnp.concatenate(
            [label.astype(jnp.int64), neg.astype(jnp.int64)], axis=1)],
    }
