"""Recompute (activation checkpointing) segment ops.

Reference mechanism: RecomputeOptimizer re-emits forward ops between user
checkpoints inside the backward region so inter-checkpoint activations are
never stored (reference: python/paddle/fluid/optimizer.py:3714,
python/paddle/fluid/backward.py:618 _append_backward_ops_with_checkpoints_).

TPU-native mechanism: append_backward collapses each inter-checkpoint forward
segment into ONE `recompute_segment_grad` op whose lowering replays the
segment under `jax.vjp(jax.checkpoint(f))` — the replay happens at backward
time inside the same XLA computation, and `prevent_cse=True` stops XLA from
de-duplicating it against the stored forward pass (which would silently pin
the activations and defeat the remat). Stateful ops (dropout) replay with the
exact per-op rng folds of the forward pass via the stable `__rng_id__` ids.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import get_op_def, register_op
from paddle_tpu.utils.enforce import EnforceError


def replay_segment(segment, env, base_rng):
    """Run a serialized op list against `env` (name -> array), mutating it.
    `segment` entries are (type, inputs, outputs, attrs) tuples captured by
    append_backward; rng folds reproduce the forward pass exactly."""
    for op_type, inputs, outputs, attrs in segment:
        op_def = get_op_def(op_type)
        ins = {
            slot: [env[n] for n in names]
            for slot, names in inputs.items()
            if names and all(n in env for n in names)
        }
        if op_def.stateful:
            if base_rng is None:
                raise EnforceError(
                    f"stateful op {op_type} in recompute segment but no base "
                    f"rng key available"
                )
            ins["__rng_key__"] = [
                jax.random.fold_in(base_rng, attrs["__rng_id__"])
            ]
        outs = op_def.lowering(True)(ins, attrs)
        for slot, names in outputs.items():
            if slot not in outs:
                continue
            vals = outs[slot]
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for name, val in zip(names, vals):
                if val is not None:
                    env[name] = val
    return env


@register_op("recompute_segment", needs_base_rng=True)
def _recompute_segment(ins, attrs):
    """Forward replay of a segment (used if a segment pseudo-op is ever
    materialized in a program; normally only the grad op below executes)."""
    env = dict(zip(attrs["__in_names__"], ins["X"]))
    base_rng = ins.get("__base_rng__", [None])[0]
    replay_segment(attrs["__segment__"], env, base_rng)
    return {"Out": [env[n] for n in attrs["__out_names__"]]}


@register_op("recompute_segment_grad", needs_base_rng=True)
def _recompute_segment_grad(ins, attrs):
    in_names = attrs["__in_names__"]
    out_names = attrs["__out_names__"]
    diff_ins = [n for n in attrs["__diff_ins__"] if n in in_names]
    diff_outs = [n for n in attrs["__diff_outs__"] if n in out_names]
    segment = attrs["__segment__"]
    xs = ins["X"]
    base_rng = ins.get("__base_rng__", [None])[0]
    if not diff_ins:
        return {}
    diff_idx = [in_names.index(n) for n in diff_ins]

    def f(diff_vals):
        env = dict(zip(in_names, xs))
        env.update(zip(diff_ins, diff_vals))
        replay_segment(segment, env, base_rng)
        return [env[n] for n in diff_outs]

    # prevent_cse: without it XLA CSEs the replay against the live forward
    # pass, keeping every intermediate activation alive to the backward —
    # exactly the memory profile recompute exists to avoid.
    # The IR-keyed policy (kernels/remat.py) selects WHAT the replay may
    # keep: "full" saves nothing (the default), "dots" keeps MXU outputs
    # and replays only elementwise work, "save_all" is the no-remat
    # control. Replay is bit-exact under every policy (same ops, same rng
    # folds), so policy choice is a memory/compute trade, never a
    # numerics change.
    from paddle_tpu.kernels import remat as _remat

    policy = _remat.checkpoint_policy(
        attrs.get("__remat_policy__", _remat.DEFAULT_POLICY))
    if policy is None:
        f_ck = jax.checkpoint(f, prevent_cse=True)
    else:
        f_ck = jax.checkpoint(f, prevent_cse=True, policy=policy)
    primal_in = [xs[i] for i in diff_idx]
    primal_out, vjp = jax.vjp(f_ck, primal_in)
    gouts = ins.get("Out@GRAD", [])
    cotangents = []
    for j, n in enumerate(diff_outs):
        pos = out_names.index(n)
        g = gouts[pos] if pos < len(gouts) and gouts[pos] is not None else None
        p = primal_out[j]
        cotangents.append(
            g.astype(p.dtype) if g is not None else jnp.zeros_like(p)
        )
    (gxs,) = vjp(cotangents)
    grads = [None] * len(xs)
    for k, i in enumerate(diff_idx):
        grads[i] = gxs[k]
    return {"X@GRAD": grads}
