"""Elementwise / matmul / reduction op lowerings.

Replaces the reference's hand-written CPU/CUDA kernels
(reference: paddle/fluid/operators/elementwise/, math/blas.h,
reduce_ops/) with jnp lowerings traced into the whole-block XLA computation —
elementwise chains fuse into neighboring matmuls, and matmuls hit the MXU in
bf16/fp32 via lax.dot_general with no per-op dispatch.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import broadcast_y, first, maybe, reduce_axes


def _elementwise(name, fn):
    @register_op(name)
    def _lower(ins, attrs, _fn=fn):
        x, y = first(ins, "X"), first(ins, "Y")
        y = broadcast_y(x, y, attrs.get("axis", -1))
        return {"Out": [_fn(x, y)]}


_elementwise("elementwise_add", jnp.add)
_elementwise("elementwise_sub", jnp.subtract)
_elementwise("elementwise_mul", jnp.multiply)
_elementwise("elementwise_div", jnp.divide)
_elementwise("elementwise_min", jnp.minimum)
_elementwise("elementwise_max", jnp.maximum)
_elementwise("elementwise_pow", jnp.power)
_elementwise("elementwise_mod", jnp.mod)
_elementwise("elementwise_floordiv", jnp.floor_divide)


@register_op("matmul")
def _matmul(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("mul")
def _mul(ins, attrs):
    """FC-style matmul with input flattening
    (reference: paddle/fluid/operators/mul_op.cc)."""
    import math

    x, y = first(ins, "X"), first(ins, "Y")
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((math.prod(xs[:xnc]), -1))
    y2 = y.reshape((math.prod(ys[:ync]), -1))
    out = x2 @ y2
    out_shape = tuple(xs[:xnc]) + tuple(ys[ync:])
    return {"Out": [out.reshape(out_shape)]}


@register_op("scale")
def _scale(ins, attrs):
    x = first(ins, "X")
    scale = maybe(ins, "ScaleTensor", attrs.get("scale", 1.0))
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * scale + bias]}
    return {"Out": [(x + bias) * scale]}


@register_op("sum")
def _sum(ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


def _unary(name, fn):
    @register_op(name)
    def _lower(ins, attrs, _fn=fn):
        return {"Out": [_fn(first(ins, "X"))]}


_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("square", jnp.square)
_unary("abs", jnp.abs)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("exp", jnp.exp)
_unary("floor", jnp.floor)
_unary("ceil", jnp.ceil)
_unary("round", jnp.round)
_unary("reciprocal", jnp.reciprocal)
_unary("sign", jnp.sign)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("erf", jax.scipy.special.erf)


@register_op("pow")
def _pow(ins, attrs):
    x = first(ins, "X")
    factor = maybe(ins, "FactorTensor", attrs.get("factor", 1.0))
    return {"Out": [jnp.power(x, factor)]}


@register_op("clip")
def _clip(ins, attrs):
    x = first(ins, "X")
    return {"Out": [jnp.clip(x, attrs.get("min"), attrs.get("max"))]}


@register_op("clip_by_norm")
def _clip_by_norm(ins, attrs):
    x = first(ins, "X")
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": [jnp.where(norm > max_norm, x * (max_norm / norm), x)]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ins, attrs):
    x = first(ins, "X")
    return {"Out": [jnp.sum(jnp.square(x)).reshape((1,))]}


@register_op("mean")
def _mean(ins, attrs):
    return {"Out": [jnp.mean(first(ins, "X")).reshape((1,))]}


def _reduce(name, fn):
    @register_op(name)
    def _lower(ins, attrs, _fn=fn):
        x = first(ins, "X")
        axes = reduce_axes(attrs, x.ndim)
        out = _fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))
        if out.ndim == 0:
            out = out.reshape((1,)) if not attrs.get("keep_scalar", False) else out
        return {"Out": [out]}


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)


@register_op("arg_max", nondiff_inputs=("X",))
def _arg_max(ins, attrs):
    x = first(ins, "X")
    return {"Out": [jnp.argmax(x, axis=attrs.get("axis", -1)).astype(jnp.int64)]}


@register_op("arg_min", nondiff_inputs=("X",))
def _arg_min(ins, attrs):
    x = first(ins, "X")
    return {"Out": [jnp.argmin(x, axis=attrs.get("axis", -1)).astype(jnp.int64)]}


@register_op("top_k", nondiff_inputs=())
def _top_k(ins, attrs):
    x = first(ins, "X")
    k = int(maybe(ins, "K", attrs.get("k", 1)))
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register_op("p_norm")
def _p_norm(ins, attrs):
    x = first(ins, "X")
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis", -1)
    keepdim = attrs.get("keepdim", False)
    out = jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )
    return {"Out": [out]}


@register_op("cumsum")
def _cumsum(ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", -1)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if attrs.get("exclusive", False):
        out = out - x
    return {"Out": [out]}


@register_op("dot")
def _dot(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=True)]}
