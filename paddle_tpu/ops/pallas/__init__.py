"""Hand-written Pallas TPU kernels overriding jnp lowerings for ops XLA
fuses poorly (the analog of the reference's hand-fused CUDA kernels,
reference: paddle/fluid/operators/fused/)."""

from paddle_tpu.ops.pallas import flash_attention  # noqa: F401
