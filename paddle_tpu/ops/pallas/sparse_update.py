"""Pallas row-scatter for the sgd_sparse SelectedRows-analog update.

reference: paddle/fluid/operators/optimizers/sgd_op.h (sparse branch) —
the reference walks SelectedRows and subtracts each row in place. The XLA
form (`param.at[ids].add(-lr * rows)`) compiles to a scatter-add, which the
TPU serializes conservatively. This kernel exploits what the scatter cannot
assume: after the duplicate-merge (segment-sum over unique ids, done in XLA
before the call), every destination row is touched ONCE, so the update is a
sequential grid over unique ids with scalar-prefetch block indexing — each
step streams one [1, D] row through VMEM and writes param[ids[i]] back,
one read + one write per touched row, no serialization analysis.

Gated by FLAGS_pallas_sparse_update (off until on-chip numbers arbitrate);
interpret-mode parity vs the XLA scatter in tests/test_pallas_kernels.py.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.common import vma_names

try:  # pragma: no cover - absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["sparse_row_update"]


def _row_update_kernel(ids_ref, rows_ref, param_ref, out_ref):
    # param is also an input mapped to the same row, so the read is
    # well-defined; the aliased output buffer keeps untouched rows
    out_ref[...] = param_ref[...] + rows_ref[...]


def sparse_row_update(param, uniq_ids, merged_rows, interpret=None):
    """param[uniq_ids[i]] += merged_rows[i] with all ids DISTINCT.
    uniq_ids [N] int32, merged_rows [N, D]. Returns the updated param."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    vma = vma_names(param)
    if pltpu is None or (interpret and vma):
        return param.at[uniq_ids].add(merged_rows.astype(param.dtype))
    n, d = merged_rows.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i, ids: (i, 0)),
            pl.BlockSpec((1, d), lambda i, ids: (ids[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, d), lambda i, ids: (ids[i], 0)),
    )
    from paddle_tpu.ops.pallas.flash_attention import _sds

    return pl.pallas_call(
        _row_update_kernel,
        grid_spec=grid_spec,
        out_shape=_sds(param.shape, param.dtype, param, merged_rows),
        input_output_aliases={2: 0},  # param (flat operand 2) -> output
        interpret=interpret,
    )(uniq_ids.astype(jnp.int32), merged_rows.astype(param.dtype), param)
