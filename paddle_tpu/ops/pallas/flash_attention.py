"""Flash attention: fused online-softmax attention as a Pallas TPU kernel.

The reference ships a hand-fused CUDA attention for inference only
(reference: paddle/fluid/operators/fused/multihead_matmul_op.cu — QK^T +
softmax + PV in one kernel, no training support, no memory scaling). This
kernel is the TPU-native upgrade: blocked over the KV length with online
softmax (never materializing the [S, S] score matrix in HBM), differentiable
via custom_vjp, causal + additive-bias support — the long-sequence building
block that SURVEY §5.7 calls out as new first-class work.

Layout: q, k, v are [B, H, S, D]; bias (optional) is [B, S] additive on key
positions (0 keep / -1e9 masked). The grid is (B*H, S/BLOCK_Q); each program
streams K/V blocks of BLOCK_K rows through VMEM, carrying (running max,
normalizer, accumulator) in registers — FLOPs land on the MXU, the running
state on the VPU.

On non-TPU backends the same kernel runs in Pallas interpret mode (tests).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.common import vma_names

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["flash_attention"]

_NEG = -1e30


def _attention_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *,
                      sm_scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    # MXU discipline: dots run in the INPUT dtype (bf16 under AMP — full MXU
    # rate) with f32 accumulation via preferred_element_type; all softmax
    # math (max/exp/normalizer) stays f32
    q = q_ref[0]  # (BQ, D)
    nk = seq_len // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, BK) f32
        if bias_ref is not None:
            s = s + bias_ref[0, 0, pl.ds(j * block_k, block_k)][None, :]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    if causal:
        # only KV blocks at or before this Q block contribute
        nk_eff = jnp.minimum((qi + 1) * block_q // block_k
                             + (1 if block_q % block_k else 0), nk)
        m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)


def _jnp_attention(q, k, v, bias, sm_scale, causal):
    """Unfused attention with the kernel's exact masking semantics — the
    off-TPU fallback when Pallas interpret mode cannot run (shard_map)."""
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)[:, None, None, :]
    if causal:
        S = q.shape[2]
        rows = jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
        s = jnp.where((cols <= rows)[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _sds(shape, dtype, *refs):
    """ShapeDtypeStruct for a pallas_call out_shape, annotated with the
    union of the refs' varying-mesh-axes: required when the kernel runs
    inside shard_map (e.g. the pipeline_stack stage body), whose vma
    checker rejects un-annotated out_shapes."""
    vma = frozenset()
    for r in refs:
        vma |= vma_names(r)
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
        except TypeError:  # pragma: no cover - older jax without vma kwarg
            pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _fwd_impl(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    B, H, S, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    bh = B * H
    q3 = q.reshape(bh, S, D)
    k3 = k.reshape(bh, S, D)
    v3 = v.reshape(bh, S, D)
    grid = (bh, S // block_q)
    kw = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
        pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0), **kw),
        pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0), **kw),
    ]
    args = [q3, k3, v3]
    if bias is not None:
        # 3-D (bh, 1, S) so the block's trailing dims satisfy TPU tiling
        # (a (1, S) 2-D block has an untileable sublane dim of 1)
        bias_bh = jnp.broadcast_to(
            bias.reshape(B, 1, S), (B, H, S)
        ).reshape(bh, 1, S).astype(jnp.float32)
        in_specs.append(
            pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0), **kw)
        )
        args.append(bias_bh)
    if bias is not None:
        def kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref):
            _attention_kernel(
                q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                sm_scale=sm_scale, causal=causal, block_q=block_q,
                block_k=block_k, seq_len=S,
            )
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
            _attention_kernel(
                q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                sm_scale=sm_scale, causal=causal, block_q=block_q,
                block_k=block_k, seq_len=S,
            )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i), **kw),
        ],
        out_shape=[
            _sds((bh, S, D), q.dtype, q3, k3, v3),
            _sds((bh, 1, S), jnp.float32, q3, k3, v3),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, S, D), lse.reshape(B, H, S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _fwd_impl(q, k, v, bias, sm_scale, causal, block_q, block_k,
                       interpret)
    return out


def _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd_impl(q, k, v, bias, sm_scale, causal, block_q, block_k,
                         interpret)
    return out, (q, k, v, bias, out, lse)


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dbias_ref, *, sm_scale, causal, block_q,
                     block_k, seq_len):
    """One (batch*head, KV block) program: stream Q blocks, accumulate
    dk/dv (+ per-head dbias) for this KV block. Scores are recomputed from
    the saved LSE, so nothing O(S^2) ever reaches HBM."""
    j = pl.program_id(1)
    # dots in input dtype, f32 accumulation (see _attention_kernel)
    k = k_ref[0]  # (BK, D)
    v = v_ref[0]
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(i, carry):
        dk, dv, dbias = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        g = g_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # (BQ, BK) f32
        if bias_ref is not None:
            s = s + bias_ref[0, 0, pl.ds(j * block_k, block_k)][None, :]
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(cols <= rows, s, _NEG)
        # fully-masked rows have lse == _NEG: their fwd output was 0, so
        # their gradient contribution must be 0, not exp(s - _NEG)
        p = jnp.where(
            (lse <= _NEG / 2)[:, None], 0.0, jnp.exp(s - lse[:, None])
        )  # (BQ, BK)
        dv_new = dv + jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        dbias_new = dbias + ds.sum(axis=0)
        return dk_new, dv_new, dbias_new

    dk0 = jnp.zeros((block_k, k_ref.shape[-1]), jnp.float32)
    dv0 = jnp.zeros_like(dk0)
    db0 = jnp.zeros((block_k,), jnp.float32)
    nq = seq_len // block_q
    if causal:
        # only Q blocks at or after this KV block contribute
        start = (j * block_k) // block_q
        dk, dv, dbias = jax.lax.fori_loop(start, nq, body, (dk0, dv0, db0))
    else:
        dk, dv, dbias = jax.lax.fori_loop(0, nq, body, (dk0, dv0, db0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    if dbias_ref is not None:
        dbias_ref[0, 0] = dbias


def _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, g_ref, lse_ref, delta_ref,
                   dq_ref, *, sm_scale, causal, block_q, block_k, seq_len):
    """One (batch*head, Q block) program: stream KV blocks, accumulate dq."""
    i = pl.program_id(1)
    # dots in input dtype, f32 accumulation (see _attention_kernel)
    q = q_ref[0]
    g = g_ref[0]
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    rows = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0, 0, pl.ds(j * block_k, block_k)][None, :]
        if causal:
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG)
        p = jnp.where(
            (lse <= _NEG / 2)[:, None], 0.0, jnp.exp(s - lse[:, None])
        )
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale

    dq0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    nk = seq_len // block_k
    if causal:
        nk_eff = jnp.minimum((i + 1) * block_q // block_k
                             + (1 if block_q % block_k else 0), nk)
        dq = jax.lax.fori_loop(0, nk_eff, body, dq0)
    else:
        dq = jax.lax.fori_loop(0, nk, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    """Blocked Pallas backward from the saved log-sum-exp (FlashAttention-2
    split: a dk/dv kernel gridded over KV blocks and a dq kernel gridded over
    Q blocks). Memory stays O(S · block) per program — the round-2 jnp
    backward materialized the full [B,H,S,S] score matrix in HBM."""
    q, k, v, bias, out, lse = res
    B, H, S, D = q.shape
    bq = min(block_q, S)
    bk = min(block_k, S)
    bh = B * H
    # delta = rowsum(dO * O) — cheap elementwise reduce, leave it to XLA
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (B, H, S)
    q3, k3, v3 = (t.reshape(bh, S, D) for t in (q, k, v))
    g3 = g.reshape(bh, S, D)
    lse3 = lse.reshape(bh, 1, S)
    delta3 = delta.reshape(bh, 1, S)
    kw = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    full = lambda: pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0), **kw)
    row = lambda: pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0), **kw)
    has_bias = bias is not None
    if has_bias:
        bias_bh = jnp.broadcast_to(
            bias.reshape(B, 1, S), (B, H, S)
        ).reshape(bh, 1, S).astype(jnp.float32)

    # ---- dk/dv (+ per-bh dbias) --------------------------------------
    kv_block = lambda: pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0), **kw)
    in_specs = [full(), kv_block(), kv_block()]
    args = [q3, k3, v3]
    if has_bias:
        in_specs.append(row())
        args.append(bias_bh)
    in_specs += [full(), row(), row()]
    args += [g3, lse3, delta3]
    kv_out_specs = [
        pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0), **kw),
        pl.BlockSpec((1, bk, D), lambda b, j: (b, j, 0), **kw),
    ]
    kv_out_shapes = [
        _sds((bh, S, D), k.dtype, q3, k3, v3, g3),
        _sds((bh, S, D), v.dtype, q3, k3, v3, g3),
    ]
    if has_bias:
        kv_out_specs.append(
            pl.BlockSpec((1, 1, bk), lambda b, j: (b, 0, j), **kw)
        )
        kv_out_shapes.append(_sds((bh, 1, S), jnp.float32, q3, k3, v3, g3))

    def dkdv_kernel(*refs):
        if has_bias:
            (q_r, k_r, v_r, b_r, g_r, l_r, d_r, dk_r, dv_r, db_r) = refs
        else:
            (q_r, k_r, v_r, g_r, l_r, d_r, dk_r, dv_r) = refs
            b_r, db_r = None, None
        _bwd_dkdv_kernel(
            q_r, k_r, v_r, b_r, g_r, l_r, d_r, dk_r, dv_r, db_r,
            sm_scale=sm_scale, causal=causal, block_q=bq, block_k=bk,
            seq_len=S,
        )

    outs = pl.pallas_call(
        dkdv_kernel,
        grid=(bh, S // bk),
        in_specs=in_specs,
        out_specs=kv_out_specs,
        out_shape=kv_out_shapes,
        interpret=interpret,
    )(*args)
    dk3, dv3 = outs[0], outs[1]
    dbias = (
        outs[2].reshape(B, H, S).sum(axis=1) if has_bias else None
    )

    # ---- dq ----------------------------------------------------------
    dq_in_specs = [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0), **kw),
        full(), full(),
    ]
    dq_args = [q3, k3, v3]
    if has_bias:
        dq_in_specs.append(row())
        dq_args.append(bias_bh)
    dq_in_specs += [
        pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0), **kw),
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i), **kw),
        pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i), **kw),
    ]
    dq_args += [g3, lse3, delta3]

    def dq_kernel(*refs):
        if has_bias:
            (q_r, k_r, v_r, b_r, g_r, l_r, d_r, dq_r) = refs
        else:
            (q_r, k_r, v_r, g_r, l_r, d_r, dq_r) = refs
            b_r = None
        _bwd_dq_kernel(
            q_r, k_r, v_r, b_r, g_r, l_r, d_r, dq_r,
            sm_scale=sm_scale, causal=causal, block_q=bq, block_k=bk,
            seq_len=S,
        )

    dq3 = pl.pallas_call(
        dq_kernel,
        grid=(bh, S // bq),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0), **kw),
        out_shape=_sds((bh, S, D), q.dtype, q3, k3, v3, g3),
        interpret=interpret,
    )(*dq_args)

    return (
        dq3.reshape(B, H, S, D),
        dk3.reshape(B, H, S, D),
        dv3.reshape(B, H, S, D),
        # cotangent dtype must match the bias primal (custom_vjp contract)
        dbias.astype(bias.dtype) if dbias is not None else None,
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=None,
                    block_q=128, block_k=128, interpret=None):
    """Fused attention over [B, H, S, D] tensors. `bias` is an optional
    [B, S] additive key-position bias (padding mask). Differentiable."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    # kernel dots run in the operand dtype (bf16 stays on the MXU fast
    # path); mixed q/k/v dtypes are promoted once here so the dots agree
    dt = jnp.result_type(q.dtype, k.dtype, v.dtype)
    q, k, v = q.astype(dt), k.astype(dt), v.astype(dt)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # pallas interpret mode inside a shard_map region trips an MLIR
    # closed_call caching bug (KeyError in cached_primitive_lowerings), so
    # off-TPU under shard_map use the numerically-identical jnp path; the
    # real chip always runs the Pallas kernel
    if interpret and vma_names(q):
        return _jnp_attention(q, k, v, bias, float(sm_scale), bool(causal))
    S = q.shape[2]
    bq = min(block_q, S)
    bk = min(block_k, S)
    while S % bq:
        bq //= 2
    while S % bk:
        bk //= 2
    return _flash(q, k, v, bias, float(sm_scale), bool(causal),
                  max(bq, 1), max(bk, 1), bool(interpret))
