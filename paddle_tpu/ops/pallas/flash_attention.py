"""Flash attention: fused online-softmax attention as a Pallas TPU kernel.

The reference ships a hand-fused CUDA attention for inference only
(reference: paddle/fluid/operators/fused/multihead_matmul_op.cu — QK^T +
softmax + PV in one kernel, no training support, no memory scaling). This
kernel is the TPU-native upgrade: blocked over the KV length with online
softmax (never materializing the [S, S] score matrix in HBM), differentiable
via custom_vjp, causal + additive-bias support — the long-sequence building
block that SURVEY §5.7 calls out as new first-class work.

Layout: q, k, v are [B, H, S, D]; bias (optional) is [B, S] additive on key
positions (0 keep / -1e9 masked). The grid is (B*H, S/BLOCK_Q); each program
streams K/V blocks of BLOCK_K rows through VMEM, carrying (running max,
normalizer, accumulator) in registers — FLOPs land on the MXU, the running
state on the VPU.

On non-TPU backends the same kernel runs in Pallas interpret mode (tests).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["flash_attention"]

_NEG = -1e30


def _attention_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *,
                      sm_scale, causal, block_q, block_k, seq_len):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (BQ, D)
    nk = seq_len // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (BQ, BK)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, pl.ds(j * block_k, block_k)][None, :]
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    if causal:
        # only KV blocks at or before this Q block contribute
        nk_eff = jnp.minimum((qi + 1) * block_q // block_k
                             + (1 if block_q % block_k else 0), nk)
        m, l, acc = jax.lax.fori_loop(0, nk_eff, body, (m0, l0, acc0))
    else:
        m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)


def _fwd_impl(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    B, H, S, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    bh = B * H
    q3 = q.reshape(bh, S, D)
    k3 = k.reshape(bh, S, D)
    v3 = v.reshape(bh, S, D)
    grid = (bh, S // block_q)
    kw = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
        pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0), **kw),
        pl.BlockSpec((1, S, D), lambda b, i: (b, 0, 0), **kw),
    ]
    args = [q3, k3, v3]
    if bias is not None:
        # 3-D (bh, 1, S) so the block's trailing dims satisfy TPU tiling
        # (a (1, S) 2-D block has an untileable sublane dim of 1)
        bias_bh = jnp.broadcast_to(
            bias.reshape(B, 1, S), (B, H, S)
        ).reshape(bh, 1, S).astype(jnp.float32)
        in_specs.append(
            pl.BlockSpec((1, 1, S), lambda b, i: (b, 0, 0), **kw)
        )
        args.append(bias_bh)
    if bias is not None:
        def kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref):
            _attention_kernel(
                q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                sm_scale=sm_scale, causal=causal, block_q=block_q,
                block_k=block_k, seq_len=S,
            )
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
            _attention_kernel(
                q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                sm_scale=sm_scale, causal=causal, block_q=block_q,
                block_k=block_k, seq_len=S,
            )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0), **kw),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i), **kw),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, S, D), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, S), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(B, H, S, D), lse.reshape(B, H, S)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    out, _ = _fwd_impl(q, k, v, bias, sm_scale, causal, block_q, block_k,
                       interpret)
    return out


def _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    out, lse = _fwd_impl(q, k, v, bias, sm_scale, causal, block_q, block_k,
                         interpret)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    """Backward from saved log-sum-exp (standard flash-attention gradient;
    jnp form — XLA tiles the [S, S] recompute per head)."""
    q, k, v, bias, out, lse = res
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * sm_scale
    if bias is not None:
        s = s + bias[:, None, None, :]
    if causal:
        S = q.shape[2]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    # a fully-masked row has lse == _NEG, making exp(s - lse) blow up; its
    # forward output was 0, so its gradient contribution must be 0 too
    p = jnp.where(
        (lse <= _NEG / 2)[..., None], 0.0, jnp.exp(s - lse[..., None])
    )
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
    dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vf)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kf) * sm_scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf) * sm_scale
    dbias = jnp.sum(ds, axis=(1, 2)) if bias is not None else None
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), dbias)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias=None, causal=False, sm_scale=None,
                    block_q=128, block_k=128, interpret=None):
    """Fused attention over [B, H, S, D] tensors. `bias` is an optional
    [B, S] additive key-position bias (padding mask). Differentiable."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    S = q.shape[2]
    bq = min(block_q, S)
    bk = min(block_k, S)
    while S % bq:
        bq //= 2
    while S % bk:
        bk //= 2
    return _flash(q, k, v, bias, float(sm_scale), bool(causal),
                  max(bq, 1), max(bk, 1), bool(interpret))
