"""Blocked top-k magnitude compaction — the DGC wire-builder kernel.

reference: the reference compacts gradients for DGC with a CUDA top-k
sampler (reference: paddle/fluid/operators/dgc_op.h via the DGC library);
SURVEY §7 names top-k compaction a Pallas candidate because a full
`lax.top_k` over a multi-million-element gradient sorts the WHOLE vector
through HBM. This kernel streams the vector once in VMEM-sized blocks,
keeps each block's local top-k (every global top-k element is by
construction in its own block's local top-k), and the tiny candidate set
(n_blocks * k) gets the final exact top-k in XLA — HBM traffic drops from
O(N log N)-ish sort movement to one read of N plus k * N/BLK candidates.

Gated by FLAGS_pallas_dgc_topk (off by default until on-chip numbers
arbitrate); numerically exact vs lax.top_k on magnitudes, asserted in
tests/test_pallas_kernels.py.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from paddle_tpu.ops.common import vma_names

try:  # pragma: no cover - absent on CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = ["blocked_topk_abs"]


def _block_topk_kernel(x_ref, vals_ref, idx_ref, *, k, block, n):
    i = pl.program_id(0)
    # pad lanes (beyond the true length n, last block only) get magnitude
    # -1: never selected over any real |x| >= 0, so indices stay < n
    pos = i * block + jax.lax.broadcasted_iota(jnp.int32, (block,), 0)
    v = jnp.where(pos < n, jnp.abs(x_ref[...]), -1.0)
    top_v, top_i = jax.lax.top_k(v, k)
    vals_ref[...] = top_v
    idx_ref[...] = (top_i + i * block).astype(jnp.int32)


def blocked_topk_abs(x, k, block=131072, interpret=None):
    """(top_k values of |x|, their indices) for a 1-D x — exact, order by
    descending magnitude. Falls back to lax.top_k when the kernel cannot
    run (inside a shard_map region off-TPU, or tiny inputs)."""
    n = x.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    vma = vma_names(x)
    if (interpret and vma) or n <= 2 * k or n <= block:
        mag = jnp.abs(x)
        top_v, top_i = jax.lax.top_k(mag, k)
        return top_v, top_i.astype(jnp.int32)
    from paddle_tpu.ops.pallas.flash_attention import _sds

    nb = -(-n // block)
    padded = jnp.pad(x, (0, nb * block - n))  # pads masked inside the kernel
    kw = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    xf = padded.astype(jnp.float32)
    vals, idx = pl.pallas_call(
        functools.partial(_block_topk_kernel, k=k, block=block, n=n),
        grid=(nb,),
        in_specs=[pl.BlockSpec((block,), lambda i: (i,), **kw)],
        out_specs=[
            pl.BlockSpec((k,), lambda i: (i,), **kw),
            pl.BlockSpec((k,), lambda i: (i,), **kw),
        ],
        out_shape=[
            _sds((nb * k,), jnp.float32, xf),
            _sds((nb * k,), jnp.int32, xf),
        ],
        interpret=interpret,
    )(xf)
    top_v, cand = jax.lax.top_k(vals, k)
    return top_v, idx[cand]
