"""Control-flow ops: while / cond, lowered to lax.while_loop / lax.cond.

The reference runs sub-blocks through a nested Executor on the host
(reference: paddle/fluid/operators/controlflow/while_op.cc:43,
conditional_block_op.h) — a host round-trip per iteration. Here the sub-block
is traced once and becomes a lax structured-control-flow region inside the
same XLA computation: no host involvement, static shapes, compiler-visible
loop body (the form XLA requires and the TPU rewards).

Carried state is inferred from the IR: every variable the sub-block writes
that already exists in the enclosing env is loop-carried; pure temporaries
stay local. This replaces the reference's StepScope machinery
(reference: paddle/fluid/operators/controlflow/while_op_helper.cc).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first
from paddle_tpu.utils.enforce import EnforceError


def _sub_block_rw(sub):
    written, read = [], []
    seen_w, seen_r = set(), set()
    for sop in sub.ops:
        for n in sop.input_names():
            if n not in seen_r:
                read.append(n)
                seen_r.add(n)
        for n in sop.output_names():
            if n not in seen_w:
                written.append(n)
                seen_w.add(n)
    return written, read


def run_control_flow_op(op, block, env, rng_key, interpret):
    if op.type == "while":
        _run_while(op, block, env, rng_key, interpret)
    elif op.type == "conditional_block":
        _run_cond(op, block, env, rng_key, interpret)
    else:
        raise EnforceError(f"unhandled control-flow op {op.type}")


def _run_while(op, block, env, rng_key, interpret):
    sub = block.program.block(op.attrs["sub_block"])
    cond_name = op.inputs["Condition"][0]
    written, _ = _sub_block_rw(sub)
    carry_names = [n for n in dict.fromkeys([cond_name] + written) if n in env]
    outer = dict(env)

    def cond_fn(carry):
        c = carry[0][cond_name]
        return jnp.reshape(c, ()).astype(bool)

    def body_fn(carry):
        state, it = carry
        local = dict(outer)
        local.update(state)
        # fold the iteration counter so stateful ops (dropout etc.) draw
        # fresh randomness each trip
        interpret(sub, local, jax.random.fold_in(rng_key, it))
        return {n: local[n] for n in carry_names}, it + 1

    init = ({n: env[n] for n in carry_names}, jnp.zeros((), jnp.uint32))
    final, _ = jax.lax.while_loop(cond_fn, body_fn, init)
    env.update(final)


def _run_cond(op, block, env, rng_key, interpret):
    """Two-armed conditional: attrs sub_block (true) and optionally
    sub_block_false; outputs listed in op.outputs['Out']."""
    cond = env[op.inputs["Cond"][0]]
    true_blk = block.program.block(op.attrs["sub_block"])
    false_idx = op.attrs.get("sub_block_false", -1)
    out_names = op.outputs.get("Out", [])
    outer = dict(env)

    def run_branch(blk):
        def fn(_):
            local = dict(outer)
            interpret(blk, local, rng_key)
            return tuple(local[n] for n in out_names)

        return fn

    def fallthrough(_):
        missing = [n for n in out_names if n not in outer]
        if missing:
            raise EnforceError(
                f"conditional_block outputs {missing} have no value when the "
                f"condition is false — provide a false branch (false_fn) that "
                f"produces them, or initialize the vars before the cond"
            )
        return tuple(outer[n] for n in out_names)

    false_fn = (
        run_branch(block.program.block(false_idx)) if false_idx >= 0 else fallthrough
    )
    outs = jax.lax.cond(
        jnp.reshape(cond, ()).astype(bool), run_branch(true_blk), false_fn, 0
    )
    for n, v in zip(out_names, outs):
        env[n] = v


# -- small helper ops used by loop constructs -------------------------------


@register_op("increment")
def _increment(ins, attrs):
    x = first(ins, "X")
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


@register_op("less_than_scalar")
def _less_than_scalar(ins, attrs):
    x = first(ins, "X")
    return {"Out": [x < attrs["value"]]}
