"""Optimizer update rules as ops.

Mirrors the reference's optimizer op kernels (reference:
paddle/fluid/operators/optimizers/sgd_op.h, momentum_op.h, adam_op.h,
lamb_op.h, lars_momentum_op.cc ...). Updates are pure functions returning
*Out states; the executor's buffer donation makes them in-place at the XLA
level. All moment arithmetic runs in fp32 even for bf16 params.
"""

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError


def _f32(x):
    return x.astype(jnp.float32)


@register_op("sgd")
def _sgd(ins, attrs):
    p, g, lr = first(ins, "Param"), first(ins, "Grad"), first(ins, "LearningRate")
    out = _f32(p) - _f32(lr) * _f32(g)
    return {"ParamOut": [out.astype(p.dtype)]}


@register_op("sgd_sparse", nondiff_inputs=("Ids",))
def _sgd_sparse(ins, attrs):
    """SelectedRows-analog row update (reference: paddle/fluid/operators/
    optimizers/sgd_op.h sparse branch; selected_rows.h:32): the embedding
    grad never materializes as a [V, D] dense tensor — the looked-up rows'
    cotangent scatter-subtracts straight into the touched parameter rows
    (duplicate ids combine inside the scatter, the segment-sum the
    reference does in SumKernel's SelectedRows branch). Emitted by the
    sparse_weight_update pass replacing lookup_table_grad + sgd."""
    p = first(ins, "Param")
    ids = first(ins, "Ids").reshape(-1).astype(jnp.int32)
    rows = first(ins, "RowGrad")
    lr = _f32(first(ins, "LearningRate")).reshape(())
    d = p.shape[-1]
    rows2 = rows.reshape(-1, d).astype(p.dtype)
    pi = attrs.get("padding_idx", -1)
    if pi is not None and pi >= 0:
        # the forward zeroed padding rows, so their grads must not land
        rows2 = jnp.where((ids == pi)[:, None], 0.0, rows2)
    scaled = -(lr.astype(p.dtype)) * rows2
    from paddle_tpu.utils.flags import flags as _flags

    if _flags.pallas_sparse_update:
        # duplicate-merge in XLA (a tiny [n_tokens, D] scatter), then the
        # Pallas one-row-per-step kernel writes the touched param rows —
        # flag-gated until on-chip numbers arbitrate (SURVEY §7)
        from paddle_tpu.ops.pallas.sparse_update import sparse_row_update

        n = ids.shape[0]
        uniq, inv, counts = jnp.unique(
            ids, return_inverse=True, return_counts=True, size=n,
            fill_value=0,
        )
        merged = jnp.zeros((n, d), p.dtype).at[inv.reshape(-1)].add(scaled)
        # fill slots duplicate id 0 with a zero row. They must run BEFORE
        # the real id-0 slot in the kernel's sequential grid: a zero-add
        # step writes the row's CURRENT value back, so a pad step ordered
        # after the real update could, under pipelined prefetch, clobber
        # it with the stale pre-update row. Pads-first ordering makes
        # every pad write the untouched original value — race-free.
        is_fill = counts == 0
        perm = jnp.argsort(jnp.where(is_fill, -1, jnp.arange(n)))
        return {
            "ParamOut": [sparse_row_update(p, uniq[perm], merged[perm])]
        }
    return {
        "ParamOut": [p.at[ids].add(scaled)],
    }


@register_op("momentum")
def _momentum(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    v, lr = _f32(first(ins, "Velocity")), _f32(first(ins, "LearningRate"))
    mu = attrs.get("mu", 0.9)
    rd = attrs.get("regularization_coeff", 0.0)
    if rd and attrs.get("regularization_method", "") == "l2_decay":
        g = g + rd * p
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - lr * (g + mu * v_out)
    else:
        p_out = p - lr * v_out
    param = first(ins, "Param")
    return {
        "ParamOut": [p_out.astype(param.dtype)],
        "VelocityOut": [v_out],
    }


@register_op("adam")
def _adam(ins, attrs):
    p = _f32(first(ins, "Param"))
    g = _f32(first(ins, "Grad"))
    m1, m2 = _f32(first(ins, "Moment1")), _f32(first(ins, "Moment2"))
    b1p, b2p = _f32(first(ins, "Beta1Pow")), _f32(first(ins, "Beta2Pow"))
    lr = _f32(first(ins, "LearningRate"))
    b1 = float(maybe(ins, "Beta1Tensor", attrs.get("beta1", 0.9)))
    b2 = float(maybe(ins, "Beta2Tensor", attrs.get("beta2", 0.999)))
    eps = attrs.get("epsilon", 1e-8)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    param = first(ins, "Param")
    return {
        "ParamOut": [p_out.astype(param.dtype)],
        "Moment1Out": [m1n],
        "Moment2Out": [m2n],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register_op("adamw")
def _adamw(ins, attrs):
    """Decoupled weight decay on top of adam."""
    coeff = attrs.get("coeff", 0.01)
    p = _f32(first(ins, "Param"))
    lr = _f32(first(ins, "LearningRate"))
    outs = _adam(ins, attrs)
    decayed = outs["ParamOut"][0].astype(jnp.float32) - lr * coeff * p
    outs["ParamOut"] = [decayed.astype(first(ins, "Param").dtype)]
    return outs


@register_op("adagrad")
def _adagrad(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    moment, lr = _f32(first(ins, "Moment")), _f32(first(ins, "LearningRate"))
    eps = attrs.get("epsilon", 1e-6)
    m_out = moment + jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "MomentOut": [m_out],
    }


@register_op("rmsprop")
def _rmsprop(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    ms, lr = _f32(first(ins, "MeanSquare")), _f32(first(ins, "LearningRate"))
    mom = _f32(first(ins, "Moment"))
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    if attrs.get("centered", False):
        mg = _f32(first(ins, "MeanGrad"))
        mg_out = rho * mg + (1 - rho) * g
        ms_out = rho * ms + (1 - rho) * jnp.square(g)
        denom = jnp.sqrt(ms_out - jnp.square(mg_out) + eps)
    else:
        mg_out = None
        ms_out = rho * ms + (1 - rho) * jnp.square(g)
        denom = jnp.sqrt(ms_out + eps)
    mom_out = momentum * mom + lr * g / denom
    p_out = p - mom_out
    outs = {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "MomentOut": [mom_out],
        "MeanSquareOut": [ms_out],
    }
    if mg_out is not None:
        outs["MeanGradOut"] = [mg_out]
    return outs


@register_op("adamax")
def _adamax(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    m, inf_norm = _f32(first(ins, "Moment")), _f32(first(ins, "InfNorm"))
    b1p, lr = _f32(first(ins, "Beta1Pow")), _f32(first(ins, "LearningRate"))
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    p_out = p - (lr / (1 - b1p)) * m_out / (inf_out + eps)
    return {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "MomentOut": [m_out],
        "InfNormOut": [inf_out],
    }


@register_op("adadelta")
def _adadelta(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    avg_sq_grad = _f32(first(ins, "AvgSquaredGrad"))
    avg_sq_upd = _f32(first(ins, "AvgSquaredUpdate"))
    rho, eps = attrs.get("rho", 0.95), attrs.get("epsilon", 1e-6)
    asg_out = rho * avg_sq_grad + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_upd + eps) / (asg_out + eps)) * g
    asu_out = rho * avg_sq_upd + (1 - rho) * jnp.square(update)
    p_out = p + update
    return {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "AvgSquaredGradOut": [asg_out],
        "AvgSquaredUpdateOut": [asu_out],
    }


@register_op("decayed_adagrad")
def _decayed_adagrad(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    moment, lr = _f32(first(ins, "Moment")), _f32(first(ins, "LearningRate"))
    decay, eps = attrs.get("decay", 0.95), attrs.get("epsilon", 1e-6)
    m_out = decay * moment + (1 - decay) * jnp.square(g)
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "MomentOut": [m_out],
    }


@register_op("ftrl")
def _ftrl(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    sq, lin = _f32(first(ins, "SquaredAccumulator")), _f32(
        first(ins, "LinearAccumulator")
    )
    lr = _f32(first(ins, "LearningRate"))
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    new_lin = lin + g - sigma * p
    x = jnp.clip(new_lin, -l1, l1) - new_lin
    y = jnp.power(new_sq, -power) / lr + 2 * l2
    p_out = x / y
    return {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "SquaredAccumOut": [new_sq],
        "LinearAccumOut": [new_lin],
    }


@register_op("lamb")
def _lamb(ins, attrs):
    """reference: paddle/fluid/operators/optimizers/lamb_op.h — layerwise
    adaptive moments, the large-batch BERT optimizer."""
    p = _f32(first(ins, "Param"))
    g = _f32(first(ins, "Grad"))
    m1, m2 = _f32(first(ins, "Moment1")), _f32(first(ins, "Moment2"))
    b1p, b2p = _f32(first(ins, "Beta1Pow")), _f32(first(ins, "Beta2Pow"))
    lr = _f32(first(ins, "LearningRate"))
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    m1_hat = m1n / (1 - b1p)
    m2_hat = m2n / (1 - b2p)
    r = m1_hat / (jnp.sqrt(m2_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_out = p - lr * trust * r
    return {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "Moment1Out": [m1n],
        "Moment2Out": [m2n],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register_op("lars_momentum")
def _lars_momentum(ins, attrs):
    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    v, lr = _f32(first(ins, "Velocity")), _f32(first(ins, "LearningRate"))
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps),
        lr,
    )
    v_out = mu * v + local_lr * (g + wd * p)
    p_out = p - v_out
    return {
        "ParamOut": [p_out.astype(first(ins, "Param").dtype)],
        "VelocityOut": [v_out],
    }


@register_op("dpsgd", stateful=True)
def _dpsgd(ins, attrs):
    """Differentially-private SGD (reference: paddle/fluid/operators/
    optimizers/dpsgd_op.cc): clip per-batch grad, add gaussian noise."""
    import jax

    from paddle_tpu.ops.common import rng_key

    p, g = _f32(first(ins, "Param")), _f32(first(ins, "Grad"))
    lr = _f32(first(ins, "LearningRate"))
    clip = attrs.get("clip", 10.0)
    batch_size = attrs.get("batch_size", 16.0)
    sigma = attrs.get("sigma", 1.0)
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = jnp.where(g_norm > clip, g * (clip / g_norm), g)
    noise = sigma * clip * jax.random.normal(rng_key(ins), g.shape)
    p_out = p - lr * (g + noise / batch_size)
    return {"ParamOut": [p_out.astype(first(ins, "Param").dtype)]}


@register_op("check_finite_and_unscale", nondiff_inputs=("Scale",))
def _check_finite_and_unscale(ins, attrs):
    """reference: paddle/fluid/operators/amp/check_finite_and_unscale_op.cc —
    unscale every gradient by 1/Scale and report whether any is non-finite."""
    xs = ins.get("X", [])
    scale = _f32(first(ins, "Scale")).reshape(())
    inv = 1.0 / scale
    found = jnp.zeros((), jnp.bool_)
    outs = []
    for x in xs:
        found = jnp.logical_or(found, jnp.logical_not(jnp.all(jnp.isfinite(x))))
        outs.append((_f32(x) * inv).astype(x.dtype))
    return {"Out": outs, "FoundInfinite": [found.reshape(1)]}


@register_op("update_loss_scaling", nondiff_inputs=("FoundInfinite", "PrevLossScaling", "InGoodSteps", "InBadSteps"))
def _update_loss_scaling(ins, attrs):
    """reference: paddle/fluid/operators/amp/update_loss_scaling_op.cc.
    On overflow: zero the gradients (skipping the update) and after
    decr_every_n_nan_or_inf consecutive overflows halve the scale; after
    incr_every_n_steps clean steps, grow it."""
    xs = ins.get("X", [])
    found = first(ins, "FoundInfinite").reshape(()).astype(jnp.bool_)
    scale = _f32(first(ins, "PrevLossScaling")).reshape(())
    good = first(ins, "InGoodSteps").reshape(()).astype(jnp.int32)
    bad = first(ins, "InBadSteps").reshape(()).astype(jnp.int32)
    incr_every = attrs.get("incr_every_n_steps", 1000)
    decr_every = attrs.get("decr_every_n_nan_or_inf", 2)
    incr_ratio = attrs.get("incr_ratio", 2.0)
    decr_ratio = attrs.get("decr_ratio", 0.5)
    new_bad = jnp.where(found, bad + 1, 0)
    new_good = jnp.where(found, 0, good + 1)
    should_decr = new_bad >= decr_every
    should_incr = new_good >= incr_every
    new_scale = jnp.where(should_decr, scale * decr_ratio, scale)
    new_scale = jnp.where(should_incr, scale * incr_ratio, new_scale)
    new_scale = jnp.maximum(new_scale, 1e-8)
    new_bad = jnp.where(should_decr, 0, new_bad)
    new_good = jnp.where(should_incr, 0, new_good)
    prev = first(ins, "PrevLossScaling")
    outs = [jnp.where(found, jnp.zeros_like(x), x) for x in xs]
    return {
        "Out": outs,
        "LossScaling": [new_scale.reshape(1).astype(prev.dtype)],
        "OutGoodSteps": [new_good.reshape(1)],
        "OutBadSteps": [new_bad.reshape(1)],
    }


@register_op("ema_update")
def _ema_update(ins, attrs):
    """Shadow-parameter EMA step (reference: python/paddle/fluid/
    optimizer.py:3166 ExponentialMovingAverage — its in-graph ema ops)."""
    p, s = first(ins, "Param"), first(ins, "Shadow")
    decay = attrs.get("decay", 0.999)
    return {"ShadowOut": [decay * s + (1.0 - decay) * p.astype(s.dtype)]}


@register_op("model_average_update")
def _model_average_update(ins, attrs):
    """Windowed running parameter sum (reference: python/paddle/fluid/
    optimizer.py:2862 ModelAverage accumulators). The effective window is
    clamp(rate * total_updates, min_window, max_window); once `count`
    reaches it the sum decays geometrically so old snapshots age out — the
    static-shape analog of the reference's sum_1/2/3 window restarts.
    Count stores (window_count, total_updates)."""
    p = first(ins, "Param")
    s, c = first(ins, "Sum"), first(ins, "Count")
    rate = attrs.get("rate", 0.15)
    min_w = attrs.get("min_window", 10000.0)
    max_w = attrs.get("max_window", 10000.0)
    cnt = c.reshape(-1)[0]
    total = c.reshape(-1)[1] if c.size > 1 else cnt
    window = jnp.clip(rate * (total + 1.0), min_w, max_w)
    at_cap = cnt >= window
    new_sum = jnp.where(
        at_cap, s * (window - 1.0) / window, s
    ) + p.astype(s.dtype)
    new_cnt = jnp.minimum(cnt + 1.0, window)
    out = jnp.stack([new_cnt, total + 1.0])
    return {"SumOut": [new_sum], "CountOut": [out]}


@register_op("dgc_momentum")
def _dgc_momentum(ins, attrs):
    """DGC update (reference: paddle/fluid/operators/dgc_op.cc semantics):
    u = mu*u + g; v += u; select |v| above the sparsity quantile; apply the
    selected (sparse) update; clear u,v at selected positions (error
    feedback keeps the rest).

    Two forms:
    * dense (default): one fused per-param op; under GSPMD the gradient
      exchange is compiler-inserted dense traffic (compression semantics
      without wire savings).
    * sparse exchange (CompiledProgram data-parallel + DGC, per-shard
      mode): the block runs per-shard under shard_map, U/V are per-shard
      state with a leading local axis, and the update is a top-k
      (index, value) all_gather over the data axis — 2*k*n floats on the
      wire instead of the dense gradient (reference:
      details/sparse_all_reduce_op_handle.h).
    """
    from paddle_tpu.parallel import env as penv

    p = first(ins, "Param")
    g = first(ins, "Grad").astype(p.dtype)
    u, v = first(ins, "U"), first(ins, "V")
    lr = _f32(first(ins, "LearningRate")).reshape(())
    step = first(ins, "CurrentStep").reshape(())
    mu = attrs.get("mu", 0.9)
    begin = attrs.get("rampup_begin_step", 0.0)
    ramp = max(attrs.get("rampup_step", 1.0), 1.0)
    sparsity = jnp.asarray(attrs.get("sparsity", [0.999]), jnp.float32)
    L = sparsity.shape[0]
    dgc_axis = penv.current_dgc_axis()

    if dgc_axis is None and u.ndim == p.ndim + 1:
        raise EnforceError(
            "dgc accumulators carry per-shard state (leading shard axis) "
            "from a sparse-exchange CompiledProgram run; keep running the "
            "compiled program, or reset the accumulators, before using the "
            "plain Executor"
        )
    if dgc_axis is not None:
        # per-shard sparse exchange: U/V arrive [1, ...] (this shard's
        # slice), Grad is this shard's local-batch gradient
        u = u[0]
        v = v[0]

    u_new = mu * u + g
    contrib = g + mu * u_new if attrs.get("use_nesterov", False) else u_new
    # warmup ramp through the sparsity list; before rampup_begin the update
    # is PLAIN momentum (reference runs the regular momentum op until
    # rampup_begin_step) — u carries velocity, v stays untouched
    idx = jnp.clip(((step - begin) * L / ramp).astype(jnp.int32), 0, L - 1)
    ratio = jnp.where(step < begin, 0.0, jnp.take(sparsity, idx))
    is_dense = ratio <= 0.0

    if dgc_axis is not None:
        from jax import lax

        size = int(np.prod(p.shape))
        # static top-k bound from the FINAL (largest-k) sparsity; the
        # traced ramp ratio masks the tail during warmup
        k_max = max(1, int(round(size * (1.0 - float(min(
            attrs.get("sparsity", [0.999])
        ))))))
        v_acc = (v + contrib).reshape(-1)
        mag = jnp.abs(v_acc)
        from paddle_tpu.utils.flags import flags as _flags

        if _flags.pallas_dgc_topk:
            # blocked VMEM-streaming top-k (ops/pallas/topk.py); falls
            # back to lax.top_k off-TPU inside shard_map
            from paddle_tpu.ops.pallas.topk import blocked_topk_abs

            _, top_idx = blocked_topk_abs(v_acc, k_max)
        else:
            _, top_idx = lax.top_k(mag, k_max)                # [k]
        k_dyn = jnp.round(size * (1.0 - ratio)).astype(jnp.int32)
        keep = (jnp.arange(k_max) < jnp.maximum(k_dyn, 1)).astype(v_acc.dtype)
        vals = v_acc[top_idx] * keep
        n = lax.psum(1, dgc_axis)

        def _sparse(_):
            # THE wire: 2*k*n floats instead of `size` — the honest DGC
            # saving
            all_idx = lax.all_gather(top_idx, dgc_axis)       # [n, k]
            all_vals = lax.all_gather(vals, dgc_axis)         # [n, k]
            sparse_update = (
                jnp.zeros((size,), v_acc.dtype)
                .at[all_idx.reshape(-1)]
                .add(all_vals.reshape(-1)) / n
            ).reshape(p.shape)
            sent = jnp.zeros((size,), bool).at[top_idx].set(keep > 0)
            sent = sent.reshape(p.shape)
            return (sparse_update,
                    jnp.where(sent, 0.0, u_new),
                    jnp.where(sent, 0.0, v_acc.reshape(p.shape)))

        def _dense(_):
            return lax.pmean(contrib, dgc_axis), u_new, v

        # phase select around lax.cond, not jnp.where: where() evaluates
        # BOTH sides, so the rampup pmean put a dense all-reduce on the
        # wire during the sparse phase. A schedule that is STATICALLY
        # sparse (rampup_begin <= 0 and every sparsity entry > 0 — the
        # production DGC config) prunes the dense branch entirely: the
        # compiled module carries no dense all-reduce at all; a genuinely
        # dynamic schedule keeps both branches but executes only one.
        statically_sparse = (
            float(begin) <= 0.0
            and min(float(x) for x in attrs.get("sparsity", [0.999])) > 0.0
        )
        if statically_sparse:
            update, u_out, v_out = _sparse(None)
        else:
            update, u_out, v_out = lax.cond(is_dense, _dense, _sparse, None)
        return {
            "ParamOut": [p - lr.astype(p.dtype) * update],
            "UOut": [u_out[None]],
            "VOut": [v_out[None]],
        }

    v_acc = v + contrib
    absv = jnp.abs(v_acc)
    thr = jnp.quantile(absv.reshape(-1).astype(jnp.float32), ratio)
    mask = absv >= thr.astype(absv.dtype)
    update = jnp.where(is_dense, contrib, jnp.where(mask, v_acc, 0.0))
    u_out = jnp.where(is_dense, u_new, jnp.where(mask, 0.0, u_new))
    v_out = jnp.where(is_dense, v, jnp.where(mask, 0.0, v_acc))
    return {
        "ParamOut": [p - lr.astype(p.dtype) * update],
        "UOut": [u_out],
        "VOut": [v_out],
    }
