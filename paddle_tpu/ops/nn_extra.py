"""Second tranche of dense op lowerings: activations, tensor utilities,
losses, vision ops (reference: paddle/fluid/operators/ — one *_op.cc per
row; here one jnp/lax lowering each, gradients synthesized via vjp).
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError


def _unary(name, fn):
    @register_op(name)
    def _lower(ins, attrs, _fn=fn):
        return {"Out": [_fn(first(ins, "X"), attrs)]}


# -- activations (reference: paddle/fluid/operators/activation_op.cc) ----
_unary("selu", lambda x, a: a.get("scale", 1.0507009873554805) * jnp.where(
    x > 0, x,
    # exp only sees non-positive values: the unselected branch must stay
    # finite or where's vjp produces 0*inf = NaN cotangents
    a.get("alpha", 1.6732632423543772) * (jnp.exp(jnp.minimum(x, 0.0)) - 1)))
_unary("brelu", lambda x, a: jnp.clip(
    x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
_unary("soft_relu", lambda x, a: jnp.log(
    1 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))))
_unary("stanh", lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
    a.get("scale_a", 0.67) * x))


@register_op("maxout")
def _maxout(ins, attrs):
    """reference: paddle/fluid/operators/maxout_op.cc. NCHW: channel groups
    reduced by max."""
    x = first(ins, "X")
    groups = attrs["groups"]
    n, c, h, w = x.shape
    return {"Out": [x.reshape(n, c // groups, groups, h, w).max(axis=2)]}


# -- tensor utilities ----------------------------------------------------
@register_op("argsort", nondiff_inputs=("X",))
def _argsort(ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(jnp.int64)]}




@register_op("diag")
def _diag(ins, attrs):
    return {"Out": [jnp.diag(first(ins, "Diagonal"))]}






@register_op("reverse")
def _reverse(ins, attrs):
    x = first(ins, "X")
    out = x
    for ax in attrs.get("axis", [0]):
        out = jnp.flip(out, axis=ax)
    return {"Out": [out]}






@register_op("shard_index", nondiff_inputs=("X",))
def _shard_index(ins, attrs):
    """reference: paddle/fluid/operators/shard_index_op.cc."""
    x = first(ins, "X")
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore = attrs.get("ignore_value", -1)
    size = (index_num + nshards - 1) // nshards
    in_shard = (x // size) == shard_id
    return {"Out": [jnp.where(in_shard, x % size, ignore)]}


@register_op("rank", nondiff_inputs=("Input",))
def _rank(ins, attrs):
    return {"Out": [jnp.asarray(first(ins, "Input").ndim, jnp.int32)]}


@register_op("size", nondiff_inputs=("Input",))
def _size(ins, attrs):
    return {"Out": [jnp.asarray(first(ins, "Input").size, jnp.int64)]}


@register_op("multiplex", nondiff_inputs=("Ids",))
def _multiplex(ins, attrs):
    """reference: paddle/fluid/operators/multiplex_op.cc — per-row pick one
    of the candidate tensors."""
    ids = first(ins, "Ids").astype(jnp.int32).reshape(-1)
    xs = jnp.stack(ins["X"])  # [K, B, ...]
    return {"Out": [xs[ids, jnp.arange(ids.shape[0])]]}


@register_op("crop_tensor")
def _crop_tensor(ins, attrs):
    x = first(ins, "X")
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    sl = tuple(
        slice(o, o + s) for o, s in zip(offsets, shape)
    )
    return {"Out": [x[sl]]}


# -- losses --------------------------------------------------------------
@register_op("log_loss", nondiff_inputs=("Labels",))
def _log_loss(ins, attrs):
    p = first(ins, "Predicted")
    y = first(ins, "Labels")
    eps = attrs.get("epsilon", 1e-4)
    return {"Loss": [-y * jnp.log(p + eps) - (1 - y) * jnp.log(1 - p + eps)]}


@register_op("rank_loss", nondiff_inputs=("Label",))
def _rank_loss(ins, attrs):
    """reference: paddle/fluid/operators/rank_loss_op.cc."""
    label = first(ins, "Label")
    left = first(ins, "Left")
    right = first(ins, "Right")
    d = left - right
    # softplus, not log(1+exp): exp overflows fp32 beyond d ~ 88
    return {"Out": [jax.nn.softplus(d) - label * d]}


@register_op("margin_rank_loss", nondiff_inputs=("Label",))
def _margin_rank_loss(ins, attrs):
    label = first(ins, "Label")
    x1 = first(ins, "X1")
    x2 = first(ins, "X2")
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register_op("dice_loss_op", nondiff_inputs=("Label",))
def _dice_loss(ins, attrs):
    """reference: python/paddle/fluid/layers/loss.py dice_loss — integer
    class labels [N, ..., 1] are one-hot encoded to x's class dim before
    the intersection/union."""
    x = first(ins, "X")
    label = first(ins, "Label")
    eps = attrs.get("epsilon", 1e-5)
    if jnp.issubdtype(label.dtype, jnp.integer):
        idx = label.reshape(label.shape[:-1]).astype(jnp.int32)
        label = jax.nn.one_hot(idx, x.shape[-1], dtype=x.dtype)
    else:
        label = label.astype(x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = 2 * jnp.sum(x * label, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(label, axis=reduce_dims)
    return {"Out": [jnp.mean(1.0 - (inter + eps) / (union + eps))]}


@register_op("bpr_loss", nondiff_inputs=("Label",))
def _bpr_loss(ins, attrs):
    """reference: paddle/fluid/operators/bpr_loss_op.cc."""
    x = first(ins, "X")  # [B, C] raw scores
    label = first(ins, "Label").astype(jnp.int32).reshape(-1)
    pos = jnp.take_along_axis(x, label[:, None], axis=1)
    diff = x - pos
    losses = jax.nn.softplus(diff)  # overflow-stable
    C = x.shape[1]
    mask = jnp.arange(C)[None, :] != label[:, None]
    return {"Out": [
        (losses * mask).sum(axis=1, keepdims=True) / max(C - 1, 1)
    ]}


@register_op("label_smooth", nondiff_inputs=())
def _label_smooth(ins, attrs):
    x = first(ins, "X")
    eps = attrs.get("epsilon", 0.1)
    prior = maybe(ins, "PriorDist")
    k = x.shape[-1]
    uniform = prior if prior is not None else 1.0 / k
    return {"Out": [(1 - eps) * x + eps * uniform]}


@register_op("cos_sim")
def _cos_sim(ins, attrs):
    x = first(ins, "X")
    y = first(ins, "Y")
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn + 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register_op("npair_loss", nondiff_inputs=("labels",))
def _npair_loss(ins, attrs):
    """reference: python/paddle/fluid/layers/loss.py npair_loss."""
    anchor = first(ins, "anchor")
    positive = first(ins, "positive")
    labels = first(ins, "labels").reshape(-1)
    l2_reg = attrs.get("l2_reg", 0.002)
    B = anchor.shape[0]
    sim = anchor @ positive.T  # [B, B]
    tgt = (labels[:, None] == labels[None, :]).astype(anchor.dtype)
    tgt = tgt / tgt.sum(axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -(tgt * logp).sum(axis=1).mean()
    reg = l2_reg * (jnp.mean(jnp.sum(anchor * anchor, axis=1))
                    + jnp.mean(jnp.sum(positive * positive, axis=1))) / 2
    return {"Out": [ce + reg]}


@register_op("mean_iou", nondiff_inputs=("Predictions", "Labels"))
def _mean_iou(ins, attrs):
    pred = first(ins, "Predictions").astype(jnp.int32).reshape(-1)
    label = first(ins, "Labels").astype(jnp.int32).reshape(-1)
    n = attrs["num_classes"]
    inter = jnp.zeros(n).at[pred].add(
        (pred == label).astype(jnp.float32)
    )
    pred_n = jnp.zeros(n).at[pred].add(1.0)
    label_n = jnp.zeros(n).at[label].add(1.0)
    union = pred_n + label_n - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.where(present, union, 1.0), 0.0)
    miou = iou.sum() / jnp.maximum(present.sum(), 1)
    return {"OutMeanIou": [miou], "OutWrong": [pred_n - inter],
            "OutCorrect": [inter]}


# -- vision --------------------------------------------------------------
def _interp(x, oh, ow, method, align_corners):
    """align_corners=True matches the fluid-1.7 default sampling grid
    (corner-aligned); False is jax.image.resize's half-pixel convention."""
    n, c, h, w = x.shape
    if not align_corners:
        return jax.image.resize(x, (n, c, oh, ow), method=method).astype(
            x.dtype
        )
    ys = (
        jnp.linspace(0, h - 1, oh)
        if oh > 1 else jnp.zeros((1,))
    )
    xs = (
        jnp.linspace(0, w - 1, ow)
        if ow > 1 else jnp.zeros((1,))
    )
    if method == "nearest":
        yi = jnp.round(ys).astype(jnp.int32)
        xi = jnp.round(xs).astype(jnp.int32)
        return x[:, :, yi][:, :, :, xi]
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]
    g = lambda yi, xi: x[:, :, yi][:, :, :, xi].astype(jnp.float32)
    out = (
        g(y0, x0) * (1 - wy) * (1 - wx)
        + g(y0, x1) * (1 - wy) * wx
        + g(y1, x0) * wy * (1 - wx)
        + g(y1, x1) * wy * wx
    )
    return out.astype(x.dtype)


@register_op("nearest_interp")
def _nearest_interp(ins, attrs):
    x = first(ins, "X")  # NCHW
    return {"Out": [_interp(
        x, attrs["out_h"], attrs["out_w"], "nearest",
        attrs.get("align_corners", True),
    )]}


@register_op("bilinear_interp")
def _bilinear_interp(ins, attrs):
    x = first(ins, "X")
    return {"Out": [_interp(
        x, attrs["out_h"], attrs["out_w"], "bilinear",
        attrs.get("align_corners", True),
    )]}


@register_op("pixel_shuffle")
def _pixel_shuffle(ins, attrs):
    """reference: paddle/fluid/operators/pixel_shuffle_op.cc."""
    x = first(ins, "X")
    r = attrs.get("upscale_factor", 1)
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3)
    return {"Out": [out.reshape(n, c // (r * r), h * r, w * r)]}


@register_op("space_to_depth")
def _space_to_depth(ins, attrs):
    x = first(ins, "X")
    b = attrs["blocksize"]
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // b, b, w // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": [out.reshape(n, c * b * b, h // b, w // b)]}


@register_op("shuffle_channel")
def _shuffle_channel(ins, attrs):
    x = first(ins, "X")
    g = attrs.get("group", 1)
    n, c, h, w = x.shape
    return {"Out": [
        x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4).reshape(x.shape)
    ]}


@register_op("temporal_shift")
def _temporal_shift(ins, attrs):
    """reference: paddle/fluid/operators/temporal_shift_op.cc. Input
    [N*T, C, H, W]; shifts 1/4 channels one step back/forward in time."""
    x = first(ins, "X")
    t = attrs["seg_num"]
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // t
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    xr = x.reshape(n, t, c, h, w)
    back = jnp.concatenate(
        [xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])], axis=1
    )
    fwd = jnp.concatenate(
        [jnp.zeros_like(xr[:, :1, c1:c2]), xr[:, :-1, c1:c2]], axis=1
    )
    rest = xr[:, :, c2:]
    return {"Out": [
        jnp.concatenate([back, fwd, rest], axis=2).reshape(x.shape)
    ]}


@register_op("unfold")
def _unfold(ins, attrs):
    """reference: paddle/fluid/operators/unfold_op.cc (im2col)."""
    x = first(ins, "X")  # NCHW
    ks = attrs["kernel_sizes"]
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0, 0, 0])
    dil = attrs.get("dilations", [1, 1])
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=ks, window_strides=strides,
        padding=[(pads[0], pads[2] if len(pads) > 2 else pads[0]),
                 (pads[1], pads[3] if len(pads) > 2 else pads[1])],
        rhs_dilation=dil,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, oh, ow]
    n, ckk = patches.shape[0], patches.shape[1]
    return {"Y": [patches.reshape(n, ckk, -1)]}


@register_op("add_position_encoding")
def _add_position_encoding(ins, attrs):
    """reference: paddle/fluid/operators/add_position_encoding_op.cc."""
    x = first(ins, "X")  # [B, S, D]
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    b, s, d = x.shape
    pos = jnp.arange(s, dtype=jnp.float32)[:, None]
    i = jnp.arange(d // 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, 2 * i / d)
    enc = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=1)
    return {"Out": [alpha * x + beta * enc[None].astype(x.dtype)]}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ins, attrs):
    """reference: paddle/fluid/operators/bilinear_tensor_product_op.cc."""
    x = first(ins, "X")  # [B, M]
    y = first(ins, "Y")  # [B, N]
    w = first(ins, "Weight")  # [O, M, N]
    out = jnp.einsum("bm,omn,bn->bo", x, w, y)
    bias = maybe(ins, "Bias")
    if bias is not None:
        out = out + bias
    return {"Out": [out]}


@register_op("pool3d")
def _pool3d(ins, attrs):
    x = first(ins, "X")  # NCDHW
    ks = attrs["ksize"]
    strides = attrs.get("strides", ks)
    ptype = attrs.get("pooling_type", "max")
    pads = attrs.get("paddings", [0, 0, 0])
    window = (1, 1) + tuple(ks)
    stride = (1, 1) + tuple(strides)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if attrs.get("global_pooling", False):
        window = (1, 1) + x.shape[2:]
        stride = window
        padding = ((0, 0),) * 5
    if ptype == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, stride, padding)
    else:
        out = lax.reduce_window(x, 0.0, lax.add, window, stride, padding)
        div = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                stride, padding)
        out = out / div
    return {"Out": [out]}


@register_op("conv3d")
def _conv3d(ins, attrs):
    x = first(ins, "Input")  # NCDHW
    w = first(ins, "Filter")  # OIDHW
    strides = attrs.get("strides", [1, 1, 1])
    pads = attrs.get("paddings", [0, 0, 0])
    dil = attrs.get("dilations", [1, 1, 1])
    groups = attrs.get("groups", 1)
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads],
        rhs_dilation=dil,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register_op("adaptive_pool2d")
def _adaptive_pool2d(ins, attrs):
    """Output-size-driven pooling (reference: pool_op.cc adaptive=True).
    Requires input H/W divisible by the output size (the TPU-friendly
    static-shape case)."""
    x = first(ins, "X")
    oh, ow = attrs["pooled_height"], attrs["pooled_width"]
    ptype = attrs.get("pooling_type", "avg")
    n, c, h, w = x.shape
    if h % oh or w % ow:
        raise EnforceError(
            f"adaptive_pool2d needs H({h})%out_h({oh})==0 and "
            f"W({w})%out_w({ow})==0 on TPU (static shapes)"
        )
    xr = x.reshape(n, c, oh, h // oh, ow, w // ow)
    if ptype == "max":
        return {"Out": [xr.max(axis=(3, 5))]}
    return {"Out": [xr.mean(axis=(3, 5))]}
