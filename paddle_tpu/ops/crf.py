"""Structured-prediction ops: linear-chain CRF, Viterbi decoding, CTC loss,
beam-search backtrace.

reference: paddle/fluid/operators/linear_chain_crf_op.h (scaled forward
recursion on CPU with per-sequence LoD loops), crf_decoding_op.h,
warpctc_op.cc (wraps the external warp-ctc CUDA library),
gather_tree_op.cc. TPU-native redesign: padded [B, T, ...] tensors with
explicit Length vectors; the recursions are log-space `lax.scan`s over time
(batch-vectorized, autodiff-able — CTC/CRF gradients come from XLA's vjp of
the scan instead of hand-written grad kernels), so the whole loss stays
on-device and differentiable.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe

_NEG = -1e30


def _crf_parts(ins):
    em = first(ins, "Emission").astype(jnp.float32)  # [B, T, D]
    trans = first(ins, "Transition").astype(jnp.float32)  # [D+2, D]
    start, stop, pair = trans[0], trans[1], trans[2:]
    length = maybe(ins, "Length")
    B, T, _ = em.shape
    if length is None:
        length = jnp.full((B,), T, jnp.int32)
    return em, start, stop, pair, length.reshape(-1).astype(jnp.int32)


def _crf_forward(em, start, stop, pair, length):
    """Log-partition per sequence: log-space forward recursion."""
    B, T, D = em.shape
    alpha0 = start[None, :] + em[:, 0, :]  # [B, D]

    def step(alpha, inp):
        e_t, t = inp
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + pair[None, :, :], axis=1
        ) + e_t
        keep = (t < length)[:, None]
        return jnp.where(keep, nxt, alpha), None

    ts = jnp.arange(1, T)
    alpha, _ = jax.lax.scan(
        step, alpha0, (jnp.moveaxis(em[:, 1:, :], 1, 0), ts)
    )
    return jax.scipy.special.logsumexp(alpha + stop[None, :], axis=1)  # [B]


@register_op("linear_chain_crf", nondiff_inputs=("Label", "Length"))
def _linear_chain_crf(ins, attrs):
    """reference: paddle/fluid/operators/linear_chain_crf_op.h:216 — the op
    outputs the NEGATIVE log-likelihood (logZ - gold score) per sequence."""
    em, start, stop, pair, length = _crf_parts(ins)
    label = first(ins, "Label").astype(jnp.int32)
    if label.ndim == 3:
        label = label[..., 0]
    B, T, D = em.shape
    log_z = _crf_forward(em, start, stop, pair, length)

    # gold-path score, masked past each sequence's length
    t_idx = jnp.arange(T)[None, :]
    in_len = t_idx < length[:, None]  # [B, T]
    em_score = jnp.sum(
        jnp.where(in_len, jnp.take_along_axis(em, label[..., None],
                                              axis=2)[..., 0], 0.0),
        axis=1,
    )
    pair_score = jnp.sum(
        jnp.where(
            t_idx[:, 1:] < length[:, None],
            pair[label[:, :-1], label[:, 1:]],
            0.0,
        ),
        axis=1,
    )
    last = jnp.take_along_axis(label, (length - 1)[:, None], axis=1)[:, 0]
    gold = em_score + pair_score + start[label[:, 0]] + stop[last]
    nll = log_z - gold
    return {
        "LogLikelihood": [nll[:, None]],
        "Alpha": [jnp.zeros_like(em)],  # parity slot (scaled-form internal)
        "EmissionExps": [jnp.exp(em)],
        "TransitionExps": [jnp.exp(jnp.concatenate(
            [start[None], stop[None], pair], axis=0))],
    }


@register_op("crf_decoding", nondiff_inputs=("Emission", "Transition",
                                             "Label", "Length"))
def _crf_decoding(ins, attrs):
    """reference: paddle/fluid/operators/crf_decoding_op.h — Viterbi. With a
    Label input the output flags positions where the best path DISAGREES
    (reference semantics: 1 marks a correct tag only when paths match)."""
    em, start, stop, pair, length = _crf_parts(ins)
    B, T, D = em.shape
    delta0 = start[None, :] + em[:, 0, :]

    def step(delta, inp):
        e_t, t = inp
        cand = delta[:, :, None] + pair[None, :, :]  # [B, from, to]
        best = cand.max(axis=1) + e_t
        back = cand.argmax(axis=1)
        keep = (t < length)[:, None]
        return jnp.where(keep, best, delta), jnp.where(
            keep, back, jnp.arange(D)[None, :]
        )

    ts = jnp.arange(1, T)
    delta, backs = jax.lax.scan(
        step, delta0, (jnp.moveaxis(em[:, 1:, :], 1, 0), ts)
    )  # backs: [T-1, B, D]
    final = delta + stop[None, :]
    last_tag = final.argmax(axis=1)  # [B]

    def trace(tag, back_t):
        prev = jnp.take_along_axis(back_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first_tag, tags = jax.lax.scan(
        trace, last_tag, backs, reverse=True
    )  # tags: [T-1, B] = tags for t=1..T-1
    path = jnp.concatenate(
        [first_tag[None, :], tags], axis=0
    ).T  # [B, T]
    in_len = jnp.arange(T)[None, :] < length[:, None]
    path = jnp.where(in_len, path, 0).astype(jnp.int64)
    label = maybe(ins, "Label")
    if label is not None:
        lab = label.astype(jnp.int64)
        if lab.ndim == 3:
            lab = lab[..., 0]
        return {"ViterbiPath": [
            jnp.where(in_len, (path == lab).astype(jnp.int64), 0)
        ]}
    return {"ViterbiPath": [path]}


@register_op("warpctc", nondiff_inputs=("Label", "LogitsLength", "LabelLength"))
def _warpctc(ins, attrs):
    """CTC loss (reference: paddle/fluid/operators/warpctc_op.cc wraps the
    external warp-ctc library; here the standard log-space alpha recursion
    runs as a lax.scan and the gradient is XLA's vjp through it).
    Logits [B, T, V] + LogitsLength [B]; Label [B, L] + LabelLength [B]."""
    logits = first(ins, "Logits").astype(jnp.float32)
    label = first(ins, "Label").astype(jnp.int32)
    blank = attrs.get("blank", 0)
    B, T, V = logits.shape
    L = label.shape[1]
    logit_len = maybe(ins, "LogitsLength")
    logit_len = (jnp.full((B,), T, jnp.int32) if logit_len is None
                 else logit_len.reshape(-1).astype(jnp.int32))
    label_len = maybe(ins, "LabelLength")
    label_len = (jnp.full((B,), L, jnp.int32) if label_len is None
                 else label_len.reshape(-1).astype(jnp.int32))

    logp = jax.nn.log_softmax(logits, axis=-1)
    S = 2 * L + 1
    s_idx = jnp.arange(S)
    # extended label sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.where(
        s_idx[None, :] % 2 == 0,
        blank,
        jnp.take_along_axis(
            label, jnp.broadcast_to(
                jnp.minimum(s_idx // 2, L - 1)[None, :], (B, S)
            ), axis=1,
        ),
    )
    # skip-transition allowed where ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    allow2 = (ext != blank) & (ext != ext_m2)

    def emit(t):
        return jnp.take_along_axis(logp[:, t, :], ext, axis=1)  # [B, S]

    alpha0 = jnp.full((B, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(label_len > 0,
                  jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2],
                                      axis=1)[:, 0],
                  _NEG)
    )

    def step(alpha, t):
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :S]
        a2 = jnp.where(allow2, a2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        nxt = merged + emit(t)
        keep = (t < logit_len)[:, None]
        return jnp.where(keep, nxt, alpha), None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    end1 = jnp.take_along_axis(alpha, (2 * label_len)[:, None], axis=1)[:, 0]
    end2_idx = jnp.maximum(2 * label_len - 1, 0)
    end2 = jnp.where(
        label_len > 0,
        jnp.take_along_axis(alpha, end2_idx[:, None], axis=1)[:, 0],
        _NEG,
    )
    loss = -jnp.logaddexp(end1, end2)
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(logit_len.astype(jnp.float32), 1.0)
    return {"Loss": [loss[:, None]], "WarpCTCGrad": [jnp.zeros_like(logits)]}


@register_op("gather_tree", nondiff_inputs=("Ids", "Parents"))
def _gather_tree(ins, attrs):
    """reference: paddle/fluid/operators/gather_tree_op.cc — beam-search
    backtrace over [T, B, W] ids/parents."""
    ids = first(ins, "Ids")
    parents = first(ins, "Parents").astype(jnp.int32)
    T, B, W = ids.shape
    beam0 = jnp.broadcast_to(jnp.arange(W, dtype=jnp.int32)[None, :], (B, W))

    def step(beam, inp):
        ids_t, parents_t = inp
        out_t = jnp.take_along_axis(ids_t, beam, axis=1)
        prev = jnp.take_along_axis(parents_t, beam, axis=1)
        return prev, out_t

    _, out = jax.lax.scan(step, beam0, (ids, parents), reverse=True)
    return {"Out": [out]}
