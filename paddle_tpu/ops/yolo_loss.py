"""YOLOv3 training loss (reference: paddle/fluid/operators/detection/
yolov3_loss_op.h) — completes the YOLO family next to ops/detection.py's
yolo_box.

Per scale: X [N, S*(5+K), H, W] raw predictions; GTBox [N, B, 4]
normalized (cx, cy, w, h); GTLabel [N, B] (zero-area boxes = padding).
Targets are built with a lax.scan over the (static) B ground-truth slots —
later boxes overwrite earlier ones on cell/anchor collision, matching the
reference's sequential loop. Anchors are chosen by best WH-IoU over ALL
anchors; only assignments landing in this scale's anchor_mask train.
Objectness negatives ignore predictions whose decoded box overlaps any gt
above ignore_thresh.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe


def _sce(x, t):
    """Sigmoid cross entropy (stable)."""
    return jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))


@register_op("yolov3_loss", nondiff_inputs=("GTBox", "GTLabel", "GTScore"))
def _yolov3_loss(ins, attrs):
    x = first(ins, "X").astype(jnp.float32)
    gtbox = first(ins, "GTBox").astype(jnp.float32)   # [N, B, 4]
    gtlabel = first(ins, "GTLabel").astype(jnp.int32)  # [N, B]
    gtscore = maybe(ins, "GTScore")
    anchors = [float(a) for a in attrs["anchors"]]
    mask = [int(m) for m in attrs["anchor_mask"]]
    K = attrs["class_num"]
    ignore_thresh = attrs.get("ignore_thresh", 0.7)
    downsample = attrs.get("downsample_ratio", 32)
    smooth = attrs.get("use_label_smooth", True)
    N, C, H, W = x.shape
    S = len(mask)
    A = len(anchors) // 2
    an_w = jnp.asarray(anchors[0::2], jnp.float32)
    an_h = jnp.asarray(anchors[1::2], jnp.float32)
    input_size = downsample * H
    p = x.reshape(N, S, 5 + K, H, W)
    tx, ty = p[:, :, 0], p[:, :, 1]
    tw, th = p[:, :, 2], p[:, :, 3]
    tobj = p[:, :, 4]
    tcls = p[:, :, 5:]                                 # [N, S, K, H, W]
    gs = (
        gtscore.astype(jnp.float32)
        if gtscore is not None
        else jnp.ones(gtlabel.shape, jnp.float32)
    )
    B = gtbox.shape[1]
    valid = (gtbox[:, :, 2] > 0) & (gtbox[:, :, 3] > 0)  # [N, B]

    # best anchor per gt by WH IoU over ALL anchors
    gw = gtbox[:, :, 2] * input_size                   # pixels
    gh = gtbox[:, :, 3] * input_size
    inter = jnp.minimum(gw[:, :, None], an_w) * jnp.minimum(
        gh[:, :, None], an_h
    )
    union = gw[:, :, None] * gh[:, :, None] + an_w * an_h - inter
    wh_iou = inter / jnp.maximum(union, 1e-10)         # [N, B, A]
    best_a = jnp.argmax(wh_iou, axis=2)                # [N, B]
    mask_arr = jnp.asarray(mask, jnp.int32)
    in_scale = (best_a[:, :, None] == mask_arr[None, None, :])
    scale_slot = jnp.argmax(in_scale, axis=2)          # [N, B] index into S
    assigned = in_scale.any(axis=2) & valid            # [N, B]

    gi = jnp.clip((gtbox[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtbox[:, :, 1] * H).astype(jnp.int32), 0, H - 1)
    t_x = gtbox[:, :, 0] * W - gi                      # in (0,1)
    t_y = gtbox[:, :, 1] * H - gj
    t_w = jnp.log(jnp.maximum(gw, 1e-8) / jnp.maximum(an_w[best_a], 1e-8))
    t_h = jnp.log(jnp.maximum(gh, 1e-8) / jnp.maximum(an_h[best_a], 1e-8))
    # reference scales box loss by (2 - w*h) * score (mixup weight)
    box_scale = (2.0 - gtbox[:, :, 2] * gtbox[:, :, 3]) * gs

    # scatter targets box-by-box (later gt wins collisions, like the
    # reference's loop)
    def build(n_idx):
        def body(carry, b):
            t_map, obj_map, cls_map, sc_map = carry
            s = scale_slot[n_idx, b]
            i = gi[n_idx, b]
            j = gj[n_idx, b]
            on = assigned[n_idx, b]

            t_map = jnp.where(
                on,
                t_map.at[:, s, j, i].set(jnp.stack([
                    t_x[n_idx, b], t_y[n_idx, b],
                    t_w[n_idx, b], t_h[n_idx, b],
                    box_scale[n_idx, b],
                ])),
                t_map,
            )
            obj_map = jnp.where(
                on, obj_map.at[s, j, i].set(gs[n_idx, b]), obj_map
            )
            cls_map = jnp.where(
                on,
                cls_map.at[:, s, j, i].set(
                    jax.nn.one_hot(gtlabel[n_idx, b], K)
                ),
                cls_map,
            )
            sc_map = jnp.where(on, sc_map.at[s, j, i].set(1.0), sc_map)
            return (t_map, obj_map, cls_map, sc_map), None

        t0 = jnp.zeros((5, S, H, W), jnp.float32)
        o0 = jnp.zeros((S, H, W), jnp.float32)
        c0 = jnp.zeros((K, S, H, W), jnp.float32)
        s0 = jnp.zeros((S, H, W), jnp.float32)
        (t_map, obj_map, cls_map, pos_map), _ = jax.lax.scan(
            body, (t0, o0, c0, s0), jnp.arange(B)
        )
        return t_map, obj_map, cls_map, pos_map

    t_map, obj_map, cls_map, pos_map = jax.vmap(build)(jnp.arange(N))
    # t_map [N, 5, S, H, W]; pos_map [N, S, H, W] 1 where a gt landed

    # objectness ignore mask: decoded pred box IoU vs ANY gt > thresh
    grid_x = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    an_w_s = an_w[mask_arr].reshape(1, S, 1, 1)
    an_h_s = an_h[mask_arr].reshape(1, S, 1, 1)
    px = (jax.nn.sigmoid(tx) + grid_x) / W             # [N, S, H, W]
    py = (jax.nn.sigmoid(ty) + grid_y) / H
    pw = jnp.exp(jnp.minimum(tw, 10.0)) * an_w_s / input_size
    ph = jnp.exp(jnp.minimum(th, 10.0)) * an_h_s / input_size

    def box_iou(px, py, pw, ph, g):
        # g [B, 4] centers; preds [...]
        px1, px2 = px - pw / 2, px + pw / 2
        py1, py2 = py - ph / 2, py + ph / 2
        gx1 = (g[:, 0] - g[:, 2] / 2)
        gx2 = (g[:, 0] + g[:, 2] / 2)
        gy1 = (g[:, 1] - g[:, 3] / 2)
        gy2 = (g[:, 1] + g[:, 3] / 2)
        iw = jnp.maximum(
            jnp.minimum(px2[..., None], gx2) - jnp.maximum(px1[..., None], gx1),
            0.0,
        )
        ih = jnp.maximum(
            jnp.minimum(py2[..., None], gy2) - jnp.maximum(py1[..., None], gy1),
            0.0,
        )
        inter = iw * ih
        union = (pw * ph)[..., None] + (g[:, 2] * g[:, 3]) - inter
        return inter / jnp.maximum(union, 1e-10)       # [..., B]

    ious = jax.vmap(
        lambda a, b, c, d, g, v: jnp.where(v, box_iou(a, b, c, d, g), 0.0)
    )(px, py, pw, ph, gtbox, valid)                    # [N, S, H, W, B]
    ignore = (ious.max(axis=-1) > ignore_thresh) & (pos_map == 0)

    # losses. obj_map carries the mixup score at positive cells (the
    # reference's objness value); it weights the positive objectness and
    # class terms.
    tgt_x, tgt_y = t_map[:, 0], t_map[:, 1]
    tgt_w, tgt_h = t_map[:, 2], t_map[:, 3]
    bscale = t_map[:, 4]
    pos = pos_map
    loss_xy = (
        (_sce(tx, tgt_x) + _sce(ty, tgt_y)) * bscale * pos
    ).sum(axis=(1, 2, 3))
    loss_wh = (
        (jnp.abs(tw - tgt_w) + jnp.abs(th - tgt_h)) * bscale * pos
    ).sum(axis=(1, 2, 3))
    # positive term: SCE vs 1.0 weighted by the score (reference :196)
    loss_obj = (
        _sce(tobj, jnp.ones_like(tobj)) * obj_map * pos
        + _sce(tobj, jnp.zeros_like(tobj)) * (1.0 - pos) * (1.0 - ignore)
    ).sum(axis=(1, 2, 3))
    # cls_map [N, K, S, H, W] -> align with tcls [N, S, K, H, W]
    cls_tgt = jnp.transpose(cls_map, (0, 2, 1, 3, 4))
    if smooth:
        # reference smooth_weight = min(1/K, 1/40): pos = 1-sw, neg = sw
        sw = min(1.0 / K, 1.0 / 40.0)
        cls_tgt = cls_tgt * (1.0 - 2.0 * sw) + sw
    loss_cls = (
        _sce(tcls, cls_tgt) * (obj_map * pos)[:, :, None]
    ).sum(axis=(1, 2, 3, 4))
    loss = loss_xy + loss_wh + loss_obj + loss_cls
    # reference ObjectnessMask: score at positives, 0 negatives, -1 ignored
    objness = jnp.where(
        pos > 0, obj_map,
        jnp.where(ignore, -1.0, 0.0),
    )
    # reference GTMatchMask: matched anchor-mask SLOT (0..S-1), -1 else
    match_mask = jnp.where(assigned, scale_slot, -1).astype(jnp.int32)
    return {
        "Loss": [loss],
        "ObjectnessMask": [objness],
        "GTMatchMask": [match_mask],
    }
