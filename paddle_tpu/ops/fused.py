"""Fused CPU-op parity family: compositions the reference hand-fused for
CPU inference (reference: paddle/fluid/operators/fused/{fusion_lstm_op.cc,
fusion_gru_op.cc, fused_embedding_seq_pool_op.cc,
fusion_seqconv_eltadd_relu_op.cc, fusion_repeated_fc_relu_op.cc,
fusion_squared_mat_sub_op.cc, fusion_seqpool_concat_op.cc,
fusion_seqpool_cvm_concat_op.cc}).

On TPU these are compositions of existing lowerings — XLA fuses the
arithmetic; registering the op names keeps reference programs loadable.
Padded+lengths tensor contract as ops/sequence.py.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError


@register_op("fusion_lstm", nondiff_inputs=("Length",))
def _fusion_lstm(ins, attrs):
    """reference: fused/fusion_lstm_op.cc — LSTM with the x-projection
    folded in. X [B, S, M], WeightX [M, 4D], WeightH [D, 4D], Bias [1, 4D]
    (peepholes unsupported -> loud error). Gate order i, f, c, o
    (reference computeCtHt order ct = f*c + i*tanh(c_in))."""
    if attrs.get("use_peepholes", False):
        raise EnforceError("fusion_lstm: peephole connections unsupported")
    x = first(ins, "X")
    wx = first(ins, "WeightX")
    gx = jnp.einsum("bsm,mg->bsg", x, wx)
    return _lstm_recurrence(gx, ins)


def _lstm_recurrence(gx, ins):
    """Shared LSTM scan over PRE-PROJECTED gates gx [B, S, 4D] (used by
    fusion_lstm and fused_embedding_fc_lstm, whose embedding rows already
    ARE the projected input)."""
    wh = first(ins, "WeightH")
    b = maybe(ins, "Bias")
    lengths = maybe(ins, "Length")
    B, S = gx.shape[0], gx.shape[1]
    D = wh.shape[0]
    h0 = maybe(ins, "H0")
    c0 = maybe(ins, "C0")
    h = h0 if h0 is not None else jnp.zeros((B, D), gx.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, D), gx.dtype)
    if b is not None:
        gx = gx + b.reshape(1, 1, -1)

    def step(carry, inp):
        h, c = carry
        g_x, t = inp
        gates = g_x + h @ wh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(g)
        h_new = o * jnp.tanh(c_new)
        if lengths is not None:
            alive = (t < lengths.reshape(-1, 1))
            h_new = jnp.where(alive, h_new, h)
            c_new = jnp.where(alive, c_new, c)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(
        step, (h, c),
        (jnp.swapaxes(gx, 0, 1), jnp.arange(S)),
    )
    return {
        "Hidden": [jnp.swapaxes(hs, 0, 1)],
        "Cell": [jnp.swapaxes(cs, 0, 1)],
    }


@register_op("fusion_gru", nondiff_inputs=("Length",))
def _fusion_gru(ins, attrs):
    """reference: fused/fusion_gru_op.cc — GRU with folded x-projection,
    Paddle gate order (update u | reset r | candidate c),
    h = u*h_prev + (1-u)*c (origin_mode=False default matches gru_unit)."""
    x = first(ins, "X")
    wx = first(ins, "WeightX")   # [M, 3D]
    wh = first(ins, "WeightH")   # [D, 3D]
    b = maybe(ins, "Bias")
    lengths = maybe(ins, "Length")
    B, S, M = x.shape
    D = wh.shape[0]
    h0 = maybe(ins, "H0")
    h = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    origin = attrs.get("origin_mode", False)
    gx = jnp.einsum("bsm,mg->bsg", x, wx)
    if b is not None:
        gx = gx + b.reshape(1, 1, -1)

    def step(h, inp):
        g_x, t = inp
        gates = g_x[:, : 2 * D] + h @ wh[:, : 2 * D]
        u = jax.nn.sigmoid(gates[:, :D])
        r = jax.nn.sigmoid(gates[:, D:])
        c = jnp.tanh(g_x[:, 2 * D:] + (r * h) @ wh[:, 2 * D:])
        if origin:
            h_new = (1.0 - u) * h + u * c
        else:
            h_new = u * h + (1.0 - u) * c
        if lengths is not None:
            h_new = jnp.where(t < lengths.reshape(-1, 1), h_new, h)
        return h_new, h_new

    _, hs = jax.lax.scan(
        step, h, (jnp.swapaxes(gx, 0, 1), jnp.arange(S))
    )
    return {"Hidden": [jnp.swapaxes(hs, 0, 1)]}


@register_op("fused_embedding_seq_pool", nondiff_inputs=("Ids", "Length"))
def _fused_embedding_seq_pool(ins, attrs):
    """reference: fused/fused_embedding_seq_pool_op.cc — lookup + sum-pool
    over the sequence axis. Ids [B, S] (+Length), W [V, D] -> [B, D]."""
    w = first(ins, "W")
    ids = first(ins, "Ids")
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    lengths = maybe(ins, "Length")
    emb = jnp.take(w, ids, axis=0)  # [B, S, D]
    pad = attrs.get("padding_idx", -1)
    mask = jnp.ones(ids.shape, bool)
    if pad is not None and pad >= 0:
        mask = mask & (ids != pad)
    if lengths is not None:
        mask = mask & (
            jnp.arange(ids.shape[1])[None, :] < lengths.reshape(-1, 1)
        )
    return {"Out": [jnp.where(mask[..., None], emb, 0.0).sum(axis=1)]}


@register_op("fusion_seqconv_eltadd_relu", nondiff_inputs=("Length",))
def _fusion_seqconv_eltadd_relu(ins, attrs):
    """reference: fused/fusion_seqconv_eltadd_relu_op.cc — sequence_conv +
    bias + relu."""
    from paddle_tpu.core.registry import get_op_def

    conv = get_op_def("sequence_conv").lower(
        {k: v for k, v in ins.items() if k in ("X", "Filter", "Length")},
        {"contextLength": attrs.get("contextLength", 3),
         "contextStart": attrs.get("contextStart", -1),
         "contextStride": attrs.get("contextStride", 1)},
    )["Out"][0]
    b = first(ins, "Bias")
    return {"Out": [jax.nn.relu(conv + b.reshape(1, 1, -1))]}


@register_op("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu(ins, attrs):
    """reference: fused/fusion_repeated_fc_relu_op.cc — N x (fc + relu)."""
    x = first(ins, "X")
    ws = ins["W"]
    bs = ins["Bias"]
    for w, b in zip(ws, bs):
        x = jax.nn.relu(x @ w + b.reshape(1, -1))
    return {"Out": [x]}


@register_op("fusion_squared_mat_sub")
def _fusion_squared_mat_sub(ins, attrs):
    """reference: fused/fusion_squared_mat_sub_op.cc —
    scalar * ((x@y)^2 - (x^2)@(y^2)) (the pairwise-interaction trick)."""
    x = first(ins, "X")
    y = first(ins, "Y")
    s = attrs.get("scalar", 1.0)
    return {"Out": [s * (jnp.square(x @ y) - jnp.square(x) @ jnp.square(y))]}


@register_op("fusion_seqpool_concat", nondiff_inputs=("Length",))
def _fusion_seqpool_concat(ins, attrs):
    """reference: fused/fusion_seqpool_concat_op.cc — sum/avg/sqrt pool of
    each input sequence, concatenated on features."""
    pools = _pool_all(ins, attrs)
    return {"Out": [jnp.concatenate(pools, axis=1)]}


@register_op("fusion_seqpool_cvm_concat", nondiff_inputs=("CVM", "Length"))
def _fusion_seqpool_cvm_concat(ins, attrs):
    """reference: fused/fusion_seqpool_cvm_concat_op.cc — seqpool + CVM
    log transform + concat (the CTR tower input builder)."""
    from paddle_tpu.core.registry import get_op_def

    pools = _pool_all(ins, attrs)
    cvm = ins.get("CVM")
    outs = []
    for p in pools:
        if attrs.get("use_cvm", True) and cvm is not None:
            p = get_op_def("cvm").lower(
                {"X": [p], "CVM": cvm}, {"use_cvm": True}
            )["Y"][0]
        outs.append(p)
    return {"Out": [jnp.concatenate(outs, axis=1)]}


def _pool_all(ins, attrs):
    ptype = attrs.get("pooltype", "SUM").upper()
    lengths = ins.get("Length")
    pools = []
    for i, x in enumerate(ins["X"]):
        l = lengths[i] if lengths and i < len(lengths) else None
        mask = (
            jnp.arange(x.shape[1])[None, :] < l.reshape(-1, 1)
            if l is not None else jnp.ones(x.shape[:2], bool)
        )
        m = mask[..., None]
        s = jnp.where(m, x, 0.0).sum(axis=1)
        if ptype == "SUM":
            pools.append(s)
        else:
            n = jnp.maximum(mask.sum(axis=1, keepdims=True).astype(x.dtype),
                            1.0)
            pools.append(s / (jnp.sqrt(n) if ptype == "SQRT" else n))
    return pools


@register_op("attention_lstm", nondiff_inputs=("Length",))
def _attention_lstm(ins, attrs):
    """reference: paddle/fluid/operators/attention_lstm_op.cc — per step:
    score[j] = relu(atted_x[j] + <c_prev, w_c>) (optionally scaled +
    re-biased + relu'd), softmax over the sequence, context = sum_j a_j
    x_j, then one LSTM step on the context. Padded form: X [B, S, M] +
    Length; AttentionWeight [(M+D), 1]; LSTMWeight [(D+M), 4D] (rows
    [0:D] hidden, [D:] input; gate order forget|input|output|tilde)."""
    x = first(ins, "X")
    aw = first(ins, "AttentionWeight")            # [(M+D), 1]
    ab = maybe(ins, "AttentionBias")
    ascalar = maybe(ins, "AttentionScalar")
    asb = maybe(ins, "AttentionScalarBias")
    lw = first(ins, "LSTMWeight")                 # [(D+M), 4D]
    lb = first(ins, "LSTMBias")                   # [1, 4D]
    c0 = first(ins, "C0")                         # [B, D]
    h0 = maybe(ins, "H0")
    lengths = maybe(ins, "Length")
    B, S, M = x.shape
    D = c0.shape[1]
    w_x = aw[:M, 0]                               # [M]
    w_c = aw[M:, 0]                               # [D]
    atted = jnp.einsum("bsm,m->bs", x, w_x)
    if ab is not None:
        atted = atted + ab.reshape(())
    wh = lw[:D]                                   # [D, 4D]
    wx = lw[D:]                                   # [M, 4D]
    h_prev = h0 if h0 is not None else jnp.zeros((B, D), x.dtype)
    valid = (
        jnp.arange(S)[None, :] < lengths.reshape(-1, 1)
        if lengths is not None else jnp.ones((B, S), bool)
    )

    def step(carry, t):
        h, c = carry
        score = jax.nn.relu(atted + (c @ w_c)[:, None])     # [B, S]
        if ascalar is not None:
            score = score * ascalar.reshape(())
            if asb is not None:
                score = jax.nn.relu(score + asb.reshape(()))
        score = jnp.where(valid, score, -1e30)
        a = jax.nn.softmax(score, axis=1)
        ctxv = jnp.einsum("bs,bsm->bm", a, x)               # [B, M]
        gates = ctxv @ wx + h @ wh + lb.reshape(1, -1)
        f = jax.nn.sigmoid(gates[:, :D])
        i = jax.nn.sigmoid(gates[:, D:2 * D])
        o = jax.nn.sigmoid(gates[:, 2 * D:3 * D])
        g = jnp.tanh(gates[:, 3 * D:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        alive = (t < lengths.reshape(-1, 1)) if lengths is not None else \
            jnp.ones((B, 1), bool)
        h_new = jnp.where(alive, h_new, h)
        c_new = jnp.where(alive, c_new, c)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(step, (h_prev, c0), jnp.arange(S))
    return {
        "Hidden": [jnp.swapaxes(hs, 0, 1)],
        "Cell": [jnp.swapaxes(cs, 0, 1)],
    }


@register_op("tree_conv", nondiff_inputs=("EdgeSet",))
def _tree_conv(ins, attrs):
    """reference: paddle/fluid/operators/tree_conv_op.h + math/tree2col.h —
    TBCNN continuous-binary-tree convolution. Patch of node n = n plus its
    direct children (the max_depth=2 window; deeper windows raise — the
    dominant TBCNN config). Mixing weights per patch member v:
    eta_t = (d - depth)/d, eta_l = (1-eta_t) * (idx-1)/(pclen-1) (0.5 when
    an only child), eta_r = (1-eta_t)(1-...). NodesVector [B, N, F],
    EdgeSet [B, E, 2] (parent, child; negative = padding),
    Filter [F, 3, O, K] -> Out [B, N, O*K]."""
    nodes = first(ins, "NodesVector")
    edges = first(ins, "EdgeSet").astype(jnp.int32)
    w = first(ins, "Filter")                      # [F, 3, O, K]
    max_depth = attrs.get("max_depth", 2)
    if max_depth != 2:
        raise EnforceError(
            f"tree_conv: only max_depth=2 (node + direct children) is "
            f"implemented; got {max_depth}"
        )
    B, N, F = nodes.shape
    E = edges.shape[1]
    O, K = w.shape[2], w.shape[3]
    wt, wl, wr = w[:, 0], w[:, 1], w[:, 2]        # [F, O, K]
    d = float(max_depth)

    def per_tree(x, es):
        parent = es[:, 0]
        child = es[:, 1]
        ev = (parent >= 0) & (child >= 0)
        # sibling stats per edge: count + 1-based order among same parent
        same = (parent[:, None] == parent[None, :]) & ev[:, None] & ev[None, :]
        pclen = same.sum(axis=1)
        order = jnp.tril(same).sum(axis=1)        # rank by edge position
        eta_t = (d - 1.0) / d
        frac = jnp.where(pclen == 1, 0.5,
                         (order - 1.0) / jnp.maximum(pclen - 1.0, 1.0))
        eta_l = (1.0 - eta_t) * frac
        eta_r = (1.0 - eta_t) * (1.0 - frac)
        # root term: depth 0 -> eta_t = 1
        out = jnp.einsum("nf,fok->nok", x, wt)
        # child contributions scattered to their parent
        xc = x[jnp.clip(child, 0, N - 1)]          # [E, F]
        contrib = (
            eta_t * jnp.einsum("ef,fok->eok", xc, wt).reshape(E, -1)
            + eta_l[:, None] * jnp.einsum("ef,fok->eok", xc, wl).reshape(E, -1)
            + eta_r[:, None] * jnp.einsum("ef,fok->eok", xc, wr).reshape(E, -1)
        )                                          # [E, O*K]
        contrib = jnp.where(ev[:, None], contrib, 0.0)
        out = out.reshape(N, -1).at[jnp.clip(parent, 0, N - 1)].add(contrib)
        return out

    out = jax.vmap(per_tree)(nodes, edges)   # [B, N, O*K]
    return {"Out": [out]}


@register_op("multihead_matmul", nondiff_inputs=("BiasQK",))
def _multihead_matmul(ins, attrs):
    """reference: fused/multihead_matmul_op.cc (inference fusion) — Input
    [B, S, 3*H*D] packed q|k|v projections (+ Bias [3*H*D]), BiasQK
    [B, H, S, S] additive attention bias. Without BiasQK the attention
    runs on the Pallas flash kernel; the full [B, H, S, S] bias form (no
    flash support for that shape) uses the XLA-fused jnp path."""
    x = first(ins, "Input")
    bias = maybe(ins, "Bias")
    bias_qk = maybe(ins, "BiasQK")
    H = attrs.get("head_number", 1)
    B, S, C3 = x.shape
    D = C3 // 3 // H
    if bias is not None:
        x = x + bias.reshape(1, 1, -1)
    qkv = x.reshape(B, S, 3, H, D)
    q = jnp.transpose(qkv[:, :, 0], (0, 2, 1, 3))      # [B, H, S, D]
    k = jnp.transpose(qkv[:, :, 1], (0, 2, 1, 3))
    v = jnp.transpose(qkv[:, :, 2], (0, 2, 1, 3))
    scale = attrs.get("alpha", 1.0)
    if bias_qk is None:
        from paddle_tpu.ops.pallas.flash_attention import flash_attention

        out = flash_attention(q, k, v, sm_scale=scale)
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        s = s + bias_qk
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return {"Out": [jnp.transpose(out, (0, 2, 1, 3)).reshape(B, S, H * D)]}


@register_op("fused_embedding_eltwise_layernorm", nondiff_inputs=("Ids",))
def _fused_embedding_eltwise_layernorm(ins, attrs):
    """reference: fused/fused_embedding_eltwise_layernorm_op.cc — sum of N
    embedding lookups + layer_norm (the BERT input encoder fusion)."""
    ids_list = ins["Ids"]
    emb_list = ins["Embs"]
    scale = first(ins, "Scale")
    bias = first(ins, "Bias")
    eps = attrs.get("epsilon", 1e-5)
    total = None
    for ids, w in zip(ids_list, emb_list):
        idv = ids
        if idv.ndim == 3 and idv.shape[-1] == 1:
            idv = idv[..., 0]
        e = jnp.take(w, idv, axis=0)
        total = e if total is None else total + e
    mu = total.mean(axis=-1, keepdims=True)
    var = jnp.var(total, axis=-1, keepdims=True)
    out = (total - mu) / jnp.sqrt(var + eps) * scale + bias
    return {"Out": [out]}


@register_op("fused_embedding_fc_lstm", nondiff_inputs=("Ids", "Length"))
def _fused_embedding_fc_lstm(ins, attrs):
    """reference: fused/fused_embedding_fc_lstm_op.cc — embedding lookup +
    fused LSTM: the embedding rows already ARE the projected gates, so the
    lookup feeds the shared recurrence directly (no x-projection)."""
    emb = first(ins, "Embeddings")                 # [V, 4D]
    ids = first(ins, "Ids")
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    if attrs.get("use_peepholes", False):
        raise EnforceError(
            "fused_embedding_fc_lstm: peephole connections unsupported"
        )
    gx = jnp.take(emb, ids, axis=0)                # [B, S, 4D]
    return _lstm_recurrence(gx, ins)


@register_op("fusion_seqexpand_concat_fc", nondiff_inputs=("Length",))
def _fusion_seqexpand_concat_fc(ins, attrs):
    """reference: fused/fusion_seqexpand_concat_fc_op.cc — X[0] is a
    sequence [B, S, M0], the rest are per-row vectors [B, Mi] broadcast
    over S; concat on features, then fc + activation."""
    xs = ins["X"]
    w = first(ins, "FCWeight")
    b = maybe(ins, "FCBias")
    seq = xs[0]
    B, S = seq.shape[0], seq.shape[1]
    parts = [seq]
    for t in xs[1:]:
        parts.append(jnp.broadcast_to(
            t[:, None, :], (B, S) + tuple(t.shape[1:])
        ))
    cat = jnp.concatenate(parts, axis=-1)
    out = jnp.einsum("bsm,mo->bso", cat, w)
    if b is not None:
        out = out + b.reshape(1, 1, -1)
    act = attrs.get("fc_activation", "identity")
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    return {"Out": [out]}


_FC_ACTS = {
    "": lambda x: x,
    "identity": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    # exact (erf) form — matches the standalone gelu op's default
    # approximate=False (fc_fuse refuses to fold an approximate gelu)
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
}


@register_op("fc")
def _fc(ins, attrs):
    """reference: paddle/fluid/operators/fc_op.cc — the target of the
    fc_fuse pass (mul + elementwise_add [+ act] collapsed at export,
    reference: paddle/fluid/framework/ir/fc_fuse_pass.cc:1)."""
    import math as _math

    x, w = first(ins, "Input"), first(ins, "W")
    b = maybe(ins, "Bias")
    k = attrs.get("in_num_col_dims", 1)
    x2 = x.reshape((_math.prod(x.shape[:k]), -1))
    out = x2 @ w
    if b is not None:
        out = out + b.reshape(1, -1)
    act = attrs.get("activation_type", "") or ""
    if act not in _FC_ACTS:
        raise EnforceError(f"fc: unsupported activation_type {act!r}")
    out = _FC_ACTS[act](out)
    return {"Out": [out.reshape(tuple(x.shape[:k]) + (w.shape[1],))]}
