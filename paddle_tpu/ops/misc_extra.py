"""Third-tranche dense ops: named VERDICT misses (edit_distance,
sample_logits, fsp, teacher_student loss, proximal updates) plus long-tail
math/sequence/metric ops.

reference: paddle/fluid/operators/{edit_distance_op.h, sample_logits_op.h,
fsp_op.h, teacher_student_sigmoid_loss_op.cc, optimizers/proximal_gd_op.h,
optimizers/proximal_adagrad_op.h, cross_entropy_op.h (CrossEntropyOpKernel2),
hash_op.h, minus_op.cc, fill_op.cc, fill_any_like_op.cc, reduce_ops/,
squeeze_op.cc, flatten_op.cc, sampling_id_op.h, chunk_eval_op.h,
positive_negative_pair_op.h, match_matrix_tensor_op.cc,
gaussian_random_batch_size_like_op.cc, pool_with_index_op.cc (3d),
gru_unit_op.h, lstm_unit_op.h, shrink_rnn_memory_op.cc, crop_op.cc}.
Each is re-expressed as vectorized jnp/lax on padded+lengths tensors
(LoD-free, SURVEY §2.2 design rule); scans replace per-sequence CPU loops.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError


# ---------------------------------------------------------------------------
# trivial math / shape
# ---------------------------------------------------------------------------


@register_op("minus")
def _minus(ins, attrs):
    """reference: paddle/fluid/operators/minus_op.cc — Out = X - Y."""
    return {"Out": [first(ins, "X") - first(ins, "Y")]}


@register_op("fill")
def _fill(ins, attrs):
    """reference: paddle/fluid/operators/fill_op.cc — fill Out with the
    attr-carried flat value list."""
    from paddle_tpu.ops.common import np_dtype

    shape = tuple(attrs["shape"])
    vals = jnp.asarray(np.asarray(attrs["value"], np_dtype(attrs)))
    return {"Out": [vals.reshape(shape)]}


@register_op("fill_any_like")
def _fill_any_like(ins, attrs):
    """reference: paddle/fluid/operators/fill_any_like_op.cc."""
    x = first(ins, "X")
    return {"Out": [jnp.full_like(x, attrs.get("value", 0.0))]}


@register_op("reduce_all", nondiff_inputs=("X",))
def _reduce_all(ins, attrs):
    """reference: paddle/fluid/operators/reduce_ops/reduce_all_op.cc."""
    return {"Out": [_bool_reduce(ins, attrs, jnp.all)]}


@register_op("reduce_any", nondiff_inputs=("X",))
def _reduce_any(ins, attrs):
    """reference: paddle/fluid/operators/reduce_ops/reduce_any_op.cc."""
    return {"Out": [_bool_reduce(ins, attrs, jnp.any)]}


def _bool_reduce(ins, attrs, fn):
    from paddle_tpu.ops.common import reduce_axes

    x = first(ins, "X").astype(bool)
    if attrs.get("reduce_all", False):
        return fn(x)
    dims = reduce_axes(attrs, x.ndim)
    return fn(x, axis=dims, keepdims=attrs.get("keep_dim", False))


@register_op("squeeze")
def _squeeze(ins, attrs):
    """reference: paddle/fluid/operators/squeeze_op.cc (v1: no XShape)."""
    x = first(ins, "X")
    axes = [a % x.ndim for a in attrs.get("axes", [])]
    if not axes:
        axes = [i for i, d in enumerate(x.shape) if d == 1]
    shape = [d for i, d in enumerate(x.shape) if i not in axes or d != 1]
    return {"Out": [x.reshape(shape)]}


@register_op("flatten")
def _flatten_v1(ins, attrs):
    """reference: paddle/fluid/operators/flatten_op.cc (v1: no XShape)."""
    x = first(ins, "X")
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    return {"Out": [x.reshape(lead, -1)]}


@register_op("crop", nondiff_inputs=("Offsets", "Y"))
def _crop(ins, attrs):
    """reference: paddle/fluid/operators/crop_op.cc — static offsets/shape
    (the dynamic Offsets input must be constant-foldable under jit)."""
    x = first(ins, "X")
    y = maybe(ins, "Y")
    shape = [int(d) for d in (
        list(y.shape) if y is not None else attrs["shape"]
    )]
    offs = maybe(ins, "Offsets")
    offsets = (
        [int(v) for v in np.asarray(offs)] if offs is not None
        else list(attrs.get("offsets", [0] * x.ndim))
    )
    slices = tuple(
        slice(o, o + s) for o, s in zip(offsets, shape)
    )
    return {"Out": [x[slices]]}


@register_op("gaussian_random_batch_size_like", stateful=True,
             nondiff_inputs=("Input",))
def _gaussian_random_bsl(ins, attrs):
    """reference: paddle/fluid/operators/gaussian_random_batch_size_like_op.cc."""
    from paddle_tpu.ops.common import seeded_rng_key

    ref = first(ins, "Input")
    shape = list(attrs["shape"])
    idx_in = attrs.get("input_dim_idx", 0)
    idx_out = attrs.get("output_dim_idx", 0)
    from paddle_tpu.ops.common import np_dtype

    shape[idx_out] = ref.shape[idx_in]
    key = seeded_rng_key(ins, attrs)
    dt = jnp.dtype(np_dtype(attrs))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        key, tuple(shape), jnp.float32
    )
    return {"Out": [out.astype(dt)]}


# ---------------------------------------------------------------------------
# losses / logits
# ---------------------------------------------------------------------------


@register_op("cross_entropy2", nondiff_inputs=("Label",))
def _cross_entropy2(ins, attrs):
    """reference: paddle/fluid/operators/cross_entropy_op.h
    CrossEntropyOpKernel2 — hard-label CE over pre-softmax'd probs;
    MatchX saves the matched probability for the grad."""
    x = first(ins, "X")
    label = first(ins, "Label")
    ignore = attrs.get("ignore_index", -100)
    lab = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
    lab_i = lab.astype(jnp.int32)
    match = jnp.take_along_axis(
        x, jnp.clip(lab_i, 0, x.shape[-1] - 1)[..., None], axis=-1
    )
    valid = (lab_i != ignore)[..., None]
    y = jnp.where(valid, -jnp.log(jnp.maximum(match, 1e-20)), 0.0)
    return {"Y": [y], "MatchX": [jnp.where(valid, match, 1.0)]}


@register_op("teacher_student_sigmoid_loss", nondiff_inputs=("Label",))
def _teacher_student_loss(ins, attrs):
    """reference: paddle/fluid/operators/teacher_student_sigmoid_loss_op.cc —
    label encodes (click z, teacher z'): -2 -> z=0 no teacher; -1 -> z=1 no
    teacher; [0,1) -> z=0, z'=label; [1,2] -> z=1, z'=label-1. Loss is the
    sigmoid CE vs z plus (when present) the sigmoid CE vs z'."""
    x = first(ins, "X").reshape(-1)
    label = first(ins, "Label").reshape(-1).astype(jnp.float32)

    def ce(z):
        return jnp.maximum(x, 0.0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))

    z = jnp.where(label < -1.5, 0.0,
                  jnp.where(label < -0.5, 1.0,
                            jnp.where(label < 1.0, 0.0, 1.0)))
    has_teacher = label >= -0.5
    zp = jnp.where(label < 1.0, label, label - 1.0)
    loss = ce(z) + jnp.where(has_teacher & (label >= 0.0), ce(zp), 0.0)
    return {"Y": [loss.reshape(-1, 1)]}


@register_op("fsp")
def _fsp(ins, attrs):
    """reference: paddle/fluid/operators/fsp_op.h — flow-of-solution-
    procedure matrix for distillation: [N, Cx, H, W] x [N, Cy, H, W] ->
    [N, Cx, Cy] = X_flat @ Y_flat^T / (H*W)."""
    x = first(ins, "X")
    y = first(ins, "Y")
    n, cx, h, w = x.shape
    cy = y.shape[1]
    xf = x.reshape(n, cx, h * w)
    yf = y.reshape(n, cy, h * w)
    out = jnp.einsum("nck,ndk->ncd", xf, yf) / float(h * w)
    return {"Out": [out]}


@register_op("sample_logits", stateful=True,
             nondiff_inputs=("Labels", "CustomizedSamples",
                             "CustomizedProbabilities"))
def _sample_logits(ins, attrs):
    """reference: paddle/fluid/operators/sample_logits_op.h — gather the
    true-label logits plus `num_samples` log-uniform negatives per row,
    subtracting log(prob) (sampled-softmax correction); accidental hits
    (a sampled negative equal to a true label of the SAME row) get -1e20."""
    from paddle_tpu.ops.common import seeded_rng_key

    logits = first(ins, "Logits")            # [N, K]
    labels = first(ins, "Labels").astype(jnp.int32)  # [N, NT]
    N, K = logits.shape
    NT = labels.shape[1]
    S = attrs.get("num_samples", 10)
    use_custom = attrs.get("use_customized_samples", False)
    if use_custom:
        samples = first(ins, "CustomizedSamples").astype(jnp.int32)
        probs = first(ins, "CustomizedProbabilities").astype(jnp.float32)
    else:
        key = seeded_rng_key(ins, attrs)
        # log-uniform (Zipfian) sampler, as the reference's LogUniformSampler
        u = jax.random.uniform(key, (N, S))
        neg = jnp.clip(
            jnp.floor(jnp.exp(u * jnp.log(float(K + 1))) - 1.0)
            .astype(jnp.int32), 0, K - 1,
        )
        samples = jnp.concatenate([labels, neg], axis=1)     # [N, NT+S]
        sf = samples.astype(jnp.float32)
        probs = (jnp.log(sf + 2.0) - jnp.log(sf + 1.0)) / jnp.log(
            float(K + 1)
        )
    sampled = jnp.take_along_axis(logits, samples, axis=1)
    sampled = sampled - jnp.log(jnp.maximum(probs, 1e-20))
    if attrs.get("remove_accidental_hits", True):
        # negative j (j >= NT) hitting any true label of its row
        hit = (samples[:, None, NT:] == labels[:, :, None]).any(axis=1)
        pad = jnp.zeros((N, NT), bool)
        sampled = sampled - jnp.concatenate([pad, hit], axis=1) * 1e20
    return {
        "Samples": [samples.astype(jnp.int64)],
        "Probabilities": [probs],
        "SampledLogits": [sampled],
        "SampledLabels": [
            jnp.broadcast_to(jnp.arange(NT, dtype=jnp.int64)[None], (N, NT))
        ],
    }


@register_op("sampling_id", stateful=True, nondiff_inputs=("X",))
def _sampling_id(ins, attrs):
    """reference: paddle/fluid/operators/sampling_id_op.h — sample one
    class index per row of a probability matrix."""
    from paddle_tpu.ops.common import seeded_rng_key

    x = first(ins, "X").astype(jnp.float32)  # [N, K] probabilities
    key = seeded_rng_key(ins, attrs)
    out = jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)), axis=1)
    return {"Out": [out.astype(jnp.int64)]}


@register_op("hash", nondiff_inputs=("X",))
def _hash(ins, attrs):
    """reference: paddle/fluid/operators/hash_op.h — per-row integer hash
    into [0, mod_by) for `num_hash` seeds. The reference uses XXH64; here a
    splitmix64-style integer mix (deterministic, different stream, same
    contract: stable bucketed ids for feature crossing)."""
    x = first(ins, "X").astype(jnp.uint32)   # [T, last]
    mod_by = attrs.get("mod_by", 1 << 20)
    num_hash = attrs.get("num_hash", 1)
    t = x.shape[0]

    def mix(h):
        # murmur3-style 32-bit finalizer (x64 mode is off on TPU configs,
        # so the mix stays in uint32)
        h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
        return h ^ (h >> 16)

    outs = []
    for seed in range(num_hash):
        h = jnp.full((t,), jnp.uint32((seed * 0x9E3779B9 + 1) & 0xFFFFFFFF))
        for j in range(x.shape[-1]):
            h = mix(h ^ x[:, j])
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    return {"Out": [jnp.stack(outs, axis=1)[:, :, None]]}


# ---------------------------------------------------------------------------
# proximal optimizers
# ---------------------------------------------------------------------------


@register_op("proximal_gd")
def _proximal_gd(ins, attrs):
    """reference: paddle/fluid/operators/optimizers/proximal_gd_op.h."""
    p = first(ins, "Param").astype(jnp.float32)
    g = first(ins, "Grad").astype(jnp.float32)
    lr = first(ins, "LearningRate").astype(jnp.float32).reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    if l1 > 0:
        out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (
            1.0 + lr * l2
        )
    else:
        out = prox / (1.0 + lr * l2)
    return {"ParamOut": [out]}


@register_op("proximal_adagrad")
def _proximal_adagrad(ins, attrs):
    """reference: paddle/fluid/operators/optimizers/proximal_adagrad_op.h —
    adagrad-scaled step, then the same proximal shrink."""
    p = first(ins, "Param").astype(jnp.float32)
    g = first(ins, "Grad").astype(jnp.float32)
    m = first(ins, "Moment").astype(jnp.float32)
    lr = first(ins, "LearningRate").astype(jnp.float32).reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    m_out = m + g * g
    lr_eff = lr / jnp.sqrt(m_out)
    prox = p - lr_eff * g
    if l1 > 0:
        out = jnp.sign(prox) * jnp.maximum(
            jnp.abs(prox) - lr_eff * l1, 0.0
        ) / (1.0 + lr_eff * l2)
    else:
        out = prox / (1.0 + lr_eff * l2)
    return {"ParamOut": [out], "MomentOut": [m_out]}


# ---------------------------------------------------------------------------
# sequence / metrics
# ---------------------------------------------------------------------------


@register_op("edit_distance", nondiff_inputs=("Hyps", "Refs", "HypsLength",
                                              "RefsLength"))
def _edit_distance(ins, attrs):
    """reference: paddle/fluid/operators/edit_distance_op.h — Levenshtein
    distance per (hyp, ref) pair. Padded+lengths form: Hyps [B, Tm],
    Refs [B, Tn] int64 with HypsLength/RefsLength [B]. The O(m*n) DP runs
    as a lax.scan over hyp positions carrying the whole DP row (vectorized
    over the batch) — fixed shapes, no per-sequence host loop."""
    hyps = first(ins, "Hyps").astype(jnp.int32)
    refs = first(ins, "Refs").astype(jnp.int32)
    B, Tm = hyps.shape
    Tn = refs.shape[1]
    hl = maybe(ins, "HypsLength")
    rl = maybe(ins, "RefsLength")
    if hl is None:
        hl = jnp.full((B,), Tm, jnp.int32)
        rl = jnp.full((B,), Tn, jnp.int32)
    hl = hl.reshape(-1).astype(jnp.int32)
    rl = rl.reshape(-1).astype(jnp.int32)

    cols = jnp.arange(Tn + 1, dtype=jnp.float32)  # [Tn+1]
    row0 = jnp.broadcast_to(cols, (B, Tn + 1))    # dist[0, j] = j

    def step(prev_row, i):
        # prev_row: dist[i]; compute dist[i+1] via an inner scan over j
        sub_cost = (hyps[:, i][:, None] != refs).astype(jnp.float32)  # [B,Tn]

        def inner(left, j):
            # left = dist[i+1, j]; compute dist[i+1, j+1]
            up = prev_row[:, j + 1]
            diag = prev_row[:, j]
            val = jnp.minimum(
                jnp.minimum(up + 1.0, left + 1.0), diag + sub_cost[:, j]
            )
            # beyond the hyp length the row is inert: carry prev_row so the
            # final gather at (hl, rl) sees the last REAL row
            val = jnp.where(i < hl, val, up)
            return val, val

        first_col = jnp.where(i < hl, jnp.float32(i + 1), prev_row[:, 0])
        _, rest = jax.lax.scan(inner, first_col, jnp.arange(Tn))
        new_row = jnp.concatenate(
            [first_col[:, None], jnp.transpose(rest)], axis=1
        )
        return new_row, None

    final_row_all, _ = jax.lax.scan(step, row0, jnp.arange(Tm))
    # final_row_all is dist[Tm] with rows frozen past each hyp's length;
    # answer per pair = dist[hl, rl]
    dist = jnp.take_along_axis(final_row_all, rl[:, None], axis=1)[:, 0]
    # empty-hyp/empty-ref edge cases match the DP init already
    if attrs.get("normalized", False):
        dist = dist / jnp.maximum(rl.astype(jnp.float32), 1.0)
    return {
        "Out": [dist.reshape(B, 1)],
        "SequenceNum": [jnp.asarray(B, jnp.int64)],
    }


@register_op("positive_negative_pair", nondiff_inputs=("Score", "Label",
                                                       "QueryID"))
def _positive_negative_pair(ins, attrs):
    """reference: paddle/fluid/operators/positive_negative_pair_op.h —
    within each query, count score-ordered pairs that agree/disagree with
    the label order."""
    score = first(ins, "Score")
    label = first(ins, "Label").reshape(-1).astype(jnp.float32)
    qid = first(ins, "QueryID").reshape(-1)
    s = score[:, -1] if score.ndim == 2 else score.reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    upper = jnp.triu(jnp.ones(same_q.shape, bool), k=1)
    valid = same_q & upper & (label[:, None] != label[None, :])
    lab_gt = label[:, None] > label[None, :]
    s_gt = s[:, None] > s[None, :]
    s_eq = s[:, None] == s[None, :]
    pos = jnp.sum(valid & ~s_eq & (lab_gt == s_gt))
    neg = jnp.sum(valid & ~s_eq & (lab_gt != s_gt))
    neu = jnp.sum(valid & s_eq)
    f = jnp.float32
    return {
        "PositivePair": [pos.astype(f).reshape(1)],
        "NegativePair": [neg.astype(f).reshape(1)],
        "NeutralPair": [neu.astype(f).reshape(1)],
    }


@register_op("match_matrix_tensor")
def _match_matrix_tensor(ins, attrs):
    """reference: paddle/fluid/operators/match_matrix_tensor_op.cc — for
    each channel t of W [D1, T, D2]: out[b, t, i, j] = x[b, i] W_t y[b, j].
    Padded form: X [B, Lx, D1], Y [B, Ly, D2]."""
    x = first(ins, "X")
    y = first(ins, "Y")
    w = first(ins, "W")
    xw = jnp.einsum("bid,dte->bite", x, w)
    out = jnp.einsum("bite,bje->btij", xw, y)
    return {"Out": [out], "Tmp": [xw]}


@register_op("shrink_rnn_memory", nondiff_inputs=("RankTable", "I"))
def _shrink_rnn_memory(ins, attrs):
    """reference: paddle/fluid/operators/shrink_rnn_memory_op.cc — keep the
    first k batch rows at step I per the rank table's active-sequence
    count. Padded form: the mask zeroes retired rows (fixed shapes)."""
    x = first(ins, "X")
    i = first(ins, "I").reshape(()).astype(jnp.int32)
    table = first(ins, "RankTable").astype(jnp.int32)  # lengths, sorted desc
    active = jnp.sum(table > i)
    mask = (jnp.arange(x.shape[0]) < active).astype(x.dtype)
    return {"Out": [x * mask.reshape((-1,) + (1,) * (x.ndim - 1))]}


# ---------------------------------------------------------------------------
# rnn units
# ---------------------------------------------------------------------------


@register_op("gru_unit")
def _gru_unit(ins, attrs):
    """reference: paddle/fluid/operators/gru_unit_op.h — one GRU step.
    Input [B, 3H] (pre-computed x projections), HiddenPrev [B, H],
    Weight [H, 3H] (update|reset | candidate), optional Bias [1, 3H]."""
    xp = first(ins, "Input")
    h_prev = first(ins, "HiddenPrev")
    w = first(ins, "Weight")
    b = maybe(ins, "Bias")
    H = h_prev.shape[1]
    if b is not None:
        xp = xp + b.reshape(1, -1)
    gate_w = w[:, : 2 * H]
    cand_w = w[:, 2 * H:]
    gates = xp[:, : 2 * H] + h_prev @ gate_w
    u = jax.nn.sigmoid(gates[:, :H])
    r = jax.nn.sigmoid(gates[:, H:])
    c = jnp.tanh(xp[:, 2 * H:] + (r * h_prev) @ cand_w)
    # reference convention: h = u * h_prev + (1 - u) * c
    h = u * h_prev + (1.0 - u) * c
    return {
        "Gate": [jnp.concatenate([u, r, c], axis=1)],
        "ResetHiddenPrev": [r * h_prev],
        "Hidden": [h],
    }


@register_op("lstm_unit")
def _lstm_unit(ins, attrs):
    """reference: paddle/fluid/operators/lstm_unit_op.h — one LSTM step
    from pre-projected gates X [B, 4H] and C_prev [B, H]."""
    x = first(ins, "X")
    c_prev = first(ins, "C_prev")
    H = c_prev.shape[1]
    forget_bias = attrs.get("forget_bias", 0.0)
    i = jax.nn.sigmoid(x[:, :H])
    f = jax.nn.sigmoid(x[:, H:2 * H] + forget_bias)
    o = jax.nn.sigmoid(x[:, 2 * H:3 * H])
    g = jnp.tanh(x[:, 3 * H:])
    c = f * c_prev + i * g
    return {"C": [c], "H": [o * jnp.tanh(c)]}


@register_op("lstmp")
def _lstmp(ins, attrs):
    """reference: paddle/fluid/operators/lstmp_op.h — LSTM with a
    projection layer: recurrence runs on the projected state r [B, P].
    Padded form: Input [B, T, 4H] (x projections), Weight [P, 4H],
    ProjWeight [H, P], optional Bias [1, 4H]."""
    x = first(ins, "Input")
    w = first(ins, "Weight")
    proj = first(ins, "ProjWeight")
    b = maybe(ins, "Bias")
    B, T, H4 = x.shape
    H = H4 // 4
    P = proj.shape[1]
    if b is not None:
        x = x + b.reshape(1, 1, -1)

    def step(carry, xt):
        r_prev, c_prev = carry
        gates = xt + r_prev @ w
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H])
        g = jnp.tanh(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:])
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        r = h @ proj
        if attrs.get("proj_clip", 0.0) > 0:
            pc = attrs["proj_clip"]
            r = jnp.clip(r, -pc, pc)
        return (r, c), (r, c)

    r0 = jnp.zeros((B, P), x.dtype)
    c0 = jnp.zeros((B, H), x.dtype)
    (_, _), (rs, cs) = jax.lax.scan(
        step, (r0, c0), jnp.transpose(x, (1, 0, 2))
    )
    return {
        "Projection": [jnp.transpose(rs, (1, 0, 2))],
        "Cell": [jnp.transpose(cs, (1, 0, 2))],
    }


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ins, attrs):
    """reference: paddle/fluid/operators/pool_with_index_op.cc (3-D)."""
    x = first(ins, "X")
    ksize = tuple(attrs.get("ksize", [2, 2, 2]))
    strides = tuple(attrs.get("strides", ksize))
    pads = attrs.get("paddings", [0, 0, 0])
    N, C, D, H, W = x.shape
    NEG = -1e30
    xp = jnp.pad(
        x.astype(jnp.float32),
        ((0, 0), (0, 0)) + tuple((p, p) for p in pads[:3]),
        constant_values=NEG,
    )
    patches = jax.lax.conv_general_dilated_patches(
        xp, ksize, strides, "VALID",
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    od, oh, ow = patches.shape[2:]
    kvol = int(np.prod(ksize))
    p = patches.reshape(N, C, kvol, od, oh, ow)
    out = p.max(axis=2)
    widx = p.argmax(axis=2)
    kd, kh, kw = ksize
    base_d = jnp.arange(od)[:, None, None] * strides[0] - pads[0]
    base_h = jnp.arange(oh)[None, :, None] * strides[1] - pads[1]
    base_w = jnp.arange(ow)[None, None, :] * strides[2] - pads[2]
    gd = base_d[None, None] + widx // (kh * kw)
    gh = base_h[None, None] + (widx // kw) % kh
    gw = base_w[None, None] + widx % kw
    mask = p.max(axis=2) <= NEG / 2
    out = jnp.where(mask, 0.0, out).astype(x.dtype)
    midx = jnp.where(
        mask, jnp.int32(-1),
        ((gd * H + gh) * W + gw).astype(jnp.int32),
    )
    return {"Out": [out], "Mask": [midx]}


# ---------------------------------------------------------------------------
# quantization ops (INT8 deploy path; the fake_quantize_dequantize_* train
# forms live in contrib/quantize.py)
# ---------------------------------------------------------------------------


def _qmax(bits):
    return float((1 << (bits - 1)) - 1)


@register_op("fake_quantize_abs_max", nondiff_inputs=("X",))
def _fake_quantize_abs_max(ins, attrs):
    """reference: paddle/fluid/operators/fake_quantize_op.cc
    FakeQuantizeAbsMax — quantize to round(x / scale * qmax) ints."""
    x = first(ins, "X").astype(jnp.float32)
    qmax = _qmax(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x))
    out = jnp.round(x / jnp.maximum(scale, 1e-8) * qmax)
    return {"Out": [jnp.clip(out, -qmax, qmax)], "OutScale": [scale.reshape(1)]}


@register_op("fake_channel_wise_quantize_abs_max", nondiff_inputs=("X",))
def _fake_cw_quantize(ins, attrs):
    """reference: fake_quantize_op.cc FakeChannelWiseQuantizeAbsMax —
    per-output-channel (dim 0) scales."""
    x = first(ins, "X").astype(jnp.float32)
    qmax = _qmax(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x.reshape(x.shape[0], -1)), axis=1)
    sc = scale.reshape((-1,) + (1,) * (x.ndim - 1))
    out = jnp.clip(jnp.round(x / jnp.maximum(sc, 1e-8) * qmax), -qmax, qmax)
    return {"Out": [out], "OutScale": [scale]}


@register_op("fake_dequantize_max_abs", nondiff_inputs=("Scale",))
def _fake_dequantize_max_abs(ins, attrs):
    """reference: fake_dequantize_op.cc — x * scale / qmax."""
    x = first(ins, "X").astype(jnp.float32)
    scale = first(ins, "Scale").astype(jnp.float32).reshape(())
    qmax = attrs.get("max_range", _qmax(8))
    return {"Out": [x * scale / qmax]}


@register_op("fake_channel_wise_dequantize_max_abs",
             nondiff_inputs=("Scales",))
def _fake_cw_dequantize(ins, attrs):
    """reference: fake_dequantize_op.cc channel-wise form: Scales is a list
    of 1-2 scale tensors (weight channel scales [+ activation scale])."""
    x = first(ins, "X").astype(jnp.float32)
    scales = ins["Scales"]
    bits = attrs.get("quant_bits", [8])
    s0 = scales[0].reshape((-1,) + (1,) * (x.ndim - 1))
    out = x * s0 / _qmax(bits[0])
    if len(scales) > 1:
        out = out * scales[1].reshape(()) / _qmax(
            bits[1] if len(bits) > 1 else 8
        )
    return {"Out": [out]}


@register_op("fake_quantize_moving_average_abs_max",
             nondiff_inputs=("X", "InScale", "InAccum", "InState"))
def _fake_quantize_moving(ins, attrs):
    """reference: fake_quantize_op.cc FakeQuantizeMovingAverageAbsMax —
    quantize with a moving-average scale; state rides as outputs."""
    x = first(ins, "X").astype(jnp.float32)
    in_scale = first(ins, "InScale").astype(jnp.float32).reshape(())
    rate = attrs.get("moving_rate", 0.9)
    qmax = _qmax(attrs.get("bit_length", 8))
    cur = jnp.max(jnp.abs(x))
    state = maybe(ins, "InState")
    accum = maybe(ins, "InAccum")
    if attrs.get("is_test", False) or state is None:
        scale = in_scale
        outs = {}
    else:
        st = state.reshape(()) * rate + 1.0
        ac = accum.reshape(()) * rate + cur
        scale = ac / st
        outs = {
            "OutState": [st.reshape(1)],
            "OutAccum": [ac.reshape(1)],
        }
    out = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-8) * qmax),
                   -qmax, qmax)
    return {"Out": [out], "OutScale": [scale.reshape(1)], **outs}


@register_op("fake_quantize_range_abs_max",
             nondiff_inputs=("X", "InScale", "Iter"))
def _fake_quantize_range(ins, attrs):
    """reference: fake_quantize_op.cc FakeQuantizeRangeAbsMax — running max
    over a window (window_size); test mode uses the stored scale."""
    x = first(ins, "X").astype(jnp.float32)
    in_scale = first(ins, "InScale").astype(jnp.float32).reshape(())
    qmax = _qmax(attrs.get("bit_length", 8))
    cur = jnp.max(jnp.abs(x))
    if attrs.get("is_test", False):
        scale = in_scale
        outs = {}
    else:
        scale = jnp.maximum(in_scale, cur)
        outs = {"OutScale": [scale.reshape(1)]}
    out = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-8) * qmax),
                   -qmax, qmax)
    return {"Out": [out], **outs} if outs else {
        "Out": [out], "OutScale": [scale.reshape(1)]
    }


@register_op("moving_average_abs_max_scale",
             nondiff_inputs=("X", "InAccum", "InState"))
def _moving_average_scale(ins, attrs):
    """reference: fake_quantize_op.cc MovingAverageAbsMaxScale — observe
    only (no quantization), used to collect output scales."""
    x = first(ins, "X")
    rate = attrs.get("moving_rate", 0.9)
    cur = jnp.max(jnp.abs(x.astype(jnp.float32)))
    state = maybe(ins, "InState")
    if attrs.get("is_test", False) or state is None:
        return {"Out": [x], "OutScale": [cur.reshape(1)]}
    st = state.reshape(()) * rate + 1.0
    ac = maybe(ins, "InAccum").reshape(()) * rate + cur
    return {
        "Out": [x],
        "OutScale": [(ac / st).reshape(1)],
        "OutState": [st.reshape(1)],
        "OutAccum": [ac.reshape(1)],
    }


@register_op("quantize", nondiff_inputs=("Input",))
def _quantize(ins, attrs):
    """reference: paddle/fluid/operators/quantize_op.cc (mkldnn deploy) —
    x * scale, rounded to int range."""
    x = first(ins, "Input").astype(jnp.float32)
    scale = attrs.get("Scale", 1.0)
    return {"Output": [jnp.round(x * scale)]}


@register_op("dequantize", nondiff_inputs=("Input",))
def _dequantize(ins, attrs):
    """reference: paddle/fluid/operators/dequantize_op.cc — x / scale."""
    x = first(ins, "Input").astype(jnp.float32)
    scale = attrs.get("Scale", 1.0)
    return {"Output": [x / scale]}


@register_op("dequantize_abs_max", nondiff_inputs=("X", "Scale"))
def _dequantize_abs_max(ins, attrs):
    """reference: paddle/fluid/operators/dequantize_abs_max_op.cc —
    int8 weights back to float: x * scale / max_range."""
    x = first(ins, "X").astype(jnp.float32)
    scale = first(ins, "Scale").astype(jnp.float32).reshape(())
    return {"Out": [x * scale / attrs.get("max_range", 127.0)]}


# ---------------------------------------------------------------------------
# CTR / PS routing utilities
# ---------------------------------------------------------------------------


@register_op("filter_by_instag", nondiff_inputs=("Ins_tag", "Filter_tag"))
def _filter_by_instag(ins, attrs):
    """reference: paddle/fluid/operators/filter_by_instag_op.h — keep rows
    whose tag list intersects the filter tags. Fixed-slate form: Ins
    [B, D] with per-row tags Ins_tag [B, T] (-1 padded); kept rows stay in
    place, dropped rows are zeroed (out_val_if_empty when nothing
    matches), LossWeight [B, 1] is the keep mask, IndexMap maps kept rows
    to themselves (the reference compacts; the static-shape contract
    masks)."""
    x = first(ins, "Ins")
    tags = first(ins, "Ins_tag").astype(jnp.int64)
    filt = first(ins, "Filter_tag").reshape(-1).astype(jnp.int64)
    if tags.ndim == 1:
        tags = tags[:, None]
    # exclude the -1 padding sentinel on BOTH sides: a padded filter slot
    # must not match every padded row
    keep = (
        (tags[:, :, None] == filt[None, None, :])
        & (tags[:, :, None] >= 0)
    ).any(axis=(1, 2))
    none_kept = ~keep.any()
    fill = attrs.get("out_val_if_empty", 0)
    # kept rows pass through; dropped rows are zero. When NOTHING matches,
    # the reference emits a dummy out_val_if_empty output with loss weight
    # 0 (train on nothing) — here the whole slate becomes the fill value
    # with all-zero weights.
    out = jnp.where(
        none_kept,
        jnp.full_like(x, jnp.asarray(fill, x.dtype)),
        jnp.where(keep[:, None], x, jnp.zeros((), x.dtype)),
    )
    lw = jnp.where(
        none_kept,
        jnp.zeros((x.shape[0], 1), jnp.float32),
        keep[:, None].astype(jnp.float32),
    )
    B = x.shape[0]
    idx = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int64)[:, None], (B, 2))
    return {"Out": [out], "LossWeight": [lw], "IndexMap": [idx]}


@register_op("merge_ids", nondiff_inputs=("Ids", "Rows", "X"))
def _merge_ids(ins, attrs):
    """reference: paddle/fluid/operators/distributed_ops/merge_ids_op.h —
    reassemble rows pulled from sharded PS tables back into the original
    id order: for each queried id, take its embedding from the shard that
    owns it (row r of table r % nshards)."""
    ids_list = ins["Ids"]
    rows_list = ins["Rows"]
    x_list = ins["X"]
    outs = []
    for ids in ids_list:
        idv = ids.reshape(-1).astype(jnp.int32)
        D = x_list[0].shape[-1]
        out = jnp.zeros((idv.shape[0], D), x_list[0].dtype)
        for rows, x in zip(rows_list, x_list):
            rowv = rows.reshape(-1).astype(jnp.int32)
            if rowv.shape[0] == 0:
                continue  # a shard that owns none of the queried ids
            # position of each queried id within this shard's row list
            eq = idv[:, None] == rowv[None, :]            # [Q, R]
            has = eq.any(axis=1)
            pos = jnp.argmax(eq, axis=1)
            out = jnp.where(has[:, None], x[pos], out)
        outs.append(out)
    return {"Out": outs}


@register_op("split_ids", nondiff_inputs=("Ids",))
def _split_ids(ins, attrs):
    """reference: paddle/fluid/operators/distributed_ops/split_ids_op.h —
    route ids to nshards PS tables by id % nshards. Fixed-slate form: each
    shard output keeps the full width with non-member slots = -1 (the
    reference compacts per shard; LoD-free contract masks instead)."""
    ids = first(ins, "Ids").reshape(-1).astype(jnp.int64)
    n = attrs.get("nshards", 0)
    if not n:
        raise EnforceError(
            "split_ids needs an explicit 'nshards' attr (the reference "
            "derives it from the Out arity, which a lowering cannot see)"
        )
    outs = []
    for s in range(n):
        m = (ids % n) == s
        outs.append(jnp.where(m, ids, jnp.int64(-1))[:, None])
    return {"Out": outs}


# ---------------------------------------------------------------------------
# final parity tranche
# ---------------------------------------------------------------------------


@register_op("unsqueeze")
def _unsqueeze_v1(ins, attrs):
    """reference: paddle/fluid/operators/unsqueeze_op.cc (v1 = v2 minus
    the XShape bookkeeping output; delegates)."""
    from paddle_tpu.core.registry import get_op_def

    return {"Out": get_op_def("unsqueeze2").lower(ins, attrs)["Out"]}


@register_op("uniform_random_batch_size_like", stateful=True,
             nondiff_inputs=("Input",))
def _uniform_random_bsl(ins, attrs):
    """reference: paddle/fluid/operators/uniform_random_batch_size_like_op.cc."""
    from paddle_tpu.ops.common import np_dtype, seeded_rng_key

    ref = first(ins, "Input")
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[
        attrs.get("input_dim_idx", 0)
    ]
    key = seeded_rng_key(ins, attrs)
    out = jax.random.uniform(
        key, tuple(shape), jnp.float32,
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0),
    )
    return {"Out": [out.astype(jnp.dtype(np_dtype(attrs)))]}


@register_op("unique", nondiff_inputs=("X",))
def _unique(ins, attrs):
    """reference: paddle/fluid/operators/unique_op.h — static-shape form:
    Out keeps X's length with unique values FRONT-compacted (first
    occurrence order is NOT preserved — values are sorted, the tail
    repeats the last unique; jnp.unique's size= contract); Index maps each
    input element to its unique slot. The reference's dynamic-size output
    cannot exist under XLA; consumers read Count/Index."""
    from paddle_tpu.ops.common import np_dtype

    x = first(ins, "X").reshape(-1)
    it = jnp.dtype(np_dtype(attrs, default="int32"))
    uniq, idx = jnp.unique(
        x, return_inverse=True, size=x.shape[0], fill_value=x[-1]
    )
    return {"Out": [uniq], "Index": [idx.astype(it)]}


@register_op("unique_with_counts", nondiff_inputs=("X",))
def _unique_with_counts(ins, attrs):
    """reference: paddle/fluid/operators/unique_with_counts_op.h — unique +
    per-value occurrence counts (same static-shape contract as unique)."""
    from paddle_tpu.ops.common import np_dtype

    x = first(ins, "X").reshape(-1)
    it = jnp.dtype(np_dtype(attrs, default="int32"))
    uniq, idx, counts = jnp.unique(
        x, return_inverse=True, return_counts=True, size=x.shape[0],
        fill_value=x[-1],
    )
    return {
        "Out": [uniq],
        "Index": [idx.astype(it)],
        "Count": [counts.astype(it)],
    }


@register_op("lookup_table_dequant", nondiff_inputs=("Ids", "W"))
def _lookup_table_dequant(ins, attrs):
    """reference: paddle/fluid/operators/lookup_table_dequant_op.h — int8
    embedding rows stored as [min, max, q0..qD]:
    out = q * (max - min) / 2^8 + min per row (dequant<T> there)."""
    w = first(ins, "W")
    ids = first(ins, "Ids").reshape(-1).astype(jnp.int32)
    rows = w[ids].astype(jnp.float32)
    mn = rows[:, 0:1]
    mx = rows[:, 1:2]
    return {"Out": [rows[:, 2:] * (mx - mn) / 256.0 + mn]}


@register_op("dgc_clip_by_norm")
def _dgc_clip_by_norm(ins, attrs):
    """reference: paddle/fluid/operators/dgc_clip_by_norm_op.h —
    clip_by_norm gated on current_step >= rampup_begin_step."""
    x = first(ins, "X").astype(jnp.float32)
    step = first(ins, "current_step").reshape(())
    begin = attrs.get("rampup_begin_step", 0.0)
    max_norm = attrs.get("max_norm", 1.0)
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    clipped = x * jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-10))
    return {"Out": [jnp.where(step < begin, x, clipped)]}


@register_op("get_tensor_from_selected_rows", nondiff_inputs=())
def _get_tensor_from_selected_rows(ins, attrs):
    """reference: paddle/fluid/operators/get_tensor_from_selected_rows_op.cc
    — identity here: the dense path has no SelectedRows runtime type
    (sgd_sparse/sparse_weight_update carry the rows+ids design instead)."""
    return {"Out": [first(ins, "X")]}


@register_op("merge_selected_rows", nondiff_inputs=())
def _merge_selected_rows(ins, attrs):
    """reference: paddle/fluid/operators/merge_selected_rows_op.cc —
    duplicate-row accumulation. Dense-path identity (duplicates are
    already segment-summed inside gather vjps; see sgd_sparse)."""
    return {"Out": [first(ins, "X")]}


@register_op("sync_batch_norm", nondiff_inputs=("Mean", "Variance"))
def _sync_batch_norm(ins, attrs):
    """reference: paddle/fluid/operators/sync_batch_norm_op.cu — cross-
    device batch statistics. Under GSPMD the batch_norm reductions over a
    'data'-sharded batch ALREADY span every device (the partitioner
    inserts the cross-replica psums the reference hand-wrote with NCCL),
    so sync_batch_norm lowers to batch_norm unchanged."""
    from paddle_tpu.core.registry import get_op_def

    return get_op_def("batch_norm").lower(ins, attrs)


@register_op("var_conv_2d", nondiff_inputs=("ROW", "COLUMN"))
def _var_conv_2d(ins, attrs):
    """reference: paddle/fluid/operators/var_conv_2d_op.cc — conv over
    per-sample variable-extent 2-D maps (the match-matrix text pipeline).
    Padded form: X [B, C, H, W] with per-sample valid extents ROW [B] /
    COLUMN [B]; a stride-s conv produces ceil(h/s) x ceil(w/s) valid cells
    per sample ((d-1)//s + 1, the reference's top_im computation); cells
    beyond a sample's extent are zeroed. W [OC, C*kh*kw]."""
    x = first(ins, "X")
    w = first(ins, "W")
    rows = maybe(ins, "ROW")
    cols = maybe(ins, "COLUMN")
    kh = attrs.get("KernelH", 3)
    kw = attrs.get("KernelW", 3)
    sh = attrs.get("StrideH", 1)
    sw = attrs.get("StrideW", 1)
    B, C, H, W_ = x.shape
    OC = w.shape[0]
    filt = w.reshape(OC, C, kh, kw)
    # zero the INPUT beyond each sample's extent too: the kernel's
    # receptive field at valid boundary cells must not read padded junk
    # (reference convolves only the h x w map), and dX then stays zero in
    # the padded region
    if rows is not None:
        rv = rows.reshape(-1).astype(jnp.int32)
        x = x * (
            jnp.arange(H)[None, :] < rv[:, None]
        )[:, None, :, None].astype(x.dtype)
    if cols is not None:
        cv = cols.reshape(-1).astype(jnp.int32)
        x = x * (
            jnp.arange(W_)[None, :] < cv[:, None]
        )[:, None, None, :].astype(x.dtype)
    # SAME-at-stride output extent: (d - 1)//s + 1
    Ho = (H - 1) // sh + 1
    Wo = (W_ - 1) // sw + 1
    pad_h = max((Ho - 1) * sh + kh - H, 0)
    pad_w = max((Wo - 1) * sw + kw - W_, 0)
    out = jax.lax.conv_general_dilated(
        x, filt, (sh, sw),
        ((pad_h // 2, pad_h - pad_h // 2), (pad_w // 2, pad_w - pad_w // 2)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if rows is not None:
        vh = (rows.reshape(-1).astype(jnp.int32) - 1) // sh + 1
        out = out * (
            jnp.arange(Ho)[None, :] < vh[:, None]
        )[:, None, :, None].astype(out.dtype)
    if cols is not None:
        vw = (cols.reshape(-1).astype(jnp.int32) - 1) // sw + 1
        out = out * (
            jnp.arange(Wo)[None, :] < vw[:, None]
        )[:, None, None, :].astype(out.dtype)
    return {"Out": [out]}


@register_op("distributed_lookup_table", nondiff_inputs=("Ids",))
def _distributed_lookup_table(ins, attrs):
    """reference: paddle/fluid/operators/distributed_ops/
    distributed_lookup_table_op.cc — embedding lookup against a
    parameter-server table. Two forms:

    * W present (single-process semantic): dense gather from the local
      table — what the reference computes once the rows are fetched.
    * no W (the PS fleet form, layers.distributed_embedding): the table
      exists ONLY on the servers; the lookup is a `jax.experimental.
      io_callback` pulling the batch's unique rows inside the compiled
      step (reference: distributed/parameter_prefetch.cc:1), prefetch-
      aware via distributed/lookup.py. With no active worker context the
      lowering RAISES — a ported PS program must not silently train on a
      local table."""
    if ins.get("W"):
        w = first(ins, "W")
        outs = []
        for ids in ins["Ids"]:
            idv = ids
            if idv.ndim >= 2 and idv.shape[-1] == 1:
                idv = idv[..., 0]
            out = jnp.take(w, idv.astype(jnp.int32), axis=0)
            pad = attrs.get("padding_idx", -1)
            if pad is not None and pad >= 0:
                out = jnp.where((idv == pad)[..., None], 0.0, out)
            outs.append(out)
        return {"Outputs": outs}
    import functools

    from jax.experimental import io_callback

    from paddle_tpu.distributed import lookup as _rl

    name = attrs.get("table_name")
    ctx = _rl.active_context()
    if ctx is None or not ctx.has(name):
        raise EnforceError(
            f"distributed_lookup_table('{name}') is a remote PS table but "
            "no remote-lookup context is active. Run this program through "
            "the PS fleet (fleet.init_worker() registers the table and "
            "activates the context); refusing to compute a local-dense "
            "answer instead."
        )
    dim = int(attrs["dim"])
    outs = []
    for ids in ins["Ids"]:
        idv = ids
        if idv.ndim >= 2 and idv.shape[-1] == 1:
            idv = idv[..., 0]
        outs.append(
            # ordered: pulls and pushes share one total order per device,
            # so step N+1's pull always observes step N's push — the
            # freshness invariant the prefetch fence validates against
            io_callback(
                functools.partial(_rl.pull_host, name),
                jax.ShapeDtypeStruct(tuple(idv.shape) + (dim,), jnp.float32),
                idv,
                ordered=True,
            )
        )
    return {"Outputs": outs}


@register_op("distributed_push_sparse", nondiff_inputs=("Ids",))
def _distributed_push_sparse(ins, attrs):
    """Backward half of the remote lookup: push the batch's merged row
    grads to the servers from INSIDE the step (ordered io_callback — the
    server update is a side effect that must survive DCE and stay sequenced
    before the next step's pull). reference: the send/prefetch pair in
    distributed_ops/prefetch_op.cc:1 + communicator send path."""
    import functools

    from jax.experimental import io_callback

    from paddle_tpu.distributed import lookup as _rl

    name = attrs.get("table_name")
    ids, grad = first(ins, "Ids"), first(ins, "Grad")
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    io_callback(
        functools.partial(_rl.push_host, name),
        (),
        ids,
        grad,
        ordered=True,
    )
    return {}
