"""Sequence ops over padded [B, S, ...] tensors + per-row lengths.

The reference's sequence_ops family operates on LoD ragged batches
(reference: paddle/fluid/operators/sequence_ops/ — sequence_pool_op.h,
sequence_softmax_op.h, sequence_expand_op.h, ...). On TPU, ragged offsets
are hostile to static-shape XLA, so the whole family is re-based on the
padded+lengths representation (SURVEY §5.7: "subsume LoD by dense
padding+segment-ids"): every op takes a dense [B, S, ...] tensor and an
optional integer Length [B]; masked positions do not contribute.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError

_NEG = -1e30


def _len_mask(x, lengths, fill=0.0):
    """[B, S] validity mask broadcast to x's rank; None lengths = all valid."""
    B, S = x.shape[0], x.shape[1]
    if lengths is None:
        return jnp.ones((B, S), bool)
    return jnp.arange(S)[None, :] < lengths.reshape(B, 1)


def _bcast(mask, x):
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


@register_op("sequence_pool", nondiff_inputs=("Length",))
def _sequence_pool(ins, attrs):
    """reference: paddle/fluid/operators/sequence_ops/sequence_pool_op.h.
    pooltype in {SUM, AVERAGE, SQRT, MAX, LAST, FIRST}; output [B, ...]."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    mask = _len_mask(x, lengths)
    m = _bcast(mask, x)
    B, S = x.shape[0], x.shape[1]
    n = (
        jnp.full((B,), S, jnp.float32)
        if lengths is None
        else jnp.maximum(lengths.astype(jnp.float32), 1.0)
    )
    nb = n.reshape((B,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.where(m, x, 0).sum(axis=1)
    elif ptype == "AVERAGE":
        out = jnp.where(m, x, 0).sum(axis=1) / nb
    elif ptype == "SQRT":
        out = jnp.where(m, x, 0).sum(axis=1) / jnp.sqrt(nb)
    elif ptype == "MAX":
        out = jnp.where(m, x, _NEG).max(axis=1)
    elif ptype == "LAST":
        idx = (
            jnp.full((B,), S - 1, jnp.int32)
            if lengths is None
            else jnp.maximum(lengths.astype(jnp.int32) - 1, 0)
        )
        out = jnp.take_along_axis(
            x, idx.reshape((B, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise EnforceError(f"unknown pooltype {ptype}")
    return {"Out": [out.astype(x.dtype)]}


@register_op("sequence_softmax", nondiff_inputs=("Length",))
def _sequence_softmax(ins, attrs):
    """Softmax over the valid prefix of each row
    (reference: sequence_softmax_op.h — there per-LoD-span)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    mask = _len_mask(x, lengths)
    z = jnp.where(mask, x, _NEG)
    out = jax.nn.softmax(z, axis=1)
    return {"Out": [jnp.where(mask, out, 0.0).astype(x.dtype)]}


@register_op("sequence_reverse", nondiff_inputs=("Length",))
def _sequence_reverse(ins, attrs):
    """Reverse each row's valid prefix, keeping padding in place
    (reference: sequence_reverse_op.cc)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    B, S = x.shape[0], x.shape[1]
    if lengths is None:
        return {"Y": [x[:, ::-1]]}
    pos = jnp.arange(S)[None, :]
    L = lengths.reshape(B, 1).astype(jnp.int32)
    src = jnp.where(pos < L, L - 1 - pos, pos)
    return {"Y": [jnp.take_along_axis(x, src.reshape((B, S) + (1,) * (x.ndim - 2)), axis=1)]}


@register_op("sequence_expand_as", nondiff_inputs=("Length",))
def _sequence_expand_as(ins, attrs):
    """Tile each batch row across its row's full sequence axis
    (reference: sequence_expand_as_op.h — x row i repeated len(y_i) times).
    Padded form: X [B, ...] -> Out [B, S, ...] masked by Length."""
    x = first(ins, "X")
    y = first(ins, "Y")
    lengths = maybe(ins, "Length")
    S = y.shape[1]
    out = jnp.broadcast_to(
        x[:, None], (x.shape[0], S) + tuple(x.shape[1:])
    )
    mask = _len_mask(out, lengths)
    return {"Out": [jnp.where(_bcast(mask, out), out, 0).astype(x.dtype)]}


@register_op("sequence_concat", nondiff_inputs=("Length",))
def _sequence_concat(ins, attrs):
    """Concatenate sequences row-wise: out row = x1_row[:l1] ++ x2_row[:l2],
    padded to S1+S2 (reference: sequence_concat_op.h). Inputs X (list),
    Length (matching list, optional => full)."""
    xs = ins["X"]
    lens = ins.get("Length")
    B = xs[0].shape[0]
    S_out = sum(x.shape[1] for x in xs)
    feat = tuple(xs[0].shape[2:])
    out = jnp.zeros((B, S_out) + feat, xs[0].dtype)
    # scatter each source row at its running offset
    offs = jnp.zeros((B,), jnp.int32)
    pos_out = jnp.arange(S_out)
    total = jnp.zeros((B,), jnp.int32)
    for i, x in enumerate(xs):
        S = x.shape[1]
        L = (
            jnp.full((B,), S, jnp.int32)
            if lens is None
            else lens[i].astype(jnp.int32)
        )
        # out[b, offs[b] + j] = x[b, j] for j < L[b]
        src_idx = pos_out[None, :] - offs[:, None]  # [B, S_out]
        valid = (src_idx >= 0) & (src_idx < L[:, None])
        gathered = jnp.take_along_axis(
            x,
            jnp.clip(src_idx, 0, S - 1).reshape((B, S_out) + (1,) * (x.ndim - 2)),
            axis=1,
        )
        out = jnp.where(_bcast(valid, out), gathered, out)
        offs = offs + L
        total = total + L
    return {"Out": [out], "OutLength": [total.astype(jnp.int64)]}


@register_op("sequence_slice", nondiff_inputs=("Offset", "Length"))
def _sequence_slice(ins, attrs):
    """Per-row slice [offset, offset+length) shifted to position 0
    (reference: sequence_slice_op.h)."""
    x = first(ins, "X")
    offset = first(ins, "Offset").astype(jnp.int32).reshape(-1)
    length = first(ins, "Length").astype(jnp.int32).reshape(-1)
    B, S = x.shape[0], x.shape[1]
    pos = jnp.arange(S)[None, :]
    src = jnp.clip(pos + offset[:, None], 0, S - 1)
    out = jnp.take_along_axis(
        x, src.reshape((B, S) + (1,) * (x.ndim - 2)), axis=1
    )
    mask = pos < length[:, None]
    return {"Out": [jnp.where(_bcast(mask, out), out, 0).astype(x.dtype)]}


@register_op("sequence_enumerate", nondiff_inputs=("X", "Length"))
def _sequence_enumerate(ins, attrs):
    """Sliding windows of ids: out[b, t] = x[b, t:t+win]
    (reference: sequence_enumerate_op.h), pad_value past the row's end."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    win = attrs.get("win_size", 2)
    pad = attrs.get("pad_value", 0)
    B, S = x.shape[0], x.shape[1]
    L = (
        jnp.full((B, 1), S, jnp.int32)
        if lengths is None
        else lengths.reshape(B, 1).astype(jnp.int32)
    )
    pos = jnp.arange(S)[None, :]
    cols = []
    for k in range(win):
        idx = jnp.clip(pos + k, 0, S - 1)
        v = jnp.take_along_axis(x, idx, axis=1)
        cols.append(jnp.where(pos + k < L, v, pad))
    return {"Out": [jnp.stack(cols, axis=-1)]}


@register_op("sequence_erase", nondiff_inputs=("X", "Length"))
def _sequence_erase(ins, attrs):
    """Remove listed tokens, compacting each row to the left
    (reference: sequence_erase_op.h). Static-shape form: output keeps S
    columns, compacted prefix + pad 0, plus the new lengths."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    tokens = jnp.asarray(attrs.get("tokens", []), x.dtype)
    B, S = x.shape[0], x.shape[1]
    valid = _len_mask(x, lengths)
    keep = valid & ~jnp.isin(x, tokens)
    # stable-compact kept entries to the front of each row
    order = jnp.argsort(~keep, axis=1, stable=True)
    compacted = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(axis=1)
    pos = jnp.arange(S)[None, :]
    out = jnp.where(pos < new_len[:, None], compacted, 0)
    return {"Out": [out], "OutLength": [new_len.astype(jnp.int64)]}


@register_op("sequence_mask", nondiff_inputs=("X",))
def _sequence_mask(ins, attrs):
    """Lengths -> [B, maxlen] 0/1 mask (reference: sequence_mask_op.h)."""
    x = first(ins, "X").reshape(-1)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise EnforceError(
            "sequence_mask needs a static maxlen attr on TPU (dynamic "
            "output shapes cannot be compiled)"
        )
    from paddle_tpu.core.dtypes import to_numpy_dtype

    dt = to_numpy_dtype(attrs.get("out_dtype", "int64"))
    mask = jnp.arange(maxlen)[None, :] < x[:, None]
    return {"Y": [mask.astype(dt)]}


@register_op("sequence_pad", nondiff_inputs=("Length",))
def _sequence_pad(ins, attrs):
    """Already-padded input re-padded with an explicit value beyond each
    row's length (reference: sequence_pad_op.h — there LoD->dense; here it
    normalizes the padded region to pad_value and reports lengths)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    pad_value = attrs.get("pad_value", 0.0)
    mask = _len_mask(x, lengths)
    out = jnp.where(_bcast(mask, x), x, pad_value)
    B, S = x.shape[0], x.shape[1]
    L = (
        jnp.full((B,), S, jnp.int64)
        if lengths is None
        else lengths.astype(jnp.int64)
    )
    return {"Out": [out.astype(x.dtype)], "Length": [L]}


@register_op("sequence_unpad", nondiff_inputs=("Length",))
def _sequence_unpad(ins, attrs):
    """Zero the padding (the static-shape analog of LoD unpad,
    reference: sequence_unpad_op.h)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    mask = _len_mask(x, lengths)
    return {"Out": [jnp.where(_bcast(mask, x), x, 0).astype(x.dtype)]}


@register_op("sequence_conv", nondiff_inputs=("Length",))
def _sequence_conv(ins, attrs):
    """Context-window convolution over time (reference: sequence_conv_op.h):
    each output position sees [t+start, t+start+ctx) rows stacked then
    projected by Filter [ctx*feat, out]."""
    x = first(ins, "X")
    w = first(ins, "Filter")
    lengths = maybe(ins, "Length")
    ctx = attrs.get("contextLength", 3)
    start = attrs.get("contextStart", -((ctx - 1) // 2))
    B, S, F = x.shape
    mask = _len_mask(x, lengths)
    xz = jnp.where(mask[..., None], x, 0)
    cols = []
    pos = jnp.arange(S)
    for k in range(ctx):
        idx = pos + start + k
        valid = (idx >= 0) & (idx < S)
        g = xz[:, jnp.clip(idx, 0, S - 1), :]
        cols.append(jnp.where(valid[None, :, None], g, 0))
    stacked = jnp.concatenate(cols, axis=-1)  # [B, S, ctx*F]
    out = jnp.einsum("bsf,fo->bso", stacked, w)
    return {"Out": [jnp.where(mask[..., None], out, 0).astype(x.dtype)]}


@register_op("sequence_expand", nondiff_inputs=("Length", "YLength", "Y"))
def _sequence_expand(ins, attrs):
    """reference: paddle/fluid/operators/sequence_ops/sequence_expand_op.h —
    repeat sequence i of X `YLength[i]` times. Padded form: X [B, S, ...]
    (or [B, ...] for ref_level row-expand), YLength [B] repeat counts;
    output [B, R_max, S, ...] with rows beyond YLength[i] zeroed (the LoD
    concat of the reference becomes an explicit repeat axis)."""
    x = first(ins, "X")
    yl = maybe(ins, "YLength")
    if yl is None:
        y = maybe(ins, "Y")
        if y is None:
            raise EnforceError(
                "sequence_expand needs YLength (per-row repeat counts) or "
                "Y (whose row width supplies them)"
            )
        yl = jnp.full((x.shape[0],), y.shape[1] if y.ndim > 1 else 1,
                      jnp.int32)
    rmax = attrs.get("max_repeat", 8)  # static bound on per-row repeats
    # OutLength must describe the EMITTED slate: clamp to the static bound
    yl = jnp.minimum(yl.reshape(-1).astype(jnp.int32), rmax)
    B = x.shape[0]
    reps = jnp.arange(rmax)[None, :] < yl[:, None]      # [B, R]
    tiled = jnp.broadcast_to(
        x[:, None], (B, rmax) + tuple(x.shape[1:])
    )
    # fill with x's OWN dtype: a 0.0 float fill would silently promote
    # int64 token ids to float
    out = jnp.where(reps.reshape((B, rmax) + (1,) * (x.ndim - 1)),
                    tiled, jnp.zeros((), x.dtype))
    return {"Out": [out], "OutLength": [yl]}


@register_op("sequence_reshape", nondiff_inputs=("Length",))
def _sequence_reshape(ins, attrs):
    """reference: sequence_ops/sequence_reshape_op.h — re-chunk the token
    stream to `new_dim` features: [B, S, D] -> [B, S*D/new_dim, new_dim]."""
    x = first(ins, "X")
    new_dim = attrs["new_dim"]
    B, S, D = x.shape
    if (S * D) % new_dim:
        raise EnforceError(
            f"sequence_reshape: S*D={S*D} not divisible by new_dim={new_dim}"
        )
    return {"Out": [x.reshape(B, S * D // new_dim, new_dim)]}


@register_op("sequence_scatter", nondiff_inputs=("Ids", "IdsLength"))
def _sequence_scatter(ins, attrs):
    """reference: sequence_ops/sequence_scatter_op.h — per-row scatter-add
    of Updates into X at Ids. Padded form: X [B, N], Ids [B, K],
    Updates [B, K], optional IdsLength [B] masking the tail."""
    x = first(ins, "X")
    ids = first(ins, "Ids").astype(jnp.int32)
    upd = first(ins, "Updates")
    idl = maybe(ins, "IdsLength")
    if idl is not None:
        mask = jnp.arange(ids.shape[1])[None, :] < idl.reshape(-1, 1)
        upd = jnp.where(mask, upd, jnp.zeros((), upd.dtype))
    B = x.shape[0]
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], ids.shape)
    return {"Out": [x.at[rows, ids].add(upd)]}


@register_op("lod_reset", nondiff_inputs=("Y",))
def _lod_reset(ins, attrs):
    """reference: lod_reset_op.h — reassigns sequence boundaries. On the
    padded+lengths representation the DATA is unchanged; the new lengths
    (Y or target_lod) ride through as OutLength for downstream sequence
    ops."""
    x = first(ins, "X")
    y = maybe(ins, "Y")
    out = {"Out": [x]}
    if y is not None:
        out["OutLength"] = [y.reshape(-1).astype(jnp.int32)]
    return out


@register_op("chunk_eval", nondiff_inputs=("Inference", "Label", "SeqLength"))
def _chunk_eval(ins, attrs):
    """reference: paddle/fluid/operators/chunk_eval_op.h — chunk-level
    precision/recall/F1 for IOB tagging. Tags encode (chunk_type, pos) as
    tag = chunk_type * num_tag + pos with IOB pos: 0=B, 1=I. Padded
    [B, S] int tags + SeqLength [B]. A chunk starts at a B tag; it spans
    following I tags of the same type; two chunk sets are compared by
    (start, end, type) equality, vectorized as per-position start/segment
    matching."""
    scheme = attrs.get("chunk_scheme", "IOB")
    if scheme != "IOB":
        raise EnforceError(
            f"chunk_eval: only the IOB scheme is implemented (got "
            f"{scheme!r}); IOE/IOBES/plain need their own tag decoders"
        )
    inf = first(ins, "Inference").reshape(
        first(ins, "Inference").shape[0], -1
    ).astype(jnp.int32)
    lab = first(ins, "Label").reshape(inf.shape).astype(jnp.int32)
    sl = maybe(ins, "SeqLength")
    num_tag = 2  # IOB: B, I
    nct = attrs.get("num_chunk_types", 1)
    excluded = attrs.get("excluded_chunk_types", []) or []
    B, S = inf.shape
    valid = (
        jnp.arange(S)[None, :] < sl.reshape(-1, 1)
        if sl is not None else jnp.ones((B, S), bool)
    )

    def chunks(tags):
        # reference tag encoding: type*num_tag + pos for real chunks; the
        # single O (outside) tag is id num_chunk_types*num_tag and NEVER
        # starts or continues a chunk
        is_o = tags >= nct * num_tag
        ctype = jnp.where(is_o, -1, tags // num_tag)
        pos = tags % num_tag
        in_chunk = valid & ~is_o
        is_b = (pos == 0) & in_chunk
        prev_t = jnp.concatenate(
            [jnp.full((B, 1), -2, jnp.int32), ctype[:, :-1]], axis=1
        )
        # a chunk also starts at an I tag whose predecessor is a different
        # type or O (conventional IOB repair, matching the reference's
        # segmentation)
        raw_start = is_b | ((pos == 1) & (ctype != prev_t) & in_chunk)
        start = raw_start
        if excluded:
            # excluded-type chunks are not COUNTED but still TERMINATE the
            # preceding chunk: boundaries use raw_start
            for e in excluded:
                start = start & (ctype != e)
        return start, raw_start, ctype, in_chunk

    s_inf, raw_inf, t_inf, in_inf = chunks(inf)
    s_lab, raw_lab, t_lab, in_lab = chunks(lab)

    # a chunk spans from its start to the position before the next chunk
    # start (counted OR excluded) OR the first non-chunk (O / invalid)
    # position
    def chunk_end(raw_start, in_chunk):
        idx = jnp.arange(S)[None, :]
        boundary = raw_start | ~in_chunk
        nxt = jnp.where(boundary, idx, S + 1)
        rev = jnp.flip(nxt, axis=1)
        runmin = jax.lax.associative_scan(jnp.minimum, rev, axis=1)
        nxt_at = jnp.flip(runmin, axis=1)  # min boundary index >= position
        after = jnp.concatenate(
            [nxt_at[:, 1:], jnp.full((B, 1), S + 1)], axis=1
        )
        return after

    end_inf = chunk_end(raw_inf, in_inf)
    end_lab = chunk_end(raw_lab, in_lab)
    seq_end = (
        sl.reshape(-1, 1).astype(jnp.int32)
        if sl is not None else jnp.full((B, 1), S, jnp.int32)
    )
    e_inf = jnp.minimum(end_inf, seq_end)
    e_lab = jnp.minimum(end_lab, seq_end)
    match = s_inf & s_lab & (t_inf == t_lab) & (e_inf == e_lab)
    n_inf = s_inf.sum()
    n_lab = s_lab.sum()
    n_cor = match.sum()
    f = jnp.float32
    precision = n_cor.astype(f) / jnp.maximum(n_inf.astype(f), 1.0)
    recall = n_cor.astype(f) / jnp.maximum(n_lab.astype(f), 1.0)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-8)
    i64 = jnp.int64
    return {
        "Precision": [precision.reshape(1)],
        "Recall": [recall.reshape(1)],
        "F1-Score": [f1.reshape(1)],
        "NumInferChunks": [n_inf.astype(i64).reshape(1)],
        "NumLabelChunks": [n_lab.astype(i64).reshape(1)],
        "NumCorrectChunks": [n_cor.astype(i64).reshape(1)],
    }


@register_op("beam_search", nondiff_inputs=("pre_ids", "pre_scores", "ids",
                                            "scores"))
def _beam_search(ins, attrs):
    """reference: paddle/fluid/operators/beam_search_op.h — ONE beam step.
    Fixed-beam form: pre_ids [B, W], pre_scores [B, W], scores [B, W, K]
    (log-probs of the K expansions per live beam). Selects the global top-W
    (per batch) of pre_scores + scores; beams already ended (pre_id ==
    end_id) keep exactly one continuation (the end token, score carried).
    Returns selected_ids [B, W], selected_scores [B, W] and parent_idx
    [B, W] (which source beam each selection extends)."""
    pre_ids = first(ins, "pre_ids").astype(jnp.int32)
    pre_scores = first(ins, "pre_scores").astype(jnp.float32)
    ids = first(ins, "ids").astype(jnp.int32)      # [B, W, K]
    scores = first(ins, "scores").astype(jnp.float32)
    end_id = attrs.get("end_id", 0)
    B, W, K = scores.shape
    ended = pre_ids == end_id                      # [B, W]
    # is_accumulated (reference default True): `scores` already include the
    # beam history, so adding pre_scores would double-count it; False means
    # per-step log-probs that accumulate here
    if attrs.get("is_accumulated", True):
        live_scores = scores
    else:
        live_scores = pre_scores[:, :, None] + scores
    # ended beams: only expansion 0 is live, forced to end_id at carried
    # score; live beams get their (accumulated) expansion scores
    exp_scores = jnp.where(
        ended[:, :, None], pre_scores[:, :, None], live_scores
    )
    first_k = jnp.arange(K)[None, None, :] == 0
    exp_valid = jnp.where(ended[:, :, None], first_k, True)
    exp_scores = jnp.where(exp_valid, exp_scores, -jnp.inf)
    exp_ids = jnp.where(ended[:, :, None], end_id, ids)
    flat = exp_scores.reshape(B, W * K)
    top_s, top_i = jax.lax.top_k(flat, W)          # [B, W]
    parent = (top_i // K).astype(jnp.int32)
    sel_ids = jnp.take_along_axis(
        exp_ids.reshape(B, W * K), top_i, axis=1
    )
    return {
        "selected_ids": [sel_ids],
        "selected_scores": [top_s],
        "parent_idx": [parent],
    }


@register_op("beam_search_decode", nondiff_inputs=("Ids", "Parents",
                                                   "Scores"))
def _beam_search_decode(ins, attrs):
    """reference: paddle/fluid/operators/beam_search_decode_op.h — backtrack
    stacked per-step selections into full sequences. Fixed form: Ids /
    Parents [T, B, W] from T beam_search steps, Scores [B, W] final beam
    scores. Returns SentenceIds [B, W, T] (end-padded) and SentenceScores
    [B, W]: lane w holds the full history of final beam w, reconstructed by
    walking parent pointers backward with a lax.scan."""
    ids = first(ins, "Ids").astype(jnp.int32)       # [T, B, W]
    parents = first(ins, "Parents").astype(jnp.int32)
    scores = first(ins, "Scores").astype(jnp.float32)  # [B, W]
    T, B, W = ids.shape
    lane0 = jnp.broadcast_to(jnp.arange(W)[None], (B, W))

    def back(lane, t):
        tok = jnp.take_along_axis(ids[t], lane, axis=1)     # [B, W]
        lane_next = jnp.take_along_axis(parents[t], lane, axis=1)
        return lane_next, tok

    _, toks = jax.lax.scan(back, lane0, jnp.arange(T - 1, -1, -1))
    # toks [T, B, W] in reverse time order -> [B, W, T] forward
    sent = jnp.flip(jnp.transpose(toks, (1, 2, 0)), axis=2)
    return {"SentenceIds": [sent], "SentenceScores": [scores]}


@register_op("sequence_topk_avg_pooling", nondiff_inputs=("ROW", "COLUMN",
                                                          "Length"))
def _sequence_topk_avg_pooling(ins, attrs):
    """reference: sequence_ops/sequence_topk_avg_pooling_op.cc — for each
    (row, channel), average the top-k values along the last axis, one
    output column per k in `topks`. Padded form: X [B, C, N, M] (the
    match-matrix output), optional Length [B] masking columns."""
    x = first(ins, "X")
    topks = [int(k) for k in attrs.get("topks", [1])]
    lengths = maybe(ins, "Length")
    B, C, N, M = x.shape
    kmax = min(max(topks), M)
    xv = x
    if lengths is not None:
        mask = jnp.arange(M)[None, None, None, :] < lengths.reshape(
            -1, 1, 1, 1
        )
        xv = jnp.where(mask, x, _NEG)
    top = jax.lax.top_k(xv, kmax)[0]              # [B, C, N, kmax]
    top = jnp.where(top <= _NEG / 2, 0.0, top)
    outs = []
    for k in topks:
        # the reference ALWAYS divides by k, even when fewer than k values
        # exist (shorter rows contribute a smaller average, not a rescaled
        # one) — consistent with the masked-Length path above
        kk = min(k, M)
        outs.append(top[..., :kk].sum(axis=-1) / float(k))  # [B, C, N]
    out = jnp.stack(outs, axis=-1)                # [B, C, N, K]
    # reference layout: [B, N, C*K]
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(B, N, -1)
    return {"Out": [out], "pos": [jnp.zeros((B, 1), jnp.int32)]}
