"""Sequence ops over padded [B, S, ...] tensors + per-row lengths.

The reference's sequence_ops family operates on LoD ragged batches
(reference: paddle/fluid/operators/sequence_ops/ — sequence_pool_op.h,
sequence_softmax_op.h, sequence_expand_op.h, ...). On TPU, ragged offsets
are hostile to static-shape XLA, so the whole family is re-based on the
padded+lengths representation (SURVEY §5.7: "subsume LoD by dense
padding+segment-ids"): every op takes a dense [B, S, ...] tensor and an
optional integer Length [B]; masked positions do not contribute.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError

_NEG = -1e30


def _len_mask(x, lengths, fill=0.0):
    """[B, S] validity mask broadcast to x's rank; None lengths = all valid."""
    B, S = x.shape[0], x.shape[1]
    if lengths is None:
        return jnp.ones((B, S), bool)
    return jnp.arange(S)[None, :] < lengths.reshape(B, 1)


def _bcast(mask, x):
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


@register_op("sequence_pool", nondiff_inputs=("Length",))
def _sequence_pool(ins, attrs):
    """reference: paddle/fluid/operators/sequence_ops/sequence_pool_op.h.
    pooltype in {SUM, AVERAGE, SQRT, MAX, LAST, FIRST}; output [B, ...]."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    mask = _len_mask(x, lengths)
    m = _bcast(mask, x)
    B, S = x.shape[0], x.shape[1]
    n = (
        jnp.full((B,), S, jnp.float32)
        if lengths is None
        else jnp.maximum(lengths.astype(jnp.float32), 1.0)
    )
    nb = n.reshape((B,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.where(m, x, 0).sum(axis=1)
    elif ptype == "AVERAGE":
        out = jnp.where(m, x, 0).sum(axis=1) / nb
    elif ptype == "SQRT":
        out = jnp.where(m, x, 0).sum(axis=1) / jnp.sqrt(nb)
    elif ptype == "MAX":
        out = jnp.where(m, x, _NEG).max(axis=1)
    elif ptype == "LAST":
        idx = (
            jnp.full((B,), S - 1, jnp.int32)
            if lengths is None
            else jnp.maximum(lengths.astype(jnp.int32) - 1, 0)
        )
        out = jnp.take_along_axis(
            x, idx.reshape((B, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise EnforceError(f"unknown pooltype {ptype}")
    return {"Out": [out.astype(x.dtype)]}


@register_op("sequence_softmax", nondiff_inputs=("Length",))
def _sequence_softmax(ins, attrs):
    """Softmax over the valid prefix of each row
    (reference: sequence_softmax_op.h — there per-LoD-span)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    mask = _len_mask(x, lengths)
    z = jnp.where(mask, x, _NEG)
    out = jax.nn.softmax(z, axis=1)
    return {"Out": [jnp.where(mask, out, 0.0).astype(x.dtype)]}


@register_op("sequence_reverse", nondiff_inputs=("Length",))
def _sequence_reverse(ins, attrs):
    """Reverse each row's valid prefix, keeping padding in place
    (reference: sequence_reverse_op.cc)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    B, S = x.shape[0], x.shape[1]
    if lengths is None:
        return {"Y": [x[:, ::-1]]}
    pos = jnp.arange(S)[None, :]
    L = lengths.reshape(B, 1).astype(jnp.int32)
    src = jnp.where(pos < L, L - 1 - pos, pos)
    return {"Y": [jnp.take_along_axis(x, src.reshape((B, S) + (1,) * (x.ndim - 2)), axis=1)]}


@register_op("sequence_expand_as", nondiff_inputs=("Length",))
def _sequence_expand_as(ins, attrs):
    """Tile each batch row across its row's full sequence axis
    (reference: sequence_expand_as_op.h — x row i repeated len(y_i) times).
    Padded form: X [B, ...] -> Out [B, S, ...] masked by Length."""
    x = first(ins, "X")
    y = first(ins, "Y")
    lengths = maybe(ins, "Length")
    S = y.shape[1]
    out = jnp.broadcast_to(
        x[:, None], (x.shape[0], S) + tuple(x.shape[1:])
    )
    mask = _len_mask(out, lengths)
    return {"Out": [jnp.where(_bcast(mask, out), out, 0).astype(x.dtype)]}


@register_op("sequence_concat", nondiff_inputs=("Length",))
def _sequence_concat(ins, attrs):
    """Concatenate sequences row-wise: out row = x1_row[:l1] ++ x2_row[:l2],
    padded to S1+S2 (reference: sequence_concat_op.h). Inputs X (list),
    Length (matching list, optional => full)."""
    xs = ins["X"]
    lens = ins.get("Length")
    B = xs[0].shape[0]
    S_out = sum(x.shape[1] for x in xs)
    feat = tuple(xs[0].shape[2:])
    out = jnp.zeros((B, S_out) + feat, xs[0].dtype)
    # scatter each source row at its running offset
    offs = jnp.zeros((B,), jnp.int32)
    pos_out = jnp.arange(S_out)
    total = jnp.zeros((B,), jnp.int32)
    for i, x in enumerate(xs):
        S = x.shape[1]
        L = (
            jnp.full((B,), S, jnp.int32)
            if lens is None
            else lens[i].astype(jnp.int32)
        )
        # out[b, offs[b] + j] = x[b, j] for j < L[b]
        src_idx = pos_out[None, :] - offs[:, None]  # [B, S_out]
        valid = (src_idx >= 0) & (src_idx < L[:, None])
        gathered = jnp.take_along_axis(
            x,
            jnp.clip(src_idx, 0, S - 1).reshape((B, S_out) + (1,) * (x.ndim - 2)),
            axis=1,
        )
        out = jnp.where(_bcast(valid, out), gathered, out)
        offs = offs + L
        total = total + L
    return {"Out": [out], "OutLength": [total.astype(jnp.int64)]}


@register_op("sequence_slice", nondiff_inputs=("Offset", "Length"))
def _sequence_slice(ins, attrs):
    """Per-row slice [offset, offset+length) shifted to position 0
    (reference: sequence_slice_op.h)."""
    x = first(ins, "X")
    offset = first(ins, "Offset").astype(jnp.int32).reshape(-1)
    length = first(ins, "Length").astype(jnp.int32).reshape(-1)
    B, S = x.shape[0], x.shape[1]
    pos = jnp.arange(S)[None, :]
    src = jnp.clip(pos + offset[:, None], 0, S - 1)
    out = jnp.take_along_axis(
        x, src.reshape((B, S) + (1,) * (x.ndim - 2)), axis=1
    )
    mask = pos < length[:, None]
    return {"Out": [jnp.where(_bcast(mask, out), out, 0).astype(x.dtype)]}


@register_op("sequence_enumerate", nondiff_inputs=("X", "Length"))
def _sequence_enumerate(ins, attrs):
    """Sliding windows of ids: out[b, t] = x[b, t:t+win]
    (reference: sequence_enumerate_op.h), pad_value past the row's end."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    win = attrs.get("win_size", 2)
    pad = attrs.get("pad_value", 0)
    B, S = x.shape[0], x.shape[1]
    L = (
        jnp.full((B, 1), S, jnp.int32)
        if lengths is None
        else lengths.reshape(B, 1).astype(jnp.int32)
    )
    pos = jnp.arange(S)[None, :]
    cols = []
    for k in range(win):
        idx = jnp.clip(pos + k, 0, S - 1)
        v = jnp.take_along_axis(x, idx, axis=1)
        cols.append(jnp.where(pos + k < L, v, pad))
    return {"Out": [jnp.stack(cols, axis=-1)]}


@register_op("sequence_erase", nondiff_inputs=("X", "Length"))
def _sequence_erase(ins, attrs):
    """Remove listed tokens, compacting each row to the left
    (reference: sequence_erase_op.h). Static-shape form: output keeps S
    columns, compacted prefix + pad 0, plus the new lengths."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    tokens = jnp.asarray(attrs.get("tokens", []), x.dtype)
    B, S = x.shape[0], x.shape[1]
    valid = _len_mask(x, lengths)
    keep = valid & ~jnp.isin(x, tokens)
    # stable-compact kept entries to the front of each row
    order = jnp.argsort(~keep, axis=1, stable=True)
    compacted = jnp.take_along_axis(x, order, axis=1)
    new_len = keep.sum(axis=1)
    pos = jnp.arange(S)[None, :]
    out = jnp.where(pos < new_len[:, None], compacted, 0)
    return {"Out": [out], "OutLength": [new_len.astype(jnp.int64)]}


@register_op("sequence_mask", nondiff_inputs=("X",))
def _sequence_mask(ins, attrs):
    """Lengths -> [B, maxlen] 0/1 mask (reference: sequence_mask_op.h)."""
    x = first(ins, "X").reshape(-1)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise EnforceError(
            "sequence_mask needs a static maxlen attr on TPU (dynamic "
            "output shapes cannot be compiled)"
        )
    from paddle_tpu.core.dtypes import to_numpy_dtype

    dt = to_numpy_dtype(attrs.get("out_dtype", "int64"))
    mask = jnp.arange(maxlen)[None, :] < x[:, None]
    return {"Y": [mask.astype(dt)]}


@register_op("sequence_pad", nondiff_inputs=("Length",))
def _sequence_pad(ins, attrs):
    """Already-padded input re-padded with an explicit value beyond each
    row's length (reference: sequence_pad_op.h — there LoD->dense; here it
    normalizes the padded region to pad_value and reports lengths)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    pad_value = attrs.get("pad_value", 0.0)
    mask = _len_mask(x, lengths)
    out = jnp.where(_bcast(mask, x), x, pad_value)
    B, S = x.shape[0], x.shape[1]
    L = (
        jnp.full((B,), S, jnp.int64)
        if lengths is None
        else lengths.astype(jnp.int64)
    )
    return {"Out": [out.astype(x.dtype)], "Length": [L]}


@register_op("sequence_unpad", nondiff_inputs=("Length",))
def _sequence_unpad(ins, attrs):
    """Zero the padding (the static-shape analog of LoD unpad,
    reference: sequence_unpad_op.h)."""
    x = first(ins, "X")
    lengths = maybe(ins, "Length")
    mask = _len_mask(x, lengths)
    return {"Out": [jnp.where(_bcast(mask, x), x, 0).astype(x.dtype)]}


@register_op("sequence_conv", nondiff_inputs=("Length",))
def _sequence_conv(ins, attrs):
    """Context-window convolution over time (reference: sequence_conv_op.h):
    each output position sees [t+start, t+start+ctx) rows stacked then
    projected by Filter [ctx*feat, out]."""
    x = first(ins, "X")
    w = first(ins, "Filter")
    lengths = maybe(ins, "Length")
    ctx = attrs.get("contextLength", 3)
    start = attrs.get("contextStart", -((ctx - 1) // 2))
    B, S, F = x.shape
    mask = _len_mask(x, lengths)
    xz = jnp.where(mask[..., None], x, 0)
    cols = []
    pos = jnp.arange(S)
    for k in range(ctx):
        idx = pos + start + k
        valid = (idx >= 0) & (idx < S)
        g = xz[:, jnp.clip(idx, 0, S - 1), :]
        cols.append(jnp.where(valid[None, :, None], g, 0))
    stacked = jnp.concatenate(cols, axis=-1)  # [B, S, ctx*F]
    out = jnp.einsum("bsf,fo->bso", stacked, w)
    return {"Out": [jnp.where(mask[..., None], out, 0).astype(x.dtype)]}
