"""Neural-network op lowerings: conv/pool/norm/activation/loss/embedding.

Replaces the reference's cuDNN-backed kernels (reference:
paddle/fluid/operators/conv_cudnn_op.cu, pool_op.cu, batch_norm_op.cu,
softmax_with_cross_entropy_op.cu, lookup_table_op.cu) with lax/jnp lowerings:
convs hit the MXU via lax.conv_general_dilated, norms/activations fuse into
their neighbors under whole-block XLA compilation.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import OpDef, OpRegistry, register_op, register_grad
from paddle_tpu.ops.common import (
    first,
    maybe,
    normalize_padding,
    rng_key,
    vma_names,
)
from paddle_tpu.utils.enforce import EnforceError

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------


def _activation(name, fn):
    @register_op(name)
    def _lower(ins, attrs, _fn=fn):
        return {"Out": [_fn(first(ins, "X"), attrs)]}


_activation("relu", lambda x, a: jax.nn.relu(x))
_activation("relu6", lambda x, a: jnp.minimum(jax.nn.relu(x), a.get("threshold", 6.0)))
_activation("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_activation("tanh", lambda x, a: jnp.tanh(x))
_activation("gelu", lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", False)))
_activation("leaky_relu", lambda x, a: jax.nn.leaky_relu(x, a.get("alpha", 0.02)))
_activation("elu", lambda x, a: jax.nn.elu(x, a.get("alpha", 1.0)))
_activation("softplus", lambda x, a: jax.nn.softplus(x))
_activation("softsign", lambda x, a: jax.nn.soft_sign(x))
_activation("silu", lambda x, a: jax.nn.silu(x))
_activation("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_activation(
    "hard_sigmoid",
    lambda x, a: jnp.clip(a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
)
_activation(
    "hard_swish",
    lambda x, a: x
    * jnp.clip(x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0))
    / a.get("scale", 6.0),
)
_activation("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))


@register_op("softmax")
def _softmax(ins, attrs):
    return {"Out": [jax.nn.softmax(first(ins, "X"), axis=attrs.get("axis", -1))]}


@register_op("log_softmax")
def _log_softmax(ins, attrs):
    return {"Out": [jax.nn.log_softmax(first(ins, "X"), axis=attrs.get("axis", -1))]}


@register_op("prelu")
def _prelu(ins, attrs):
    x, alpha = first(ins, "X"), first(ins, "Alpha")
    if attrs.get("mode", "all") == "channel" and alpha.ndim == 1:
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(x > 0, x, alpha * x)]}


# ---------------------------------------------------------------------------
# conv / pool
# ---------------------------------------------------------------------------


@register_op("conv2d")
def _conv2d(ins, attrs):
    """reference: paddle/fluid/operators/conv_op.cc (NCHW, OIHW filters)."""
    x, w = first(ins, "Input"), first(ins, "Filter")
    strides = attrs.get("strides", [1, 1])
    dilations = attrs.get("dilations", [1, 1])
    groups = attrs.get("groups", 1)
    layout = attrs.get("data_format", "NCHW")
    if layout == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        spatial = x.shape[1:3]
    else:
        dn = ("NCHW", "OIHW", "NCHW")
        spatial = x.shape[2:4]
    ksize = w.shape[2:4] if layout == "NCHW" else w.shape[0:2]
    padding = normalize_padding(attrs, 2, ksize, strides, spatial)
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(strides),
        padding=padding,
        rhs_dilation=tuple(dilations),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None,
    )
    return {"Output": [out]}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ins, attrs):
    attrs = dict(attrs)
    x = first(ins, "Input")
    channels = x.shape[1] if attrs.get("data_format", "NCHW") == "NCHW" else x.shape[-1]
    attrs["groups"] = channels
    return {"Output": _conv2d(ins, attrs)["Output"]}


@register_op("conv2d_transpose")
def _conv2d_transpose(ins, attrs):
    """Transposed conv as an input-dilated forward conv (supports groups,
    which lax.conv_transpose does not). Filter layout follows the reference:
    [in_c, out_c/groups, kh, kw] (reference: paddle/fluid/operators/
    conv_transpose_op.cc)."""
    x, w = first(ins, "Input"), first(ins, "Filter")
    strides = tuple(attrs.get("strides", [1, 1]))
    groups = attrs.get("groups", 1)
    pads = attrs.get("paddings", [0, 0])
    if len(pads) == 2:
        ph, pw = pads
        pads4 = (ph, ph, pw, pw)
    else:
        pads4 = tuple(pads)
    in_c, oc_per_g, kh, kw = w.shape
    # [in_c, out_c/g, kh, kw] -> flipped, grouped OIHW [out_c, in_c/g, kh, kw]
    wf = jnp.flip(w, (2, 3))
    wf = wf.reshape(groups, in_c // groups, oc_per_g, kh, kw)
    wf = jnp.swapaxes(wf, 1, 2).reshape(groups * oc_per_g, in_c // groups, kh, kw)
    padding = (
        (kh - 1 - pads4[0], kh - 1 - pads4[1]),
        (kw - 1 - pads4[2], kw - 1 - pads4[3]),
    )
    out = jax.lax.conv_general_dilated(
        x,
        wf,
        window_strides=(1, 1),
        padding=padding,
        lhs_dilation=strides,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register_op("pool2d")
def _pool2d(ins, attrs):
    """reference: paddle/fluid/operators/pool_op.cc."""
    x = first(ins, "X")
    ptype = attrs.get("pooling_type", "max")
    layout = attrs.get("data_format", "NCHW")
    if layout != "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    spatial = x.shape[2:4]
    if attrs.get("global_pooling", False) or (
        attrs.get("adaptive", False) and list(attrs.get("ksize", [1, 1])) == [1, 1]
    ):
        red = jnp.max if ptype == "max" else jnp.mean
        out = red(x, axis=(2, 3), keepdims=True)
    elif attrs.get("adaptive", False):
        oh, ow = attrs["ksize"]
        red = jnp.max if ptype == "max" else jnp.mean
        # adaptive pooling with uniform regions (exact when divisible)
        n, c, h, wd = x.shape
        out = red(
            x[:, :, : (h // oh) * oh, : (wd // ow) * ow].reshape(
                n, c, oh, h // oh, ow, wd // ow
            ),
            axis=(3, 5),
        )
    else:
        ksize = tuple(attrs.get("ksize", [2, 2]))
        strides = tuple(attrs.get("strides", ksize))
        padding = normalize_padding(attrs, 2, ksize, strides, spatial)
        window = (1, 1) + ksize
        strides4 = (1, 1) + strides
        pads4 = ((0, 0), (0, 0)) + padding
        if ptype == "max":
            out = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, window, strides4, pads4
            )
            out = out.astype(x.dtype)
        else:
            summed = jax.lax.reduce_window(
                x, 0.0, jax.lax.add, window, strides4, pads4
            )
            if attrs.get("exclusive", True) and any(p != (0, 0) for p in padding):
                ones = jnp.ones_like(x)
                counts = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add, window, strides4, pads4
                )
                out = summed / counts
            else:
                out = summed / (ksize[0] * ksize[1])
    if layout != "NCHW":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


@register_op("batch_norm", nondiff_inputs=("Mean", "Variance"))
def _batch_norm(ins, attrs):
    """reference: paddle/fluid/operators/batch_norm_op.cc. Running stats are
    data outputs (MeanOut/VarianceOut), not side effects — functional-state
    threading replaces the reference's in-place variable mutation."""
    x = first(ins, "X")
    scale, bias = first(ins, "Scale"), first(ins, "Bias")
    mean, var = first(ins, "Mean"), first(ins, "Variance")
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    axes = (
        tuple(i for i in range(x.ndim) if i != 1)
        if layout == "NCHW"
        else tuple(range(x.ndim - 1))
    )
    shape = (1, -1) + (1,) * (x.ndim - 2) if layout == "NCHW" else (-1,)
    if attrs.get("is_test", False) or attrs.get("use_global_stats", False):
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = jnp.zeros_like(mean)
        saved_var = jnp.zeros_like(var)
    else:
        compute = x.astype(jnp.float32)
        use_mean = jnp.mean(compute, axis=axes)
        use_var = jnp.var(compute, axis=axes)
        mean_out = momentum * mean + (1.0 - momentum) * use_mean.astype(mean.dtype)
        var_out = momentum * var + (1.0 - momentum) * use_var.astype(var.dtype)
        saved_mean = use_mean
        saved_var = 1.0 / jnp.sqrt(use_var + eps)
    inv = 1.0 / jnp.sqrt(use_var.astype(jnp.float32) + eps)
    y = (x.astype(jnp.float32) - use_mean.reshape(shape)) * inv.reshape(shape)
    y = y * scale.reshape(shape) + bias.reshape(shape)
    return {
        "Y": [y.astype(x.dtype)],
        "MeanOut": [mean_out],
        "VarianceOut": [var_out],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


@register_op("layer_norm")
def _layer_norm(ins, attrs):
    x = first(ins, "X")
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(begin, x.ndim))
    compute = x.astype(jnp.float32)
    mean = jnp.mean(compute, axis=axes, keepdims=True)
    var = jnp.var(compute, axis=axes, keepdims=True)
    y = (compute - mean) / jnp.sqrt(var + eps)
    scale, bias = maybe(ins, "Scale"), maybe(ins, "Bias")
    norm_shape = x.shape[begin:]
    if scale is not None:
        y = y * scale.reshape(norm_shape).astype(jnp.float32)
    if bias is not None:
        y = y + bias.reshape(norm_shape).astype(jnp.float32)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [jnp.squeeze(mean, axes)],
        "Variance": [jnp.squeeze(var, axes)],
    }


@register_op("instance_norm")
def _instance_norm(ins, attrs):
    x = first(ins, "X")
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    scale, bias = maybe(ins, "Scale"), maybe(ins, "Bias")
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return {"Y": [y], "SavedMean": [mean], "SavedVariance": [var]}


@register_op("group_norm")
def _group_norm(ins, attrs):
    x = first(ins, "X")
    groups = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    g = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    y = ((g - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    scale, bias = maybe(ins, "Scale"), maybe(ins, "Bias")
    shape = (1, -1) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    return {"Y": [y], "Mean": [mean.reshape(n, groups)], "Variance": [var.reshape(n, groups)]}


# ---------------------------------------------------------------------------
# dropout (stateful: consumes the executor-provided rng key)
# ---------------------------------------------------------------------------


@register_op("dropout", stateful=True)
def _dropout(ins, attrs):
    """reference: paddle/fluid/operators/dropout_op.cc. Both implementations
    of the reference are supported; mask is a saved output consumed by the
    custom grad (so backward reuses the forward mask instead of re-sampling)."""
    x = first(ins, "X")
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        return {"Out": [out], "Mask": [jnp.ones_like(x)]}
    from paddle_tpu.ops.common import seeded_rng_key

    key = seeded_rng_key(ins, attrs)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    else:
        out = x * mask
    return {"Out": [out], "Mask": [mask]}


@register_grad("dropout")
def _dropout_grad(ins, attrs):
    dout = first(ins, "Out@GRAD")
    mask = first(ins, "Mask")
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        dx = dout if impl == "upscale_in_train" else dout * (1.0 - p)
    elif impl == "upscale_in_train":
        dx = dout * mask / (1.0 - p)
    else:
        dx = dout * mask
    return {"X@GRAD": [dx]}


# ---------------------------------------------------------------------------
# embedding
# ---------------------------------------------------------------------------


@register_op("lookup_table_v2", nondiff_inputs=("Ids",))
def _lookup_table(ins, attrs):
    """reference: paddle/fluid/operators/lookup_table_op.cc. Dense gather on
    TPU; the billion-feature sparse path lives in the PS stack instead
    (SelectedRows grads are a host-side concern there)."""
    w, ids = first(ins, "W"), first(ins, "Ids")
    out = jnp.take(w, ids, axis=0)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return {"Out": [out]}


@register_op("lookup_table", nondiff_inputs=("Ids",))
def _lookup_table_v1(ins, attrs):
    w, ids = first(ins, "W"), first(ins, "Ids")
    if ids.ndim == 2 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    return _lookup_table({"W": [w], "Ids": [ids]}, attrs)


@register_op("lookup_table_ps", nondiff_inputs=("Idx",))
def _lookup_table_ps(ins, attrs):
    """PS-backed embedding lookup: `Rows` are the batch's unique embedding
    vectors pulled from the parameter server by the worker (host side,
    fleet/parameter_server.py), `Idx` maps each id occurrence to its row.
    The gather's vjp sums duplicate-id grads into per-row grads — exactly
    the SelectedRows grad aggregation the reference does in
    lookup_table_grad (reference: paddle/fluid/operators/lookup_table_op.h
    LookupTableGradKernel) but expressed as dense XLA."""
    rows, idx = first(ins, "Rows"), first(ins, "Idx")
    return {"Out": [jnp.take(rows, idx, axis=0)]}


def _sdpa_seq_parallel(ins, attrs):
    """Sequence-parallel route: when the op carries seq_parallel='ring' |
    'ulysses' and the active mesh (CompiledProgram.with_parallel) has the
    named seq axis >1, attention runs sequence-sharded — ring rotation via
    ppermute or Ulysses head-scatter all_to_alls (parallel/ring.py,
    parallel/ulysses.py). Returns None when the plain single-shard path
    should run (no mesh, axis absent/size 1). SURVEY §5.7 IR-path form."""
    mode = attrs.get("seq_parallel")
    if not mode:
        return None
    from paddle_tpu.parallel import env as penv

    mesh = penv.current_mesh()
    axis = attrs.get("seq_axis", "seq")
    if mesh is None or axis not in mesh.axis_names:
        return None
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if sizes.get(axis, 1) <= 1:
        return None
    q, k, v = first(ins, "Q"), first(ins, "K"), first(ins, "V")
    if vma_names(q):
        raise EnforceError(
            "seq_parallel scaled_dot_product_attention cannot run inside an "
            "already-manual region (e.g. a pipeline_stack body); shard the "
            "sequence axis on the outer program instead"
        )
    if ins.get("Bias"):
        raise EnforceError(
            "seq_parallel scaled_dot_product_attention does not take Bias; "
            "fold padding into the sequence instead"
        )
    causal = attrs.get("causal", False)
    scale = attrs.get("sm_scale")
    if mode == "ring":
        from paddle_tpu.parallel.ring import ring_attention

        out = ring_attention(q, k, v, mesh, seq_axis=axis, causal=causal,
                             scale=scale, batch_axis="data")
    elif mode == "ulysses":
        from paddle_tpu.parallel.ulysses import ulysses_attention

        out = ulysses_attention(q, k, v, mesh, seq_axis=axis, causal=causal,
                                scale=scale, batch_axis="data")
    else:
        raise EnforceError(
            f"unknown seq_parallel mode {mode!r} (want 'ring' or 'ulysses')"
        )
    return {"Out": [out]}


def _sdpa_reference(ins, attrs):
    """Unfused attention (XLA-fused path): q,k,v [B,H,S,D], optional additive
    key bias [B,S]."""
    import math as _math

    sp = _sdpa_seq_parallel(ins, attrs)
    if sp is not None:
        return sp
    q, k, v = first(ins, "Q"), first(ins, "K"), first(ins, "V")
    bias = first(ins, "Bias") if ins.get("Bias") else None
    scale = attrs.get("sm_scale") or 1.0 / _math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias[:, None, None, :]
    if attrs.get("causal", False):
        S = q.shape[2]
        m = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(m[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return {"Out": [jnp.einsum("bhqk,bhkd->bhqd", p, v)]}


def _sdpa_pallas(ins, attrs):
    from paddle_tpu.kernels import registry as kernel_registry
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    sp = _sdpa_seq_parallel(ins, attrs)
    if sp is not None:
        return sp
    sel = kernel_registry.selected("flash_attention")
    if sel is None:
        # composite fallback is mandatory: PADDLE_TPU_KERNELS=off, or
        # auto off-TPU (interpret mode is a parity tool, not a fast path)
        return _sdpa_reference(ins, attrs)
    q, k, v = first(ins, "Q"), first(ins, "K"), first(ins, "V")
    bias = first(ins, "Bias") if ins.get("Bias") else None
    return {
        "Out": [
            flash_attention(
                q, k, v, bias=bias,
                causal=attrs.get("causal", False),
                sm_scale=attrs.get("sm_scale"),
                interpret=sel.interpret,
            )
        ]
    }


OpRegistry.register(
    OpDef(
        "scaled_dot_product_attention",
        _sdpa_reference,
        pallas=_sdpa_pallas,
        nondiff_inputs=(),
    )
)


# ---------------------------------------------------------------------------
# fused decode attention (paddle_tpu/kernels/): cached (dense slotted) and
# paged (block-arena row feeds). The reference lowerings ARE the composite
# primitive sequences the old layer composites emitted — bit-identity
# between kernel-on and kernel-off paths is by shared definition
# (kernels/attention.py), not by test luck.
# ---------------------------------------------------------------------------


def _cached_attention_reference(ins, attrs):
    from paddle_tpu.kernels import attention as fused

    q, k, v = first(ins, "Q"), first(ins, "KCache"), first(ins, "VCache")
    bias = first(ins, "Bias")
    return {"Out": [fused.cached_attention_composite(
        q, k, v, bias, attrs.get("sm_scale", 1.0))]}


def _cached_attention_pallas(ins, attrs):
    from paddle_tpu.kernels import attention as fused
    from paddle_tpu.kernels import registry as kernel_registry

    sel = kernel_registry.selected("cached_attention")
    if sel is None:
        return _cached_attention_reference(ins, attrs)
    q, k, v = first(ins, "Q"), first(ins, "KCache"), first(ins, "VCache")
    bias = first(ins, "Bias")
    return {"Out": [fused.decode_attention(
        q, k, v, bias, attrs.get("sm_scale", 1.0),
        interpret=sel.interpret)]}


OpRegistry.register(
    OpDef(
        "cached_attention",
        _cached_attention_reference,
        pallas=_cached_attention_pallas,
        nondiff_inputs=("Bias",),
    )
)


def _paged_attention_reference(ins, attrs):
    from paddle_tpu.kernels import attention as fused

    q = first(ins, "Q")
    ka, va = first(ins, "KArena"), first(ins, "VArena")
    rows, bias = first(ins, "Rows"), first(ins, "Bias")
    return {"Out": [fused.paged_attention_composite(
        q, ka, va, rows, bias, attrs["seqs"], attrs["length"],
        attrs.get("sm_scale", 1.0))]}


def _paged_attention_pallas(ins, attrs):
    from paddle_tpu.kernels import attention as fused
    from paddle_tpu.kernels import registry as kernel_registry

    sel = kernel_registry.selected("paged_attention")
    if sel is None:
        return _paged_attention_reference(ins, attrs)
    q = first(ins, "Q")
    ka, va = first(ins, "KArena"), first(ins, "VArena")
    rows, bias = first(ins, "Rows"), first(ins, "Bias")
    return {"Out": [fused.paged_attention(
        q, ka, va, rows, bias, attrs["seqs"], attrs["length"],
        attrs.get("sm_scale", 1.0), interpret=sel.interpret)]}


OpRegistry.register(
    OpDef(
        "paged_attention",
        _paged_attention_reference,
        pallas=_paged_attention_pallas,
        nondiff_inputs=("Rows", "Bias"),
    )
)


@register_op("one_hot", nondiff_inputs=("X",))
def _one_hot(ins, attrs):
    x = first(ins, "X")
    depth = attrs.get("depth")
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    return {"Out": [jax.nn.one_hot(x, depth, dtype=jnp.float32)]}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register_op("cross_entropy", nondiff_inputs=("Label",))
def _cross_entropy(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    eps = 1e-8
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        if label.ndim == x.ndim:
            label = label[..., 0]
        picked = jnp.take_along_axis(x, label[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(picked + eps)
        ignore = attrs.get("ignore_index", -100)
        loss = jnp.where(label[..., None] == ignore, 0.0, loss)
    return {"Y": [loss]}


@register_op("softmax_with_cross_entropy", nondiff_inputs=("Label",))
def _softmax_with_ce(ins, attrs):
    """reference: paddle/fluid/operators/softmax_with_cross_entropy_op.cu —
    fused, numerically stable via log-sum-exp."""
    logits, label = first(ins, "Logits"), first(ins, "Label")
    axis = attrs.get("axis", -1)
    axis = axis % logits.ndim
    log_probs = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(log_probs)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * log_probs, axis=axis, keepdims=True)
    else:
        # label has a size-1 class axis when its rank matches the logits
        squeezed = (
            jnp.squeeze(label, axis=axis) if label.ndim == logits.ndim else label
        )
        idx = jnp.expand_dims(squeezed.astype(jnp.int32), axis)
        picked = jnp.take_along_axis(log_probs, idx, axis=axis)
        loss = -picked
        ignore = attrs.get("ignore_index", -100)
        loss = jnp.where(jnp.expand_dims(squeezed, axis) == ignore, 0.0, loss)
    return {"Softmax": [softmax], "Loss": [loss]}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ins, attrs):
    x, label = first(ins, "X"), first(ins, "Label")
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    if attrs.get("normalize", False):
        norm = jnp.maximum(jnp.sum(label != ignore).astype(loss.dtype), 1.0)
        loss = loss / norm
    return {"Out": [loss]}


@register_op("square_error_cost")
def _square_error_cost(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    return {"Out": [jnp.square(x - y)]}


@register_op("huber_loss")
def _huber_loss(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    delta = attrs.get("delta", 1.0)
    diff = y - x
    absd = jnp.abs(diff)
    loss = jnp.where(
        absd <= delta, 0.5 * jnp.square(diff), delta * (absd - 0.5 * delta)
    )
    return {"Out": [loss], "Residual": [diff]}


@register_op("smooth_l1_loss")
def _smooth_l1(ins, attrs):
    x, y = first(ins, "X"), first(ins, "Y")
    sigma2 = attrs.get("sigma", 1.0) ** 2
    diff = x - y
    absd = jnp.abs(diff)
    loss = jnp.where(
        absd < 1.0 / sigma2, 0.5 * sigma2 * jnp.square(diff), absd - 0.5 / sigma2
    )
    return {"Out": [jnp.sum(loss, axis=tuple(range(1, x.ndim)), keepdims=False).reshape(-1, 1)], "Diff": [diff]}


@register_op("kldiv_loss")
def _kldiv_loss(ins, attrs):
    x, target = first(ins, "X"), first(ins, "Target")
    loss = target * (jnp.log(jnp.maximum(target, 1e-10)) - x)
    reduction = attrs.get("reduction", "mean")
    if reduction == "mean":
        loss = jnp.mean(loss).reshape((1,))
    elif reduction == "sum":
        loss = jnp.sum(loss).reshape((1,))
    elif reduction == "batchmean":
        loss = (jnp.sum(loss) / x.shape[0]).reshape((1,))
    return {"Loss": [loss]}


# ---------------------------------------------------------------------------
# metrics (reference: paddle/fluid/operators/metrics/)
# ---------------------------------------------------------------------------


@register_op("accuracy", nondiff_inputs=("Out", "Indices", "Label"))
def _accuracy(ins, attrs):
    idx, label = first(ins, "Indices"), first(ins, "Label")
    if label.ndim == 1:
        label = label[:, None]
    correct = jnp.any(idx == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(idx.shape[0], jnp.float32)
    return {
        "Accuracy": [(num_correct / total).reshape((1,))],
        "Correct": [num_correct.astype(jnp.int32).reshape((1,))],
        "Total": [jnp.asarray([idx.shape[0]], jnp.int32)],
    }


@register_op("auc", nondiff_inputs=("Predict", "Label"))
def _auc(ins, attrs):
    """Streaming AUC via fixed histogram buckets
    (reference: paddle/fluid/operators/metrics/auc_op.cc)."""
    pred, label = first(ins, "Predict"), first(ins, "Label")
    stat_pos, stat_neg = first(ins, "StatPos"), first(ins, "StatNeg")
    num_thresholds = attrs.get("num_thresholds", 4095)
    pos_score = pred[:, -1] if pred.ndim == 2 else pred
    bucket = jnp.clip(
        (pos_score * num_thresholds).astype(jnp.int64), 0, num_thresholds
    )
    lab = label.reshape(-1).astype(jnp.int64)
    pos_inc = jnp.zeros_like(stat_pos).at[bucket].add(lab)
    neg_inc = jnp.zeros_like(stat_neg).at[bucket].add(1 - lab)
    new_pos = stat_pos + pos_inc
    new_neg = stat_neg + neg_inc
    # integrate trapezoid over descending threshold
    tp = jnp.cumsum(new_pos[::-1])
    fp = jnp.cumsum(new_neg[::-1])
    total_pos, total_neg = tp[-1], fp[-1]
    tpr = tp / jnp.maximum(total_pos, 1)
    fpr = fp / jnp.maximum(total_neg, 1)
    auc = jnp.trapezoid(tpr, fpr)
    return {
        "AUC": [auc.reshape((1,))],
        "StatPosOut": [new_pos],
        "StatNegOut": [new_neg],
    }
