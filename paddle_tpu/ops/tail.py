"""Registry tail: the last applicable reference ops (VERDICT r4 item 6).

reference: paddle/fluid/operators/{pyramid_hash_op.cc, split_selected_rows_op.cc,
requantize_op.cc, coalesce_tensor_op.cc, controlflow/select_input_output_op.cc,
cudnn_lstm_op.cc, pull_box_sparse_op.cc, save_op.cc, load_op.cc,
save_combine_op.cc, load_combine_op.cc, controlflow/tensor_array_read_write.cc,
lod_tensor_to_array_op.cc, array_to_lod_tensor_op.cc, merge_lod_tensor_op.cc}.

Design notes:
* TensorArray ops exist behind the reference names with DENSE semantics: the
  array value is a Python tuple of tensors threaded through the env; indices
  must be trace-time concrete (constants, unrolled loops) — a data-dependent
  index raises with guidance (the lax.while path cannot grow stacks).
* save/load ops persist through io.py's combined npz format (ordinal keys) —
  functionally equivalent to the reference's save/load ops, not
  byte-compatible with its protobuf tensor format.
* pull/push_box_sparse map BoxPS onto the remote-lookup context
  (distributed/lookup.py) — the table lives on the PS, pulled in-step.
* pyramid_hash keeps the reference's structure (n-gram windows hashed into a
  1-D weight space, rand_len chunks concatenated to num_emb) on padded
  [B, S] + Length inputs; the hash is FNV-1a rather than XXH32 (learned
  weights make the hash family immaterial — only determinism matters).
"""

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import OpDef, OpRegistry, register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError, enforce

_TARRAY = "__tensor_array__"


def _concrete_index(i, attrs, op_name):
    if attrs.get("static_index") is not None:
        # build-time constant folded in by layers.array_write/array_read
        # (inside jit even a fill_constant output is an abstract tracer)
        return int(attrs["static_index"])
    try:
        arr = np.asarray(i)
    except Exception:
        raise EnforceError(
            f"{op_name}: the array index must be trace-time concrete (a "
            "constant or an unrolled Python loop counter). Data-dependent "
            "TensorArray indexing cannot compile to static shapes — use "
            "dense stacking (layers.stack / layers.gather) or lax-style "
            "carried state instead"
        ) from None
    return int(arr.reshape(-1)[0])


def _as_array_val(v):
    if isinstance(v, tuple) and len(v) == 2 and v[0] is _TARRAY:
        return list(v[1])
    return None


@register_op("write_to_array", nondiff_inputs=("I",))
def _write_to_array(ins, attrs):
    x, i = first(ins, "X"), first(ins, "I")
    idx = _concrete_index(i, attrs, "write_to_array")
    existing = maybe(ins, "Array")
    prev = _as_array_val(existing)
    enforce(
        existing is None or prev is not None,
        "write_to_array: Array input is not a TensorArray (pass the "
        "output of a previous array_write, not a plain tensor)",
    )
    prev = list(prev) if prev is not None else []
    while len(prev) <= idx:
        prev.append(None)
    prev[idx] = x
    return {"Out": [(_TARRAY, tuple(prev))]}


@register_op("read_from_array", nondiff_inputs=("I",))
def _read_from_array(ins, attrs):
    arr, i = first(ins, "X"), first(ins, "I")
    vals = _as_array_val(arr)
    enforce(vals is not None, "read_from_array: X is not a TensorArray")
    idx = _concrete_index(i, attrs, "read_from_array")
    enforce(
        0 <= idx < len(vals) and vals[idx] is not None,
        f"read_from_array: index {idx} not written (array has {len(vals)})",
    )
    return {"Out": [vals[idx]]}


@register_op("lod_tensor_to_array")
def _lod_tensor_to_array(ins, attrs):
    """Dense analog: unstack axis 0 into a TensorArray (the reference
    splits by rank table for DynamicRNN; padded tensors make the per-step
    split a plain unstack)."""
    x = first(ins, "X")
    return {"Out": [(_TARRAY, tuple(x[t] for t in range(x.shape[0])))]}


@register_op("array_to_lod_tensor")
def _array_to_lod_tensor(ins, attrs):
    vals = _as_array_val(first(ins, "X"))
    enforce(vals is not None, "array_to_lod_tensor: X is not a TensorArray")
    enforce(
        all(v is not None for v in vals),
        "array_to_lod_tensor: array has unwritten slots",
    )
    return {"Out": [jnp.stack(list(vals))]}


def _lod_refusal(name):
    def lower(ins, attrs):
        raise EnforceError(
            f"{name} splits/merges rows by a runtime boolean mask — "
            "dynamic row counts cannot compile to static shapes on TPU. "
            "Use layers.cond (both-branch select) or a masked `where` over "
            "the full batch instead (SURVEY §5.7 LoD rule)."
        )

    OpRegistry.register(OpDef(name, lower))


_lod_refusal("split_lod_tensor")
_lod_refusal("merge_lod_tensor")


@register_op("select_input", nondiff_inputs=("Mask",))
def _select_input(ins, attrs):
    """reference: controlflow/select_input_output_op.cc — Out = X[mask].
    All branch tensors must share shape/dtype (static-shape contract). A
    concrete out-of-range mask raises; a traced one clamps to the last
    branch (a data-dependent branch index cannot be validated in-graph)."""
    xs, mask = ins["X"], first(ins, "Mask")
    shapes = {tuple(x.shape) for x in xs}
    enforce(
        len(shapes) == 1,
        f"select_input: branch shapes differ {sorted(shapes)} — a traced "
        "select needs identical shapes (pad or restructure)",
    )
    if not isinstance(mask, jax.core.Tracer):
        m = int(np.asarray(mask).reshape(-1)[0])
        enforce(
            0 <= m < len(xs),
            f"select_input: mask {m} out of range for {len(xs)} branches",
        )
    idx = jnp.clip(mask.reshape(()).astype(jnp.int32), 0, len(xs) - 1)
    return {"Out": [jnp.stack(list(xs))[idx]]}


@register_op("select_output", nondiff_inputs=("Mask",),
             needs_out_counts=True)
def _select_output(ins, attrs):
    """Out[i] = X when i == mask else zeros — the dense form of routing
    one value to the mask-th branch (consumers pair it with select_input
    on the same mask). Output arity comes from the op desc
    (__out_counts__, injected by the executor)."""
    x, mask = first(ins, "X"), first(ins, "Mask")
    idx = mask.reshape(()).astype(jnp.int32)
    counts = attrs.get("__out_counts__") or {}
    n_out = int(counts.get("Out", attrs.get("n_out", 2)))
    outs = [jnp.where(idx == i, x, jnp.zeros_like(x)) for i in range(n_out)]
    return {"Out": outs}


@register_op("split_selected_rows")
def _split_selected_rows(ins, attrs):
    """reference: split_selected_rows_op.cc — rows split by
    height_sections. Dense form: split axis 0 into the given sections."""
    x = first(ins, "X")
    sections = attrs.get("height_sections", [])
    enforce(sections, "split_selected_rows needs height_sections")
    enforce(
        sum(sections) == x.shape[0],
        f"height_sections {sections} must sum to rows {x.shape[0]}",
    )
    outs, off = [], 0
    for s in sections:
        outs.append(x[off:off + s])
        off += s
    return {"Out": outs}


@register_op("requantize", nondiff_inputs=("Input",))
def _requantize(ins, attrs):
    """reference: requantize_op.cc (int8 deploy) — rescale a quantized
    tensor between scale domains: round(x * scale_out / scale_in)."""
    x = first(ins, "Input").astype(jnp.float32)
    s_in = attrs.get("Scale_in", 1.0)
    s_out = attrs.get("Scale_out", 1.0)
    return {"Output": [jnp.round(x * (s_out / s_in))]}


@register_op("coalesce_tensor")
def _coalesce_tensor(ins, attrs):
    """reference: coalesce_tensor_op.cc — fuse tensors into one contiguous
    buffer for batched collectives/optimizer sweeps. XLA owns real memory
    layout, so the semantic survives as: FusedOutput = concat of flattened
    inputs (alignment-free), Output[i] = the matching view."""
    xs = ins["Input"]
    dtype = xs[0].dtype
    if attrs.get("set_constant"):
        c = attrs.get("constant", 0.0)
        outs = [jnp.full(x.shape, c, dtype) for x in xs]
        fused = jnp.full((sum(int(np.prod(x.shape)) for x in xs),), c, dtype)
        return {"Output": outs, "FusedOutput": [fused]}
    fused = jnp.concatenate([x.reshape(-1) for x in xs])
    return {"Output": list(xs), "FusedOutput": [fused]}


def _cudnn_lstm_lower(ins, attrs):
    if ins.get("W"):
        raise EnforceError(
            "cudnn_lstm with a packed opaque W blob is a cuDNN memory "
            "layout; this build takes per-layer weight lists (WeightIh/"
            "WeightHh/Bias) on the `lstm` op — same capability, "
            "transparent layout (ops/rnn.py lstm)"
        )
    return OpRegistry.get("lstm").lowering()(ins, attrs)


OpRegistry.register(
    OpDef("cudnn_lstm", _cudnn_lstm_lower, nondiff_inputs=("SequenceLength",))
)


# ---------------------------------------------------------------------------
# BoxPS sparse pull/push -> remote-lookup context
# ---------------------------------------------------------------------------


@register_op("pull_box_sparse", nondiff_inputs=("Ids",))
def _pull_box_sparse(ins, attrs):
    """reference: pull_box_sparse_op.cc (Baidu AIBox embedding service) —
    each id slot pulls [.., size] rows from the shared box table. Mapped
    onto the remote-lookup context: the table lives on the parameter
    servers, pulled in-step (distributed/lookup.py); without an active
    context the op refuses (no silent local fallback)."""
    import functools

    from jax.experimental import io_callback

    from paddle_tpu.distributed import lookup as _rl

    name = attrs.get("table_name", "__box_sparse__")
    ctx = _rl.active_context()
    if ctx is None or not ctx.has(name):
        raise EnforceError(
            f"pull_box_sparse: no active remote-lookup context for table "
            f"'{name}'. Register the box table on a RemoteLookupContext "
            "(distributed/lookup.py) and activate it, or use "
            "layers.distributed_embedding / layers.sparse_embedding"
        )
    dim = int(attrs["size"])
    outs = []
    for ids in ins["Ids"]:
        idv = ids[..., 0] if (ids.ndim >= 2 and ids.shape[-1] == 1) else ids
        outs.append(
            io_callback(
                functools.partial(_rl.pull_host, name),
                jax.ShapeDtypeStruct(tuple(idv.shape) + (dim,), jnp.float32),
                idv,
                ordered=True,
            )
        )
    return {"Out": outs}


@register_op("push_box_sparse", nondiff_inputs=("Ids",))
def _push_box_sparse(ins, attrs):
    """Backward half of pull_box_sparse: merged row grads to the servers."""
    import functools

    from jax.experimental import io_callback

    from paddle_tpu.distributed import lookup as _rl

    name = attrs.get("table_name", "__box_sparse__")
    ctx = _rl.active_context()
    if ctx is None or not ctx.has(name):
        raise EnforceError(
            f"push_box_sparse: no active remote-lookup context for table "
            f"'{name}' (see pull_box_sparse)"
        )
    grads = ins.get("Out@GRAD") or ins.get("Grad")
    enforce(
        grads is not None and len(grads) == len(ins["Ids"]),
        "push_box_sparse: needs one Grad per Ids slot — an absent grad "
        "would silently drop the update",
    )
    for ids, g in zip(ins["Ids"], grads):
        idv = ids[..., 0] if (ids.ndim >= 2 and ids.shape[-1] == 1) else ids
        io_callback(
            functools.partial(_rl.push_host, name), (), idv, g, ordered=True
        )
    return {}


# ---------------------------------------------------------------------------
# save / load as ops
# ---------------------------------------------------------------------------


def _host_write(path, arrays):
    from paddle_tpu.io import _write_combined

    _write_combined(path, {f"x{i}": np.asarray(a) for i, a in
                           enumerate(arrays)})
    return ()


def _host_write_varargs(path, *arrays):
    # io_callback unpacks its operands into the callback's positionals
    return _host_write(path, list(arrays))


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


@register_op("save", stateful=True)
def _save(ins, attrs):
    """reference: save_op.cc — persist one variable to file_path. Traced
    values write through an ordered host callback; concrete values write
    immediately (startup programs)."""
    import functools

    from jax.experimental import io_callback

    x = first(ins, "X")
    path = attrs["file_path"]
    if _is_traced(x):
        io_callback(functools.partial(_host_write_varargs, path), (), x,
                    ordered=True)
    else:
        _host_write(path, [x])
    return {}


@register_op("save_combine", stateful=True)
def _save_combine(ins, attrs):
    import functools

    from jax.experimental import io_callback

    xs = ins["X"]
    path = attrs["file_path"]
    if any(_is_traced(x) for x in xs):
        io_callback(
            functools.partial(_host_write_varargs, path), (), *xs,
            ordered=True,
        )
    else:
        _host_write(path, list(xs))
    return {}


def _host_read(path):
    import re

    from paddle_tpu.io import _read_combined

    d = _read_combined(path)
    if all(re.fullmatch(r"x\d+", k) for k in d):
        # written by the save/save_combine ops: ordinal order
        return [d[k] for k in sorted(d, key=lambda s: int(s[1:]))]
    # any other combined container (e.g. io.save_params output): values in
    # sorted-name order — deterministic, documented
    return [d[k] for k in sorted(d)]


@register_op("load")
def _load(ins, attrs):
    """reference: load_op.cc — the read happens at trace time (loads run
    in startup/once-off programs; the value becomes a program constant)."""
    vals = _host_read(attrs["file_path"])
    enforce(len(vals) == 1, "load: file holds more than one tensor")
    return {"Out": [jnp.asarray(vals[0])]}


@register_op("load_combine")
def _load_combine(ins, attrs):
    vals = _host_read(attrs["file_path"])
    return {"Out": [jnp.asarray(v) for v in vals]}


# ---------------------------------------------------------------------------
# pyramid_hash
# ---------------------------------------------------------------------------


def _fnv1a(words, salt):
    """Vectorized FNV-1a over the last axis (uint32), salted."""
    h = jnp.full(words.shape[:-1], np.uint32(2166136261 ^ salt),
                 jnp.uint32)
    for k in range(words.shape[-1]):
        h = (h ^ words[..., k].astype(jnp.uint32)) * np.uint32(16777619)
    return h


@register_op("pyramid_hash", nondiff_inputs=("X", "Length"),
             stateful=True)
def _pyramid_hash(ins, attrs):
    """reference: pyramid_hash_op.cc — every n-gram window (n = 2 ..
    pyramid_layer) of the id sequence hashes into a 1-D weight space;
    num_emb/rand_len chunks of rand_len weights concatenate into the term
    embedding. Padded form: X [B, S] + Length [B] -> Out [B, P, num_emb]
    with P = sum over layers of (S - n + 1); DropPos [B, P] marks live
    terms (window inside the sequence, surviving train-time term dropout).
    Padded-out rows are zero."""
    x = first(ins, "X")
    if x.ndim >= 3 and x.shape[-1] == 1:
        x = x[..., 0]
    lengths = maybe(ins, "Length")
    w = first(ins, "W").reshape(-1)  # [space_len + rand_len]
    num_emb = int(attrs["num_emb"])
    rand_len = int(attrs["rand_len"])
    space_len = int(attrs["space_len"])
    pyramid_layer = int(attrs.get("pyramid_layer", 2))
    drop_p = float(attrs.get("drop_out_percent", 0.0))
    training = bool(attrs.get("is_training", 0))
    enforce(num_emb % rand_len == 0,
            "pyramid_hash: num_emb must be a multiple of rand_len")
    B, S = x.shape
    if lengths is None:
        lengths = jnp.full((B,), S, jnp.int32)
    lengths = lengths.reshape(-1).astype(jnp.int32)
    chunks = num_emb // rand_len
    outs, masks = [], []
    for ilayer in range(1, pyramid_layer):
        n = ilayer + 1
        if n > S:
            break
        win = jnp.arange(S - n + 1)[:, None] + jnp.arange(n)[None]
        words = x[:, win]  # [B, S-n+1, n]
        valid = (jnp.arange(S - n + 1)[None] + n) <= lengths[:, None]
        parts = []
        for j in range(chunks):
            pos = _fnv1a(words, salt=j * 2654435761 % (1 << 32)) % space_len
            gather = pos[..., None] + jnp.arange(rand_len)[None, None]
            parts.append(w[gather])  # [B, S-n+1, rand_len]
        emb = jnp.concatenate(parts, axis=-1)
        outs.append(emb)
        masks.append(valid)
    enforce(outs, "pyramid_hash: sequence too short for any window")
    out = jnp.concatenate(outs, axis=1)
    mask = jnp.concatenate(masks, axis=1)
    if training and drop_p > 0.0 and "__rng_key__" in ins:
        keep = jax.random.uniform(ins["__rng_key__"][0], mask.shape) >= drop_p
        mask = mask & keep
    out = out * mask[..., None].astype(out.dtype)
    return {"Out": [out], "DropPos": [mask.astype(jnp.int32)]}
