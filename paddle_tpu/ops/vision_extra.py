"""Fourth-tranche vision/detection ops: deformable convolutions,
position-sensitive RoI pooling, FPN proposal routing, proposal generation,
extra NMS variants.

reference: paddle/fluid/operators/{deformable_conv_op.cu,
deformable_conv_v1_op.cu, deformable_psroi_pooling_op.cu, psroi_pool_op.h,
prroi_pool_op.h, detection/density_prior_box_op.cc,
detection/distribute_fpn_proposals_op.cc,
detection/collect_fpn_proposals_op.cc, detection/generate_proposals_op.cc,
detection/multiclass_nms_op.cc (nms2), detection/locality_aware_nms_op.cc,
detection/retinanet_detection_output_op.cc, random_crop_op.h,
similarity_focus_op.h}. TPU-native redesign: per-thread CUDA loops become
fixed-shape vectorized gathers (bilinear taps as static kernel-position
loops), LoD roi batching becomes explicit RoisNum/BatchId tensors, and
variable-length outputs become fixed slates with counts — the same design
rules as ops/vision.py and ops/detection.py.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.ops.vision import _bilinear_gather, _roi_batch_ids
from paddle_tpu.utils.enforce import EnforceError

_NEG = -1e30


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------


def _deform_sample(x, offset, mask, kh, kw, stride, pad, dilation, dg):
    """Gather bilinear-sampled deformed patches.

    x [N, C, H, W]; offset [N, 2*dg*kh*kw, Ho, Wo] (y then x per tap, per
    deformable group); mask [N, dg*kh*kw, Ho, Wo] or None (v1).
    Returns patches [N, C, kh*kw, Ho, Wo]."""
    N, C, H, W = x.shape
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilation
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cg = C // dg  # channels per deformable group
    base_y = jnp.arange(Ho) * sh - ph
    base_x = jnp.arange(Wo) * sw - pw
    off = offset.reshape(N, dg, kh * kw, 2, Ho, Wo)
    if mask is not None:
        m = mask.reshape(N, dg, kh * kw, Ho, Wo)
    parts = []
    bi = jnp.arange(N, dtype=jnp.int32)
    for g in range(dg):
        xg = x[:, g * cg:(g + 1) * cg]  # [N, cg, H, W]
        taps = []
        for t in range(kh * kw):
            i, j = t // kw, t % kw
            ys = base_y[None, :, None] + i * dh + off[:, g, t, 0]  # [N,Ho,Wo]
            xs = base_x[None, None, :] + j * dw + off[:, g, t, 1]
            # zero-pad out-of-bounds (reference DmcnIm2colBilinear)
            samp = _bilinear_gather(
                xg, bi, ys.reshape(N, -1), xs.reshape(N, -1)
            )  # [N, Ho*Wo, cg]
            samp = jnp.transpose(samp, (0, 2, 1)).reshape(N, cg, Ho, Wo)
            if mask is not None:
                samp = samp * m[:, g, t][:, None]
            taps.append(samp)
        parts.append(jnp.stack(taps, axis=2))  # [N, cg, k, Ho, Wo]
    return jnp.concatenate(parts, axis=1), Ho, Wo


def _deformable_conv_impl(ins, attrs, modulated):
    x = first(ins, "Input")
    offset = first(ins, "Offset")
    w = first(ins, "Filter")  # [Co, C/groups, kh, kw]
    mask = first(ins, "Mask") if (modulated and ins.get("Mask")) else None
    stride = tuple(attrs.get("strides", [1, 1]))
    pad = tuple(attrs.get("paddings", [0, 0]))
    dil = tuple(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    dg = attrs.get("deformable_groups", 1)
    Co, Cpg, kh, kw = w.shape
    N, C, H, W = x.shape
    patches, Ho, Wo = _deform_sample(x, offset, mask, kh, kw, stride, pad,
                                     dil, dg)
    # patches [N, C, k, Ho, Wo] x w [Co, C/groups, kh*kw] -> [N, Co, Ho, Wo]
    wf = w.reshape(Co, Cpg, kh * kw)
    if groups == 1:
        out = jnp.einsum(
            "nckp,ock->nop",
            patches.reshape(N, C, kh * kw, Ho * Wo),
            wf,
        )
    else:
        cg = C // groups
        og = Co // groups
        outs = []
        for g in range(groups):
            outs.append(jnp.einsum(
                "nckp,ock->nop",
                patches[:, g * cg:(g + 1) * cg].reshape(
                    N, cg, kh * kw, Ho * Wo
                ),
                wf[g * og:(g + 1) * og],
            ))
        out = jnp.concatenate(outs, axis=1)
    return {"Output": [out.reshape(N, Co, Ho, Wo)]}


@register_op("deformable_conv", nondiff_inputs=())
def _deformable_conv(ins, attrs):
    """reference: paddle/fluid/operators/deformable_conv_op.cu — modulated
    deformable conv v2 (offsets + multiplicative mask per tap)."""
    return _deformable_conv_impl(ins, attrs, modulated=True)


@register_op("deformable_conv_v1", nondiff_inputs=())
def _deformable_conv_v1(ins, attrs):
    """reference: paddle/fluid/operators/deformable_conv_v1_op.cu — DCN v1
    (offsets only)."""
    return _deformable_conv_impl(ins, attrs, modulated=False)


# ---------------------------------------------------------------------------
# position-sensitive / precise RoI pooling
# ---------------------------------------------------------------------------


@register_op("psroi_pool", nondiff_inputs=("ROIs", "RoisNum", "BatchId"))
def _psroi_pool(ins, attrs):
    """reference: paddle/fluid/operators/psroi_pool_op.h — position-
    sensitive average pooling: output channel c at bin (ph, pw) pools
    INPUT channel c*PH*PW + ph*PW + pw over that bin. Fixed per-bin pixel
    bounds with masking, as ops/vision.py roi_pool does."""
    x = first(ins, "X")
    rois = first(ins, "ROIs")
    R = rois.shape[0]
    C, H, W = x.shape[1], x.shape[2], x.shape[3]
    PH = attrs.get("pooled_height", 1)
    PW = attrs.get("pooled_width", 1)
    oc = attrs.get("output_channels", C // (PH * PW))
    scale = attrs.get("spatial_scale", 1.0)
    if oc * PH * PW != C:
        raise EnforceError(
            f"psroi_pool: input channels {C} != output_channels {oc} * "
            f"{PH} * {PW}"
        )
    bi = _roi_batch_ids(ins, R)
    x1 = jnp.round(rois[:, 0]) * scale
    y1 = jnp.round(rois[:, 1]) * scale
    x2 = (jnp.round(rois[:, 2]) + 1.0) * scale
    y2 = (jnp.round(rois[:, 3]) + 1.0) * scale
    rh = jnp.maximum(y2 - y1, 0.1)
    rw = jnp.maximum(x2 - x1, 0.1)
    bin_h = rh / PH
    bin_w = rw / PW
    mh = -(-H // PH) + 2  # static per-bin bound
    mw = -(-W // PW) + 2

    ib = jnp.arange(PH)[None, :]
    h_lo = jnp.floor(y1[:, None] + ib * bin_h[:, None]).astype(jnp.int32)
    h_hi = jnp.ceil(y1[:, None] + (ib + 1) * bin_h[:, None]).astype(jnp.int32)
    jb = jnp.arange(PW)[None, :]
    w_lo = jnp.floor(x1[:, None] + jb * bin_w[:, None]).astype(jnp.int32)
    w_hi = jnp.ceil(x1[:, None] + (jb + 1) * bin_w[:, None]).astype(jnp.int32)
    h_lo = jnp.clip(h_lo, 0, H)
    h_hi = jnp.clip(h_hi, 0, H)
    w_lo = jnp.clip(w_lo, 0, W)
    w_hi = jnp.clip(w_hi, 0, W)

    hr = h_lo[:, :, None] + jnp.arange(mh)[None, None, :]   # [R, PH, mh]
    wr = w_lo[:, :, None] + jnp.arange(mw)[None, None, :]   # [R, PW, mw]
    hmask = hr < h_hi[:, :, None]
    wmask = wr < w_hi[:, :, None]
    hc = jnp.clip(hr, 0, H - 1)
    wc = jnp.clip(wr, 0, W - 1)

    xr = x.reshape(x.shape[0], oc, PH, PW, H, W)
    b_b = jnp.broadcast_to(bi[:, None, None, None, None],
                           (R, PH, mh, PW, mw))
    h_b = jnp.broadcast_to(hc[:, :, :, None, None], (R, PH, mh, PW, mw))
    w_b = jnp.broadcast_to(wc[:, None, None, :, :], (R, PH, mh, PW, mw))
    ph_b = jnp.broadcast_to(
        jnp.arange(PH)[None, :, None, None, None], (R, PH, mh, PW, mw)
    )
    pw_b = jnp.broadcast_to(
        jnp.arange(PW)[None, None, None, :, None], (R, PH, mh, PW, mw)
    )
    vals = xr[b_b, :, ph_b, pw_b, h_b, w_b]  # [R, PH, mh, PW, mw, oc]
    m = (hmask[:, :, :, None, None] & wmask[:, None, None, :, :])[..., None]
    s = jnp.where(m, vals, 0.0).sum(axis=(2, 4))      # [R, PH, PW, oc]
    cnt = jnp.maximum(m.sum(axis=(2, 4)), 1)
    out = (s / cnt).astype(x.dtype)
    return {"Out": [jnp.transpose(out, (0, 3, 1, 2))]}


@register_op("prroi_pool", nondiff_inputs=("ROIs", "RoisNum", "BatchId"))
def _prroi_pool(ins, attrs):
    """reference: paddle/fluid/operators/prroi_pool_op.h — precise RoI
    pooling (exact integral of the bilinear surface over each bin). TPU
    form: a dense fixed sub-grid of bilinear samples averaged per bin —
    converges to the integral, differentiable everywhere, static shapes
    (the closed-form per-pixel integration of the reference is a
    data-dependent loop XLA cannot tile)."""
    x = first(ins, "X")
    rois = first(ins, "ROIs")
    R = rois.shape[0]
    PH = attrs.get("pooled_height", 1)
    PW = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    s = 4  # sub-samples per bin axis
    bi = _roi_batch_ids(ins, R)
    x1 = rois[:, 0] * scale
    y1 = rois[:, 1] * scale
    x2 = rois[:, 2] * scale
    y2 = rois[:, 3] * scale
    bin_h = jnp.maximum(y2 - y1, 0.0) / PH
    bin_w = jnp.maximum(x2 - x1, 0.0) / PW
    iy = (jnp.arange(PH * s) + 0.5) / s
    ix = (jnp.arange(PW * s) + 0.5) / s
    ys = y1[:, None] + iy[None, :] * bin_h[:, None]
    xs = x1[:, None] + ix[None, :] * bin_w[:, None]
    yy = jnp.broadcast_to(ys[:, :, None], (R, PH * s, PW * s))
    xx = jnp.broadcast_to(xs[:, None, :], (R, PH * s, PW * s))
    sampled = _bilinear_gather(x, bi, yy, xx)  # [R, PH*s, PW*s, C]
    C = x.shape[1]
    out = sampled.reshape(R, PH, s, PW, s, C).mean(axis=(2, 4))
    return {"Out": [jnp.transpose(out, (0, 3, 1, 2))]}


@register_op("deformable_psroi_pooling",
             nondiff_inputs=("ROIs", "RoisNum", "BatchId"))
def _deformable_psroi_pooling(ins, attrs):
    """reference: paddle/fluid/operators/deformable_psroi_pooling_op.cu —
    psroi pooling whose bins shift by learned offsets (Trans input,
    [R, 2, part_h, part_w] scaled by trans_std). no_trans=True degrades to
    plain average psroi with bilinear taps."""
    x = first(ins, "X")
    rois = first(ins, "ROIs")
    trans = maybe(ins, "Trans")
    R = rois.shape[0]
    C = x.shape[1]
    PH = attrs.get("pooled_height", attrs.get("pooled_size", 1))
    PW = attrs.get("pooled_width", attrs.get("pooled_size", 1))
    oc = attrs.get("output_dim", C // (PH * PW))
    scale = attrs.get("spatial_scale", 1.0)
    trans_std = attrs.get("trans_std", 0.1)
    no_trans = attrs.get("no_trans", trans is None)
    sp = attrs.get("sample_per_part", 4)
    bi = _roi_batch_ids(ins, R)
    x1 = jnp.round(rois[:, 0]) * scale - 0.5
    y1 = jnp.round(rois[:, 1]) * scale - 0.5
    x2 = (jnp.round(rois[:, 2]) + 1.0) * scale - 0.5
    y2 = (jnp.round(rois[:, 3]) + 1.0) * scale - 0.5
    rh = jnp.maximum(y2 - y1, 0.1)
    rw = jnp.maximum(x2 - x1, 0.1)
    bin_h = rh / PH
    bin_w = rw / PW
    ph_ids = jnp.arange(PH * PW) // PW
    pw_ids = jnp.arange(PH * PW) % PW
    if not no_trans and trans is not None:
        # trans [R, 2, part_h, part_w]: per-bin offsets in roi-size units;
        # map each pooled bin onto its part cell
        part_h = trans.shape[2]
        part_w = trans.shape[3]
        bh = (ph_ids * part_h // PH).astype(jnp.int32)
        bw = (pw_ids * part_w // PW).astype(jnp.int32)
        off_y = trans[:, 0][:, bh, bw] * trans_std * rh[:, None]
        off_x = trans[:, 1][:, bh, bw] * trans_std * rw[:, None]
    else:
        off_y = jnp.zeros((R, PH * PW))
        off_x = jnp.zeros((R, PH * PW))
    iy = (jnp.arange(sp) + 0.5) / sp
    ys = (y1[:, None, None] + (ph_ids[None, :, None] + iy[None, None, :])
          * bin_h[:, None, None] + off_y[:, :, None])   # [R, PH*PW, sp]
    xs = (x1[:, None, None] + (pw_ids[None, :, None] + iy[None, None, :])
          * bin_w[:, None, None] + off_x[:, :, None])
    yy = jnp.broadcast_to(ys[:, :, :, None], (R, PH * PW, sp, sp))
    xx = jnp.broadcast_to(xs[:, :, None, :], (R, PH * PW, sp, sp))
    sampled = _bilinear_gather(
        x.reshape(x.shape[0], C, x.shape[2], x.shape[3]), bi,
        yy.reshape(R, -1), xx.reshape(R, -1),
    ).reshape(R, PH * PW, sp * sp, C)
    avg = sampled.mean(axis=2)                          # [R, PH*PW, C]
    # position-sensitive channel select: bin (ph, pw) reads channel block
    # c*PH*PW + ph*PW + pw
    avg = avg.reshape(R, PH * PW, oc, PH * PW)
    binids = jnp.arange(PH * PW)
    out = avg[:, binids, :, binids]                     # [PH*PW, R, oc]
    out = jnp.transpose(out, (1, 2, 0)).reshape(R, oc, PH, PW)
    return {"Out": [out.astype(x.dtype)],
            "TopCount": [jnp.full((R, oc, PH, PW), sp * sp, jnp.float32)]}


# ---------------------------------------------------------------------------
# FPN proposal routing
# ---------------------------------------------------------------------------


@register_op("distribute_fpn_proposals", nondiff_inputs=("FpnRois",))
def _distribute_fpn_proposals(ins, attrs):
    """reference: detection/distribute_fpn_proposals_op.cc — route each roi
    to its FPN level by sqrt(area): level = floor(log2(sqrt(wh)/refer_scale
    * refer_level)). Fixed-slate: each level gets an [R, 4] tensor with
    non-member rows zeroed, plus per-level counts and the restore index."""
    rois = first(ins, "FpnRois")  # [R, 4]
    lo = attrs["min_level"]
    hi = attrs["max_level"]
    refer_level = attrs["refer_level"]
    refer_scale = attrs["refer_scale"]
    R = rois.shape[0]
    w = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    h = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    sc = jnp.sqrt(w * h)
    lvl = jnp.floor(jnp.log2(sc / refer_scale + 1e-6)) + refer_level
    lvl = jnp.clip(lvl, lo, hi).astype(jnp.int32)
    outs, counts = [], []
    for l in range(lo, hi + 1):
        m = (lvl == l)
        outs.append(jnp.where(m[:, None], rois, 0.0))
        counts.append(m.sum().astype(jnp.int32))
    # restore contract (reference: concat(level outputs)[restore[i]] ==
    # original roi i): our fixed slates keep every roi at its ORIGINAL row
    # within its level's [R, 4] slate, so the concat position of roi i is
    # (level(i) - lo) * R + i
    restore = ((lvl - lo) * R + jnp.arange(R, dtype=jnp.int32)).reshape(R, 1)
    return {
        "MultiFpnRois": outs,
        "RestoreIndex": [restore],
        "MultiLevelRoIsNum": [jnp.stack(counts)],
    }


@register_op("collect_fpn_proposals",
             nondiff_inputs=("MultiLevelRois", "MultiLevelScores"))
def _collect_fpn_proposals(ins, attrs):
    """reference: detection/collect_fpn_proposals_op.cc — concat per-level
    rois, keep the post_nms_topN by score (fixed slate)."""
    rois = jnp.concatenate(ins["MultiLevelRois"], axis=0)
    scores = jnp.concatenate(
        [s.reshape(-1) for s in ins["MultiLevelScores"]], axis=0
    )
    # zero-padded slate rows (distribute_fpn_proposals' non-member slots)
    # are degenerate boxes — they must not compete with real proposals
    degenerate = (rois[:, 2] <= rois[:, 0]) & (rois[:, 3] <= rois[:, 1])
    scores = jnp.where(degenerate, _NEG, scores)
    k = min(attrs.get("post_nms_topN", 100), scores.shape[0])
    sel = jnp.argsort(-scores)[:k]
    valid = scores[sel] > _NEG / 2
    return {
        "FpnRois": [jnp.where(valid[:, None], rois[sel], 0.0)],
        "RoisNum": [valid.sum().astype(jnp.int32).reshape(1)],
    }


@register_op("generate_proposals",
             nondiff_inputs=("Scores", "BboxDeltas", "ImInfo", "Anchors",
                             "Variances"))
def _generate_proposals(ins, attrs):
    """reference: detection/generate_proposals_op.cc — RPN proposal
    generation: decode anchor deltas, clip to image, filter small boxes,
    greedy NMS, emit post_nms_topN slate (scored, zero-padded). Single
    image per call (B=1 path; vmap for batches upstream)."""
    scores = first(ins, "Scores")       # [N, A, H, W]
    deltas = first(ins, "BboxDeltas")   # [N, 4A, H, W]
    im_info = first(ins, "ImInfo")      # [N, 3]
    anchors = first(ins, "Anchors")     # [H, W, A, 4] or [H*W*A, 4]
    variances = maybe(ins, "Variances")
    pre_n = attrs.get("pre_nms_topN", 6000)
    post_n = attrs.get("post_nms_topN", 1000)
    nms_thresh = attrs.get("nms_thresh", 0.7)
    min_size = attrs.get("min_size", 0.0)
    N = scores.shape[0]
    A = scores.shape[1]
    anc = anchors.reshape(-1, 4)
    var = variances.reshape(-1, 4) if variances is not None else None

    def per_image(sc, dl, info):
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)          # H,W,A order
        d = jnp.transpose(
            dl.reshape(A, 4, sc.shape[1], sc.shape[2]), (2, 3, 0, 1)
        ).reshape(-1, 4)
        # decode (reference BoxCoder decode_center_size)
        aw = anc[:, 2] - anc[:, 0] + 1.0
        ah = anc[:, 3] - anc[:, 1] + 1.0
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        v = var if var is not None else jnp.ones_like(anc)
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        wo = jnp.exp(jnp.minimum(v[:, 2] * d[:, 2], 10.0)) * aw
        ho = jnp.exp(jnp.minimum(v[:, 3] * d[:, 3], 10.0)) * ah
        x1 = cx - wo * 0.5
        y1 = cy - ho * 0.5
        x2 = cx + wo * 0.5 - 1.0
        y2 = cy + ho * 0.5 - 1.0
        # clip to image
        imh, imw = info[0], info[1]
        x1 = jnp.clip(x1, 0.0, imw - 1.0)
        y1 = jnp.clip(y1, 0.0, imh - 1.0)
        x2 = jnp.clip(x2, 0.0, imw - 1.0)
        y2 = jnp.clip(y2, 0.0, imh - 1.0)
        keep = ((x2 - x1 + 1.0) >= min_size) & ((y2 - y1 + 1.0) >= min_size)
        s = jnp.where(keep, s, _NEG)
        k1 = min(pre_n, s.shape[0])
        sel = jnp.argsort(-s)[:k1]
        boxes = jnp.stack([x1, y1, x2, y2], axis=1)[sel]
        ss = s[sel]
        from paddle_tpu.ops.detection import _iou, _nms_single_class

        iou_full = _iou(boxes, boxes)
        ks, ki = _nms_single_class(iou_full, ss, nms_thresh,
                                   min(post_n, k1))
        valid = ks > _NEG / 2
        return (
            jnp.where(valid[:, None], boxes[ki], 0.0),
            jnp.where(valid, ks, 0.0),
            valid.sum().astype(jnp.int32),
        )

    rois, rscores, num = jax.vmap(per_image)(scores, deltas, im_info)
    return {
        "RpnRois": [rois.reshape(-1, 4)],
        "RpnRoiProbs": [rscores.reshape(-1, 1)],
        "RpnRoisNum": [num],
    }


# ---------------------------------------------------------------------------
# NMS variants
# ---------------------------------------------------------------------------


@register_op("multiclass_nms2", nondiff_inputs=("BBoxes", "Scores"))
def _multiclass_nms2(ins, attrs):
    """reference: detection/multiclass_nms_op.cc (nms2 adds the Index
    output — WHICH input boxes survived, so consumers can gather original
    features). Delegates to the fixed-slate multiclass_nms, whose per-class
    slates carry the original box ids; empty slots are -1."""
    from paddle_tpu.ops.detection import _multiclass_nms

    out = _multiclass_nms(ins, attrs)
    return {
        "Out": out["Out"],
        "Index": [out["Index"][0].reshape(-1, 1)],
        "NmsRoisNum": [out["NumDetections"][0].astype(jnp.int32)],
        "NumDetections": out["NumDetections"],
    }


@register_op("locality_aware_nms", nondiff_inputs=("BBoxes", "Scores"))
def _locality_aware_nms(ins, attrs):
    """reference: detection/locality_aware_nms_op.cc (EAST-style OCR):
    first score-weighted-merge boxes with IoU above the threshold into
    their best-scoring representative, then standard multiclass NMS on the
    merged slate."""
    from paddle_tpu.ops.detection import _iou, _multiclass_nms

    bboxes = first(ins, "BBoxes")  # [B, N, 4]
    scores = first(ins, "Scores")  # [B, C, N]
    nms_thresh = attrs.get("nms_threshold", 0.3)

    def merge_one(boxes, sc):
        s = sc.max(axis=0)  # class-max score drives locality merge
        iou = _iou(boxes, boxes)
        near = (iou > nms_thresh).astype(boxes.dtype)
        wsum = near @ s
        merged = (near * s[None, :]) @ boxes / jnp.maximum(wsum, 1e-8)[:, None]
        return merged

    merged = jax.vmap(merge_one)(bboxes, scores)
    return _multiclass_nms(
        {"BBoxes": [merged], "Scores": [scores]}, attrs
    )


@register_op("retinanet_detection_output",
             nondiff_inputs=("BBoxes", "Scores", "Anchors", "ImInfo"))
def _retinanet_detection_output(ins, attrs):
    """reference: detection/retinanet_detection_output_op.cc — decode
    per-level anchor deltas, take per-level top-k by score, then
    multiclass NMS. Inputs here are the already-concatenated levels:
    BBoxes [B, N, 4] deltas, Scores [B, N, C], Anchors [N, 4]."""
    from paddle_tpu.ops.detection import _multiclass_nms

    deltas = first(ins, "BBoxes")
    scores = first(ins, "Scores")     # [B, N, C]
    anchors = first(ins, "Anchors")   # [N, 4]
    im_info = first(ins, "ImInfo")    # [B, 3]
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    cx = deltas[:, :, 0] * aw + acx
    cy = deltas[:, :, 1] * ah + acy
    w = jnp.exp(jnp.minimum(deltas[:, :, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(deltas[:, :, 3], 10.0)) * ah
    x1 = cx - 0.5 * w
    y1 = cy - 0.5 * h
    x2 = cx + 0.5 * w - 1.0
    y2 = cy + 0.5 * h - 1.0
    imh = im_info[:, 0:1]
    imw = im_info[:, 1:2]
    boxes = jnp.stack([
        jnp.clip(x1, 0.0, imw - 1.0),
        jnp.clip(y1, 0.0, imh - 1.0),
        jnp.clip(x2, 0.0, imw - 1.0),
        jnp.clip(y2, 0.0, imh - 1.0),
    ], axis=-1)
    out = _multiclass_nms(
        {"BBoxes": [boxes], "Scores": [jnp.transpose(scores, (0, 2, 1))]},
        {
            "score_threshold": attrs.get("score_threshold", 0.05),
            "nms_threshold": attrs.get("nms_threshold", 0.3),
            "nms_top_k": attrs.get("nms_top_k", 1000),
            "keep_top_k": attrs.get("keep_top_k", 100),
            "background_label": -1,
        },
    )
    return {"Out": out["Out"], "NumDetections": out["NumDetections"]}


# ---------------------------------------------------------------------------
# misc vision
# ---------------------------------------------------------------------------


@register_op("random_crop", stateful=True, nondiff_inputs=("X", "Seed"))
def _random_crop(ins, attrs):
    """reference: paddle/fluid/operators/random_crop_op.h — crop the
    trailing dims to attr `shape` at a uniform random offset."""
    from paddle_tpu.ops.common import seeded_rng_key

    x = first(ins, "X")
    shape = [int(d) for d in attrs["shape"]]
    nd = len(shape)
    key = seeded_rng_key(ins, attrs)
    keys = jax.random.split(key, nd)
    starts = [jnp.asarray(0)] * (x.ndim - nd) + [
        jax.random.randint(
            keys[i], (), 0, x.shape[x.ndim - nd + i] - shape[i] + 1
        )
        for i in range(nd)
    ]
    out = jax.lax.dynamic_slice(
        x, starts, list(x.shape[: x.ndim - nd]) + shape
    )
    return {"Out": [out], "SeedOut": [ins.get("Seed", [jnp.zeros(1)])[0]]}


@register_op("similarity_focus", nondiff_inputs=("X",))
def _similarity_focus(ins, attrs):
    """reference: paddle/fluid/operators/similarity_focus_op.h — for each
    selected channel (axis=1, per `indexes`), mark the (h, w) argmax per
    remaining row/col greedily; TPU form: mark every (h, w) that is the max
    of its row OR its column in the selected channel slice (a vectorized
    over-approximation of the reference's sequential tie-breaking,
    documented deviation)."""
    x = first(ins, "X")  # [N, C, H, W]
    indexes = attrs.get("indexes", [0])
    N, C, H, W = x.shape
    mask = jnp.zeros_like(x)
    for idx in indexes:
        sl = x[:, idx]  # [N, H, W]
        row_max = sl == sl.max(axis=2, keepdims=True)
        col_max = sl == sl.max(axis=1, keepdims=True)
        m = (row_max | col_max).astype(x.dtype)  # [N, H, W]
        mask = jnp.maximum(mask, m[:, None, :, :])
    return {"Out": [mask]}


@register_op("density_prior_box", nondiff_inputs=("Input", "Image"))
def _density_prior_box(ins, attrs):
    """reference: detection/density_prior_box_op.h — density-sampled prior
    boxes: for each feature cell, each (fixed_size, density) pairs with
    each fixed_ratio and tiles density^2 shifted centers. Output
    [H, W, P, 4] normalized + matching variances. All loop bounds are
    static attrs, so the whole grid is one broadcasted computation."""
    feat = first(ins, "Input")
    img = first(ins, "Image")
    H, W = feat.shape[2], feat.shape[3]
    imh, imw = img.shape[2], img.shape[3]
    densities = [int(d) for d in attrs.get("densities", [])]
    fixed_sizes = [float(s) for s in attrs.get("fixed_sizes", [])]
    fixed_ratios = [float(r) for r in attrs.get("fixed_ratios", [1.0])]
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    step_w = attrs.get("step_w", 0.0) or float(imw) / W
    step_h = attrs.get("step_h", 0.0) or float(imh) / H
    step_avg = int((step_w + step_h) * 0.5)

    cx = (jnp.arange(W) + offset) * step_w       # [W]
    cy = (jnp.arange(H) + offset) * step_h       # [H]
    cxg = jnp.broadcast_to(cx[None, :], (H, W))
    cyg = jnp.broadcast_to(cy[:, None], (H, W))
    boxes = []
    for fs, density in zip(fixed_sizes, densities):
        shift = step_avg // density
        for r in fixed_ratios:
            bw = fs * float(np.sqrt(r))
            bh = fs / float(np.sqrt(r))
            base_x = cxg - step_avg / 2.0 + shift / 2.0
            base_y = cyg - step_avg / 2.0 + shift / 2.0
            for di in range(density):
                for dj in range(density):
                    ccx = base_x + dj * shift
                    ccy = base_y + di * shift
                    boxes.append(jnp.stack([
                        jnp.maximum((ccx - bw / 2.0) / imw, 0.0),
                        jnp.maximum((ccy - bh / 2.0) / imh, 0.0),
                        jnp.minimum((ccx + bw / 2.0) / imw, 1.0),
                        jnp.minimum((ccy + bh / 2.0) / imh, 1.0),
                    ], axis=-1))
    out = jnp.stack(boxes, axis=2)               # [H, W, P, 4]
    P = out.shape[2]
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32)[None, None, None, :],
        (H, W, P, 4),
    )
    return {"Boxes": [out.astype(feat.dtype)], "Variances": [var]}


@register_op("target_assign", nondiff_inputs=("MatchIndices", "NegIndices",
                                              "X"))
def _target_assign(ins, attrs):
    """reference: detection/target_assign_op.h — gather per-prior targets
    by match index: out[i, j] = x[i, match[i, j]] where matched, else
    mismatch_value (weight 0). Padded form: X [N, P, K],
    MatchIndices [N, M] (-1 = unmatched)."""
    x = first(ins, "X")
    match = first(ins, "MatchIndices").astype(jnp.int32)
    mismatch = attrs.get("mismatch_value", 0)
    N, M = match.shape
    safe = jnp.clip(match, 0, x.shape[1] - 1)
    rows = jnp.broadcast_to(jnp.arange(N)[:, None], (N, M))
    gathered = x[rows, safe]                      # [N, M, K]
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.asarray(mismatch, x.dtype))
    wt = matched.astype(jnp.float32)
    neg = maybe(ins, "NegIndices")
    if neg is not None:
        # negative priors also get weight 1 (classification background)
        neg = neg.reshape(N, -1).astype(jnp.int32)
        nmask = jnp.zeros((N, M), bool)
        nrows = jnp.broadcast_to(jnp.arange(N)[:, None], neg.shape)
        nvalid = neg >= 0
        nmask = nmask.at[nrows, jnp.clip(neg, 0, M - 1)].max(nvalid)
        wt = jnp.maximum(wt, nmask[..., None].astype(jnp.float32))
    return {"Out": [out], "OutWeight": [wt]}


@register_op("rpn_target_assign", stateful=True,
             nondiff_inputs=("Anchor", "GtBoxes", "IsCrowd", "ImInfo"))
def _rpn_target_assign(ins, attrs):
    """reference: detection/rpn_target_assign_op.cc — label anchors for RPN
    training: positives = best-IoU anchor per gt + anchors with IoU >
    positive_overlap; negatives = IoU < negative_overlap; random subsample
    to rpn_batch_size_per_im at rpn_fg_fraction. Fixed-slate form: outputs
    per-anchor labels [A] (1 fg / 0 bg / -1 ignore) and regression targets
    [A, 4] instead of the reference's compacted index lists."""
    from paddle_tpu.ops.common import seeded_rng_key
    from paddle_tpu.ops.detection import _iou

    anchors = first(ins, "Anchor")                # [A, 4]
    gt = first(ins, "GtBoxes")                    # [G, 4]
    is_crowd = maybe(ins, "IsCrowd")
    pos_thr = attrs.get("rpn_positive_overlap", 0.7)
    neg_thr = attrs.get("rpn_negative_overlap", 0.3)
    batch = attrs.get("rpn_batch_size_per_im", 256)
    fg_frac = attrs.get("rpn_fg_fraction", 0.5)
    A = anchors.shape[0]
    iou = _iou(anchors, gt)                       # [A, G]
    # crowd gts (reference excludes them before matching) and zero-area
    # padded slate rows must not produce matches
    gt_valid = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
    if is_crowd is not None:
        gt_valid = gt_valid & (is_crowd.reshape(-1) == 0)
    iou = jnp.where(gt_valid[None, :], iou, 0.0)
    best_per_anchor = iou.max(axis=1)
    argbest = iou.argmax(axis=1)
    labels = jnp.full((A,), -1, jnp.int32)
    labels = jnp.where(best_per_anchor < neg_thr, 0, labels)
    labels = jnp.where(best_per_anchor >= pos_thr, 1, labels)
    # the best anchor for each gt is positive regardless of threshold —
    # only for gts that actually overlap something (a zero column would
    # otherwise promote EVERY anchor)
    best_per_gt = iou.max(axis=0)                 # [G]
    is_best = (
        (iou == best_per_gt[None, :]) & (best_per_gt[None, :] > 0)
    ).any(axis=1)
    labels = jnp.where(is_best, 1, labels)
    # random subsample: keep at most fg_cap positives / bg_cap negatives
    key = seeded_rng_key(ins, attrs)
    k1, k2 = jax.random.split(key)
    fg_cap = int(batch * fg_frac)
    scores_fg = jnp.where(labels == 1, jax.random.uniform(k1, (A,)), -1.0)
    fg_rank = jnp.argsort(-scores_fg)
    fg_keep = jnp.zeros((A,), bool).at[fg_rank[:fg_cap]].set(True) & (
        labels == 1
    )
    n_fg = fg_keep.sum()
    bg_cap = batch
    scores_bg = jnp.where(labels == 0, jax.random.uniform(k2, (A,)), -1.0)
    bg_rank = jnp.argsort(-scores_bg)
    bg_pos = jnp.arange(A) < jnp.maximum(bg_cap - n_fg, 0)
    bg_keep = jnp.zeros((A,), bool).at[bg_rank].set(bg_pos) & (labels == 0)
    final = jnp.where(fg_keep, 1, jnp.where(bg_keep, 0, -1))
    # regression targets vs the matched gt
    tgt = _encode_center_size(anchors, gt[argbest])
    return {
        "ScoreIndex": [jnp.where(final >= 0, jnp.arange(A), -1)
                       .astype(jnp.int32)],
        "LocationIndex": [jnp.where(final == 1, jnp.arange(A), -1)
                          .astype(jnp.int32)],
        "TargetLabel": [final.reshape(A, 1)],
        "TargetBBox": [jnp.where((final == 1)[:, None], tgt, 0.0)],
        "BBoxInsideWeight": [
            jnp.broadcast_to((final == 1)[:, None], (A, 4))
            .astype(jnp.float32)
        ],
    }


@register_op("roi_perspective_transform",
             nondiff_inputs=("ROIs", "RoisNum", "BatchId"))
def _roi_perspective_transform(ins, attrs):
    """reference: detection/roi_perspective_transform_op.cc — warp each
    quadrilateral RoI (8 coords: x0..y3 clockwise from top-left) into an
    axis-aligned [H, W] patch via the reference's homography estimate.
    Out-of-image samples are 0 and columns beyond the per-RoI normalized
    width are masked; the reference's additional per-pixel in_quad test
    only differs for DEGENERATE (concave/self-intersecting) quads, which
    are not checked here. The per-RoI normalized width/height adaptation
    is kept (matrix built exactly as get_transform_matrix)."""
    x = first(ins, "X")
    rois = first(ins, "ROIs")                     # [R, 8]
    H = attrs.get("transformed_height", 8)
    W = attrs.get("transformed_width", 8)
    scale = attrs.get("spatial_scale", 1.0)
    R = rois.shape[0]
    bi = _roi_batch_ids(ins, R)
    rx = rois[:, 0::2] * scale                    # [R, 4]
    ry = rois[:, 1::2] * scale
    x0, x1, x2, x3 = rx[:, 0], rx[:, 1], rx[:, 2], rx[:, 3]
    y0, y1, y2, y3 = ry[:, 0], ry[:, 1], ry[:, 2], ry[:, 3]
    len1 = jnp.hypot(x0 - x1, y0 - y1)
    len2 = jnp.hypot(x1 - x2, y1 - y2)
    len3 = jnp.hypot(x2 - x3, y2 - y3)
    len4 = jnp.hypot(x3 - x0, y3 - y0)
    est_h = (len2 + len4) / 2.0
    est_w = (len1 + len3) / 2.0
    nh = max(2, H)
    nw = jnp.clip(
        jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, 1e-5)) + 1, 2, W
    )
    dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
    dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
    den = dx1 * dy2 - dx2 * dy1 + 1e-5
    m6 = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
    m7 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
    m3 = (y1 - y0 + m6 * (nw - 1) * y1) / (nw - 1)
    m4 = (y3 - y0 + m7 * (nh - 1) * y3) / (nh - 1)
    m0 = (x1 - x0 + m6 * (nw - 1) * x1) / (nw - 1)
    m1 = (x3 - x0 + m7 * (nh - 1) * x3) / (nh - 1)
    ow = jnp.arange(W, dtype=jnp.float32)
    oh = jnp.arange(H, dtype=jnp.float32)
    owg = jnp.broadcast_to(ow[None, None, :], (R, H, W))
    ohg = jnp.broadcast_to(oh[None, :, None], (R, H, W))
    u = m0[:, None, None] * owg + m1[:, None, None] * ohg + x0[:, None, None]
    v = m3[:, None, None] * owg + m4[:, None, None] * ohg + y0[:, None, None]
    wdiv = m6[:, None, None] * owg + m7[:, None, None] * ohg + 1.0
    in_w = u / wdiv
    in_h = v / wdiv
    sampled = _bilinear_gather(
        x, bi, in_h.reshape(R, -1), in_w.reshape(R, -1)
    )  # [R, H*W, C] — zero outside the image
    C = x.shape[1]
    out = jnp.transpose(
        sampled.reshape(R, H, W, C), (0, 3, 1, 2)
    )
    # mask positions beyond this roi's normalized width (nw varies per roi)
    wmask = ow[None, None, :] < nw[:, None, None]
    out = out * wmask[:, None, :, :].astype(out.dtype)
    return {"Out": [out.astype(x.dtype)],
            "Out2InIdx": [jnp.zeros((R, 1), jnp.int32)],
            "Out2InWeights": [jnp.zeros((R, 1), jnp.float32)],
            "TransformMatrix": [jnp.stack(
                [m0, m1, x0, m3, m4, y0, m6, m7, jnp.ones_like(m0)], axis=1
            )]}


def _encode_center_size(boxes, matched_gt):
    """Center-size regression targets (reference BoxCoder encode, legacy
    +1 pixel convention) — shared by the three target-assign ops."""
    bw = boxes[:, 2] - boxes[:, 0] + 1.0
    bh = boxes[:, 3] - boxes[:, 1] + 1.0
    bcx = boxes[:, 0] + 0.5 * bw
    bcy = boxes[:, 1] + 0.5 * bh
    gw = matched_gt[:, 2] - matched_gt[:, 0] + 1.0
    gh = matched_gt[:, 3] - matched_gt[:, 1] + 1.0
    gcx = matched_gt[:, 0] + 0.5 * gw
    gcy = matched_gt[:, 1] + 0.5 * gh
    return jnp.stack([
        (gcx - bcx) / bw, (gcy - bcy) / bh,
        jnp.log(gw / bw), jnp.log(gh / bh),
    ], axis=1)


@register_op("generate_proposal_labels", stateful=True,
             nondiff_inputs=("RpnRois", "GtClasses", "IsCrowd", "GtBoxes",
                             "ImInfo"))
def _generate_proposal_labels(ins, attrs):
    """reference: detection/generate_proposal_labels_op.cc — label RPN
    proposals for the second stage: fg = max-IoU >= fg_thresh, bg =
    bg_thresh_lo <= max-IoU < bg_thresh_hi; random-subsample to
    batch_size_per_im at fg_fraction; regression targets vs the matched
    gt. Fixed-slate form: all R proposals stay in place, sampled-out rows
    get label -1 and zero weights (the reference compacts to the sampled
    subset)."""
    from paddle_tpu.ops.common import seeded_rng_key
    from paddle_tpu.ops.detection import _iou

    rois = first(ins, "RpnRois")                  # [R, 4]
    gt_cls = first(ins, "GtClasses").reshape(-1).astype(jnp.int32)
    gt = first(ins, "GtBoxes")                    # [G, 4]
    is_crowd = maybe(ins, "IsCrowd")
    batch = attrs.get("batch_size_per_im", 256)
    fg_frac = attrs.get("fg_fraction", 0.25)
    fg_thresh = attrs.get("fg_thresh", 0.5)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)
    gt_valid = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
    if is_crowd is not None:
        gt_valid = gt_valid & (is_crowd.reshape(-1) == 0)
    # the reference appends the gt boxes to the candidate set so every gt
    # has at least one IoU-1.0 foreground candidate even when the RPN is
    # still random; padded gt rows are zeroed out of contention
    rois = jnp.concatenate(
        [rois, jnp.where(gt_valid[:, None], gt, 0.0)], axis=0
    )
    R = rois.shape[0]
    iou = jnp.where(gt_valid[None, :], _iou(rois, gt), 0.0)  # [R, G]
    best = iou.max(axis=1)
    arg = iou.argmax(axis=1)
    is_fg = best >= fg_thresh
    is_bg = (best >= bg_lo) & (best < bg_hi)
    key = seeded_rng_key(ins, attrs)
    k1, k2 = jax.random.split(key)
    fg_cap = int(batch * fg_frac)
    r1 = jnp.where(is_fg, jax.random.uniform(k1, (R,)), -1.0)
    fg_keep = jnp.zeros((R,), bool).at[jnp.argsort(-r1)[:fg_cap]].set(
        True
    ) & is_fg
    n_fg = fg_keep.sum()
    r2 = jnp.where(is_bg, jax.random.uniform(k2, (R,)), -1.0)
    bg_take = jnp.arange(R) < jnp.maximum(batch - n_fg, 0)
    bg_keep = jnp.zeros((R,), bool).at[jnp.argsort(-r2)].set(bg_take) & is_bg
    labels = jnp.where(fg_keep, gt_cls[arg], jnp.where(bg_keep, 0, -1))
    tgt = _encode_center_size(rois, gt[arg])
    tgt = jnp.where(fg_keep[:, None], tgt, 0.0)
    # reference expands targets per class: [R, 4*class_nums] with the
    # 4-vector written in the matched class's slot
    class_nums = attrs.get("class_nums", 1)
    if class_nums > 1:
        slot = jax.nn.one_hot(
            jnp.clip(labels, 0, class_nums - 1), class_nums,
            dtype=tgt.dtype,
        ) * fg_keep[:, None]                       # [R, C]
        tgt_exp = (slot[:, :, None] * tgt[:, None, :]).reshape(R, -1)
        w_in = jnp.repeat(slot, 4, axis=1)
        w_out = jnp.broadcast_to(
            (fg_keep | bg_keep)[:, None].astype(jnp.float32),
            (R, 4 * class_nums),
        )
    else:
        tgt_exp = tgt
        w_in = jnp.broadcast_to(
            fg_keep[:, None].astype(jnp.float32), (R, 4)
        )
        w_out = jnp.broadcast_to(
            (fg_keep | bg_keep)[:, None].astype(jnp.float32), (R, 4)
        )
    return {
        "Rois": [rois],
        "LabelsInt32": [labels.reshape(R, 1)],
        "BboxTargets": [tgt_exp],
        "BboxInsideWeights": [w_in],
        "BboxOutsideWeights": [w_out],
        "RoisNum": [(fg_keep | bg_keep).sum().astype(jnp.int32).reshape(1)],
    }


@register_op("retinanet_target_assign", stateful=True,
             nondiff_inputs=("Anchor", "GtBoxes", "GtLabels", "IsCrowd",
                             "ImInfo"))
def _retinanet_target_assign(ins, attrs):
    """reference: detection/retinanet_target_assign_op.cc — one-stage
    anchor labeling: fg = max-IoU >= positive_overlap (class label from
    the matched gt), bg = max-IoU < negative_overlap, in-between ignored;
    NO subsampling (focal loss handles imbalance). Fixed-slate per-anchor
    outputs like rpn_target_assign."""
    from paddle_tpu.ops.detection import _iou

    anchors = first(ins, "Anchor")
    gt = first(ins, "GtBoxes")
    gt_labels = first(ins, "GtLabels").reshape(-1).astype(jnp.int32)
    is_crowd = maybe(ins, "IsCrowd")
    pos_thr = attrs.get("positive_overlap", 0.5)
    neg_thr = attrs.get("negative_overlap", 0.4)
    A = anchors.shape[0]
    gt_valid = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
    if is_crowd is not None:
        gt_valid = gt_valid & (is_crowd.reshape(-1) == 0)
    iou = jnp.where(gt_valid[None, :], _iou(anchors, gt), 0.0)
    best = iou.max(axis=1)
    arg = iou.argmax(axis=1)
    # 0 = background, -1 = ignored, >0 = 1-based class of the matched gt
    labels = jnp.where(
        best >= pos_thr, gt_labels[arg],
        jnp.where(best < neg_thr, 0, -1),
    )
    # best anchor per gt is ALWAYS positive (guarded against zero-IoU
    # columns — padded or unreachable gts), as in rpn_target_assign
    best_per_gt = iou.max(axis=0)
    is_best = (
        (iou == best_per_gt[None, :]) & (best_per_gt[None, :] > 0)
    ).any(axis=1)
    labels = jnp.where(is_best, gt_labels[arg], labels)
    fg = labels > 0
    tgt = _encode_center_size(anchors, gt[arg])
    return {
        "LocationIndex": [jnp.where(fg, jnp.arange(A), -1)
                          .astype(jnp.int32)],
        "ScoreIndex": [jnp.where(labels >= 0, jnp.arange(A), -1)
                       .astype(jnp.int32)],
        "TargetLabel": [labels.reshape(A, 1)],
        "TargetBBox": [jnp.where(fg[:, None], tgt, 0.0)],
        "BBoxInsideWeight": [jnp.broadcast_to(
            fg[:, None].astype(jnp.float32), (A, 4)
        )],
        "ForegroundNumber": [jnp.maximum(fg.sum(), 1)
                             .astype(jnp.int32).reshape(1)],
    }
