"""Vision ops: RoI pooling/alignment, grid sampling, resampling, LRN, pooling
with indices, patch extraction.

The reference implements these as CUDA kernels with per-thread scalar loops
(reference: paddle/fluid/operators/roi_align_op.cu, roi_pool_op.cu,
grid_sampler_op.cu, affine_grid_op.cc, lrn_op.cc, pool_with_index_op.cu,
unpool_op.cc, interpolate_op.cc, im2sequence_op.cc). TPU-native redesign:
everything is expressed as fixed-shape vectorized gathers/reductions so XLA
can tile them — RoIs carry an explicit batch-id tensor instead of LoD, and
"adaptive" sampling counts become static attrs (data-dependent loop bounds
don't exist under jit).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe

_NEG = -1e30


# ---------------------------------------------------------------------------
# bilinear helpers
# ---------------------------------------------------------------------------


def _bilinear_gather(x, bi, ys, xs):
    """Sample x [N, C, H, W] at float coords (ys, xs) [R, ...] for batch ids
    bi [R]; out-of-range samples contribute 0 (reference roi_align
    semantics: x in [-1, H] clamps, outside that is zero)."""
    H, W = x.shape[2], x.shape[3]
    valid = (ys > -1.0) & (ys < H) & (xs > -1.0) & (xs < W)
    y = jnp.clip(ys, 0.0, H - 1)
    xq = jnp.clip(xs, 0.0, W - 1)
    y0 = jnp.floor(y).astype(jnp.int32)
    x0 = jnp.floor(xq).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    ly = (y - y0).astype(x.dtype)
    lx = (xq - x0).astype(x.dtype)
    hy, hx = 1.0 - ly, 1.0 - lx
    # broadcast batch ids over the sample grid dims
    bfull = bi.reshape((-1,) + (1,) * (ys.ndim - 1))
    bfull = jnp.broadcast_to(bfull, ys.shape)

    def corner(yy, xx):
        # advanced indexing -> gather: [R, ..., C]
        return x[bfull, :, yy, xx]

    w00 = (hy * hx)[..., None]
    w01 = (hy * lx)[..., None]
    w10 = (ly * hx)[..., None]
    w11 = (ly * lx)[..., None]
    out = (
        corner(y0, x0) * w00
        + corner(y0, x1) * w01
        + corner(y1, x0) * w10
        + corner(y1, x1) * w11
    )
    return out * valid[..., None].astype(x.dtype)


def _roi_batch_ids(ins, num_rois):
    """Batch id per RoI: explicit BatchId tensor, or derived from per-image
    counts (RoisNum), else all zeros (single image)."""
    bid = maybe(ins, "BatchId")
    if bid is not None:
        return bid.astype(jnp.int32)
    rois_num = maybe(ins, "RoisNum")
    if rois_num is not None:
        # id[i] = #{j : i >= cumsum(rois_num)[j]} — fixed-shape scan-free
        bounds = jnp.cumsum(rois_num.astype(jnp.int32))
        idx = jnp.arange(num_rois, dtype=jnp.int32)
        return jnp.sum(idx[:, None] >= bounds[None, :], axis=1).astype(jnp.int32)
    return jnp.zeros((num_rois,), jnp.int32)


@register_op("roi_align", nondiff_inputs=("ROIs", "RoisNum", "BatchId"))
def _roi_align(ins, attrs):
    """reference: paddle/fluid/operators/roi_align_op.cc. sampling_ratio<=0
    (adaptive ceil(roi/bin) in the reference) becomes a static 2x2 grid —
    data-dependent sample counts cannot exist under XLA."""
    x = first(ins, "X")
    rois = first(ins, "ROIs")
    R = rois.shape[0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    s = attrs.get("sampling_ratio", -1)
    s = int(s) if s and s > 0 else 2
    aligned = attrs.get("aligned", False)
    off = 0.5 if aligned else 0.0
    bi = _roi_batch_ids(ins, R)

    x1 = rois[:, 0] * scale - off
    y1 = rois[:, 1] * scale - off
    x2 = rois[:, 2] * scale - off
    y2 = rois[:, 3] * scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    bin_w = rw / pw
    bin_h = rh / ph
    # sample coords: ys [R, ph*s], xs [R, pw*s]
    iy = (jnp.arange(ph * s) + 0.5) / s  # fractional bin positions
    ix = (jnp.arange(pw * s) + 0.5) / s
    ys = y1[:, None] + iy[None, :] * bin_h[:, None]  # [R, ph*s]
    xs = x1[:, None] + ix[None, :] * bin_w[:, None]  # [R, pw*s]
    yy = jnp.broadcast_to(ys[:, :, None], (R, ph * s, pw * s))
    xx = jnp.broadcast_to(xs[:, None, :], (R, ph * s, pw * s))
    sampled = _bilinear_gather(x, bi, yy, xx)  # [R, ph*s, pw*s, C]
    C = x.shape[1]
    sampled = sampled.reshape(R, ph, s, pw, s, C).mean(axis=(2, 4))
    return {"Out": [jnp.transpose(sampled, (0, 3, 1, 2))]}


@register_op("roi_pool", nondiff_inputs=("ROIs", "RoisNum", "BatchId"))
def _roi_pool(ins, attrs):
    """reference: paddle/fluid/operators/roi_pool_op.cc — exact integer-bin
    max pooling. Fixed-shape form: each bin gathers at most
    ceil(H/ph)+1 x ceil(W/pw)+1 integer positions (a static bound on the
    reference's dynamic bin extents) and masks rows past the bin end."""
    x = first(ins, "X")
    rois = first(ins, "ROIs")
    R = rois.shape[0]
    C, H, W = x.shape[1], x.shape[2], x.shape[3]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    bi = _roi_batch_ids(ins, R)

    x1 = jnp.round(rois[:, 0] * scale).astype(jnp.int32)
    y1 = jnp.round(rois[:, 1] * scale).astype(jnp.int32)
    x2 = jnp.round(rois[:, 2] * scale).astype(jnp.int32)
    y2 = jnp.round(rois[:, 3] * scale).astype(jnp.int32)
    rh = jnp.maximum(y2 - y1 + 1, 1)
    rw = jnp.maximum(x2 - x1 + 1, 1)

    mh = -(-H // ph) + 1  # static per-bin row bound
    mw = -(-W // pw) + 1

    def bin_edges(start, size, n, i):
        lo = start + (i * size) // n
        hi = start + ((i + 1) * size + n - 1) // n  # ceil
        return lo, hi

    ib = jnp.arange(ph)[None, :]  # [1, ph]
    h_lo, h_hi = bin_edges(y1[:, None], rh[:, None], ph, ib)  # [R, ph]
    jb = jnp.arange(pw)[None, :]
    w_lo, w_hi = bin_edges(x1[:, None], rw[:, None], pw, jb)  # [R, pw]
    h_lo = jnp.clip(h_lo, 0, H)
    h_hi = jnp.clip(h_hi, 0, H)
    w_lo = jnp.clip(w_lo, 0, W)
    w_hi = jnp.clip(w_hi, 0, W)

    hr = h_lo[:, :, None] + jnp.arange(mh)[None, None, :]  # [R, ph, mh]
    wr = w_lo[:, :, None] + jnp.arange(mw)[None, None, :]  # [R, pw, mw]
    hmask = hr < h_hi[:, :, None]
    wmask = wr < w_hi[:, :, None]
    hc = jnp.clip(hr, 0, H - 1)
    wc = jnp.clip(wr, 0, W - 1)

    bfull = bi[:, None, None, None, None]
    hfull = hc[:, :, :, None, None]  # [R, ph, mh, 1, 1]
    wfull = wc[:, None, None, :, :]  # [R, 1, 1, pw, mw]
    b_b = jnp.broadcast_to(bfull, (R, ph, mh, pw, mw))
    h_b = jnp.broadcast_to(hfull, (R, ph, mh, pw, mw))
    w_b = jnp.broadcast_to(wfull, (R, ph, mh, pw, mw))
    vals = x[b_b, :, h_b, w_b]  # [R, ph, mh, pw, mw, C]
    mask = (hmask[:, :, :, None, None] & wmask[:, None, None, :, :])
    vals = jnp.where(mask[..., None], vals, _NEG)
    # vals axes [R, ph, mh, pw, mw, C] -> [R, C, ph, pw, mh*mw]
    flat = jnp.transpose(vals, (0, 5, 1, 3, 2, 4)).reshape(R, C, ph, pw, mh * mw)
    mx = flat.max(axis=-1)
    out = jnp.where(mx <= _NEG / 2, 0.0, mx).astype(x.dtype)
    # argmax (flat h*W+w index into the input image) for Unpool-style uses
    amax = flat.argmax(axis=-1)  # [R, C, ph, pw] index into mh*mw
    hi_idx = amax // mw
    wi_idx = amax % mw
    h_sel = jnp.take_along_axis(
        jnp.broadcast_to(hc[:, None, :, None, :], (R, C, ph, pw, mh)),
        hi_idx[..., None], axis=-1,
    )[..., 0]
    w_sel = jnp.take_along_axis(
        jnp.broadcast_to(wc[:, None, None, :, :], (R, C, ph, pw, mw)),
        wi_idx[..., None], axis=-1,
    )[..., 0]
    argmax = (h_sel * W + w_sel).astype(jnp.int64)
    # empty bin: reference writes Out=0, Argmax=-1 (roi_pool_op.cu:81) so
    # unpool-style consumers skip the bin instead of hitting a real pixel
    argmax = jnp.where(mx <= _NEG / 2, jnp.int64(-1), argmax)
    return {"Out": [out], "Argmax": [argmax]}


@register_op("grid_sampler", nondiff_inputs=())
def _grid_sampler(ins, attrs):
    """reference: paddle/fluid/operators/grid_sampler_op.cc — bilinear
    sampling of X [N,C,H,W] at Grid [N,Hg,Wg,2] normalized coords.
    Zero-padding semantics: each of the four corners is weighted by its OWN
    in-bound mask (ref GetGridPointValue's isInBound per corner), so a sample
    straddling the border fades toward 0 rather than clamping — this differs
    from roi_align's clamp-inside-(-1,H) window, hence a separate gather."""
    x = first(ins, "X")
    grid = first(ins, "Grid")
    N, C, H, W = x.shape
    align = attrs.get("align_corners", True)
    gx = grid[..., 0].astype(jnp.float32)
    gy = grid[..., 1].astype(jnp.float32)
    if align:
        xs = (gx + 1.0) / 2.0 * (W - 1)
        ys = (gy + 1.0) / 2.0 * (H - 1)
    else:
        xs = ((gx + 1.0) * W - 1.0) / 2.0
        ys = ((gy + 1.0) * H - 1.0) / 2.0
    Hg, Wg = grid.shape[1], grid.shape[2]
    ys = ys.reshape(N, -1)
    xs = xs.reshape(N, -1)
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    ly = (ys - y0).astype(x.dtype)
    lx = (xs - x0).astype(x.dtype)
    hy, hx = 1.0 - ly, 1.0 - lx
    bi = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32)[:, None], y0.shape)

    def corner(yy, xx):
        inb = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
        yc = jnp.clip(yy, 0, H - 1)
        xc = jnp.clip(xx, 0, W - 1)
        return x[bi, :, yc, xc] * inb[..., None].astype(x.dtype)

    out = (
        corner(y0, x0) * (hy * hx)[..., None]
        + corner(y0, x0 + 1) * (hy * lx)[..., None]
        + corner(y0 + 1, x0) * (ly * hx)[..., None]
        + corner(y0 + 1, x0 + 1) * (ly * lx)[..., None]
    )
    out = out.reshape(N, Hg, Wg, C)
    return {"Output": [jnp.transpose(out, (0, 3, 1, 2))]}


@register_op("affine_grid")
def _affine_grid(ins, attrs):
    """reference: paddle/fluid/operators/affine_grid_op.cc. Theta [N,2,3] ->
    Output [N,H,W,2]."""
    theta = first(ins, "Theta")
    shape = maybe(ins, "OutputShape")
    if shape is not None:
        hs, ws = int(shape[2]), int(shape[3])
    else:
        out_shape = attrs["output_shape"]
        hs, ws = int(out_shape[2]), int(out_shape[3])
    align = attrs.get("align_corners", True)

    def axis_coords(n):
        if align:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    ys = axis_coords(hs)
    xs = axis_coords(ws)
    xg, yg = jnp.meshgrid(xs, ys)  # [H, W]
    base = jnp.stack([xg, yg, jnp.ones_like(xg)], axis=-1)  # [H, W, 3]
    out = jnp.einsum(
        "hwk,nck->nhwc", base.astype(theta.dtype), theta
    )  # [N, H, W, 2]
    return {"Output": [out]}


@register_op("affine_channel")
def _affine_channel(ins, attrs):
    """reference: paddle/fluid/operators/affine_channel_op.cc — per-channel
    x * scale + bias (conv-BN folding target)."""
    x = first(ins, "X")
    scale = first(ins, "Scale").reshape(-1)
    bias = first(ins, "Bias").reshape(-1)
    layout = attrs.get("data_layout", "NCHW")
    shape = (
        (1, -1) + (1,) * (x.ndim - 2) if layout == "NCHW" else
        (1,) * (x.ndim - 1) + (-1,)
    )
    return {"Out": [x * scale.reshape(shape) + bias.reshape(shape)]}


@register_op("lrn")
def _lrn(ins, attrs):
    """reference: paddle/fluid/operators/lrn_op.cc — across-channel local
    response normalization via a channel-axis window sum (reduce_window)."""
    x = first(ins, "X")
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x.astype(jnp.float32))
    lo = (n - 1) // 2
    hi = n - 1 - lo
    window_sum = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        (1, n, 1, 1), (1, 1, 1, 1), ((0, 0), (lo, hi), (0, 0), (0, 0)),
    )
    mid = jnp.power(k + alpha * window_sum, beta)
    return {
        "Out": [(x.astype(jnp.float32) / mid).astype(x.dtype)],
        "MidOut": [mid],
    }


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ins, attrs):
    """reference: paddle/fluid/operators/pool_with_index_op.cc. Patches are
    extracted with conv_general_dilated_patches (one XLA op), then max +
    argmax over the window axis; -inf pre-padding keeps padded positions out
    of the max (plain conv padding would inject zeros)."""
    x = first(ins, "X")
    ksize = tuple(attrs.get("ksize", [2, 2]))
    strides = tuple(attrs.get("strides", ksize))
    pads = attrs.get("paddings", [0, 0])
    ph, pw = (pads[0], pads[1]) if len(pads) == 2 else (pads[0], pads[2])
    N, C, H, W = x.shape
    xp = jnp.pad(
        x.astype(jnp.float32),
        ((0, 0), (0, 0), (ph, ph), (pw, pw)),
        constant_values=_NEG,
    )
    patches = jax.lax.conv_general_dilated_patches(
        xp, ksize, strides, "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, oh, ow], feature dim ordered (C, kh, kw)
    oh, ow = patches.shape[2], patches.shape[3]
    kh, kw = ksize
    p = patches.reshape(N, C, kh * kw, oh, ow)
    out = p.max(axis=2).astype(x.dtype)
    widx = p.argmax(axis=2)  # [N, C, oh, ow] flat window index
    base_h = jnp.arange(oh)[:, None] * strides[0] - ph
    base_w = jnp.arange(ow)[None, :] * strides[1] - pw
    gh = base_h[None, None] + widx // kw
    gw = base_w[None, None] + widx % kw
    mask = p.max(axis=2) <= _NEG / 2
    out = jnp.where(mask, 0.0, out).astype(x.dtype)
    # all-padding window: index -1 (never a negative real position) so
    # unpool consumers skip it, mirroring roi_pool's empty-bin sentinel
    midx = jnp.where(mask, jnp.int32(-1), (gh * W + gw).astype(jnp.int32))
    return {"Out": [out], "Mask": [midx]}


@register_op("unpool", nondiff_inputs=("Indices",))
def _unpool(ins, attrs):
    """reference: paddle/fluid/operators/unpool_op.cc — max-unpool: scatter
    values to the recorded argmax positions of an earlier pool."""
    x = first(ins, "X")
    idx = first(ins, "Indices").astype(jnp.int32)
    N, C, H, W = x.shape
    oh, ow = attrs["unpooled_height"], attrs["unpooled_width"]
    flat = jnp.zeros((N, C, oh * ow), x.dtype)
    vals = x.reshape(N, C, H * W)
    iflat = idx.reshape(N, C, H * W)
    # -1 sentinel (empty pool bin): JAX scatter wraps negative indices, so
    # remap to oh*ow — out-of-bounds scatter updates are DROPPED (the
    # documented default mode), which is exactly the skip we need
    iflat = jnp.where(iflat < 0, oh * ow, iflat)
    out = flat.at[
        jnp.arange(N)[:, None, None],
        jnp.arange(C)[None, :, None],
        iflat,
    ].add(vals)
    return {"Out": [out.reshape(N, C, oh, ow)]}


@register_op("trilinear_interp")
def _trilinear_interp(ins, attrs):
    """reference: paddle/fluid/operators/interpolate_op.cc (trilinear).
    X [N,C,D,H,W] resized via separable 1-D linear interpolation per axis."""
    x = first(ins, "X")
    out_size = maybe(ins, "OutSize")
    if out_size is not None:
        od, oh, ow = (int(v) for v in out_size)
    else:
        od = attrs.get("out_d", -1)
        oh = attrs.get("out_h", -1)
        ow = attrs.get("out_w", -1)
    align = attrs.get("align_corners", True)
    align_mode = attrs.get("align_mode", 1)

    def axis_pos(n_in, n_out):
        i = jnp.arange(n_out, dtype=jnp.float32)
        if align:
            scale = (n_in - 1) / max(n_out - 1, 1)
            return i * scale
        scale = n_in / n_out
        if align_mode == 0:
            return jnp.clip((i + 0.5) * scale - 0.5, 0.0, n_in - 1)
        return jnp.clip(i * scale, 0.0, n_in - 1)

    def interp_axis(v, axis, n_out):
        n_in = v.shape[axis]
        pos = axis_pos(n_in, n_out)
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, n_in - 1)
        w = (pos - lo).astype(v.dtype)
        vlo = jnp.take(v, lo, axis=axis)
        vhi = jnp.take(v, hi, axis=axis)
        shape = [1] * v.ndim
        shape[axis] = n_out
        w = w.reshape(shape)
        return vlo * (1 - w) + vhi * w

    out = interp_axis(x, 2, od)
    out = interp_axis(out, 3, oh)
    out = interp_axis(out, 4, ow)
    return {"Out": [out]}


@register_op("im2sequence")
def _im2sequence(ins, attrs):
    """reference: paddle/fluid/operators/im2sequence_op.cc. Patches of
    X [N,C,H,W] flattened to [N*oh*ow, C*kh*kw] (row-major over N, oh, ow) —
    the LoD the reference attaches becomes the implied (N, oh*ow) grouping."""
    x = first(ins, "X")
    kh, kw = attrs["kernels"]
    strides = tuple(attrs.get("strides", [1, 1]))
    pads = attrs.get("paddings", [0, 0, 0, 0])
    if len(pads) == 2:
        pads = [pads[0], pads[1], pads[0], pads[1]]
    N, C = x.shape[0], x.shape[1]
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), strides,
        ((pads[0], pads[2]), (pads[1], pads[3])),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, oh, ow]
    oh, ow = patches.shape[2], patches.shape[3]
    out = jnp.transpose(patches, (0, 2, 3, 1)).reshape(N * oh * ow, C * kh * kw)
    return {"Out": [out]}


@register_op("shuffle_batch", stateful=True)
def _shuffle_batch(ins, attrs):
    """reference: paddle/fluid/operators/shuffle_batch_op.cc — random
    row permutation; the permutation is emitted so it can be undone."""
    from paddle_tpu.ops.common import seeded_rng_key

    x = first(ins, "X")
    key = seeded_rng_key(ins, attrs)
    perm = jax.random.permutation(key, x.shape[0])
    return {
        "Out": [x[perm]],
        "ShuffleIdx": [perm.astype(jnp.int64)],
        "SeedOut": [jnp.zeros((1,), jnp.int64)],
    }


@register_op("conv3d_transpose")
def _conv3d_transpose(ins, attrs):
    """Transposed 3-D conv as input-dilated forward conv (reference:
    paddle/fluid/operators/conv_transpose_op.cc)."""
    x, w = first(ins, "Input"), first(ins, "Filter")
    strides = tuple(attrs.get("strides", [1, 1, 1]))
    groups = attrs.get("groups", 1)
    pads = attrs.get("paddings", [0, 0, 0])
    if len(pads) == 3:
        pads6 = [(p, p) for p in pads]
    else:
        pads6 = [(pads[2 * i], pads[2 * i + 1]) for i in range(3)]
    in_c, oc_per_g, kd, kh, kw = w.shape
    wf = jnp.flip(w, (2, 3, 4))
    wf = wf.reshape(groups, in_c // groups, oc_per_g, kd, kh, kw)
    wf = jnp.swapaxes(wf, 1, 2).reshape(
        groups * oc_per_g, in_c // groups, kd, kh, kw
    )
    ks = (kd, kh, kw)
    padding = tuple(
        (ks[i] - 1 - pads6[i][0], ks[i] - 1 - pads6[i][1]) for i in range(3)
    )
    out = jax.lax.conv_general_dilated(
        x, wf,
        window_strides=(1, 1, 1),
        padding=padding,
        lhs_dilation=strides,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=groups,
    )
    return {"Output": [out]}


@register_op("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ins, attrs):
    from paddle_tpu.core.registry import OpRegistry

    attrs = dict(attrs)
    x = first(ins, "Input")
    attrs["groups"] = x.shape[1]
    base = OpRegistry.get("conv2d_transpose")
    return base.lower(ins, attrs)
