"""Operator library: importing this package registers every op lowering.

The analog of the reference's static-registrar op library
(reference: paddle/fluid/operators/ — 560 REGISTER_OPERATOR sites); here
registration is module import, and there is one jax lowering per op instead
of per-(place, dtype, layout) kernels.
"""

from paddle_tpu.ops import common  # noqa: F401
from paddle_tpu.ops import math  # noqa: F401
from paddle_tpu.ops import nn  # noqa: F401
from paddle_tpu.ops import tensor  # noqa: F401
from paddle_tpu.ops import optimizers  # noqa: F401
from paddle_tpu.ops import control_flow  # noqa: F401
from paddle_tpu.ops import recompute  # noqa: F401
from paddle_tpu.ops import rnn  # noqa: F401
from paddle_tpu.ops import sequence  # noqa: F401
from paddle_tpu.ops import detection  # noqa: F401
from paddle_tpu.ops import pipeline  # noqa: F401
from paddle_tpu.ops import nn_extra  # noqa: F401
from paddle_tpu.ops import py_func  # noqa: F401
from paddle_tpu.ops import vision  # noqa: F401
from paddle_tpu.ops import moe  # noqa: F401
from paddle_tpu.ops import misc_extra  # noqa: F401
from paddle_tpu.ops import vision_extra  # noqa: F401
from paddle_tpu.ops import fused  # noqa: F401
from paddle_tpu.ops import yolo_loss  # noqa: F401
from paddle_tpu.ops import extras  # noqa: F401
from paddle_tpu.ops import sharded_embedding  # noqa: F401
from paddle_tpu.ops import crf  # noqa: F401
from paddle_tpu.ops import tail  # noqa: F401
