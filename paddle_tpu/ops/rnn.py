"""Recurrent ops: fused LSTM/GRU sequence kernels and the `recurrent` op.

The reference implements RNNs three ways: per-timestep C++ kernels driven by
LoD (reference: paddle/fluid/operators/lstm_op.h, gru_op.h), a cuDNN fused
path (reference: paddle/fluid/operators/cudnn_lstm_op.cu.cc), and the
`recurrent` op running a sub-block per step through a nested Executor
(reference: paddle/fluid/operators/recurrent_op.h:189). TPU-native, all
three collapse onto `lax.scan`: the step function is traced once, XLA
unrolls nothing, the MXU sees one batched matmul per gate per step, and
variable-length sequences are handled by padded tensors + a length mask
(SURVEY §5.7: LoD is subsumed by dense padding on TPU).

Gate orders (documented contract, matches the cuDNN/PyTorch convention):
  LSTM: [i, f, g, o]   GRU: [r, z, n] with separate hidden bias for n.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe
from paddle_tpu.utils.enforce import EnforceError


def _mask_step(t, lengths, new, old):
    """Where t >= length, keep the previous carry (padded region)."""
    if lengths is None:
        return new
    keep = (t < lengths)[:, None].astype(new.dtype)
    return keep * new + (1 - keep) * old


def _lstm_layer(x, h0, c0, w_ih, w_hh, b, lengths, reverse=False):
    """One direction of one LSTM layer. x: [B, S, I]; returns
    (out [B, S, H], h_last [B, H], c_last [B, H])."""
    xs = jnp.swapaxes(x, 0, 1)  # [S, B, I] scan over time
    steps = jnp.arange(xs.shape[0])
    if reverse:
        xs = xs[::-1]
        steps = steps[::-1]
    # hoist the input projection out of the scan: one big MXU matmul
    gx = jnp.einsum("sbi,ig->sbg", xs, w_ih) + b

    def step(carry, inp):
        h, c = carry
        g_x, t = inp
        gates = g_x + h @ w_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        h_new = _mask_step(t, lengths, h_new, h)
        c_new = _mask_step(t, lengths, c_new, c)
        out = h_new if lengths is None else _mask_step(
            t, lengths, h_new, jnp.zeros_like(h_new)
        )
        return (h_new, c_new), out

    (h_last, c_last), outs = jax.lax.scan(step, (h0, c0), (gx, steps))
    if reverse:
        outs = outs[::-1]
    return jnp.swapaxes(outs, 0, 1), h_last, c_last


def _gru_layer(x, h0, w_ih, w_hh, b_ih, b_hh, lengths, reverse=False):
    """One direction of one GRU layer (cuDNN formulation:
    n = tanh(x W_n + b_in + r * (h W_hn + b_hn)))."""
    xs = jnp.swapaxes(x, 0, 1)
    steps = jnp.arange(xs.shape[0])
    if reverse:
        xs = xs[::-1]
        steps = steps[::-1]
    gx = jnp.einsum("sbi,ig->sbg", xs, w_ih) + b_ih

    def step(carry, inp):
        h = carry
        g_x, t = inp
        g_h = h @ w_hh + b_hh
        xr, xz, xn = jnp.split(g_x, 3, axis=-1)
        hr, hz, hn = jnp.split(g_h, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        h_new = _mask_step(t, lengths, h_new, h)
        out = h_new if lengths is None else _mask_step(
            t, lengths, h_new, jnp.zeros_like(h_new)
        )
        return h_new, out

    h_last, outs = jax.lax.scan(step, h0, (gx, steps))
    if reverse:
        outs = outs[::-1]
    return jnp.swapaxes(outs, 0, 1), h_last


def _stack_directions(x, layer_fn, num_layers, bidirectional):
    """Run a (possibly bidirectional) RNN stack; `layer_fn(inp, idx, reverse)`
    runs one layer-direction. Returns (out, per-layer-direction last states)."""
    n_dir = 2 if bidirectional else 1
    out = x
    lasts = []
    for layer in range(num_layers):
        outs_dir = []
        for d in range(n_dir):
            idx = layer * n_dir + d
            res = layer_fn(out, idx, reverse=(d == 1))
            outs_dir.append(res[0])
            lasts.append(res[1:])
        out = (
            jnp.concatenate(outs_dir, axis=-1) if n_dir == 2 else outs_dir[0]
        )
    return out, lasts


@register_op("lstm", nondiff_inputs=("SequenceLength",))
def _lstm(ins, attrs):
    """Fused multi-layer (bi)LSTM over padded [B, S, I] input.

    Inputs: Input, InitH/InitC [L*D, B, H], WeightIh/WeightHh/Bias lists
    (one per layer-direction), optional SequenceLength [B].
    Outputs: Out [B, S, H*D], LastH, LastC [L*D, B, H].
    reference: paddle/fluid/operators/cudnn_lstm_op.cu.cc (capability parity;
    weight layout here is per-layer arrays, not one opaque cuDNN blob).
    """
    x = first(ins, "Input")
    h0s = first(ins, "InitH")
    c0s = first(ins, "InitC")
    w_ih = ins["WeightIh"]
    w_hh = ins["WeightHh"]
    bias = ins["Bias"]
    lengths = maybe(ins, "SequenceLength")
    num_layers = attrs.get("num_layers", 1)
    bidirectional = attrs.get("is_bidirec", False)

    def layer_fn(inp, idx, reverse):
        return _lstm_layer(
            inp, h0s[idx], c0s[idx], w_ih[idx], w_hh[idx], bias[idx],
            lengths, reverse,
        )

    out, lasts = _stack_directions(x, layer_fn, num_layers, bidirectional)
    last_h = jnp.stack([l[0] for l in lasts])
    last_c = jnp.stack([l[1] for l in lasts])
    return {"Out": [out], "LastH": [last_h], "LastC": [last_c]}


@register_op("gru", nondiff_inputs=("SequenceLength",))
def _gru(ins, attrs):
    """Fused multi-layer (bi)GRU over padded [B, S, I] input
    (reference: paddle/fluid/operators/gru_op.h — there LoD-batched, here
    padded + SequenceLength)."""
    x = first(ins, "Input")
    h0s = first(ins, "InitH")
    w_ih = ins["WeightIh"]
    w_hh = ins["WeightHh"]
    b_ih = ins["BiasIh"]
    b_hh = ins["BiasHh"]
    lengths = maybe(ins, "SequenceLength")
    num_layers = attrs.get("num_layers", 1)
    bidirectional = attrs.get("is_bidirec", False)

    def layer_fn(inp, idx, reverse):
        return _gru_layer(
            inp, h0s[idx], w_ih[idx], w_hh[idx], b_ih[idx], b_hh[idx],
            lengths, reverse,
        )

    out, lasts = _stack_directions(x, layer_fn, num_layers, bidirectional)
    last_h = jnp.stack([l[0] for l in lasts])
    return {"Out": [out], "LastH": [last_h]}


@register_op("recurrent", stateful=True, needs_block=True)
def _recurrent(ins, attrs):
    """StaticRNN engine: scan a sub-block over the time axis.

    The reference's recurrent_op runs its step block through a nested
    Executor once per timestep with per-step scopes
    (reference: paddle/fluid/operators/recurrent_op.h:189); here the step
    block is traced ONCE into a `lax.scan` body, so the schedule lives in
    XLA, and the generic vjp grad (core/backward.py) differentiates straight
    through the scan — no RecurrentGradOp machinery.

    attrs:
      sub_block        — step block index
      step_input_vars  — [outer [T,...] names fed sliced per step]
      inner_input_vars — matching sub-block var names
      state_init_vars  — [outer init names]
      state_inner_vars — [sub-block memory names]
      state_next_vars  — [sub-block names holding the updated memory]
      step_output_vars — [sub-block names stacked into [T,...] outputs]
      reverse          — scan the time axis backwards (T comes from the
                         leading axis of the first step input)
    ins slots: X (step inputs), Init (initial states), Ex (external reads).
    """
    block = attrs["_ctx_block"]
    sub = block.program.block(attrs["sub_block"])
    step_xs = ins.get("X", [])
    inits = ins.get("Init", [])
    ex_names = attrs.get("ex_vars", [])
    ex_vals = ins.get("Ex", [])
    inner_inputs = attrs.get("inner_input_vars", [])
    state_inner = attrs.get("state_inner_vars", [])
    state_next = attrs.get("state_next_vars", [])
    out_names = attrs.get("step_output_vars", [])
    reverse = attrs.get("reverse", False)
    if not step_xs:
        raise EnforceError(
            "recurrent op needs at least one step input (X) to define the "
            "scan length"
        )
    rng = ins.get("__rng_key__", [jax.random.PRNGKey(0)])[0]

    from paddle_tpu.core.executor import _interpret_block

    outer_env = dict(zip(ex_names, ex_vals))
    T = step_xs[0].shape[0]

    def body(carry, t):
        states = carry
        env = dict(outer_env)
        for name, x in zip(inner_inputs, step_xs):
            env[name] = jax.lax.dynamic_index_in_dim(
                x, t, axis=0, keepdims=False
            )
        env.update(zip(state_inner, states))
        _interpret_block(sub, env, jax.random.fold_in(rng, t))
        new_states = tuple(env[n] for n in state_next)
        outs = tuple(env[n] for n in out_names)
        return new_states, outs

    ts = jnp.arange(T - 1, -1, -1) if reverse else jnp.arange(T)
    final_states, stacked = jax.lax.scan(body, tuple(inits), ts)
    if reverse:
        stacked = tuple(o[::-1] for o in stacked)
    return {
        "Out": list(stacked),
        "LastState": list(final_states),
    }
