"""Tensor creation / manipulation / comparison / random op lowerings.

Replaces the reference's tensor kernels (reference: paddle/fluid/operators/
reshape_op.cc, transpose_op.cc, concat_op.cc, gather_op.cu, cast_op.cu,
fill_constant_op.cc, gaussian_random_op.cu, uniform_random_op.cu ...).
Random ops are counter-based: they consume a key the executor derives from
(program seed, run counter, op index) — deterministic replay without the
reference's per-device curand generator state.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first, maybe, np_dtype, rng_key

# -- creation ---------------------------------------------------------------


@register_op("fill_constant")
def _fill_constant(ins, attrs):
    shape = maybe(ins, "ShapeTensor", attrs.get("shape", [1]))
    dtype = np_dtype(attrs)
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dtype)]}


@register_op("fill_constant_batch_size_like")
def _fill_constant_bsl(ins, attrs):
    x = first(ins, "Input")
    shape = list(attrs.get("shape"))
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = x.shape[in_idx]
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=np_dtype(attrs))]}


@register_op("fill_zeros_like")
def _fill_zeros_like(ins, attrs):
    return {"Out": [jnp.zeros_like(first(ins, "X"))]}


@register_op("assign")
def _assign(ins, attrs):
    return {"Out": [first(ins, "X")]}


@register_op("assign_value")
def _assign_value(ins, attrs):
    import numpy as np

    values = np.array(attrs["values"], dtype=np_dtype(attrs)).reshape(attrs["shape"])
    return {"Out": [jnp.asarray(values)]}


@register_op("range", nondiff_inputs=("Start", "End", "Step"))
def _range(ins, attrs):
    start, end, step = first(ins, "Start"), first(ins, "End"), first(ins, "Step")
    # shapes must be static under XLA: require concrete (constant) bounds;
    # reshape to () first - jax refuses float() on [1]-shaped arrays
    return {
        "Out": [
            jnp.arange(
                float(jnp.reshape(start, ())),
                float(jnp.reshape(end, ())),
                float(jnp.reshape(step, ())),
            ).astype(start.dtype)
        ]
    }


@register_op("linspace")
def _linspace(ins, attrs):
    start, stop, num = first(ins, "Start"), first(ins, "Stop"), first(ins, "Num")
    return {"Out": [jnp.linspace(float(start), float(stop), int(num))]}


@register_op("eye")
def _eye(ins, attrs):
    return {
        "Out": [
            jnp.eye(attrs["num_rows"], attrs.get("num_columns"), dtype=np_dtype(attrs))
        ]
    }


# -- manipulation -----------------------------------------------------------


@register_op("reshape2")
def _reshape2(ins, attrs):
    x = first(ins, "X")
    shape = maybe(ins, "Shape", attrs.get("shape"))
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": [x.reshape(tuple(int(s) for s in shape))], "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register_op("reshape")
def _reshape(ins, attrs):
    out = _reshape2(ins, attrs)
    return {"Out": out["Out"]}


@register_op("transpose2")
def _transpose2(ins, attrs):
    x = first(ins, "X")
    return {
        "Out": [jnp.transpose(x, attrs["axis"])],
        "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)],
    }


@register_op("transpose")
def _transpose(ins, attrs):
    return {"Out": [jnp.transpose(first(ins, "X"), attrs["axis"])]}


@register_op("flatten2")
def _flatten2(ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", 1)
    import math

    out = x.reshape((math.prod(x.shape[:axis]) if axis else 1, -1))
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register_op("squeeze2")
def _squeeze2(ins, attrs):
    x = first(ins, "X")
    axes = attrs.get("axes", [])
    axes = [a % x.ndim for a in axes] if axes else [
        i for i, s in enumerate(x.shape) if s == 1
    ]
    return {
        "Out": [jnp.squeeze(x, tuple(a for a in axes if x.shape[a] == 1))],
        "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)],
    }


@register_op("unsqueeze2")
def _unsqueeze2(ins, attrs):
    x = first(ins, "X")
    out = x
    # reference inserts axes in DECLARATION order, each against the rank
    # grown so far (unsqueeze_op.cc GetOutputShape) — do not sort
    for a in attrs.get("axes", []):
        out = jnp.expand_dims(out, a)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, dtype=x.dtype)]}


@register_op("concat")
def _concat(ins, attrs):
    axis = int(maybe(ins, "AxisTensor", attrs.get("axis", 0)))
    return {"Out": [jnp.concatenate(ins["X"], axis=axis)]}


@register_op("split")
def _split(ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = jnp.cumsum(jnp.array(sections[:-1]))
        outs = jnp.split(x, [int(i) for i in idx], axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def _stack(ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def _unstack(ins, attrs):
    x = first(ins, "X")
    axis = attrs.get("axis", 0)
    parts = jnp.split(x, x.shape[axis], axis=axis)
    return {"Y": [jnp.squeeze(p, axis) for p in parts]}


@register_op("slice")
def _slice(ins, attrs):
    x = first(ins, "Input")
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    return {"Out": [x[tuple(idx)]]}


@register_op("strided_slice")
def _strided_slice(ins, attrs):
    x = first(ins, "Input")
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(
        attrs["axes"], attrs["starts"], attrs["ends"], attrs["strides"]
    ):
        idx[a] = slice(s, e, st)
    return {"Out": [x[tuple(idx)]]}


@register_op("expand")
def _expand(ins, attrs):
    x = first(ins, "X")
    times = attrs.get("expand_times")
    return {"Out": [jnp.tile(x, tuple(times))]}


@register_op("expand_as")
def _expand_as(ins, attrs):
    x, target = first(ins, "X"), first(ins, "target_tensor")
    return {"Out": [jnp.broadcast_to(x, target.shape)]}


@register_op("tile")
def _tile(ins, attrs):
    return {"Out": [jnp.tile(first(ins, "X"), tuple(attrs["repeat_times"]))]}


@register_op("gather", nondiff_inputs=("Index",))
def _gather(ins, attrs):
    x, index = first(ins, "X"), first(ins, "Index")
    return {"Out": [jnp.take(x, index.reshape(-1), axis=attrs.get("axis", 0))]}


@register_op("gather_nd", nondiff_inputs=("Index",))
def _gather_nd(ins, attrs):
    x, index = first(ins, "X"), first(ins, "Index")
    return {"Out": [x[tuple(jnp.moveaxis(index, -1, 0))]]}


@register_op("scatter", nondiff_inputs=("Ids",))
def _scatter(ins, attrs):
    x, ids, updates = first(ins, "X"), first(ins, "Ids"), first(ins, "Updates")
    # mode="drop" silently skips out-of-range rows — the paged decode
    # arena's "this batch slot writes nowhere" encoding (feed row R)
    kw = {"mode": attrs["mode"]} if attrs.get("mode") else {}
    if attrs.get("overwrite", True):
        out = x.at[ids.reshape(-1)].set(updates, **kw)
    else:
        out = x.at[ids.reshape(-1)].add(updates, **kw)
    return {"Out": [out]}


@register_op("scatter_nd_add", nondiff_inputs=("Index",))
def _scatter_nd_add(ins, attrs):
    x, index, updates = first(ins, "X"), first(ins, "Index"), first(ins, "Updates")
    return {"Out": [x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)]}


@register_op("index_select", nondiff_inputs=("Index",))
def _index_select(ins, attrs):
    x, index = first(ins, "X"), first(ins, "Index")
    return {"Out": [jnp.take(x, index, axis=attrs.get("dim", 0))]}


@register_op("flip")
def _flip(ins, attrs):
    return {"Out": [jnp.flip(first(ins, "X"), tuple(attrs["axis"]))]}


@register_op("roll")
def _roll(ins, attrs):
    return {
        "Out": [
            jnp.roll(
                first(ins, "X"), tuple(attrs["shifts"]), tuple(attrs.get("axis", [0]))
            )
        ]
    }


@register_op("pad")
def _pad(ins, attrs):
    x = first(ins, "X")
    p = attrs["paddings"]
    pads = tuple((p[2 * i], p[2 * i + 1]) for i in range(x.ndim))
    return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))]}


@register_op("pad2d")
def _pad2d(ins, attrs):
    x = first(ins, "X")
    p = attrs["paddings"]
    mode = attrs.get("mode", "constant")
    pads = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]))
    if mode == "constant":
        return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))]}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": [jnp.pad(x, pads, mode=jmode)]}


@register_op("cast")
def _cast(ins, attrs):
    x = first(ins, "X")
    return {"Out": [x.astype(np_dtype(attrs, "out_dtype"))]}


@register_op("shape", nondiff_inputs=("Input",))
def _shape(ins, attrs):
    x = first(ins, "Input")
    return {"Out": [jnp.asarray(x.shape, dtype=jnp.int32)]}


@register_op("where", nondiff_inputs=("Condition",))
def _where(ins, attrs):
    cond, x, y = first(ins, "Condition"), first(ins, "X"), first(ins, "Y")
    return {"Out": [jnp.where(cond, x, y)]}


@register_op("where_index", nondiff_inputs=("Condition",))
def _where_index(ins, attrs):
    cond = first(ins, "Condition")
    return {"Out": [jnp.argwhere(cond).astype(jnp.int64)]}


# -- comparison / logical ---------------------------------------------------


def _compare(name, fn):
    @register_op(name, nondiff_inputs=("X", "Y"))
    def _lower(ins, attrs, _fn=fn):
        return {"Out": [_fn(first(ins, "X"), first(ins, "Y"))]}


_compare("equal", jnp.equal)
_compare("not_equal", jnp.not_equal)
_compare("less_than", jnp.less)
_compare("less_equal", jnp.less_equal)
_compare("greater_than", jnp.greater)
_compare("greater_equal", jnp.greater_equal)


@register_op("logical_and", nondiff_inputs=("X", "Y"))
def _logical_and(ins, attrs):
    return {"Out": [jnp.logical_and(first(ins, "X"), first(ins, "Y"))]}


@register_op("logical_or", nondiff_inputs=("X", "Y"))
def _logical_or(ins, attrs):
    return {"Out": [jnp.logical_or(first(ins, "X"), first(ins, "Y"))]}


@register_op("logical_not", nondiff_inputs=("X",))
def _logical_not(ins, attrs):
    return {"Out": [jnp.logical_not(first(ins, "X"))]}


@register_op("isfinite", nondiff_inputs=("X",))
def _isfinite(ins, attrs):
    # reference: paddle/fluid/operators/isfinite_op.cc — reduces to a single
    # bool: "all finite"
    return {"Out": [jnp.all(jnp.isfinite(first(ins, "X"))).reshape((1,))]}


@register_op("isfinite_v2", nondiff_inputs=("X",))
def _isfinite_v2(ins, attrs):
    return {"Out": [jnp.isfinite(first(ins, "X"))]}


# -- random (stateful) ------------------------------------------------------


from paddle_tpu.ops.common import seeded_rng_key as _key_for


@register_op("gaussian_random", stateful=True)
def _gaussian_random(ins, attrs):
    shape = tuple(maybe(ins, "ShapeTensor", attrs.get("shape")))
    dtype = np_dtype(attrs)
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        _key_for(ins, attrs), shape, dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op("uniform_random", stateful=True)
def _uniform_random(ins, attrs):
    shape = tuple(maybe(ins, "ShapeTensor", attrs.get("shape")))
    dtype = np_dtype(attrs)
    out = jax.random.uniform(
        _key_for(ins, attrs),
        shape,
        minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0),
        dtype=jnp.float32,
    )
    return {"Out": [out.astype(dtype)]}


@register_op("truncated_gaussian_random", stateful=True)
def _truncated_gaussian_random(ins, attrs):
    shape = tuple(attrs.get("shape"))
    std = attrs.get("std", 1.0)
    mean = attrs.get("mean", 0.0)
    out = mean + std * jax.random.truncated_normal(
        _key_for(ins, attrs), -2.0, 2.0, shape, dtype=jnp.float32
    )
    return {"Out": [out.astype(np_dtype(attrs))]}


@register_op("randint", stateful=True)
def _randint(ins, attrs):
    shape = tuple(attrs.get("shape"))
    out = jax.random.randint(
        _key_for(ins, attrs), shape, attrs.get("low", 0), attrs.get("high", 100)
    )
    return {"Out": [out.astype(np_dtype(attrs, default="int64"))]}


@register_op("randperm", stateful=True)
def _randperm(ins, attrs):
    n = attrs["n"]
    return {
        "Out": [
            jax.random.permutation(_key_for(ins, attrs), n).astype(
                np_dtype(attrs, default="int64")
            )
        ]
    }


@register_op("bernoulli", stateful=True)
def _bernoulli(ins, attrs):
    x = first(ins, "X")
    return {
        "Out": [jax.random.bernoulli(_key_for(ins, attrs), x).astype(x.dtype)]
    }


@register_op("print")
def _print(ins, attrs):
    """Debug print via jax.debug (reference: paddle/fluid/operators/
    print_op.cc + platform/lodtensor_printer.cc)."""
    x = first(ins, "In")
    jax.debug.print(attrs.get("message", "print") + ": {x}", x=x)
    return {"Out": [x]}


@register_op("batched_gather", nondiff_inputs=("Index",))
def _batched_gather(ins, attrs):
    """Per-row gather along axis 1: X [B, S, ...] + Index [B, P] ->
    [B, P, ...] (the masked-position gather BERT-style pretraining needs;
    the reference reaches the same result with LoD + sequence ops)."""
    x = first(ins, "X")
    idx = first(ins, "Index").astype(jnp.int32)
    idx_e = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return {"Out": [jnp.take_along_axis(
        x, jnp.broadcast_to(idx_e, idx.shape + x.shape[2:]), axis=1
    )]}
