"""Sharded-embedding ops: dedup slab gather + fused row-sparse update.

The graph half of paddle_tpu/embedding/: the host engine (store.py)
resolves ids -> hot-cache slots once per batch; these ops only ever see
cache-sized tensors, so the billion-row table never exists on device.

``sharded_embedding_lookup``'s generic vjp (core/backward.py) would
materialize a dense [capacity, D] table cotangent and hand it to the
dense optimizer; the deferred ``sharded_embedding_update`` pass
(passes.py) fuses grad + optimizer into ``sharded_embedding_sgd`` — the
same SelectedRows fusion sgd_sparse does for lookup_table, but indexed
by cache slot and segment-summing over the dedup inverse index first.
"""

import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first


@register_op("sharded_embedding_lookup", nondiff_inputs=("Slots", "Inv"))
def _sharded_embedding_lookup(ins, attrs):
    """Out[b, s, :] = Table[Slots[Inv[b, s]], :].

    The first take is the ONLY table-wide gather in the step (the dedup
    property gather.py asserts from the HLO); the second fans the U_pad
    unique rows back out to id occurrences — a cache-local move. On an
    ep mesh the slab is row-sharded P('ep', None) (spec_layout role
    ``embedding_shard``), so the gather's interconnect traffic is the
    unique rows, never the slab."""
    table = first(ins, "Table")
    slots = first(ins, "Slots").astype(jnp.int32)
    inv = first(ins, "Inv").astype(jnp.int32)
    rows = jnp.take(table, slots, axis=0)          # [U_pad, D]
    out = jnp.take(rows, inv, axis=0)              # ids.shape + [D]
    return {"Out": [out]}


@register_op("sharded_embedding_sgd", nondiff_inputs=("Slots", "Inv"))
def _sharded_embedding_sgd(ins, attrs):
    """Fused dedup-grad + SGD row scatter on the hot slab.

    OutGrad [*, U?, D] is the lookup output's cotangent; segment-summing
    it over Inv merges duplicate-id grads into per-unique-row grads
    (bucket rows past the true unique count receive zero — padding slots
    repeat a real slot, and scatter-adding their zero update is a
    no-op), then one scatter-add applies -lr * rowgrad at the slots.
    Rows the batch never touched are not read or written — the property
    behind cache-size-invariant training (store.py)."""
    table = first(ins, "Table")
    slots = first(ins, "Slots").astype(jnp.int32)
    inv = first(ins, "Inv").astype(jnp.int32).reshape(-1)
    og = first(ins, "OutGrad")
    d = table.shape[-1]
    u_pad = slots.shape[0]
    rowg = (
        jnp.zeros((u_pad, d), jnp.float32)
        .at[inv]
        .add(og.reshape(-1, d).astype(jnp.float32))
    )
    upd = (-float(attrs["lr"]) * rowg).astype(table.dtype)
    return {"TableOut": [table.at[slots].add(upd)]}
