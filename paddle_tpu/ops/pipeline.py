"""`pipeline_stack` op: GPipe pipeline parallelism on the Program/IR path.

The reference's pipeline cuts the program into sections run by host threads
passing scopes through queues (reference: python/paddle/fluid/
optimizer.py:3414 PipelineOptimizer, paddle/fluid/framework/
section_worker.cc:142). On TPU the schedule must live inside the compiled
computation, so the IR form mirrors the dominant pipelined workload — a
stack of identical layers: the per-layer body is a sub-block (built by
layers.pipeline.PipelinedStack), its parameters are STACKED with a leading
[num_layers] axis sharded over the mesh's `stage` axis, and the lowering
wraps parallel/pipeline.pipeline_apply (ppermute ring + microbatch ticks)
in a nested shard_map — real cross-stage overlap, differentiable through
the generic vjp path.

Off-mesh (no `stage` axis, single device, plain Executor) the same op
degrades to a lax.scan over the stacked layers — identical numerics, no
pipeline, which is what makes single-device parity tests possible.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.core.registry import register_op

from paddle_tpu.parallel.env import shard_map as _shard_map
from paddle_tpu.utils.enforce import EnforceError


def _body_runner(sub, inner_x, inner_out, param_inner, ex, bindings, rng):
    """block_fn(layer_params, h) for pipeline_apply / the scan fallback.
    layer_params' first leaf is the per-layer index (for RNG folding)."""
    from paddle_tpu.core.executor import _interpret_block
    from paddle_tpu.parallel.env import collective_context

    def block_fn(layer_params, h):
        layer_idx = layer_params[0]
        env = dict(ex)
        env[inner_x] = h
        env.update(zip(param_inner, layer_params[1:]))
        key = jax.random.fold_in(rng, layer_idx.astype(jnp.uint32))
        with collective_context(bindings):
            _interpret_block(sub, env, key)
        return env[inner_out]

    return block_fn


@register_op("pipeline_stack", stateful=True, needs_block=True,
             nondiff_inputs=())
def _pipeline_stack(ins, attrs):
    block = attrs["_ctx_block"]
    sub = block.program.block(attrs["sub_block"])
    x = ins["X"][0]
    stacked = list(ins.get("StackedParams", []))
    ex_names = attrs.get("ex_vars", [])
    ex = dict(zip(ex_names, ins.get("Ex", [])))
    inner_x = attrs["inner_x"]
    inner_out = attrs["inner_out"]
    param_inner = attrs.get("param_inner_vars", [])
    num_mb = attrs.get("num_microbatches", 1)
    stage_axis = attrs.get("stage_axis", "stage")
    bindings = dict(attrs.get("ring_bindings", {}))
    rng = ins.get("__rng_key__", [jax.random.PRNGKey(0)])[0]
    if not stacked:
        raise EnforceError("pipeline_stack needs stacked layer params")
    L = stacked[0].shape[0]
    layer_ids = jnp.arange(L)

    # schedule choice: op attr (PipelinedStack(schedule=...)), overridden
    # by with_parallel(pipeline_schedule=...) via the thread-local the
    # compiler binds around lowering — the same value it joined into the
    # compile-cache fingerprint
    from paddle_tpu.parallel.pipeline_runtime.runtime import (
        current_schedule_override,
    )

    schedule_kind = attrs.get("schedule") or "gpipe"
    interleave = attrs.get("interleave")
    ov_kind, ov_v = current_schedule_override()
    if ov_kind is not None:
        schedule_kind = ov_kind
        interleave = ov_v if ov_v is not None else None
    elif ov_v is not None:
        interleave = ov_v

    from paddle_tpu.parallel.env import current_mesh

    mesh = current_mesh()
    on_mesh = (
        mesh is not None
        and stage_axis in mesh.axis_names
        and mesh.shape[stage_axis] > 1
    )

    if not on_mesh:
        # degenerate path: the SAME microbatch loop, minus the ring — per
        # microbatch, scan the stacked layers. Looping microbatches (not
        # scanning the full batch) keeps the per-gemm shapes identical to
        # the pipelined arms, so single-device parity is BITWISE, not
        # just allclose (the evidence gate's no-pipeline reference).
        body = _body_runner(
            sub, inner_x, inner_out, param_inner, ex, bindings, rng
        )

        def layer(h, p):
            return body(p, h), None

        if num_mb > 1 and x.shape[0] % num_mb == 0:
            from paddle_tpu.parallel.pipeline import split_microbatches

            def run_mb(_, xm):
                out, __ = lax.scan(layer, xm, (layer_ids, *stacked))
                return _, out

            _, outs = lax.scan(run_mb, 0, split_microbatches(x, num_mb))
            return {"Out": [outs.reshape(x.shape)]}
        out, _ = lax.scan(layer, x, (layer_ids, *stacked))
        return {"Out": [out]}

    from paddle_tpu.parallel.pipeline import (
        pipeline_apply,
        split_microbatches,
    )
    from paddle_tpu.parallel.pipeline_runtime.runtime import (
        interleave_permutation,
        pipeline_apply_interleaved,
    )
    from paddle_tpu.parallel.pipeline_runtime.schedule import (
        compile_schedule,
    )

    n_stage = mesh.shape[stage_axis]
    # validates the (kind, stages, microbatches, interleave) tuple — a
    # contention-ful 1f1b config fails HERE, pre-trace, with the why
    sched = compile_schedule(schedule_kind, n_stage, num_mb, interleave)
    if sched.kind == "1f1b":
        # circular virtual-stage assignment: permute stacked rows (and
        # layer_ids with them, so per-layer RNG folds follow the layer)
        # BEFORE the P(stage) shard — device d holds chunks d, d+s, ...
        perm = jnp.asarray(
            interleave_permutation(L, n_stage, sched.interleave)
        )
        stacked = [p[perm] for p in stacked]
        layer_ids = layer_ids[perm]

    # per-param specs for the non-stage dims (TP etc.), leading dim 'stage'
    extra_specs = attrs.get("param_specs") or [()] * len(stacked)
    in_param_specs = tuple(
        P(stage_axis, *spec) for spec in extra_specs
    )
    # resolve the batch axis the way CompiledProgram does ('data' if
    # present, else the mesh's first axis) so the activation stays batch-
    # sharded instead of silently replicating onto every device
    if "data" in mesh.axis_names:
        data_axis = "data"
    elif mesh.axis_names[0] != stage_axis:
        data_axis = mesh.axis_names[0]
    else:
        data_axis = None
    x_spec = P(data_axis) if data_axis else P()
    ex_specs = tuple(P() for _ in ex_names)

    def sharded_fn(x, layer_ids, stacked, ex_vals):
        ex_local = dict(zip(ex_names, ex_vals))
        body = _body_runner(
            sub, inner_x, inner_out, param_inner, ex_local, bindings, rng
        )
        x_mb = split_microbatches(x, num_mb)
        if sched.kind == "1f1b":
            outs = pipeline_apply_interleaved(
                body, (layer_ids, *stacked), x_mb, stage_axis,
                sched.interleave, collect="broadcast",
            )
        else:
            outs = pipeline_apply(
                body, (layer_ids, *stacked), x_mb, stage_axis,
                collect="broadcast",
            )
        return outs.reshape(x.shape)

    out = _shard_map(
        sharded_fn,
        mesh=mesh,
        in_specs=(x_spec, P(stage_axis), in_param_specs, ex_specs),
        out_specs=x_spec,
        body_has_pallas=True,  # stage bodies may lower sdpa through Pallas
    )(x, layer_ids, tuple(stacked), tuple(ex.values()))
    return {"Out": [out]}
