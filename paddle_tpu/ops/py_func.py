"""py_func: user Python inside the compiled step via host callback.

reference: paddle/fluid/operators/py_func_op.cc + python/paddle/fluid/
layers/nn.py py_func — arbitrary user Python runs per step with tensor
inputs/outputs. TPU-native: the callable is invoked through a JAX host
callback, so the XLA computation stays whole and the host round-trip
happens only at this op's boundary.

Design notes:
* The callables live on a token object stored directly in the op's attrs
  (`_pyfunc_token`), so their lifetime is the program's — no global registry
  to leak. Programs containing py_func are not serializable (same as the
  reference: a pickled ProgramDesc cannot carry Python closures).
* Without a backward_func the op uses `io_callback` — an EFFECTFUL
  callback XLA must not elide, so side-effect-only uses (logging, metric
  sinks) run even when nothing downstream consumes the output.
* With a backward_func the op is differentiable (custom_vjp); integer
  inputs get float0 cotangents (JAX's contract for non-differentiable
  primals) and are omitted from backward_func's gradient outputs.
"""

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op
from paddle_tpu.utils.enforce import EnforceError


class PyFuncToken:
    """Carries the user callables inside op attrs (clone-safe by identity)."""

    def __init__(self, forward, backward=None, skip_input_idx=()):
        self.forward = forward
        self.backward = backward
        self.skip_input_idx = frozenset(skip_input_idx)

    def __deepcopy__(self, memo):
        return self  # clones share the token; callables are not copyable


@register_op("py_func", stateful=True)
def _py_func(ins, attrs):
    token = attrs.get("_pyfunc_token")
    if not isinstance(token, PyFuncToken):
        raise EnforceError(
            "py_func op has no callable token — programs containing "
            "py_func cannot be rebuilt from serialized bytes (Python "
            "closures do not serialize; same restriction as the reference)"
        )
    fwd, bwd = token.forward, token.backward
    xs = tuple(ins.get("X", []))
    out_shapes = [tuple(s) for s in attrs["out_shapes"]]
    out_dtypes = attrs["out_dtypes"]
    from paddle_tpu.core.dtypes import to_numpy_dtype

    result_spec = tuple(
        jax.ShapeDtypeStruct(s, to_numpy_dtype(d))
        for s, d in zip(out_shapes, out_dtypes)
    )

    def call_fwd(*arrays):
        out = fwd(*arrays)
        return tuple(out) if isinstance(out, (list, tuple)) else (out,)

    if bwd is None:
        # io_callback: ordered side effects XLA cannot elide — the op runs
        # even when its outputs feed nothing (logging/metric sinks)
        from jax.experimental import io_callback

        outs = io_callback(call_fwd, result_spec, *xs, ordered=True)
        outs = jax.tree.map(jax.lax.stop_gradient, outs)
        return {"Out": list(outs)}

    diff_idx = [
        i for i, x in enumerate(xs)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    ]

    @jax.custom_vjp
    def run(*xs):
        return jax.pure_callback(call_fwd, result_spec, *xs)

    def run_fwd(*xs):
        outs = jax.pure_callback(call_fwd, result_spec, *xs)
        return outs, (xs, outs)

    def run_bwd(res, gs):
        saved_xs, saved_outs = res
        bwd_args = [
            x for i, x in enumerate(saved_xs)
            if i not in token.skip_input_idx
        ]

        def call_bwd(*arrays):
            # backward_func(non-skipped inputs..., outputs..., out_grads...)
            # -> one gradient per DIFFERENTIABLE input (reference calling
            # convention, py_func_op.cc + skip_vars_in_backward_input)
            out = bwd(*arrays)
            out = tuple(out) if isinstance(out, (list, tuple)) else (out,)
            return out

        diff_spec = tuple(
            jax.ShapeDtypeStruct(saved_xs[i].shape, saved_xs[i].dtype)
            for i in diff_idx
        )
        diff_grads = jax.pure_callback(
            call_bwd, diff_spec, *bwd_args, *saved_outs, *gs
        )
        grads = []
        it = iter(diff_grads)
        for i, x in enumerate(saved_xs):
            if i in diff_idx:
                grads.append(next(it))
            else:
                # integer/bool primals take float0 cotangents
                grads.append(
                    np.zeros(jnp.shape(x), dtype=jax.dtypes.float0)
                )
        return tuple(grads)

    run.defvjp(run_fwd, run_bwd)
    return {"Out": list(run(*xs))}
