"""Mixture-of-Experts FFN as a first-class IR op.

Expert parallelism on the Program/Executor surface (SURVEY §2.7 names it
new first-class work the 2020 reference lacks; the closest reference analog
is distributed sparse lookup, not expert routing). The op computes top-2
gated expert FFNs over stacked [E, ...] expert weights:

- with an active mesh (CompiledProgram.with_parallel) whose `expert_axis`
  has size > 1: tokens and experts are sharded over that axis inside a
  shard_map; tokens travel to their expert's device via one lax.all_to_all
  each way over ICI (parallel/moe.py moe_ffn_local);
- otherwise: the same routing math runs dense on one device, so a plain
  Executor run is the numerical reference for the sharded one.

The load-balance aux loss rides as a second output for the caller to add
to the objective.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

from paddle_tpu.parallel.env import shard_map as _shard_map
from paddle_tpu.ops.common import first, vma_names
from paddle_tpu.utils.enforce import EnforceError

_ACTS = {
    "gelu": jax.nn.gelu,
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


def _expert_ffn(act_fn):
    def fn(params, buf):
        """params: (w1 [H,F], b1 [F], w2 [F,H], b2 [H]); buf [C, H]."""
        w1, b1, w2, b2 = params
        h = act_fn(buf @ w1 + b1)
        return h @ w2 + b2

    return fn


@register_op("moe_ffn")
def _moe_ffn(ins, attrs):
    x = first(ins, "X")           # [..., H] (any leading dims = tokens)
    gate_w = first(ins, "GateW")  # [H, E]
    w1 = first(ins, "W1")         # [E, H, F]
    b1 = first(ins, "B1")         # [E, F]
    w2 = first(ins, "W2")         # [E, F, H]
    b2 = first(ins, "B2")         # [E, H]
    axis = attrs.get("expert_axis", "expert")
    cf = attrs.get("capacity_factor", 2.0)
    capacity = attrs.get("capacity", 0)
    act_fn = _ACTS[attrs.get("activation", "gelu")]
    E = gate_w.shape[1]

    orig_shape = x.shape
    xt = x.reshape(-1, x.shape[-1])
    T = xt.shape[0]
    expert_fn = _expert_ffn(act_fn)

    from paddle_tpu.parallel import env as penv

    mesh = penv.current_mesh()
    n = 1
    if mesh is not None and axis in mesh.axis_names:
        n = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)
    if n > 1 and vma_names(xt):
        raise EnforceError(
            "moe_ffn cannot run inside an already-manual region (e.g. a "
            "pipeline_stack body); place the MoE layer on the outer program"
        )

    if n > 1:
        if E % n:
            raise EnforceError(
                f"num_experts {E} must divide expert axis '{axis}' size {n}"
            )
        if T % n:
            raise EnforceError(
                f"expert axis '{axis}' size {n} must divide the token "
                f"count {T}"
            )
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.parallel.moe import moe_ffn_local

        # per-source-device capacity, ceil so the TOTAL per-expert buffer
        # (n * cap_local) is never below the dense path's explicit
        # capacity — dense vs sharded drop behavior matches when the
        # capacity is generous
        cap_local = -(-capacity // n) if capacity else None

        def local(xs, gw, p1, p2, p3, p4):
            y, aux = moe_ffn_local(
                xs, gw, (p1, p2, p3, p4), expert_fn, axis,
                capacity_factor=cf, capacity=cap_local, global_aux=True,
            )
            return y, aux

        y, aux = _shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(), P(axis), P(axis), P(axis), P(axis)),
            out_specs=(P(axis, None), P()),
        )(xt, gate_w, w1, b1, w2, b2)
    else:
        from paddle_tpu.parallel.moe import top2_gating

        cap = capacity or max(int(cf * T * 2 / E), 4)
        logits = xt @ gate_w
        dispatch, combine, aux = top2_gating(logits, cap)
        buf = jnp.einsum("tec,th->ech", dispatch.astype(xt.dtype), xt)
        out = jax.vmap(expert_fn)((w1, b1, w2, b2), buf)
        y = jnp.einsum("tec,ech->th", combine.astype(xt.dtype), out)

    return {
        "Out": [y.reshape(orig_shape).astype(x.dtype)],
        "AuxLoss": [aux.astype(jnp.float32)],
    }
