"""General IR pass framework: named program-rewrite passes + a pass manager.

TPU-native analog of the reference's ir/ pass infrastructure
(reference: paddle/fluid/framework/ir/pass.h:40 Pass::Apply,
paddle/fluid/inference/analysis/ir_pass_manager.cc:36 IRPassManager) — but
where the reference needed 126 passes (fusion, layout, memory reuse), XLA
owns fusion/layout/scheduling here, so the passes that remain are the
*semantic* program rewrites: dead-code elimination, test-mode flipping,
precision casts, quantization. AMP (amp/decorator.py) and QAT
(contrib/quantize.py) use the same rewrite style; inference/ composes these
through a PassManager.

A pass is a callable `(Program, PassContext) -> Program` registered by name.
Passes may mutate in place and return the same Program, or return a new one.

Producer/consumer reasoning inside passes goes through the shared
control-flow-aware use-def analysis (analysis/usedef.py) — a var read only by
a while/conditional_block body still counts as consumed, so fusions can't
delete a producer a sub-block reads. `PassManager(verify_each_pass=True)`
runs the program verifier (analysis/verify.py) after every pass and raises
naming the pass that broke an invariant.
"""

from paddle_tpu.analysis.usedef import build_usedef
from paddle_tpu.utils.enforce import EnforceError, enforce

__all__ = [
    "register_pass",
    "get_pass",
    "PassContext",
    "PassManager",
]

_PASS_REGISTRY = {}


def register_pass(name):
    """Decorator: register a pass callable under `name`
    (reference: paddle/fluid/framework/ir/pass.h REGISTER_PASS)."""

    def deco(fn):
        enforce(name not in _PASS_REGISTRY, f"pass '{name}' already registered")
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def get_pass(name):
    enforce(name in _PASS_REGISTRY, f"no pass named '{name}'; have "
            f"{sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name]


class PassContext:
    """Shared state passed to every pass: the scope holding parameters (so
    weight-rewriting passes can transform values, not just the graph), the
    fetch targets (for liveness), and free-form options."""

    def __init__(self, scope=None, feed_names=(), fetch_names=(), **options):
        self.scope = scope
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.options = options
        self.stats = {}  # pass name -> info dict, for debugging/reporting

    def opt(self, key, default=None):
        return self.options.get(key, default)


class PassManager:
    """Apply a sequence of named passes (reference:
    paddle/fluid/inference/analysis/ir_pass_manager.cc:36).

    With ``verify_each_pass=True`` the program verifier
    (analysis/verify.py) runs after every pass; a pass that introduces a
    NEW error-grade diagnostic (relative to the program as it entered the
    manager) raises EnforceError naming that pass. Per-pass diagnostics are
    recorded under ``ctx.stats['verify'][pass_name]`` either way."""

    def __init__(self, pass_names, verify_each_pass=False):
        self.pass_names = list(pass_names)
        self.verify_each_pass = verify_each_pass
        for n in self.pass_names:
            get_pass(n)  # fail fast on unknown names

    def _verify(self, program, ctx):
        from paddle_tpu.analysis.verify import verify_program

        return verify_program(
            program, feed_names=ctx.feed_names, fetch_names=ctx.fetch_names,
        )

    def run(self, program, ctx=None):
        ctx = ctx or PassContext()
        seen = None
        if self.verify_each_pass:
            # pre-existing diagnostics are the caller's, not a pass's
            seen = {d.key() for d in self._verify(program, ctx)}
        for name in self.pass_names:
            out = get_pass(name)(program, ctx)
            program = out if out is not None else program
            if self.verify_each_pass:
                diags = self._verify(program, ctx)
                for d in diags:
                    d.pass_name = name
                fresh = [
                    d for d in diags
                    if d.severity == "error" and d.key() not in seen
                ]
                ctx.stats.setdefault("verify", {})[name] = [
                    str(d) for d in diags if d.key() not in seen
                ]
                if fresh:
                    detail = "\n".join(str(d) for d in fresh)
                    raise EnforceError(
                        f"pass '{name}' broke program invariants "
                        f"({len(fresh)} new error"
                        f"{'s' if len(fresh) > 1 else ''}):\n{detail}"
                    )
                seen |= {d.key() for d in diags}
        return program


# ---------------------------------------------------------------------------
# built-in passes
# ---------------------------------------------------------------------------


@register_pass("dead_code_elimination")
def _dce_pass(program, ctx):
    """Drop ops that don't (transitively) feed a fetch and have no side
    effects (reference: paddle/fluid/framework/prune.cc). Requires
    ctx.fetch_names."""
    from paddle_tpu.analysis.usedef import live_ops

    if not ctx.fetch_names:
        return program
    # only the global block: sub-blocks (cond/while bodies) carry their own
    # liveness through the parent control-flow op, and pruning them against
    # the TOP-LEVEL fetches would empty loop bodies
    block = program.global_block()
    live = live_ops(block, ctx.fetch_names)
    live_set = {id(op) for op in live}
    before = len(block.ops)
    block.ops = [op for op in block.ops if id(op) in live_set]
    removed = before - len(block.ops)
    if removed:
        program._bump_version()
    ctx.stats["dead_code_elimination"] = {"removed_ops": removed}
    return program


@register_pass("flip_test_mode")
def _flip_test_pass(program, ctx):
    """Force is_test=True on every op that has a train/test behavior split
    (dropout, batch_norm, ...) — the inference analog of clone(for_test)."""
    from paddle_tpu.core.ir import _test_mode_attrs

    flipped = 0
    for block in program.blocks:
        for op in block.ops:
            if "is_test" in _test_mode_attrs(op.type):
                if not op.attrs.get("is_test"):
                    op.attrs["is_test"] = True
                    flipped += 1
    if flipped:
        program._bump_version()
    ctx.stats["flip_test_mode"] = {"flipped_ops": flipped}
    return program


@register_pass("bf16_cast")
def _bf16_cast_pass(program, ctx):
    """Cast MXU-friendly regions to bfloat16 for inference using the AMP
    white/black lists (reference: the mkldnn/TensorRT precision passes, e.g.
    paddle/fluid/inference/api/paddle_pass_builder.cc — re-targeted to the
    TPU's native low-precision dtype). Weights feeding white-listed ops are
    cast in the scope so the executable reads bf16 parameters directly."""
    from paddle_tpu.amp.decorator import (
        AutoMixedPrecisionLists,
        rewrite_program_amp,
    )

    rewrite_program_amp(
        program,
        amp_lists=AutoMixedPrecisionLists(
            custom_white_list=ctx.opt("bf16_white_list"),
            custom_black_list=ctx.opt("bf16_black_list"),
        ),
        dest_dtype="bfloat16",
    )
    ctx.stats["bf16_cast"] = {"enabled": True}
    return program


@register_pass("fold_constants")
def _fold_constants_pass(program, ctx):
    """Evaluate fetch-independent constant subgraphs (ops whose inputs are
    all produced by earlier constant ops, starting from fill_constant) once
    at analysis time and replace them with scope-resident values
    (reference: paddle/fluid/framework/ir/ constant-folding behavior; XLA
    also folds, but folding here shrinks the traced program and lets later
    passes see literal values). Requires ctx.scope."""
    import jax.numpy as jnp

    from paddle_tpu.core.registry import OpRegistry

    if ctx.scope is None:
        return program
    block = program.global_block()
    const_vals = {}
    folded_ops = []
    feed_set = set(ctx.feed_names)
    for op in block.ops:
        ins = [n for ns in op.inputs.values() for n in ns]
        foldable = (op.type == "fill_constant" and not ins) or (
            ins and all(n in const_vals for n in ins)
        )
        if foldable and OpRegistry.has(op.type):
            op_def = OpRegistry.get(op.type)
            foldable = not op_def.stateful and not any(
                n in feed_set for n in op.output_names()
            )
        elif foldable:
            foldable = False
        if not foldable:
            # a non-folded op overwriting a tracked var invalidates its
            # constant value — later reads must NOT see the stale fold
            for n in op.output_names():
                const_vals.pop(n, None)
            continue
        try:
            env = {
                slot: [const_vals[n] for n in names]
                for slot, names in op.inputs.items()
            }
            out = op_def.lower(env, dict(op.attrs))
        except Exception:
            out = None
        ok = out is not None
        new_vals = {}
        if ok:
            for slot, names in op.outputs.items():
                vals = out.get(slot)
                if vals is None or len(vals) != len(names):
                    ok = False
                    break
                for n, v in zip(names, vals):
                    new_vals[n] = jnp.asarray(v)
        if ok:
            const_vals.update(new_vals)
            folded_ops.append(op)
        else:
            # evaluation failed: the op runs at serve time and overwrites its
            # outputs — drop any stale constant tracking for them
            for n in op.output_names():
                const_vals.pop(n, None)
    if folded_ops:
        folded_set = {id(op) for op in folded_ops}
        # only fold ops whose outputs aren't ALSO written by non-folded ops
        block.ops = [op for op in block.ops if id(op) not in folded_set]
        # keep only constants still read by the remaining program
        still_read = {
            n for op in block.ops for n in op.input_names()
        } | set(ctx.fetch_names)
        for n, v in const_vals.items():
            if n in still_read:
                ctx.scope.set(n, v)
                var = block._find_var_recursive(n)
                if var is not None:
                    var.persistable = True
        program._bump_version()
    ctx.stats["fold_constants"] = {
        "folded_ops": len(folded_ops),
        "materialized": int(
            sum(1 for n in const_vals if ctx.scope.has_var(n))
        ),
    }
    return program


@register_pass("strip_debug_ops")
def _strip_debug_pass(program, ctx):
    """Remove print/assert instrumentation for serving builds."""
    removed = 0
    for block in program.blocks:
        before = len(block.ops)
        block.ops = [op for op in block.ops if op.type not in ("print",)]
        removed += before - len(block.ops)
    if removed:
        program._bump_version()
    ctx.stats["strip_debug_ops"] = {"removed_ops": removed}
    return program


@register_pass("sparse_weight_update")
def _sparse_weight_update_pass(program, ctx):
    """Fuse lookup_table*_grad + sgd into a row-sparse sgd_sparse update —
    the SelectedRows analog for the dense path (reference:
    paddle/fluid/framework/selected_rows.h:32; operators/optimizers/
    sgd_op.h sparse branch). The [V, D] dense gradient never materializes:
    the looked-up rows' cotangent scatter-subtracts into the touched
    parameter rows. Applies only where the dense grad has exactly one
    producer (the lookup grad) and one consumer (the sgd) — grad clip,
    regularizers, or multi-use embeddings keep the dense form.

    Skipped under microbatching: Ids differ per microbatch while grads are
    accumulated across them, so the fused form would silently use one
    microbatch's ids.
    """
    if getattr(program, "_num_microbatches", 1) and \
            getattr(program, "_num_microbatches", 1) > 1:
        ctx.stats["sparse_weight_update"] = {"rewritten": 0,
                                             "skipped": "microbatched"}
        return program
    block = program.global_block()
    usedef = build_usedef(block)

    lookup_types = {"lookup_table_grad", "lookup_table_v2_grad"}
    rewrites = []  # (sgd_op, grad_op)
    for op in block.ops:
        if op.type != "sgd":
            continue
        gname = op.inputs["Grad"][0]
        prods = usedef.producers.get(gname, [])
        cons = usedef.consumers.get(gname, [])
        v = block.vars.get(gname)
        if (
            len(prods) == 1
            and prods[0].type in lookup_types
            and len(cons) == 1
            and cons[0] is op
            and not (v is not None and v.persistable)
        ):
            rewrites.append((op, prods[0]))

    if not rewrites:
        ctx.stats["sparse_weight_update"] = {"rewritten": 0}
        return program

    from paddle_tpu.core.ir import Operator

    replaced = {id(o) for pair in rewrites for o in pair}
    new_ops = []
    for op in block.ops:
        if id(op) not in replaced:
            new_ops.append(op)
            continue
        match = next((pair for pair in rewrites if pair[0] is op), None)
        if match is None:
            continue  # the grad op: dropped (fused into sgd_sparse)
        sgd_op, grad_op = match
        # RowGrad is the lookup OUTPUT's cotangent (Out@GRAD input slot)
        new_ops.append(Operator(
            block, "sgd_sparse",
            {
                "Param": list(sgd_op.inputs["Param"]),
                "Ids": list(grad_op.inputs["Ids"]),
                "RowGrad": list(grad_op.inputs["Out@GRAD"]),
                "LearningRate": list(sgd_op.inputs["LearningRate"]),
            },
            {"ParamOut": list(sgd_op.outputs["ParamOut"])},
            {
                "padding_idx": grad_op.attrs.get("padding_idx", -1),
                "op_role": sgd_op.attrs.get("op_role", 0),
            },
        ))
        block.vars.pop(gname := sgd_op.inputs["Grad"][0], None)
    block.ops = new_ops
    program._bump_version()
    ctx.stats["sparse_weight_update"] = {"rewritten": len(rewrites)}
    return program


@register_pass("sharded_embedding_update")
def _sharded_embedding_update_pass(program, ctx):
    """Fuse sharded_embedding_lookup_grad + the dense optimizer op into
    one ``sharded_embedding_sgd`` row-scatter on the hot slab
    (ops/sharded_embedding.py) — the engine analog of
    sparse_weight_update. Mandatory where it matches, not opportunistic:
    a dense optimizer step on the slab touches rows the batch never
    looked up (Adam moments drift untouched cached rows), which breaks
    the two-tier engine's cache-size-invariance contract (embedding/
    store.py) — so a grad the pass CANNOT fuse (extra consumers, grad
    clip) is a build error, not a silent fallback."""
    block = program.global_block()
    slabs = {
        t["slab"]: t
        for t in (getattr(program, "_sharded_tables", None) or {}).values()
    }
    grad_ops = [
        op for op in block.ops
        if op.type == "sharded_embedding_lookup_grad"
        and op.inputs.get("Table", [None])[0] in slabs
    ]
    if not grad_ops:
        ctx.stats["sharded_embedding_update"] = {"rewritten": 0}
        return program
    if (getattr(program, "_num_microbatches", 1) or 1) > 1:
        raise EnforceError(
            "sharded_embedding cannot run microbatched: slots/inv feeds "
            "differ per microbatch while grads accumulate across them"
        )
    usedef = build_usedef(block)
    rewrites = {}  # id(grad_op) -> (grad_op, opt_op)
    for gop in grad_ops:
        gname = gop.outputs["Table@GRAD"][0]
        slab = gop.inputs["Table"][0]
        cons = usedef.consumers.get(gname, [])
        ok = (
            len(cons) == 1
            and cons[0].inputs.get("Grad", [None])[0] == gname
            and cons[0].inputs.get("Param", [None])[0] == slab
        )
        if not ok:
            raise EnforceError(
                f"sharded table slab '{slab}': its gradient must flow "
                "straight into one optimizer op (the engine's row-sparse "
                "SGD replaces it). Gradient clip / regularizers / extra "
                f"consumers are unsupported on sharded tables; consumers: "
                f"{[c.type for c in cons]}"
            )
        rewrites[id(gop)] = (gop, cons[0])

    from paddle_tpu.core.ir import Operator

    opt_ids = {id(opt) for _g, opt in rewrites.values()}
    new_ops, dropped_vars = [], set()
    for op in block.ops:
        if id(op) in opt_ids:
            # the dense optimizer op: dropped; its private accumulators
            # (moments, beta pows) become dead vars
            for slot, names in op.inputs.items():
                if slot in ("Param", "Grad", "LearningRate"):
                    continue
                dropped_vars.update(names)
            continue
        if id(op) not in rewrites:
            new_ops.append(op)
            continue
        gop, opt = rewrites[id(op)]
        gname = gop.outputs["Table@GRAD"][0]
        slab = gop.inputs["Table"][0]
        new_ops.append(Operator(
            block, "sharded_embedding_sgd",
            {
                "Table": [slab],
                "Slots": list(gop.inputs["Slots"]),
                "Inv": list(gop.inputs["Inv"]),
                "OutGrad": list(gop.inputs["Out@GRAD"]),
            },
            {"TableOut": [slab]},
            {
                "lr": slabs[slab]["lr"],
                "table_name": slabs[slab]["table_name"],
                "op_role": opt.attrs.get("op_role", 0),
            },
        ))
        dropped_vars.add(gname)
    block.ops = new_ops
    # drop vars no remaining op touches (the dense grad + dead slots)
    still_used = {
        n for op in block.ops
        for names in list(op.inputs.values()) + list(op.outputs.values())
        for n in names
    }
    for n in dropped_vars - still_used:
        block.vars.pop(n, None)
    program._bump_version()
    ctx.stats["sharded_embedding_update"] = {"rewritten": len(rewrites)}
    return program


def apply_deferred_sharded_embedding_rewrite(program):
    """Execution-time hook (the apply_deferred_sparse_rewrite pattern):
    layers.sharded_embedding marks the program; executors call this
    before building a compile entry, so the rewrite sees the final op
    list (backward + optimizer present, microbatching decided)."""
    if not getattr(program, "_wants_sharded_embedding_update", False):
        return
    if not any(
        op.type == "sharded_embedding_lookup_grad"
        for op in program.global_block().ops
    ):
        # inference program (or minimize not run yet): nothing to fuse;
        # keep the mark so a later-minimized clone still rewrites
        return
    program._wants_sharded_embedding_update = False
    _PASS_REGISTRY["sharded_embedding_update"](program, PassContext())


def apply_deferred_sparse_rewrite(program):
    """Execution-time hook: SGDOptimizer.minimize marks the program instead
    of rewriting it (a wrapping PipelineOptimizer sets _num_microbatches
    AFTER minimize returns, and the fused sgd_sparse cannot microbatch).
    Executors call this before building a compile entry."""
    if not getattr(program, "_wants_sparse_embedding", False):
        return
    program._wants_sparse_embedding = False
    num_mb = getattr(program, "_num_microbatches", 1) or 1
    if num_mb > 1:
        return  # microbatched: the dense form is the correct one
    _PASS_REGISTRY["sparse_weight_update"](program, PassContext())


# ---------------------------------------------------------------------------
# export-time pattern fusion (reference: framework/ir/fc_fuse_pass.cc,
# conv_bn_fuse_pass.cc, multihead_matmul_fuse_pass.cc)
# ---------------------------------------------------------------------------


@register_pass("fc_fuse")
def _fc_fuse_pass(program, ctx):
    """mul + elementwise_add(1-D bias) [+ activation] -> one `fc` op
    (reference: paddle/fluid/framework/ir/fc_fuse_pass.cc:1). Shrinks the
    traced inference program; XLA sees one fused dot+bias+act region.

    Use maps come from analysis/usedef.py, so an intermediate read by a
    while/conditional_block body counts its control-flow op as a consumer
    and the pattern correctly refuses to swallow it."""
    block = program.global_block()
    usedef = build_usedef(block, ctx.fetch_names)
    drop = set()
    rewrites = {}  # id(mul op) -> replacement Operator
    from paddle_tpu.core.ir import Operator

    # acts fusable only when their attrs match what the fc op computes
    fusable_act = {
        "relu": lambda a: True,
        "tanh": lambda a: True,
        "sigmoid": lambda a: True,
        "gelu": lambda a: not a.get("approximate", False),
        "relu6": lambda a: a.get("threshold", 6.0) == 6.0,
    }
    for op in block.ops:
        if op.type != "mul" or id(op) in drop:
            continue
        if op.attrs.get("y_num_col_dims", 1) != 1:
            continue
        w_var = block._find_var_recursive(op.inputs["Y"][0])
        if w_var is None or not w_var.shape or len(w_var.shape) != 2:
            continue  # the fc lowering assumes a 2-D weight
        k = op.attrs.get("x_num_col_dims", 1)
        out = op.outputs["Out"][0]
        add = usedef.sole_consumer(out)
        if add is None or add.type != "elementwise_add":
            continue
        if add.inputs["X"][0] != out:  # bias must be the Y operand
            continue
        # bias must align on the LAST axis (mul out rank is k+1): the fc
        # op adds it per-column
        if add.attrs.get("axis", -1) not in (-1, k):
            continue
        bias_name = add.inputs["Y"][0]
        bias_var = block._find_var_recursive(bias_name)
        if bias_var is None or not bias_var.shape or len(bias_var.shape) != 1:
            continue
        add_out = add.outputs["Out"][0]
        act_op = usedef.sole_consumer(add_out)
        act = ""
        final_out = add_out
        tail = [op, add]
        if (
            act_op is not None
            and act_op.type in fusable_act
            and fusable_act[act_op.type](act_op.attrs)
        ):
            act = act_op.type
            final_out = act_op.outputs["Out"][0]
            tail.append(act_op)
        rewrites[id(op)] = Operator(
            block, "fc",
            {
                "Input": list(op.inputs["X"]),
                "W": list(op.inputs["Y"]),
                "Bias": [bias_name],
            },
            {"Out": [final_out]},
            {
                "in_num_col_dims": op.attrs.get("x_num_col_dims", 1),
                "activation_type": act,
            },
        )
        drop.update(id(o) for o in tail)
    if not rewrites:
        ctx.stats["fc_fuse"] = {"fused": 0}
        return program
    new_ops = []
    for op in block.ops:
        if id(op) in rewrites:
            new_ops.append(rewrites[id(op)])
        elif id(op) not in drop:
            new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    ctx.stats["fc_fuse"] = {"fused": len(rewrites)}
    return program


@register_pass("conv_bn_fuse")
def _conv_bn_fuse_pass(program, ctx):
    """Fold inference-mode batch_norm into the preceding conv's weights
    (reference: paddle/fluid/framework/ir/conv_bn_fuse_pass.cc:1):
    W' = W * gamma / sqrt(var + eps) per out-channel, and the BN becomes a
    per-channel bias add. Free accuracy-preserving speed: the BN's separate
    scale/shift (and its stats reads) disappear from the executable.
    Requires ctx.scope (weight values are rewritten in place)."""
    import numpy as np

    from paddle_tpu.core.ir import Operator

    if ctx.scope is None:
        ctx.stats["conv_bn_fuse"] = {"fused": 0, "skipped": "no scope"}
        return program
    block = program.global_block()
    usedef = build_usedef(block, ctx.fetch_names)
    drop = set()
    replacements = {}  # id(bn op) -> new bias-add Operator
    fused = 0
    for op in block.ops:
        if op.type not in ("conv2d", "depthwise_conv2d") or id(op) in drop:
            continue
        if op.attrs.get("data_format", "NCHW") not in ("NCHW", "AnyLayout"):
            continue
        conv_out = op.outputs["Output"][0]
        nxt = usedef.sole_consumer(conv_out)
        bias_add = None
        bn = nxt
        if nxt is not None and nxt.type == "elementwise_add":
            y = block._find_var_recursive(nxt.inputs["Y"][0])
            if y is None or not y.persistable:
                continue
            bias_add = nxt
            bn = usedef.sole_consumer(nxt.outputs["Out"][0])
        if bn is None or bn.type != "batch_norm":
            continue
        if not bn.attrs.get("is_test"):
            continue
        if bn.attrs.get("data_layout", "NCHW") != "NCHW":
            continue
        # BN side outputs must be dead (stats don't update in test mode,
        # but a reader of SavedMean etc. would lose its producer). MeanOut/
        # VarianceOut alias the bn's own Mean/Variance inputs — the bn
        # itself reading them is not an external consumer.
        side = [
            n
            for slot in ("MeanOut", "VarianceOut", "SavedMean",
                         "SavedVariance")
            for n in bn.outputs.get(slot, ())
            if any(c is not bn for c in usedef.consumers.get(n, ()))
        ]
        if side:
            continue
        w_name = op.inputs["Filter"][0]
        if len(usedef.consumers.get(w_name, [])) != 1:
            # shared filter: folding would corrupt the other use (sub-block
            # conv reads count — they appear via their control-flow op)
            continue
        names = {
            "scale": bn.inputs["Scale"][0],
            "shift": bn.inputs["Bias"][0],
            "mean": bn.inputs["Mean"][0],
            "var": bn.inputs["Variance"][0],
        }
        if not all(ctx.scope.has_var(n) for n in names.values()) or \
                not ctx.scope.has_var(w_name):
            continue
        gamma = np.asarray(ctx.scope.find_var(names["scale"]), np.float64)
        beta = np.asarray(ctx.scope.find_var(names["shift"]), np.float64)
        mean = np.asarray(ctx.scope.find_var(names["mean"]), np.float64)
        var = np.asarray(ctx.scope.find_var(names["var"]), np.float64)
        w = np.asarray(ctx.scope.find_var(w_name))
        eps = bn.attrs.get("epsilon", 1e-5)
        factor = gamma / np.sqrt(var + eps)  # [Cout]
        new_w = (w.astype(np.float64)
                 * factor[:, None, None, None]).astype(w.dtype)
        if bias_add is not None:
            # only a per-channel bias (size Cout, broadcast on axis 1) can
            # fold into the BN shift
            if bias_add.attrs.get("axis", -1) != 1:
                continue
            b_name = bias_add.inputs["Y"][0]
            b = np.asarray(ctx.scope.find_var(b_name), np.float64) \
                if ctx.scope.has_var(b_name) else None
            if b is None or b.size != mean.size:
                continue
        else:
            b = np.zeros_like(mean)
        new_b = (beta + (b.reshape(-1) - mean) * factor).astype(w.dtype)
        # materialize the folded bias under a fresh persistable var
        bn_out = bn.outputs["Y"][0]
        fb_name = f"{w_name}__bn_folded_bias"
        block.create_var(
            name=fb_name, shape=[int(new_b.shape[0])],
            dtype=str(new_b.dtype), persistable=True,
        )
        ctx.scope.set(fb_name, new_b)
        ctx.scope.set(w_name, new_w)
        replacements[id(bn)] = Operator(
            block, "elementwise_add",
            {"X": [conv_out], "Y": [fb_name]},
            {"Out": [bn_out]},
            {"axis": 1},
        )
        if bias_add is not None:
            drop.add(id(bias_add))
        fused += 1
    if not fused:
        ctx.stats["conv_bn_fuse"] = {"fused": 0}
        return program
    new_ops = []
    for op in block.ops:
        if id(op) in replacements:
            new_ops.append(replacements[id(op)])
        elif id(op) not in drop:
            new_ops.append(op)
    block.ops = new_ops
    program._bump_version()
    ctx.stats["conv_bn_fuse"] = {"fused": fused}
    return program


@register_pass("multihead_matmul_fuse")
def _multihead_fuse_pass(program, ctx):
    """Collapse the unfused attention core — matmul(qk^T, alpha)
    [+ additive bias] -> softmax [-> test-mode dropout] -> matmul(pv) —
    into one scaled_dot_product_attention op, which the Pallas flash
    kernel serves (reference: paddle/fluid/framework/ir/
    multihead_matmul_fuse_pass.cc:1; their target is the CUDA fused op,
    ours is the flash lowering). Ported inference programs get the fused
    kernel without model changes."""
    from paddle_tpu.core.ir import Operator

    block = program.global_block()
    usedef = build_usedef(block, ctx.fetch_names)
    drop = set()
    rewrites = {}  # id(qk matmul) -> list of replacement Operators
    fused = 0
    for sm in block.ops:
        if sm.type != "softmax" or id(sm) in drop:
            continue
        if sm.attrs.get("axis", -1) not in (-1, 3):
            continue
        sm_in = sm.inputs["X"][0]
        prod = usedef.producers.get(sm_in, [])
        if len(prod) != 1:
            continue
        add = None
        qk = prod[0]
        if qk.type == "elementwise_add":
            add = qk
            p2 = usedef.producers.get(add.inputs["X"][0], [])
            if len(p2) != 1:
                continue
            qk = p2[0]
            if usedef.sole_consumer(qk.outputs["Out"][0], add) is None:
                continue
        if qk.type != "matmul" or not qk.attrs.get("transpose_Y"):
            continue
        if qk.attrs.get("transpose_X"):
            continue
        if usedef.sole_consumer((add or qk).outputs["Out"][0], sm) is None:
            continue
        q_name = qk.inputs["X"][0]
        k_name = qk.inputs["Y"][0]
        qv = block._find_var_recursive(q_name)
        if qv is None or qv.shape is None or len(qv.shape) != 4:
            continue  # [B, H, S, D] attention only
        # downstream: softmax -> (dropout) -> matmul(p, v)
        pv = usedef.sole_consumer(sm.outputs["Out"][0])
        dropout = None
        if pv is not None and pv.type == "dropout":
            impl = pv.attrs.get(
                "dropout_implementation", "downgrade_in_infer"
            )
            identity = pv.attrs.get("is_test") and (
                impl == "upscale_in_train"
                or not pv.attrs.get("dropout_prob", 0.0)
            )
            if not identity:
                continue
            # dropping the op must not orphan a live Mask reader
            if any(
                usedef.consumers.get(n)
                for n in pv.outputs.get("Mask", ())
            ) or any(n in usedef.protected
                     for n in pv.outputs.get("Mask", ())):
                continue
            dropout = pv
            pv = usedef.sole_consumer(dropout.outputs["Out"][0])
        if (
            pv is None
            or pv.type != "matmul"
            or pv.attrs.get("transpose_X")
            or pv.attrs.get("transpose_Y")
            or pv.attrs.get("alpha", 1.0) != 1.0
        ):
            continue
        probs_name = (dropout or sm).outputs["Out"][0]
        if pv.inputs["X"][0] != probs_name:
            continue
        v_name = pv.inputs["Y"][0]
        new_ops = []
        sdpa_ins = {"Q": [q_name], "K": [k_name], "V": [v_name]}
        if add is not None:
            bias_name = add.inputs["Y"][0]
            bv = block._find_var_recursive(bias_name)
            if bv is None or bv.shape is None:
                continue
            bshape = list(bv.shape)
            # ONLY the [B,1,1,S] key-bias form is sdpa's Bias semantic; a
            # raw 2-D add would have broadcast as trailing [S_q, S_k]
            # (relative-position bias) — different math, skip the fusion
            if len(bshape) == 4 and bshape[1] == 1 and bshape[2] == 1:
                # [B,1,1,S]: reuse the pre-reshape [B,S] source if there is
                # one, else flatten here
                bprod = usedef.producers.get(bias_name, [])
                src = None
                if len(bprod) == 1 and bprod[0].type in ("reshape2",
                                                         "reshape"):
                    cand = bprod[0].inputs["X"][0]
                    cv = block._find_var_recursive(cand)
                    if cv is not None and cv.shape is not None \
                            and len(cv.shape) == 2:
                        src = cand
                if src is None:
                    flat = f"{bias_name}__sdpa_flat"
                    block.create_var(
                        name=flat, shape=[bshape[0], bshape[3]],
                        dtype=bv.dtype,
                    )
                    new_ops.append(Operator(
                        block, "reshape",
                        {"X": [bias_name]}, {"Out": [flat]},
                        {"shape": [0, int(bshape[3])]
                         if bshape[3] and bshape[3] > 0 else [0, -1]},
                    ))
                    src = flat
                sdpa_ins["Bias"] = [src]
            else:
                continue
        new_ops.append(Operator(
            block, "scaled_dot_product_attention",
            sdpa_ins,
            {"Out": [pv.outputs["Out"][0]]},
            {"sm_scale": qk.attrs.get("alpha", 1.0) or 1.0},
        ))
        # insert at the PV matmul's position — the LAST op of the matched
        # pattern dominates every pattern input (V's producer may sit
        # between the QK matmul and the PV matmul in program order)
        rewrites[id(pv)] = new_ops
        drop.update(
            id(o) for o in (qk, add, sm, dropout) if o is not None
        )
        fused += 1
    if not fused:
        ctx.stats["multihead_matmul_fuse"] = {"fused": 0}
        return program
    out_ops = []
    for op in block.ops:
        if id(op) in rewrites:
            out_ops.extend(rewrites[id(op)])
        elif id(op) not in drop:
            out_ops.append(op)
    block.ops = out_ops
    program._bump_version()
    ctx.stats["multihead_matmul_fuse"] = {"fused": fused}
    return program


def resolve_tensor_array_indices(program):
    """Execution-time fixup: fold each TensorArray op's index into a
    `static_index` attr when the index var's SOLE writer in the whole
    program is one fill_constant. Runs when the program is COMPLETE (a
    build-time fold would miss later writers — e.g. a While body
    incrementing the index AFTER the array op was appended, which must
    stay dynamic and hit the loud error in ops/tail.py)."""
    marker = getattr(program, "_tarray_resolved_version", None)
    if marker == program._version:
        return
    targets = [
        op
        for b in program.blocks
        for op in b.ops
        if op.type in ("write_to_array", "read_from_array")
    ]
    if targets:
        writers = {}
        for b in program.blocks:
            for op in b.ops:
                for n in op.output_names():
                    writers.setdefault(n, []).append(op)
        for op in targets:
            iname = op.inputs["I"][0]
            w = writers.get(iname, [])
            if len(w) == 1 and w[0].type == "fill_constant":
                op.attrs["static_index"] = int(w[0].attrs.get("value", 0))
            else:
                op.attrs.pop("static_index", None)
    program._tarray_resolved_version = program._version
