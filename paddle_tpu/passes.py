"""General IR pass framework: named program-rewrite passes + a pass manager.

TPU-native analog of the reference's ir/ pass infrastructure
(reference: paddle/fluid/framework/ir/pass.h:40 Pass::Apply,
paddle/fluid/inference/analysis/ir_pass_manager.cc:36 IRPassManager) — but
where the reference needed 126 passes (fusion, layout, memory reuse), XLA
owns fusion/layout/scheduling here, so the passes that remain are the
*semantic* program rewrites: dead-code elimination, test-mode flipping,
precision casts, quantization. AMP (amp/decorator.py) and QAT
(contrib/quantize.py) use the same rewrite style; inference/ composes these
through a PassManager.

A pass is a callable `(Program, PassContext) -> Program` registered by name.
Passes may mutate in place and return the same Program, or return a new one.
"""

from paddle_tpu.utils.enforce import enforce

__all__ = [
    "register_pass",
    "get_pass",
    "PassContext",
    "PassManager",
]

_PASS_REGISTRY = {}


def register_pass(name):
    """Decorator: register a pass callable under `name`
    (reference: paddle/fluid/framework/ir/pass.h REGISTER_PASS)."""

    def deco(fn):
        enforce(name not in _PASS_REGISTRY, f"pass '{name}' already registered")
        _PASS_REGISTRY[name] = fn
        return fn

    return deco


def get_pass(name):
    enforce(name in _PASS_REGISTRY, f"no pass named '{name}'; have "
            f"{sorted(_PASS_REGISTRY)}")
    return _PASS_REGISTRY[name]


class PassContext:
    """Shared state passed to every pass: the scope holding parameters (so
    weight-rewriting passes can transform values, not just the graph), the
    fetch targets (for liveness), and free-form options."""

    def __init__(self, scope=None, feed_names=(), fetch_names=(), **options):
        self.scope = scope
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.options = options
        self.stats = {}  # pass name -> info dict, for debugging/reporting

    def opt(self, key, default=None):
        return self.options.get(key, default)


class PassManager:
    """Apply a sequence of named passes (reference:
    paddle/fluid/inference/analysis/ir_pass_manager.cc:36)."""

    def __init__(self, pass_names):
        self.pass_names = list(pass_names)
        for n in self.pass_names:
            get_pass(n)  # fail fast on unknown names

    def run(self, program, ctx=None):
        ctx = ctx or PassContext()
        for name in self.pass_names:
            out = get_pass(name)(program, ctx)
            program = out if out is not None else program
        return program


# ---------------------------------------------------------------------------
# built-in passes
# ---------------------------------------------------------------------------


@register_pass("dead_code_elimination")
def _dce_pass(program, ctx):
    """Drop ops that don't (transitively) feed a fetch and have no side
    effects (reference: paddle/fluid/framework/prune.cc). Requires
    ctx.fetch_names."""
    from paddle_tpu.core.executor import live_ops

    if not ctx.fetch_names:
        return program
    # only the global block: sub-blocks (cond/while bodies) carry their own
    # liveness through the parent control-flow op, and pruning them against
    # the TOP-LEVEL fetches would empty loop bodies
    block = program.global_block()
    live = live_ops(block, ctx.fetch_names)
    live_set = {id(op) for op in live}
    before = len(block.ops)
    block.ops = [op for op in block.ops if id(op) in live_set]
    removed = before - len(block.ops)
    if removed:
        program._bump_version()
    ctx.stats["dead_code_elimination"] = {"removed_ops": removed}
    return program


@register_pass("flip_test_mode")
def _flip_test_pass(program, ctx):
    """Force is_test=True on every op that has a train/test behavior split
    (dropout, batch_norm, ...) — the inference analog of clone(for_test)."""
    from paddle_tpu.core.ir import _test_mode_attrs

    flipped = 0
    for block in program.blocks:
        for op in block.ops:
            if "is_test" in _test_mode_attrs(op.type):
                if not op.attrs.get("is_test"):
                    op.attrs["is_test"] = True
                    flipped += 1
    if flipped:
        program._bump_version()
    ctx.stats["flip_test_mode"] = {"flipped_ops": flipped}
    return program


@register_pass("bf16_cast")
def _bf16_cast_pass(program, ctx):
    """Cast MXU-friendly regions to bfloat16 for inference using the AMP
    white/black lists (reference: the mkldnn/TensorRT precision passes, e.g.
    paddle/fluid/inference/api/paddle_pass_builder.cc — re-targeted to the
    TPU's native low-precision dtype). Weights feeding white-listed ops are
    cast in the scope so the executable reads bf16 parameters directly."""
    from paddle_tpu.amp.decorator import (
        AutoMixedPrecisionLists,
        rewrite_program_amp,
    )

    rewrite_program_amp(
        program,
        amp_lists=AutoMixedPrecisionLists(
            custom_white_list=ctx.opt("bf16_white_list"),
            custom_black_list=ctx.opt("bf16_black_list"),
        ),
        dest_dtype="bfloat16",
    )
    ctx.stats["bf16_cast"] = {"enabled": True}
    return program


@register_pass("fold_constants")
def _fold_constants_pass(program, ctx):
    """Evaluate fetch-independent constant subgraphs (ops whose inputs are
    all produced by earlier constant ops, starting from fill_constant) once
    at analysis time and replace them with scope-resident values
    (reference: paddle/fluid/framework/ir/ constant-folding behavior; XLA
    also folds, but folding here shrinks the traced program and lets later
    passes see literal values). Requires ctx.scope."""
    import jax.numpy as jnp

    from paddle_tpu.core.registry import OpRegistry

    if ctx.scope is None:
        return program
    block = program.global_block()
    const_vals = {}
    folded_ops = []
    feed_set = set(ctx.feed_names)
    for op in block.ops:
        ins = [n for ns in op.inputs.values() for n in ns]
        foldable = (op.type == "fill_constant" and not ins) or (
            ins and all(n in const_vals for n in ins)
        )
        if foldable and OpRegistry.has(op.type):
            op_def = OpRegistry.get(op.type)
            foldable = not op_def.stateful and not any(
                n in feed_set for n in op.output_names()
            )
        elif foldable:
            foldable = False
        if not foldable:
            # a non-folded op overwriting a tracked var invalidates its
            # constant value — later reads must NOT see the stale fold
            for n in op.output_names():
                const_vals.pop(n, None)
            continue
        try:
            env = {
                slot: [const_vals[n] for n in names]
                for slot, names in op.inputs.items()
            }
            out = op_def.lower(env, dict(op.attrs))
        except Exception:
            out = None
        ok = out is not None
        new_vals = {}
        if ok:
            for slot, names in op.outputs.items():
                vals = out.get(slot)
                if vals is None or len(vals) != len(names):
                    ok = False
                    break
                for n, v in zip(names, vals):
                    new_vals[n] = jnp.asarray(v)
        if ok:
            const_vals.update(new_vals)
            folded_ops.append(op)
        else:
            # evaluation failed: the op runs at serve time and overwrites its
            # outputs — drop any stale constant tracking for them
            for n in op.output_names():
                const_vals.pop(n, None)
    if folded_ops:
        folded_set = {id(op) for op in folded_ops}
        # only fold ops whose outputs aren't ALSO written by non-folded ops
        block.ops = [op for op in block.ops if id(op) not in folded_set]
        # keep only constants still read by the remaining program
        still_read = {
            n for op in block.ops for n in op.input_names()
        } | set(ctx.fetch_names)
        for n, v in const_vals.items():
            if n in still_read:
                ctx.scope.set(n, v)
                var = block._find_var_recursive(n)
                if var is not None:
                    var.persistable = True
        program._bump_version()
    ctx.stats["fold_constants"] = {
        "folded_ops": len(folded_ops),
        "materialized": int(
            sum(1 for n in const_vals if ctx.scope.has_var(n))
        ),
    }
    return program


@register_pass("strip_debug_ops")
def _strip_debug_pass(program, ctx):
    """Remove print/assert instrumentation for serving builds."""
    removed = 0
    for block in program.blocks:
        before = len(block.ops)
        block.ops = [op for op in block.ops if op.type not in ("print",)]
        removed += before - len(block.ops)
    if removed:
        program._bump_version()
    ctx.stats["strip_debug_ops"] = {"removed_ops": removed}
    return program


@register_pass("sparse_weight_update")
def _sparse_weight_update_pass(program, ctx):
    """Fuse lookup_table*_grad + sgd into a row-sparse sgd_sparse update —
    the SelectedRows analog for the dense path (reference:
    paddle/fluid/framework/selected_rows.h:32; operators/optimizers/
    sgd_op.h sparse branch). The [V, D] dense gradient never materializes:
    the looked-up rows' cotangent scatter-subtracts into the touched
    parameter rows. Applies only where the dense grad has exactly one
    producer (the lookup grad) and one consumer (the sgd) — grad clip,
    regularizers, or multi-use embeddings keep the dense form.

    Skipped under microbatching: Ids differ per microbatch while grads are
    accumulated across them, so the fused form would silently use one
    microbatch's ids.
    """
    if getattr(program, "_num_microbatches", 1) and \
            getattr(program, "_num_microbatches", 1) > 1:
        ctx.stats["sparse_weight_update"] = {"rewritten": 0,
                                             "skipped": "microbatched"}
        return program
    block = program.global_block()
    producers = {}
    consumers = {}
    for op in block.ops:
        for n in op.output_names():
            producers.setdefault(n, []).append(op)
        for n in op.input_names():
            consumers.setdefault(n, []).append(op)

    lookup_types = {"lookup_table_grad", "lookup_table_v2_grad"}
    rewrites = []  # (sgd_op, grad_op)
    for op in block.ops:
        if op.type != "sgd":
            continue
        gname = op.inputs["Grad"][0]
        prods = producers.get(gname, [])
        cons = consumers.get(gname, [])
        v = block.vars.get(gname)
        if (
            len(prods) == 1
            and prods[0].type in lookup_types
            and len(cons) == 1
            and cons[0] is op
            and not (v is not None and v.persistable)
        ):
            rewrites.append((op, prods[0]))

    if not rewrites:
        ctx.stats["sparse_weight_update"] = {"rewritten": 0}
        return program

    from paddle_tpu.core.ir import Operator

    replaced = {id(o) for pair in rewrites for o in pair}
    new_ops = []
    for op in block.ops:
        if id(op) not in replaced:
            new_ops.append(op)
            continue
        match = next((pair for pair in rewrites if pair[0] is op), None)
        if match is None:
            continue  # the grad op: dropped (fused into sgd_sparse)
        sgd_op, grad_op = match
        # RowGrad is the lookup OUTPUT's cotangent (Out@GRAD input slot)
        new_ops.append(Operator(
            block, "sgd_sparse",
            {
                "Param": list(sgd_op.inputs["Param"]),
                "Ids": list(grad_op.inputs["Ids"]),
                "RowGrad": list(grad_op.inputs["Out@GRAD"]),
                "LearningRate": list(sgd_op.inputs["LearningRate"]),
            },
            {"ParamOut": list(sgd_op.outputs["ParamOut"])},
            {
                "padding_idx": grad_op.attrs.get("padding_idx", -1),
                "op_role": sgd_op.attrs.get("op_role", 0),
            },
        ))
        block.vars.pop(gname := sgd_op.inputs["Grad"][0], None)
    block.ops = new_ops
    program._bump_version()
    ctx.stats["sparse_weight_update"] = {"rewritten": len(rewrites)}
    return program


def apply_deferred_sparse_rewrite(program):
    """Execution-time hook: SGDOptimizer.minimize marks the program instead
    of rewriting it (a wrapping PipelineOptimizer sets _num_microbatches
    AFTER minimize returns, and the fused sgd_sparse cannot microbatch).
    Executors call this before building a compile entry."""
    if not getattr(program, "_wants_sparse_embedding", False):
        return
    program._wants_sparse_embedding = False
    num_mb = getattr(program, "_num_microbatches", 1) or 1
    if num_mb > 1:
        return  # microbatched: the dense form is the correct one
    _PASS_REGISTRY["sparse_weight_update"](program, PassContext())
